"""Length-bucketed context gather (r6): exactness + engagement.

The bucketed chunk sorts lanes into 2 static length buckets INSIDE one
compiled program (``SELDON_TPU_CTX_BUCKETS``, default on) so short
streams stop paying the longest stream's gather/ctx-einsum cost.  The
contract these tests pin: bucketing is a pure PERFORMANCE choice —
greedy tokens are bit-identical bucketed vs unbucketed, ring vs pool
chunk impl, and under the w8a8 int8 lane; and a lane's output never
depends on which bucket its co-batch landed in.

The fast-tier half is one lean smoke (bimodal parity + uniform
degeneracy on the ring impl, single-layer model — the default tier
must catch a broken bucket path without paying the full matrix); the
@slow half runs every combination plus the real 32/448-token bimodal
shape the bench certifies.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from seldon_core_tpu.models.transformer import TransformerLM


CFG = dict(vocab_size=64, d_model=32, num_layers=2, num_heads=4, max_len=128)
# single-layer twin for the fast tier: compile cost is per layer
CFG_FAST = dict(CFG, num_layers=1)


@pytest.fixture(scope="module")
def lm():
    module = TransformerLM(dtype=jnp.float32, **CFG)
    params = module.init(jax.random.key(0), jnp.zeros((1, 8), jnp.int32))["params"]
    return module, params


def _bimodal_prompts(short, long, n, vocab=64):
    rng = np.random.default_rng(7)
    return [
        rng.integers(0, vocab, size=(short if i % 2 == 0 else long,)).astype(
            np.int32
        )
        for i in range(n)
    ]


def _engine(params, monkeypatch, *, buckets, impl, n_slots, cfg=None,
            **engine_kw):
    monkeypatch.setenv("SELDON_TPU_CTX_BUCKETS", buckets)
    monkeypatch.setenv("SELDON_TPU_CHUNK_IMPL", impl)
    from seldon_core_tpu.models.paged import PagedEngine

    kw = dict(dtype=jnp.float32, page_size=8, max_slots=n_slots,
              steps_per_call=4)
    kw.update(engine_kw)
    return PagedEngine(params, **(cfg or CFG), **kw)


def _serve(eng, prompts, max_new=10):
    streams = [eng.submit(p, max_new_tokens=max_new) for p in prompts]
    eng.run()
    return np.stack([s.result for s in streams])


def _run(params, prompts, monkeypatch, *, buckets, impl, max_new=10,
         cfg=None, **engine_kw):
    eng = _engine(params, monkeypatch, buckets=buckets, impl=impl,
                  n_slots=len(prompts), cfg=cfg, **engine_kw)
    toks = _serve(eng, prompts, max_new=max_new)
    return toks, eng.engine_stats()


def _greedy_uncached(module, params, prompt, n):
    tokens = np.asarray(prompt, np.int32)[None, :]
    out = []
    for _ in range(n):
        logits = module.apply({"params": params}, jnp.asarray(tokens))
        nxt = int(jnp.argmax(logits[0, -1]))
        out.append(nxt)
        tokens = np.concatenate([tokens, [[nxt]]], axis=1)
    return out


class TestBucketedGatherFastTier:
    def test_bimodal_parity_and_uniform_degeneracy(self, monkeypatch):
        """One bucketed and one unbucketed ring engine serve the SAME
        bimodal then uniform batches: bimodal tokens identical with the
        2-bucket program engaged; uniform traffic degenerates to one
        bucket (equal horizons) and stays identical — the knob is a
        pure performance choice, pinned in the default tier."""
        module = TransformerLM(dtype=jnp.float32, **CFG_FAST)
        params = module.init(
            jax.random.key(0), jnp.zeros((1, 8), jnp.int32)
        )["params"]
        bimodal = _bimodal_prompts(4, 40, 4)
        rng = np.random.default_rng(3)
        uniform = [
            rng.integers(0, 64, size=(9,)).astype(np.int32) for _ in range(4)
        ]
        eng2 = _engine(params, monkeypatch, buckets="2", impl="ring",
                       n_slots=4, cfg=CFG_FAST)
        eng1 = _engine(params, monkeypatch, buckets="1", impl="ring",
                       n_slots=4, cfg=CFG_FAST)
        bi2, bi1 = _serve(eng2, bimodal), _serve(eng1, bimodal)
        assert eng2.engine_stats()["bucketed_chunks"] > 0
        assert eng1.engine_stats()["bucketed_chunks"] == 0
        assert np.array_equal(bi2, bi1)
        # ground truth for one short and one long stream, so the A/B
        # can't both be wrong the same way
        for i in (0, 1):
            want = _greedy_uncached(module, params, bimodal[i], 10)
            assert bi2[i].tolist() == want, i
        marker = eng2.engine_stats()["bucketed_chunks"]
        un2, un1 = _serve(eng2, uniform), _serve(eng1, uniform)
        assert eng2.engine_stats()["bucketed_chunks"] == marker  # degenerated
        assert np.array_equal(un2, un1)

    def test_partial_occupancy_splits_live_lanes_not_idle(self, monkeypatch):
        """Host-level planning contract (no compiles): with most slots
        idle, the live streams split at THEIR midpoint — idle lanes are
        filler, they must not displace short live streams into the long
        bucket (the drain/low-occupancy case), and a 2-bucket plan
        implies some live lane actually runs at the cheaper horizon."""
        from types import SimpleNamespace

        module = TransformerLM(dtype=jnp.float32, **CFG_FAST)
        params = module.init(
            jax.random.key(2), jnp.zeros((1, 8), jnp.int32)
        )["params"]
        eng = _engine(params, monkeypatch, buckets="2", impl="ring",
                      n_slots=16, cfg=CFG_FAST)
        # 4 live streams (2 short, 2 long) in a 16-slot engine
        live = {1: 6, 5: 7, 9: 60, 13: 58}
        for slot, length in live.items():
            eng._lengths[slot] = length
        runnable = [SimpleNamespace(slot=s) for s in live]
        buckets, perm = eng._plan_buckets(runnable, steps=4, pages_h=16)
        assert len(buckets) == 2
        (b0, h0), (b1, h1) = buckets
        assert b0 + b1 == 16 and h0 < h1
        short_bucket = set(perm[:b0].tolist())
        assert {1, 5} <= short_bucket          # short live lanes stay cheap
        assert not ({9, 13} & short_bucket)    # long live lanes in bucket 1
        # short bucket's horizon covers only the short lanes: 7 tokens
        # at page_size 8 -> 1 page
        assert h0 == 1
        assert sorted(perm.tolist()) == list(range(16))  # a permutation

    def test_invalid_buckets_env_rejected(self, monkeypatch):
        monkeypatch.setenv("SELDON_TPU_CTX_BUCKETS", "3")
        from seldon_core_tpu.models.paged import PagedEngine

        module = TransformerLM(dtype=jnp.float32, **CFG_FAST)
        params = module.init(
            jax.random.key(1), jnp.zeros((1, 8), jnp.int32)
        )["params"]
        with pytest.raises(ValueError, match="SELDON_TPU_CTX_BUCKETS"):
            PagedEngine(params, dtype=jnp.float32, page_size=8,
                        max_slots=2, steps_per_call=4, **CFG_FAST)


@pytest.mark.slow
class TestBucketedGatherMatrix:
    def test_bimodal_parity_all_combinations(self, lm, monkeypatch):
        """One bimodal batch through {ring,pool} x {bucketed,unbucketed}:
        four identical token matrices, and the bucketed runs must have
        actually engaged the 2-bucket program (not degenerated)."""
        _, params = lm
        prompts = _bimodal_prompts(4, 40, 8)
        ref = None
        for impl in ("ring", "pool"):
            for buckets in ("1", "2"):
                got, stats = _run(
                    params, prompts, monkeypatch, buckets=buckets,
                    impl=impl, max_new=20,
                )
                if buckets == "2":
                    assert stats["bucketed_chunks"] > 0, impl
                else:
                    assert stats["bucketed_chunks"] == 0, impl
                if ref is None:
                    ref = got
                else:
                    assert np.array_equal(ref, got), (impl, buckets)

    def test_bucketed_matches_uncached_recompute(self, lm, monkeypatch):
        """Absolute ground truth, not just A/B: bucketed greedy equals
        the full uncached forward re-run token by token."""
        module, params = lm
        prompts = _bimodal_prompts(5, 33, 4)
        got, stats = _run(params, prompts, monkeypatch, buckets="2",
                          impl="ring", max_new=12)
        assert stats["bucketed_chunks"] > 0
        for i, p in enumerate(prompts):
            assert got[i].tolist() == _greedy_uncached(module, params, p, 12), i

    def test_lane_output_independent_of_co_batch_bucket(self, lm, monkeypatch):
        """The short stream decodes the same tokens whether its
        co-batch is short (one bucket) or long (two buckets) — the
        per-stream determinism continuous batching promises, now also
        across bucket shapes."""
        _, params = lm
        short = np.arange(6, dtype=np.int32) % 64
        alone, _ = _run(params, [short, short + 1], monkeypatch,
                        buckets="2", impl="ring")
        longp = (np.arange(40, dtype=np.int32) * 5) % 64
        mixed, stats = _run(params, [short, longp], monkeypatch,
                            buckets="2", impl="ring")
        assert stats["bucketed_chunks"] > 0
        assert np.array_equal(alone[0], mixed[0])

    def test_w8a8_bucketed_cross_parity(self, lm, monkeypatch):
        """The PR-1 int8 lane must stay exact under the new gather:
        w8a8 bucketed == w8a8 unbucketed (per-token activation scales
        are lane-order-blind), and bucketing engages."""
        _, params = lm
        f32 = jax.tree.map(
            lambda a: a.astype(jnp.float32) if hasattr(a, "astype") else a,
            params,
        )
        prompts = _bimodal_prompts(4, 36, 4)
        ref, _ = _run(f32, prompts, monkeypatch, buckets="1", impl="ring",
                      precision="w8a8")
        for impl in ("ring", "pool"):
            got, stats = _run(f32, prompts, monkeypatch, buckets="2",
                              impl=impl, precision="w8a8")
            assert stats["bucketed_chunks"] > 0, impl
            assert np.array_equal(ref, got), impl

    def test_bimodal_32_448_parity_ring_pool_bucketed(self, monkeypatch):
        """The bench-certified shape: 32/448-token bimodal prompts (the
        ISSUE r6 acceptance workload), at test-sized width/stream
        count."""
        cfg = dict(vocab_size=128, d_model=32, num_layers=2, num_heads=4,
                   max_len=512)
        module = TransformerLM(dtype=jnp.float32, **cfg)
        params = module.init(
            jax.random.key(1), jnp.zeros((1, 8), jnp.int32)
        )["params"]
        prompts = _bimodal_prompts(32, 448, 8, vocab=128)
        ref = None
        for impl in ("ring", "pool"):
            for buckets in ("2", "1"):
                got, stats = _run(
                    params, prompts, monkeypatch, buckets=buckets,
                    impl=impl, max_new=16, cfg=cfg,
                    page_size=64, steps_per_call=8,
                )
                if buckets == "2":
                    assert stats["bucketed_chunks"] > 0, impl
                if ref is None:
                    ref = got
                else:
                    assert np.array_equal(ref, got), (impl, buckets)
