"""Zero-copy buffer-view data plane (r14, docs §9a).

Covers the whole lane: BufferView semantics + the SRT1 framing
agreement (Python vs the C ABI table), the native ingress frame lanes
(HTTP + h2c gRPC PredictRaw), by-reference transport telemetry, the
engines' batched view submission (jaxserver + paged — bit-exact vs
per-request), and the SELDON_TPU_ZERO_COPY=0 parity gate.
"""

import asyncio
import base64
import json
import threading

import numpy as np
import pytest

from seldon_core_tpu import codec
from seldon_core_tpu.codec import bufview
from seldon_core_tpu.codec.bufview import BufferView


# ---------------------------------------------------------------------------
# BufferView semantics
# ---------------------------------------------------------------------------


class TestBufferView:
    def test_from_array_is_zero_copy_and_shares_memory(self):
        arr = np.arange(12, dtype=np.float32).reshape(3, 4)
        view = BufferView.from_array(arr)
        assert not view.copied
        got = view.array()
        assert got is arr  # the exact array, not even a new view object
        # np.asarray interop resolves through __array__, still the view
        assert np.asarray(view) is arr

    def test_from_array_non_contiguous_compacts_once_and_flags_it(self):
        strided = np.arange(24, dtype=np.float32).reshape(4, 6)[:, ::2]
        view = BufferView.from_array(strided)
        assert view.copied
        np.testing.assert_array_equal(view.array(), strided)

    def test_from_bytes_is_view_over_the_buffer(self):
        payload = np.arange(8, dtype=np.int32).tobytes()
        view = BufferView.from_bytes(payload, "int32", (2, 4))
        arr = view.array()
        assert not arr.flags.writeable
        root = arr
        while getattr(root, "base", None) is not None:
            root = root.base
        # rooted in the ingress buffer -> no copy between wire and array
        assert bytes(root) == payload

    def test_from_bytes_misaligned_names_offset_and_dtype(self):
        with pytest.raises(codec.PayloadError) as e:
            BufferView.from_bytes(b"\x00" * 10, "float32", (3,), offset=1)
        msg = str(e.value)
        assert "offset 1" in msg and "float32" in msg

    def test_buffer_too_small_is_payload_error(self):
        with pytest.raises(codec.PayloadError):
            BufferView("float32", (4, 4), b"\x00" * 8)

    def test_zero_d_and_empty(self):
        scalar = BufferView.from_bytes(
            np.float32(2.5).tobytes(), "float32", ()
        )
        assert scalar.shape == () and float(scalar.array()) == 2.5
        empty = BufferView.from_array(np.empty((0, 7), np.int8))
        assert empty.nbytes == 0 and empty.array().shape == (0, 7)


# ---------------------------------------------------------------------------
# SRT1 framing: round-trips + the C ABI agreement
# ---------------------------------------------------------------------------


class TestFraming:
    @pytest.mark.parametrize("dtype", ["float32", "int8", "bfloat16", "uint8",
                                       "int64", "float16"])
    @pytest.mark.parametrize("shape", [(), (0,), (5,), (2, 3, 4), (1, 4096)])
    def test_frame_roundtrip_bit_exact(self, dtype, shape):
        dt = codec.np_dtype(dtype)
        n = int(np.prod(shape)) if shape else 1
        src = (np.arange(n) % 100 + 1).astype(dt).reshape(shape)
        view = bufview.unpack_frame(bufview.pack_frame(src))
        assert view.dtype == dt and view.shape == tuple(shape)
        assert view.tobytes() == src.tobytes()
        assert not view.copied

    def test_payload_is_8_byte_aligned_in_frame(self):
        for ndim in range(0, 9):
            shape = (1,) * ndim
            frame = bufview.pack_frame(np.zeros(shape, np.float64))
            # header = 8 + 8*ndim: always a multiple of 8
            assert (len(frame) - 8) % 8 == 0
            assert bufview.frame_header(np.dtype(np.float64), shape) == \
                frame[: 8 + 8 * ndim]

    def test_frame_is_little_endian(self):
        frame = bufview.pack_frame(np.array([1], dtype="<i4"))
        assert frame[:4] == b"SRT1"
        assert frame[-4:] == (1).to_bytes(4, "little")

    def test_big_endian_source_is_byteswapped_not_corrupted(self):
        # dtype('>f4').name drops the byte order, so without the
        # encode-side swap the payload would decode as garbage
        be = np.array([1.0, 2.0], dtype=">f4")
        out = bufview.unpack_frame(bufview.pack_frame(be)).array()
        np.testing.assert_array_equal(out, [1.0, 2.0])
        assert out.dtype == np.dtype("<f4")

    def test_multi_frame_container_roundtrip_and_alignment(self):
        payloads = [
            np.arange(3, dtype=np.int8),           # 3-byte payload: pad needed
            np.arange(4, dtype=np.float32).reshape(2, 2),
            np.array([7], dtype=np.int64),
        ]
        blob = bufview.pack_frames(payloads)
        views = bufview.unpack_frames(blob)
        assert len(views) == 3
        for src, v in zip(payloads, views):
            assert v.tobytes() == src.tobytes() and v.shape == src.shape
            assert not v.copied  # views over the container, zero copy
        # single frame: container == plain frame, both decoders agree
        one = bufview.pack_frames([payloads[1]])
        assert one == bufview.pack_frame(payloads[1])
        assert len(bufview.unpack_frames(one)) == 1

    def test_multi_frame_bad_padding_raises(self):
        blob = bytearray(bufview.pack_frames(
            [np.arange(3, dtype=np.int8), np.arange(2, dtype=np.int8)]
        ))
        # corrupt an inter-frame pad byte: frame 1 = 8 header + 8 shape
        # + 3 payload = 19 bytes, padded to 24 — offsets 19-23 are pad
        blob[20] = 0xFF
        with pytest.raises(codec.PayloadError) as e:
            bufview.unpack_frames(bytes(blob))
        assert "padding" in str(e.value)

    @pytest.mark.parametrize("mutate,needle", [
        (lambda f: b"XXXX" + f[4:], "magic"),
        (lambda f: f[:4] + bytes([99]) + f[5:], "dtype code 99"),
        (lambda f: f[:16], "shape"),
        (lambda f: f[:6], "truncated"),
        (lambda f: f + b"\x00", "carries"),
    ])
    def test_malformed_frames_raise_named_payload_errors(self, mutate, needle):
        frame = bufview.pack_frame(np.arange(6, dtype=np.float32).reshape(2, 3))
        with pytest.raises(codec.PayloadError) as e:
            bufview.unpack_frame(mutate(frame))
        assert needle in str(e.value)

    def test_overflow_crafted_shape_fails_validation_like_cpp(self):
        # shape [2**32, 2**32] wraps an int64 product to 0: must be a
        # NAMED validation error at unpack (parity with srt1_payload_
        # bytes' kMaxElems guard), never a later numpy reshape error
        import struct as _struct

        frame = (_struct.pack("<IBBH", bufview.SRT1_MAGIC, 0, 2, 0)
                 + _struct.pack("<2q", 1 << 32, 1 << 32))
        with pytest.raises(codec.PayloadError) as e:
            bufview.unpack_frame(frame)
        assert "ceiling" in str(e.value)
        # the C++ validator rejects the identical bytes
        import ctypes

        from seldon_core_tpu.native import get_lib

        lib = get_lib()
        if lib is not None and hasattr(lib, "srt1_payload_bytes"):
            buf = (ctypes.c_uint8 * len(frame)).from_buffer_copy(frame)
            assert lib.srt1_payload_bytes(buf, len(frame)) == -1

    def test_c_abi_agreement(self):
        """The three SRT1 implementations cannot drift: the C table
        (native/codec.cc srt1_*) must agree with SRT1_DTYPES, header
        sizing and full-frame validation byte-for-byte."""
        import ctypes

        from seldon_core_tpu.native import get_lib

        lib = get_lib()
        if lib is None or not hasattr(lib, "srt1_item_size"):
            pytest.skip("native library not built")
        assert lib.srt1_magic() == bufview.SRT1_MAGIC
        for code, name in enumerate(bufview.SRT1_DTYPES):
            assert lib.srt1_item_size(code) == codec.np_dtype(name).itemsize, name
        assert lib.srt1_item_size(len(bufview.SRT1_DTYPES)) == -1
        for ndim in range(0, 9):
            assert lib.srt1_header_bytes(ndim) == 8 + 8 * ndim
        assert lib.srt1_header_bytes(9) == -1
        # full-frame validation parity on good and bad frames
        good = bufview.pack_frame(np.arange(10, dtype=np.int8).reshape(2, 5))
        bad = good[:4] + bytes([99]) + good[5:]

        def c_payload_bytes(frame):
            buf = (ctypes.c_uint8 * len(frame)).from_buffer_copy(frame)
            return lib.srt1_payload_bytes(buf, len(frame))

        assert c_payload_bytes(good) == 10
        assert c_payload_bytes(bad) == -1

    def test_stack_views_single_view_is_passthrough(self):
        arr = np.arange(8, dtype=np.float32).reshape(2, 4)
        batch, offsets = bufview.stack_views([BufferView.from_array(arr)])
        assert batch is arr  # NO copy for a lone full batch
        assert offsets == [0, 2]

    def test_stack_views_many_one_allocation(self):
        views = [
            BufferView.from_array(np.full((r, 3), r, np.float32))
            for r in (1, 2, 3)
        ]
        batch, offsets = bufview.stack_views(views)
        assert batch.shape == (6, 3) and offsets == [0, 1, 3, 6]
        for i, r in enumerate((1, 2, 3)):
            assert (batch[offsets[i]:offsets[i + 1]] == r).all()

    def test_stack_views_shape_mismatch_names_the_culprit(self):
        with pytest.raises(codec.PayloadError) as e:
            bufview.stack_views([np.zeros((1, 3), np.float32),
                                 np.zeros((1, 4), np.float32)])
        assert "view 1" in str(e.value)


# ---------------------------------------------------------------------------
# message + transport integration
# ---------------------------------------------------------------------------


class TestMessageIntegration:
    def test_internal_message_view_payload_degrades_to_proto(self):
        from seldon_core_tpu.runtime.message import InternalMessage

        arr = np.arange(6, dtype=np.float32).reshape(2, 3)
        msg = InternalMessage(payload=BufferView.from_array(arr), kind="rawTensor")
        # host_payload materialises the VIEW (no copy)
        assert msg.host_payload() is arr
        # remote boundaries degrade cleanly to the ordinary rawTensor
        proto = msg.to_proto()
        assert proto.data.WhichOneof("data_oneof") == "rawTensor"
        assert proto.data.rawTensor.data == arr.tobytes()
        body = msg.to_json()
        assert base64.b64decode(body["data"]["rawTensor"]["data"]) == arr.tobytes()

    def test_local_client_meters_zero_copy_bytes(self):
        import prometheus_client as prom

        from seldon_core_tpu.engine.graph import UnitSpec
        from seldon_core_tpu.engine.transport import LocalClient
        from seldon_core_tpu.runtime.message import InternalMessage

        class Echo:
            def predict(self, X, names, meta=None):
                return np.asarray(X)

        unit = UnitSpec(name="zc-meter", type="MODEL", component=Echo())
        client = LocalClient(unit, Echo())
        arr = np.arange(100, dtype=np.float32)
        msg = InternalMessage(payload=BufferView.from_array(arr), kind="rawTensor")

        asyncio.new_event_loop().run_until_complete(client.transform_input(msg))
        got = prom.REGISTRY.get_sample_value(
            "seldon_tpu_transport_zero_copy_bytes_total",
            {"unit": "zc-meter", "method": "predict", "transport": "local"},
        )
        assert got is not None and got >= arr.nbytes

    def test_plain_ndarray_payload_does_not_count_as_zero_copy(self):
        from seldon_core_tpu.engine.transport import LocalClient

        assert LocalClient._ref_bytes(
            type("M", (), {"payload": np.zeros(4)})()
        ) == 0
        view_msg = type("M", (), {"payload": BufferView.from_array(np.zeros(4))})()
        assert LocalClient._ref_bytes(view_msg) == 32


# ---------------------------------------------------------------------------
# engines: batched view submission, bit-exact vs per-request
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def mlp_server():
    from seldon_core_tpu.models.jaxserver import JaxServer

    server = JaxServer(
        model="mlp", num_classes=5, input_shape=(8,), dtype="float32",
        warmup_dtypes=("float32",), max_batch_size=16, warmup=True,
    )
    server.load()
    yield server
    server.unload()


class TestJaxServerViews:
    def test_raw_batch_views_matches_per_request_predict(self, mlp_server):
        rng = np.random.default_rng(3)
        arrays = [rng.normal(size=(r, 8)).astype(np.float32) for r in (1, 3, 2)]
        views = [BufferView.from_array(a) for a in arrays]
        outs = mlp_server.raw_batch_views(views)
        assert [o.shape[0] for o in outs] == [1, 3, 2]
        for a, o in zip(arrays, outs):
            ref = np.asarray(mlp_server.predict(a, []))
            np.testing.assert_array_equal(o.reshape(ref.shape), ref)

    def test_raw_batch_views_accepts_frames_end_to_end(self, mlp_server):
        x = np.ones((2, 8), np.float32)
        view = bufview.unpack_frame(bufview.pack_frame(x))
        (out,) = mlp_server.raw_batch_views([view])
        ref = np.asarray(mlp_server.predict(x, []))
        np.testing.assert_array_equal(out.reshape(ref.shape), ref)

    def test_mixed_dtype_wave_canonicalises(self, mlp_server):
        outs = mlp_server.raw_batch_views([
            np.ones((1, 8), np.float32),
            np.ones((1, 8), np.float64),  # not warmed: canonicalises
        ])
        np.testing.assert_array_equal(outs[0], outs[1])


def test_paged_submit_views_rolls_back_on_partial_admission():
    """All-or-nothing admission: when a later view's admission fails,
    the already-admitted streams are cancelled — not left decoding
    tokens nobody holds a handle to."""
    import jax
    import jax.numpy as jnp

    from seldon_core_tpu.models.paged import PagedEngine
    from seldon_core_tpu.models.transformer import TransformerLM
    from seldon_core_tpu.runtime.component import MicroserviceError

    cfg = dict(vocab_size=64, d_model=32, num_layers=1, num_heads=2, max_len=64)
    lm = TransformerLM(dtype=jnp.float32, **cfg)
    params = lm.init(jax.random.key(0), jnp.zeros((1, 4), jnp.int32))["params"]
    eng = PagedEngine(params, dtype=jnp.float32, page_size=8, max_slots=2,
                      steps_per_call=4, **cfg)
    try:
        ok = np.arange(5, dtype=np.int32) % 64
        too_long = np.arange(80, dtype=np.int32) % 64  # > max_len
        with pytest.raises(MicroserviceError):
            eng.submit_views([ok, ok, too_long], max_new_tokens=4)
        # both admitted streams rolled back: nothing left queued
        assert eng.engine_stats()["queued_streams"] == 0
    finally:
        eng.close()


def test_paged_submit_views_bit_exact_vs_submit():
    import jax
    import jax.numpy as jnp

    from seldon_core_tpu.models.paged import PagedEngine
    from seldon_core_tpu.models.transformer import TransformerLM

    cfg = dict(vocab_size=64, d_model=32, num_layers=1, num_heads=2, max_len=128)
    lm = TransformerLM(dtype=jnp.float32, **cfg)
    params = lm.init(jax.random.key(0), jnp.zeros((1, 4), jnp.int32))["params"]
    eng = PagedEngine(params, dtype=jnp.float32, page_size=8, max_slots=2,
                      steps_per_call=4, **cfg)
    try:
        prompts = [
            np.arange(5, dtype=np.int32) % 64,
            (np.arange(9, dtype=np.int32) * 3) % 64,
        ]
        views = [
            bufview.unpack_frame(bufview.pack_frame(p)) for p in prompts
        ]
        batched = eng.submit_views(views, max_new_tokens=6)
        eng.run()
        ref = [eng.submit(p, max_new_tokens=6) for p in prompts]
        eng.run()
        for b, r in zip(batched, ref):
            assert b.error is None and r.error is None
            # greedy decode bit-exact: view-submitted == array-submitted
            np.testing.assert_array_equal(np.asarray(b.result),
                                          np.asarray(r.result))
    finally:
        eng.close()


# ---------------------------------------------------------------------------
# ingress lanes (HTTP frame lane, gRPC PredictRaw, knob-off parity)
# ---------------------------------------------------------------------------


@pytest.fixture()
def loop_thread():
    loop = asyncio.new_event_loop()
    thread = threading.Thread(target=loop.run_forever, daemon=True)
    thread.start()
    yield loop
    loop.call_soon_threadsafe(loop.stop)
    thread.join(timeout=5)


def _gateway(component, two_node=False):
    from seldon_core_tpu.engine import PredictorService, UnitSpec
    from seldon_core_tpu.engine.server import Gateway

    model = UnitSpec(name="m", type="MODEL", component=component)
    if two_node:
        class Identity:
            def transform_input(self, X, names, meta=None):
                return np.asarray(X)

        root = UnitSpec(name="pre", type="TRANSFORMER", component=Identity(),
                        children=[model])
    else:
        root = model
    return Gateway([(PredictorService(root, name="p"), 1.0)])


class Doubler:
    def predict(self, X, names, meta=None):
        return np.asarray(X) * 2


class TestIngressFrameLane:
    def _handler(self, loop, two_node=True):
        from seldon_core_tpu.native.frontserver import GatewayRawHandler

        return GatewayRawHandler(_gateway(Doubler(), two_node=two_node), loop)

    def test_http_frame_lane_roundtrip(self, loop_thread):
        handler = self._handler(loop_thread)
        x = np.arange(8, dtype=np.float32).reshape(2, 4)
        status, ctype, body = handler(
            "POST", "/api/v0.1/predictions", bufview.pack_frame(x)
        )
        assert status == 200 and ctype == "application/x-seldon-raw"
        np.testing.assert_array_equal(
            bufview.unpack_frame(body).array(), x * 2
        )

    def test_frame_lane_bit_exact_vs_json_lane(self, loop_thread):
        handler = self._handler(loop_thread)
        x = np.linspace(-1, 1, 12, dtype=np.float32).reshape(3, 4)
        _, _, frame_body = handler(
            "POST", "/api/v0.1/predictions", bufview.pack_frame(x)
        )
        out_on = bufview.unpack_frame(frame_body).array()
        jreq = json.dumps({"data": {"rawTensor": {
            "shape": [3, 4], "dtype": "float32",
            "data": base64.b64encode(x.tobytes()).decode(),
        }}}).encode()
        status, _, jbody = handler("POST", "/api/v0.1/predictions", jreq)
        assert status == 200
        rt = json.loads(jbody)["data"]["rawTensor"]
        out_off = np.frombuffer(
            base64.b64decode(rt["data"]), dtype=rt["dtype"]
        ).reshape(3, 4)
        assert out_on.tobytes() == out_off.tobytes()  # bit-exact lanes

    def test_multi_frame_container_serves_batched(self, loop_thread, mlp_server):
        # the batched-submission surface: N frames in one body -> ONE
        # raw_batch_views micro-batch -> a response container
        from seldon_core_tpu.native.frontserver import GatewayRawHandler

        handler = GatewayRawHandler(_gateway(mlp_server, two_node=False),
                                    loop_thread)
        xs = [np.full((r, 8), r, np.float32) for r in (1, 2)]
        status, ctype, body = handler(
            "POST", "/predict", bufview.pack_frames(xs)
        )
        assert status == 200 and ctype == "application/x-seldon-raw"
        outs = bufview.unpack_frames(body)
        assert len(outs) == 2
        for x, o in zip(xs, outs):
            ref = np.asarray(mlp_server.predict(x, []))
            np.testing.assert_array_equal(
                o.array().reshape(ref.shape), ref
            )

    def test_multi_frame_needs_single_local_model(self, loop_thread):
        # a 2-node graph cannot serve the bookkeeping-bypassing batched
        # container: clear 400, not a wrong answer
        handler = self._handler(loop_thread, two_node=True)
        status, ctype, body = handler(
            "POST", "/predict",
            bufview.pack_frames([np.ones((1, 4), np.float32)] * 2),
        )
        assert status == 400
        assert "single-local-MODEL" in json.loads(body)["status"]["info"]

    def test_single_model_gateway_takes_predict_sync_path(self, loop_thread):
        # single local MODEL: the frame lane runs on the calling thread
        # (predict_sync) — the response must still be correct even
        # though the loop never sees the request
        handler = self._handler(loop_thread, two_node=False)
        x = np.ones((1, 4), np.float32)
        status, ctype, body = handler(
            "POST", "/predict", bufview.pack_frame(x)
        )
        assert status == 200 and ctype == "application/x-seldon-raw"
        np.testing.assert_array_equal(
            bufview.unpack_frame(body).array(), x * 2
        )

    def test_malformed_frame_is_400_json(self, loop_thread):
        handler = self._handler(loop_thread)
        bad = bufview.pack_frame(np.ones(4, np.float32))[:-2]
        status, ctype, body = handler("POST", "/predict", b"SRT1" + bad[4:])
        assert status == 400 and ctype == "application/json"
        assert json.loads(body)["status"]["reason"] == "BAD_REQUEST"

    def test_lane_off_rejects_frames_with_remedy(self, loop_thread, monkeypatch):
        monkeypatch.setenv("SELDON_TPU_ZERO_COPY", "0")
        handler = self._handler(loop_thread)
        status, _, body = handler(
            "POST", "/predict", bufview.pack_frame(np.ones(4, np.float32))
        )
        assert status == 400
        assert "SELDON_TPU_ZERO_COPY" in json.loads(body)["status"]["info"]

    def test_lane_off_json_path_is_untouched(self, loop_thread, monkeypatch):
        monkeypatch.setenv("SELDON_TPU_ZERO_COPY", "0")
        handler = self._handler(loop_thread)
        status, _, body = handler(
            "POST", "/api/v0.1/predictions",
            json.dumps({"data": {"ndarray": [[1.0, 2.0, 3.0, 4.0]]}}).encode(),
        )
        assert status == 200
        out = json.loads(body)
        assert out["data"]["ndarray"] == [[2.0, 4.0, 6.0, 8.0]]


class TestGrpcPredictRaw:
    def _handler(self, loop):
        from seldon_core_tpu.engine.native_ingress import _DeploymentGrpcHandler

        return _DeploymentGrpcHandler(_gateway(Doubler(), two_node=True), loop)

    def test_predict_raw_roundtrip(self, loop_thread):
        handler = self._handler(loop_thread)
        x = np.arange(6, dtype=np.float32).reshape(2, 3)
        status, msg, payload = handler(
            "/seldon.protos.Seldon/PredictRaw", bufview.pack_frame(x)
        )
        assert status == 0, msg
        np.testing.assert_array_equal(
            bufview.unpack_frame(payload).array(), x * 2
        )

    def test_predict_raw_malformed_is_invalid_argument(self, loop_thread):
        handler = self._handler(loop_thread)
        status, msg, _ = handler("/seldon.protos.Seldon/PredictRaw", b"SRT1xx")
        assert status == 3 and "SRT1" in msg

    def test_predict_raw_gated_off_is_unimplemented(self, loop_thread, monkeypatch):
        monkeypatch.setenv("SELDON_TPU_ZERO_COPY", "0")
        handler = self._handler(loop_thread)
        status, msg, _ = handler(
            "/seldon.protos.Seldon/PredictRaw",
            bufview.pack_frame(np.ones(3, np.float32)),
        )
        assert status == 12 and "SELDON_TPU_ZERO_COPY" in msg

    def test_predict_raw_multi_frame_batched(self, loop_thread, mlp_server):
        from seldon_core_tpu.engine.native_ingress import _DeploymentGrpcHandler

        handler = _DeploymentGrpcHandler(
            _gateway(mlp_server, two_node=False), loop_thread
        )
        xs = [np.full((r, 8), 0.5 * r, np.float32) for r in (2, 1)]
        status, msg, payload = handler(
            "/seldon.protos.Seldon/PredictRaw", bufview.pack_frames(xs)
        )
        assert status == 0, msg
        outs = bufview.unpack_frames(payload)
        for x, o in zip(xs, outs):
            ref = np.asarray(mlp_server.predict(x, []))
            np.testing.assert_array_equal(o.array().reshape(ref.shape), ref)

    def test_predict_raw_multi_frame_unstackable_is_client_fault(
            self, loop_thread, mlp_server):
        # frames that don't stack (mismatched widths) are the CLIENT's
        # mistake: INVALID_ARGUMENT (3), matching the HTTP lane's 400 —
        # never INTERNAL
        from seldon_core_tpu.engine.native_ingress import _DeploymentGrpcHandler

        handler = _DeploymentGrpcHandler(
            _gateway(mlp_server, two_node=False), loop_thread
        )
        status, msg, _ = handler(
            "/seldon.protos.Seldon/PredictRaw",
            bufview.pack_frames([np.ones((1, 8), np.float32),
                                 np.ones((1, 4), np.float32)]),
        )
        assert status == 3 and "stack" in msg

    def test_predict_raw_multi_frame_ineligible_graph(self, loop_thread):
        handler = self._handler(loop_thread)  # 2-node graph
        status, msg, _ = handler(
            "/seldon.protos.Seldon/PredictRaw",
            bufview.pack_frames([np.ones((1, 4), np.float32)] * 2),
        )
        assert status == 3 and "single-local-MODEL" in msg

    def test_proto_predict_path_unchanged(self, loop_thread):
        from seldon_core_tpu.proto import pb

        handler = self._handler(loop_thread)
        req = pb.SeldonMessage()
        req.data.rawTensor.dtype = "float32"
        req.data.rawTensor.shape.extend([1, 3])
        req.data.rawTensor.data = np.ones((1, 3), np.float32).tobytes()
        status, _, payload = handler(
            "/seldon.protos.Seldon/Predict", req.SerializeToString()
        )
        assert status == 0
        out = pb.SeldonMessage.FromString(payload)
        np.testing.assert_array_equal(
            codec.get_data_from_proto(out), np.full((1, 3), 2.0, np.float32)
        )


class TestNativeServerE2E:
    """Through the REAL C++ ingress: an SRT1 frame posted to a
    fallback-only deployment (no in-C++ model) must fall through to the
    Python buffer-view lane — the r14 C++ fix; it previously 500'd out
    of an armless fast lane."""

    def test_frame_falls_through_to_python_lane(self, loop_thread):
        import socket

        from seldon_core_tpu.native import frontserver as fsmod
        from seldon_core_tpu.native.frontserver import (
            GatewayRawHandler,
            NativeFrontServer,
            read_http_response,
        )

        if not fsmod.available():
            pytest.skip("native front server library not built")
        handler = GatewayRawHandler(_gateway(Doubler(), two_node=True),
                                    loop_thread)
        x = np.arange(8, dtype=np.float32).reshape(2, 4)
        frame = bufview.pack_frame(x)
        with NativeFrontServer(raw_handler=handler) as srv:
            req = (b"POST /api/v0.1/predictions HTTP/1.1\r\nHost: t\r\n"
                   b"Content-Type: application/x-seldon-raw\r\n"
                   b"Content-Length: " + str(len(frame)).encode()
                   + b"\r\n\r\n" + frame)
            s = socket.create_connection(("127.0.0.1", srv.port), timeout=10)
            try:
                s.sendall(req)
                status, body, _ = read_http_response(s, b"", timeout_s=20)
            finally:
                s.close()
        assert status == 200
        np.testing.assert_array_equal(bufview.unpack_frame(body).array(), x * 2)


# ---------------------------------------------------------------------------
# codec/device satellites
# ---------------------------------------------------------------------------


class TestDeviceHelpers:
    def test_from_device_many_single_fetch_matches_individual(self):
        import jax.numpy as jnp

        xs = [jnp.arange(4) + i for i in range(3)]
        many = codec.from_device_many(xs)
        for m, x in zip(many, xs):
            np.testing.assert_array_equal(m, np.asarray(x))
        # host arrays pass through
        host = codec.from_device_many([np.ones(2)])
        np.testing.assert_array_equal(host[0], np.ones(2))

    def test_to_device_skips_cast_when_dtype_matches(self):
        arr = np.arange(4, dtype=np.float32)
        x = codec.to_device(arr, dtype="float32")
        assert str(x.dtype) == "float32"
        np.testing.assert_array_equal(np.asarray(x), arr)

    def test_to_device_still_casts_when_needed(self):
        import jax.numpy as jnp

        x = codec.to_device(np.arange(4, dtype=np.float32), dtype=jnp.bfloat16)
        assert str(x.dtype) == "bfloat16"


def test_knob_is_registered_and_default_on(monkeypatch):
    from seldon_core_tpu.runtime import knobs

    assert "SELDON_TPU_ZERO_COPY" in knobs.ENV_KNOBS
    assert knobs.ENV_KNOBS["SELDON_TPU_ZERO_COPY"].zero_off
    monkeypatch.delenv("SELDON_TPU_ZERO_COPY", raising=False)
    assert bufview.zero_copy_enabled()
    monkeypatch.setenv("SELDON_TPU_ZERO_COPY", "0")
    assert not bufview.zero_copy_enabled()
