"""r20 telemetry plane, fleet half: the TelemetryAggregator's poll
loop (deadline + trace headers on every hop, breaker-contained dials,
stale-not-crashed freshness), the merged fleet view (rollup, adapter
and prefix residency), schema-version incompatibility, and the
FleetPrometheusBridge export.

Fast tier drives stub HTTP replicas (canned snapshots, captured
headers); the real 2-supervised-worker e2e is @slow.
"""

import http.server
import json
import socket
import threading
import time

import pytest

from seldon_core_tpu.controlplane import fleetview
from seldon_core_tpu.engine.transport import CircuitBreaker
from seldon_core_tpu.utils import telemetry


@pytest.fixture(autouse=True)
def _fresh_breakers():
    CircuitBreaker.reset_all()
    yield
    CircuitBreaker.reset_all()


def _point(**over):
    p = {
        "t": 1.0, "queue_depth": 2, "active_slots": 1,
        "active_slots_total": 4, "goodput_tok_s": 100.0,
        "prefill_tok_s": 40.0, "completed_s": 1.5, "prefix_hit_pct": 50.0,
        "prefix_pages_cached": 6, "pool_pages_used": 10,
        "pool_pages_total": 40, "adapters": [], "shed_s": 0.0,
        "expired_s": 0.0, "preempted_s": 0.0, "restored_s": 0.0,
        "migrated_out_s": 0.0, "migrated_in_s": 0.0, "cost_page_s_s": 2.0,
        "chunk_p99_ms": 12.0, "predict_cost_s": 0.3, "health": "healthy",
    }
    p.update(over)
    p["saturation"] = telemetry.saturation_score(p)
    return p


class _StubReplica:
    """A threaded HTTP server answering /debug/telemetry with a canned
    snapshot, capturing every request's headers."""

    def __init__(self, snapshot):
        self.snapshot = snapshot
        self.raw_body = None  # overrides snapshot when set (garbage tests)
        self.headers = []
        stub = self

        class Handler(http.server.BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 — stdlib naming
                stub.headers.append(dict(self.headers))
                body = (
                    stub.raw_body if stub.raw_body is not None
                    else json.dumps(stub.snapshot).encode()
                )
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):  # quiet
                pass

        self.server = http.server.ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        self.port = self.server.server_address[1]
        self.url = f"http://127.0.0.1:{self.port}"
        self._thread = threading.Thread(
            target=self.server.serve_forever, daemon=True
        )
        self._thread.start()

    def close(self):
        self.server.shutdown()
        self.server.server_close()


def _snapshot(replica_id, point):
    return {
        "schema_version": telemetry.TELEMETRY_SCHEMA_VERSION,
        "replica_id": replica_id, "t": 1.0, "window_s": 30.0,
        "capacity": 256, "points": [point], "latest": point,
    }


class _Clock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t


class TestEndpointParsing:
    def test_knob_grammar_named_bare_and_off(self):
        eps = fleetview.endpoints_from_knob(
            "r0=http://h0:9000, http://h1:9100/,r2=https://h2:9200"
        )
        assert eps == {
            "r0": "http://h0:9000",
            "h1:9100": "http://h1:9100",
            "r2": "https://h2:9200",
        }
        assert fleetview.endpoints_from_knob("") == {}
        assert fleetview.endpoints_from_knob("0") == {}

    def test_endpoints_from_supervisor_specs(self):
        class Spec:
            def __init__(self, port):
                self.http_port = port

        class SP:
            def __init__(self, port):
                self.spec = Spec(port)

        class Sup:
            processes = {"lm-0": SP(9700), "lm-1": SP(9701)}

        assert fleetview.endpoints_from_supervisor(Sup()) == {
            "lm-0": "http://127.0.0.1:9700",
            "lm-1": "http://127.0.0.1:9701",
        }


class TestAggregatorPolling:
    def test_two_replicas_merge_in_one_poll(self):
        a = _StubReplica(_snapshot("ra", _point(adapters=["tenant-a"])))
        b = _StubReplica(_snapshot("rb", _point(
            goodput_tok_s=60.0, queue_depth=6, adapters=["tenant-a",
                                                         "tenant-b"],
            prefix_pages_cached=2,
        )))
        agg = fleetview.TelemetryAggregator(
            endpoints={"a": a.url, "b": b.url}, poll_s=0.1, stale_s=5.0,
        )
        try:
            view = agg.poll_once()
            reps = view["replicas"]
            assert reps["a"]["state"] == "ok"
            assert reps["b"]["state"] == "ok"
            assert reps["a"]["replica_id"] == "ra"
            roll = view["rollup"]
            assert roll["replicas_total"] == 2
            assert roll["replicas_ok"] == 2
            assert roll["fleet_goodput_tok_s"] == pytest.approx(160.0)
            assert roll["fleet_queue_depth"] == 8
            assert roll["fleet_cost_page_s_s"] == pytest.approx(4.0)
            # residency maps merge across replicas
            assert view["adapters"] == {
                "tenant-a": ["a", "b"], "tenant-b": ["b"],
            }
            assert view["prefix_pages"] == {"a": 6, "b": 2}
        finally:
            a.close()
            b.close()

    def test_poll_hops_carry_deadline_and_trace_headers(self):
        from seldon_core_tpu.utils import deadlines, tracing

        a = _StubReplica(_snapshot("ra", _point()))
        agg = fleetview.TelemetryAggregator(
            endpoints={"a": a.url}, poll_s=0.1, stale_s=5.0,
        )
        tracer = tracing.setup_tracing("fleet-test")
        try:
            with deadlines.activate(deadlines.Deadline.after_ms(30000)):
                with tracer.span("fleet.poll", trace_id="fleet-puid"):
                    agg.poll_once()
            hdrs = a.headers[-1]
            assert int(hdrs["X-Seldon-Deadline-Ms"]) > 0
            assert "traceparent" in {k.lower() for k in hdrs}
            # window rides the query, not a header
            assert agg.replica_states()["a"]["state"] == "ok"
        finally:
            tracing._tracer = None
            a.close()

    def test_killed_replica_goes_stale_not_crashed(self):
        """The freshness criterion: a SIGKILLed replica's last snapshot
        is retained and ages to `stale`; the poll loop neither raises
        nor marks the surviving replica."""
        clock = _Clock()
        a = _StubReplica(_snapshot("ra", _point()))
        b = _StubReplica(_snapshot("rb", _point()))
        agg = fleetview.TelemetryAggregator(
            endpoints={"a": a.url, "b": b.url}, poll_s=0.1, stale_s=5.0,
            clock=clock,
        )
        try:
            agg.poll_once()
            assert {r["state"] for r in agg.replica_states().values()} == {"ok"}
            a.close()  # the "SIGKILL": connection refused from now on
            clock.t += 6.0  # past stale_s
            view = agg.poll_once()  # must not raise
            reps = view["replicas"]
            assert reps["a"]["state"] == "stale"
            assert reps["a"]["last_err"]  # the fault is reported
            assert reps["a"]["latest"]["goodput_tok_s"] == 100.0  # retained
            assert reps["b"]["state"] == "ok"
            # stale replicas drop OUT of the capacity rollup
            roll = view["rollup"]
            assert roll["replicas_ok"] == 1
            assert roll["replicas_stale"] == 1
            assert roll["fleet_goodput_tok_s"] == pytest.approx(100.0)
        finally:
            b.close()

    def test_future_schema_version_marks_incompatible(self):
        snap = _snapshot("ra", _point())
        snap["schema_version"] = telemetry.TELEMETRY_SCHEMA_VERSION + 1
        a = _StubReplica(snap)
        agg = fleetview.TelemetryAggregator(
            endpoints={"a": a.url}, poll_s=0.1, stale_s=5.0,
        )
        try:
            view = agg.poll_once()
            r = view["replicas"]["a"]
            assert r["state"] == "incompatible"
            assert "schema_version" in r["last_err"]
            assert view["rollup"]["replicas_incompatible"] == 1
            assert view["rollup"]["replicas_ok"] == 0
        finally:
            a.close()

    def test_garbage_answer_marks_incompatible_without_tripping_breaker(self):
        a = _StubReplica(None)
        a.raw_body = b"not json at all"
        agg = fleetview.TelemetryAggregator(
            endpoints={"a": a.url}, poll_s=0.1, stale_s=5.0,
        )
        try:
            for _ in range(8):  # more than the breaker's trip threshold
                agg.poll_once()
            assert agg.replica_states()["a"]["state"] == "incompatible"
            # an answering endpoint is breaker-healthy: garbage never
            # opens the circuit (the replica is alive, just wrong)
            breaker = CircuitBreaker._registry.get(f"fleet:{a.url}")
            if breaker is not None:
                assert breaker.counters["trips"] == 0
        finally:
            a.close()

    def test_dead_endpoint_trips_breaker_then_fast_fails(self):
        with socket.socket() as s:  # a port with nothing listening
            s.bind(("127.0.0.1", 0))
            dead = f"http://127.0.0.1:{s.getsockname()[1]}"
        agg = fleetview.TelemetryAggregator(
            endpoints={"a": dead}, poll_s=0.1, stale_s=5.0, timeout_s=0.5,
        )
        for _ in range(8):
            agg.poll_once()  # never raises
        breaker = CircuitBreaker._registry.get(f"fleet:{dead}")
        assert breaker is not None
        assert breaker.counters["trips"] >= 1
        assert breaker.counters["fastfails"] >= 1  # open = no dial attempt
        assert agg.replica_states()["a"]["state"] == "never"


class TestFleetBridge:
    def test_rollup_and_replica_gauges_export(self):
        import prometheus_client

        from seldon_core_tpu.utils.metrics import (
            FLEET_EXCLUDED,
            FLEET_METRICS,
            FleetPrometheusBridge,
        )

        a = _StubReplica(_snapshot("ra", _point()))
        registry = prometheus_client.CollectorRegistry()
        agg = fleetview.TelemetryAggregator(
            endpoints={"a": a.url}, poll_s=0.1, stale_s=5.0,
        )
        agg.bridge = FleetPrometheusBridge(agg, registry=registry)
        try:
            agg.poll_once()  # collects the bridge after the poll
            text = prometheus_client.generate_latest(registry).decode()
            rollup = agg.fleet_rollup()
            for key, (_, metric, _) in FLEET_METRICS.items():
                assert metric in text, f"{key} -> {metric} not exported"
            assert 'seldon_tpu_fleet_replica_saturation{replica="a"}' in text
            assert 'seldon_tpu_fleet_replica_state{replica="a"} 0.0' in text
            assert f"seldon_tpu_fleet_replicas {float(rollup['replicas_ok'])}" \
                in text
            # the contract closes both ways: every rollup key is mapped
            # or excluded (graftlint enforces this statically too)
            assert set(rollup) == set(FLEET_METRICS) | FLEET_EXCLUDED
        finally:
            a.close()


@pytest.mark.slow
def test_two_supervised_workers_converge_and_survive_sigkill():
    """The full r20 fleet loop across real processes: two supervised
    StreamingLM replicas serve /debug/telemetry; the aggregator (fed by
    endpoints_from_supervisor) reports BOTH ok within one poll; a
    SIGKILLed replica transitions to `stale` without failing the poll
    loop, while the survivor keeps reporting."""
    import urllib.request

    import numpy as np

    from seldon_core_tpu.controlplane.supervisor import (
        ProcessSpec,
        Supervisor,
    )

    def _free_port():
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            return s.getsockname()[1]

    params = json.dumps([
        {"name": "vocab_size", "value": "256", "type": "INT"},
        {"name": "d_model", "value": "32", "type": "INT"},
        {"name": "num_layers", "value": "1", "type": "INT"},
        {"name": "num_heads", "value": "2", "type": "INT"},
        {"name": "max_len", "value": "128", "type": "INT"},
        {"name": "max_new_tokens", "value": "8", "type": "INT"},
        {"name": "max_slots", "value": "2", "type": "INT"},
        {"name": "steps_per_call", "value": "4", "type": "INT"},
        {"name": "seed", "value": "0", "type": "INT"},
    ])
    env = {"JAX_PLATFORMS": "cpu", "SELDON_TPU_PLATFORM": "cpu"}
    sup = Supervisor()
    try:
        for i in range(2):
            sup.add(ProcessSpec(
                name=f"lm-{i}",
                component="seldon_core_tpu.models.paged.StreamingLM",
                http_port=_free_port(), grpc_port=_free_port(),
                parameters_json=params, env=dict(env),
            ), wait_ready_s=240.0)
        endpoints = fleetview.endpoints_from_supervisor(sup)
        assert set(endpoints) == {"lm-0", "lm-1"}

        # drive one real predict through lm-0 so its ring has traffic
        port0 = sup.processes["lm-0"].spec.http_port
        prompt = (np.arange(5) % 64).tolist()
        req = urllib.request.Request(
            f"http://127.0.0.1:{port0}/predict",
            data=json.dumps({"data": {"ndarray": [prompt]}}).encode(),
            headers={"Content-Type": "application/json"},
        )
        urllib.request.urlopen(req, timeout=60).read()

        agg = fleetview.TelemetryAggregator(
            endpoints=endpoints, poll_s=0.2, stale_s=2.0, timeout_s=10.0,
        )
        view = agg.poll_once()  # ONE poll reports the whole fleet
        assert {r["state"] for r in view["replicas"].values()} == {"ok"}
        assert view["rollup"]["replicas_ok"] == 2
        ids = {r["replica_id"] for r in view["replicas"].values()}
        assert ids == {"lm-0", "lm-1"}  # PREDICTIVE_UNIT_ID round-trip

        # SIGKILL one replica (and stop its supervisor respawns)
        victim = sup.processes["lm-1"]
        victim._stop.set()
        victim.proc.kill()
        victim.proc.wait(timeout=30)
        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline:
            view = agg.poll_once()  # must never raise
            if view["replicas"]["lm-1"]["state"] == "stale":
                break
            time.sleep(0.3)
        assert view["replicas"]["lm-1"]["state"] == "stale"
        assert view["replicas"]["lm-0"]["state"] == "ok"
        assert view["rollup"]["replicas_ok"] == 1
        assert view["rollup"]["replicas_stale"] == 1
    finally:
        sup.stop_all()


class TestGatewayDebugEndpoints:
    """The gateway's r20 /debug surface: the replica snapshot at
    /debug/telemetry and the merged fleet view at /debug/fleet."""

    def _gateway(self, component):
        from seldon_core_tpu.engine import PredictorService, UnitSpec
        from seldon_core_tpu.engine.server import Gateway

        svc = PredictorService(
            UnitSpec(name="lm", type="MODEL", component=component),
            name="main",
        )
        return Gateway([(svc, 1.0)])

    def test_debug_telemetry_serves_component_snapshot(self):
        import asyncio

        from aiohttp.test_utils import TestClient, TestServer

        from seldon_core_tpu.runtime import TPUComponent
        from seldon_core_tpu.engine.server import build_gateway_app

        class RingModel(TPUComponent):
            windows = []

            def telemetry_snapshot(self, window_s=0.0):
                self.windows.append(window_s)
                return _snapshot("ra", _point())

            def predict(self, X, names, meta=None):
                return X

        app = build_gateway_app(self._gateway(RingModel()))

        async def scenario():
            client = TestClient(TestServer(app))
            await client.start_server()
            snap = await (await client.get("/debug/telemetry")).json()
            await client.get("/debug/telemetry", params={"window": "30"})
            await client.close()
            return snap

        snap = asyncio.run(scenario())
        assert snap["schema_version"] == telemetry.TELEMETRY_SCHEMA_VERSION
        assert snap["replica_id"] == "ra"
        assert RingModel.windows == [0.0, 30.0]  # ?window= reaches the ring

    def test_debug_telemetry_without_ring_reports_disabled(self):
        import asyncio

        import numpy as np

        from aiohttp.test_utils import TestClient, TestServer

        from seldon_core_tpu.runtime import TPUComponent
        from seldon_core_tpu.engine.server import build_gateway_app

        class Plain(TPUComponent):
            def predict(self, X, names, meta=None):
                return np.asarray(X)

        app = build_gateway_app(self._gateway(Plain()))

        async def scenario():
            client = TestClient(TestServer(app))
            await client.start_server()
            out = await (await client.get("/debug/telemetry")).json()
            await client.close()
            return out

        out = asyncio.run(scenario())
        assert out["components"] == {}
        assert "info" in out

    def test_debug_fleet_polls_knob_endpoints(self, monkeypatch):
        import asyncio

        import numpy as np

        from aiohttp.test_utils import TestClient, TestServer

        from seldon_core_tpu.runtime import TPUComponent
        from seldon_core_tpu.engine.server import build_gateway_app

        class Plain(TPUComponent):
            def predict(self, X, names, meta=None):
                return np.asarray(X)

        a = _StubReplica(_snapshot("ra", _point()))
        monkeypatch.setenv("SELDON_TPU_FLEET_ENDPOINTS",
                           f"ra={a.url}")
        app = build_gateway_app(self._gateway(Plain()))

        async def scenario():
            client = TestClient(TestServer(app))
            await client.start_server()
            view = await (await client.get("/debug/fleet")).json()
            again = await (await client.get("/debug/fleet")).json()
            await client.close()
            return view, again

        try:
            view, again = asyncio.run(scenario())
            assert view["enabled"] is True
            assert view["replicas"]["ra"]["state"] == "ok"
            assert view["rollup"]["replicas_ok"] == 1
            # polls are throttled to the poll interval: the immediate
            # second GET serves the same poll's view
            assert again["polls"] == view["polls"] == 1
        finally:
            a.close()

    def test_debug_fleet_without_endpoints_reports_disabled(self, monkeypatch):
        import asyncio

        import numpy as np

        from aiohttp.test_utils import TestClient, TestServer

        from seldon_core_tpu.runtime import TPUComponent
        from seldon_core_tpu.engine.server import build_gateway_app

        class Plain(TPUComponent):
            def predict(self, X, names, meta=None):
                return np.asarray(X)

        monkeypatch.delenv("SELDON_TPU_FLEET_ENDPOINTS", raising=False)
        app = build_gateway_app(self._gateway(Plain()))

        async def scenario():
            client = TestClient(TestServer(app))
            await client.start_server()
            out = await (await client.get("/debug/fleet")).json()
            await client.close()
            return out

        out = asyncio.run(scenario())
        assert out["enabled"] is False
