"""Chaos test for the paged DCN edge (VERDICT r5 #8).

A StreamingLM (paged continuous-batching engine) runs as a
``remote: true`` graph node in a supervisor-spawned worker process —
the DCN-edge deployment shape.  Mid-request, the worker is SIGKILLed:

* the in-flight paged stream must fail CLEANLY — a clear upstream
  error (or FAILURE status) within a bounded wait, never a hang;
* the supervisor's restart loop must respawn the worker on the same
  endpoint, and the retried request must return the CORRECT answer —
  bit-identical to the pre-kill greedy result (params are
  seed-deterministic, greedy decode ignores sampling seeds).

Reference analogue: InternalPredictionService.java:439-467 (engine
retry semantics against microservice pods k8s restarts) and the
reference's rolling-update disruption test.  Fast tier: tiny model,
one worker spawn + one respawn.
"""

import asyncio
import time

import numpy as np
import pytest

from seldon_core_tpu.controlplane import TpuDeployment
from seldon_core_tpu.controlplane.deployer import build_generation
from seldon_core_tpu.runtime.component import MicroserviceError
from seldon_core_tpu.runtime.message import InternalMessage


def _chaos_spec() -> TpuDeployment:
    params = [
        # big enough that 240 one-step chunks span seconds even on a
        # fast CPU (the kill must land mid-stream), small enough that
        # the worker's compiles stay in the readiness budget
        {"name": "vocab_size", "value": "2048", "type": "INT"},
        {"name": "d_model", "value": "64", "type": "INT"},
        {"name": "num_layers", "value": "2", "type": "INT"},
        {"name": "num_heads", "value": "4", "type": "INT"},
        {"name": "max_len", "value": "256", "type": "INT"},
        {"name": "max_new_tokens", "value": "240", "type": "INT"},
        {"name": "page_size", "value": "8", "type": "INT"},
        {"name": "max_slots", "value": "2", "type": "INT"},
        # steps_per_call=1 -> one compiled chunk per token: the request
        # spans many engine steps, so the kill reliably lands mid-stream
        {"name": "steps_per_call", "value": "1", "type": "INT"},
        {"name": "seed", "value": "0", "type": "INT"},
    ]
    return TpuDeployment.from_dict(
        {
            "name": "paged-chaos",
            "annotations": {
                # the long decode (and its first-request compiles on a
                # loaded CI host) must not trip the default 5 s gRPC
                # deadline before the chaos does its work
                "seldon.io/grpc-read-timeout": "180000",
                # worker boot = interpreter + jax import + engine build;
                # ~45 s cold on the 1-CPU CI host
                "seldon.io/worker-ready-timeout-s": "120",
            },
            "predictors": [
                {
                    "name": "main",
                    "traffic": 100,
                    "graph": {
                        "name": "paged-lm",
                        "type": "MODEL",
                        "component_class":
                            "seldon_core_tpu.models.paged.StreamingLM",
                        "parameters": params,
                        "remote": True,
                    },
                }
            ],
        }
    )


@pytest.mark.e2e
def test_worker_killed_mid_request_fails_cleanly_then_restart_recovers():
    spec = _chaos_spec()
    prompt = (np.arange(6, dtype=np.int32) % 64)[None, :]

    async def scenario():
        gen = await asyncio.to_thread(build_generation, spec)
        try:
            assert gen.supervisor is not None
            worker = list(gen.supervisor.processes.values())[0]
            assert worker.alive() and worker.ready()

            # ---- 1. baseline: a full request against the live worker
            # (pays the worker's compiles; greedy + seed-deterministic
            # params make this THE correct answer for every retry)
            out = await gen.gateway.predict(InternalMessage(payload=prompt))
            assert out.status is None or out.status.get("status") != "FAILURE"
            expected = np.asarray(out.array())
            assert expected.shape[-1] == 240  # the full decode ran

            # ---- 2. kill the worker MID-REQUEST: the in-flight paged
            # stream must fail cleanly within a bounded wait, not hang.
            # Shrinking sleeps per attempt: on a host fast enough to
            # finish 240 warm chunks inside the window, retry with a
            # tighter one (killing at 0 s — mid-connection — is still a
            # valid chaos shape; the assertions below don't change).
            inflight = None
            for delay in (0.15, 0.05, 0.0):
                inflight = asyncio.ensure_future(
                    gen.gateway.predict(InternalMessage(payload=prompt))
                )
                if delay:
                    await asyncio.sleep(delay)
                if not inflight.done():
                    break
            assert not inflight.done(), (
                "request finished before every kill window — decode too "
                "fast for the chaos; raise max_new_tokens"
            )
            worker.proc.kill()  # SIGKILL, no grace — the chaos
            t0 = time.monotonic()
            failed_cleanly = False
            try:
                res = await asyncio.wait_for(inflight, timeout=30.0)
                status = (res.status or {}).get("status")
                failure_reason = str(res.status)
                failed_cleanly = status == "FAILURE"
            except MicroserviceError as e:
                failure_reason = str(e)
                failed_cleanly = True
            elapsed = time.monotonic() - t0
            assert failed_cleanly, (
                "in-flight stream on a killed worker must surface an "
                f"error, got a success payload ({failure_reason})"
            )
            assert elapsed < 30.0  # bounded: wait_for would have thrown

            # ---- 3. the supervisor restart path: same spec, same
            # endpoint; readiness returns once the respawned process
            # serves (restart backoff starts at 0.5 s)
            deadline = time.monotonic() + 120.0
            while time.monotonic() < deadline:
                if worker.alive() and worker.ready():
                    break
                await asyncio.sleep(0.25)
            else:
                raise AssertionError("supervisor never respawned the worker")
            assert worker.restarts >= 1

            # ---- 4. correctness after recovery: the retried request
            # returns the exact pre-kill greedy answer
            out2 = await gen.gateway.predict(InternalMessage(payload=prompt))
            assert out2.status is None or out2.status.get("status") != "FAILURE"
            np.testing.assert_array_equal(np.asarray(out2.array()), expected)
        finally:
            await gen.gateway.close()
            await asyncio.to_thread(gen.stop_scaling)

    asyncio.run(scenario())
