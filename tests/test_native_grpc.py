"""Native h2c gRPC lane: the C++ ingress serving seldon.protos.Seldon/
Predict over HTTP/2 prior-knowledge cleartext — the native lane for the
contract surface (reference: the Java engine's gRPC server,
SeldonGrpcServer.java:30-60; here the whole request path is C++ until
the batched model call).

Driven by the REAL grpc Python client over real loopback sockets (the
strictest conformance check available: grpc-core's HPACK encoder,
flow-control windows and framing must all interoperate), plus the
native h2c load client for throughput-shaped traffic.
"""

import ctypes
import http.client
import json
import threading

import grpc
import numpy as np
import pytest

from seldon_core_tpu.native import frontserver as fsmod
from seldon_core_tpu.native import get_lib
from seldon_core_tpu.native.frontserver import (
    NativeFrontServer,
    native_load_grpc,
)
from seldon_core_tpu.proto import pb, services

pytestmark = pytest.mark.skipif(
    not fsmod.available(), reason="native front server library not built"
)


def _channel(port):
    return grpc.insecure_channel(f"127.0.0.1:{port}")


def _tensor_req(arr, puid=None):
    arr = np.asarray(arr, np.float64)
    req = pb.SeldonMessage()
    req.data.tensor.shape.extend(list(arr.shape))
    req.data.tensor.values.extend(arr.ravel().tolist())
    if puid:
        req.meta.puid = puid
    return req


class TestHuffmanTable:
    def test_selftest(self):
        """Canonical construction must reproduce the published RFC 7541
        spot codes and round-trip a gRPC method path."""
        lib = get_lib()
        lib.h2_huff_selftest.restype = ctypes.c_int32
        assert lib.h2_huff_selftest() == 0


class TestGrpcPredict:
    def test_tensor_roundtrip_with_puid(self):
        def model(batch):
            return batch.astype(np.float32).sum(axis=1, keepdims=True) * np.ones(
                (1, 3), np.float32
            )

        with NativeFrontServer(model_fn=model, feature_dim=4, out_dim=3,
                               model_name="m") as srv:
            with _channel(srv.port) as ch:
                predict = services.unary_callable(ch, "Seldon", "Predict")
                resp = predict(_tensor_req([[1, 2, 3, 4], [5, 6, 7, 8]],
                                           puid="p-123"), timeout=10)
        assert list(resp.data.tensor.shape) == [2, 3]
        assert list(resp.data.tensor.values) == [10.0] * 3 + [26.0] * 3
        assert resp.meta.puid == "p-123"
        assert dict(resp.meta.requestPath) == {"m": "native"}

    def test_raw_tensor_uint8_mirrored(self):
        seen_dtypes = []

        def model(batch):
            seen_dtypes.append(batch.dtype)
            return batch.astype(np.float32) * 2.0

        with NativeFrontServer(model_fn=model, feature_dim=4, out_dim=4) as srv:
            req = pb.SeldonMessage()
            req.data.rawTensor.dtype = "uint8"
            req.data.rawTensor.shape.extend([1, 4])
            req.data.rawTensor.data = np.array([[1, 2, 3, 4]], np.uint8).tobytes()
            with _channel(srv.port) as ch:
                predict = services.unary_callable(ch, "Seldon", "Predict")
                resp = predict(req, timeout=10)
        # request used rawTensor -> response mirrors rawTensor (f32)
        rt = resp.data.rawTensor
        assert rt.dtype == "float32"
        out = np.frombuffer(rt.data, np.float32).reshape(list(rt.shape))
        np.testing.assert_allclose(out, [[2.0, 4.0, 6.0, 8.0]])
        assert seen_dtypes == [np.dtype(np.uint8)]

    def test_unimplemented_method(self):
        with NativeFrontServer(stub=True, feature_dim=4, out_dim=3) as srv:
            with _channel(srv.port) as ch:
                fb = services.unary_callable(ch, "Seldon", "SendFeedback")
                with pytest.raises(grpc.RpcError) as exc:
                    fb(pb.Feedback(), timeout=10)
        assert exc.value.code() == grpc.StatusCode.UNIMPLEMENTED

    def test_inexpressible_payload_invalid_argument(self):
        with NativeFrontServer(stub=True, feature_dim=4, out_dim=3) as srv:
            req = pb.SeldonMessage()
            req.strData = "not a tensor"
            with _channel(srv.port) as ch:
                predict = services.unary_callable(ch, "Seldon", "Predict")
                with pytest.raises(grpc.RpcError) as exc:
                    predict(req, timeout=10)
        assert exc.value.code() == grpc.StatusCode.INVALID_ARGUMENT

    def test_model_exception_is_internal(self):
        def model(batch):
            raise RuntimeError("boom")

        with NativeFrontServer(model_fn=model, feature_dim=4, out_dim=3) as srv:
            with _channel(srv.port) as ch:
                predict = services.unary_callable(ch, "Seldon", "Predict")
                with pytest.raises(grpc.RpcError) as exc:
                    predict(_tensor_req([[1, 2, 3, 4]]), timeout=10)
        assert exc.value.code() == grpc.StatusCode.INTERNAL

    def test_sequential_calls_exercise_dynamic_table(self):
        """Repeated calls on one channel: grpc-core indexes headers into
        the HPACK dynamic table after the first request — later requests
        arrive as indexed fields our decoder must resolve."""
        with NativeFrontServer(stub=True, feature_dim=4, out_dim=3) as srv:
            with _channel(srv.port) as ch:
                predict = services.unary_callable(ch, "Seldon", "Predict")
                for _ in range(40):
                    resp = predict(_tensor_req([[1, 2, 3, 4]]), timeout=10)
        assert len(resp.data.tensor.values) == 3

    def test_concurrent_streams_one_channel(self):
        """Many interleaved streams on a single h2 connection."""

        def model(batch):
            return batch.astype(np.float32).sum(axis=1, keepdims=True)

        errs = []
        with NativeFrontServer(model_fn=model, feature_dim=2, out_dim=1,
                               max_batch=16) as srv:
            with _channel(srv.port) as ch:
                predict = services.unary_callable(ch, "Seldon", "Predict")

                def worker(v):
                    try:
                        for _ in range(10):
                            resp = predict(_tensor_req([[v, v]]), timeout=10)
                            assert list(resp.data.tensor.values) == [2.0 * v]
                    except Exception as e:  # noqa: BLE001
                        errs.append(e)

                threads = [threading.Thread(target=worker, args=(float(i + 1),))
                           for i in range(8)]
                for t in threads:
                    t.start()
                for t in threads:
                    t.join()
        assert not errs

    def test_large_request_flow_control(self):
        """A multi-megabyte rawTensor request spans many DATA frames and
        needs window updates both ways."""
        rows, cols = 64, 50000  # ~3.2 MB uint8

        def model(batch):
            return batch.astype(np.float32).sum(axis=1, keepdims=True)

        with NativeFrontServer(model_fn=model, feature_dim=cols, out_dim=1,
                               max_batch=64) as srv:
            req = pb.SeldonMessage()
            req.data.rawTensor.dtype = "uint8"
            req.data.rawTensor.shape.extend([rows, cols])
            req.data.rawTensor.data = np.ones((rows, cols), np.uint8).tobytes()
            with _channel(srv.port) as ch:
                predict = services.unary_callable(ch, "Seldon", "Predict")
                resp = predict(req, timeout=30)
        rt = resp.data.rawTensor
        out = np.frombuffer(rt.data, np.float32).reshape(list(rt.shape))
        assert out.shape == (rows, 1)
        np.testing.assert_allclose(out[:, 0], float(cols))


class TestHttpCoexistence:
    def test_http1_and_h2_share_the_port(self):
        """HTTP/1.1 JSON and h2c gRPC land on the same listener."""
        with NativeFrontServer(stub=True, feature_dim=4, out_dim=3) as srv:
            conn = http.client.HTTPConnection("127.0.0.1", srv.port, timeout=10)
            conn.request("POST", "/api/v0.1/predictions",
                         body=json.dumps({"data": {"tensor": {
                             "shape": [1, 4], "values": [1, 2, 3, 4]}}}),
                         headers={"Content-Type": "application/json"})
            r = conn.getresponse()
            http_body = json.loads(r.read())
            conn.close()
            assert r.status == 200
            with _channel(srv.port) as ch:
                predict = services.unary_callable(ch, "Seldon", "Predict")
                resp = predict(_tensor_req([[1, 2, 3, 4]]), timeout=10)
        assert http_body["data"]["tensor"]["values"][0] == pytest.approx(0.9)
        assert resp.data.tensor.values[0] == pytest.approx(0.9)


class TestNativeGrpcLoadClient:
    def test_stub_load_and_error_classification(self):
        lib = get_lib()
        if not hasattr(lib, "lg_run_h2"):
            pytest.skip("lg_run_h2 not in native lib")
        with NativeFrontServer(stub=True, feature_dim=4, out_dim=3) as srv:
            req = _tensor_req([[1, 2, 3, 4]])
            out = native_load_grpc(
                srv.port, "/seldon.protos.Seldon/Predict",
                req.SerializeToString(), seconds=1.5, connections=2, depth=16,
            )
            assert out["ok"] > 0 and out["non2xx"] == 0 and out["errors"] == 0
            bad = native_load_grpc(
                srv.port, "/seldon.protos.Seldon/SendFeedback", b"",
                seconds=0.5, connections=1, depth=2,
            )
            assert bad["ok"] == 0 and bad["non2xx"] > 0
