"""Native h2c gRPC lane: the C++ ingress serving seldon.protos.Seldon/
Predict over HTTP/2 prior-knowledge cleartext — the native lane for the
contract surface (reference: the Java engine's gRPC server,
SeldonGrpcServer.java:30-60; here the whole request path is C++ until
the batched model call).

Driven by the REAL grpc Python client over real loopback sockets (the
strictest conformance check available: grpc-core's HPACK encoder,
flow-control windows and framing must all interoperate), plus the
native h2c load client for throughput-shaped traffic.
"""

import ctypes
import http.client
import json
import threading
import time

import grpc
import numpy as np
import pytest

from seldon_core_tpu.native import frontserver as fsmod
from seldon_core_tpu.native import get_lib
from seldon_core_tpu.native.frontserver import (
    NativeFrontServer,
    native_load_grpc,
)
from seldon_core_tpu.proto import pb, services

pytestmark = pytest.mark.skipif(
    not fsmod.available(), reason="native front server library not built"
)


def _channel(port):
    return grpc.insecure_channel(f"127.0.0.1:{port}")


def _tensor_req(arr, puid=None):
    arr = np.asarray(arr, np.float64)
    req = pb.SeldonMessage()
    req.data.tensor.shape.extend(list(arr.shape))
    req.data.tensor.values.extend(arr.ravel().tolist())
    if puid:
        req.meta.puid = puid
    return req


class TestHuffmanTable:
    def test_selftest(self):
        """Canonical construction must reproduce the published RFC 7541
        spot codes and round-trip a gRPC method path."""
        lib = get_lib()
        lib.h2_huff_selftest.restype = ctypes.c_int32
        assert lib.h2_huff_selftest() == 0


class TestGrpcPredict:
    def test_tensor_roundtrip_with_puid(self):
        def model(batch):
            return batch.astype(np.float32).sum(axis=1, keepdims=True) * np.ones(
                (1, 3), np.float32
            )

        with NativeFrontServer(model_fn=model, feature_dim=4, out_dim=3,
                               model_name="m") as srv:
            with _channel(srv.port) as ch:
                predict = services.unary_callable(ch, "Seldon", "Predict")
                resp = predict(_tensor_req([[1, 2, 3, 4], [5, 6, 7, 8]],
                                           puid="p-123"), timeout=10)
        assert list(resp.data.tensor.shape) == [2, 3]
        assert list(resp.data.tensor.values) == [10.0] * 3 + [26.0] * 3
        assert resp.meta.puid == "p-123"
        assert dict(resp.meta.requestPath) == {"m": "native"}

    def test_raw_tensor_uint8_mirrored(self):
        seen_dtypes = []

        def model(batch):
            seen_dtypes.append(batch.dtype)
            return batch.astype(np.float32) * 2.0

        with NativeFrontServer(model_fn=model, feature_dim=4, out_dim=4) as srv:
            req = pb.SeldonMessage()
            req.data.rawTensor.dtype = "uint8"
            req.data.rawTensor.shape.extend([1, 4])
            req.data.rawTensor.data = np.array([[1, 2, 3, 4]], np.uint8).tobytes()
            with _channel(srv.port) as ch:
                predict = services.unary_callable(ch, "Seldon", "Predict")
                resp = predict(req, timeout=10)
        # request used rawTensor -> response mirrors rawTensor (f32)
        rt = resp.data.rawTensor
        assert rt.dtype == "float32"
        out = np.frombuffer(rt.data, np.float32).reshape(list(rt.shape))
        np.testing.assert_allclose(out, [[2.0, 4.0, 6.0, 8.0]])
        assert seen_dtypes == [np.dtype(np.uint8)]

    def test_unimplemented_method(self):
        with NativeFrontServer(stub=True, feature_dim=4, out_dim=3) as srv:
            with _channel(srv.port) as ch:
                fb = services.unary_callable(ch, "Seldon", "SendFeedback")
                with pytest.raises(grpc.RpcError) as exc:
                    fb(pb.Feedback(), timeout=10)
        assert exc.value.code() == grpc.StatusCode.UNIMPLEMENTED

    def test_inexpressible_payload_invalid_argument(self):
        with NativeFrontServer(stub=True, feature_dim=4, out_dim=3) as srv:
            req = pb.SeldonMessage()
            req.strData = "not a tensor"
            with _channel(srv.port) as ch:
                predict = services.unary_callable(ch, "Seldon", "Predict")
                with pytest.raises(grpc.RpcError) as exc:
                    predict(req, timeout=10)
        assert exc.value.code() == grpc.StatusCode.INVALID_ARGUMENT

    def test_model_exception_is_internal(self):
        def model(batch):
            raise RuntimeError("boom")

        with NativeFrontServer(model_fn=model, feature_dim=4, out_dim=3) as srv:
            with _channel(srv.port) as ch:
                predict = services.unary_callable(ch, "Seldon", "Predict")
                with pytest.raises(grpc.RpcError) as exc:
                    predict(_tensor_req([[1, 2, 3, 4]]), timeout=10)
        assert exc.value.code() == grpc.StatusCode.INTERNAL

    def test_sequential_calls_exercise_dynamic_table(self):
        """Repeated calls on one channel: grpc-core indexes headers into
        the HPACK dynamic table after the first request — later requests
        arrive as indexed fields our decoder must resolve."""
        with NativeFrontServer(stub=True, feature_dim=4, out_dim=3) as srv:
            with _channel(srv.port) as ch:
                predict = services.unary_callable(ch, "Seldon", "Predict")
                for _ in range(40):
                    resp = predict(_tensor_req([[1, 2, 3, 4]]), timeout=10)
        assert len(resp.data.tensor.values) == 3

    def test_concurrent_streams_one_channel(self):
        """Many interleaved streams on a single h2 connection."""

        def model(batch):
            return batch.astype(np.float32).sum(axis=1, keepdims=True)

        errs = []
        with NativeFrontServer(model_fn=model, feature_dim=2, out_dim=1,
                               max_batch=16) as srv:
            with _channel(srv.port) as ch:
                predict = services.unary_callable(ch, "Seldon", "Predict")

                def worker(v):
                    try:
                        for _ in range(10):
                            resp = predict(_tensor_req([[v, v]]), timeout=10)
                            assert list(resp.data.tensor.values) == [2.0 * v]
                    except Exception as e:  # noqa: BLE001
                        errs.append(e)

                threads = [threading.Thread(target=worker, args=(float(i + 1),))
                           for i in range(8)]
                for t in threads:
                    t.start()
                for t in threads:
                    t.join()
        assert not errs

    def test_large_request_flow_control(self):
        """A multi-megabyte rawTensor request spans many DATA frames and
        needs window updates both ways."""
        rows, cols = 64, 50000  # ~3.2 MB uint8

        def model(batch):
            return batch.astype(np.float32).sum(axis=1, keepdims=True)

        with NativeFrontServer(model_fn=model, feature_dim=cols, out_dim=1,
                               max_batch=64) as srv:
            req = pb.SeldonMessage()
            req.data.rawTensor.dtype = "uint8"
            req.data.rawTensor.shape.extend([rows, cols])
            req.data.rawTensor.data = np.ones((rows, cols), np.uint8).tobytes()
            with _channel(srv.port) as ch:
                predict = services.unary_callable(ch, "Seldon", "Predict")
                resp = predict(req, timeout=30)
        rt = resp.data.rawTensor
        out = np.frombuffer(rt.data, np.float32).reshape(list(rt.shape))
        assert out.shape == (rows, 1)
        np.testing.assert_allclose(out[:, 0], float(cols))


class TestHttpCoexistence:
    def test_http1_and_h2_share_the_port(self):
        """HTTP/1.1 JSON and h2c gRPC land on the same listener."""
        with NativeFrontServer(stub=True, feature_dim=4, out_dim=3) as srv:
            conn = http.client.HTTPConnection("127.0.0.1", srv.port, timeout=10)
            conn.request("POST", "/api/v0.1/predictions",
                         body=json.dumps({"data": {"tensor": {
                             "shape": [1, 4], "values": [1, 2, 3, 4]}}}),
                         headers={"Content-Type": "application/json"})
            r = conn.getresponse()
            http_body = json.loads(r.read())
            conn.close()
            assert r.status == 200
            with _channel(srv.port) as ch:
                predict = services.unary_callable(ch, "Seldon", "Predict")
                resp = predict(_tensor_req([[1, 2, 3, 4]]), timeout=10)
        assert http_body["data"]["tensor"]["values"][0] == pytest.approx(0.9)
        assert resp.data.tensor.values[0] == pytest.approx(0.9)


class TestNativeGrpcLoadClient:
    def test_stub_load_and_error_classification(self):
        lib = get_lib()
        if not hasattr(lib, "lg_run_h2"):
            pytest.skip("lg_run_h2 not in native lib")
        with NativeFrontServer(stub=True, feature_dim=4, out_dim=3) as srv:
            req = _tensor_req([[1, 2, 3, 4]])
            out = native_load_grpc(
                srv.port, "/seldon.protos.Seldon/Predict",
                req.SerializeToString(), seconds=1.5, connections=2, depth=16,
            )
            assert out["ok"] > 0 and out["non2xx"] == 0 and out["errors"] == 0
            bad = native_load_grpc(
                srv.port, "/seldon.protos.Seldon/SendFeedback", b"",
                seconds=0.5, connections=1, depth=2,
            )
            assert bad["ok"] == 0 and bad["non2xx"] > 0


class TestFullContractFallback:
    """The native ingress serves the ENTIRE gRPC contract on one port:
    methods/payloads outside the in-C++ fast lane cross to Python whole
    while the wire stays native (reference parity: the Java engine's
    single gRPC server, SeldonService.java:30-67)."""

    @staticmethod
    def _echo_grpc_handler(path, body):
        if path.endswith("SendFeedback"):
            fb = pb.Feedback.FromString(body)
            out = pb.SeldonMessage()
            out.meta.tags["reward_seen"].string_value = str(fb.reward)
            return 0, "", out.SerializeToString()
        if path.endswith("Predict"):
            req = pb.SeldonMessage.FromString(body)
            out = pb.SeldonMessage()
            out.strData = "fallback:" + req.strData
            return 0, "", out.SerializeToString()
        return 12, "no handler", b""

    def test_sendfeedback_served_natively(self):
        with NativeFrontServer(stub=True, feature_dim=4, out_dim=3,
                               grpc_handler=self._echo_grpc_handler) as srv:
            fb = pb.Feedback(reward=0.75)
            with _channel(srv.port) as ch:
                send = services.unary_callable(ch, "Seldon", "SendFeedback")
                resp = send(fb, timeout=10)
        assert resp.meta.tags["reward_seen"].string_value == "0.75"

    def test_strdata_predict_falls_back_not_invalid(self):
        with NativeFrontServer(stub=True, feature_dim=4, out_dim=3,
                               grpc_handler=self._echo_grpc_handler) as srv:
            req = pb.SeldonMessage(strData="hello")
            with _channel(srv.port) as ch:
                predict = services.unary_callable(ch, "Seldon", "Predict")
                resp = predict(req, timeout=10)
        assert resp.strData == "fallback:hello"

    def test_handler_error_status_propagates(self):
        def bad(path, body):
            return 3, "bad feedback shape", b""

        with NativeFrontServer(stub=True, feature_dim=4, out_dim=3,
                               grpc_handler=bad) as srv:
            with _channel(srv.port) as ch:
                send = services.unary_callable(ch, "Seldon", "SendFeedback")
                with pytest.raises(grpc.RpcError) as exc:
                    send(pb.Feedback(), timeout=10)
        assert exc.value.code() == grpc.StatusCode.INVALID_ARGUMENT
        assert "bad feedback shape" in exc.value.details()


class TestGenerateStreamNative:
    """Server-streaming over the C++ h2c lane: response HEADERS, one
    DATA frame per pushed message, grpc-status trailers."""

    def _streaming_server(self, produce):
        holder = {}

        def handler(path, body, handle):
            assert path == "/seldon.protos.Seldon/GenerateStream"
            t = threading.Thread(
                target=produce, args=(holder["srv"], body, handle), daemon=True
            )
            t.start()
            return 0

        srv = NativeFrontServer(stub=True, feature_dim=4, out_dim=3,
                                grpc_stream_handler=handler)
        holder["srv"] = srv
        return srv

    def test_chunks_arrive_in_order_then_ok(self):
        def produce(srv, body, handle):
            req = pb.SeldonMessage.FromString(body)
            for i in range(3):
                out = pb.SeldonMessage()
                out.data.ndarray.values.add().number_value = float(i)
                out.meta.puid = req.meta.puid
                assert srv.stream_push(handle, out.SerializeToString()) == 0
            srv.stream_close(handle, 0, "")

        with self._streaming_server(produce) as srv:
            req = pb.SeldonMessage()
            req.meta.puid = "gen-1"
            with _channel(srv.port) as ch:
                gen = services.unary_stream_callable(ch, "Seldon", "GenerateStream")
                got = list(gen(req, timeout=15))
        assert [m.data.ndarray.values[0].number_value for m in got] == [0.0, 1.0, 2.0]
        assert all(m.meta.puid == "gen-1" for m in got)

    def test_error_close_maps_to_grpc_status(self):
        def produce(srv, body, handle):
            srv.stream_close(handle, 3, "prompt too long")

        with self._streaming_server(produce) as srv:
            with _channel(srv.port) as ch:
                gen = services.unary_stream_callable(ch, "Seldon", "GenerateStream")
                with pytest.raises(grpc.RpcError) as exc:
                    list(gen(pb.SeldonMessage(), timeout=15))
        assert exc.value.code() == grpc.StatusCode.INVALID_ARGUMENT
        assert "prompt too long" in exc.value.details()

    def test_push_after_client_cancel_reports_dead(self):
        saw = {"dead": None}
        release = threading.Event()

        def produce(srv, body, handle):
            out = pb.SeldonMessage()
            out.strData = "x"
            assert srv.stream_push(handle, out.SerializeToString()) == 0
            release.wait(timeout=10)  # until the client cancelled
            # connection closed: push must report dead so the engine
            # stream gets cancelled instead of decoding into the void
            for _ in range(100):
                rc = srv.stream_push(handle, out.SerializeToString())
                if rc < 0:
                    break
                time.sleep(0.05)
            # real producers ALWAYS close (releases the C++ handle +
            # inflight count); closing a dead stream must be safe
            srv.stream_close(handle, 1, "client cancelled")
            saw["dead"] = rc

        with self._streaming_server(produce) as srv:
            ch = _channel(srv.port)
            gen = services.unary_stream_callable(ch, "Seldon", "GenerateStream")
            it = gen(pb.SeldonMessage(), timeout=15)
            next(it)  # first chunk arrives
            it.cancel()
            ch.close()
            release.set()
            for _ in range(100):
                if saw["dead"] is not None:
                    break
                time.sleep(0.05)
        assert saw["dead"] == -1


class TestGatewayFullContract:
    """native_ingress + Gateway: feedback and token streaming ride the
    C++ port with full engine semantics."""

    def test_feedback_and_generate_stream_through_gateway(self):
        import asyncio

        from seldon_core_tpu.engine import PredictorService, UnitSpec
        from seldon_core_tpu.engine.native_ingress import serve_native_ingress
        from seldon_core_tpu.engine.server import Gateway
        from seldon_core_tpu.models.paged import StreamingLM

        lm = StreamingLM(
            vocab_size=64, d_model=32, num_layers=1, num_heads=2,
            max_len=64, max_new_tokens=6, page_size=8, max_slots=2,
            steps_per_call=2,
        )

        async def scenario():
            unit = UnitSpec(name="lm", type="MODEL", component=lm)
            gateway = Gateway([(PredictorService(unit, name="gen"), 1.0)])
            handle = await serve_native_ingress(gateway, host="127.0.0.1", http_port=0)
            try:
                def client():
                    with _channel(handle.port) as ch:
                        # unary predict through the native port (fallback
                        # lane: StreamingLM has no raw fast lane)
                        req = pb.SeldonMessage()
                        req.data.ndarray.values.add().list_value.MergeFrom(
                            _ndarray_row([1, 2, 3])
                        )
                        predict = services.unary_callable(ch, "Seldon", "Predict")
                        unary = predict(req, timeout=60)
                        unary_tokens = [
                            int(v.number_value)
                            for v in unary.data.ndarray.values[0].list_value.values
                        ]
                        # the same prompt streamed: identical greedy ids
                        gen = services.unary_stream_callable(
                            ch, "Seldon", "GenerateStream"
                        )
                        sreq = pb.SeldonMessage()
                        sreq.data.ndarray.values.add().list_value.MergeFrom(
                            _ndarray_row([1, 2, 3])
                        )
                        streamed = []
                        for m in gen(sreq, timeout=60):
                            streamed.extend(
                                int(v.number_value)
                                for v in m.data.ndarray.values[0].list_value.values
                            )
                        # feedback: bare (no puid) routes to the single
                        # predictor and succeeds over the native port
                        send = services.unary_callable(ch, "Seldon", "SendFeedback")
                        fresp = send(pb.Feedback(reward=1.0), timeout=30)
                        return unary_tokens, streamed, fresp
                unary_tokens, streamed, fresp = await asyncio.to_thread(client)
                assert streamed == unary_tokens
                assert len(unary_tokens) == 6
                assert fresp.status.status == pb.Status.SUCCESS or fresp.status.code in (0, 200)
            finally:
                await handle.stop()
                lm.shutdown()

        asyncio.run(scenario())


def _ndarray_row(vals):
    from google.protobuf import struct_pb2

    lv = struct_pb2.ListValue()
    for v in vals:
        lv.values.add().number_value = float(v)
    return lv


class TestLoadClientAgainstGrpcPython:
    """The C++ h2 load client drives THIRD-PARTY gRPC servers: the
    r5 HPACK upgrade decodes dynamic-table/Huffman response headers
    (grpc-python installs table entries with its first response and
    indexes them afterwards — the old literal-scan classifier counted
    every post-first response as an error).  This is what makes the
    bench's relay-free native-vs-python stub comparison possible."""

    def test_stub_load_against_grpc_python_server(self):
        import asyncio

        lib = get_lib()
        if not hasattr(lib, "lg_run_h2"):
            pytest.skip("lg_run_h2 not in native lib")
        from seldon_core_tpu.engine import PredictorService, UnitSpec
        from seldon_core_tpu.engine.server import Gateway
        from seldon_core_tpu.engine.sync_server import build_sync_seldon_server
        from seldon_core_tpu.native.frontserver import native_load_grpc

        async def scenario():
            svc = PredictorService(
                UnitSpec(name="stub", type="MODEL", implementation="SIMPLE_MODEL")
            )
            gateway = Gateway([(svc, 1.0)])
            server = build_sync_seldon_server(
                gateway, asyncio.get_running_loop(),
                max_message_bytes=16 * 1024 * 1024,
            )
            port = server.add_insecure_port("127.0.0.1:0")
            server.start()
            try:
                return await asyncio.to_thread(
                    native_load_grpc, port, "/seldon.protos.Seldon/Predict",
                    _tensor_req([[1, 2, 3]]).SerializeToString(), 1.5, 2, 8,
                )
            finally:
                server.stop(grace=None)

        out = asyncio.run(scenario())
        # many requests complete and NONE misclassify: the dynamic-table
        # decode keeps working past the first response per connection
        assert out["ok"] > 20
        assert out["non2xx"] == 0 and out["errors"] == 0
