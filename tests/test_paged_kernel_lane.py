"""The fused Pallas decode lane as the pool-impl DEFAULT + the int8
KV pool with per-page scales (r18, ROADMAP 1).

Fast tier: the `auto` default's resolution rules, the `=0` escape
hatch's byte-for-byte lowering identity with the historical XLA gather
program, the int8 gating/accounting arithmetic, the container layout
(int8 pages + scale frames across the framing implementations), and
the kernel_active observability surface.

Slow tier: the standing parity matrix — greedy kernel-on vs kernel-off
bit-exactness at f32 across ring|pool × prefix × w8a8 × spec-verify ×
adapters (mirroring the r17 migration matrix), plus the int8-KV vs
native-pool top-1 agreement bound (quantisation is page-bounded, NOT
bit-exact — the test pins the honest claim).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from seldon_core_tpu.models.paged import (
    PagedEngine,
    paged_capacity_streams,
    paged_hbm_accounting,
)
from seldon_core_tpu.models.transformer import TransformerLM

CFG = dict(vocab_size=64, d_model=32, num_layers=1, num_heads=2, max_len=256)


@pytest.fixture(scope="module")
def params():
    lm = TransformerLM(dtype=jnp.float32, **CFG)
    return lm.init(jax.random.key(0), jnp.zeros((1, 8), jnp.int32))["params"]


def _engine(params, **kw):
    base = dict(dtype=jnp.float32, page_size=8, max_slots=4, steps_per_call=4)
    base.update(kw)
    return PagedEngine(params, **CFG, **base)


def _prompts(n=4, seed=5):
    rng = np.random.default_rng(seed)
    return [
        rng.integers(0, CFG["vocab_size"], size=(14 + 3 * i,)).astype(np.int32)
        for i in range(n)
    ]


def _decode_all(eng, prompts, max_new=12, **kw):
    streams = [eng.submit(p, max_new_tokens=max_new, **kw) for p in prompts]
    eng.run()
    out = np.stack([s.result for s in streams])
    eng.close()
    return out


# ---------------------------------------------------------------------------
# default flip (fast): auto resolution + the =0 escape hatch
# ---------------------------------------------------------------------------


class TestKernelDefaultFlip:
    def test_auto_resolves_off_the_tpu_backend(self, params, monkeypatch):
        """The r18 default is `auto`: kernel ON only when the backend is
        a TPU — a CPU host's pool engine must run the gather lane with
        no WARN (auto's silent fallback is the point of auto)."""
        monkeypatch.delenv("SELDON_TPU_PAGED_KERNEL", raising=False)
        monkeypatch.setenv("SELDON_TPU_CHUNK_IMPL", "pool")
        eng = _engine(params)
        try:
            expect = jax.default_backend() == "tpu"
            assert eng._kernel_active is expect
            assert eng.engine_stats()["kernel_active"] == int(expect)
        finally:
            eng.close()

    def test_force_activates_kernel_and_gauge(self, params, monkeypatch):
        monkeypatch.setenv("SELDON_TPU_PAGED_KERNEL", "force")
        monkeypatch.setenv("SELDON_TPU_CHUNK_IMPL", "pool")
        eng = _engine(params)
        try:
            assert eng._kernel_active is True
            assert eng.engine_stats()["kernel_active"] == 1
            _decode_all(eng, _prompts(2), max_new=4)
        finally:
            eng.close()

    def test_chunk_records_carry_kernel_active(self, params, monkeypatch):
        """Every flight-recorder chunk record names its decode lane —
        the post-hoc answer to 'was the kernel live for this chunk?'."""
        monkeypatch.setenv("SELDON_TPU_PAGED_KERNEL", "0")
        monkeypatch.setenv("SELDON_TPU_CHUNK_IMPL", "pool")
        eng = _engine(params)
        try:
            [eng.submit(p, max_new_tokens=4) for p in _prompts(2)]
            eng.run()
            recs = eng.engine_stats(detail=True)["recorder"]
            assert recs and all(r["kernel_active"] == 0 for r in recs)
        finally:
            eng.close()

    def test_kernel_gauges_are_bridge_mapped(self):
        """The engine_stats contract: both new keys must export through
        the Prometheus bridge (the observability contract test enforces
        the full mapping; this pins the canonical metric names)."""
        from seldon_core_tpu.utils.metrics import ENGINE_STATS_METRICS

        kind, name, _ = ENGINE_STATS_METRICS["kernel_active"]
        assert (kind, name) == ("gauge", "seldon_tpu_engine_kernel_active")
        kind, name, _ = ENGINE_STATS_METRICS["kv_dtype_int8"]
        assert (kind, name) == ("gauge", "seldon_tpu_engine_kv_dtype_int8")

    def test_kernel_off_recovers_xla_program_byte_for_byte(
        self, params, monkeypatch
    ):
        """`SELDON_TPU_PAGED_KERNEL=0` must lower the EXACT historical
        gather program: on a non-TPU backend `auto` resolves to the
        same lane, so the two lowerings must be byte-identical text —
        the default flip cannot perturb the fallback program."""
        if jax.default_backend() == "tpu":
            pytest.skip("auto resolves ON for TPU backends — the "
                        "contrast arm needs a non-TPU host")
        monkeypatch.setenv("SELDON_TPU_CHUNK_IMPL", "pool")

        def lowered(mode):
            if mode is None:
                monkeypatch.delenv("SELDON_TPU_PAGED_KERNEL", raising=False)
            else:
                monkeypatch.setenv("SELDON_TPU_PAGED_KERNEL", mode)
            eng = _engine(params)
            try:
                return eng.lower_chunk(2, ((eng.max_slots, 4),)).as_text()
            finally:
                eng.close()

        assert lowered("0") == lowered(None)


# ---------------------------------------------------------------------------
# int8 KV pool gating + accounting (fast)
# ---------------------------------------------------------------------------


class TestInt8Gating:
    def test_int8_pool_engine_engages_and_reports(self, params, monkeypatch):
        monkeypatch.setenv("SELDON_TPU_KV_DTYPE", "int8")
        monkeypatch.setenv("SELDON_TPU_CHUNK_IMPL", "pool")
        monkeypatch.setenv("SELDON_TPU_PAGED_KERNEL", "0")
        eng = _engine(params)
        try:
            assert eng._kv_int8 is True
            assert eng.pages_k.dtype == jnp.int8
            assert eng.scales_k.dtype == jnp.float32
            assert eng.scales_k.shape == (CFG["num_layers"], eng.num_pages)
            assert eng.engine_stats()["kv_dtype_int8"] == 1
        finally:
            eng.close()

    def test_int8_requires_pool_impl_falls_back_with_warn(
        self, params, monkeypatch, caplog
    ):
        monkeypatch.setenv("SELDON_TPU_KV_DTYPE", "int8")
        monkeypatch.setenv("SELDON_TPU_CHUNK_IMPL", "ring")
        eng = _engine(params)
        try:
            assert eng._kv_int8 is False
            assert eng.scales_k is None
            assert eng.pages_k.dtype == jnp.float32
            assert "keeping the native pool dtype" in caplog.text
        finally:
            eng.close()

    def test_unknown_kv_dtype_raises_named(self, params, monkeypatch):
        monkeypatch.setenv("SELDON_TPU_KV_DTYPE", "fp4")
        with pytest.raises(ValueError, match="SELDON_TPU_KV_DTYPE"):
            _engine(params)


class TestInt8Accounting:
    KW = dict(num_layers=8, d_model=512, page_size=64, chunk_impl="pool",
              flat_pool=False, dtype_bytes=2)

    def test_int8_roughly_doubles_capacity(self):
        budget = 8 << 30
        bf16 = paged_capacity_streams(budget, 512, **self.KW)
        int8 = paged_capacity_streams(budget, 512, kv_dtype="int8", **self.KW)
        # pages at 1 byte/element + 64B/page of scales vs 2 bytes/element
        assert 1.9 <= int8 / bf16 <= 2.0

    def test_scale_table_is_priced_per_page(self):
        acct = paged_hbm_accounting(streams=1, ctx_len=512, kv_dtype="int8",
                                    **self.KW)
        pages = -(-512 // 64)
        tok = self.KW["num_layers"] * self.KW["d_model"] * 2  # 1 byte/elt
        scale = self.KW["num_layers"] * 2 * 4                 # 8B/page
        pad = 2.0  # the split layout's tile pad
        assert acct["pool_bytes"] == int(pages * (64 * tok * pad + scale))

    def test_ring_working_set_ignores_kv_dtype(self):
        """The ring impl never stores int8 (pool-impl-only lever): its
        gathered working set prices at the COMPUTE dtype either way."""
        kw = dict(self.KW, chunk_impl="ring")
        a = paged_hbm_accounting(streams=4, ctx_len=512, **kw)
        b = paged_hbm_accounting(streams=4, ctx_len=512, kv_dtype="int8", **kw)
        assert a["working_set_bytes"] == b["working_set_bytes"]


# ---------------------------------------------------------------------------
# int8 containers: scale frames across the framing implementations (fast)
# ---------------------------------------------------------------------------


def _int8_payload(rng, pages=3, ps=8, L=2, d=32):
    k = rng.integers(-127, 127, size=(L, pages, ps, d), dtype=np.int8)
    v = rng.integers(-127, 127, size=(L, pages, ps, d), dtype=np.int8)
    return {
        "prompt": np.arange(ps * pages - 2, dtype=np.int32),
        "last_logits": rng.random(64).astype(np.float32),
        "k": k, "v": v,
        "k_scales": rng.random((L, pages)).astype(np.float32) + 0.01,
        "v_scales": rng.random((L, pages)).astype(np.float32) + 0.01,
    }


class TestInt8Containers:
    def test_handoff_roundtrip_crc_clean(self):
        from seldon_core_tpu.codec import bufview

        p = _int8_payload(np.random.default_rng(0))
        out = bufview.unpack_kv_handoff(bufview.pack_kv_handoff(p))
        for key in ("k", "v", "k_scales", "v_scales"):
            np.testing.assert_array_equal(out[key], p[key])
        assert out["k_scales"].dtype == np.float32

    def test_migration_roundtrip_scales_appended(self):
        from seldon_core_tpu.codec import bufview

        p = _int8_payload(np.random.default_rng(1))
        p.update(tokens=np.arange(2, dtype=np.int32),
                 key_data=np.zeros(2, np.uint32), req_id="m1", seed=3)
        out = bufview.unpack_kv_migration(bufview.pack_kv_migration(p))
        np.testing.assert_array_equal(out["v_scales"], p["v_scales"])
        assert out["req_id"] == "m1"

    def test_int8_pages_without_scales_reject_named(self):
        from seldon_core_tpu.codec import bufview

        p = _int8_payload(np.random.default_rng(2))
        del p["k_scales"]
        with pytest.raises(bufview.PayloadError, match="k_scales"):
            bufview.pack_kv_handoff(p)

    def test_scales_without_int8_pages_reject_named(self):
        from seldon_core_tpu.codec import bufview

        p = _int8_payload(np.random.default_rng(3))
        p["k"] = p["k"].astype(np.float32)
        p["v"] = p["v"].astype(np.float32)
        with pytest.raises(bufview.PayloadError, match="int8"):
            bufview.pack_kv_handoff(p)

    def test_corrupt_int8_container_rejects_via_crc(self):
        from seldon_core_tpu.codec import bufview

        buf = bytearray(bufview.pack_kv_handoff(
            _int8_payload(np.random.default_rng(4))))
        buf[len(buf) // 2] ^= 0xFF
        with pytest.raises(bufview.PayloadError, match="CRC"):
            bufview.unpack_kv_handoff(bytes(buf))

    def test_native_framing_agrees_on_int8_scale_frames(self):
        """The C ABI (native/codec.cc) must walk an int8+scales
        container frame-by-frame to the same payload sizes and the same
        CRC the python lane computed — the three-implementation framing
        agreement extended to the r18 layout."""
        import ctypes

        from seldon_core_tpu.codec import bufview
        from seldon_core_tpu.native import get_lib

        lib = get_lib()
        if lib is None or not (hasattr(lib, "srt1_payload_bytes")
                               and hasattr(lib, "srt1_crc32c")):
            pytest.skip("native library not built")
        p = _int8_payload(np.random.default_rng(5))
        for key in ("k", "v", "k_scales", "v_scales"):
            frame = bufview.pack_frame(p[key])
            buf = (ctypes.c_uint8 * len(frame)).from_buffer_copy(frame)
            assert lib.srt1_payload_bytes(buf, len(frame)) == p[key].nbytes, key
        # the CRC the int8+scales container actually ships under must be
        # reproducible by the C lane over the identical covered bytes
        import struct

        container = bufview.pack_kv_handoff(p)
        magic, stored = struct.unpack("<II", container[-8:])
        assert magic == bufview.SRT1_CRC_MAGIC
        covered = container[:-8]
        assert lib.srt1_crc32c(covered, len(covered), 0) == stored
        assert bufview._crc32c_py(covered) == stored


# ---------------------------------------------------------------------------
# the standing parity matrix (slow): kernel-on vs kernel-off greedy
# bit-exactness at f32, every engine variant
# ---------------------------------------------------------------------------


def _ab_tokens(params, monkeypatch, engine_kw=None, submit_kw=None,
               chunk_impl="pool"):
    engine_kw = engine_kw or {}
    submit_kw = submit_kw or {}
    out = {}
    for mode in ("0", "force"):
        monkeypatch.setenv("SELDON_TPU_PAGED_KERNEL", mode)
        monkeypatch.setenv("SELDON_TPU_CHUNK_IMPL", chunk_impl)
        eng = _engine(params, **engine_kw)
        out[mode] = _decode_all(eng, _prompts(4), max_new=12, **submit_kw)
    return out["0"], out["force"]


@pytest.mark.slow
class TestKernelParityMatrix:
    @pytest.mark.parametrize("impl", ["ring", "pool"])
    @pytest.mark.parametrize("precision", ["", "w8a8"])
    @pytest.mark.parametrize("prefix", [True, False])
    def test_kernel_on_off_bit_exact(
        self, params, monkeypatch, impl, precision, prefix
    ):
        """Kernel on vs off must be a pure performance choice: greedy
        bit-exact at f32 in every chunk/precision/prefix variant (on
        the ring impl the knob is a no-op — same assertion)."""
        off, on = _ab_tokens(
            params, monkeypatch, chunk_impl=impl,
            engine_kw=dict(precision=precision, prefix_cache=prefix),
        )
        np.testing.assert_array_equal(off, on)

    def test_kernel_on_off_bit_exact_spec_verify(self, params, monkeypatch):
        off, on = _ab_tokens(
            params, monkeypatch,
            engine_kw=dict(speculative={"draft": "ngram", "draft_k": 2}),
        )
        np.testing.assert_array_equal(off, on)

    def test_kernel_on_off_bit_exact_adapters(self, params, monkeypatch):
        from seldon_core_tpu.models.registry import WeightRegistry
        from seldon_core_tpu.ops.lora import adapter_bytes, make_lora_params

        adapters = {
            f"t{i}": make_lora_params(
                100 + i, num_layers=CFG["num_layers"],
                d_model=CFG["d_model"], rank=2,
            )
            for i in range(2)
        }

        def tokens(mode):
            monkeypatch.setenv("SELDON_TPU_PAGED_KERNEL", mode)
            monkeypatch.setenv("SELDON_TPU_CHUNK_IMPL", "pool")
            reg = WeightRegistry(budget_bytes=0)
            for name, ad in adapters.items():
                reg.register(name, (lambda a=ad: a),
                             bytes_hint=adapter_bytes(ad))
            eng = _engine(params, max_adapters=2, lora_rank=2,
                          weight_registry=reg)
            streams = [
                eng.submit(p, max_new_tokens=12,
                           adapter=("t0" if i % 2 else "t1"))
                for i, p in enumerate(_prompts(4))
            ]
            eng.run()
            out = np.stack([s.result for s in streams])
            eng.close()
            return out

        # a K-mixed adapter wave: the in-kernel BGMV fold vs the
        # gathered einsum pair must agree token-for-token
        np.testing.assert_array_equal(tokens("0"), tokens("force"))

    def test_int8_kv_top1_agreement_bound(self, params, monkeypatch):
        """Int8-KV is NOT bit-exact — per-page abs-max quantisation is
        a bounded perturbation.  The honest claim under test: greedy
        decode top-1 agreement with the native pool stays high — first
        tokens exact, full-sequence agreement >= 0.75 (measured 0.86 at
        this deterministic seed/config; random tiny-model logits sit
        far closer together than trained-model logits, so this is the
        pessimistic end of the bound)."""
        def tokens(kv):
            monkeypatch.setenv("SELDON_TPU_KV_DTYPE", kv)
            monkeypatch.setenv("SELDON_TPU_CHUNK_IMPL", "pool")
            monkeypatch.setenv("SELDON_TPU_PAGED_KERNEL", "0")
            eng = _engine(params, max_slots=8)
            return _decode_all(eng, _prompts(8, seed=11), max_new=16)

        native, int8 = tokens("bf16"), tokens("int8")
        assert (native[:, 0] == int8[:, 0]).all()
        assert (native == int8).mean() >= 0.75

    def test_int8_kv_kernel_vs_gather_bit_exact(self, params, monkeypatch):
        """Same quantised pool, two readers: the kernel's in-register
        dequant must agree with the gather lane's dequant token-for-
        token (quantisation error is identical — the READ path is what
        differs)."""
        off, on = _ab_tokens(params, monkeypatch)
        monkeypatch.setenv("SELDON_TPU_KV_DTYPE", "int8")
        off8, on8 = _ab_tokens(params, monkeypatch)
        np.testing.assert_array_equal(off, on)
        np.testing.assert_array_equal(off8, on8)
