"""Native C++ front server tests: the data-plane ingress over real
loopback sockets — fast lane (JSON tensor/ndarray + binary raw frames,
C++ batching, stub and Python models), fallback lane (full engine
semantics via GatewayRawHandler), lifecycle, ordering, and a
concurrency smoke.  Equivalent role to the reference's engine
controller tests (reference: engine/src/test/java/.../
TestRestClientControllerExternalGraphs.java:41-80) with the transport
real instead of mocked.
"""

import http.client
import json
import socket
import threading
import time

import numpy as np
import pytest

from seldon_core_tpu.native import frontserver as fsmod
from seldon_core_tpu.native.frontserver import (
    GatewayRawHandler,
    NativeFrontServer,
    pack_raw_frame,
    unpack_raw_frame,
)

pytestmark = pytest.mark.skipif(
    not fsmod.available(), reason="native front server library not built"
)


def post(port, path, body, content_type="application/json"):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=10)
    conn.request("POST", path, body=body, headers={"Content-Type": content_type})
    r = conn.getresponse()
    data = r.read()
    conn.close()
    return r.status, data


def get(port, path):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=10)
    conn.request("GET", path)
    r = conn.getresponse()
    data = r.read()
    conn.close()
    return r.status, data


def tensor_body(arr, puid=None):
    arr = np.asarray(arr, dtype=np.float64)
    body = {"data": {"tensor": {"shape": list(arr.shape), "values": arr.ravel().tolist()}}}
    if puid:
        body["meta"] = {"puid": puid}
    return json.dumps(body).encode()


class TestStubMode:
    """Pure C++ path: the SIMPLE_MODEL benchmarking methodology."""

    @pytest.fixture()
    def server(self):
        with NativeFrontServer(stub=True, out_dim=3, feature_dim=4, model_name="stub") as srv:
            yield srv

    def test_json_tensor_roundtrip(self, server):
        status, data = post(server.port, "/api/v0.1/predictions", tensor_body([[1, 2, 3, 4]]))
        assert status == 200
        out = json.loads(data)
        assert out["data"]["tensor"]["shape"] == [1, 3]
        np.testing.assert_allclose(
            out["data"]["tensor"]["values"], [0.9, 0.05, 0.05], atol=1e-6
        )
        assert out["meta"]["requestPath"] == {"stub": "native"}
        assert out["meta"]["puid"]  # generated

    def test_puid_echoed(self, server):
        status, data = post(
            server.port, "/api/v0.1/predictions", tensor_body([[1, 2, 3, 4]], puid="pu-42")
        )
        assert status == 200
        assert json.loads(data)["meta"]["puid"] == "pu-42"

    def test_json_ndarray(self, server):
        body = json.dumps({"data": {"ndarray": [[1, 2, 3, 4], [5, 6, 7, 8]]}}).encode()
        status, data = post(server.port, "/api/v0.1/predictions", body)
        assert status == 200
        assert json.loads(data)["data"]["tensor"]["shape"] == [2, 3]

    def test_raw_frame_roundtrip(self, server):
        frame = pack_raw_frame(np.ones((3, 4), np.float32))
        status, data = post(
            server.port, "/api/v0.1/predictions", frame, "application/x-seldon-raw"
        )
        assert status == 200
        out = unpack_raw_frame(data)
        assert out.shape == (3, 3)
        np.testing.assert_allclose(out[0], [0.9, 0.05, 0.05], atol=1e-6)

    def test_control_endpoints(self, server):
        assert get(server.port, "/ping") == (200, b"pong")
        assert get(server.port, "/live") == (200, b"live")
        assert get(server.port, "/ready")[0] == 200
        server.set_ready(False)
        assert get(server.port, "/ready")[0] == 503
        server.set_ready(True)
        status, data = get(server.port, "/stats")
        assert status == 200
        assert json.loads(data)["requests"] >= 1

    def test_wrong_feature_dim_falls_to_404_without_raw_handler(self, server):
        # cols != feature_dim and no fallback handler -> NOT_IMPLEMENTED
        status, data = post(server.port, "/api/v0.1/predictions", tensor_body([[1, 2]]))
        assert status == 404
        assert json.loads(data)["status"]["reason"] == "NOT_IMPLEMENTED"

    def test_unknown_path(self, server):
        status, data = post(server.port, "/nope", b"{}")
        assert status == 404

    def test_keep_alive_reuse(self, server):
        conn = http.client.HTTPConnection("127.0.0.1", server.port, timeout=10)
        for _ in range(20):
            conn.request(
                "POST", "/api/v0.1/predictions", body=tensor_body([[1, 2, 3, 4]]),
                headers={"Content-Type": "application/json"},
            )
            r = conn.getresponse()
            assert r.status == 200
            r.read()
        conn.close()
        assert server.stats()["connections"] == 1


class TestPythonModel:
    def test_batch_callback(self):
        calls = []

        def model(batch):
            calls.append(batch.shape)
            return batch.sum(axis=1, keepdims=True) * np.ones((1, 2))

        with NativeFrontServer(model_fn=model, feature_dim=3, out_dim=2) as srv:
            status, data = post(srv.port, "/api/v0.1/predictions", tensor_body([[1, 2, 3]]))
            assert status == 200
            out = json.loads(data)
            np.testing.assert_allclose(out["data"]["tensor"]["values"], [6.0, 6.0])
            assert calls and calls[0][1] == 3

    def test_python_exception_becomes_500(self):
        def model(batch):
            raise RuntimeError("boom")

        with NativeFrontServer(model_fn=model, feature_dim=3, out_dim=2) as srv:
            status, data = post(srv.port, "/api/v0.1/predictions", tensor_body([[1, 2, 3]]))
            assert status == 500
            assert json.loads(data)["status"]["reason"] == "ENGINE_ERROR"

    def test_coalescing_under_load(self):
        def model(batch):
            time.sleep(0.002)  # make the call slow enough to coalesce behind
            return np.zeros((batch.shape[0], 1), np.float32)

        with NativeFrontServer(model_fn=model, feature_dim=2, out_dim=1, max_batch=32) as srv:
            body = tensor_body([[1, 2]])
            errs = []

            def hammer():
                try:
                    for _ in range(25):
                        status, _ = post(srv.port, "/api/v0.1/predictions", body)
                        assert status == 200
                except Exception as e:  # noqa: BLE001
                    errs.append(e)

            threads = [threading.Thread(target=hammer) for _ in range(8)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            assert not errs
            st = srv.stats()
            assert st["rows"] == 200
            # coalescing happened: strictly fewer model calls than requests
            assert st["batches"] < st["rows"]


class TestUint8FastLane:
    def test_uint8_frame_reaches_model_as_uint8(self):
        """A uint8 SRT1 frame must reach model_fn dtype-preserved (no
        4x float inflation) and round-trip correctly."""
        seen = []

        def model(batch):
            seen.append(batch.dtype)
            return batch.astype(np.float32).sum(axis=1, keepdims=True)

        with NativeFrontServer(model_fn=model, feature_dim=4, out_dim=1) as srv:
            frame = pack_raw_frame(np.array([[1, 2, 3, 4]], np.uint8))
            status, data = post(srv.port, "/api/v0.1/predictions", frame,
                                content_type="application/x-seldon-raw")
            assert status == 200
            out = unpack_raw_frame(data)
            np.testing.assert_allclose(np.asarray(out).ravel(), [10.0])
            assert seen == [np.dtype(np.uint8)]

    def test_mixed_dtype_requests_never_share_a_batch(self):
        """Concurrent f32 and u8 requests must land in separate model
        calls — each (shape, dtype) is its own compiled program."""
        batches = []
        lock = threading.Lock()

        def model(batch):
            with lock:
                batches.append((batch.dtype.str, batch.shape[0]))
            time.sleep(0.002)
            return np.zeros((batch.shape[0], 1), np.float32)

        with NativeFrontServer(model_fn=model, feature_dim=2, out_dim=1,
                               max_batch=64) as srv:
            f32 = pack_raw_frame(np.ones((1, 2), np.float32))
            u8 = pack_raw_frame(np.ones((1, 2), np.uint8))
            errs = []

            def hammer(frame):
                try:
                    for _ in range(20):
                        status, _ = post(srv.port, "/api/v0.1/predictions", frame,
                                         content_type="application/x-seldon-raw")
                        assert status == 200
                except Exception as e:  # noqa: BLE001
                    errs.append(e)

            threads = [threading.Thread(target=hammer, args=(f,))
                       for f in (f32, u8) for _ in range(4)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            assert not errs
            dtypes = {d for d, _ in batches}
            assert dtypes == {"<f4", "|u1"}


class TestBatchWorkerPipeline:
    def test_concurrent_model_calls(self):
        """batch_threads > 1 must overlap slow model calls — the
        pipelining that sets throughput through a high-latency
        device link."""
        inflight = []
        peak = [0]
        lock = threading.Lock()

        def model(batch):
            with lock:
                inflight.append(1)
                peak[0] = max(peak[0], len(inflight))
            time.sleep(0.05)
            with lock:
                inflight.pop()
            return np.zeros((batch.shape[0], 1), np.float32)

        with NativeFrontServer(model_fn=model, feature_dim=2, out_dim=1,
                               max_batch=1, batch_threads=4) as srv:
            body = tensor_body([[1, 2]])
            errs = []

            def worker():
                try:
                    for _ in range(3):
                        status, _ = post(srv.port, "/api/v0.1/predictions", body)
                        assert status == 200
                except Exception as e:  # noqa: BLE001
                    errs.append(e)

            threads = [threading.Thread(target=worker) for _ in range(8)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            assert not errs
            # with max_batch=1 every request is its own model call;
            # 4 workers must have overlapped at least 2 calls
            assert peak[0] >= 2


class TestRawFallbackLane:
    def test_custom_raw_handler(self):
        seen = []

        def handler(method, path, body):
            seen.append((method, path, body))
            return 200, "application/json", b'{"ok": true}'

        with NativeFrontServer(stub=True, feature_dim=4, raw_handler=handler) as srv:
            # strData payload cannot ride the fast lane
            status, data = post(
                srv.port, "/api/v0.1/predictions", json.dumps({"strData": "hi"}).encode()
            )
            assert status == 200
            assert json.loads(data) == {"ok": True}
            assert seen[0][0] == "POST"
            # feedback always goes to the fallback lane
            status, _ = post(srv.port, "/api/v0.1/feedback", b'{"reward": 1.0}')
            assert status == 200
            assert len(seen) == 2

    def test_raw_handler_content_type_propagates(self):
        def handler(method, path, body):
            return 200, "application/x-seldon-raw", b"\x01\x02\x03"

        with NativeFrontServer(stub=True, feature_dim=4, raw_handler=handler) as srv:
            conn = http.client.HTTPConnection("127.0.0.1", srv.port, timeout=10)
            conn.request("POST", "/api/v0.1/predictions", body=b'{"strData":"x"}',
                         headers={"Content-Type": "application/json"})
            r = conn.getresponse()
            assert r.status == 200
            assert r.getheader("Content-Type") == "application/x-seldon-raw"
            assert r.read() == b"\x01\x02\x03"
            conn.close()

    def test_handler_exception_is_500(self):
        def handler(method, path, body):
            raise RuntimeError("nope")

        with NativeFrontServer(stub=True, feature_dim=4, raw_handler=handler) as srv:
            status, data = post(srv.port, "/api/v0.1/predictions", b'{"strData": "x"}')
            assert status == 500

    def test_gateway_raw_handler_full_semantics(self):
        """Exotic payloads flow through the real engine via the bridge."""
        import asyncio

        from seldon_core_tpu.engine import PredictorService, UnitSpec
        from seldon_core_tpu.engine.server import Gateway
        from seldon_core_tpu.runtime import TPUComponent

        class Doubler(TPUComponent):
            def predict(self, X, names, meta=None):
                return np.asarray(X) * 2

        loop = asyncio.new_event_loop()
        thread = threading.Thread(target=loop.run_forever, daemon=True)
        thread.start()
        try:
            gw = Gateway(
                [(PredictorService(UnitSpec(name="m", type="MODEL", component=Doubler())), 1.0)]
            )
            handler = GatewayRawHandler(gw, loop)
            with NativeFrontServer(
                stub=True, feature_dim=9999, raw_handler=handler
            ) as srv:
                # feature_dim mismatch pushes this to the fallback lane:
                # the response comes from the real executor
                status, data = post(
                    srv.port, "/api/v0.1/predictions", tensor_body([[1.0, 2.0]])
                )
                assert status == 200
                out = json.loads(data)
                np.testing.assert_allclose(out["data"]["tensor"]["values"], [2.0, 4.0])
                assert "m" in out["meta"]["requestPath"]
        finally:
            loop.call_soon_threadsafe(loop.stop)
            thread.join(timeout=5)


class TestNativeIngressE2E:
    """Deployment-level wiring: spec annotation -> C++ ingress on the
    HTTP port, fast lane for single-MODEL graphs, engine fallback for
    everything else."""

    def test_jaxserver_fast_lane_deployment(self):
        import asyncio
        import os

        from seldon_core_tpu.controlplane import Deployer, TpuDeployment
        from seldon_core_tpu.controlplane.deployer import serve_deployment

        examples = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                                "examples")

        async def scenario():
            spec = TpuDeployment.load(os.path.join(examples, "single_model.yaml"))
            spec.annotations["seldon.io/frontend"] = "native"
            spec.http_port, spec.grpc_port = 0, 0

            import socket as socketmod

            s = socketmod.socket()
            s.bind(("127.0.0.1", 0))
            spec.http_port = s.getsockname()[1]
            s2 = socketmod.socket()
            s2.bind(("127.0.0.1", 0))
            spec.grpc_port = s2.getsockname()[1]
            s.close(); s2.close()

            deployer = Deployer(device_ids=[0])
            await deployer.apply(spec)
            http_handle, grpc_handle = await serve_deployment(deployer, spec.name,
                                                              host="127.0.0.1")
            from seldon_core_tpu.engine.native_ingress import NativeIngressHandle

            assert isinstance(http_handle, NativeIngressHandle)

            def client_work():
                # fast lane: tensor payload, softmax outputs sum to 1
                status, data = post(spec.http_port, "/api/v0.1/predictions",
                                    tensor_body([[0.1, 0.2, 0.3, 0.4]]))
                assert status == 200
                out = json.loads(data)
                assert out["data"]["tensor"]["shape"] == [1, 3]
                assert abs(sum(out["data"]["tensor"]["values"]) - 1.0) < 1e-4
                assert out["data"]["names"] == ["setosa", "versicolor", "virginica"]
                # fallback lane: strData is not fast-lane expressible;
                # the engine rejects it for this model with a clean 4xx/5xx
                status, _ = post(spec.http_port, "/api/v0.1/predictions",
                                 json.dumps({"strData": "hi"}).encode())
                assert status in (400, 500)
                # control + observability endpoints
                assert get(spec.http_port, "/ping") == (200, b"pong")
                status, body2 = get(spec.http_port, "/metrics")
                assert status == 200 and b"seldon" in body2
                return http_handle.stats()

            # readiness refresh needs a beat
            for _ in range(50):
                status, _ = await asyncio.to_thread(get, spec.http_port, "/ready")
                if status == 200:
                    break
                await asyncio.sleep(0.1)
            stats = await asyncio.to_thread(client_work)
            assert stats["fast_requests"] >= 1
            assert stats["raw_requests"] >= 2
            await http_handle.stop()
            await grpc_handle.stop(0)
            await deployer.delete(spec.name)

        asyncio.run(scenario())

    def test_rolling_update_switches_fast_lane_weights(self):
        """The fast lane must serve the NEW generation after a rolling
        swap (the reference's fixed-model rollout determinism trick,
        reference: testing/scripts/test_rolling_updates.py)."""
        import asyncio
        import os

        from seldon_core_tpu.controlplane import Deployer, TpuDeployment
        from seldon_core_tpu.controlplane.deployer import serve_deployment

        examples = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                                "examples")

        async def scenario():
            spec = TpuDeployment.load(os.path.join(examples, "single_model.yaml"))
            spec.annotations["seldon.io/frontend"] = "native"
            import socket as socketmod

            s = socketmod.socket(); s.bind(("127.0.0.1", 0))
            spec.http_port = s.getsockname()[1]
            s2 = socketmod.socket(); s2.bind(("127.0.0.1", 0))
            spec.grpc_port = s2.getsockname()[1]
            s.close(); s2.close()

            deployer = Deployer(device_ids=[0])
            await deployer.apply(spec)
            http_handle, grpc_handle = await serve_deployment(deployer, spec.name,
                                                              host="127.0.0.1")
            body = tensor_body([[0.1, 0.2, 0.3, 0.4]])
            status, data = await asyncio.to_thread(
                post, spec.http_port, "/api/v0.1/predictions", body)
            assert status == 200
            v1 = json.loads(data)["data"]["tensor"]["values"]

            # generation 2: same model family, different seed -> different weights
            spec2 = TpuDeployment.load(os.path.join(examples, "single_model.yaml"))
            spec2.annotations["seldon.io/frontend"] = "native"
            spec2.http_port, spec2.grpc_port = spec.http_port, spec.grpc_port
            spec2.predictors[0].graph.parameters.append(
                {"name": "seed", "value": "123", "type": "INT"}
            )
            await deployer.apply(spec2)
            status, data = await asyncio.to_thread(
                post, spec.http_port, "/api/v0.1/predictions", body)
            assert status == 200
            v2 = json.loads(data)["data"]["tensor"]["values"]
            assert not np.allclose(v1, v2), "fast lane still serving old generation"

            await http_handle.stop()
            await grpc_handle.stop(0)
            await deployer.delete(spec.name)

        asyncio.run(scenario())

    def test_traffic_split_uses_fallback_lane(self):
        import asyncio
        import os

        from seldon_core_tpu.controlplane import Deployer, TpuDeployment
        from seldon_core_tpu.controlplane.deployer import serve_deployment

        examples = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                                "examples")

        async def scenario():
            spec = TpuDeployment.load(os.path.join(examples, "mab_abtest.yaml"))
            spec.annotations["seldon.io/frontend"] = "native"
            import socket as socketmod

            s = socketmod.socket(); s.bind(("127.0.0.1", 0))
            spec.http_port = s.getsockname()[1]
            s2 = socketmod.socket(); s2.bind(("127.0.0.1", 0))
            spec.grpc_port = s2.getsockname()[1]
            s.close(); s2.close()

            deployer = Deployer(device_ids=[0, 1])
            await deployer.apply(spec)
            http_handle, grpc_handle = await serve_deployment(deployer, spec.name,
                                                              host="127.0.0.1")
            from seldon_core_tpu.engine.native_ingress import fast_lane_for

            # multi-node graph: no fast lane, but full semantics via engine
            assert fast_lane_for(deployer.deployments[spec.name].gateway) is None

            def client_work():
                status, data = post(spec.http_port, "/api/v0.1/predictions",
                                    tensor_body([[1, 1, 1, 1]]))
                assert status == 200
                out = json.loads(data)
                assert "eg-router" in out["meta"]["routing"]
                # feedback flows through the fallback lane to the engine
                fb = {"request": json.loads(tensor_body([[1, 1, 1, 1]])),
                      "response": out, "reward": 1.0}
                status, _ = post(spec.http_port, "/api/v0.1/feedback",
                                 json.dumps(fb).encode())
                assert status == 200

            await asyncio.to_thread(client_work)
            st = http_handle.stats()
            assert st["raw_requests"] >= 2 and st["fast_requests"] == 0
            await http_handle.stop()
            await grpc_handle.stop(0)
            await deployer.delete(spec.name)

        asyncio.run(scenario())


class TestProtocolEdges:
    def test_malformed_json_falls_back_cleanly(self):
        def handler(method, path, body):
            return 400, "application/json", b'{"status":{"code":400}}'

        with NativeFrontServer(stub=True, feature_dim=4, raw_handler=handler) as srv:
            status, _ = post(srv.port, "/api/v0.1/predictions", b"{not json")
            assert status == 400

    def test_bad_raw_frame_falls_back(self):
        with NativeFrontServer(stub=True, feature_dim=4) as srv:
            status, data = post(
                srv.port, "/api/v0.1/predictions", b"garbage", "application/x-seldon-raw"
            )
            assert status == 404  # no raw handler registered

    def test_ragged_ndarray_rejected_from_fast_lane(self):
        # ragged rows must not be silently reshaped; they fall back
        # (and 404 here, with no raw handler registered)
        with NativeFrontServer(stub=True, out_dim=3) as srv:
            body = json.dumps({"data": {"ndarray": [[1, 2], [3, 4, 5, 6]]}}).encode()
            status, _ = post(srv.port, "/api/v0.1/predictions", body)
            assert status == 404

    def test_overflow_raw_frame_rejected(self):
        # shape dims that overflow the element count must not crash
        import struct

        with NativeFrontServer(stub=True, feature_dim=4) as srv:
            head = struct.pack("<IBBH", 0x31545253, 0, 2, 0)
            shape = struct.pack("<2q", 2**62, 4)
            status, _ = post(srv.port, "/api/v0.1/predictions",
                             head + shape + b"", "application/x-seldon-raw")
            assert status == 404  # falls out of the fast lane, no handler
            assert get(srv.port, "/ping") == (200, b"pong")  # still alive

    def test_half_close_still_answered(self):
        # client sends a request then shutdown(SHUT_WR): legal HTTP
        # half-close; the buffered request must still be served
        with NativeFrontServer(stub=True, out_dim=3, feature_dim=4) as srv:
            body = tensor_body([[1, 2, 3, 4]])
            s = socket.create_connection(("127.0.0.1", srv.port), timeout=10)
            s.sendall(
                b"POST /api/v0.1/predictions HTTP/1.1\r\nHost: x\r\n"
                b"Content-Type: application/json\r\nContent-Length: "
                + str(len(body)).encode() + b"\r\n\r\n" + body
            )
            s.shutdown(socket.SHUT_WR)
            buf = b""
            while True:
                chunk = s.recv(65536)
                if not chunk:
                    break
                buf += chunk
            s.close()
            assert b" 200 " in buf.split(b"\r\n", 1)[0]
            assert b'"shape":[1,3]' in buf

    def test_pipelined_requests_keep_order(self):
        with NativeFrontServer(stub=True, out_dim=3, feature_dim=4) as srv:
            s = socket.create_connection(("127.0.0.1", srv.port), timeout=10)
            reqs = b""
            for i in range(5):
                body = tensor_body([[1, 2, 3, 4]], puid=f"pu-{i}")
                reqs += (
                    b"POST /api/v0.1/predictions HTTP/1.1\r\nHost: x\r\n"
                    b"Content-Type: application/json\r\nContent-Length: "
                    + str(len(body)).encode() + b"\r\n\r\n" + body
                )
            s.sendall(reqs)
            buf = b""
            deadline = time.time() + 10
            puids = []
            while len(puids) < 5 and time.time() < deadline:
                chunk = s.recv(65536)
                if not chunk:
                    break
                buf += chunk
                while b"\r\n\r\n" in buf:
                    head, rest = buf.split(b"\r\n\r\n", 1)
                    cl = [h for h in head.split(b"\r\n") if h.lower().startswith(b"content-length")]
                    n = int(cl[0].split(b":")[1])
                    if len(rest) < n:
                        break
                    puids.append(json.loads(rest[:n])["meta"]["puid"])
                    buf = rest[n:]
            s.close()
            assert puids == [f"pu-{i}" for i in range(5)]

    def test_concurrency_smoke_qps(self):
        """Floor check: the native ingress must comfortably beat the
        Python servers on the same host (full target tracked in bench)."""
        with NativeFrontServer(stub=True, out_dim=3, feature_dim=4) as srv:
            body = tensor_body([[1, 2, 3, 4]])
            raw = (
                b"POST /api/v0.1/predictions HTTP/1.1\r\nHost: x\r\n"
                b"Content-Type: application/json\r\nContent-Length: "
                + str(len(body)).encode() + b"\r\n\r\n" + body
            )
            errs = []

            def worker(n):
                try:
                    s = socket.create_connection(("127.0.0.1", srv.port), timeout=10)
                    s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                    buf = b""
                    for _ in range(n):
                        s.sendall(raw)
                        while True:
                            if b"\r\n\r\n" in buf:
                                head, rest = buf.split(b"\r\n\r\n", 1)
                                assert b" 200 " in head.split(b"\r\n")[0]
                                cl = int(
                                    [h for h in head.split(b"\r\n")
                                     if h.lower().startswith(b"content-length")][0].split(b":")[1]
                                )
                                if len(rest) >= cl:
                                    buf = rest[cl:]
                                    break
                            chunk = s.recv(65536)
                            if not chunk:
                                raise RuntimeError("closed")
                            buf += chunk
                    s.close()
                except Exception as e:  # noqa: BLE001
                    errs.append(e)

            # best-of-3 windows: a single window on a loaded shared CI
            # host swings with scheduler noise (the same min-of-N
            # discipline the bench adopted, ADVICE r4) — the floor is
            # about the ingress, not about this minute's neighbors
            n, nthreads = 500, 8
            best = 0.0
            for _ in range(3):
                threads = [
                    threading.Thread(target=worker, args=(n,)) for _ in range(nthreads)
                ]
                t0 = time.perf_counter()
                for t in threads:
                    t.start()
                for t in threads:
                    t.join()
                dt = time.perf_counter() - t0
                assert not errs
                best = max(best, n * nthreads / dt)
                if best > 2000:
                    break
            assert best > 2000, f"native ingress too slow: {best:.0f} req/s"


class TestHardeningRound2:
    """Regressions for the round-2 review findings."""

    def test_chunked_transfer_rejected_411(self):
        with NativeFrontServer(stub=True, feature_dim=4) as srv:
            s = socket.create_connection(("127.0.0.1", srv.port), timeout=5)
            s.sendall(
                b"POST /api/v0.1/predictions HTTP/1.1\r\n"
                b"Host: x\r\nTransfer-Encoding: chunked\r\n"
                b"Content-Type: application/json\r\n\r\n"
                b"5\r\nhello\r\n0\r\n\r\n"
            )
            s.settimeout(5)
            data = b""
            while b"\r\n\r\n" not in data:
                chunk = s.recv(4096)
                if not chunk:
                    break
                data += chunk
            s.close()
            assert b"411" in data.split(b"\r\n", 1)[0]
            # connection is closed, chunk stream never parsed as requests
            assert data.count(b"HTTP/1.1") == 1

    def test_query_string_forwarded_to_raw_lane(self):
        seen = {}

        def handler(method, path, body):
            seen["path"] = path
            return 200, "application/json", b"{}"

        with NativeFrontServer(stub=True, feature_dim=4, raw_handler=handler) as srv:
            status, _ = post(srv.port, "/api/v0.1/feedback?predictor=canary&x=1", b"{}")
            assert status == 200
            assert seen["path"] == "/api/v0.1/feedback?predictor=canary&x=1"

    def test_zero_row_raw_frame_not_fast_laned(self):
        with NativeFrontServer(stub=True, feature_dim=4) as srv:
            frame = pack_raw_frame(np.zeros((0, 4), np.float32))
            status, data = post(srv.port, "/api/v0.1/predictions", frame,
                                content_type="application/x-seldon-raw")
            # no raw handler: empty batch rejected off the fast lane -> 404
            assert status == 404

    def test_puid_with_quote_escaped_in_response(self):
        with NativeFrontServer(stub=True, out_dim=3, feature_dim=4) as srv:
            status, data = post(srv.port, "/api/v0.1/predictions",
                                tensor_body([[1, 2, 3, 4]], puid='a"b\\c'))
            assert status == 200
            out = json.loads(data)  # must parse: puid escaped
            assert out["meta"]["puid"] == 'a"b\\c'


class TestRawHandlerSemantics:
    """GatewayRawHandler parity with the Python app's request handling."""

    def _handler_with_dummy_gateway(self):
        import asyncio

        calls = {}

        class DummyOut:
            status = None

            def to_json(self):
                return {"data": {"ndarray": [[1.0]]}}

        class DummyGateway:
            def by_name(self, name):
                calls["by_name"] = name
                return self if name == "canary" else None

            def pick(self):
                calls["pick"] = True
                return self

            async def predict(self, msg, predictor=None):
                calls["predictor"] = predictor
                return DummyOut()

            async def explain(self, msg):
                calls["explained"] = True
                return DummyOut()

            def pause(self):
                calls["paused"] = True

            def unpause(self):
                calls["unpaused"] = True

        loop = asyncio.new_event_loop()
        t = threading.Thread(target=loop.run_forever, daemon=True)
        t.start()
        return GatewayRawHandler(DummyGateway(), loop), calls, loop

    def test_get_predictions_with_json_query(self):
        h, calls, loop = self._handler_with_dummy_gateway()
        try:
            import urllib.parse

            payload = urllib.parse.quote(json.dumps({"data": {"ndarray": [[1, 2]]}}))
            status, _, body = h("GET", f"/api/v0.1/predictions?json={payload}", b"")
            assert status == 200
            assert json.loads(body)["data"]["ndarray"] == [[1.0]]
        finally:
            loop.call_soon_threadsafe(loop.stop)

    def test_form_encoded_json_field(self):
        h, calls, loop = self._handler_with_dummy_gateway()
        try:
            import urllib.parse

            body = urllib.parse.urlencode({"json": json.dumps({"data": {"ndarray": [[1]]}})}).encode()
            status, _, _ = h("POST", "/api/v0.1/predictions", body)
            assert status == 200
        finally:
            loop.call_soon_threadsafe(loop.stop)

    def test_empty_body_is_400_not_500(self):
        h, calls, loop = self._handler_with_dummy_gateway()
        try:
            status, _, body = h("POST", "/api/v0.1/predictions", b"")
            assert status == 400
            assert json.loads(body)["status"]["reason"] == "BAD_REQUEST"
        finally:
            loop.call_soon_threadsafe(loop.stop)

    def test_explanations_honour_predictor_query(self):
        h, calls, loop = self._handler_with_dummy_gateway()
        try:
            status, _, _ = h(
                "POST", "/api/v0.1/explanations?predictor=canary",
                json.dumps({"data": {"ndarray": [[1]]}}).encode(),
            )
            assert status == 200
            assert calls["by_name"] == "canary"
            assert calls.get("explained")
            assert "pick" not in calls
        finally:
            loop.call_soon_threadsafe(loop.stop)

    def test_pause_unpause_routes(self):
        h, calls, loop = self._handler_with_dummy_gateway()
        try:
            status, _, body = h("POST", "/pause", b"")
            assert (status, body) == (200, b"paused")
            assert calls.get("paused")
            status, _, body = h("PUT", "/unpause", b"")
            assert (status, body) == (200, b"unpaused")
            assert calls.get("unpaused")
        finally:
            loop.call_soon_threadsafe(loop.stop)


class TestHostBinding:
    def test_binds_loopback_only(self):
        with NativeFrontServer(stub=True, feature_dim=4, host="127.0.0.1") as srv:
            status, _ = get(srv.port, "/ping")
            assert status == 200

    def test_invalid_host_fails_loudly(self):
        with pytest.raises(OSError):
            NativeFrontServer(stub=True, feature_dim=4, host="not-an-ip").start()


class TestRawFrameClient:
    """The SDK's keep-alive binary client against the C++ fast lane."""

    def test_roundtrip_and_keepalive(self):
        from seldon_core_tpu.client.client import RawFrameClient

        with NativeFrontServer(stub=True, feature_dim=4, out_dim=3, model_name="s") as srv:
            with RawFrameClient(port=srv.port) as client:
                for _ in range(5):  # same socket, five requests
                    out = client.predict(np.ones((2, 4), np.float32))
                    assert out.shape == (2, 3)
                stats = srv.stats()
                assert stats["requests"] >= 5

    def test_transparent_reconnect_after_server_restart(self):
        """A keep-alive socket invalidated by a server restart on the
        same port is transparently re-dialed — the one retryable case."""
        import socket as socket_mod
        import time

        from seldon_core_tpu.client.client import RawFrameClient

        s = socket_mod.socket()
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
        s.close()

        first = NativeFrontServer(stub=True, feature_dim=4, out_dim=3, port=port, host="127.0.0.1")
        first.start()
        client = RawFrameClient(port=port)
        second = None
        try:
            assert client.predict(np.ones((1, 4), np.float32)).shape == (1, 3)
            first.stop()
            second = NativeFrontServer(stub=True, feature_dim=4, out_dim=3, port=port, host="127.0.0.1")
            for _ in range(20):  # the port may linger briefly
                try:
                    second.start()
                    break
                except Exception:
                    time.sleep(0.2)
            # the client's kept-alive socket is dead; predict must
            # transparently reconnect and succeed
            out = client.predict(np.ones((1, 4), np.float32))
            assert out.shape == (1, 3)
        finally:
            client.close()
            first.stop()
            if second is not None:
                second.stop()

    def test_dead_server_raises_without_duplicate_send(self):
        from seldon_core_tpu.client.client import RawFrameClient

        srv = NativeFrontServer(stub=True, feature_dim=4, out_dim=3)
        srv.start()
        port = srv.port
        client = RawFrameClient(port=port)
        try:
            assert client.predict(np.ones((1, 4), np.float32)).shape == (1, 3)
            srv.stop()
            with pytest.raises((ConnectionError, OSError, RuntimeError)):
                client.predict(np.ones((1, 4), np.float32))
        finally:
            client.close()
            srv.stop()

    def test_failure_status_raises(self):
        from seldon_core_tpu.client.client import RawFrameClient

        def handler(method, path, body):
            return 503, "application/json", b'{"status":{"status":"FAILURE"}}'

        with NativeFrontServer(stub=True, feature_dim=4, raw_handler=handler) as srv:
            with RawFrameClient(port=srv.port, path="/not-fast-lane") as client:
                with pytest.raises(RuntimeError, match="503"):
                    client.predict(np.ones((2, 9), np.float32))


class TestReadHttpResponseResetSemantics:
    """RST handling in the shared response reader: reset before ANY
    byte on a reused socket is the idle-keep-alive race (retryable,
    StaleConnection); reset mid-response is not."""

    class _Sock:
        def __init__(self, script):
            self.script = list(script)

        def settimeout(self, t):
            pass

        def recv(self, n):
            item = self.script.pop(0)
            if isinstance(item, Exception):
                raise item
            return item

    def test_rst_before_any_byte_is_stale(self):
        sock = self._Sock([ConnectionResetError()])
        with pytest.raises(fsmod.StaleConnection):
            fsmod.read_http_response(sock, b"")

    def test_rst_mid_headers_is_not_stale(self):
        sock = self._Sock([b"HTTP/1.1 200 OK\r\n", ConnectionResetError()])
        with pytest.raises(ConnectionError) as ei:
            fsmod.read_http_response(sock, b"")
        assert not isinstance(ei.value, fsmod.StaleConnection)

    def test_rst_mid_body_is_not_stale(self):
        sock = self._Sock([
            b"HTTP/1.1 200 OK\r\nContent-Length: 5\r\n\r\nab",
            ConnectionResetError(),
        ])
        with pytest.raises(ConnectionError) as ei:
            fsmod.read_http_response(sock, b"")
        assert not isinstance(ei.value, fsmod.StaleConnection)

    def test_leftover_buffer_counts_as_received(self):
        # bytes already buffered from this response mean a reset is
        # mid-response even if recv never returned anything
        sock = self._Sock([ConnectionResetError()])
        with pytest.raises(ConnectionError) as ei:
            fsmod.read_http_response(sock, b"HTTP/1.1 2")
        assert not isinstance(ei.value, fsmod.StaleConnection)


class TestNativeLoadgen:
    """The C++ epoll load client (native/loadgen.cc) — the bench's
    client must be cheaper than the server it measures."""

    @staticmethod
    def _payload(path="/api/v0.1/predictions"):
        from seldon_core_tpu.testing.loadgen import build_http_blob

        return build_http_blob(
            path, fsmod.pack_raw_frame(np.ones((1, 4), np.float32)),
            content_type="application/x-seldon-raw",
        )

    def test_counts_match_server_stats(self):
        with NativeFrontServer(stub=True, out_dim=3, feature_dim=4, model_name="stub") as srv:
            out = fsmod.native_load(srv.port, self._payload(), seconds=1.0,
                                    connections=2, depth=8)
            assert out is not None
            assert out["errors"] == 0 and out["non2xx"] == 0
            assert out["ok"] > 100  # sanity: real throughput flowed
            stats = srv.stats()
        # every counted completion was a request the server actually served
        # (the server may have served a few more in the drain window)
        assert stats["requests"] >= out["ok"]
        assert stats["failures"] == 0

    def test_non_2xx_not_counted_as_ok(self):
        with NativeFrontServer(stub=True, out_dim=3, feature_dim=4, model_name="stub") as srv:
            out = fsmod.native_load(srv.port, self._payload(path="/nope"),
                                    seconds=0.5, connections=2, depth=4)
            assert out is not None
            assert out["ok"] == 0
            assert out["non2xx"] > 0

    def test_connection_refused_reports_errors(self):
        # a port nothing listens on: every connection dies, zero counted
        sock = socket.socket()
        sock.bind(("127.0.0.1", 0))
        port = sock.getsockname()[1]
        sock.close()  # free it; nothing listens now
        out = fsmod.native_load(port, self._payload(), seconds=0.5,
                                connections=3, depth=2)
        assert out is not None
        assert out["ok"] == 0
        assert out["errors"] == 3

    def test_bad_args_are_rejected(self):
        out = fsmod.native_load(1, b"", seconds=0.5, connections=2, depth=2)
        assert out is not None
        assert out["ok"] == 0 and out["errors"] >= 1

    def test_connection_close_server_counts_delivered_responses(self):
        """A server that answers once then closes (Connection: close)
        must yield its delivered responses as ok, not as errors."""
        import socketserver

        class OneShot(socketserver.BaseRequestHandler):
            def handle(self):
                buf = b""
                while b"\r\n\r\n" not in buf:
                    chunk = self.request.recv(4096)
                    if not chunk:
                        return
                    buf += chunk
                body = b"{}"
                self.request.sendall(
                    b"HTTP/1.1 200 OK\r\nConnection: close\r\n"
                    b"Content-Length: %d\r\n\r\n%s" % (len(body), body)
                )
                # close happens when handle returns

        with socketserver.ThreadingTCPServer(("127.0.0.1", 0), OneShot) as srv:
            t = threading.Thread(target=srv.serve_forever, daemon=True)
            t.start()
            out = fsmod.native_load(
                srv.server_address[1], self._payload(), seconds=0.5,
                connections=3, depth=1,
            )
            srv.shutdown()
        assert out is not None
        # each connection delivered exactly one response before closing;
        # the close with one request still owed is the server's choice,
        # not a client error
        assert out["ok"] == 3, out
        assert out["errors"] == 0, out
