"""Page-granular automatic prefix caching (r9): refcounted page reuse,
cached-prefill skip, LRU reclamation.

Correctness bar: greedy decode is bit-exact cache-on vs cache-off —
shared pages are read-only bit-identical KV — across both chunk impls
(ring | pool) × w8a8 × speculative (including the draft-hint lane).
Exactness is asserted in the f32 regime, the same single-numeric-regime
discipline every cross-program parity suite here uses (bf16 carries the
documented one-ulp cross-program caveat — see tools/profile_prefix_cache).

Fast tier: one tiny engine pays the only compiles; the allocator,
index, capacity and collision tests are host-side.  The full parity
matrix and the churn test are @slow.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from seldon_core_tpu.models import paged as paged_mod
from seldon_core_tpu.models.paged import PagedEngine, StreamingLM
from seldon_core_tpu.models.transformer import TransformerLM
from seldon_core_tpu.runtime.component import MicroserviceError

CFG = dict(vocab_size=64, d_model=32, num_layers=1, num_heads=2, max_len=128)


@pytest.fixture(scope="module")
def params():
    lm = TransformerLM(dtype=jnp.float32, **CFG)
    return lm.init(jax.random.key(0), jnp.zeros((1, 8), jnp.int32))["params"]


def _engine(params, **kw):
    base = dict(dtype=jnp.float32, page_size=8, max_slots=2, steps_per_call=4)
    base.update(kw)
    return PagedEngine(params, **CFG, **base)


def _shared_prompts(n=3, shared_tokens=16, seed=0):
    """n prompts sharing a ``shared_tokens`` system prefix (page-aligned
    at page_size 8) with distinct suffixes."""
    rng = np.random.default_rng(seed)
    shared = rng.integers(0, CFG["vocab_size"], size=(shared_tokens,)).astype(
        np.int32
    )
    return [
        np.concatenate(
            [shared, rng.integers(0, CFG["vocab_size"], size=(3 + i,)).astype(np.int32)]
        )
        for i in range(n)
    ]


class TestPrefixReuse:
    def test_sequential_shared_prefix_bit_exact_with_hits(self, params):
        """First request misses and publishes the prefix pages; every
        follower maps them and prefills only its suffix — emitting
        exactly the tokens the cache-off engine emits."""
        on = _engine(params)
        off = _engine(params, prefix_cache=False)
        prompts = _shared_prompts()
        for p in prompts:
            a = on.generate(p, max_new_tokens=6)
            b = off.generate(p, max_new_tokens=6)
            np.testing.assert_array_equal(a, b)
        s = on.engine_stats()
        assert s["prefix_misses"] == 1 and s["prefix_hits"] == 2
        # 16 shared tokens = 2 pages skipped per follower
        assert s["prefix_tokens_saved"] == 2 * 16
        assert s["prefix_pages_cached"] > 0
        # cached pages are NOT "used": they are reclaimable capacity
        assert s["pool_pages_used"] == 0
        so = off.engine_stats()
        assert so["prefix_hits"] == so["prefix_misses"] == 0
        assert so["prefix_pages_cached"] == 0

    def test_concurrent_streams_share_pages_by_refcount(self, params):
        """A follower admitted while the publisher still decodes maps
        the same physical pages (refcount 2, identical block-table
        prefix) — sharing is block-table indirection, not a copy."""
        on = _engine(params, max_slots=2)
        prompts = _shared_prompts(n=2)
        a = on.submit(prompts[0], max_new_tokens=20)
        on.step()  # admit + prefill + first chunk; registers the prefix
        assert a.slot is not None and a.result is None
        b = on.submit(prompts[1], max_new_tokens=12)
        on.step()  # admits b mid-flight
        assert b.slot is not None and b.result is None
        assert b.cached_len == 16
        shared_pages = a.pages[:2]
        assert b.pages[:2] == shared_pages
        for p in shared_pages:
            assert int(on._page_ref[p]) == 2
        on.run()
        off = _engine(params, prefix_cache=False)
        np.testing.assert_array_equal(
            a.result, off.generate(prompts[0], max_new_tokens=20)
        )
        np.testing.assert_array_equal(
            b.result, off.generate(prompts[1], max_new_tokens=12)
        )
        # both finished: shared pages sit on the LRU exactly once
        for p in shared_pages:
            assert int(on._page_ref[p]) == 0
            assert p in on._lru

    def test_env_knob_disables(self, params, monkeypatch):
        monkeypatch.setenv("SELDON_TPU_PREFIX_CACHE", "0")
        eng = _engine(params)
        for p in _shared_prompts():
            eng.generate(p, max_new_tokens=4)
        s = eng.engine_stats()
        assert s["prefix_hits"] == s["prefix_misses"] == 0
        assert s["prefix_pages_cached"] == 0
        assert len(eng._free_pages) == eng.num_pages - 1  # all freed eagerly

    def test_constructor_arg_wins_over_env(self, params, monkeypatch):
        monkeypatch.setenv("SELDON_TPU_PREFIX_CACHE", "0")
        eng = _engine(params, prefix_cache=True)
        assert eng._prefix_cache_enabled
        eng.generate(_shared_prompts()[0], max_new_tokens=4)
        assert eng.engine_stats()["prefix_pages_cached"] > 0

    def test_last_prompt_page_stays_private(self, params):
        """Even an exactly page-aligned prompt keeps its final page out
        of the index: the suffix prefill always has >= 1 token to
        produce next-token logits from."""
        eng = _engine(params)
        prompt = np.arange(16, dtype=np.int32) % CFG["vocab_size"]  # 2 pages
        eng.generate(prompt, max_new_tokens=4)
        eng.generate(prompt.copy(), max_new_tokens=4)
        s = eng.engine_stats()
        assert s["prefix_hits"] == 1
        # only page 0 is shareable: (16 - 1) // 8 = 1 full page
        assert s["prefix_tokens_saved"] == 8


class TestAllocator:
    def test_alloc_free_refcount_discipline(self, params):
        eng = _engine(params)
        with eng._lock:
            total = eng.num_pages - 1
            got = eng._alloc_locked(3)
            assert len(got) == 3 and len(eng._free_pages) == total - 3
            assert all(int(eng._page_ref[p]) == 1 for p in got)
            assert eng._alloc_locked(total) is None  # over capacity: refused
            eng._free_locked(got)
            assert len(eng._free_pages) == total
            assert all(int(eng._page_ref[p]) == 0 for p in got)

    def test_alloc_reclaims_lru_cached_pages(self, params):
        eng = _engine(params)
        eng.generate(_shared_prompts()[0], max_new_tokens=4)
        s = eng.engine_stats()
        assert s["prefix_pages_cached"] > 0
        with eng._lock:
            total = eng.num_pages - 1
            got = eng._alloc_locked(total)  # must evict every cached page
            assert got is not None and len(got) == total
        s = eng.engine_stats()
        assert s["prefix_pages_cached"] == 0
        assert s["prefix_evictions"] > 0

    def test_debug_invariants_clean_under_workload(self, params, monkeypatch):
        monkeypatch.setenv("SELDON_TPU_PAGED_DEBUG", "1")
        eng = _engine(params)
        assert eng._debug_invariants
        for p in _shared_prompts():
            eng.generate(p, max_new_tokens=6)  # raises on any violation

    def test_registration_noop_after_fail_all_race(self, params):
        """fail_all from another thread between admission and prefix
        registration clears the stream's pages but leaves its slot id:
        registration must detect the lost slot and publish nothing
        (regression: it indexed the emptied pages list)."""
        eng = _engine(params)
        stream = eng.submit(_shared_prompts(n=1)[0], max_new_tokens=4)
        with eng._lock:
            admitted = eng._admit_locked()
        assert admitted and admitted[0][0] is stream
        eng.fail_all(RuntimeError("injected"))
        assert stream.pages == [] and stream.slot is not None
        with eng._lock:
            eng._register_prefix_locked(stream)  # must not raise
        assert not eng._prefix_index

    def test_invariant_checker_catches_corruption(self, params):
        eng = _engine(params)
        stream = eng.submit(_shared_prompts()[0], max_new_tokens=20)
        eng.step()
        assert stream.slot is not None
        with eng._lock:
            eng._free_pages.append(stream.pages[0])  # free AND mapped
            with pytest.raises(RuntimeError, match="invariant"):
                eng._check_invariants_locked()
            eng._free_pages.pop()
            eng._check_invariants_locked()  # restored: clean
        eng.run()


class TestAdmissionCapacity:
    def test_admitted_after_evicting_cached_pages(self, params):
        """A request is admitted when only LRU-cached pages stand in
        its way: allocation reclaims them instead of stalling."""
        # 6 usable pages; a finished 2-page-prompt stream caches 1 page
        eng = _engine(params, num_pages=7, max_slots=1)
        first = _shared_prompts(n=1)[0][:15]
        out_a = eng.generate(first, max_new_tokens=4)
        assert eng.engine_stats()["prefix_pages_cached"] == 1
        # 40 tokens prompt + 8 new = 6 pages: needs the cached one back
        big = (np.arange(40, dtype=np.int32) * 3) % CFG["vocab_size"]
        out_b = eng.generate(big, max_new_tokens=8)
        s = eng.engine_stats()
        assert s["prefix_evictions"] >= 1
        assert s["completed"] == 2 and s["evictions"] == 0  # no stream evicted
        off = _engine(params, num_pages=7, max_slots=1, prefix_cache=False)
        np.testing.assert_array_equal(out_a, off.generate(first, max_new_tokens=4))
        np.testing.assert_array_equal(out_b, off.generate(big, max_new_tokens=8))

    def test_submit_guard_prices_full_pool_not_free_list(self, params):
        """The SEQUENCE_TOO_LONG ceiling is the whole non-trash pool —
        a warm cache must never shrink the admissible request size."""
        eng = _engine(params, num_pages=7, max_slots=1)
        eng.generate(_shared_prompts(n=1)[0][:15], max_new_tokens=4)
        assert eng.engine_stats()["prefix_pages_cached"] > 0
        # exactly fills the pool: admissible despite the cached pages
        ok = eng.submit(np.arange(40, dtype=np.int32) % 64, max_new_tokens=8)
        eng.run()
        assert ok.error is None and ok.result is not None
        # one page over the pool: rejected regardless of cache state
        with pytest.raises(MicroserviceError, match="needs 7 pages") as exc:
            eng.submit(np.arange(48, dtype=np.int32) % 64, max_new_tokens=8)
        assert exc.value.reason == "SEQUENCE_TOO_LONG"


class TestCollisionHardening:
    def test_colliding_keys_verify_tokens_before_sharing(self, params, monkeypatch):
        """With every chain key colliding, token-equality verification
        must keep foreign KV out of the match — different prompts stay
        private (and correct); identical prompts still share."""
        monkeypatch.setattr(paged_mod, "prefix_chain_key", lambda p, t: 7)
        eng = _engine(params)
        off = _engine(params, prefix_cache=False)
        p1 = (np.arange(20, dtype=np.int32) * 5) % CFG["vocab_size"]
        p2 = (np.arange(20, dtype=np.int32) * 11 + 3) % CFG["vocab_size"]
        np.testing.assert_array_equal(
            eng.generate(p1, max_new_tokens=6), off.generate(p1, max_new_tokens=6)
        )
        np.testing.assert_array_equal(
            eng.generate(p2, max_new_tokens=6), off.generate(p2, max_new_tokens=6)
        )
        s = eng.engine_stats()
        assert s["prefix_hits"] == 0 and s["prefix_misses"] == 2
        # identical tokens DO match under the colliding key
        np.testing.assert_array_equal(
            eng.generate(p1.copy(), max_new_tokens=6),
            off.generate(p1.copy(), max_new_tokens=6),
        )
        assert eng.engine_stats()["prefix_hits"] == 1


class TestObservabilitySurface:
    def test_engine_stats_carries_prefix_keys(self, params):
        s = _engine(params).engine_stats()
        for key in ("prefix_hits", "prefix_misses", "prefix_evictions",
                    "prefix_tokens_saved", "prefix_pages_cached"):
            assert key in s

    def test_flight_recorder_records_carry_prefix_fields(
        self, params, monkeypatch
    ):
        monkeypatch.setenv("SELDON_TPU_FLIGHT_RECORDER", "64")
        eng = _engine(params)
        for p in _shared_prompts(n=2):
            eng.generate(p, max_new_tokens=4)
        recs = eng.engine_stats(detail=True)["recorder"]
        assert recs
        for rec in recs:
            for key in ("prefix_hits", "prefix_tokens_saved",
                        "prefix_pages_cached"):
                assert key in rec
        # one admission wave hit (the second request)
        assert sum(r["prefix_hits"] for r in recs) == 1
        assert sum(r["prefix_tokens_saved"] for r in recs) == 16

    def test_streaminglm_exports_prefix_gauges(self):
        comp = StreamingLM(max_slots=2, steps_per_call=2, **CFG)
        comp.load()
        try:
            keys = {m["key"] for m in comp.metrics()}
            assert {"paged_prefix_hit_rate", "paged_prefix_pages_cached",
                    "paged_prefix_tokens_saved"} <= keys
        finally:
            comp.shutdown()


@pytest.mark.slow
class TestParityMatrix:
    """The tentpole correctness bar: greedy bit-exactness cache-on vs
    cache-off across chunk impls × w8a8 × speculative (incl. the
    draft-hint oracle lane), in the f32 exactness regime."""

    MCFG = dict(vocab_size=64, d_model=32, num_layers=2, num_heads=4,
                max_len=64)

    @pytest.fixture(scope="class")
    def mparams(self):
        lm = TransformerLM(dtype=jnp.float32, **self.MCFG)
        return lm.init(jax.random.key(1), jnp.zeros((1, 8), jnp.int32))["params"]

    def _prompts(self):
        rng = np.random.default_rng(3)
        shared = rng.integers(0, 64, size=(17,)).astype(np.int32)
        return [
            np.concatenate(
                [shared, rng.integers(0, 64, size=(2 + i,)).astype(np.int32)]
            )
            for i in range(3)
        ]

    def _run(self, params, monkeypatch, *, impl, precision, speculative,
             prefix_cache, hints=None):
        monkeypatch.setenv("SELDON_TPU_CHUNK_IMPL", impl)
        eng = PagedEngine(
            params, dtype=jnp.float32, page_size=8, max_slots=2,
            steps_per_call=4, precision=precision,
            speculative=speculative, prefix_cache=prefix_cache, **self.MCFG,
        )
        outs = []
        for i, p in enumerate(self._prompts()):
            stream = eng.submit(
                p, max_new_tokens=8,
                draft_hint=None if hints is None else hints[i],
            )
            eng.run()
            outs.append(stream.result)
        return outs, eng.engine_stats()

    @pytest.mark.parametrize("impl", ["ring", "pool"])
    @pytest.mark.parametrize("precision", ["", "w8a8"])
    def test_plain_decode_parity(self, mparams, monkeypatch, impl, precision):
        on, s_on = self._run(mparams, monkeypatch, impl=impl,
                             precision=precision, speculative=None,
                             prefix_cache=True)
        off, _ = self._run(mparams, monkeypatch, impl=impl,
                           precision=precision, speculative=None,
                           prefix_cache=False)
        for a, b in zip(on, off):
            np.testing.assert_array_equal(a, b)
        assert s_on["prefix_hits"] == 2  # the cache actually engaged

    @pytest.mark.parametrize("precision", ["", "w8a8"])
    @pytest.mark.parametrize("draft", ["ngram", "oracle"])
    def test_speculative_parity_including_draft_hint(
        self, mparams, monkeypatch, precision, draft
    ):
        plain, _ = self._run(mparams, monkeypatch, impl="ring",
                             precision=precision, speculative=None,
                             prefix_cache=False)
        spec_cfg = {"draft": draft, "draft_k": 3}
        hints = list(plain) if draft == "oracle" else None
        on, s_on = self._run(mparams, monkeypatch, impl="ring",
                             precision=precision, speculative=spec_cfg,
                             prefix_cache=True, hints=hints)
        off, _ = self._run(mparams, monkeypatch, impl="ring",
                           precision=precision, speculative=spec_cfg,
                           prefix_cache=False, hints=hints)
        for a, b, c in zip(on, off, plain):
            np.testing.assert_array_equal(a, b)
            np.testing.assert_array_equal(a, c)
        assert s_on["prefix_hits"] == 2


@pytest.mark.slow
class TestEvictionChurn:
    def test_competing_prefixes_churn_with_invariants(self, params, monkeypatch):
        """Two system prompts through a pool sized for one: sustained
        LRU reclamation (the PrefixCacheThrash traffic shape) with the
        debug audit on, outputs exact throughout."""
        monkeypatch.setenv("SELDON_TPU_PAGED_DEBUG", "1")
        rng = np.random.default_rng(9)
        shareds = [
            rng.integers(0, 64, size=(24,)).astype(np.int32) for _ in range(2)
        ]
        prompts = [
            np.concatenate(
                [shareds[i % 2],
                 rng.integers(0, 64, size=(3 + i,)).astype(np.int32)]
            )
            for i in range(6)
        ]
        eng = _engine(params, num_pages=8, max_slots=1)
        off = _engine(params, num_pages=8, max_slots=1, prefix_cache=False)
        for p in prompts:
            np.testing.assert_array_equal(
                eng.generate(p, max_new_tokens=6),
                off.generate(p, max_new_tokens=6),
            )
        s = eng.engine_stats()
        assert s["prefix_evictions"] > 0
        assert s["completed"] == 6
