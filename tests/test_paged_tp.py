"""Tensor-parallel paged generation (r11): GSPMD `model`-axis sharding
of the KV pool, the (w8a8) projections, and every engine program.

Correctness bar, same discipline as the prefix cache / bucket PRs:
greedy decode is BIT-EXACT TP=1 vs TP=N in the f32 exactness regime —
the TP program computes the same einsums over head shards and
all-reduces the partial sums, and f32 addition over the same operand
partitioning is the venue where that must reproduce exactly.  The TP=1
program must stay byte-identical to the pre-TP engine (mesh=None takes
the EXACT historical jit path), so single-chip deployments carry zero
regression risk.

Fast tier: knob/mesh semantics, `parallel/sharding.py` unit coverage,
the TP=1 byte-identical lowering, one tp=2 parity smoke, and the
monitoring surface (engine_stats -> Prometheus bridge -> StreamingLM
gauges) — conftest forces 8 CPU host devices, so tp=2 runs everywhere.
The full parity matrix (ring|pool × w8a8 × speculative × prefix-cache)
and the promoted MULTICHIP dry-run are @slow.
"""

import logging

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from seldon_core_tpu.models.paged import PagedEngine, StreamingLM
from seldon_core_tpu.models.transformer import TransformerLM
from seldon_core_tpu.parallel.mesh import create_mesh, resolve_tp, tp_mesh
from seldon_core_tpu.parallel.sharding import (
    infer_param_specs,
    shard_decode_state,
    shard_params,
)

CFG = dict(vocab_size=64, d_model=32, num_layers=1, num_heads=4, max_len=64)


@pytest.fixture(scope="module")
def params():
    lm = TransformerLM(dtype=jnp.float32, **CFG)
    return lm.init(jax.random.key(0), jnp.zeros((1, 8), jnp.int32))["params"]


def _engine(params, **kw):
    base = dict(dtype=jnp.float32, page_size=8, max_slots=2, steps_per_call=4)
    base.update(kw)
    return PagedEngine(params, **CFG, **base)


def _prompts(n=2, seed=3):
    rng = np.random.default_rng(seed)
    return [
        rng.integers(0, CFG["vocab_size"], size=(5 + 3 * i,)).astype(np.int32)
        for i in range(n)
    ]


def _serve(eng, prompts, max_new=6, hints=None):
    streams = [
        eng.submit(
            p, max_new_tokens=max_new,
            draft_hint=None if hints is None else hints[i],
        )
        for i, p in enumerate(prompts)
    ]
    eng.run()
    for s in streams:
        assert s.error is None, s.error
    return [s.result for s in streams]


class TestTpKnob:
    """resolve_tp / tp_mesh: the ONE place the knob's precedence lives."""

    def test_explicit_beats_env(self, monkeypatch):
        monkeypatch.setenv("SELDON_TPU_TP", "4")
        assert resolve_tp(2) == 2

    def test_env_fallback_and_default_off(self, monkeypatch):
        monkeypatch.setenv("SELDON_TPU_TP", "2")
        assert resolve_tp(None) == 2
        assert resolve_tp(0) == 2
        # an explicit 1 FORCES single-chip over the env
        assert resolve_tp(1) == 1
        monkeypatch.delenv("SELDON_TPU_TP")
        assert resolve_tp(None) == 1

    def test_env_zero_spells_off(self, monkeypatch):
        # SELDON_TPU_TP=0 disables, matching every other =0 knob —
        # it must never crash engine load
        monkeypatch.setenv("SELDON_TPU_TP", "0")
        assert resolve_tp(None) == 1
        assert tp_mesh(None) is None

    def test_degree_below_one_rejected(self):
        with pytest.raises(ValueError):
            resolve_tp(-1)

    def test_tp_one_means_no_mesh(self, monkeypatch):
        monkeypatch.delenv("SELDON_TPU_TP", raising=False)
        assert tp_mesh(1) is None
        assert tp_mesh(None) is None

    def test_builds_model_mesh_when_devices_allow(self):
        mesh = tp_mesh(2)
        assert mesh is not None
        assert dict(zip(mesh.axis_names, mesh.devices.shape)) == {"model": 2}

    def test_degrades_to_single_chip_with_warn(self, caplog):
        with caplog.at_level(
            logging.WARNING, logger="seldon_core_tpu.parallel.mesh"
        ):
            assert tp_mesh(4096) is None
        assert any("degrading to single-chip" in r.message
                   for r in caplog.records)

    def test_strict_raises_instead_of_degrading(self):
        with pytest.raises(ValueError, match="degrading"):
            tp_mesh(4096, strict=True)


class TestShardingUnits:
    """infer_param_specs / shard_params / shard_decode_state coverage."""

    @pytest.fixture(scope="class")
    def mesh(self):
        return create_mesh({"model": 2}, devices=jax.devices()[:2])

    def test_spec_choices_dense_conv_bias_scale(self, mesh):
        from jax.sharding import PartitionSpec as P

        tree = {
            "dense": np.zeros((256, 128), np.float32),
            "conv": np.zeros((3, 3, 16, 64), np.float32),
            "bias": np.zeros((128,), np.float32),
            "scale": np.zeros((8,), np.float32),
        }
        specs = infer_param_specs(tree, mesh, min_weight_size=1024)
        # dense: largest divisible dim carries the model axis
        assert specs["dense"] == P("model", None)
        # conv: the output-channel dim (largest) shards
        assert specs["conv"] == P(None, None, None, "model")
        # small weights replicate
        assert specs["bias"] == P()
        assert specs["scale"] == P()

    def test_shard_decode_state_round_trip(self, mesh):
        tree = {"w": np.arange(64, dtype=np.float32).reshape(8, 8)}
        pool_shape = (1, 5, 8, 4, 8)
        p2, pk, pv = shard_decode_state(
            tree, mesh, pool_shape=pool_shape, dtype=jnp.float32,
            min_weight_size=0, num_heads=4,
        )
        # pools: created ALREADY sharded on the heads dim, zeros
        assert pk.shape == pool_shape and pv.shape == pool_shape
        assert pk.sharding.spec[3] == "model"
        assert pk.addressable_shards[0].data.shape[3] == 2  # 4 heads / 2
        np.testing.assert_array_equal(np.asarray(pk), np.zeros(pool_shape))
        # params: values survive the sharded placement bit-exactly
        np.testing.assert_array_equal(np.asarray(p2["w"]), tree["w"])
        assert p2["w"].sharding.spec == ("model", None)

    def test_indivisible_heads_replicate_pool_with_warn(self, mesh, caplog):
        with caplog.at_level(
            logging.WARNING, logger="seldon_core_tpu.parallel.sharding"
        ):
            _, pk, _ = shard_decode_state(
                {}, mesh, pool_shape=(1, 5, 8, 3, 8), dtype=jnp.float32,
                num_heads=3,
            )
        assert any("NOT sharded" in r.message for r in caplog.records)
        # replicated: one device holds the full pool shape
        assert pk.addressable_shards[0].data.shape == (1, 5, 8, 3, 8)

    def test_unannotatable_leaf_degrades_replicated_with_warn(
        self, mesh, caplog
    ):
        """Satellite guard: a leaf whose spec device_put rejects falls
        back to replicated with a WARN; a leaf that cannot be placed at
        all passes through host-side — engine load NEVER crashes on one
        odd checkpoint leaf."""
        from jax.sharding import PartitionSpec as P

        tree = {"good": np.zeros((4, 4), np.float32),
                "bad": np.zeros((6,), np.float32),
                "alien": "not-an-array"}
        specs = {"good": P(), "bad": P(None, "model"),  # rank mismatch
                 "alien": P()}
        with caplog.at_level(
            logging.WARNING, logger="seldon_core_tpu.parallel.sharding"
        ):
            out = shard_params(tree, mesh, specs=specs)
        msgs = [r.message for r in caplog.records]
        assert any("falling back to replicated" in m for m in msgs)
        assert any("not device-placeable" in m for m in msgs)
        np.testing.assert_array_equal(np.asarray(out["bad"]), tree["bad"])
        assert out["alien"] == "not-an-array"  # host-side pass-through


class TestTpOneByteIdentical:
    """The no-regression bar for single-chip hosts: tp=1 resolves to
    mesh=None, which takes the EXACT historical jit path — the lowered
    chunk program is byte-identical and carries no collectives."""

    @staticmethod
    def _lower_chunk(eng, steps=2, horizon=4):
        # the engine's shared audit surface: same body selection and
        # _tp_jit annotation as the serving path, so this can't drift
        return eng.lower_chunk(steps, ((eng.max_slots, horizon),)).as_text()

    def test_tp1_knob_program_byte_identical_to_meshless(
        self, params, monkeypatch
    ):
        monkeypatch.delenv("SELDON_TPU_TP", raising=False)
        plain = _engine(params)
        knob = _engine(params, tp=1)
        try:
            assert knob._mesh is None and knob.tp_degree == 1
            a = self._lower_chunk(plain)
            b = self._lower_chunk(knob)
        finally:
            plain.close()
            knob.close()
        assert a == b

    def test_tp1_program_carries_no_collectives(self, params):
        eng = _engine(params)
        try:
            text = self._lower_chunk(eng)
        finally:
            eng.close()
        for op in ("all-reduce", "all-gather", "reduce-scatter",
                   "collective-permute"):
            assert op not in text


class TestTpParitySmoke:
    """Fast-tier tp=2 coverage: one ring/f32 combo decodes bit-exactly
    vs TP=1, and the TP bookkeeping surfaces honestly."""

    def test_tp2_greedy_bit_exact_and_stats(self, params):
        off = _engine(params, tp=1)
        outs_off = _serve(off, _prompts())
        s_off = off.engine_stats()
        off.close()

        on = _engine(params, tp=2, shard_min_weight_size=0)
        assert on.tp_degree == 2
        outs_on = _serve(on, _prompts())
        s_on = on.engine_stats()
        on.close()

        for a, b in zip(outs_on, outs_off):
            np.testing.assert_array_equal(a, b)
        assert s_on["tp_degree"] == 2 and s_off["tp_degree"] == 1
        # heads-sharded pool: one device holds HALF the K+V bytes
        assert s_on["pool_shard_bytes"] == s_off["pool_shard_bytes"] // 2

    def test_env_knob_reaches_engine(self, params, monkeypatch):
        monkeypatch.setenv("SELDON_TPU_TP", "2")
        eng = _engine(params, shard_min_weight_size=0)
        try:
            assert eng.tp_degree == 2
        finally:
            eng.close()

    def test_oversized_tp_degrades_engine_to_single_chip(
        self, params, caplog
    ):
        with caplog.at_level(
            logging.WARNING, logger="seldon_core_tpu.parallel.mesh"
        ):
            eng = _engine(params, tp=4096)
        try:
            assert eng.tp_degree == 1 and eng._mesh is None
        finally:
            eng.close()
        assert any("degrading to single-chip" in r.message
                   for r in caplog.records)


class TestTpObservability:
    """tp_degree + per-shard pool bytes thread engine_stats -> the
    Prometheus bridge -> StreamingLM's component gauges."""

    def test_bridge_exports_tp_gauges(self, params):
        import prometheus_client as prom

        from seldon_core_tpu.utils.metrics import GenerationPrometheusBridge

        registry = prom.CollectorRegistry()
        eng = _engine(params, tp=2, shard_min_weight_size=0)
        try:
            GenerationPrometheusBridge(
                eng, deployment_name="d", predictor_name="p",
                model_name="m", registry=registry,
            ).collect()
            labels = {"deployment_name": "d", "predictor_name": "p",
                      "model_name": "m"}
            assert registry.get_sample_value(
                "seldon_tpu_engine_tp_degree", labels) == 2.0
            assert registry.get_sample_value(
                "seldon_tpu_engine_pool_shard_bytes", labels
            ) == float(eng.engine_stats()["pool_shard_bytes"])
        finally:
            eng.close()

    def test_streaminglm_tp_knob_and_gauge(self):
        comp = StreamingLM(max_slots=2, steps_per_call=2, tp=2, **CFG)
        comp.load()
        try:
            assert comp.engine.tp_degree == 2
            by_key = {m["key"]: m["value"] for m in comp.metrics()}
            assert by_key["paged_tp_degree"] == 2
        finally:
            comp.shutdown()

    def test_chunk_records_carry_tp_degree(self, params, monkeypatch):
        monkeypatch.setenv("SELDON_TPU_FLIGHT_RECORDER", "64")
        eng = _engine(params, tp=2, shard_min_weight_size=0)
        try:
            _serve(eng, _prompts())
            recs = eng.recorder.snapshot()
            assert recs and all(r["tp_degree"] == 2 for r in recs
                                if r.get("phase") == "decode")
        finally:
            eng.close()


@pytest.mark.slow
class TestTpParityMatrix:
    """The tentpole correctness bar: greedy bit-exactness TP=1 vs TP=2
    across chunk impls × w8a8 × speculative × prefix-cache on/off, in
    the f32 exactness regime."""

    MCFG = dict(vocab_size=64, d_model=32, num_layers=2, num_heads=4,
                max_len=64)

    @pytest.fixture(scope="class")
    def mparams(self):
        lm = TransformerLM(dtype=jnp.float32, **self.MCFG)
        return lm.init(jax.random.key(1), jnp.zeros((1, 8), jnp.int32))["params"]

    def _mprompts(self):
        rng = np.random.default_rng(3)
        shared = rng.integers(0, 64, size=(17,)).astype(np.int32)
        return [
            np.concatenate(
                [shared, rng.integers(0, 64, size=(2 + i,)).astype(np.int32)]
            )
            for i in range(3)
        ]

    def _run(self, params, monkeypatch, *, tp, impl, precision, speculative,
             prefix_cache):
        monkeypatch.setenv("SELDON_TPU_CHUNK_IMPL", impl)
        # tp passed EXPLICITLY (1 forces single-chip): the TP-off
        # baseline must stay off even with SELDON_TPU_TP in the env,
        # or the parity check degenerates to TP-vs-TP
        eng = PagedEngine(
            params, dtype=jnp.float32, page_size=8, max_slots=2,
            steps_per_call=4, precision=precision, speculative=speculative,
            prefix_cache=prefix_cache, tp=tp,
            shard_min_weight_size=0, **self.MCFG,
        )
        assert eng.tp_degree == tp
        outs = []
        try:
            for p in self._mprompts():
                stream = eng.submit(p, max_new_tokens=8)
                eng.run()
                outs.append(stream.result)
        finally:
            eng.close()
        return outs

    @pytest.mark.parametrize("impl", ["ring", "pool"])
    @pytest.mark.parametrize("precision", ["", "w8a8"])
    @pytest.mark.parametrize("spec", [None, {"draft": "ngram", "draft_k": 3}])
    @pytest.mark.parametrize("prefix_cache", [True, False])
    def test_tp2_bit_exact_vs_tp1(
        self, mparams, monkeypatch, impl, precision, spec, prefix_cache
    ):
        kw = dict(impl=impl, precision=precision, speculative=spec,
                  prefix_cache=prefix_cache)
        off = self._run(mparams, monkeypatch, tp=1, **kw)
        on = self._run(mparams, monkeypatch, tp=2, **kw)
        for a, b in zip(on, off):
            np.testing.assert_array_equal(a, b)


@pytest.mark.slow
class TestMultichipDryrunPromotion:
    """The MULTICHIP `paged_tp` dry-run as a real test: TP-on vs TP-off
    greedy token equality on whatever mesh the host exposes, DEGRADING
    to tp=1 on single-device hosts instead of skipping silently (the
    parity assert then pins the meshless path against itself — still a
    real decode, never a skip)."""

    def test_tp_on_vs_off_on_host_mesh(self):
        n_dev = len(jax.devices())
        tp = max(d for d in (4, 2, 1) if d <= n_dev)
        lm_cfg = dict(vocab_size=64, d_model=32, num_layers=1, num_heads=4,
                      max_len=32)
        lm_params = TransformerLM(dtype=jnp.float32, **lm_cfg).init(
            jax.random.key(0), jnp.zeros((1, 8), jnp.int32)
        )["params"]

        def build(tp_n, **kw):
            # tp passed EXPLICITLY (1 forces single-chip even with
            # SELDON_TPU_TP exported) — the off arm must really be off
            return PagedEngine(
                lm_params, dtype=jnp.float32, page_size=8, max_slots=2,
                steps_per_call=2, tp=tp_n,
                shard_min_weight_size=0, **lm_cfg, **kw,
            )

        prompts = [np.array([5, 9, 13], np.int32), np.array([1, 2], np.int32)]

        on = build(tp)
        assert on.tp_degree == tp  # strict: a degrade here is a failure
        outs_on = _serve(on, prompts, max_new=4)
        on.close()

        off = build(1)
        outs_off = _serve(off, prompts, max_new=4)
        off.close()

        for a, b in zip(outs_on, outs_off):
            np.testing.assert_array_equal(a, b)

        # the speculative verify lane on the same mesh stays bit-exact
        spec = build(tp, speculative={"draft_k": 2, "ngram": 2})
        spec_out = spec.generate(prompts[0], max_new_tokens=4)
        spec.close()
        np.testing.assert_array_equal(spec_out, outs_off[0])
