"""Ring attention + long-context transformer tests on the virtual
8-device mesh: the sequence-parallel path must match the single-device
oracle exactly (same math, different schedule)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from seldon_core_tpu.parallel import create_mesh
from seldon_core_tpu.parallel.ring_attention import (

    plain_attention,
    ring_attention,
    sequence_sharding,
)


pytestmark = pytest.mark.slow  # compile-heavy: excluded from the default fast tier (make test-all)


def qkv(batch=2, seq=16, heads=2, dim=8, seed=0):
    rng = np.random.default_rng(seed)
    shape = (batch, seq, heads, dim)
    return tuple(jnp.asarray(rng.normal(size=shape).astype(np.float32)) for _ in range(3))


class TestRingAttention:
    def test_matches_plain_full(self):
        mesh = create_mesh({"seq": 8})
        q, k, v = qkv()
        expected = plain_attention(q, k, v, causal=False)
        out = ring_attention(q, k, v, mesh=mesh, seq_axis="seq", causal=False)
        np.testing.assert_allclose(np.asarray(out), np.asarray(expected), rtol=2e-5, atol=2e-5)

    def test_matches_plain_causal(self):
        mesh = create_mesh({"seq": 8})
        q, k, v = qkv(seed=1)
        expected = plain_attention(q, k, v, causal=True)
        out = ring_attention(q, k, v, mesh=mesh, seq_axis="seq", causal=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(expected), rtol=2e-5, atol=2e-5)

    def test_ring_of_two(self):
        mesh = create_mesh({"seq": 2})
        q, k, v = qkv(seq=8, seed=2)
        expected = plain_attention(q, k, v, causal=True)
        out = ring_attention(q, k, v, mesh=mesh, seq_axis="seq", causal=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(expected), rtol=2e-5, atol=2e-5)

    def test_long_sequence_sharded_input(self):
        """Inputs arrive already sequence-sharded (the serving layout)."""
        mesh = create_mesh({"seq": 8})
        q, k, v = qkv(batch=1, seq=64, heads=2, dim=4, seed=3)
        sharding = sequence_sharding(mesh)
        qs, ks, vs = (jax.device_put(x, sharding) for x in (q, k, v))
        out = ring_attention(qs, ks, vs, mesh=mesh, causal=False)
        expected = plain_attention(q, k, v, causal=False)
        np.testing.assert_allclose(np.asarray(out), np.asarray(expected), rtol=2e-5, atol=2e-5)

    def test_gradients_flow(self):
        mesh = create_mesh({"seq": 4})
        q, k, v = qkv(seq=8, seed=4)

        def loss(q, k, v):
            return ring_attention(q, k, v, mesh=mesh, causal=True).sum()

        grads = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
        for g in grads:
            assert np.isfinite(np.asarray(g)).all()


class TestTransformer:
    def test_encoder_forward(self):
        from seldon_core_tpu.models.transformer import TransformerEncoder

        module = TransformerEncoder(
            num_classes=4, vocab_size=100, d_model=32, num_layers=2, num_heads=4,
            max_len=64, dtype=jnp.float32,
        )
        tokens = np.random.default_rng(0).integers(0, 100, size=(2, 16))
        variables = module.init(jax.random.key(0), tokens)
        out = module.apply(variables, tokens)
        assert out.shape == (2, 4)

    def test_lm_causal_property(self):
        """Changing a future token must not change past logits."""
        from seldon_core_tpu.models.transformer import TransformerLM

        module = TransformerLM(vocab_size=50, d_model=32, num_layers=2, num_heads=4,
                               max_len=32, dtype=jnp.float32)
        rng = np.random.default_rng(0)
        tokens = rng.integers(0, 50, size=(1, 8))
        variables = module.init(jax.random.key(0), tokens)
        out1 = module.apply(variables, tokens)
        tokens2 = tokens.copy()
        tokens2[0, -1] = (tokens2[0, -1] + 1) % 50
        out2 = module.apply(variables, tokens2)
        np.testing.assert_allclose(out1[0, :-1], out2[0, :-1], rtol=1e-5, atol=1e-5)
        assert not np.allclose(out1[0, -1], out2[0, -1])

    def test_ring_transformer_matches_plain(self):
        """Same weights, sequence-parallel attention == plain attention."""
        from seldon_core_tpu.models.transformer import TransformerEncoder, ring_attn_fn

        mesh = create_mesh({"seq": 8})
        tokens = np.random.default_rng(1).integers(0, 64, size=(2, 32))

        plain = TransformerEncoder(num_classes=3, vocab_size=64, d_model=32, num_layers=2,
                                   num_heads=4, max_len=64, dtype=jnp.float32)
        variables = plain.init(jax.random.key(0), tokens)
        expected = plain.apply(variables, tokens)

        ringed = TransformerEncoder(num_classes=3, vocab_size=64, d_model=32, num_layers=2,
                                    num_heads=4, max_len=64, dtype=jnp.float32,
                                    attn_fn=ring_attn_fn(mesh))
        out = ringed.apply(variables, tokens)
        np.testing.assert_allclose(np.asarray(out), np.asarray(expected), rtol=2e-4, atol=2e-4)

    def test_long_context_memory_scaling(self):
        """Ring path handles a sequence length where per-device KV is 1/8."""
        from seldon_core_tpu.models.transformer import TransformerEncoder, ring_attn_fn

        mesh = create_mesh({"seq": 8})
        module = TransformerEncoder(num_classes=2, vocab_size=64, d_model=16, num_layers=1,
                                    num_heads=2, max_len=1024, dtype=jnp.float32,
                                    attn_fn=ring_attn_fn(mesh))
        tokens = np.random.default_rng(2).integers(0, 64, size=(1, 1024))
        variables = module.init(jax.random.key(0), tokens[:, :8])
        out = module.apply(variables, tokens)
        assert out.shape == (1, 2)
        assert np.isfinite(np.asarray(out)).all()


class TestLongContextServing:
    def test_transformer_through_jaxserver(self):
        """Token-sequence model served via the standard component path."""
        from seldon_core_tpu.models.jaxserver import JaxServer
        from seldon_core_tpu.runtime import InternalMessage, dispatch

        server = JaxServer(
            model="transformer_encoder",
            num_classes=2,
            input_shape=(32,),
            dtype="float32",
            warmup_dtypes=("int32",),
            max_batch_size=4,
            warmup=False,
            model_kwargs={"vocab_size": 64, "d_model": 16, "num_layers": 1,
                          "num_heads": 2, "max_len": 32},
        )
        server.load()
        tokens = np.random.default_rng(0).integers(0, 64, size=(2, 32)).astype(np.int32)
        out = server.predict(tokens, [])
        assert out.shape == (2, 2)
        msg = InternalMessage(payload=tokens, kind="rawTensor")
        resp = dispatch.predict(server, msg)
        assert np.asarray(resp.payload).shape == (2, 2)
        server.unload()

    def test_longcontext_example_spec_validates(self):
        import os

        from seldon_core_tpu.controlplane import TpuDeployment, default_and_validate

        path = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                            "examples", "longcontext_transformer.yaml")
        default_and_validate(TpuDeployment.load(path))
