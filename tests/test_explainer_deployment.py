"""Explainer through the control plane + gateway /explanations route."""

import asyncio

import numpy as np

from seldon_core_tpu.controlplane import Deployer, TpuDeployment
from seldon_core_tpu.engine.server import build_gateway_app
from seldon_core_tpu.runtime.message import InternalMessage


def run(coro):
    return asyncio.run(coro)


SPEC = {
    "name": "explained",
    "predictors": [
        {
            "name": "main",
            "explainer": {"type": "integrated_gradients", "steps": 8},
            "graph": {
                "name": "clf",
                "type": "MODEL",
                "implementation": "JAX_SERVER",
                "parameters": [
                    {"name": "model", "value": "mlp", "type": "STRING"},
                    {"name": "num_classes", "value": "3", "type": "INT"},
                    {"name": "input_shape", "value": "[4]", "type": "JSON"},
                    {"name": "dtype", "value": "float32", "type": "STRING"},
                    {"name": "warmup", "value": "false", "type": "BOOL"},
                    {"name": "max_batch_size", "value": "4", "type": "INT"},
                ],
            },
        }
    ],
}


class TestExplainerDeployment:
    def test_explain_via_service(self):
        async def scenario():
            deployer = Deployer(device_ids=[0])
            managed = await deployer.apply(TpuDeployment.from_dict(SPEC))
            svc = managed.gateway.predictors[0]
            assert svc.explainer is not None
            out = await svc.explain(
                InternalMessage(payload=np.ones((1, 4), np.float32), kind="rawTensor",
                                names=["a", "b", "c", "d"])
            )
            await deployer.delete("explained")
            return out

        out = run(scenario())
        assert out.status["status"] == "SUCCESS"
        assert out.payload["method"] == "integrated_gradients"
        assert np.asarray(out.payload["attributions"]).shape == (1, 4)

    def test_explanations_rest_route(self):
        async def scenario():
            from aiohttp.test_utils import TestClient, TestServer

            deployer = Deployer(device_ids=[0])
            managed = await deployer.apply(TpuDeployment.from_dict(SPEC))
            app = build_gateway_app(managed.gateway)
            client = TestClient(TestServer(app))
            await client.start_server()
            resp = await client.post(
                "/api/v0.1/explanations",
                json={"data": {"names": ["a", "b", "c", "d"], "ndarray": [[1.0, 1.0, 1.0, 1.0]]}},
            )
            body = await resp.json()
            await client.close()
            await deployer.delete("explained")
            return resp.status, body

        status, body = run(scenario())
        assert status == 200
        assert body["jsonData"]["method"] == "integrated_gradients"

    def test_no_explainer_404(self):
        async def scenario():
            deployer = Deployer(device_ids=[0])
            spec = TpuDeployment.from_dict(
                {
                    "name": "plain",
                    "predictors": [
                        {"name": "p", "graph": {"name": "m", "type": "MODEL",
                                                "implementation": "SIMPLE_MODEL"}}
                    ],
                }
            )
            managed = await deployer.apply(spec)
            out = await managed.gateway.predictors[0].explain(
                InternalMessage(payload=np.ones((1, 2)), kind="tensor")
            )
            await deployer.delete("plain")
            return out

        out = run(scenario())
        assert out.status["status"] == "FAILURE"
        assert out.status["code"] == 404


class TestKernelShapDeployment:
    def test_kernel_shap_through_gateway_route(self):
        spec = {
            "name": "shap-explained",
            "predictors": [
                {
                    "name": "main",
                    "explainer": {"type": "kernel_shap", "n_samples": 64},
                    "graph": dict(SPEC["predictors"][0]["graph"]),
                }
            ],
        }

        async def scenario():
            from aiohttp.test_utils import TestClient, TestServer

            deployer = Deployer(device_ids=[0])
            managed = await deployer.apply(TpuDeployment.from_dict(spec))
            app = build_gateway_app(managed.gateway)
            client = TestClient(TestServer(app))
            await client.start_server()
            resp = await client.post(
                "/api/v0.1/explanations",
                json={"data": {"ndarray": [[1.0, -1.0, 0.5, 2.0]]}},
            )
            body = await resp.json()
            await client.close()
            await deployer.delete("shap-explained")
            return resp.status, body

        status, body = run(scenario())
        assert status == 200
        payload = body["jsonData"]
        assert payload["method"] == "kernel_shap"
        attrs = np.asarray(payload["attributions"])
        assert attrs.shape == (1, 4) and np.isfinite(attrs).all()


class TestAnchorsDeployment:
    def test_anchors_through_gateway_route(self):
        """{type: anchors} in the explainer block, end-to-end through
        the /explanations route (reference analogue: AnchorTabular in
        the alibi container, seldondeployment_explainers.go:57-59)."""
        bg = np.random.default_rng(7).uniform(0, 1, size=(256, 4))
        spec = {
            "name": "anchor-explained",
            "predictors": [
                {
                    "name": "main",
                    "explainer": {
                        "type": "anchors",
                        "n_bins": 4,
                        "n_samples": 64,
                        "background": bg.tolist(),
                    },
                    "graph": dict(SPEC["predictors"][0]["graph"]),
                }
            ],
        }

        async def scenario():
            from aiohttp.test_utils import TestClient, TestServer

            deployer = Deployer(device_ids=[0])
            managed = await deployer.apply(TpuDeployment.from_dict(spec))
            app = build_gateway_app(managed.gateway)
            client = TestClient(TestServer(app))
            await client.start_server()
            resp = await client.post(
                "/api/v0.1/explanations",
                json={"data": {"ndarray": [[0.9, 0.1, 0.5, 0.7]]}},
            )
            body = await resp.json()
            await client.close()
            await deployer.delete("anchor-explained")
            return resp.status, body

        status, body = run(scenario())
        assert status == 200
        payload = body["jsonData"]
        assert payload["method"] == "anchors"
        a = payload["anchors"][0]
        # the anchor is a rule over the 4 features with a measured
        # precision/coverage — contents depend on the mlp's random
        # weights; the contract (shape + fields) is what this asserts
        assert set(a) >= {"features", "predicates", "precision",
                          "coverage", "met_threshold", "target"}
        assert all(0 <= j < 4 for j in a["features"])
