"""WeightRegistry (r16): HBM-budgeted hot-load/unload of named weight
sets — refcount pins, LRU reclaim of cached sets, budget pressure as a
clean 503, and the capacity accounting the engine's adapter pool prices
into paged_hbm_accounting.

Host-side only: entries here are plain numpy trees, no engine and no
device work — the engine-coupled paths live in tests/test_lora.py.
"""

import threading

import numpy as np
import pytest

from seldon_core_tpu.models.registry import WeightRegistry
from seldon_core_tpu.runtime.component import MicroserviceError


def _set(n_bytes: int):
    """A loader producing a weight set of exactly ``n_bytes``."""
    def loader():
        return {"w": np.zeros((n_bytes // 4,), np.float32)}

    return loader


class TestResidencyLifecycle:
    def test_loader_runs_once_and_hits_after(self):
        reg = WeightRegistry(budget_bytes=0)
        calls = []

        def loader():
            calls.append(1)
            return {"w": np.ones((4,), np.float32)}

        reg.register("a", loader)
        v1 = reg.acquire("a")
        v2 = reg.acquire("a")
        assert v1 is v2 and len(calls) == 1
        s = reg.stats()
        assert s["loads"] == 1 and s["hits"] == 1 and s["misses"] == 1
        reg.release("a")
        reg.release("a")
        # refcount 0: still materialised (cached), re-acquire is a hit
        assert reg.acquire("a") is v1
        assert reg.stats()["hits"] == 2

    def test_unknown_name_is_404(self):
        reg = WeightRegistry()
        with pytest.raises(MicroserviceError) as e:
            reg.acquire("ghost")
        assert e.value.reason == "WEIGHTS_UNKNOWN"
        assert e.value.status_code == 404

    def test_release_last_pin_parks_on_lru_not_freed(self):
        reg = WeightRegistry(budget_bytes=0)
        reg.register("a", _set(1024))
        reg.acquire("a")
        reg.release("a")
        s = reg.stats()
        entry = s["entries"][0]
        assert entry["resident"] and not entry["pinned"]
        assert s["reclaimable_weight_bytes"] == 1024
        assert s["resident_bytes"] == 0  # pinned bytes only

    def test_unregister_refuses_pinned(self):
        reg = WeightRegistry()
        reg.register("a", _set(64))
        reg.acquire("a")
        with pytest.raises(MicroserviceError) as e:
            reg.unregister("a")
        assert e.value.reason == "WEIGHTS_IN_USE"
        reg.release("a")
        reg.unregister("a")
        assert not reg.known("a")


class TestBudgetPressure:
    def test_lru_evicts_cached_oldest_first(self):
        reg = WeightRegistry(budget_bytes=2048)
        for name in ("a", "b", "c"):
            reg.register(name, _set(1024), bytes_hint=1024)
        reg.acquire("a"); reg.release("a")
        reg.acquire("b"); reg.release("b")
        reg.acquire("c")  # must evict "a" (oldest cached)
        names = {
            e["name"]: e for e in reg.stats()["entries"]
        }
        assert not names["a"]["resident"]
        assert names["b"]["resident"] and names["c"]["resident"]
        assert reg.stats()["evictions"] == 1
        # "a" re-acquires by re-loading (a second load, not a failure)
        reg.release("c")
        reg.acquire("a")
        assert reg.stats()["loads"] == 4

    def test_all_pinned_budget_exhaustion_is_503(self):
        reg = WeightRegistry(budget_bytes=2048)
        reg.register("a", _set(1024), bytes_hint=1024)
        reg.register("b", _set(1024), bytes_hint=1024)
        reg.register("c", _set(1024), bytes_hint=1024)
        reg.acquire("a")
        reg.acquire("b")
        with pytest.raises(MicroserviceError) as e:
            reg.acquire("c")
        assert e.value.reason == "WEIGHTS_BUDGET"
        assert e.value.status_code == 503
        # releasing a pin unblocks the load
        reg.release("b")
        reg.acquire("c")

    def test_unhinted_load_sizes_post_hoc_and_reclaims(self):
        reg = WeightRegistry(budget_bytes=2048)
        reg.register("a", _set(1024))
        reg.register("b", _set(1024))
        reg.register("c", _set(1024))
        reg.acquire("a"); reg.release("a")
        reg.acquire("b"); reg.release("b")
        reg.acquire("c")  # no hint: loads, then evicts "a" post-hoc
        names = {e["name"]: e for e in reg.stats()["entries"]}
        assert not names["a"]["resident"] and names["c"]["resident"]

    def test_unhinted_overbudget_pinned_rolls_back(self):
        reg = WeightRegistry(budget_bytes=512)
        reg.register("big", _set(1024))
        with pytest.raises(MicroserviceError) as e:
            reg.acquire("big")
        assert e.value.reason == "WEIGHTS_BUDGET"
        entry = reg.stats()["entries"][0]
        assert not entry["resident"] and entry["refcount"] == 0

    def test_zero_budget_never_evicts_or_fails(self):
        reg = WeightRegistry(budget_bytes=0)
        for i in range(8):
            reg.register(f"s{i}", _set(1 << 20))
            reg.acquire(f"s{i}")
            reg.release(f"s{i}")
        assert reg.stats()["evictions"] == 0
        assert all(e["resident"] for e in reg.stats()["entries"])


class TestConcurrency:
    def test_concurrent_acquire_release_stays_consistent(self):
        reg = WeightRegistry(budget_bytes=8 * 1024)
        for i in range(6):
            reg.register(f"s{i}", _set(1024), bytes_hint=1024)
        errors = []

        def worker(seed):
            rng = np.random.default_rng(seed)
            for _ in range(50):
                name = f"s{int(rng.integers(6))}"
                try:
                    reg.acquire(name)
                    reg.release(name)
                except MicroserviceError:
                    pass  # transient budget pressure is a valid outcome
                except Exception as exc:  # noqa: BLE001 — the assertion target
                    errors.append(exc)

        threads = [threading.Thread(target=worker, args=(i,)) for i in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errors
        s = reg.stats()
        assert all(e["refcount"] == 0 for e in s["entries"])
        if reg.budget_bytes:
            assert (
                s["resident_bytes"] + s["reclaimable_weight_bytes"]
                <= reg.budget_bytes
            )


class TestCapacityAccounting:
    def test_adapter_bytes_price_into_peak_and_capacity(self):
        from seldon_core_tpu.models.paged import (
            paged_capacity_streams,
            paged_hbm_accounting,
        )

        kw = dict(ctx_len=512, d_model=256, num_layers=4)
        plain = paged_hbm_accounting(streams=4, **kw)
        pool = paged_hbm_accounting(
            streams=4, adapter_bytes=1 << 20,
            reclaimable_weight_bytes=1 << 18, **kw
        )
        assert pool["peak_bytes"] == plain["peak_bytes"] + (1 << 20)
        assert pool["adapter_bytes"] == 1 << 20
        # reclaimable weights report next to reclaimable pages, never
        # against peak
        assert (
            pool["reclaimable_bytes"]
            == plain["reclaimable_bytes"] + (1 << 18)
        )
        budget = 1 << 30
        base_cap = paged_capacity_streams(budget, 512, d_model=256, num_layers=4)
        ad_cap = paged_capacity_streams(
            budget, 512, d_model=256, num_layers=4,
            adapter_bytes=budget // 2,
        )
        # the factor pool reserves off the top BEFORE the division
        assert ad_cap <= base_cap // 2 + 1

    def test_lora_pool_bytes_match_shardings(self):
        from seldon_core_tpu.ops.lora import LoraPool

        pool = LoraPool(num_layers=2, d_model=64, max_adapters=3, rank=4)
        full = pool.hbm_bytes(1)
        half = pool.hbm_bytes(2)
        # per target only ONE factor shards (the other replicates), so
        # the per-shard bytes sit strictly between full/2 and full
        assert full / 2 < half < full
