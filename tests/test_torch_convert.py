"""torch -> flax checkpoint conversion.

Exactness criterion: flax-init params, inverse-transformed into a
synthetic torchvision-style state_dict, must convert back to the
identical tree leaf-for-leaf — proving name mapping and layout
transposes are mutually inverse.  A forward pass on the converted tree
proves it is actually servable.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from seldon_core_tpu.utils.torch_convert import (

    convert_torch_resnet,
    resnet_layout,
)


pytestmark = pytest.mark.slow  # compile-heavy: excluded from the default fast tier (make test-all)


def _flatten(tree, prefix=()):
    out = {}
    for k, v in tree.items():
        if isinstance(v, dict):
            out.update(_flatten(v, prefix + (k,)))
        else:
            out[prefix + (k,)] = np.asarray(v)
    return out


def _to_torch_names(variables, arch):
    """Inverse of the converter: flax tree -> torchvision names."""
    stage_sizes, kind = resnet_layout(arch)
    block_name = "BottleneckBlock" if kind == "bottleneck" else "BasicBlock"
    convs = 3 if kind == "bottleneck" else 2
    sd = {}
    params, stats = variables["params"], variables["batch_stats"]

    def put_bn(tp, node, snode):
        sd[f"{tp}.weight"] = np.asarray(node["scale"])
        sd[f"{tp}.bias"] = np.asarray(node["bias"])
        sd[f"{tp}.running_mean"] = np.asarray(snode["mean"])
        sd[f"{tp}.running_var"] = np.asarray(snode["var"])

    sd["conv1.weight"] = np.transpose(params["conv_init"]["kernel"], (3, 2, 0, 1))
    put_bn("bn1", params["bn_init"], stats["bn_init"])
    b = 0
    for stage, size in enumerate(stage_sizes, start=1):
        for j in range(size):
            fb = f"{block_name}_{b}"
            for c in range(convs):
                sd[f"layer{stage}.{j}.conv{c+1}.weight"] = np.transpose(
                    params[fb][f"Conv_{c}"]["kernel"], (3, 2, 0, 1)
                )
                put_bn(f"layer{stage}.{j}.bn{c+1}", params[fb][f"BatchNorm_{c}"],
                       stats[fb][f"BatchNorm_{c}"])
            if "shortcut_conv" in params[fb]:
                sd[f"layer{stage}.{j}.downsample.0.weight"] = np.transpose(
                    params[fb]["shortcut_conv"]["kernel"], (3, 2, 0, 1)
                )
                put_bn(f"layer{stage}.{j}.downsample.1", params[fb]["shortcut_bn"],
                       stats[fb]["shortcut_bn"])
            b += 1
    sd["fc.weight"] = np.transpose(params["head"]["kernel"], (1, 0))
    sd["fc.bias"] = np.asarray(params["head"]["bias"])
    return sd


@pytest.mark.parametrize("arch,cls_name", [("resnet18", "ResNet18"), ("resnet50", "ResNet50")])
def test_roundtrip_exact_and_servable(arch, cls_name):
    from seldon_core_tpu.models import resnet as resnet_mod

    module = getattr(resnet_mod, cls_name)(num_classes=16, dtype=jnp.float32)
    variables = module.init(jax.random.key(0), jnp.zeros((1, 64, 64, 3)))
    flax_vars = {
        "params": jax.tree_util.tree_map(np.asarray, variables["params"]),
        "batch_stats": jax.tree_util.tree_map(np.asarray, variables["batch_stats"]),
    }
    sd = _to_torch_names(flax_vars, arch)
    converted = convert_torch_resnet(sd, arch=arch)

    want = _flatten(flax_vars)
    got = _flatten(converted)
    assert set(got) == set(want)
    for key in want:
        np.testing.assert_array_equal(got[key], want[key], err_msg=str(key))

    # the converted tree actually serves
    logits = module.apply(
        {"params": converted["params"], "batch_stats": converted["batch_stats"]},
        jnp.ones((2, 64, 64, 3)),
    )
    assert logits.shape == (2, 16)
    assert np.isfinite(np.asarray(logits)).all()


def test_missing_key_reports_name():
    sd = {"conv1.weight": np.zeros((64, 3, 7, 7))}
    with pytest.raises(KeyError, match="bn1.weight"):
        convert_torch_resnet(sd, arch="resnet50")


def test_leftover_keys_rejected():
    from seldon_core_tpu.models import resnet as resnet_mod

    module = resnet_mod.ResNet18(num_classes=4, dtype=jnp.float32)
    variables = module.init(jax.random.key(0), jnp.zeros((1, 32, 32, 3)))
    flax_vars = {
        "params": jax.tree_util.tree_map(np.asarray, variables["params"]),
        "batch_stats": jax.tree_util.tree_map(np.asarray, variables["batch_stats"]),
    }
    sd = _to_torch_names(flax_vars, "resnet18")
    sd["some.stray.tensor"] = np.zeros(3)
    with pytest.raises(ValueError, match="unconverted"):
        convert_torch_resnet(sd, arch="resnet18")


def test_torch_file_to_msgpack(tmp_path):
    torch = pytest.importorskip("torch")
    from flax import serialization  # noqa: F401

    from seldon_core_tpu.models import resnet as resnet_mod
    from seldon_core_tpu.utils.torch_convert import convert_checkpoint

    module = resnet_mod.ResNet18(num_classes=4, dtype=jnp.float32)
    variables = module.init(jax.random.key(0), jnp.zeros((1, 32, 32, 3)))
    flax_vars = {
        "params": jax.tree_util.tree_map(np.asarray, variables["params"]),
        "batch_stats": jax.tree_util.tree_map(np.asarray, variables["batch_stats"]),
    }
    sd = {k: torch.from_numpy(v.copy()) for k, v in _to_torch_names(flax_vars, "resnet18").items()}
    pt = tmp_path / "resnet18.pt"
    torch.save(sd, pt)
    out = tmp_path / "resnet18.msgpack"
    converted = convert_checkpoint(str(pt), str(out), arch="resnet18")
    assert out.exists() and out.stat().st_size > 1000
    np.testing.assert_array_equal(
        converted["params"]["head"]["bias"], flax_vars["params"]["head"]["bias"]
    )


def _torchvision_resnet18_keys():
    """The literal torchvision resnet18 state_dict key list (written
    from torchvision's documented naming, independent of the converter,
    so a shared naming error cannot cancel out)."""
    keys = ["conv1.weight"]
    keys += [f"bn1.{s}" for s in ("weight", "bias", "running_mean", "running_var", "num_batches_tracked")]
    downsampled = {("layer2", 0), ("layer3", 0), ("layer4", 0)}
    for layer, blocks in (("layer1", 2), ("layer2", 2), ("layer3", 2), ("layer4", 2)):
        for j in range(blocks):
            for c in (1, 2):
                keys.append(f"{layer}.{j}.conv{c}.weight")
                keys += [
                    f"{layer}.{j}.bn{c}.{s}"
                    for s in ("weight", "bias", "running_mean", "running_var", "num_batches_tracked")
                ]
            if (layer, j) in downsampled:
                keys.append(f"{layer}.{j}.downsample.0.weight")
                keys += [
                    f"{layer}.{j}.downsample.1.{s}"
                    for s in ("weight", "bias", "running_mean", "running_var", "num_batches_tracked")
                ]
    keys += ["fc.weight", "fc.bias"]
    return keys


def test_converter_consumes_exact_torchvision_key_set():
    """The converter's expected names ARE torchvision's names: feeding
    the literal torchvision resnet18 key list (with correct shapes)
    converts with nothing missing and nothing left over."""
    from seldon_core_tpu.models import resnet as resnet_mod

    module = resnet_mod.ResNet18(num_classes=1000, dtype=jnp.float32)
    variables = module.init(jax.random.key(0), jnp.zeros((1, 64, 64, 3)))
    shaped = _to_torch_names(
        {
            "params": jax.tree_util.tree_map(np.asarray, variables["params"]),
            "batch_stats": jax.tree_util.tree_map(np.asarray, variables["batch_stats"]),
        },
        "resnet18",
    )
    fixture_keys = _torchvision_resnet18_keys()
    # shape source: the flax-derived dict; key list: the literal fixture
    sd = {}
    for key in fixture_keys:
        if key.endswith("num_batches_tracked"):
            sd[key] = np.zeros((), np.int64)
        else:
            assert key in shaped, f"fixture key {key} not produced by inverse map"
            sd[key] = shaped[key]
    assert set(k for k in shaped) == set(
        k for k in fixture_keys if not k.endswith("num_batches_tracked")
    )
    converted = convert_torch_resnet(sd, arch="resnet18")
    assert "conv_init" in converted["params"]


def test_lightning_prefix_stripped(tmp_path):
    torch = pytest.importorskip("torch")

    from seldon_core_tpu.utils.torch_convert import load_torch_state_dict

    sd = {"model.conv1.weight": torch.zeros(2, 2), "model.fc.bias": torch.zeros(2)}
    path = tmp_path / "lightning.ckpt"
    torch.save({"state_dict": sd}, path)
    loaded = load_torch_state_dict(str(path))
    assert set(loaded) == {"conv1.weight", "fc.bias"}
