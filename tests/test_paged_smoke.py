"""Fast-tier smoke for the paged generation lane.

The full paged suite (`test_paged.py`, ~450 s of compiles) carries
@slow; this file keeps the marquee lane covered in the DEFAULT tier —
one tiny engine, submit → run → exact shape/termination contract —
so a fast-tier-only CI run still catches a broken decode path.
"""

import numpy as np

import jax.numpy as jnp


def test_tiny_engine_decodes_and_reuses_slots():
    import jax

    from seldon_core_tpu.models.paged import PagedEngine
    from seldon_core_tpu.models.transformer import TransformerLM

    cfg = dict(vocab_size=64, d_model=32, num_layers=1, num_heads=2, max_len=128)
    lm = TransformerLM(dtype=jnp.float32, **cfg)
    params = lm.init(jax.random.key(0), jnp.zeros((1, 4), jnp.int32))["params"]
    eng = PagedEngine(
        params, dtype=jnp.float32, page_size=8, max_slots=2,
        steps_per_call=4, **cfg,
    )
    try:
        prompts = [
            np.arange(5, dtype=np.int32) % 64,
            (np.arange(9, dtype=np.int32) * 3) % 64,
            np.ones(3, np.int32),
        ]
        streams = [eng.submit(p, max_new_tokens=6) for p in prompts]
        eng.run()
        for s in streams:
            assert s.error is None
            out = np.asarray(s.result)
            assert out.shape == (6,)
            assert ((out >= 0) & (out < 64)).all()
        # determinism: same prompt, same seed -> same tokens
        again = eng.submit(prompts[0], max_new_tokens=6)
        eng.run()
        np.testing.assert_array_equal(np.asarray(again.result),
                                      np.asarray(streams[0].result))
    finally:
        eng.close()
