"""MAB routers, outlier detectors, and the feedback learning loop
end-to-end through the engine (reference: components/routers tests +
the engine feedback call stack, SURVEY §3.3)."""

import asyncio

import numpy as np
import pytest

import seldon_core_tpu.components  # noqa: F401 — registers implementations
from seldon_core_tpu.components.outliers import MahalanobisDetector
from seldon_core_tpu.components.routers import EpsilonGreedy, ThompsonSampling
from seldon_core_tpu.engine import GraphExecutor, UnitSpec
from seldon_core_tpu.runtime import InternalFeedback, InternalMessage, TPUComponent
from seldon_core_tpu.utils.persistence import PersistenceManager


def run(coro):
    return asyncio.run(coro)


def msg(arr):
    return InternalMessage(payload=np.asarray(arr, dtype=np.float64), kind="tensor")


class TestEpsilonGreedy:
    def test_learns_best_branch(self):
        mab = EpsilonGreedy(n_branches=3, epsilon=0.1, seed=0)
        # branch 1 pays best
        pay = [0.2, 0.9, 0.4]
        rng = np.random.default_rng(0)
        for _ in range(300):
            b = mab.route(None, [])
            reward = float(rng.random() < pay[b])
            mab.send_feedback(None, [], reward, None, routing=b)
        values = mab.branch_values()
        assert int(np.argmax(values)) == 1
        # exploit mode picks branch 1 overwhelmingly
        picks = [mab.route(None, []) for _ in range(200)]
        assert picks.count(1) > 150

    def test_optimistic_exploration(self):
        mab = EpsilonGreedy(n_branches=2, epsilon=0.0, seed=0)
        first = mab.route(None, [])
        mab.send_feedback(None, [], 1.0, None, routing=first)
        # unexplored branch has infinite optimistic value -> tried next
        assert mab.route(None, []) != first

    def test_epsilon_decay(self):
        mab = EpsilonGreedy(n_branches=2, epsilon=0.5, decay=0.5, seed=0)
        mab.send_feedback(None, [], 1.0, None, routing=0)
        mab.send_feedback(None, [], 1.0, None, routing=0)
        assert mab.epsilon == pytest.approx(0.125)

    def test_checkpoint_roundtrip(self, tmp_path):
        mab = EpsilonGreedy(n_branches=2, seed=0)
        for _ in range(10):
            mab.send_feedback(None, [], 1.0, None, routing=1)
        manager = PersistenceManager(str(tmp_path), "mab")
        assert manager.save(mab)

        fresh = EpsilonGreedy(n_branches=2, seed=0)
        assert manager.restore(fresh)
        np.testing.assert_array_equal(fresh.counts, mab.counts)
        np.testing.assert_array_equal(fresh.reward_sums, mab.reward_sums)


class TestThompsonSampling:
    def test_converges_to_best(self):
        ts = ThompsonSampling(n_branches=2, seed=1)
        rng = np.random.default_rng(1)
        pay = [0.3, 0.8]
        for _ in range(400):
            b = ts.route(None, [])
            ts.send_feedback(None, [], float(rng.random() < pay[b]), None, routing=b)
        picks = [ts.route(None, []) for _ in range(100)]
        assert picks.count(1) > 80

    def test_checkpoint_roundtrip(self):
        ts = ThompsonSampling(n_branches=2, seed=0)
        ts.send_feedback(None, [], 1.0, None, routing=0)
        state = ts.checkpoint_state()
        fresh = ThompsonSampling(n_branches=2, seed=0)
        fresh.restore_state(state)
        np.testing.assert_array_equal(fresh.alpha, ts.alpha)


class TestMabThroughEngine:
    def test_full_feedback_loop(self):
        """MAB router in a live graph: predict -> feedback -> learn.
        The reference's bandit demo (seldon-mab chart) as a unit test."""

        class PayingModel(TPUComponent):
            def __init__(self, value):
                self.value = value

            def predict(self, X, names, meta=None):
                return np.array([[self.value]])

        mab = EpsilonGreedy(n_branches=2, epsilon=0.2, seed=3)
        g = UnitSpec(
            name="mab",
            type="ROUTER",
            component=mab,
            children=[
                UnitSpec(name="bad", type="MODEL", component=PayingModel(0.1)),
                UnitSpec(name="good", type="MODEL", component=PayingModel(0.9)),
            ],
        )
        ex = GraphExecutor(g)

        async def loop():
            rng = np.random.default_rng(4)
            for _ in range(150):
                resp = await ex.predict(msg([[1.0]]))
                value = float(np.asarray(resp.payload).ravel()[0])
                reward = float(rng.random() < value)
                fb = InternalFeedback(request=msg([[1.0]]), response=resp, reward=reward)
                await ex.send_feedback(fb)
            # after learning, most traffic goes to the good branch
            routes = []
            for _ in range(60):
                resp = await ex.predict(msg([[1.0]]))
                routes.append(resp.meta.routing["mab"])
            return routes

        routes = run(loop())
        assert routes.count(1) > 40

    def test_declarative_mab_graph(self):
        g = UnitSpec.from_dict(
            {
                "name": "mab",
                "type": "ROUTER",
                "implementation": "EPSILON_GREEDY",
                "parameters": [
                    {"name": "n_branches", "value": "2", "type": "INT"},
                    {"name": "epsilon", "value": "0.3", "type": "FLOAT"},
                ],
                "children": [
                    {"name": "a", "type": "MODEL", "implementation": "SIMPLE_MODEL"},
                    {"name": "b", "type": "MODEL", "implementation": "SIMPLE_MODEL"},
                ],
            }
        )
        ex = GraphExecutor(g)
        out = run(ex.predict(msg([[1.0]])))
        assert out.meta.routing["mab"] in (0, 1)


class TestMahalanobis:
    def test_scores_flag_outliers(self):
        det = MahalanobisDetector(threshold=25.0, min_samples=20)
        rng = np.random.default_rng(0)
        normal = rng.normal(size=(200, 3))
        det.score(normal)
        outlier_scores = det.score(np.array([[50.0, 50.0, 50.0]]))
        assert outlier_scores[0] > 25.0
        assert det.tags()["outlier"] is True

    def test_normal_data_not_flagged(self):
        det = MahalanobisDetector(threshold=25.0, min_samples=20)
        rng = np.random.default_rng(0)
        det.score(rng.normal(size=(200, 3)))
        det.score(rng.normal(size=(5, 3)))
        assert det.tags()["outlier"] is False

    def test_as_transformer_in_graph(self):
        class Echo(TPUComponent):
            def predict(self, X, names, meta=None):
                return X

        det = MahalanobisDetector(threshold=25.0, min_samples=5)
        rng = np.random.default_rng(0)
        det.score(rng.normal(size=(100, 2)))

        g = UnitSpec(
            name="od",
            type="TRANSFORMER",
            component=det,
            children=[UnitSpec(name="m", type="MODEL", component=Echo())],
        )
        ex = GraphExecutor(g)
        out = run(ex.predict(msg([[99.0, 99.0]])))
        np.testing.assert_array_equal(out.payload, [[99.0, 99.0]])  # pass-through
        assert out.meta.tags["outlier"] is True
        assert any(m["key"] == "outliers_total" for m in out.meta.metrics)

    def test_checkpoint_roundtrip(self):
        det = MahalanobisDetector()
        rng = np.random.default_rng(0)
        det.score(rng.normal(size=(50, 2)))
        state = det.checkpoint_state()
        fresh = MahalanobisDetector()
        fresh.restore_state(state)
        assert fresh.n == det.n
        np.testing.assert_allclose(fresh.mean, det.mean)


class TestVAEOutlier:
    def test_fit_and_detect(self, tmp_path):
        from seldon_core_tpu.components.outliers import VAEOutlierDetector

        rng = np.random.default_rng(0)
        normal = rng.normal(size=(256, 4)).astype(np.float32) * 0.1
        det = VAEOutlierDetector(latent_dim=2, hidden_dim=16, seed=0)
        losses = det.fit(normal, epochs=100)
        assert losses[-1] < losses[0]  # training converges

        normal_scores = det.score(normal[:16])
        outlier_scores = det.score(np.full((4, 4), 8.0, np.float32))
        assert outlier_scores.mean() > normal_scores.mean() * 10
        det.threshold = float(normal_scores.max() * 5)
        det.score(np.full((1, 4), 8.0, np.float32))
        assert det.tags()["outlier"] is True

        # save -> reload -> same scores
        path = str(tmp_path / "vae.msgpack")
        det.save(path)
        fresh = VAEOutlierDetector(n_features=4, latent_dim=2, hidden_dim=16,
                                   model_uri=path, seed=0)
        fresh.load()
        np.testing.assert_allclose(
            fresh.score(normal[:8]), det.score(normal[:8]), rtol=1e-5
        )

    def test_registered(self):
        import seldon_core_tpu.components  # noqa: F401
        from seldon_core_tpu.engine.units import BUILTIN_IMPLEMENTATIONS

        assert "OUTLIER_VAE" in BUILTIN_IMPLEMENTATIONS


class TestIsolationForest:
    """Reference parity: isolation-forest detector
    (components/outlier-detection/isolation-forest/CoreIsolationForest.py),
    re-designed with packed trees + jitted level-synchronous traversal."""

    def _fitted(self, threshold=0.6):
        from seldon_core_tpu.components.outliers import IsolationForestDetector

        rng = np.random.default_rng(0)
        normal = rng.normal(size=(512, 3)).astype(np.float32)
        det = IsolationForestDetector(n_trees=50, subsample=128, threshold=threshold, seed=1)
        det.fit(normal)
        return det, normal

    def test_outliers_score_higher_and_flag(self):
        det, normal = self._fitted()
        inlier = det.score(normal[:32])
        outlier = det.score(np.full((4, 3), 12.0, np.float32))
        assert outlier.min() > inlier.mean() + 0.15
        assert det.tags()["outlier"] is True
        assert det.tags()["outlier_count"] == 4

    def test_normal_data_not_flagged(self):
        det, normal = self._fitted()
        scores = det.score(normal[:64])
        assert (scores < 0.6).mean() > 0.9
        assert det.tags()["outlier_count"] <= 3

    def test_dual_use_transformer(self):
        det, normal = self._fitted()
        X = normal[:8]
        out = det.transform_input(X, [])
        np.testing.assert_array_equal(out, X)
        assert any(m["key"] == "outlier_score_max" for m in det.metrics())

    def test_explicit_state_roundtrip(self):
        from seldon_core_tpu.components.outliers import IsolationForestDetector

        det, normal = self._fitted()
        state = det.checkpoint_state()
        assert state is not None and "features" in state  # pickle-free
        clone = IsolationForestDetector()
        clone.restore_state(state)
        probe = np.concatenate([normal[:8], np.full((2, 3), 9.0, np.float32)])
        np.testing.assert_allclose(clone.score(probe), det.score(probe), rtol=1e-5)

    def test_unfitted_rejects(self):
        from seldon_core_tpu.components.outliers import IsolationForestDetector

        with pytest.raises(RuntimeError):
            IsolationForestDetector().score(np.zeros((1, 2)))


class TestSeq2SeqOutlier:
    """Reference parity: seq2seq-LSTM detector
    (components/outlier-detection/seq2seq-lstm/CoreSeq2SeqLSTM.py), as a
    flax LSTM encoder-decoder scored in one XLA program."""

    def _waves(self, n, t=24, rng=None):
        rng = rng or np.random.default_rng(0)
        phase = rng.uniform(0, 2 * np.pi, size=(n, 1))
        steps = np.linspace(0, 4 * np.pi, t)[None, :]
        return (np.sin(steps + phase) * 0.5 + 0.5).astype(np.float32)

    def test_fit_and_detect_anomalous_sequences(self, tmp_path):
        from seldon_core_tpu.components.outliers import Seq2SeqOutlierDetector

        det = Seq2SeqOutlierDetector(hidden_dim=16, seed=0)
        losses = det.fit(self._waves(64), epochs=200, learning_rate=5e-3)
        assert losses[-1] < losses[0]

        normal_scores = det.score(self._waves(8, rng=np.random.default_rng(7)))
        noise = np.random.default_rng(3).uniform(size=(8, 24)).astype(np.float32)
        noise_scores = det.score(noise)
        assert noise_scores.mean() > normal_scores.mean() * 2

        # threshold between the two -> flags exactly the anomalies
        det.threshold = float((normal_scores.mean() + noise_scores.mean()) / 2)
        det.score(noise)
        assert det.tags()["outlier"] is True
        det.score(self._waves(8, rng=np.random.default_rng(11)))
        assert det.tags()["outlier_count"] <= 1

        # params round-trip through flax serialization + model_uri
        path = tmp_path / "seq2seq.msgpack"
        det.save(str(path))
        clone = Seq2SeqOutlierDetector(n_features=1, hidden_dim=16, model_uri=str(path))
        clone.load()
        np.testing.assert_allclose(clone.score(noise), noise_scores, rtol=1e-5)

    def test_multifeature_and_3d_input(self):
        from seldon_core_tpu.components.outliers import Seq2SeqOutlierDetector

        rng = np.random.default_rng(0)
        seqs = rng.normal(size=(16, 10, 3)).astype(np.float32) * 0.1
        det = Seq2SeqOutlierDetector(hidden_dim=8, seed=0)
        det.fit(seqs, epochs=3)
        scores = det.predict(seqs[:4], [])
        assert scores.shape == (4, 1)
