"""2-D (data x model) serving mesh + sequence-sharded long-context
paged decode (r19).

Correctness bar, same discipline as the r11 TP round: greedy decode is
BIT-EXACT (dp=2, tp=2) vs tp-only vs single-chip in the f32 exactness
regime.  The page-dim sharding of the KV pool is exact by construction
— the per-step gather reads one page's rows, so each data shard
contributes either the real rows or zeros and the all-reduce sums one
nonzero term — and the tests pin that, not approximate it.

The no-regression bar: ``dp=1`` resolves through the EXACT
:func:`tp_mesh` path, so the 1-D ``{model: N}`` lowering and the
``mesh=None`` single-chip lowering are byte-identical to the r11
programs (lowering-text asserted below).

Fast tier: resolve_dp/resolve_mesh precedence + degrade order,
create_mesh/mesh_shape round-trips, shard_decode_state page-dim
coverage, dp=1 byte-identity, one (2,2) parity smoke, accounting and
the ring-attention-over-``data`` oracle (conftest forces 8 CPU host
devices, so (2,2) runs everywhere).  The full (2,2) parity matrix and
the scaled long-context admit/decode point are @slow.
"""

import logging

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from seldon_core_tpu.models.paged import (
    PagedEngine,
    StreamingLM,
    paged_hbm_accounting,
    paged_max_context,
)
from seldon_core_tpu.models.transformer import TransformerLM
from seldon_core_tpu.parallel.mesh import (
    create_mesh,
    mesh_shape,
    resolve_dp,
    resolve_mesh,
    tp_mesh,
)
from seldon_core_tpu.parallel.sharding import shard_decode_state

CFG = dict(vocab_size=64, d_model=32, num_layers=1, num_heads=4, max_len=64)


@pytest.fixture(scope="module")
def params():
    lm = TransformerLM(dtype=jnp.float32, **CFG)
    return lm.init(jax.random.key(0), jnp.zeros((1, 8), jnp.int32))["params"]


def _engine(params, **kw):
    base = dict(dtype=jnp.float32, page_size=8, max_slots=2, steps_per_call=4)
    base.update(kw)
    return PagedEngine(params, **CFG, **base)


def _prompts(n=2, seed=3):
    rng = np.random.default_rng(seed)
    return [
        rng.integers(0, CFG["vocab_size"], size=(5 + 3 * i,)).astype(np.int32)
        for i in range(n)
    ]


def _serve(eng, prompts, max_new=6):
    streams = [eng.submit(p, max_new_tokens=max_new) for p in prompts]
    eng.run()
    for s in streams:
        assert s.error is None, s.error
    return [s.result for s in streams]


class TestDpKnob:
    """resolve_dp: resolve_tp's twin over SELDON_TPU_DP."""

    def test_explicit_beats_env(self, monkeypatch):
        monkeypatch.setenv("SELDON_TPU_DP", "4")
        assert resolve_dp(2) == 2
        # an explicit 1 FORCES one replica group over the env
        assert resolve_dp(1) == 1

    def test_env_fallback_and_default_off(self, monkeypatch):
        monkeypatch.setenv("SELDON_TPU_DP", "2")
        assert resolve_dp(None) == 2
        assert resolve_dp(0) == 2
        monkeypatch.delenv("SELDON_TPU_DP")
        assert resolve_dp(None) == 1

    def test_env_zero_spells_off(self, monkeypatch):
        monkeypatch.setenv("SELDON_TPU_DP", "0")
        assert resolve_dp(None) == 1

    def test_degree_below_one_rejected(self):
        with pytest.raises(ValueError):
            resolve_dp(-2)


class TestResolveMesh:
    """resolve_mesh: THE precedence home for the 2-D serving mesh."""

    def test_explicit_mesh_wins(self):
        mesh = create_mesh({"model": 2}, devices=jax.devices()[:2])
        assert resolve_mesh(mesh=mesh, tp=4, dp=2) is mesh

    def test_mesh_axes_beat_knobs(self, monkeypatch):
        monkeypatch.setenv("SELDON_TPU_TP", "4")
        monkeypatch.setenv("SELDON_TPU_DP", "2")
        mesh = resolve_mesh(mesh_axes={"model": 2})
        assert mesh_shape(mesh) == {"model": 2}

    def test_dp1_delegates_to_tp_mesh(self, monkeypatch):
        monkeypatch.delenv("SELDON_TPU_DP", raising=False)
        mesh = resolve_mesh(tp=2)
        want = tp_mesh(2)
        assert mesh_shape(mesh) == mesh_shape(want) == {"model": 2}
        # and the same devices in the same order — the byte-identity
        # precondition for the 1-D program
        assert list(mesh.devices.flat) == list(want.devices.flat)

    def test_all_ones_is_single_chip(self, monkeypatch):
        monkeypatch.delenv("SELDON_TPU_TP", raising=False)
        monkeypatch.delenv("SELDON_TPU_DP", raising=False)
        assert resolve_mesh() is None
        assert resolve_mesh(tp=1, dp=1) is None

    def test_two_d_mesh_is_data_major(self):
        mesh = resolve_mesh(tp=2, dp=2)
        assert mesh.axis_names == ("data", "model")
        assert mesh_shape(mesh) == {"data": 2, "model": 2}
        # data-major grid: each model group spans ADJACENT device ids
        # (fast ICI neighbours for the per-layer all-reduces)
        ids = [[d.id for d in row] for row in mesh.devices]
        assert ids == [[0, 1], [2, 3]]

    def test_dp_only_mesh_drops_model_axis(self):
        mesh = resolve_mesh(tp=1, dp=2)
        assert mesh_shape(mesh) == {"data": 2}

    def test_env_knobs_build_the_mesh(self, monkeypatch):
        monkeypatch.setenv("SELDON_TPU_TP", "2")
        monkeypatch.setenv("SELDON_TPU_DP", "2")
        assert mesh_shape(resolve_mesh()) == {"data": 2, "model": 2}

    def test_degrade_shrinks_data_axis_first(self, caplog):
        # 8 virtual devices: dp=8 x tp=2 = 16 cannot fit; the model
        # degree survives and data shrinks to 8 // 2 = 4
        with caplog.at_level(
            logging.WARNING, logger="seldon_core_tpu.parallel.mesh"
        ):
            mesh = resolve_mesh(tp=2, dp=8)
        assert mesh_shape(mesh) == {"data": 4, "model": 2}
        msgs = [r.message for r in caplog.records]
        assert any(
            "shrinking the data axis first" in m
            and "data=8" in m and "model=2" in m
            for m in msgs
        ), msgs

    def test_degrade_to_single_chip_names_both_axes(self, caplog):
        with caplog.at_level(
            logging.WARNING, logger="seldon_core_tpu.parallel.mesh"
        ):
            assert resolve_mesh(tp=4096, dp=2) is None
        assert any(
            "data=2" in r.message and "model=4096" in r.message
            and "single-chip" in r.message
            for r in caplog.records
        )

    def test_strict_raises_instead_of_degrading(self):
        with pytest.raises(ValueError, match="shrinking the data axis"):
            resolve_mesh(tp=2, dp=8, strict=True)
        with pytest.raises(ValueError, match="single-chip"):
            resolve_mesh(tp=4096, dp=2, strict=True)


class TestCreateMeshRoundTrip:
    """Satellite 2: create_mesh's docstring/default drift fixed and the
    2-D round-trip pinned."""

    def test_two_d_round_trip_preserves_order(self):
        axes = {"data": 2, "model": 2}
        mesh = create_mesh(axes, devices=jax.devices()[:4])
        assert mesh_shape(mesh) == axes
        assert mesh.axis_names == ("data", "model")

    def test_default_is_all_data(self):
        # the trainer's pure replica mesh — the documented default
        assert mesh_shape(create_mesh()) == {"data": len(jax.devices())}

    def test_wildcard_fills_remaining(self):
        mesh = create_mesh({"data": -1, "model": 2},
                           devices=jax.devices()[:8])
        assert mesh_shape(mesh) == {"data": 4, "model": 2}


class TestSeqShardUnits:
    """shard_decode_state: the pool's page dim over `data`, heads dim
    over `model`."""

    @pytest.fixture(scope="class")
    def mesh(self):
        return create_mesh({"data": 2, "model": 2},
                           devices=jax.devices()[:4])

    def test_pool_sharded_on_both_axes(self, mesh):
        pool_shape = (1, 6, 8, 4, 8)
        _, pk, pv = shard_decode_state(
            {}, mesh, pool_shape=pool_shape, dtype=jnp.float32, num_heads=4,
        )
        assert tuple(pk.sharding.spec) == (None, "data", None, "model")
        # one device holds pages/2 x heads/2
        assert pk.addressable_shards[0].data.shape == (1, 3, 8, 2, 8)
        np.testing.assert_array_equal(np.asarray(pv), np.zeros(pool_shape))

    def test_indivisible_pages_replicate_page_dim_with_warn(
        self, mesh, caplog
    ):
        with caplog.at_level(
            logging.WARNING, logger="seldon_core_tpu.parallel.sharding"
        ):
            _, pk, _ = shard_decode_state(
                {}, mesh, pool_shape=(1, 5, 8, 4, 8), dtype=jnp.float32,
                num_heads=4,
            )
        assert any("num_pages=5" in r.message for r in caplog.records)
        # heads sharding survives; only the page dim replicates
        assert tuple(pk.sharding.spec)[3] == "model"
        assert pk.addressable_shards[0].data.shape[1] == 5

    def test_seq_shard_off_replicates_page_dim_silently(self, mesh, caplog):
        with caplog.at_level(
            logging.WARNING, logger="seldon_core_tpu.parallel.sharding"
        ):
            _, pk, _ = shard_decode_state(
                {}, mesh, pool_shape=(1, 6, 8, 4, 8), dtype=jnp.float32,
                num_heads=4, seq_shard=False,
            )
        # an explicit opt-out is not a degrade: no WARN
        assert not any("num_pages" in r.message for r in caplog.records)
        assert pk.addressable_shards[0].data.shape[1] == 6
        assert tuple(pk.sharding.spec)[3] == "model"

    def test_one_d_model_mesh_keeps_historical_spec(self):
        mesh1d = create_mesh({"model": 2}, devices=jax.devices()[:2])
        _, pk, _ = shard_decode_state(
            {}, mesh1d, pool_shape=(1, 6, 8, 4, 8), dtype=jnp.float32,
            num_heads=4,
        )
        assert tuple(pk.sharding.spec) == (None, None, None, "model")


class TestDp1ByteIdentical:
    """The r11 no-regression bar carried forward: dp=1 lowers the EXACT
    1-D program, and dp=tp=1 the EXACT single-chip program."""

    @staticmethod
    def _lower_chunk(eng, steps=2, horizon=4):
        return eng.lower_chunk(steps, ((eng.max_slots, horizon),)).as_text()

    def test_dp1_tp2_program_byte_identical_to_tp_mesh(self, params):
        via_knob = _engine(params, tp=2, dp=1, shard_min_weight_size=0)
        via_mesh = _engine(
            params, mesh=tp_mesh(2), shard_min_weight_size=0
        )
        try:
            assert via_knob.dp_degree == 1
            a = self._lower_chunk(via_knob)
            b = self._lower_chunk(via_mesh)
        finally:
            via_knob.close()
            via_mesh.close()
        assert a == b

    def test_dp1_tp1_program_byte_identical_to_meshless(
        self, params, monkeypatch
    ):
        monkeypatch.delenv("SELDON_TPU_TP", raising=False)
        monkeypatch.delenv("SELDON_TPU_DP", raising=False)
        plain = _engine(params)
        knob = _engine(params, tp=1, dp=1)
        try:
            assert knob._mesh is None and knob.dp_degree == 1
            a = self._lower_chunk(plain)
            b = self._lower_chunk(knob)
        finally:
            plain.close()
            knob.close()
        assert a == b


class TestMeshParitySmoke:
    """Fast-tier (2,2) coverage: bit-exact greedy vs tp-only vs
    single-chip, plus the sharding bookkeeping."""

    def test_mesh22_greedy_bit_exact_three_ways(self, params):
        single = _engine(params, tp=1)
        outs_single = _serve(single, _prompts())
        s_single = single.engine_stats()
        single.close()

        tponly = _engine(params, tp=2, shard_min_weight_size=0)
        outs_tp = _serve(tponly, _prompts())
        tponly.close()

        mesh = _engine(params, tp=2, dp=2, shard_min_weight_size=0)
        assert mesh.tp_degree == 2 and mesh.dp_degree == 2
        outs_mesh = _serve(mesh, _prompts())
        s_mesh = mesh.engine_stats()
        mesh.close()

        for a, b, c in zip(outs_mesh, outs_tp, outs_single):
            np.testing.assert_array_equal(a, b)
            np.testing.assert_array_equal(a, c)
        assert s_mesh["dp_degree"] == 2 and s_single["dp_degree"] == 1
        # pool sharded over BOTH axes: one device holds at most a
        # quarter of the single-chip bytes (pool may round up to a dp
        # multiple of pages first, hence <=)
        assert s_mesh["pool_shard_bytes"] * 4 <= (
            s_single["pool_shard_bytes"] + s_single["pool_shard_bytes"] // 2
        )

    def test_pool_pages_round_up_to_dp_multiple(self, params):
        eng = _engine(params, tp=2, dp=2, shard_min_weight_size=0)
        try:
            assert eng.num_pages % 2 == 0
            assert tuple(eng.pages_k.sharding.spec) == (
                None, "data", None, "model",
            )
        finally:
            eng.close()

    def test_env_knobs_reach_engine(self, params, monkeypatch):
        monkeypatch.setenv("SELDON_TPU_TP", "2")
        monkeypatch.setenv("SELDON_TPU_DP", "2")
        eng = _engine(params, shard_min_weight_size=0)
        try:
            assert eng.tp_degree == 2 and eng.dp_degree == 2
        finally:
            eng.close()

    def test_seq_shard_off_still_bit_exact(self, params, monkeypatch):
        monkeypatch.setenv("SELDON_TPU_SEQ_SHARD", "0")
        eng = _engine(params, tp=2, dp=2, shard_min_weight_size=0)
        try:
            # pure throughput replicas: page dim replicated, decode
            # unchanged
            assert tuple(eng.pages_k.sharding.spec)[1] is None
            outs = _serve(eng, _prompts())
        finally:
            eng.close()
        monkeypatch.delenv("SELDON_TPU_SEQ_SHARD")
        ref = _engine(params, tp=1)
        try:
            ref_outs = _serve(ref, _prompts())
        finally:
            ref.close()
        for a, b in zip(outs, ref_outs):
            np.testing.assert_array_equal(a, b)

    def test_indivisible_slots_fall_back_with_warn(self, params, caplog):
        with caplog.at_level(
            logging.WARNING, logger="seldon_core_tpu.models.paged"
        ):
            eng = _engine(
                params, tp=2, dp=2, max_slots=3, shard_min_weight_size=0
            )
        try:
            assert eng.dp_degree == 2 and not eng._lane_sharded
            outs = _serve(eng, _prompts(3))
        finally:
            eng.close()
        ref = _engine(params, tp=1, max_slots=3)
        try:
            ref_outs = _serve(ref, _prompts(3))
        finally:
            ref.close()
        for a, b in zip(outs, ref_outs):
            np.testing.assert_array_equal(a, b)
        assert any("max_slots" in r.message for r in caplog.records)

    def test_speculative_mesh22_bit_exact(self, params):
        prompt = np.array([5, 9, 5, 9, 5, 9, 5], np.int32)
        ref = _engine(params, tp=1)
        want = ref.generate(prompt, max_new_tokens=8).tolist()
        ref.close()
        eng = _engine(
            params, tp=2, dp=2, shard_min_weight_size=0,
            speculative={"draft_k": 3, "ngram": 2},
        )
        try:
            got = eng.generate(prompt, max_new_tokens=8).tolist()
        finally:
            eng.close()
        assert got == want


class TestGeneratorLaneDp:
    """dp knob threading through the contiguous + speculative lanes."""

    def test_generator_dp_mesh_parity(self, params):
        from seldon_core_tpu.models.generate import Generator

        base = dict(dtype=jnp.float32, quantize="", **CFG)
        plain = Generator(params, tp=1, **base)
        prompt = np.array([[3, 1, 4, 1, 5]], np.int32)
        want = plain.generate(prompt, max_new_tokens=8)
        mesh_gen = Generator(params, tp=1, dp=2, **base)
        assert mesh_gen.dp_degree == 2 and mesh_gen.tp_degree == 1
        got = mesh_gen.generate(prompt, max_new_tokens=8)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))

    def test_speculative_generator_mesh22_bit_exact(self, params):
        from seldon_core_tpu.models.speculative import SpeculativeGenerator

        prompt = np.array([3, 1, 4, 1, 5, 9, 2, 6], np.int32)

        def run(**kw):
            g = SpeculativeGenerator(
                params, dtype=jnp.float32, page_size=8,
                shard_min_weight_size=0, **CFG, **kw,
            )
            return g.generate(prompt, max_new_tokens=8).tolist()

        assert run(tp=2, dp=2) == run(tp=1)

    def test_speculative_pool_rounds_and_shards(self, params):
        from seldon_core_tpu.models.speculative import SpeculativeGenerator

        g = SpeculativeGenerator(
            params, dtype=jnp.float32, page_size=8,
            shard_min_weight_size=0, tp=2, dp=2, **CFG,
        )
        # max_len 64 / page 8 + trash = 9 pages, rounded to 10 for dp=2
        assert g.target.pk.shape[1] == 10
        assert tuple(g.target.pk.sharding.spec)[1] == "data"


class TestAccountingDp:
    """paged_hbm_accounting dp_degree + paged_max_context."""

    KW = dict(d_model=256, num_layers=4, dtype_bytes=2, flat_pool=True,
              chunk_impl="ring")

    def test_dp_divides_kv_terms_and_keys_stay_separate(self):
        full = paged_hbm_accounting(streams=4, ctx_len=2048, **self.KW)
        both = paged_hbm_accounting(
            streams=4, ctx_len=2048, tp_degree=2, dp_degree=2, **self.KW
        )
        assert both["tp_degree"] == 2 and both["dp_degree"] == 2
        assert both["pool_bytes"] == full["pool_bytes"] // 4
        assert both["working_set_bytes"] == full["working_set_bytes"] // 4
        # dp alone divides by 2 and must NOT inflate the tp key
        dp_only = paged_hbm_accounting(
            streams=4, ctx_len=2048, dp_degree=2, **self.KW
        )
        assert dp_only["tp_degree"] == 1 and dp_only["dp_degree"] == 2
        assert dp_only["pool_bytes"] == full["pool_bytes"] // 2

    def test_indivisible_pool_pages_price_full_bytes(self):
        full = paged_hbm_accounting(streams=1, ctx_len=2048, **self.KW)
        fb = paged_hbm_accounting(
            streams=1, ctx_len=2048, dp_degree=2, num_pool_pages=33,
            **self.KW
        )
        # mirror shard_decode_state's WARN fallback: replicated page dim
        assert fb["dp_degree"] == 1
        assert fb["pool_bytes"] == full["pool_bytes"]
        ok = paged_hbm_accounting(
            streams=1, ctx_len=2048, dp_degree=2, num_pool_pages=34,
            **self.KW
        )
        assert ok["dp_degree"] == 2

    def test_max_context_scales_with_data_axis(self):
        budget = 64 << 20
        single = paged_max_context(budget, **self.KW)
        mesh = paged_max_context(budget, tp_degree=2, dp_degree=2, **self.KW)
        assert single > 0 and single % 64 == 0
        assert mesh > single
        assert mesh % 64 == 0

    def test_max_context_zero_when_one_page_overflows(self):
        assert paged_max_context(16, **self.KW) == 0

    def test_long_context_certificate(self):
        """The bench's admit certificate as arithmetic: per-shard bytes
        < budget < full bytes at 32k, so the 2-D mesh admits a context
        no single chip can hold."""
        ctx = 32 * 1024
        full = paged_hbm_accounting(streams=1, ctx_len=ctx, **self.KW)
        shard = paged_hbm_accounting(
            streams=1, ctx_len=ctx, tp_degree=2, dp_degree=2, **self.KW
        )
        budget = (shard["peak_bytes"] + full["peak_bytes"]) // 2
        assert shard["peak_bytes"] < budget < full["peak_bytes"]
        assert paged_max_context(budget, **self.KW) < ctx
        assert paged_max_context(
            budget, tp_degree=2, dp_degree=2, **self.KW
        ) >= ctx


class TestRingOracleOverDataAxis:
    """Satellite 1: ring attention runs over the SERVING mesh's `data`
    axis — the same axis that page-shards the paged pool — and matches
    the plain_attention oracle (the long-context numerics pin)."""

    def test_ring_over_serving_data_axis_matches_oracle(self):
        from seldon_core_tpu.parallel.ring_attention import (
            plain_attention,
            ring_attention,
        )

        mesh = resolve_mesh(tp=2, dp=2)
        rng = np.random.default_rng(11)
        q, k, v = (
            jnp.asarray(rng.normal(size=(1, 32, 4, 8)).astype(np.float32))
            for _ in range(3)
        )
        want = plain_attention(q, k, v, causal=True)
        got = ring_attention(q, k, v, mesh=mesh, seq_axis="data", causal=True)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-5
        )


class TestMeshObservability:
    """dp_degree threads engine_stats -> Prometheus bridge ->
    StreamingLM gauges -> chunk records."""

    def test_bridge_exports_dp_gauge(self, params):
        import prometheus_client as prom

        from seldon_core_tpu.utils.metrics import GenerationPrometheusBridge

        registry = prom.CollectorRegistry()
        eng = _engine(params, tp=2, dp=2, shard_min_weight_size=0)
        try:
            GenerationPrometheusBridge(
                eng, deployment_name="d", predictor_name="p",
                model_name="m", registry=registry,
            ).collect()
            labels = {"deployment_name": "d", "predictor_name": "p",
                      "model_name": "m"}
            assert registry.get_sample_value(
                "seldon_tpu_engine_dp_degree", labels) == 2.0
        finally:
            eng.close()

    def test_streaminglm_dp_knob_and_gauge(self):
        comp = StreamingLM(max_slots=2, steps_per_call=2, tp=2, dp=2, **CFG)
        comp.load()
        try:
            assert comp.engine.dp_degree == 2
            by_key = {m["key"]: m["value"] for m in comp.metrics()}
            assert by_key["paged_dp_degree"] == 2
        finally:
            comp.shutdown()

    def test_chunk_records_carry_dp_degree(self, params, monkeypatch):
        monkeypatch.setenv("SELDON_TPU_FLIGHT_RECORDER", "64")
        eng = _engine(params, tp=2, dp=2, shard_min_weight_size=0)
        try:
            _serve(eng, _prompts())
            recs = eng.recorder.snapshot()
            assert recs and all(r["dp_degree"] == 2 for r in recs
                                if r.get("phase") == "decode")
        finally:
            eng.close()


@pytest.mark.slow
class TestMeshParityMatrix:
    """Satellite 4: the (2,2) parity matrix on the forced-8-device CPU
    host — greedy bit-exactness (dp=2, tp=2) vs single-chip across
    chunk impls x w8a8 x speculative x prefix-cache."""

    MCFG = dict(vocab_size=64, d_model=32, num_layers=2, num_heads=4,
                max_len=64)

    @pytest.fixture(scope="class")
    def mparams(self):
        lm = TransformerLM(dtype=jnp.float32, **self.MCFG)
        return lm.init(jax.random.key(1), jnp.zeros((1, 8), jnp.int32))["params"]

    def _mprompts(self):
        rng = np.random.default_rng(3)
        shared = rng.integers(0, 64, size=(17,)).astype(np.int32)
        return [
            np.concatenate(
                [shared, rng.integers(0, 64, size=(2 + i,)).astype(np.int32)]
            )
            for i in range(3)
        ]

    def _run(self, params, monkeypatch, *, dp, tp, impl, precision,
             speculative, prefix_cache):
        monkeypatch.setenv("SELDON_TPU_CHUNK_IMPL", impl)
        eng = PagedEngine(
            params, dtype=jnp.float32, page_size=8, max_slots=2,
            steps_per_call=4, precision=precision, speculative=speculative,
            prefix_cache=prefix_cache, tp=tp, dp=dp,
            shard_min_weight_size=0, **self.MCFG,
        )
        assert eng.tp_degree == tp and eng.dp_degree == dp
        outs = []
        try:
            for p in self._mprompts():
                stream = eng.submit(p, max_new_tokens=8)
                eng.run()
                outs.append(stream.result)
        finally:
            eng.close()
        return outs

    @pytest.mark.parametrize("impl", ["ring", "pool"])
    @pytest.mark.parametrize("precision", ["", "w8a8"])
    @pytest.mark.parametrize("spec", [None, {"draft": "ngram", "draft_k": 3}])
    @pytest.mark.parametrize("prefix_cache", [True, False])
    def test_mesh22_bit_exact_vs_single_chip(
        self, mparams, monkeypatch, impl, precision, spec, prefix_cache
    ):
        kw = dict(impl=impl, precision=precision, speculative=spec,
                  prefix_cache=prefix_cache)
        off = self._run(mparams, monkeypatch, dp=1, tp=1, **kw)
        on = self._run(mparams, monkeypatch, dp=2, tp=2, **kw)
        for a, b in zip(on, off):
            np.testing.assert_array_equal(a, b)


@pytest.mark.slow
class TestLongContextAdmit:
    """The scaled long-context point: the accounting says a single
    chip's budget cannot admit the context but the (2,2) mesh can, and
    the decode under that mesh is bit-exact vs an unconstrained
    single-chip replay."""

    LCFG = dict(vocab_size=64, d_model=32, num_layers=1, num_heads=4,
                max_len=512)

    def test_admit_and_decode_under_mesh(self):
        lm = TransformerLM(dtype=jnp.float32, **self.LCFG)
        params = lm.init(jax.random.key(2), jnp.zeros((1, 8), jnp.int32))["params"]
        ctx = 384
        acct_kw = dict(
            d_model=self.LCFG["d_model"],
            num_layers=self.LCFG["num_layers"],
            page_size=8, dtype_bytes=4, flat_pool=True, chunk_impl="ring",
        )
        full = paged_hbm_accounting(streams=1, ctx_len=ctx, **acct_kw)
        shard = paged_hbm_accounting(
            streams=1, ctx_len=ctx, tp_degree=2, dp_degree=2, **acct_kw
        )
        budget = (shard["peak_bytes"] + full["peak_bytes"]) // 2
        # the certificate: only the mesh admits this context
        assert paged_max_context(budget, page_size=8, **{
            k: v for k, v in acct_kw.items() if k != "page_size"
        }) < ctx
        assert paged_max_context(budget, page_size=8, tp_degree=2,
                                 dp_degree=2, **{
            k: v for k, v in acct_kw.items() if k != "page_size"
        }) >= ctx

        prompt = np.random.default_rng(5).integers(
            0, self.LCFG["vocab_size"], size=(ctx - 16,)
        ).astype(np.int32)

        def decode(**kw):
            eng = PagedEngine(
                params, dtype=jnp.float32, page_size=8, max_slots=2,
                steps_per_call=4, shard_min_weight_size=0,
                **self.LCFG, **kw,
            )
            try:
                return _serve(eng, [prompt], max_new=8)[0]
            finally:
                eng.close()

        on = decode(tp=2, dp=2)
        off = decode(tp=1)
        np.testing.assert_array_equal(on, off)
