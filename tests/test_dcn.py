"""Cross-process (DCN) serving: remote graph nodes + multi-host jax.

VERDICT round-2 item 7: a deployment whose graph spans
supervisor-spawned worker processes via GrpcClient edges (process
placement emitting endpoints), plus a real 2-process
``jax.distributed`` exercise of parallel/multihost.py.

Reference analogue: the operator creates one Deployment+Service per
graph container and the engine calls them over the pod network
(reference: operator/controllers/seldondeployment_controller.go:268-494,
engine/.../InternalPredictionService.java:192-467); multi-host compute
is the reference's NCCL/MPI layer re-done as jax.distributed + XLA
collectives over DCN.
"""

import asyncio
import os
import socket
import subprocess
import sys
import textwrap
import time

import numpy as np
import pytest

from seldon_core_tpu.controlplane import Deployer, TpuDeployment
from seldon_core_tpu.runtime.message import InternalMessage

pytestmark = pytest.mark.slow  # compile-heavy: excluded from the default fast tier (make test-all)


REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _remote_child_spec(name: str) -> TpuDeployment:
    return TpuDeployment.from_dict(
        {
            "name": name,
            "predictors": [
                {
                    "name": "main",
                    "traffic": 100,
                    "graph": {
                        "name": "combiner",
                        "type": "COMBINER",
                        "implementation": "AVERAGE_COMBINER",
                        "children": [
                            {
                                "name": "local-leg",
                                "type": "MODEL",
                                "implementation": "SIMPLE_MODEL",
                            },
                            {
                                "name": "remote-leg",
                                "type": "MODEL",
                                "implementation": "SIMPLE_MODEL",
                                "remote": True,
                            },
                        ],
                    },
                }
            ],
        }
    )


@pytest.mark.e2e
class TestRemoteGraphNode:
    def test_graph_spans_worker_process_over_grpc(self):
        """remote:true node runs in a supervisor-spawned process; the
        executor reaches it over a GrpcClient DCN edge; the combiner
        merges the local and remote legs."""
        spec = _remote_child_spec("dcn-e2e")

        async def scenario():
            deployer = Deployer()
            managed = await deployer.apply(spec, ready_timeout_s=90.0)
            gen = managed.current
            assert gen.supervisor is not None
            workers = list(gen.supervisor.processes.values())
            assert len(workers) == 1
            assert workers[0].alive() and workers[0].ready()
            # endpoint was emitted onto the generation's cloned graph...
            remote_unit = [
                u for u in gen.spec.predictors[0].graph.walk() if u.name == "remote-leg"
            ][0]
            assert remote_unit.endpoint is not None
            assert remote_unit.endpoint.port == workers[0].spec.grpc_port
            # ...but never onto the caller's spec object
            caller_unit = [
                u for u in spec.predictors[0].graph.walk() if u.name == "remote-leg"
            ][0]
            assert caller_unit.endpoint is None

            out = await managed.gateway.predict(InternalMessage(payload=np.ones((1, 2))))
            assert out.status is None or out.status.get("status") != "FAILURE"
            # both legs return StubModel.OUTPUT; the average equals it
            np.testing.assert_allclose(out.array(), [[0.9, 0.05, 0.05]])
            # the remote hop is recorded in the request path
            assert "remote-leg" in out.meta.request_path

            pid = workers[0].proc.pid
            await deployer.delete("dcn-e2e")
            return pid

        pid = asyncio.run(scenario())
        for _ in range(50):
            try:
                os.kill(pid, 0)
            except OSError:
                break
            time.sleep(0.1)
        else:
            raise AssertionError(f"worker pid {pid} still alive after delete")

    def test_rolling_reapply_respawns_worker(self):
        """Re-applying the same spec object builds a fresh generation
        with its own worker; the old worker is drained afterwards."""
        spec = _remote_child_spec("dcn-roll")

        async def scenario():
            deployer = Deployer()
            managed = await deployer.apply(spec, ready_timeout_s=90.0)
            first = managed.current
            first_worker = list(first.supervisor.processes.values())[0]
            first_port = first_worker.spec.grpc_port
            managed = await deployer.apply(spec, ready_timeout_s=90.0)
            second = managed.current
            second_port = list(second.supervisor.processes.values())[0].spec.grpc_port
            assert second.generation == first.generation + 1
            assert second_port != first_port
            out = await managed.gateway.predict(InternalMessage(payload=np.ones((1, 2))))
            np.testing.assert_allclose(out.array(), [[0.9, 0.05, 0.05]])
            # old generation's drain (background) eventually stops its worker
            for _ in range(100):
                if not first_worker.alive():
                    break
                await asyncio.sleep(0.1)
            else:
                raise AssertionError("old generation worker never stopped")
            await deployer.delete("dcn-roll")

        asyncio.run(scenario())


_MULTIHOST_WORKER = textwrap.dedent(
    """
    import os, sys
    import jax
    jax.config.update("jax_platforms", "cpu")
    from seldon_core_tpu.parallel import multihost

    is_multi = multihost.initialize()
    info = multihost.host_info()
    assert is_multi, info
    assert info["process_count"] == 2, info
    assert info["global_devices"] == 8, info

    import jax.numpy as jnp
    from functools import partial
    from jax import lax
    from jax.sharding import PartitionSpec as P
    from jax.experimental.shard_map import shard_map

    mesh = multihost.global_mesh({"data": 8})

    @jax.jit
    @partial(shard_map, mesh=mesh, in_specs=P(), out_specs=P())
    def total(x):
        return lax.psum(x, "data")

    # replicated input; psum over the 8 devices spanning both processes
    y = float(total(jnp.asarray(1.0)))
    assert y == 8.0, y
    print(f"MULTIHOST_OK process={info['process_index']} psum={y}", flush=True)
    """
)


@pytest.mark.e2e
class TestMultihostJaxDistributed:
    def test_two_process_psum_over_dcn(self, tmp_path):
        """parallel/multihost.py drives a real 2-process
        jax.distributed runtime; a psum spans both processes."""
        port = socket.socket()
        port.bind(("127.0.0.1", 0))
        coord = f"127.0.0.1:{port.getsockname()[1]}"
        port.close()

        script = tmp_path / "worker.py"
        script.write_text(_MULTIHOST_WORKER)
        procs = []
        for pid in range(2):
            env = dict(os.environ)
            env.update(
                {
                    "JAX_COORDINATOR_ADDRESS": coord,
                    "JAX_NUM_PROCESSES": "2",
                    "JAX_PROCESS_ID": str(pid),
                    "JAX_PLATFORMS": "cpu",
                    "XLA_FLAGS": "--xla_force_host_platform_device_count=4",
                    # the worker runs from a tmp script path; the repo
                    # root is not implicitly importable there
                    "PYTHONPATH": REPO_ROOT + os.pathsep + env.get("PYTHONPATH", ""),
                }
            )
            procs.append(
                subprocess.Popen(
                    [sys.executable, str(script)],
                    env=env,
                    stdout=subprocess.PIPE,
                    stderr=subprocess.STDOUT,
                    text=True,
                    cwd=REPO_ROOT,
                )
            )
        outputs = []
        for p in procs:
            out, _ = p.communicate(timeout=180)
            outputs.append(out)
        for i, (p, out) in enumerate(zip(procs, outputs)):
            assert p.returncode == 0, f"process {i} failed:\n{out}"
            assert "MULTIHOST_OK" in out, out
        assert any("process=0" in o for o in outputs)
        assert any("process=1" in o for o in outputs)
