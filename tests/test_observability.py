"""Observability tests: prometheus metric names/tags, tracing spans,
request-pair logging (reference: analytics.md:9-16 metric contract,
PredictionService.java:169-202 pair format)."""

import asyncio
import os
import json

import numpy as np
import pytest
from prometheus_client import CollectorRegistry

from seldon_core_tpu.engine import PredictorService, UnitSpec
from seldon_core_tpu.runtime import InternalFeedback, InternalMessage, TPUComponent
from seldon_core_tpu.utils.metrics import PrometheusObserver
from seldon_core_tpu.utils.reqlogger import JsonlPairLogger
from seldon_core_tpu.utils import tracing


def run(coro):
    return asyncio.run(coro)


def msg(arr):
    return InternalMessage(payload=np.asarray(arr, dtype=np.float64), kind="tensor")


class MetricModel(TPUComponent):
    def predict(self, X, names, meta=None):
        return np.asarray(X) * 2

    def metrics(self):
        return [
            {"key": "my_counter", "type": "COUNTER", "value": 2.0},
            {"key": "my_gauge", "type": "GAUGE", "value": 7.5, "tags": {"stage": "test"}},
            {"key": "my_timer", "type": "TIMER", "value": 120.0},
        ]

    def send_feedback(self, features, names, reward, truth, routing=None):
        return None


def sample(registry, name, labels):
    return registry.get_sample_value(name, labels)


class TestPrometheus:
    def test_reference_metric_names_and_tags(self):
        registry = CollectorRegistry()
        obs = PrometheusObserver("dep1", "pred1", registry=registry)
        svc = PredictorService(
            UnitSpec(name="m", type="MODEL", component=MetricModel()),
            name="pred1",
            observer=obs,
        )
        out = run(svc.predict(msg([[1.0]])))
        assert out.status["status"] == "SUCCESS"

        base = {"deployment_name": "dep1", "predictor_name": "pred1", "model_name": "m"}
        # custom metrics with the reference's deployment/predictor/model tags
        assert sample(registry, "my_counter_total", base) == 2.0
        assert sample(registry, "my_gauge", dict(base, stage="test")) == 7.5
        assert sample(registry, "my_timer_count", base) == 1.0
        # engine server histogram
        assert (
            sample(
                registry,
                "seldon_api_engine_server_requests_duration_seconds_count",
                {"deployment_name": "dep1", "predictor_name": "pred1", "method": "predictions", "code": "200"},
            )
            == 1.0
        )
        # engine->node client histogram
        assert (
            sample(
                registry,
                "seldon_api_engine_client_requests_duration_seconds_count",
                dict(base, method="transform_input"),
            )
            == 1.0
        )

    def test_feedback_counters(self):
        registry = CollectorRegistry()
        obs = PrometheusObserver("dep1", "pred1", registry=registry)
        svc = PredictorService(
            UnitSpec(name="m", type="MODEL", component=MetricModel()),
            observer=obs,
        )
        resp = run(svc.predict(msg([[1.0]])))
        fb = InternalFeedback(request=msg([[1.0]]), response=resp, reward=0.8)
        run(svc.send_feedback(fb))
        base = {"deployment_name": "dep1", "predictor_name": "pred1", "model_name": "m"}
        assert sample(registry, "seldon_api_model_feedback_total", base) == 1.0
        assert sample(registry, "seldon_api_model_feedback_reward_total", base) == pytest.approx(0.8)

    def test_observer_errors_never_break_data_plane(self):
        def exploding_observer(event, unit, payload):
            raise RuntimeError("observer bug")

        svc = PredictorService(
            UnitSpec(name="m", type="MODEL", component=MetricModel()),
            observer=exploding_observer,
        )
        out = run(svc.predict(msg([[1.0]])))
        assert out.status["status"] == "SUCCESS"


class TestTracing:
    def test_spans_per_request_and_node(self):
        tracer = tracing.setup_tracing("test-svc")
        try:
            svc = PredictorService(UnitSpec(name="m", type="MODEL", component=MetricModel()))
            out = run(svc.predict(msg([[1.0]])))
            puid = out.meta.puid
            spans = tracer.find(puid)
            names = {s.name for s in spans}
            assert "predictor.predict" in names
            assert "node.m.transform_input" in names
            for s in spans:
                assert s.duration_s >= 0
        finally:
            tracing._tracer = None

    def test_jsonl_export(self, tmp_path):
        path = str(tmp_path / "spans.jsonl")
        tracer = tracing.setup_tracing("test-svc", export_path=path)
        try:
            with tracer.span("op", trace_id="t1", foo="bar"):
                pass
            lines = [json.loads(l) for l in open(path)]
            assert lines[0]["traceId"] == "t1"
            assert lines[0]["tags"]["foo"] == "bar"
        finally:
            tracer.close()
            tracing._tracer = None


class TestRequestLogger:
    def test_pair_logged(self, tmp_path):
        path = str(tmp_path / "pairs.jsonl")
        svc = PredictorService(
            UnitSpec(name="m", type="MODEL", component=MetricModel()),
            request_logger=JsonlPairLogger(path),
        )
        run(svc.predict(msg([[3.0]])))
        pairs = [json.loads(l) for l in open(path)]
        assert len(pairs) == 1
        assert pairs[0]["request"]["data"]["tensor"]["values"] == [3.0]
        assert pairs[0]["response"]["data"]["tensor"]["values"] == [6.0]
        assert pairs[0]["puid"]


class TestRequestLogConsumer:
    """The consumer side of the pair stream (VERDICT r2 missing #3;
    reference: seldon-request-logger/app/app.py:15-60 indexes pairs
    into ES — here SQLite + the same CloudEvents ingestion surface)."""

    def test_predict_log_ingest_query_by_puid(self, tmp_path):
        """The full loop: predict -> pair logged -> indexed -> queryable."""
        from seldon_core_tpu.utils.reqconsumer import PairIndex

        path = str(tmp_path / "pairs.jsonl")
        svc = PredictorService(
            UnitSpec(name="m", type="MODEL", component=MetricModel()),
            request_logger=JsonlPairLogger(path),
        )
        out = run(svc.predict(msg([[3.0]])))
        puid = out.meta.puid
        index = PairIndex(str(tmp_path / "pairs.sqlite"))
        assert index.ingest_jsonl(path) == 1
        pair = index.get(puid)
        assert pair is not None
        assert pair["request"]["data"]["tensor"]["values"] == [3.0]
        assert pair["response"]["data"]["tensor"]["values"] == [6.0]
        assert index.get("no-such-puid") is None

    def test_http_pair_logger_to_consumer_e2e(self, tmp_path):
        """HttpPairLogger -> CloudEvents POST -> consumer app -> query:
        the reference's engine->logger wire, end to end over sockets."""
        import asyncio
        import time as _time

        from seldon_core_tpu.utils.reqconsumer import PairIndex, build_consumer_app
        from seldon_core_tpu.utils.reqlogger import HttpPairLogger

        async def scenario():
            from aiohttp.test_utils import TestClient, TestServer

            index = PairIndex()
            client = TestClient(TestServer(build_consumer_app(index)))
            await client.start_server()
            url = f"http://127.0.0.1:{client.port}/"

            svc = PredictorService(
                UnitSpec(name="m", type="MODEL", component=MetricModel()),
                request_logger=HttpPairLogger(url),
            )
            out = await svc.predict(msg([[4.0]]))
            # the logger posts from a background thread
            deadline = _time.time() + 10.0
            while index.count() < 1 and _time.time() < deadline:
                await asyncio.sleep(0.05)
            svc.request_logger.close()

            got = await client.get(f"/pairs/{out.meta.puid}")
            body = await got.json()
            listed = await client.get("/pairs", params={"limit": "10"})
            listing = await listed.json()
            stats = await (await client.get("/stats")).json()
            await client.close()
            return got.status, body, listing, stats

        status, body, listing, stats = run(scenario())
        assert status == 200
        assert body["response"]["data"]["tensor"]["values"] == [8.0]
        assert listing["count"] == 1
        assert stats["pairs"] == 1

    def test_deployment_annotation_wires_pair_logging(self, tmp_path):
        """`seldon.io/request-log-jsonl` on a deployment spec turns on
        pair logging declaratively (the reference's
        message.logging.service env wiring)."""
        import asyncio

        from seldon_core_tpu.controlplane import Deployer, TpuDeployment
        from seldon_core_tpu.utils.reqconsumer import PairIndex

        path = str(tmp_path / "pairs.jsonl")
        spec = TpuDeployment.from_dict({
            "name": "logged-dep",
            "annotations": {"seldon.io/request-log-jsonl": path},
            "predictors": [{
                "name": "main", "traffic": 100,
                "graph": {"name": "stub", "type": "MODEL",
                          "implementation": "SIMPLE_MODEL"},
            }],
        })

        async def scenario():
            deployer = Deployer(device_ids=[0])
            managed = await deployer.apply(spec)
            out = await managed.gateway.predict(msg([[1.0]]))
            await deployer.delete("logged-dep")
            return out.meta.puid

        puid = asyncio.run(scenario())
        index = PairIndex()
        assert index.ingest_jsonl(path) >= 1
        assert index.get(puid) is not None

    def test_query_filters_and_upsert(self):
        from seldon_core_tpu.utils.reqconsumer import PairIndex

        index = PairIndex()
        for i, (puid, predictor) in enumerate(
            [("p1", "main"), ("p2", "main"), ("p3", "canary")]
        ):
            index.ingest({
                "puid": puid, "time": 100.0 + i,
                "request": {"data": {"ndarray": [[i]]}},
                "response": {"meta": {"puid": puid, "tags": {"predictor": predictor}}},
            })
        assert index.count() == 3
        assert len(index.query(predictor="main", limit=10)) == 2
        assert len(index.query(since=101.5, limit=10)) == 1
        # re-ingesting the same puid upserts, never duplicates
        index.ingest({"puid": "p1", "time": 200.0,
                      "request": {}, "response": {"meta": {"puid": "p1"}}})
        assert index.count() == 3
        assert index.get("p1")["time"] == 200.0
        # a pair without any puid is rejected loudly
        import pytest as _pytest

        with _pytest.raises(ValueError):
            index.ingest({"request": {}, "response": {}})


class TestMonitoringAssets:
    """The shipped prometheus/alertmanager/grafana configs stay coherent
    with the metric names the code emits (reference analogue: the
    seldon-core-analytics chart's rules + dashboards)."""

    MONITORING = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "monitoring")

    def _load(self, name):
        import yaml

        with open(os.path.join(self.MONITORING, name)) as f:
            return yaml.safe_load(f)

    def test_alert_rules_parse_and_reference_emitted_metrics(self):
        rules = self._load("alert-rules.yml")
        exprs = " ".join(
            r["expr"] for g in rules["groups"] for r in g["rules"]
        )
        # metric families that PrometheusObserver and the detectors emit
        for metric in (
            "seldon_api_engine_server_requests_duration_seconds",
            "seldon_api_engine_client_requests_duration_seconds",
            "seldon_api_model_feedback",
            "outliers_total",
            # generation lane (StreamingLM/SpeculativeLM metrics())
            "paged_pool_utilization",
            "paged_evictions",
            "speculative_acceptance_rate",
        ):
            assert metric in exprs, f"alert rules no longer cover {metric}"
        for g in rules["groups"]:
            for r in g["rules"]:
                assert r["labels"]["severity"] in ("info", "warning", "critical")
                assert "summary" in r["annotations"]

    def test_prometheus_config_wires_rules_and_alertmanager(self):
        prom = self._load("prometheus.yml")
        assert "alert-rules.yml" in prom["rule_files"]
        targets = prom["alerting"]["alertmanagers"][0]["static_configs"][0]["targets"]
        assert targets == ["localhost:9093"]

    def test_alertmanager_routes_and_inhibition(self):
        am = self._load("alertmanager.yml")
        names = {r["name"] for r in am["receivers"]}
        assert am["route"]["receiver"] in names
        for route in am["route"].get("routes", []):
            assert route["receiver"] in names
        assert am["inhibit_rules"]

    def test_dashboards_parse_and_use_emitted_metrics(self):
        import json

        gdir = os.path.join(self.MONITORING, "grafana")
        dashboards = [f for f in os.listdir(gdir) if f.endswith(".json")]
        # predictions + outliers + generation (reference ships several)
        assert len(dashboards) >= 3
        emitted_families = ("seldon_api", "outliers_total", "paged_", "speculative_")
        for name in dashboards:
            with open(os.path.join(gdir, name)) as f:
                dash = json.load(f)
            assert dash["panels"], name
            exprs = " ".join(
                t["expr"] for p in dash["panels"] for t in p.get("targets", [])
            )
            assert any(fam in exprs for fam in emitted_families), name

    def test_generation_dashboard_covers_engine_stats(self):
        import json

        with open(os.path.join(self.MONITORING, "grafana", "generation-dashboard.json")) as f:
            dash = json.load(f)
        exprs = " ".join(
            t["expr"] for p in dash["panels"] for t in p.get("targets", [])
        )
        for metric in ("paged_pool_utilization", "paged_tokens_emitted",
                       "paged_stall_events", "speculative_acceptance_rate"):
            assert metric in exprs, metric


class TestOtlpExporter:
    """OTLP/HTTP JSON export (Jaeger >=1.35 / otel-collector :4318
    ingest) emitted with the stdlib — no opentelemetry-sdk."""

    def _collector(self):
        import http.server
        import threading

        received = []

        class Handler(http.server.BaseHTTPRequestHandler):
            def do_POST(self):
                body = self.rfile.read(int(self.headers["Content-Length"]))
                received.append((self.path, json.loads(body)))
                self.send_response(200)
                self.end_headers()
                self.wfile.write(b"{}")

            def log_message(self, *a):
                pass

        srv = http.server.HTTPServer(("127.0.0.1", 0), Handler)
        threading.Thread(target=srv.serve_forever, daemon=True).start()
        return srv, received

    def test_spans_ship_in_otlp_shape(self):
        from seldon_core_tpu.utils.tracing import OtlpHttpExporter, Tracer

        srv, received = self._collector()
        try:
            exporter = OtlpHttpExporter(
                endpoint=f"http://127.0.0.1:{srv.server_port}/v1/traces",
                service_name="svc-x",
                batch_size=2,
            )
            tracer = Tracer(exporter=exporter)
            with tracer.span("predictor.predict", trace_id="puid-1", model="m1"):
                # nested span: parent linkage comes from the contextvar
                # stack, the way the engine's node spans nest in practice
                with tracer.span("node.transform_input"):
                    pass
            # batch_size=2 -> one POST fired (on the export worker)
            exporter.flush()
            assert len(received) == 1
            path, body = received[0]
            assert path == "/v1/traces"
            rs = body["resourceSpans"][0]
            svc_attr = rs["resource"]["attributes"][0]
            assert svc_attr == {"key": "service.name", "value": {"stringValue": "svc-x"}}
            spans = rs["scopeSpans"][0]["spans"]
            # child closes (and records) first
            spans.sort(key=lambda x: x["name"])
            assert [s["name"] for s in spans] == ["node.transform_input", "predictor.predict"]
            spans.reverse()  # [parent, child]
            # same puid -> same 32-hex traceId; child links its parent
            assert spans[0]["traceId"] == spans[1]["traceId"]
            assert len(spans[0]["traceId"]) == 32 and len(spans[0]["spanId"]) == 16
            # the child inherited the trace and links the parent's real id
            assert spans[1]["parentSpanId"] == spans[0]["spanId"]
            assert spans[1]["spanId"] != spans[0]["spanId"]
            assert int(spans[0]["endTimeUnixNano"]) >= int(spans[0]["startTimeUnixNano"])
            assert exporter.exported == 2
        finally:
            srv.shutdown()

    def test_collector_down_never_raises(self):
        from seldon_core_tpu.utils.tracing import OtlpHttpExporter, Span

        exporter = OtlpHttpExporter(endpoint="http://127.0.0.1:1/v1/traces", timeout_s=0.2)
        assert exporter.export([Span(trace_id="t", name="n", start_s=0.0)]) is False
        assert exporter.failures == 1
        exporter.close()

    def test_setup_tracing_env_wiring(self, monkeypatch):
        from seldon_core_tpu.utils import tracing

        srv, received = self._collector()
        try:
            monkeypatch.setenv(
                "OTEL_EXPORTER_OTLP_ENDPOINT", f"http://127.0.0.1:{srv.server_port}"
            )
            tracer = tracing.setup_tracing(service_name="env-svc")
            assert tracer.exporter is not None
            assert tracer.exporter.endpoint.endswith("/v1/traces")
            with tracer.span("op", trace_id="p"):
                pass
            tracer.close()  # flushes the partial batch
            assert len(received) == 1
        finally:
            srv.shutdown()
            tracing._tracer = None


class TestKafkaPairLogger:
    """Kafka streaming pair logger exercised through a mocked client
    (the gated path is now tested beyond the ImportError gate)."""

    def _fake_kafka(self, monkeypatch):
        import sys
        import types

        sends = []

        class FakeProducer:
            def __init__(self, bootstrap_servers=None, value_serializer=None):
                self.bootstrap = bootstrap_servers
                self.serializer = value_serializer
                self.flushed = self.closed = False

            def send(self, topic, value):
                sends.append((topic, self.serializer(value)))

            def flush(self):
                self.flushed = True

            def close(self):
                self.closed = True

        mod = types.ModuleType("kafka")
        mod.KafkaProducer = FakeProducer
        monkeypatch.setitem(sys.modules, "kafka", mod)
        return sends

    def test_pairs_stream_to_topic(self, monkeypatch):
        from seldon_core_tpu.runtime.message import InternalMessage
        from seldon_core_tpu.utils.reqlogger import KafkaPairLogger

        sends = self._fake_kafka(monkeypatch)
        logger = KafkaPairLogger("broker:9092", topic="pairs")
        req = InternalMessage(payload=np.asarray([[1.0, 2.0]]), kind="ndarray")
        req.meta.puid = "p-1"
        logger(req, req.with_payload(np.asarray([[0.9]])))
        assert len(sends) == 1
        topic, raw = sends[0]
        assert topic == "pairs"
        pair = json.loads(raw)
        assert pair["request"]["data"]["ndarray"] == [[1.0, 2.0]]
        assert pair["response"]["data"]["ndarray"] == [[0.9]]
        logger.close()
        assert logger._producer.flushed and logger._producer.closed


class TestSharedRegistryObservers:
    def test_two_observers_one_registry_no_duplicate_timeseries(self):
        """Two predictors of one deployment (or a rolling re-apply)
        share the process registry; metric objects must be shared, with
        only label values differing."""
        import prometheus_client as prom

        from seldon_core_tpu.utils.metrics import PrometheusObserver, api_latency_sampler

        registry = prom.CollectorRegistry()
        a = PrometheusObserver("dep", "main", registry=registry)
        b = PrometheusObserver("dep", "canary", registry=registry)
        # both paths that register metrics must not collide
        a("predict_done", "m", 0.01)
        b("predict_done", "m", 0.02)
        sampler_a = api_latency_sampler(a)
        sampler_b = api_latency_sampler(b)
        sampler_a(), sampler_b()  # prime both without raising
        for _ in range(10):
            a("predict_done", "m", 0.2)
        assert sampler_a() > 0.0
        assert sampler_b() == 0.0  # canary saw no traffic
