"""Observability tests: prometheus metric names/tags, tracing spans,
request-pair logging (reference: analytics.md:9-16 metric contract,
PredictionService.java:169-202 pair format)."""

import asyncio
import os
import json
import struct
import threading

import numpy as np
import pytest
from prometheus_client import CollectorRegistry

from seldon_core_tpu.engine import PredictorService, UnitSpec
from seldon_core_tpu.runtime import InternalFeedback, InternalMessage, TPUComponent
from seldon_core_tpu.utils.metrics import PrometheusObserver
from seldon_core_tpu.utils.reqlogger import JsonlPairLogger
from seldon_core_tpu.utils import tracing


def run(coro):
    return asyncio.run(coro)


def msg(arr):
    return InternalMessage(payload=np.asarray(arr, dtype=np.float64), kind="tensor")


class MetricModel(TPUComponent):
    def predict(self, X, names, meta=None):
        return np.asarray(X) * 2

    def metrics(self):
        return [
            {"key": "my_counter", "type": "COUNTER", "value": 2.0},
            {"key": "my_gauge", "type": "GAUGE", "value": 7.5, "tags": {"stage": "test"}},
            {"key": "my_timer", "type": "TIMER", "value": 120.0},
        ]

    def send_feedback(self, features, names, reward, truth, routing=None):
        return None


def sample(registry, name, labels):
    return registry.get_sample_value(name, labels)


class TestPrometheus:
    def test_reference_metric_names_and_tags(self):
        registry = CollectorRegistry()
        obs = PrometheusObserver("dep1", "pred1", registry=registry)
        svc = PredictorService(
            UnitSpec(name="m", type="MODEL", component=MetricModel()),
            name="pred1",
            observer=obs,
        )
        out = run(svc.predict(msg([[1.0]])))
        assert out.status["status"] == "SUCCESS"

        base = {"deployment_name": "dep1", "predictor_name": "pred1", "model_name": "m"}
        # custom metrics with the reference's deployment/predictor/model tags
        assert sample(registry, "my_counter_total", base) == 2.0
        assert sample(registry, "my_gauge", dict(base, stage="test")) == 7.5
        assert sample(registry, "my_timer_count", base) == 1.0
        # engine server histogram
        assert (
            sample(
                registry,
                "seldon_api_engine_server_requests_duration_seconds_count",
                {"deployment_name": "dep1", "predictor_name": "pred1", "method": "predictions", "code": "200"},
            )
            == 1.0
        )
        # engine->node client histogram
        assert (
            sample(
                registry,
                "seldon_api_engine_client_requests_duration_seconds_count",
                dict(base, method="transform_input"),
            )
            == 1.0
        )

    def test_feedback_counters(self):
        registry = CollectorRegistry()
        obs = PrometheusObserver("dep1", "pred1", registry=registry)
        svc = PredictorService(
            UnitSpec(name="m", type="MODEL", component=MetricModel()),
            observer=obs,
        )
        resp = run(svc.predict(msg([[1.0]])))
        fb = InternalFeedback(request=msg([[1.0]]), response=resp, reward=0.8)
        run(svc.send_feedback(fb))
        base = {"deployment_name": "dep1", "predictor_name": "pred1", "model_name": "m"}
        assert sample(registry, "seldon_api_model_feedback_total", base) == 1.0
        assert sample(registry, "seldon_api_model_feedback_reward_total", base) == pytest.approx(0.8)

    def test_observer_errors_never_break_data_plane(self):
        def exploding_observer(event, unit, payload):
            raise RuntimeError("observer bug")

        svc = PredictorService(
            UnitSpec(name="m", type="MODEL", component=MetricModel()),
            observer=exploding_observer,
        )
        out = run(svc.predict(msg([[1.0]])))
        assert out.status["status"] == "SUCCESS"


class TestTracing:
    def test_spans_per_request_and_node(self):
        tracer = tracing.setup_tracing("test-svc")
        try:
            svc = PredictorService(UnitSpec(name="m", type="MODEL", component=MetricModel()))
            out = run(svc.predict(msg([[1.0]])))
            puid = out.meta.puid
            spans = tracer.find(puid)
            names = {s.name for s in spans}
            assert "predictor.predict" in names
            assert "node.m.transform_input" in names
            for s in spans:
                assert s.duration_s >= 0
        finally:
            tracing._tracer = None

    def test_jsonl_export(self, tmp_path):
        path = str(tmp_path / "spans.jsonl")
        tracer = tracing.setup_tracing("test-svc", export_path=path)
        try:
            with tracer.span("op", trace_id="t1", foo="bar"):
                pass
            lines = [json.loads(l) for l in open(path)]
            assert lines[0]["traceId"] == "t1"
            assert lines[0]["tags"]["foo"] == "bar"
        finally:
            tracer.close()
            tracing._tracer = None

    def test_jsonl_export_keeps_parent_linkage(self, tmp_path):
        """Span.to_dict carries spanId/parentSpanId, so a trace
        reassembled from the JSONL file keeps the same tree the OTLP
        exporter ships — the file lane must not lose linkage."""
        path = str(tmp_path / "spans.jsonl")
        tracer = tracing.setup_tracing("test-svc", export_path=path)
        try:
            with tracer.span("parent", trace_id="t1") as parent:
                with tracer.span("child"):
                    pass
            by_name = {
                line["name"]: line
                for line in (json.loads(l) for l in open(path))
            }
            assert by_name["parent"]["spanId"] == parent.span_id
            assert by_name["parent"]["parentSpanId"] is None  # root
            # round-trip linkage: the child's parentSpanId resolves to
            # the parent's spanId within the same trace
            assert by_name["child"]["parentSpanId"] == by_name["parent"]["spanId"]
            assert by_name["child"]["traceId"] == by_name["parent"]["traceId"]
            assert by_name["child"]["spanId"] != by_name["parent"]["spanId"]
        finally:
            tracer.close()
            tracing._tracer = None


class TestRequestLogger:
    def test_pair_logged(self, tmp_path):
        path = str(tmp_path / "pairs.jsonl")
        svc = PredictorService(
            UnitSpec(name="m", type="MODEL", component=MetricModel()),
            request_logger=JsonlPairLogger(path),
        )
        run(svc.predict(msg([[3.0]])))
        pairs = [json.loads(l) for l in open(path)]
        assert len(pairs) == 1
        assert pairs[0]["request"]["data"]["tensor"]["values"] == [3.0]
        assert pairs[0]["response"]["data"]["tensor"]["values"] == [6.0]
        assert pairs[0]["puid"]


class TestRequestLogConsumer:
    """The consumer side of the pair stream (VERDICT r2 missing #3;
    reference: seldon-request-logger/app/app.py:15-60 indexes pairs
    into ES — here SQLite + the same CloudEvents ingestion surface)."""

    def test_predict_log_ingest_query_by_puid(self, tmp_path):
        """The full loop: predict -> pair logged -> indexed -> queryable."""
        from seldon_core_tpu.utils.reqconsumer import PairIndex

        path = str(tmp_path / "pairs.jsonl")
        svc = PredictorService(
            UnitSpec(name="m", type="MODEL", component=MetricModel()),
            request_logger=JsonlPairLogger(path),
        )
        out = run(svc.predict(msg([[3.0]])))
        puid = out.meta.puid
        index = PairIndex(str(tmp_path / "pairs.sqlite"))
        assert index.ingest_jsonl(path) == 1
        pair = index.get(puid)
        assert pair is not None
        assert pair["request"]["data"]["tensor"]["values"] == [3.0]
        assert pair["response"]["data"]["tensor"]["values"] == [6.0]
        assert index.get("no-such-puid") is None

    def test_http_pair_logger_to_consumer_e2e(self, tmp_path):
        """HttpPairLogger -> CloudEvents POST -> consumer app -> query:
        the reference's engine->logger wire, end to end over sockets."""
        import asyncio
        import time as _time

        from seldon_core_tpu.utils.reqconsumer import PairIndex, build_consumer_app
        from seldon_core_tpu.utils.reqlogger import HttpPairLogger

        async def scenario():
            from aiohttp.test_utils import TestClient, TestServer

            index = PairIndex()
            client = TestClient(TestServer(build_consumer_app(index)))
            await client.start_server()
            url = f"http://127.0.0.1:{client.port}/"

            svc = PredictorService(
                UnitSpec(name="m", type="MODEL", component=MetricModel()),
                request_logger=HttpPairLogger(url),
            )
            out = await svc.predict(msg([[4.0]]))
            # the logger posts from a background thread
            deadline = _time.time() + 10.0
            while index.count() < 1 and _time.time() < deadline:
                await asyncio.sleep(0.05)
            svc.request_logger.close()

            got = await client.get(f"/pairs/{out.meta.puid}")
            body = await got.json()
            listed = await client.get("/pairs", params={"limit": "10"})
            listing = await listed.json()
            stats = await (await client.get("/stats")).json()
            await client.close()
            return got.status, body, listing, stats

        status, body, listing, stats = run(scenario())
        assert status == 200
        assert body["response"]["data"]["tensor"]["values"] == [8.0]
        assert listing["count"] == 1
        assert stats["pairs"] == 1

    def test_deployment_annotation_wires_pair_logging(self, tmp_path):
        """`seldon.io/request-log-jsonl` on a deployment spec turns on
        pair logging declaratively (the reference's
        message.logging.service env wiring)."""
        import asyncio

        from seldon_core_tpu.controlplane import Deployer, TpuDeployment
        from seldon_core_tpu.utils.reqconsumer import PairIndex

        path = str(tmp_path / "pairs.jsonl")
        spec = TpuDeployment.from_dict({
            "name": "logged-dep",
            "annotations": {"seldon.io/request-log-jsonl": path},
            "predictors": [{
                "name": "main", "traffic": 100,
                "graph": {"name": "stub", "type": "MODEL",
                          "implementation": "SIMPLE_MODEL"},
            }],
        })

        async def scenario():
            deployer = Deployer(device_ids=[0])
            managed = await deployer.apply(spec)
            out = await managed.gateway.predict(msg([[1.0]]))
            await deployer.delete("logged-dep")
            return out.meta.puid

        puid = asyncio.run(scenario())
        index = PairIndex()
        assert index.ingest_jsonl(path) >= 1
        assert index.get(puid) is not None

    def test_query_filters_and_upsert(self):
        from seldon_core_tpu.utils.reqconsumer import PairIndex

        index = PairIndex()
        for i, (puid, predictor) in enumerate(
            [("p1", "main"), ("p2", "main"), ("p3", "canary")]
        ):
            index.ingest({
                "puid": puid, "time": 100.0 + i,
                "request": {"data": {"ndarray": [[i]]}},
                "response": {"meta": {"puid": puid, "tags": {"predictor": predictor}}},
            })
        assert index.count() == 3
        assert len(index.query(predictor="main", limit=10)) == 2
        assert len(index.query(since=101.5, limit=10)) == 1
        # re-ingesting the same puid upserts, never duplicates
        index.ingest({"puid": "p1", "time": 200.0,
                      "request": {}, "response": {"meta": {"puid": "p1"}}})
        assert index.count() == 3
        assert index.get("p1")["time"] == 200.0
        # a pair without any puid is rejected loudly
        import pytest as _pytest

        with _pytest.raises(ValueError):
            index.ingest({"request": {}, "response": {}})


class TestPairStamping:
    """r21 pair enrichment: every logged pair carries a W3C traceparent
    and the response's cost-ledger totals, so an indexer can pivot
    pair -> trace -> capture -> bill without a join table."""

    _TRACEPARENT = r"^00-[0-9a-f]{32}-[0-9a-f]{16}-01$"

    def _pair_msgs(self, puid="puid-abc", cost=None):
        import re  # noqa: F401 — used by callers via the class regex

        req = msg([[1.0]])
        resp = msg([[2.0]])
        resp.meta.puid = puid
        if cost is not None:
            resp.meta.tags["cost"] = cost
        return req, resp

    def test_traceparent_is_puid_derived_without_a_live_span(self):
        import re

        from seldon_core_tpu.utils.reqlogger import build_pair

        req, resp = self._pair_msgs()
        pair = build_pair(req, resp)
        assert re.match(self._TRACEPARENT, pair["traceparent"])
        # deterministic: the same puid always yields the same ids (the
        # OTLP exporter mints the same trace id, so the pivot holds)
        again = build_pair(*self._pair_msgs())
        assert again["traceparent"] == pair["traceparent"]
        other = build_pair(*self._pair_msgs(puid="puid-xyz"))
        assert other["traceparent"] != pair["traceparent"]

    def test_traceparent_uses_the_live_span_when_one_is_active(self):
        import re

        from seldon_core_tpu.utils.reqlogger import build_pair
        from seldon_core_tpu.utils.tracing import w3c_trace_id

        tracer = tracing.setup_tracing("pair-test")
        try:
            with tracer.span("op", trace_id="t-live") as span:
                pair = build_pair(*self._pair_msgs())
            assert re.match(self._TRACEPARENT, pair["traceparent"])
            assert pair["traceparent"] == \
                f"00-{w3c_trace_id('t-live')}-{span.span_id}-01"
        finally:
            tracing._tracer = None

    def test_cost_totals_ride_the_pair(self):
        from seldon_core_tpu.utils.reqlogger import build_pair

        cost = {"page_seconds": 0.25, "decode_tokens": 8, "adapter": "base"}
        pair = build_pair(*self._pair_msgs(cost=cost))
        assert pair["cost"] == cost
        # and a costless response (telemetry off) simply omits the key
        assert "cost" not in build_pair(*self._pair_msgs())


class TestHttpPairLoggerDrainClose:
    """Satellite 3: the buffered sink's failure modes — a full queue
    drops (counted, data plane never blocks), a dead collector loses
    pairs without raising, close() drains then joins."""

    def test_full_queue_drops_and_counts(self):
        from seldon_core_tpu.utils.reqlogger import HttpPairLogger

        lg = HttpPairLogger("http://127.0.0.1:9/", capacity=2)
        # wedge the drain thread by filling faster than a dead-URL POST
        # can fail: stop the thread first so the queue genuinely fills
        lg._queue.put(None)
        lg._thread.join(timeout=5.0)
        req, resp = msg([[1.0]]), msg([[2.0]])
        resp.meta.puid = "p"
        for _ in range(4):
            lg(req, resp)
        assert lg.dropped == 2  # capacity 2, four offered

    def test_dead_collector_never_raises_and_close_is_bounded(self):
        import time as _time

        from seldon_core_tpu.utils.reqlogger import HttpPairLogger

        # port 9 (discard) refuses immediately: the POST fails fast,
        # the drain loop logs and keeps going
        lg = HttpPairLogger("http://127.0.0.1:9/", capacity=8,
                            timeout_s=0.2)
        req, resp = msg([[1.0]]), msg([[2.0]])
        resp.meta.puid = "p"
        for _ in range(3):
            lg(req, resp)  # must not raise
        t0 = _time.monotonic()
        lg.close()
        assert _time.monotonic() - t0 < 5.0
        assert not lg._thread.is_alive()
        assert lg.dropped == 0  # failures are lost downstream, not drops


class TestGatewayRequestLogger:
    """Satellite 1: the gateway-level pair sink — one logger sees every
    FINALIZED pair (predictor tag already stamped) regardless of which
    predictor served, and a sink failure never loses a request."""

    def _gateway(self, request_logger):
        from seldon_core_tpu.engine.server import Gateway

        svc = PredictorService(
            UnitSpec(name="m", type="MODEL", component=MetricModel()),
            name="main",
        )
        return Gateway([(svc, 1.0)], request_logger=request_logger)

    def test_pairs_logged_after_finalize(self, tmp_path):
        import re

        path = str(tmp_path / "gw-pairs.jsonl")
        gw = self._gateway(JsonlPairLogger(path))
        out = run(gw.predict(msg([[3.0]])))
        pairs = [json.loads(l) for l in open(path)]
        assert len(pairs) == 1
        assert pairs[0]["puid"] == out.meta.puid
        # finalize ran first: the pair records WHO served it
        assert pairs[0]["response"]["meta"]["tags"]["predictor"] == "main"
        assert re.match(TestPairStamping._TRACEPARENT,
                        pairs[0]["traceparent"])

    def test_sink_failure_loses_the_pair_never_the_request(self):
        calls = []

        def broken_logger(request, response):
            calls.append(1)
            raise RuntimeError("sink down")

        gw = self._gateway(broken_logger)
        out = run(gw.predict(msg([[3.0]])))
        assert calls == [1]
        assert out.payload is not None  # the request still served

    def test_close_closes_the_sink(self):
        class ClosableSink:
            closed = False

            def __call__(self, request, response):
                pass

            def close(self):
                self.closed = True

        sink = ClosableSink()
        gw = self._gateway(sink)
        run(gw.close())
        assert sink.closed is True


class TestGatewayLoggerAnnotation:
    """`seldon.io/request-logger` resolves to a sink by spec shape:
    http(s) URL, kafka:brokers/topic, else a JSONL path."""

    def _resolve(self, spec):
        from seldon_core_tpu.controlplane.deployer import (
            _gateway_logger_from_annotations,
        )

        return _gateway_logger_from_annotations(
            {} if spec is None else {"seldon.io/request-logger": spec}
        )

    def test_unset_is_none(self):
        assert self._resolve(None) is None
        assert self._resolve("") is None

    def test_http_url_builds_http_sink(self):
        from seldon_core_tpu.utils.reqlogger import HttpPairLogger

        lg = self._resolve("http://collector:8080/")
        try:
            assert isinstance(lg, HttpPairLogger)
            assert lg.url == "http://collector:8080/"
        finally:
            lg.close()

    def test_kafka_spec_builds_kafka_sink(self):
        from seldon_core_tpu.utils.reqlogger import KafkaPairLogger

        lg = self._resolve("kafka:b1:9092,b2:9092/pairs")
        try:
            assert isinstance(lg, KafkaPairLogger)
            assert lg.topic == "pairs"
        finally:
            lg.close(timeout_s=1.0)

    def test_malformed_kafka_spec_fails_loudly(self):
        from seldon_core_tpu.controlplane.deployer import DeploymentSpecError

        with pytest.raises(DeploymentSpecError, match="kafka"):
            self._resolve("kafka:no-topic-here")

    def test_anything_else_is_a_jsonl_path(self, tmp_path):
        from seldon_core_tpu.utils.reqlogger import JsonlPairLogger as JPL

        lg = self._resolve(str(tmp_path / "x.jsonl"))
        assert isinstance(lg, JPL)

    def test_deployment_annotation_wires_gateway_logger(self, tmp_path):
        """End to end through the deployer: the annotation lands on the
        GATEWAY (not the per-predictor graph lane) and every served
        request leaves a stamped pair."""
        import re

        from seldon_core_tpu.controlplane import Deployer, TpuDeployment

        path = str(tmp_path / "gw.jsonl")
        spec = TpuDeployment.from_dict({
            "name": "gw-logged-dep",
            "annotations": {"seldon.io/request-logger": path},
            "predictors": [{
                "name": "main", "traffic": 100,
                "graph": {"name": "stub", "type": "MODEL",
                          "implementation": "SIMPLE_MODEL"},
            }],
        })

        async def scenario():
            deployer = Deployer(device_ids=[0])
            managed = await deployer.apply(spec)
            assert isinstance(managed.gateway.request_logger,
                              JsonlPairLogger)
            out = await managed.gateway.predict(msg([[1.0]]))
            await deployer.delete("gw-logged-dep")
            return out.meta.puid

        puid = asyncio.run(scenario())
        pairs = [json.loads(l) for l in open(path)]
        assert [p["puid"] for p in pairs] == [puid]
        assert re.match(TestPairStamping._TRACEPARENT,
                        pairs[0]["traceparent"])
        assert pairs[0]["response"]["meta"]["tags"]["predictor"] == "main"


class TestMonitoringAssets:
    """The shipped prometheus/alertmanager/grafana configs stay coherent
    with the metric names the code emits (reference analogue: the
    seldon-core-analytics chart's rules + dashboards)."""

    MONITORING = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "monitoring")

    def _load(self, name):
        import yaml

        with open(os.path.join(self.MONITORING, name)) as f:
            return yaml.safe_load(f)

    def test_alert_rules_parse_and_reference_emitted_metrics(self):
        rules = self._load("alert-rules.yml")
        exprs = " ".join(
            r["expr"] for g in rules["groups"] for r in g["rules"]
        )
        # metric families that PrometheusObserver and the detectors emit
        for metric in (
            "seldon_api_engine_server_requests_duration_seconds",
            "seldon_api_engine_client_requests_duration_seconds",
            "seldon_api_model_feedback",
            "outliers_total",
            # generation lane (StreamingLM/SpeculativeLM metrics())
            "paged_pool_utilization",
            "paged_evictions",
            "speculative_acceptance_rate",
            # per-hop transport telemetry (engine -> node clients, r8)
            "seldon_tpu_transport_errors_total",
            "seldon_tpu_transport_requests_total",
            "seldon_tpu_transport_retries_total",
            # the recompile sentinel (utils/jitwatch.py)
            "seldon_tpu_jit_compiles_total",
        ):
            assert metric in exprs, f"alert rules no longer cover {metric}"
        names = {r["alert"] for g in rules["groups"] for r in g["rules"]}
        assert "TransportErrorBudgetBurn" in names
        for g in rules["groups"]:
            for r in g["rules"]:
                assert r["labels"]["severity"] in ("info", "warning", "critical")
                assert "summary" in r["annotations"]

    def test_prometheus_config_wires_rules_and_alertmanager(self):
        prom = self._load("prometheus.yml")
        assert "alert-rules.yml" in prom["rule_files"]
        targets = prom["alerting"]["alertmanagers"][0]["static_configs"][0]["targets"]
        assert targets == ["localhost:9093"]

    def test_alertmanager_routes_and_inhibition(self):
        am = self._load("alertmanager.yml")
        names = {r["name"] for r in am["receivers"]}
        assert am["route"]["receiver"] in names
        for route in am["route"].get("routes", []):
            assert route["receiver"] in names
        assert am["inhibit_rules"]

    def test_dashboards_parse_and_use_emitted_metrics(self):
        import json

        gdir = os.path.join(self.MONITORING, "grafana")
        dashboards = [f for f in os.listdir(gdir) if f.endswith(".json")]
        # predictions + outliers + generation (reference ships several)
        assert len(dashboards) >= 3
        emitted_families = (
            "seldon_api",
            "outliers_total",
            "paged_",
            "speculative_",
            "seldon_tpu_fleet_",
        )
        for name in dashboards:
            with open(os.path.join(gdir, name)) as f:
                dash = json.load(f)
            assert dash["panels"], name
            exprs = " ".join(
                t["expr"] for p in dash["panels"] for t in p.get("targets", [])
            )
            assert any(fam in exprs for fam in emitted_families), name

    def test_predictions_dashboard_covers_transport_telemetry(self):
        import json

        with open(os.path.join(self.MONITORING, "grafana", "predictions-dashboard.json")) as f:
            dash = json.load(f)
        exprs = " ".join(
            t["expr"] for p in dash["panels"] for t in p.get("targets", [])
        )
        for metric in (
            "seldon_tpu_transport_requests_total",
            "seldon_tpu_transport_errors_total",
            "seldon_tpu_transport_network_seconds",
            "seldon_tpu_transport_serialize_seconds",
            "seldon_tpu_transport_request_bytes_total",
            "seldon_tpu_transport_inflight",
            "seldon_tpu_transport_retries_total",
            "seldon_tpu_jit_compiles_total",
        ):
            assert metric in exprs, f"predictions dashboard lost {metric}"

    def test_generation_dashboard_covers_engine_stats(self):
        import json

        with open(os.path.join(self.MONITORING, "grafana", "generation-dashboard.json")) as f:
            dash = json.load(f)
        exprs = " ".join(
            t["expr"] for p in dash["panels"] for t in p.get("targets", [])
        )
        for metric in ("paged_pool_utilization", "paged_tokens_emitted",
                       "paged_stall_events", "speculative_acceptance_rate"):
            assert metric in exprs, metric


class TestOtlpExporter:
    """OTLP/HTTP JSON export (Jaeger >=1.35 / otel-collector :4318
    ingest) emitted with the stdlib — no opentelemetry-sdk."""

    def _collector(self):
        import http.server
        import threading

        received = []

        class Handler(http.server.BaseHTTPRequestHandler):
            def do_POST(self):
                body = self.rfile.read(int(self.headers["Content-Length"]))
                received.append((self.path, json.loads(body)))
                self.send_response(200)
                self.end_headers()
                self.wfile.write(b"{}")

            def log_message(self, *a):
                pass

        srv = http.server.HTTPServer(("127.0.0.1", 0), Handler)
        threading.Thread(target=srv.serve_forever, daemon=True).start()
        return srv, received

    def test_spans_ship_in_otlp_shape(self):
        from seldon_core_tpu.utils.tracing import OtlpHttpExporter, Tracer

        srv, received = self._collector()
        try:
            exporter = OtlpHttpExporter(
                endpoint=f"http://127.0.0.1:{srv.server_port}/v1/traces",
                service_name="svc-x",
                batch_size=2,
            )
            tracer = Tracer(exporter=exporter)
            with tracer.span("predictor.predict", trace_id="puid-1", model="m1"):
                # nested span: parent linkage comes from the contextvar
                # stack, the way the engine's node spans nest in practice
                with tracer.span("node.transform_input"):
                    pass
            # batch_size=2 -> one POST fired (on the export worker)
            exporter.flush()
            assert len(received) == 1
            path, body = received[0]
            assert path == "/v1/traces"
            rs = body["resourceSpans"][0]
            svc_attr = rs["resource"]["attributes"][0]
            assert svc_attr == {"key": "service.name", "value": {"stringValue": "svc-x"}}
            spans = rs["scopeSpans"][0]["spans"]
            # child closes (and records) first
            spans.sort(key=lambda x: x["name"])
            assert [s["name"] for s in spans] == ["node.transform_input", "predictor.predict"]
            spans.reverse()  # [parent, child]
            # same puid -> same 32-hex traceId; child links its parent
            assert spans[0]["traceId"] == spans[1]["traceId"]
            assert len(spans[0]["traceId"]) == 32 and len(spans[0]["spanId"]) == 16
            # the child inherited the trace and links the parent's real id
            assert spans[1]["parentSpanId"] == spans[0]["spanId"]
            assert spans[1]["spanId"] != spans[0]["spanId"]
            assert int(spans[0]["endTimeUnixNano"]) >= int(spans[0]["startTimeUnixNano"])
            assert exporter.exported == 2
        finally:
            srv.shutdown()

    def test_collector_down_never_raises(self):
        from seldon_core_tpu.utils.tracing import OtlpHttpExporter, Span

        exporter = OtlpHttpExporter(endpoint="http://127.0.0.1:1/v1/traces", timeout_s=0.2)
        assert exporter.export([Span(trace_id="t", name="n", start_s=0.0)]) is False
        assert exporter.failures == 1
        exporter.close()

    def test_full_queue_drops_oldest_and_counts(self):
        """A blackholed collector must not grow memory without limit:
        the export queue is bounded, overflow sheds the OLDEST batch,
        and the loss lands in the `dropped` counter."""
        import threading

        from seldon_core_tpu.utils.tracing import OtlpHttpExporter, Span

        release = threading.Event()
        exporter = OtlpHttpExporter(
            endpoint="http://127.0.0.1:1/v1/traces",
            batch_size=1, max_queue_batches=2, timeout_s=0.2,
        )
        # wedge the worker inside its current batch: every batch after
        # the in-flight one piles into the bounded queue
        orig_export = exporter.export
        first = threading.Event()

        def blocked_export(spans):
            first.set()
            release.wait(timeout=10)
            return orig_export(spans)

        exporter.export = blocked_export
        try:
            exporter(Span(trace_id="t", name="s0", start_s=0.0))
            assert first.wait(timeout=5)  # worker is now wedged
            for i in range(1, 8):  # 7 more batches into a queue of 2
                exporter(Span(trace_id="t", name=f"s{i}", start_s=0.0))
            assert exporter._queue.qsize() <= 2  # bounded under load
            assert exporter.dropped == 5  # 7 offered - 2 retained
        finally:
            release.set()
            exporter.close()

    def test_unwedged_exporter_drops_nothing(self):
        from seldon_core_tpu.utils.tracing import OtlpHttpExporter, Span

        srv, received = self._collector()
        try:
            exporter = OtlpHttpExporter(
                endpoint=f"http://127.0.0.1:{srv.server_port}/v1/traces",
                batch_size=1,  # default queue bound: 8 batches fit easily
            )
            for i in range(8):
                exporter(Span(trace_id="t", name=f"s{i}", start_s=0.0))
            exporter.flush()
            assert exporter.dropped == 0
            assert exporter.exported == 8
        finally:
            srv.shutdown()

    def test_setup_tracing_env_wiring(self, monkeypatch):
        from seldon_core_tpu.utils import tracing

        srv, received = self._collector()
        try:
            monkeypatch.setenv(
                "OTEL_EXPORTER_OTLP_ENDPOINT", f"http://127.0.0.1:{srv.server_port}"
            )
            tracer = tracing.setup_tracing(service_name="env-svc")
            assert tracer.exporter is not None
            assert tracer.exporter.endpoint.endswith("/v1/traces")
            with tracer.span("op", trace_id="p"):
                pass
            tracer.close()  # flushes the partial batch
            assert len(received) == 1
        finally:
            srv.shutdown()
            tracing._tracer = None


class FakeKafkaBroker:
    """In-repo Kafka broker speaking Metadata v0 + Produce v0 over a
    real socket (the reference ships a runnable cluster, kafka/
    kafka.json:1-30; this is the no-egress stand-in).  Decoding here is
    written INDEPENDENTLY of utils/kafka.py's encoder — struct-level,
    CRC re-verified — so the contract test catches a wrong frame on
    either side rather than a shared bug cancelling out."""

    def __init__(self, partitions: int = 2):
        import socket

        self.partitions = partitions
        self.records = []  # (topic, partition, key, value)
        self.produce_frames = []  # raw produce request payloads
        self._srv = socket.socket()
        self._srv.bind(("127.0.0.1", 0))
        self._srv.listen(4)
        self.port = self._srv.getsockname()[1]
        self._running = True
        self._thread = threading.Thread(target=self._serve, daemon=True)
        self._thread.start()

    # ---- wire helpers (independent decode) --------------------------------

    @staticmethod
    def _rd_str(buf, off):
        (n,) = struct.unpack_from(">h", buf, off)
        off += 2
        if n < 0:
            return None, off
        return buf[off:off + n].decode(), off + n

    @staticmethod
    def _wr_str(s):
        b = s.encode()
        return struct.pack(">h", len(b)) + b

    def _serve(self):
        while self._running:
            try:
                conn, _ = self._srv.accept()
            except OSError:
                return
            threading.Thread(target=self._handle, args=(conn,), daemon=True).start()

    def _recv_exact(self, conn, n):
        out = b""
        while len(out) < n:
            chunk = conn.recv(n - len(out))
            if not chunk:
                return None
            out += chunk
        return out

    def _handle(self, conn):
        try:
            while True:
                head = self._recv_exact(conn, 4)
                if head is None:
                    return
                (size,) = struct.unpack(">i", head)
                payload = self._recv_exact(conn, size)
                if payload is None:
                    return
                api_key, api_version, corr = struct.unpack_from(">hhi", payload, 0)
                _client, off = self._rd_str(payload, 8)
                assert api_version == 0, f"broker only speaks v0, got {api_version}"
                if api_key == 3:
                    resp = self._metadata_response(payload, off)
                elif api_key == 0:
                    resp = self._produce_response(payload, off)
                else:
                    return
                frame = struct.pack(">i", corr) + resp
                conn.sendall(struct.pack(">i", len(frame)) + frame)
        except (ConnectionError, OSError):
            return
        finally:
            conn.close()

    def _metadata_response(self, buf, off):
        (n_topics,) = struct.unpack_from(">i", buf, off)
        off += 4
        names = []
        for _ in range(n_topics):
            name, off = self._rd_str(buf, off)
            names.append(name)
        out = struct.pack(">i", 1)  # one broker
        out += struct.pack(">i", 0) + self._wr_str("127.0.0.1") + struct.pack(">i", self.port)
        out += struct.pack(">i", len(names))
        for name in names:
            parts = b""
            for p in range(self.partitions):
                parts += struct.pack(">hii", 0, p, 0)  # err, id, leader=node 0
                parts += struct.pack(">ii", 1, 0)      # replicas [0]
                parts += struct.pack(">ii", 1, 0)      # isr [0]
            out += struct.pack(">h", 0) + self._wr_str(name)
            out += struct.pack(">i", self.partitions) + parts
        return out

    def _produce_response(self, buf, off):
        import zlib

        self.produce_frames.append(buf)
        _acks, _timeout = struct.unpack_from(">hi", buf, off)
        off += 6
        (n_topics,) = struct.unpack_from(">i", buf, off)
        off += 4
        resp_topics = b""
        for _ in range(n_topics):
            topic, off = self._rd_str(buf, off)
            (n_parts,) = struct.unpack_from(">i", buf, off)
            off += 4
            parts_resp = b""
            for _ in range(n_parts):
                partition, mset_size = struct.unpack_from(">ii", buf, off)
                off += 8
                end = off + mset_size
                base = len(self.records)
                while off + 12 <= end:
                    _offset, msize = struct.unpack_from(">qi", buf, off)
                    off += 12
                    (crc,) = struct.unpack_from(">I", buf, off)
                    body = buf[off + 4:off + msize]
                    off += msize
                    assert zlib.crc32(body) & 0xFFFFFFFF == crc, "CRC mismatch"
                    magic, _attrs = struct.unpack_from(">bb", body, 0)
                    assert magic == 0
                    (klen,) = struct.unpack_from(">i", body, 2)
                    p = 6
                    key = None
                    if klen >= 0:
                        key = body[p:p + klen]
                        p += klen
                    (vlen,) = struct.unpack_from(">i", body, p)
                    p += 4
                    value = body[p:p + vlen]
                    self.records.append((topic, partition, key, value))
                parts_resp += struct.pack(">ihq", partition, 0, base)
            resp_topics += self._wr_str(topic) + struct.pack(">i", n_parts) + parts_resp
        return struct.pack(">i", n_topics) + resp_topics

    def close(self):
        self._running = False
        self._srv.close()


class TestKafkaPairLogger:
    """The Kafka lane produced to a (fake) broker over a real socket:
    wire frames byte-verified broker-side (VERDICT r4 missing #3 —
    the lane had never produced to anything)."""

    def test_pairs_stream_to_topic_over_the_wire(self):
        from seldon_core_tpu.runtime.message import InternalMessage
        from seldon_core_tpu.utils.reqlogger import KafkaPairLogger

        broker = FakeKafkaBroker(partitions=2)
        try:
            logger = KafkaPairLogger(f"127.0.0.1:{broker.port}", topic="pairs")
            req = InternalMessage(payload=np.asarray([[1.0, 2.0]]), kind="ndarray")
            req.meta.puid = "p-1"
            logger(req, req.with_payload(np.asarray([[0.9]])))
            logger.close()  # drains the queue, so the send has landed
            assert logger.sent == 1 and logger.dropped == 0
            assert len(broker.records) == 1
            topic, partition, key, value = broker.records[0]
            assert topic == "pairs"
            assert 0 <= partition < 2
            assert key == b"p-1"  # puid-keyed -> stable partition
            pair = json.loads(value)
            assert pair["request"]["data"]["ndarray"] == [[1.0, 2.0]]
            assert pair["response"]["data"]["ndarray"] == [[0.9]]
            assert pair["puid"] == "p-1"
            # byte-level: the produce frame carries v0 framing
            assert any(b"pairs" in f for f in broker.produce_frames)
        finally:
            broker.close()

    def test_puid_keys_pin_partition(self):
        from seldon_core_tpu.runtime.message import InternalMessage
        from seldon_core_tpu.utils.reqlogger import KafkaPairLogger

        broker = FakeKafkaBroker(partitions=4)
        try:
            logger = KafkaPairLogger(f"127.0.0.1:{broker.port}", topic="t")
            req = InternalMessage(payload=np.asarray([[1.0]]), kind="ndarray")
            req.meta.puid = "same-puid"
            for _ in range(3):
                logger(req, req.with_payload(np.asarray([[2.0]])))
            logger.close()
            parts = {p for (_, p, _, _) in broker.records}
            assert len(broker.records) == 3 and len(parts) == 1
        finally:
            broker.close()

    def test_multi_broker_bootstrap_falls_through_dead_entries(self):
        """Standard 'b1:9092,b2:9092' bootstrap lists parse, and an
        unreachable first broker falls through to a live one."""
        from seldon_core_tpu.utils.kafka import MiniKafkaProducer

        broker = FakeKafkaBroker(partitions=1)
        try:
            p = MiniKafkaProducer(
                f"127.0.0.1:1,127.0.0.1:{broker.port}", timeout_s=1.0
            )
            assert p.send("t", b"v") == 0
            p.close()
        finally:
            broker.close()

    def test_producer_reconnects_after_connection_drop(self):
        """A dead connection is dropped (with the metadata cache) and
        the next send reconnects — one broker hiccup must not kill the
        logging lane for the process lifetime."""
        from seldon_core_tpu.utils.kafka import MiniKafkaProducer

        broker = FakeKafkaBroker(partitions=1)
        try:
            p = MiniKafkaProducer(f"127.0.0.1:{broker.port}", timeout_s=1.0)
            assert p.send("t", b"one") == 0
            # sever every live connection under the producer
            for sock in list(p._conns.values()):
                sock.close()
            p._conns.clear()  # simulate the post-error _drop state
            assert p.send("t", b"two") >= 0
            assert [v for (_, _, _, v) in broker.records] == [b"one", b"two"]
            p.close()
        finally:
            broker.close()

    def test_broker_outage_is_counted_not_silent(self):
        """Pairs lost to a dead broker must show in the counters, not
        only in a warning log line.  (The outage is a never-listening
        port: closing a FakeKafkaBroker mid-accept leaves CPython's
        deferred-fd-close serving one more connection.)"""
        from seldon_core_tpu.runtime.message import InternalMessage
        from seldon_core_tpu.utils.reqlogger import KafkaPairLogger

        import socket

        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
        s.close()  # nothing ever listens here
        logger = KafkaPairLogger(f"127.0.0.1:{port}", topic="t", timeout_s=0.5)
        req = InternalMessage(payload=np.asarray([[1.0]]), kind="ndarray")
        req.meta.puid = "p"
        logger(req, req.with_payload(np.asarray([[2.0]])))
        logger.close()
        assert logger.failed == 1 and logger.sent == 0

    def test_close_is_bounded_with_full_queue_and_stuck_broker(self, monkeypatch):
        """Shutdown must not hang when the queue is full AND the broker
        is wedged mid-send: the old blocking put(None) waited for queue
        room that a stuck drain thread would never free.  close() now
        signals a stop flag with a deadline and returns."""
        import time as _time

        from seldon_core_tpu.runtime.message import InternalMessage
        from seldon_core_tpu.utils.reqlogger import KafkaPairLogger

        broker = FakeKafkaBroker(partitions=1)
        try:
            logger = KafkaPairLogger(
                f"127.0.0.1:{broker.port}", topic="t", capacity=1
            )
            # wedge the producer: every send blocks far past the test
            monkeypatch.setattr(
                logger._producer, "send",
                lambda *a, **k: _time.sleep(30),
            )
            req = InternalMessage(payload=np.asarray([[1.0]]), kind="ndarray")
            req.meta.puid = "p"
            # first pair occupies the drain thread inside the stuck
            # send; the second fills the capacity-1 queue
            logger(req, req.with_payload(np.asarray([[2.0]])))
            deadline = _time.monotonic() + 2.0
            while logger._queue.qsize() > 0 and _time.monotonic() < deadline:
                _time.sleep(0.01)  # wait for the drain thread to pick up #1
            logger(req, req.with_payload(np.asarray([[2.0]])))
            assert logger._queue.full()
            t0 = _time.monotonic()
            logger.close(timeout_s=0.5)
            assert _time.monotonic() - t0 < 5.0  # bounded, not wedged
        finally:
            broker.close()

    def test_close_still_flushes_pending_pairs(self):
        """The bounded close keeps the old flush semantics when the
        broker is healthy: pairs enqueued before close() land."""
        from seldon_core_tpu.runtime.message import InternalMessage
        from seldon_core_tpu.utils.reqlogger import KafkaPairLogger

        broker = FakeKafkaBroker(partitions=1)
        try:
            logger = KafkaPairLogger(f"127.0.0.1:{broker.port}", topic="t")
            req = InternalMessage(payload=np.asarray([[1.0]]), kind="ndarray")
            req.meta.puid = "p"
            for _ in range(5):
                logger(req, req.with_payload(np.asarray([[2.0]])))
            logger.close()
            assert logger.sent == 5 and len(broker.records) == 5
        finally:
            broker.close()

    def test_producer_roundtrip_primitives(self):
        """encode/decode of the v0 message set are inverses and CRC'd
        (the recorded-bytes half of the contract)."""
        from seldon_core_tpu.utils.kafka import decode_message_set, encode_message_set

        mset = encode_message_set(b"k", b"v" * 100)
        assert decode_message_set(mset) == [(b"k", b"v" * 100)]
        corrupted = mset[:-1] + bytes([mset[-1] ^ 0xFF])
        with pytest.raises(ValueError, match="CRC"):
            decode_message_set(corrupted)


class TestHistogramQuantileSamplerEdges:
    """Edge cases of the windowed-quantile estimate the autoscaler
    consumes: a counter reset must not interpolate garbage from
    negative deltas, and all-traffic-in-+Inf must return the last
    finite bound rather than inf/nonsense."""

    def _sampler(self, quantile=0.95):
        import prometheus_client as prom

        from seldon_core_tpu.utils.metrics import HistogramQuantileSampler

        registry = prom.CollectorRegistry()
        hist = prom.Histogram(
            "edge_hist", "t", registry=registry,
            buckets=(0.1, 1.0, 10.0),
        )
        return hist, HistogramQuantileSampler(hist, quantile=quantile)

    def test_counter_reset_returns_zero_then_recovers(self):
        hist, sampler = self._sampler()
        for _ in range(20):
            hist.observe(0.05)
        sampler()  # prime the window
        for _ in range(10):
            hist.observe(0.05)
        assert sampler() > 0.0
        # counter reset: the previous sample claims MORE cumulative
        # traffic than the live histogram now shows (process restart /
        # histogram re-registration) -> negative deltas
        sampler._last = [c + 1000.0 for c in sampler._last]
        got = sampler()
        assert got == 0.0  # no garbage (pre-guard this interpolated junk)
        # and the very next window is healthy again
        for _ in range(10):
            hist.observe(0.05)
        recovered = sampler()
        assert 0.0 < recovered <= 0.1

    def test_all_traffic_in_inf_bucket_returns_last_finite_bound(self):
        hist, sampler = self._sampler()
        sampler()  # prime
        for _ in range(50):
            hist.observe(99.0)  # beyond every finite bucket bound
        got = sampler()
        assert got == 10.0  # the last finite bound, never inf or 0

    def test_empty_window_stays_zero(self):
        _hist, sampler = self._sampler()
        assert sampler() == 0.0
        assert sampler() == 0.0


class TestJitSentinel:
    """utils/jitwatch.py: the first call per distinct argument-shape
    signature is a compile event — counted and WARNed; repeat shapes
    are free of both."""

    def test_counts_once_per_signature_and_warns(self, caplog):
        import logging

        import prometheus_client as prom

        from seldon_core_tpu.utils.jitwatch import JitSentinel

        import jax
        import jax.numpy as jnp

        sentinel = JitSentinel("test_prog_sig")
        fn = sentinel.wrap(jax.jit(lambda x: x * 2), static="variant=a")
        before = prom.REGISTRY.get_sample_value(
            "seldon_tpu_jit_compiles_total", {"program": "test_prog_sig"}
        ) or 0.0
        with caplog.at_level(logging.WARNING, logger="seldon_core_tpu.utils.jitwatch"):
            fn(jnp.zeros((2, 2)))
            fn(jnp.ones((2, 2)))   # same signature: no new compile
            fn(jnp.zeros((4, 4)))  # new shape: compile event
        assert sentinel.compiles == 2
        after = prom.REGISTRY.get_sample_value(
            "seldon_tpu_jit_compiles_total", {"program": "test_prog_sig"}
        )
        assert after - before == 2.0
        warns = [r for r in caplog.records if "jit compile" in r.getMessage()]
        assert len(warns) == 2
        # the WARN names the program AND the triggering signature
        assert "test_prog_sig" in warns[0].getMessage()
        assert "(2, 2)" in warns[0].getMessage()
        assert "(4, 4)" in warns[1].getMessage()

    def test_static_key_separates_variants(self):
        from seldon_core_tpu.utils.jitwatch import JitSentinel

        import jax
        import jax.numpy as jnp

        sentinel = JitSentinel("test_prog_static")
        a = sentinel.wrap(jax.jit(lambda x: x + 1), static="steps=8")
        b = sentinel.wrap(jax.jit(lambda x: x + 2), static="steps=16")
        a(jnp.zeros((2,)))
        b(jnp.zeros((2,)))  # same array shape, distinct static key
        assert sentinel.compiles == 2

    def test_kill_switch_returns_fn_unwrapped(self, monkeypatch):
        from seldon_core_tpu.utils.jitwatch import JitSentinel

        monkeypatch.setenv("SELDON_TPU_JIT_SENTINEL", "0")
        sentinel = JitSentinel("test_prog_off")
        fn = lambda x: x  # noqa: E731
        assert sentinel.wrap(fn) is fn

    def test_engine_stats_exposes_summed_compiles(self):
        """PagedEngine wires sentinels on its chunk/prefill programs and
        engine_stats carries the sum (bridge-excluded: jitwatch exports
        the per-program split itself)."""
        import numpy as np

        import jax
        import jax.numpy as jnp

        from seldon_core_tpu.models.paged import PagedEngine
        from seldon_core_tpu.models.transformer import TransformerLM

        lm = TransformerLM(vocab_size=256, d_model=64, num_layers=1,
                           num_heads=4, max_len=128, dtype=jnp.float32)
        params = lm.init(jax.random.key(0), jnp.zeros((1, 8), jnp.int32))["params"]
        eng = PagedEngine(
            params, vocab_size=256, d_model=64, num_layers=1, num_heads=4,
            max_len=128, page_size=16, max_slots=2, steps_per_call=4,
            dtype=jnp.float32,
        )
        try:
            assert eng.engine_stats()["jit_compiles"] == 0
            eng.submit(np.arange(8, dtype=np.int32), max_new_tokens=4)
            while eng.has_work():
                eng.step()
            # at least the prefill + one chunk program compiled
            assert eng.engine_stats()["jit_compiles"] >= 2
        finally:
            eng.close()


class TestSharedRegistryObservers:
    def test_two_observers_one_registry_no_duplicate_timeseries(self):
        """Two predictors of one deployment (or a rolling re-apply)
        share the process registry; metric objects must be shared, with
        only label values differing."""
        import prometheus_client as prom

        from seldon_core_tpu.utils.metrics import PrometheusObserver, api_latency_sampler

        registry = prom.CollectorRegistry()
        a = PrometheusObserver("dep", "main", registry=registry)
        b = PrometheusObserver("dep", "canary", registry=registry)
        # both paths that register metrics must not collide
        a("predict_done", "m", 0.01)
        b("predict_done", "m", 0.02)
        sampler_a = api_latency_sampler(a)
        sampler_b = api_latency_sampler(b)
        sampler_a(), sampler_b()  # prime both without raising
        for _ in range(10):
            a("predict_done", "m", 0.2)
        assert sampler_a() > 0.0
        assert sampler_b() == 0.0  # canary saw no traffic
