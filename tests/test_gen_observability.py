"""Generation-engine observability (r7): per-request lifecycle spans
linked into the request trace, the per-chunk flight recorder, and the
Prometheus bridge's complete-by-contract mapping of engine_stats().

Fast tier: one tiny engine (the test_paged_smoke config) pays the only
compiles; everything else is host-side.
"""

import json
import os

import numpy as np
import pytest

import jax.numpy as jnp

from seldon_core_tpu.utils import tracing
from seldon_core_tpu.utils.flightrec import FlightRecorder


CFG = dict(vocab_size=64, d_model=32, num_layers=1, num_heads=2, max_len=128)


def _tiny_engine(**kw):
    import jax

    from seldon_core_tpu.models.paged import PagedEngine
    from seldon_core_tpu.models.transformer import TransformerLM

    lm = TransformerLM(dtype=jnp.float32, **CFG)
    params = lm.init(jax.random.key(0), jnp.zeros((1, 4), jnp.int32))["params"]
    base = dict(dtype=jnp.float32, page_size=8, max_slots=2, steps_per_call=4)
    base.update(kw)
    return PagedEngine(params, **CFG, **base)


class TestLifecycleSpans:
    """The r7 acceptance criterion: ONE trace for one generation
    request carries the engine-level request span AND the gen.*
    lifecycle spans, linked via puid (trace_id) + parent_span_id."""

    def test_gen_spans_link_to_request_span_by_puid_and_parent(self):
        tracer = tracing.setup_tracing("gen-obs-test")
        eng = _tiny_engine()
        try:
            with tracer.span("microservice.predict", trace_id="puid-7") as root:
                stream = eng.submit(
                    np.arange(5, dtype=np.int32) % 64, max_new_tokens=6
                )
            eng.run()
            assert stream.error is None
            spans = {s.name: s for s in tracer.find("puid-7")}
            # the engine-level request span plus the full lifecycle
            for name in ("microservice.predict", "gen.queued",
                         "gen.prefill", "gen.decode", "gen.finish"):
                assert name in spans, f"missing {name} in trace"
            for name in ("gen.queued", "gen.prefill", "gen.decode",
                         "gen.finish"):
                s = spans[name]
                assert s.trace_id == "puid-7"  # puid linkage
                assert s.parent_span_id == root.span_id  # span linkage
                assert s.tags["puid"] == "puid-7"
                assert s.duration_s >= 0.0
            assert spans["gen.prefill"].tags["prompt_len"] == 5
            assert spans["gen.finish"].tags["tokens"] == 6
            assert spans["gen.queued"].tags["queue_depth"] == 0
        finally:
            eng.close()
            tracing._tracer = None

    def test_no_tracer_no_spans_no_cost(self):
        eng = _tiny_engine()
        try:
            stream = eng.submit(np.ones(3, np.int32), max_new_tokens=4)
            assert stream.trace_id == ""  # linkage never captured
            eng.run()
            assert stream.error is None
        finally:
            eng.close()

    def test_explicit_trace_id_wins_over_context(self):
        tracer = tracing.setup_tracing("gen-obs-test2")
        eng = _tiny_engine()
        try:
            stream = eng.submit(
                np.ones(3, np.int32), max_new_tokens=4, trace_id="req-x",
            )
            eng.run()
            assert stream.error is None
            names = {s.name for s in tracer.find("req-x")}
            assert {"gen.queued", "gen.prefill", "gen.decode",
                    "gen.finish"} <= names
        finally:
            eng.close()
            tracing._tracer = None


class TestFlightRecorder:
    def test_ring_is_bounded_and_seq_monotonic(self):
        rec = FlightRecorder(capacity=4)
        for i in range(10):
            rec.record({"wall_ms": float(i), "queue_depth": i})
        snap = rec.snapshot()
        assert len(snap) == 4
        assert [r["seq"] for r in snap] == [7, 8, 9, 10]
        assert rec.stats()["records"] == 4
        assert rec.stats()["last_queue_depth"] == 9

    def test_since_consumes_incrementally(self):
        rec = FlightRecorder(capacity=8)
        for i in range(3):
            rec.record({"wall_ms": 1.0})
        assert len(rec.since(0)) == 3
        assert len(rec.since(3)) == 0
        rec.record({"wall_ms": 2.0})
        got = rec.since(3)
        assert len(got) == 1 and got[0]["seq"] == 4

    def test_dump_on_breach_writes_jsonl_with_cooldown(self, tmp_path):
        clock = [1000.0]
        rec = FlightRecorder(
            capacity=16, dump_p99_ms=50.0, dump_dir=str(tmp_path),
            dump_cooldown_s=30.0, clock=lambda: clock[0],
        )
        for _ in range(10):
            rec.record({"wall_ms": 1.0})
        assert rec.dumps == 0  # fast chunks: no breach check even runs
        rec.record({"wall_ms": 99.0})  # p99 of the window now breaches
        assert rec.dumps == 1
        lines = [json.loads(l) for l in open(rec.last_dump_path)]
        assert len(lines) == 11
        assert lines[-1]["wall_ms"] == 99.0
        # cooldown: a sustained breach produces one dump per window
        rec.record({"wall_ms": 120.0})
        assert rec.dumps == 1
        clock[0] += 31.0
        rec.record({"wall_ms": 120.0})
        assert rec.dumps == 2

    def test_quantile_and_manual_dump(self, tmp_path):
        rec = FlightRecorder(capacity=128)
        for i in range(100):
            rec.record({"wall_ms": float(i + 1)})
        assert rec.quantile_ms(0.5) == pytest.approx(51.0, abs=2)
        assert rec.quantile_ms(0.99) == pytest.approx(99.0, abs=2)
        path = rec.dump_jsonl(str(tmp_path / "ring.jsonl"))
        assert sum(1 for _ in open(path)) == 100


class TestEngineRecorder:
    def test_engine_stats_detail_carries_chunk_records(self, monkeypatch):
        monkeypatch.setenv("SELDON_TPU_FLIGHT_RECORDER", "64")
        eng = _tiny_engine()
        try:
            eng.submit(np.arange(4, dtype=np.int32), max_new_tokens=6)
            eng.run()
            base = eng.engine_stats()
            assert "recorder" not in base  # default surface unchanged
            stats = eng.engine_stats(detail=True)
            recs = stats["recorder"]
            assert recs and stats["recorder_stats"]["records"] == len(recs)
            for rec in recs:
                assert rec["phase"] == "decode"
                assert rec["wall_ms"] > 0
                assert rec["steps"] == 4
                assert rec["occupancy"] >= 1
                assert isinstance(rec["buckets"], list)
                for key in ("admissions", "stalls", "queue_depth", "tokens",
                            "prefill_tokens", "decode_tokens"):
                    assert key in rec
                # r15 contract: "tokens" is the wave's TOTAL work and
                # the prefill/decode split decomposes it exactly
                assert rec["tokens"] == (
                    rec["prefill_tokens"] + rec["decode_tokens"]
                )
            assert sum(r["decode_tokens"] for r in recs) == base["tokens"]
            assert (
                sum(r["prefill_tokens"] for r in recs)
                == base["prefill_tokens"]
            )
        finally:
            eng.close()

    def test_recorder_disabled_by_env(self, monkeypatch):
        monkeypatch.setenv("SELDON_TPU_FLIGHT_RECORDER", "0")
        eng = _tiny_engine()
        try:
            assert eng.recorder is None
            stats = eng.engine_stats(detail=True)
            assert stats["recorder"] == []
        finally:
            eng.close()


class TestPrometheusBridgeContract:
    """CI contract: every engine_stats() key is either mapped to a
    canonical metric or explicitly excluded — new counters cannot
    silently skip Prometheus export."""

    def test_every_engine_stats_key_mapped_or_excluded(
        self, monkeypatch, tmp_path
    ):
        from seldon_core_tpu.utils import capture
        from seldon_core_tpu.utils.metrics import (
            ENGINE_STATS_EXCLUDED,
            ENGINE_STATS_METRICS,
        )

        # capture on: the r21 keys are mapped, but the plane defaults
        # OFF and engine_stats sheds them on the off lane — the
        # phantom check below needs the full key set emitted
        monkeypatch.setenv("SELDON_TPU_CAPTURE", "1")
        monkeypatch.setenv("SELDON_TPU_CAPTURE_DIR", str(tmp_path))
        # KV tier on for the same reason: the r22 kv_tier_* keys are
        # mapped but default OFF, and the off lane sheds them
        monkeypatch.setenv("SELDON_TPU_KV_OFFLOAD", "1")
        capture.reset_default_store()
        eng = _tiny_engine()
        try:
            stats = eng.engine_stats()
            unmapped = [
                k for k in stats
                if k not in ENGINE_STATS_METRICS
                and k not in ENGINE_STATS_EXCLUDED
            ]
            assert not unmapped, (
                f"engine_stats keys with no GenerationPrometheusBridge "
                f"mapping and no exclusion entry: {unmapped}"
            )
            # and the inverse: the mapping doesn't name phantom keys
            phantom = [k for k in ENGINE_STATS_METRICS if k not in stats]
            assert not phantom, f"mapped keys engine_stats never emits: {phantom}"
            for key in ENGINE_STATS_EXCLUDED:
                assert key in stats
        finally:
            eng.close()
            capture.reset_default_store()

    def test_mapping_uses_canonical_names_and_kinds(self):
        from seldon_core_tpu.utils.metrics import ENGINE_STATS_METRICS

        for key, (kind, name, doc) in ENGINE_STATS_METRICS.items():
            assert name.startswith("seldon_tpu_engine_"), name
            assert kind in ("counter", "gauge")
            if kind == "counter":
                assert name.endswith("_total"), name
            assert doc
        # the ISSUE-named canonical set is present
        names = {n for _, n, _ in ENGINE_STATS_METRICS.values()}
        assert {"seldon_tpu_engine_slot_occupancy",
                "seldon_tpu_engine_queue_depth",
                "seldon_tpu_engine_tokens_total",
                "seldon_tpu_engine_evictions_total"} <= names


class TestPrometheusBridgeExport:
    def test_counters_gauges_and_histogram_land_in_registry(self, monkeypatch):
        import prometheus_client as prom

        from seldon_core_tpu.utils.metrics import GenerationPrometheusBridge

        monkeypatch.setenv("SELDON_TPU_FLIGHT_RECORDER", "64")
        registry = prom.CollectorRegistry()
        eng = _tiny_engine()
        try:
            bridge = GenerationPrometheusBridge(
                eng, deployment_name="dep", predictor_name="main",
                model_name="lm", registry=registry,
            )
            eng.submit(np.arange(4, dtype=np.int32), max_new_tokens=6)
            eng.run()
            bridge.collect()
            labels = {"deployment_name": "dep", "predictor_name": "main",
                      "model_name": "lm"}
            stats = eng.engine_stats()

            def val(name):
                return registry.get_sample_value(name, labels)

            assert val("seldon_tpu_engine_tokens_total") == stats["tokens"]
            assert val("seldon_tpu_engine_chunks_total") == stats["chunks"]
            assert val("seldon_tpu_engine_slot_occupancy") == 0.0
            assert val("seldon_tpu_engine_queue_depth") == 0.0
            assert (
                val("seldon_tpu_engine_chunk_duration_seconds_count")
                == stats["chunks"]
            )
            assert val("seldon_tpu_engine_chunk_p99_ms") > 0.0
            # second collect with no new work: counters must NOT re-add
            bridge.collect()
            assert val("seldon_tpu_engine_tokens_total") == stats["tokens"]
            assert (
                val("seldon_tpu_engine_chunk_duration_seconds_count")
                == stats["chunks"]  # each chunk observed exactly once
            )
        finally:
            eng.close()

    def test_counter_reset_rebases_instead_of_incing_garbage(self):
        import prometheus_client as prom

        from seldon_core_tpu.utils.metrics import GenerationPrometheusBridge

        class FakeEngine:
            def __init__(self):
                self.stats = {"tokens": 100, "queued_streams": 0}
                self.recorder = None

            def engine_stats(self, detail=False):
                return dict(self.stats)

        registry = prom.CollectorRegistry()
        fake = FakeEngine()
        bridge = GenerationPrometheusBridge(fake, registry=registry)
        bridge.collect()
        labels = {"deployment_name": "", "predictor_name": "", "model_name": ""}
        assert registry.get_sample_value(
            "seldon_tpu_engine_tokens_total", labels) == 100.0
        fake.stats["tokens"] = 30  # engine replaced: cumulative went DOWN
        bridge.collect()
        # rebased on the new engine's count, not inc'd by a negative
        assert registry.get_sample_value(
            "seldon_tpu_engine_tokens_total", labels) == 130.0

    def test_collect_never_raises(self):
        from seldon_core_tpu.utils.metrics import GenerationPrometheusBridge

        class Exploding:
            recorder = None

            def engine_stats(self, detail=False):
                raise RuntimeError("engine gone")

        GenerationPrometheusBridge(Exploding()).collect()  # must not raise


class TestDebugEndpoints:
    """The gateway's /debug surface: engine stats (with the recorder
    ring under ?detail=1) and the tracer's span ring."""

    def _gateway(self):
        from seldon_core_tpu.engine import PredictorService, UnitSpec
        from seldon_core_tpu.engine.server import Gateway
        from seldon_core_tpu.runtime import TPUComponent

        class FakeEngine:
            def engine_stats(self, detail=False):
                out = {"chunks": 3, "tokens": 42, "queued_streams": 1,
                       "active_slots": 2}
                if detail:
                    out["recorder"] = [
                        {"seq": 1, "phase": "decode", "wall_ms": 1.5,
                         "queue_depth": 1}
                    ]
                return out

        class GenModel(TPUComponent):
            def __init__(self):
                super().__init__()
                self.engine = FakeEngine()

            def predict(self, X, names, meta=None):
                return np.asarray(X)

        svc = PredictorService(
            UnitSpec(name="lm", type="MODEL", component=GenModel()),
            name="main",
        )
        return Gateway([(svc, 1.0)])

    def test_debug_engine_reports_stats_and_detail(self):
        import asyncio

        from aiohttp.test_utils import TestClient, TestServer

        from seldon_core_tpu.engine.server import build_gateway_app

        async def scenario():
            client = TestClient(TestServer(build_gateway_app(self._gateway())))
            await client.start_server()
            plain = await (await client.get("/debug/engine")).json()
            detail = await (
                await client.get("/debug/engine", params={"detail": "1"})
            ).json()
            await client.close()
            return plain, detail

        plain, detail = asyncio.run(scenario())
        assert plain["main"]["lm"]["tokens"] == 42
        assert "recorder" not in plain["main"]["lm"]
        assert detail["main"]["lm"]["recorder"][0]["wall_ms"] == 1.5

    def test_debug_traces_serves_span_ring(self):
        import asyncio

        from aiohttp.test_utils import TestClient, TestServer

        from seldon_core_tpu.engine.server import build_gateway_app

        app = build_gateway_app(self._gateway())
        tracer = tracing.setup_tracing("debug-ep-test")
        try:
            with tracer.span("predictor.predict", trace_id="p-1"):
                pass
            with tracer.span("other", trace_id="p-2"):
                pass

            async def scenario():
                client = TestClient(TestServer(app))
                await client.start_server()
                allsp = await (await client.get("/debug/traces")).json()
                one = await (
                    await client.get("/debug/traces",
                                     params={"trace_id": "p-1"})
                ).json()
                await client.close()
                return allsp, one

            allsp, one = asyncio.run(scenario())
            assert allsp["enabled"] and len(allsp["spans"]) == 2
            assert [s["traceId"] for s in one["spans"]] == ["p-1"]
            assert one["spans"][0]["spanId"]
        finally:
            tracing._tracer = None

    def test_debug_traces_without_tracer_says_disabled(self):
        import asyncio

        from aiohttp.test_utils import TestClient, TestServer

        from seldon_core_tpu.engine.server import build_gateway_app

        assert tracing.get_tracer() is None

        async def scenario():
            client = TestClient(TestServer(build_gateway_app(self._gateway())))
            await client.start_server()
            out = await (await client.get("/debug/traces")).json()
            await client.close()
            return out

        out = asyncio.run(scenario())
        assert out["enabled"] is False and out["spans"] == []


class TestProfileEngineTraceTool:
    def test_tool_importable_and_argparse_defaults(self):
        import importlib.util

        path = os.path.join(
            os.path.dirname(__file__), os.pardir, "tools",
            "profile_engine_trace.py",
        )
        spec = importlib.util.spec_from_file_location("pet", path)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        assert callable(mod.main)
