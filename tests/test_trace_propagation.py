"""Cross-process trace propagation as a CONTRACT.

Every ``NodeClient`` (local / REST / gRPC / the meta-carrier native
lane) must carry the caller's span context on every method, and the
microservice runtime must parent its ``_traced`` dispatch spans under
it — a span created in the gateway may never become a fresh root in a
worker (reference: the Jaeger interceptors on every hop,
microservice.py:124-155; PAPERS.md: Dapper).  Also under contract
here: the per-hop ``seldon_tpu_transport_*`` telemetry (complete by
contract like the engine bridge), the GrpcClient per-attempt failure
history, and puid uniqueness across process generations.
"""

import asyncio
import json
import os
import socket
import subprocess
import sys
import time

import numpy as np
import pytest

from seldon_core_tpu.engine.graph import Endpoint, UnitSpec
from seldon_core_tpu.engine.transport import GrpcClient, LocalClient, RestClient
from seldon_core_tpu.runtime import dispatch, grpc_server, rest
from seldon_core_tpu.runtime.component import MicroserviceError, TPUComponent
from seldon_core_tpu.runtime.message import InternalFeedback, InternalMessage
from seldon_core_tpu.utils import tracing

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def run(coro):
    return asyncio.run(coro)


def msg(arr=((1.0, 2.0),), puid="puid-prop"):
    m = InternalMessage(payload=np.asarray(arr, dtype=np.float64), kind="tensor")
    m.meta.puid = puid
    return m


class Omni(TPUComponent):
    """Implements every node method so one component serves all five
    NodeClient calls."""

    def predict(self, X, names, meta=None):
        return np.asarray(X) * 2

    def transform_input(self, X, names, meta=None):
        return np.asarray(X) + 1

    def transform_output(self, X, names, meta=None):
        return np.asarray(X) - 1

    def route(self, X, names):
        return 0

    def aggregate(self, features_list, names_list):
        return np.mean(np.asarray(features_list), axis=0)

    def send_feedback(self, features, names, reward, truth, routing=None):
        return None


# ---------------------------------------------------------------------------
# W3C primitives
# ---------------------------------------------------------------------------


class TestW3CPrimitives:
    def test_inject_extract_roundtrip_preserves_identity(self):
        ctx = tracing.SpanContext(trace_id="puid-42", span_id="ab" * 8)
        carrier = {}
        carrier[tracing.TRACEPARENT_HEADER] = ctx.to_traceparent()
        carrier[tracing.TRACESTATE_HEADER] = ctx.to_tracestate()
        got = tracing.extract(carrier)
        assert got is not None
        assert got.trace_id == "puid-42"  # logical id survives via tracestate
        assert got.span_id == "ab" * 8
        assert got.sampled is True
        # the wire id is the puid's stable 32-hex derivation — the same
        # one the OTLP exporter ships
        assert got.hex_trace_id == tracing.w3c_trace_id("puid-42")
        assert len(got.hex_trace_id) == 32

    def test_traceparent_shape_is_w3c(self):
        ctx = tracing.SpanContext(trace_id="p", span_id="12" * 8)
        tp = ctx.to_traceparent()
        version, tid, sid, flags = tp.split("-")
        assert version == "00" and len(tid) == 32 and len(sid) == 16 and flags == "01"

    def test_foreign_caller_without_tracestate_adopts_hex_id(self):
        got = tracing.extract({
            "traceparent": f"00-{'1a' * 16}-{'2b' * 8}-01",
        })
        assert got is not None and got.trace_id == "1a" * 16

    @pytest.mark.parametrize("bad", [
        "", "garbage", "00-zz-xx-01",
        f"00-{'0' * 32}-{'2b' * 8}-01",       # all-zero trace id forbidden
        f"00-{'1a' * 16}-{'0' * 16}-01",      # all-zero span id forbidden
        f"ff-{'1a' * 16}-{'2b' * 8}-01",      # forbidden version
    ])
    def test_malformed_traceparent_is_ignored_not_fatal(self, bad):
        assert tracing.extract({"traceparent": bad}) is None

    def test_extract_reads_grpc_metadata_tuples_case_insensitively(self):
        md = [("Traceparent", f"00-{'1a' * 16}-{'2b' * 8}-01")]
        got = tracing.extract(md)
        assert got is not None and got.span_id == "2b" * 8

    def test_inject_without_active_span_is_noop(self):
        assert tracing.inject({}) == {}
        assert tracing.inject_metadata() == []

    def test_sampled_flag_and_foreign_tracestate_survive_the_hop(self):
        """An upstream's do-not-sample decision (flags=00) and other
        vendors' tracestate members must be re-emitted verbatim by the
        NEXT hop's inject — the caller owns the sampling decision."""
        tracer = tracing.setup_tracing("flag-carry")
        try:
            incoming = {
                "traceparent": f"00-{'1a' * 16}-{'2b' * 8}-00",
                "tracestate": "congo=t61rcWkgMzE,rojo=00f067aa0ba902b7",
            }
            ctx = tracing.extract(incoming)
            assert ctx is not None and ctx.sampled is False
            with tracing.activate_context(ctx):
                with tracer.span("node.hop", trace_id="local-puid"):
                    outgoing = tracing.inject({})
            assert outgoing["traceparent"].endswith("-00"), outgoing
            state = outgoing["tracestate"].split(",")
            assert state[0].startswith("seldon-tpu=")
            assert "congo=t61rcWkgMzE" in state
            assert "rojo=00f067aa0ba902b7" in state
        finally:
            tracing._tracer = None


# ---------------------------------------------------------------------------
# the propagation contract, per transport, per NodeClient method
# ---------------------------------------------------------------------------

METHODS = ["transform_input", "transform_output", "route", "aggregate", "send_feedback"]
# what microservice-level span name each method lands as when the unit
# is a non-MODEL type (we use UNKNOWN so transform_input stays itself)
_ARG_OF = {
    "transform_input": lambda: msg(),
    "transform_output": lambda: msg(),
    "route": lambda: msg(),
    "aggregate": lambda: [msg(), msg()],
    "send_feedback": lambda: InternalFeedback(request=msg(), reward=1.0),
}


def _unit(name="n", type_="MODEL_ROUTER_COMBO", endpoint=None):
    # a type that is not MODEL, so transform_input dispatches as itself
    u = UnitSpec(name=name, type="TRANSFORMER")
    u.endpoint = endpoint
    return u


async def _serve_rest(component):
    from aiohttp.test_utils import TestServer

    app = rest.build_app(component, unit_id="n")
    server = TestServer(app)
    await server.start_server()
    return server, Endpoint(host="127.0.0.1", port=server.port, transport="REST")


async def _serve_grpc(component):
    server = grpc_server.build_server(component, unit_id="n")
    port = server.add_insecure_port("127.0.0.1:0")
    await server.start()
    return server, Endpoint(host="127.0.0.1", port=port, transport="GRPC")


class TestNodeClientPropagationContract:
    """Every NodeClient method, every transport: the dispatch span must
    share the caller's trace id and link the caller's span as parent."""

    @pytest.fixture(autouse=True)
    def _tracer(self):
        self.tracer = tracing.setup_tracing("prop-contract")
        yield
        tracing._tracer = None

    def _assert_linked(self, root, method):
        name = f"microservice.{method}"
        spans = [s for s in self.tracer.spans if s.name == name]
        assert spans, f"no {name} span recorded"
        child = spans[-1]
        assert child.trace_id == root.trace_id, (
            f"{name} started a fresh trace {child.trace_id!r} "
            f"instead of joining {root.trace_id!r}"
        )
        assert child.parent_span_id == root.span_id, (
            f"{name} is an orphan root (parent {child.parent_span_id!r}, "
            f"expected {root.span_id!r})"
        )

    @pytest.mark.parametrize("method", METHODS)
    def test_local_client(self, method):
        client = LocalClient(_unit(), Omni())

        async def scenario():
            with self.tracer.span("node.hop", trace_id="puid-prop") as root:
                await getattr(client, method)(_ARG_OF[method]())
            return root

        self._assert_linked(run(scenario()), method)

    @pytest.mark.parametrize("method", METHODS)
    def test_rest_client(self, method):
        async def scenario():
            server, endpoint = await _serve_rest(Omni())
            client = RestClient(_unit(endpoint=endpoint))
            try:
                with self.tracer.span("node.hop", trace_id="puid-prop") as root:
                    await getattr(client, method)(_ARG_OF[method]())
            finally:
                await client.close()
                await server.close()
            return root

        self._assert_linked(run(scenario()), method)

    @pytest.mark.parametrize("method", METHODS)
    def test_grpc_client(self, method):
        async def scenario():
            server, endpoint = await _serve_grpc(Omni())
            client = GrpcClient(_unit(endpoint=endpoint))
            try:
                with self.tracer.span("node.hop", trace_id="puid-prop") as root:
                    await getattr(client, method)(_ARG_OF[method]())
            finally:
                await client.close()
                await server.stop(grace=None)
            return root

        self._assert_linked(run(scenario()), method)

    @pytest.mark.parametrize("method", METHODS)
    def test_meta_carrier_native_lane(self, method):
        """The InternalMessage.meta carrier alone (no ambient
        contextvar, no headers — the native-ingress / queue-hand-off
        shape) must parent dispatch identically."""
        with self.tracer.span("node.hop", trace_id="puid-prop") as root:
            carrier = tracing.inject({})
        arg = _ARG_OF[method]()
        first = arg[0] if isinstance(arg, list) else arg
        meta = getattr(first, "meta", None) or first.request.meta
        meta.trace_context = dict(carrier)
        args = (Omni(), arg) + (("n",) if method == "send_feedback" else ())
        getattr(dispatch, method)(*args)
        self._assert_linked(root, method)

    def test_meta_carrier_is_consumed_not_echoed(self):
        with self.tracer.span("node.hop", trace_id="puid-prop"):
            carrier = tracing.inject({})
        m = msg()
        m.meta.trace_context = dict(carrier)
        out = dispatch.predict(Omni(), m)
        assert m.meta.trace_context == {}
        assert out.meta.trace_context == {}
        assert "traceContext" not in out.to_json().get("meta", {})


class TestExternalCallerAdoption:
    """A foreign caller's traceparent at the gateway: the WHOLE graph
    joins the caller's trace (trace identity flows down from the root),
    and puid lookups still work via the puid tag."""

    def test_graph_joins_external_trace_and_puid_stays_findable(self):
        from seldon_core_tpu.engine import PredictorService

        tracer = tracing.setup_tracing("ext-adopt")
        try:
            svc = PredictorService(
                UnitSpec(name="m", type="MODEL", component=Omni()), name="main"
            )
            ext = tracing.SpanContext(trace_id="ext-trace-99", span_id="c3" * 8)

            async def scenario():
                with tracing.activate_context(ext):
                    return await svc.predict(msg(puid=""))

            out = run(scenario())
            puid = out.meta.puid
            spans = list(tracer.spans)
            assert spans and all(s.trace_id == "ext-trace-99" for s in spans), (
                "a node span split off the external trace: "
                f"{[(s.name, s.trace_id) for s in spans]}"
            )
            pred = [s for s in spans if s.name == "predictor.predict"][0]
            assert pred.parent_span_id == "c3" * 8
            # the puid survives as a tag and find() answers by it
            assert pred.tags["puid"] == puid
            assert {s.name for s in tracer.find(puid)} >= {
                "predictor.predict", "node.m.transform_input",
            }
        finally:
            tracing._tracer = None


class TestGraphHasNoOrphanRoots:
    """A full in-process graph run: exactly ONE root (the predictor
    span); every other span parents into the tree."""

    def test_single_root_full_chain(self):
        from seldon_core_tpu.engine import PredictorService

        tracer = tracing.setup_tracing("orphan-check")
        try:
            graph = UnitSpec(
                name="combiner", type="COMBINER",
                implementation="AVERAGE_COMBINER",
                children=[
                    UnitSpec(name="a", type="MODEL", component=Omni()),
                    UnitSpec(name="b", type="MODEL", component=Omni()),
                ],
            )
            svc = PredictorService(graph, name="main")
            out = run(svc.predict(msg()))
            assert out.status["status"] == "SUCCESS"
            spans = tracer.find(out.meta.puid)
            roots = [s for s in spans if s.parent_span_id is None]
            assert len(spans) >= 6  # predictor + 3 node hops + dispatches
            assert [r.name for r in roots] == ["predictor.predict"]
            by_id = {s.span_id: s for s in spans}
            for s in spans:
                if s.parent_span_id is not None:
                    assert s.parent_span_id in by_id, f"{s.name} dangles"
        finally:
            tracing._tracer = None


# ---------------------------------------------------------------------------
# per-hop transport telemetry
# ---------------------------------------------------------------------------


class TestTransportTelemetry:
    def test_contract_is_complete(self):
        """Every quantitative hop measurement maps to a canonical
        metric — the same completeness rule the engine bridge enforces."""
        from seldon_core_tpu.utils import metrics as m

        hop_fields = {
            "unit", "method", "transport", "request_bytes",
            "response_bytes", "zero_copy_bytes", "serialize_seconds",
            "network_seconds", "retries", "error", "requests", "failovers",
        }
        mapped = set(m.TRANSPORT_METRICS) | m.TRANSPORT_RECORD_EXCLUDED
        unmapped = hop_fields - mapped - {
            "serialize_s",  # _Hop internal names land as *_seconds
        }
        assert not unmapped, f"hop fields with no metric mapping: {unmapped}"
        for kind, name, doc in m.TRANSPORT_METRICS.values():
            assert name.startswith("seldon_tpu_transport_")
            assert kind in ("counter", "gauge", "histogram") and doc

    def test_rest_hop_records_bytes_split_and_inflight(self):
        import prometheus_client as prom

        from seldon_core_tpu.utils import metrics as m

        async def scenario():
            server, endpoint = await _serve_rest(Omni())
            unit = UnitSpec(name="telem-rest", type="MODEL")
            unit.endpoint = endpoint
            client = RestClient(unit)
            try:
                await client.transform_input(msg())
            finally:
                await client.close()
                await server.close()

        run(scenario())
        labels = {"unit": "telem-rest", "method": "predict", "transport": "rest"}
        g = prom.REGISTRY.get_sample_value
        assert g("seldon_tpu_transport_requests_total", labels) == 1.0
        assert g("seldon_tpu_transport_request_bytes_total", labels) > 0
        assert g("seldon_tpu_transport_response_bytes_total", labels) > 0
        assert g("seldon_tpu_transport_serialize_seconds_count", labels) == 1.0
        assert g("seldon_tpu_transport_network_seconds_count", labels) == 1.0
        # the split is a decomposition: codec + network <= total elapsed,
        # and the in-flight gauge returned to zero
        assert g("seldon_tpu_transport_inflight", labels) == 0.0
        # children are pre-bound, so the error counter exists at zero
        assert (g("seldon_tpu_transport_errors_total", labels) or 0.0) == 0.0

    def test_grpc_error_hop_counts_error_and_retries(self):
        import prometheus_client as prom

        async def scenario():
            unit = UnitSpec(name="telem-grpc-err", type="MODEL")
            unit.endpoint = Endpoint(host="127.0.0.1", port=_free_port(), transport="GRPC")
            client = GrpcClient(unit, deadline_s=0.4, retries=2)
            with pytest.raises(MicroserviceError):
                await client.transform_input(msg())
            await client.close()

        run(scenario())
        labels = {"unit": "telem-grpc-err", "method": "predict", "transport": "grpc"}
        g = prom.REGISTRY.get_sample_value
        assert g("seldon_tpu_transport_errors_total", labels) == 1.0
        assert g("seldon_tpu_transport_retries_total", labels) == 1.0
        assert g("seldon_tpu_transport_inflight", labels) == 0.0

    def test_kill_switch_disables_recording(self, monkeypatch):
        import prometheus_client as prom

        from seldon_core_tpu.utils import metrics as m

        monkeypatch.setenv("SELDON_TPU_TRANSPORT_TELEMETRY", "0")
        m.record_transport_hop("off-unit", "predict", "rest", request_bytes=10)
        assert prom.REGISTRY.get_sample_value(
            "seldon_tpu_transport_requests_total",
            {"unit": "off-unit", "method": "predict", "transport": "rest"},
        ) is None

    def test_hop_tags_land_on_the_node_span(self):
        tracer = tracing.setup_tracing("hop-tags")
        try:
            async def scenario():
                server, endpoint = await _serve_rest(Omni())
                unit = UnitSpec(name="tagged", type="MODEL")
                unit.endpoint = endpoint
                client = RestClient(unit)
                try:
                    with tracer.span("node.tagged.predict", trace_id="p-tag") as hop:
                        await client.transform_input(msg())
                finally:
                    await client.close()
                    await server.close()
                return hop

            hop = run(scenario())
            assert hop.tags["transport"] == "rest"
            assert hop.tags["request_bytes"] > 0
            assert hop.tags["response_bytes"] > 0
            assert hop.tags["serialize_ms"] >= 0
            assert hop.tags["network_ms"] >= 0
        finally:
            tracing._tracer = None


# ---------------------------------------------------------------------------
# GrpcClient per-attempt failure history (post-mortem diagnosability)
# ---------------------------------------------------------------------------


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


class TestGrpcAttemptHistory:
    def test_exhausted_call_carries_full_attempt_history(self):
        unit = UnitSpec(name="dead", type="MODEL")
        unit.endpoint = Endpoint(host="127.0.0.1", port=_free_port(), transport="GRPC")
        client = GrpcClient(unit, deadline_s=0.4, retries=3)

        async def scenario():
            try:
                await client.transform_input(msg())
            except MicroserviceError as e:
                return e
            finally:
                await client.close()
            return None

        err = run(scenario())
        assert err is not None and err.reason == "UPSTREAM_GRPC_ERROR"
        # machine-readable history: one entry per attempt, each with a
        # status name and its elapsed time
        assert len(err.attempts) == 3
        for i, att in enumerate(err.attempts, start=1):
            assert att["attempt"] == i
            assert att["status"] == "UNAVAILABLE"
            assert att["elapsed_ms"] >= 0
        # and the human-readable message names every attempt too
        assert "attempts" in err.message and "UNAVAILABLE" in err.message

    def test_channel_recovers_after_endpoint_respawn(self):
        """An UNAVAILABLE call drops the cached channel, so a later
        call to a RESPAWNED worker at the same address connects
        immediately instead of failing fast from inside the old
        subchannel's reconnect backoff (the chaos-test regression:
        kill -> retries poison the channel -> recovery request fails)."""
        port = _free_port()
        unit = UnitSpec(name="respawn", type="MODEL")
        unit.endpoint = Endpoint(host="127.0.0.1", port=port, transport="GRPC")
        client = GrpcClient(unit, deadline_s=2.0, retries=2)

        async def scenario():
            # 1. endpoint down: exhausted retries, channel reset
            with pytest.raises(MicroserviceError):
                await client.transform_input(msg())
            # 2. "respawn" a worker on the SAME port
            server = grpc_server.build_server(Omni())
            bound = server.add_insecure_port(f"127.0.0.1:{port}")
            assert bound == port
            await server.start()
            try:
                out = await client.transform_input(msg())
                return out
            finally:
                await client.close()
                await server.stop(grace=None)

        out = run(scenario())
        np.testing.assert_allclose(out.array(), np.asarray([[2.0, 4.0]]))

    def test_non_retryable_status_fails_fast_with_single_attempt(self):
        """A server that answers with a non-transient failure must not
        burn the retry budget."""

        class Boom(TPUComponent):
            def predict(self, X, names, meta=None):
                raise MicroserviceError("bad input", status_code=400, reason="BAD")

        async def scenario():
            server = grpc_server.build_server(Boom())
            port = server.add_insecure_port("127.0.0.1:0")
            await server.start()
            unit = UnitSpec(name="boom", type="MODEL")
            unit.endpoint = Endpoint(host="127.0.0.1", port=port, transport="GRPC")
            client = GrpcClient(unit, retries=3)
            try:
                out = await client.transform_input(msg())
                return out
            finally:
                await client.close()
                await server.stop(grace=None)

        # component errors come back as FAILURE payloads (status carried
        # in-band), so transport-level retries never fire for them
        out = run(scenario())
        assert out.status["status"] == "FAILURE"


# ---------------------------------------------------------------------------
# puid hardening: unique across processes, respawns, and forks
# ---------------------------------------------------------------------------


class TestPuidHardening:
    def test_multiprocess_uniqueness(self):
        """Three process generations each minting puids: zero
        collisions (the pre-hardening counter restarted at 0 with a
        process-lifetime prefix, so respawned workers collided)."""
        code = (
            "from seldon_core_tpu.runtime.puid import new_puid\n"
            "print('\\n'.join(new_puid() for _ in range(200)))\n"
        )
        batches = []
        for _ in range(3):
            out = subprocess.run(
                [sys.executable, "-c", code], cwd=REPO_ROOT,
                capture_output=True, text=True, timeout=60, check=True,
            )
            batches.append(out.stdout.split())
        all_puids = [p for b in batches for p in b]
        assert len(all_puids) == 600
        assert len(set(all_puids)) == 600, "puids collided across process generations"

    def test_fork_reseeds_prefix(self):
        """A fork after import must not duplicate the generator state
        into the child (pre-fork supervisors would otherwise mint the
        parent's puids again).  Exercised in a clean subprocess: this
        test process has jax (and its threads) loaded, where a raw
        fork is unsafe."""
        code = (
            "import os, sys\n"
            "from seldon_core_tpu.runtime.puid import new_puid\n"
            "parent = {new_puid() for _ in range(50)}\n"
            "r, w = os.pipe()\n"
            "pids = []\n"
            "for _ in range(2):\n"
            "    pid = os.fork()\n"
            "    if pid == 0:\n"
            "        os.close(r)\n"
            "        out = '\\n'.join(new_puid() for _ in range(50))\n"
            "        os.write(w, (out + '\\n').encode())\n"
            "        os._exit(0)\n"
            "    pids.append(pid)\n"
            "os.close(w)\n"
            "data = b''\n"
            "while True:\n"
            "    chunk = os.read(r, 65536)\n"
            "    if not chunk: break\n"
            "    data += chunk\n"
            "for pid in pids: os.waitpid(pid, 0)\n"
            "children = data.decode().split()\n"
            "assert len(children) == 100, len(children)\n"
            "everything = parent | set(children)\n"
            "assert len(everything) == 150, 'fork duplicated puid state'\n"
            "print('OK')\n"
        )
        out = subprocess.run(
            [sys.executable, "-c", code], cwd=REPO_ROOT,
            capture_output=True, text=True, timeout=60,
        )
        assert out.returncode == 0, out.stderr
        assert "OK" in out.stdout

    def test_new_puid_format_and_local_uniqueness(self):
        from seldon_core_tpu.runtime.puid import new_puid

        got = {new_puid() for _ in range(1000)}
        assert len(got) == 1000
        assert all(len(p) == 24 for p in got)


# ---------------------------------------------------------------------------
# the acceptance scenario: a REAL multi-process graph (REST + gRPC hops
# into a spawned worker) produces ONE stitched trace
# ---------------------------------------------------------------------------


@pytest.mark.e2e
class TestMultiProcessStitchedTrace:
    def test_gateway_to_worker_trace_is_single_tree(self, tmp_path):
        worker_spans_path = str(tmp_path / "worker-spans.jsonl")
        worker_log_path = str(tmp_path / "worker.log")
        http_port, grpc_port = _free_port(), _free_port()
        env = dict(
            os.environ,
            TRACING="1",
            SELDON_TPU_TRACE_EXPORT=worker_spans_path,
            JAX_PLATFORMS="cpu",
        )
        # worker output to a FILE: an undrained stdout pipe would wedge
        # a chatty worker once the 64 KB buffer fills
        with open(worker_log_path, "wb") as worker_log:
            proc = subprocess.Popen(
                [
                    sys.executable, "-m", "seldon_core_tpu.runtime.microservice",
                    "seldon_core_tpu.engine.units.StubModel",
                    "--api", "BOTH", "--http-port", str(http_port),
                    "--grpc-port", str(grpc_port), "--host", "127.0.0.1",
                    "--unit-id", "worker",
                ],
                cwd=REPO_ROOT, env=env,
                stdout=worker_log, stderr=subprocess.STDOUT,
            )
        try:
            self._await_ready(proc, http_port, worker_log_path)
            tracer = tracing.setup_tracing("stitch-gateway")
            try:
                from seldon_core_tpu.engine import PredictorService

                graph = UnitSpec(
                    name="combiner", type="COMBINER",
                    implementation="AVERAGE_COMBINER",
                    children=[
                        UnitSpec(
                            name="rest-leg", type="MODEL", remote=True,
                            endpoint=Endpoint("127.0.0.1", http_port, "REST"),
                        ),
                        UnitSpec(
                            name="grpc-leg", type="MODEL", remote=True,
                            endpoint=Endpoint("127.0.0.1", grpc_port, "GRPC"),
                        ),
                    ],
                )
                svc = PredictorService(graph, name="main")
                out = run(self._predict_and_close(svc))
                assert out.status["status"] == "SUCCESS"
                puid = out.meta.puid
                local_spans = [s.to_dict() for s in tracer.spans]
            finally:
                tracing._tracer = None
        finally:
            proc.terminate()
            proc.wait(timeout=20)

        deadline = time.time() + 10
        worker_spans = []
        while time.time() < deadline:
            if os.path.exists(worker_spans_path):
                with open(worker_spans_path) as f:
                    worker_spans = [json.loads(l) for l in f if l.strip()]
                if len(worker_spans) >= 2:
                    break
            time.sleep(0.2)
        assert len(worker_spans) >= 2, "worker exported no dispatch spans"

        spans = local_spans + worker_spans
        # ---- the acceptance criterion: one stitched trace ----
        shared = [s for s in spans if s["traceId"] == puid]
        assert len(shared) / len(spans) >= 0.99
        # zero orphan roots from microservice dispatch: every worker
        # span links a parent that exists on the gateway side
        local_ids = {s["spanId"] for s in local_spans}
        micro = [s for s in worker_spans if s["name"].startswith("microservice.")]
        assert micro and all(s["parentSpanId"] for s in micro), (
            "microservice dispatch minted orphan root spans"
        )
        for s in micro:
            assert s["parentSpanId"] in local_ids, (
                f"worker span {s['name']} parents {s['parentSpanId']!r}, "
                "which is not a gateway span"
            )
        # both transports actually hopped
        hops = {s["name"] for s in local_spans}
        assert "node.rest-leg.transform_input" in hops
        assert "node.grpc-leg.transform_input" in hops

    @staticmethod
    async def _predict_and_close(svc):
        try:
            return await svc.predict(msg(puid=""))
        finally:
            await svc.close()

    @staticmethod
    def _await_ready(proc, http_port, log_path, timeout_s=60):
        import urllib.request

        deadline = time.time() + timeout_s
        while time.time() < deadline:
            if proc.poll() is not None:
                with open(log_path, errors="replace") as f:
                    out = f.read()
                raise AssertionError(f"worker died at startup:\n{out[-4000:]}")
            try:
                with urllib.request.urlopen(
                    f"http://127.0.0.1:{http_port}/health/ping", timeout=1
                ) as resp:
                    if resp.status < 400:
                        return
            except Exception:
                time.sleep(0.2)
        raise AssertionError("worker never became ready")
