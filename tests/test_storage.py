"""Cloud storage + credentials, exercised through mocked SDKs.

The reference tests its storage lanes with mocked clients
(reference: python/tests/test_s3_storage.py); same approach here since
the environment is egress-free: fake boto3 / google.cloud.storage /
azure.storage.blob modules are injected into sys.modules and the
downloader's behaviour (listing, prefix-relative paths, credential
plumbing) is asserted against them.
"""

import base64
import sys
import types

import pytest

from seldon_core_tpu.utils.credentials import (
    AzureCredentials,
    GcsCredentials,
    S3Credentials,
)


class TestS3Credentials:
    def test_from_env_reference_names(self):
        env = {
            "AWS_ACCESS_KEY_ID": "AK",
            "AWS_SECRET_ACCESS_KEY": "SK",
            "AWS_ENDPOINT_URL": "http://minio:9000",
            "AWS_REGION": "us-east-1",
            "USE_SSL": "0",
        }
        creds = S3Credentials.from_env(env)
        kwargs = creds.client_kwargs()
        assert kwargs == {
            "aws_access_key_id": "AK",
            "aws_secret_access_key": "SK",
            "endpoint_url": "http://minio:9000",
            "region_name": "us-east-1",
            "use_ssl": False,
        }

    def test_from_secret_base64_values(self):
        secret = {
            "awsAccessKeyID": base64.b64encode(b"AK2").decode(),
            "awsSecretAccessKey": base64.b64encode(b"SK2").decode(),
            "s3Endpoint": "s3.example.com",
        }
        creds = S3Credentials.from_secret(secret)
        assert creds.access_key == "AK2"
        assert creds.secret_key == "SK2"
        assert creds.endpoint == "s3.example.com"

    def test_empty_env_omits_kwargs(self):
        kwargs = S3Credentials.from_env({}).client_kwargs()
        assert kwargs == {"use_ssl": True}


class TestOtherCredentials:
    def test_gcs_from_env(self):
        creds = GcsCredentials.from_env({"GOOGLE_APPLICATION_CREDENTIALS": "/sa.json"})
        assert creds.service_account_file == "/sa.json"

    def test_azure_from_env(self):
        creds = AzureCredentials.from_env(
            {"AZURE_STORAGE_ACCOUNT": "acct", "AZURE_STORAGE_ACCESS_KEY": "key"}
        )
        assert creds.account_name == "acct" and creds.account_key == "key"


@pytest.fixture
def fake_s3(monkeypatch):
    """boto3 stand-in recording calls and serving two objects."""
    calls = {}

    class FakeS3:
        def list_objects_v2(self, Bucket, Prefix):
            calls["list"] = (Bucket, Prefix)
            return {
                "Contents": [
                    {"Key": f"{Prefix}/weights.msgpack"},
                    {"Key": f"{Prefix}/sub/meta.json"},
                ]
            }

        def download_file(self, bucket, key, dest):
            calls.setdefault("downloads", []).append((bucket, key, dest))
            with open(dest, "wb") as f:
                f.write(b"data:" + key.encode())

    fake = types.ModuleType("boto3")
    fake.client = lambda service, **kwargs: calls.setdefault("client", (service, kwargs)) and FakeS3() or FakeS3()
    monkeypatch.setitem(sys.modules, "boto3", fake)
    return calls


class TestS3Download:
    def test_lists_downloads_and_plumbs_credentials(self, fake_s3, tmp_path, monkeypatch):
        from seldon_core_tpu.utils import storage

        monkeypatch.setenv("AWS_ACCESS_KEY_ID", "AK")
        monkeypatch.setenv("AWS_SECRET_ACCESS_KEY", "SK")
        monkeypatch.setenv("AWS_ENDPOINT_URL", "http://minio:9000")
        out = storage.download("s3://models/resnet/v1", out_dir=str(tmp_path))
        assert out == str(tmp_path)
        assert fake_s3["list"] == ("models", "resnet/v1")
        service, kwargs = fake_s3["client"]
        assert service == "s3"
        assert kwargs["aws_access_key_id"] == "AK"
        assert kwargs["endpoint_url"] == "http://minio:9000"
        # prefix-relative layout preserved
        assert (tmp_path / "weights.msgpack").read_bytes() == b"data:resnet/v1/weights.msgpack"
        assert (tmp_path / "sub" / "meta.json").exists()

    def test_empty_bucket_raises(self, tmp_path, monkeypatch):
        fake = types.ModuleType("boto3")

        class Empty:
            def list_objects_v2(self, Bucket, Prefix):
                return {}

        fake.client = lambda *a, **k: Empty()
        monkeypatch.setitem(sys.modules, "boto3", fake)
        from seldon_core_tpu.utils import storage

        with pytest.raises(FileNotFoundError):
            storage.download("s3://models/none", out_dir=str(tmp_path))


@pytest.fixture
def fake_gcs(monkeypatch):
    calls = {}

    class Blob:
        def __init__(self, name):
            self.name = name

        def download_to_filename(self, dest):
            calls.setdefault("downloads", []).append((self.name, dest))
            with open(dest, "wb") as f:
                f.write(b"gcs:" + self.name.encode())

    class FakeClient:
        def bucket(self, name):
            calls["bucket"] = name
            return name

        def list_blobs(self, bucket, prefix):
            calls["list"] = (bucket, prefix)
            return [Blob(f"{prefix}/model.msgpack")]

    gcloud = types.ModuleType("google.cloud")
    gcs_mod = types.ModuleType("google.cloud.storage")
    gcs_mod.Client = FakeClient
    FakeClient.from_service_account_json = classmethod(
        lambda cls, path: calls.setdefault("sa_file", path) and cls() or cls()
    )
    gcloud.storage = gcs_mod
    monkeypatch.setitem(sys.modules, "google.cloud", gcloud)
    monkeypatch.setitem(sys.modules, "google.cloud.storage", gcs_mod)
    return calls


class TestGcsDownload:
    def test_downloads_with_service_account(self, fake_gcs, tmp_path, monkeypatch):
        from seldon_core_tpu.utils import storage

        monkeypatch.setenv("GOOGLE_APPLICATION_CREDENTIALS", "/sa.json")
        out = storage.download("gs://bucket/models/m1", out_dir=str(tmp_path))
        assert out == str(tmp_path)
        assert fake_gcs["sa_file"] == "/sa.json"
        assert fake_gcs["list"] == ("bucket", "models/m1")
        assert (tmp_path / "model.msgpack").read_bytes() == b"gcs:models/m1/model.msgpack"


@pytest.fixture
def fake_azure(monkeypatch):
    calls = {}

    class Downloader:
        def __init__(self, name):
            self.name = name

        def readinto(self, f):
            f.write(b"az:" + self.name.encode())

    class Container:
        def list_blobs(self, name_starts_with):
            calls["list"] = name_starts_with
            return [types.SimpleNamespace(name=f"{name_starts_with}/weights.bin")]

        def download_blob(self, name):
            calls.setdefault("downloads", []).append(name)
            return Downloader(name)

    class FakeService:
        def get_container_client(self, container):
            calls["container"] = container
            return Container()

    def service_ctor(account_url=None, credential=None):
        calls["account_url"] = account_url
        calls["credential"] = credential
        return FakeService()

    az = types.ModuleType("azure")
    az_storage = types.ModuleType("azure.storage")
    az_blob = types.ModuleType("azure.storage.blob")
    az_blob.BlobServiceClient = service_ctor
    az_blob.BlobServiceClient.from_connection_string = lambda cs: calls.setdefault("cs", cs) and FakeService() or FakeService()
    az_storage.blob = az_blob
    az.storage = az_storage
    monkeypatch.setitem(sys.modules, "azure", az)
    monkeypatch.setitem(sys.modules, "azure.storage", az_storage)
    monkeypatch.setitem(sys.modules, "azure.storage.blob", az_blob)
    return calls


class TestAzureDownload:
    def test_azure_scheme(self, fake_azure, tmp_path, monkeypatch):
        from seldon_core_tpu.utils import storage

        monkeypatch.setenv("AZURE_STORAGE_ACCOUNT", "acct")
        monkeypatch.setenv("AZURE_STORAGE_ACCESS_KEY", "key")
        out = storage.download("azure://acct/container/models/m1", out_dir=str(tmp_path))
        assert out == str(tmp_path)
        assert fake_azure["account_url"] == "https://acct.blob.core.windows.net"
        assert fake_azure["credential"] == "key"
        assert fake_azure["container"] == "container"
        assert fake_azure["list"] == "models/m1"
        assert (tmp_path / "weights.bin").read_bytes() == b"az:models/m1/weights.bin"

    def test_native_https_form(self, fake_azure, tmp_path):
        from seldon_core_tpu.utils import storage

        out = storage.download(
            "https://acct.blob.core.windows.net/container/models/m2", out_dir=str(tmp_path)
        )
        assert out == str(tmp_path)
        assert fake_azure["account_url"] == "https://acct.blob.core.windows.net"

    def test_missing_container_rejected(self, fake_azure, tmp_path):
        from seldon_core_tpu.utils import storage

        with pytest.raises(ValueError):
            storage.download("azure://acct", out_dir=str(tmp_path))
