"""Dispatch-layer tests per node role and payload kind.

Mirrors the reference's wrapper test strategy
(reference: python/tests/test_model_microservice.py,
test_router_microservice.py, test_combiner_microservice.py).
"""

import numpy as np
import pytest

from seldon_core_tpu.proto import pb
from seldon_core_tpu.runtime import (
    InternalFeedback,
    InternalMessage,
    MicroserviceError,
    TPUComponent,
    counter_metric,
    gauge_metric,
)
from seldon_core_tpu.runtime import dispatch
from seldon_core_tpu.runtime.params import ParameterError, parse_parameters


class DoublerModel(TPUComponent):
    def predict(self, X, names, meta=None):
        return np.asarray(X) * 2

    def class_names(self):
        return ["c0", "c1"]

    def tags(self):
        return {"model": "doubler"}

    def metrics(self):
        return [counter_metric("seen", 1), gauge_metric("load", 0.5)]


class EchoModel(TPUComponent):
    def predict(self, X, names, meta=None):
        return X


class RawModel(TPUComponent):
    def predict_raw(self, msg):
        out = pb.SeldonMessage()
        out.strData = "raw:" + (msg.strData or "")
        return out


class FirstRouter(TPUComponent):
    def route(self, features, names):
        return 0


class BadRouter(TPUComponent):
    def route(self, features, names):
        return "nope"


class MeanCombiner(TPUComponent):
    def aggregate(self, features_list, names_list):
        return np.mean([np.asarray(f) for f in features_list], axis=0)


class FeedbackRecorder(TPUComponent):
    def __init__(self):
        self.seen = []

    def send_feedback(self, features, names, reward, truth, routing=None):
        self.seen.append((np.asarray(features).tolist(), reward, routing))
        return None


def tensor_msg(arr, names=None, kind="tensor"):
    arr = np.asarray(arr, dtype=np.float64 if kind == "tensor" else np.float32)
    return InternalMessage(payload=arr, names=list(names or []), kind=kind)


class TestPredict:
    def test_tensor(self):
        out = dispatch.predict(DoublerModel(), tensor_msg([[1.0, 2.0]]))
        np.testing.assert_array_equal(out.payload, [[2.0, 4.0]])
        assert out.names == ["c0", "c1"]
        assert out.kind == "tensor"
        assert out.meta.tags == {"model": "doubler"}
        assert [m["key"] for m in out.meta.metrics] == ["seen", "load"]

    def test_kind_echo_raw(self):
        out = dispatch.predict(DoublerModel(), tensor_msg([[1, 2]], kind="rawTensor"))
        assert out.kind == "rawTensor"

    def test_strdata(self):
        class Upper(TPUComponent):
            def predict(self, X, names, meta=None):
                return X.upper()

        out = dispatch.predict(Upper(), InternalMessage(payload="abc", kind="strData"))
        assert out.payload == "ABC"

    def test_bindata(self):
        out = dispatch.predict(EchoModel(), InternalMessage(payload=b"xyz", kind="binData"))
        assert out.payload == b"xyz"

    def test_jsondata(self):
        out = dispatch.predict(EchoModel(), InternalMessage(payload={"k": 1}, kind="jsonData"))
        assert out.payload == {"k": 1}

    def test_raw_override(self):
        out = dispatch.predict(RawModel(), InternalMessage(payload="x", kind="strData"))
        assert out.payload == "raw:x"

    def test_device_array_materialized_by_default(self):
        import jax.numpy as jnp

        captured = {}

        class Capture(TPUComponent):
            def predict(self, X, names, meta=None):
                captured["type"] = type(X)
                return X

        msg = InternalMessage(payload=jnp.ones((2, 2)), kind="rawTensor")
        dispatch.predict(Capture(), msg)
        assert captured["type"] is np.ndarray

    def test_device_array_passthrough_opt_in(self):
        import jax

        class DeviceModel(TPUComponent):
            accepts_device_arrays = True

            def predict(self, X, names, meta=None):
                assert isinstance(X, jax.Array)
                return X * 3

        import jax.numpy as jnp

        msg = InternalMessage(payload=jnp.ones((2,)), kind="rawTensor")
        out = dispatch.predict(DeviceModel(), msg)
        np.testing.assert_array_equal(out.host_payload(), [3.0, 3.0])

    def test_invalid_metrics_rejected(self):
        class BadMetrics(EchoModel):
            def metrics(self):
                return [{"key": "x"}]

        with pytest.raises(MicroserviceError):
            dispatch.predict(BadMetrics(), tensor_msg([1.0]))


class TestTransforms:
    def test_transform_input(self):
        class AddOne(TPUComponent):
            def transform_input(self, X, names, meta=None):
                return np.asarray(X) + 1

        out = dispatch.transform_input(AddOne(), tensor_msg([[0.0]]))
        np.testing.assert_array_equal(out.payload, [[1.0]])

    def test_transform_output(self):
        class Neg(TPUComponent):
            def transform_output(self, X, names, meta=None):
                return -np.asarray(X)

        out = dispatch.transform_output(Neg(), tensor_msg([3.0]))
        np.testing.assert_array_equal(out.payload, [-3.0])


class TestRoute:
    def test_route_wraps_branch(self):
        out = dispatch.route(FirstRouter(), tensor_msg([[1.0]]))
        assert np.asarray(out.payload).ravel()[0] == 0

    def test_route_type_checked(self):
        with pytest.raises(MicroserviceError):
            dispatch.route(BadRouter(), tensor_msg([[1.0]]))


class TestAggregate:
    def test_mean(self):
        msgs = [tensor_msg([[2.0, 4.0]]), tensor_msg([[4.0, 8.0]])]
        out = dispatch.aggregate(MeanCombiner(), msgs)
        np.testing.assert_array_equal(out.payload, [[3.0, 6.0]])

    def test_tags_union(self):
        m1 = tensor_msg([[1.0]])
        m1.meta.tags["a"] = 1
        m2 = tensor_msg([[2.0]])
        m2.meta.tags["b"] = 2
        out = dispatch.aggregate(MeanCombiner(), [m1, m2])
        assert out.meta.tags["a"] == 1 and out.meta.tags["b"] == 2

    def test_empty_raises(self):
        with pytest.raises(MicroserviceError):
            dispatch.aggregate(MeanCombiner(), [])


class TestFeedback:
    def test_feedback_routing_extraction(self):
        rec = FeedbackRecorder()
        resp = tensor_msg([[9.0]])
        resp.meta.routing["router0"] = 1
        fb = InternalFeedback(request=tensor_msg([[5.0]]), response=resp, reward=0.7)
        out = dispatch.send_feedback(rec, fb, predictive_unit_id="router0")
        assert rec.seen == [([[5.0]], 0.7, 1)]
        assert np.asarray(out.payload).size == 0

    def test_feedback_default_response(self):
        out = dispatch.send_feedback(EchoModel(), InternalFeedback(request=tensor_msg([1.0]), reward=0.0))
        assert np.asarray(out.payload).size == 0


class TestMessageRoundtrips:
    def test_proto_roundtrip_with_meta(self):
        msg = tensor_msg([[1.0, 2.0]], names=["x", "y"])
        msg.meta.puid = "p-123"
        msg.meta.tags["t"] = "v"
        msg.meta.routing["r"] = 2
        msg.meta.metrics.append(counter_metric("c", 3))
        proto = msg.to_proto()
        back = InternalMessage.from_proto(proto)
        assert back.meta.puid == "p-123"
        assert back.meta.tags == {"t": "v"}
        assert back.meta.routing == {"r": 2}
        assert back.meta.metrics[0]["key"] == "c"
        np.testing.assert_array_equal(back.payload, [[1.0, 2.0]])
        assert back.names == ["x", "y"]

    def test_json_roundtrip(self):
        body = {"meta": {"puid": "j1"}, "data": {"names": ["a"], "ndarray": [[1, 2]]}}
        msg = InternalMessage.from_json(body)
        assert msg.meta.puid == "j1" and msg.kind == "ndarray"
        out = msg.to_json()
        assert out["data"]["ndarray"] == [[1, 2]]
        assert out["meta"]["puid"] == "j1"

    def test_feedback_proto_roundtrip(self):
        fb = InternalFeedback(request=tensor_msg([1.0]), reward=0.5)
        back = InternalFeedback.from_proto(fb.to_proto())
        assert back.reward == 0.5
        np.testing.assert_array_equal(back.request.payload, [1.0])


class TestParams:
    def test_typed_parsing(self):
        kwargs = parse_parameters(
            [
                {"name": "s", "value": "hi", "type": "STRING"},
                {"name": "i", "value": "3", "type": "INT"},
                {"name": "f", "value": "0.5", "type": "FLOAT"},
                {"name": "b", "value": "true", "type": "BOOL"},
                {"name": "j", "value": '{"k": [1]}', "type": "JSON"},
            ]
        )
        assert kwargs == {"s": "hi", "i": 3, "f": 0.5, "b": True, "j": {"k": [1]}}

    def test_bad_type(self):
        with pytest.raises(ParameterError):
            parse_parameters([{"name": "x", "value": "1", "type": "NOPE"}])

    def test_bad_value(self):
        with pytest.raises(ParameterError):
            parse_parameters([{"name": "x", "value": "abc", "type": "INT"}])
