"""kv-cache generation: parity with full recompute, bucketing, sampling.

The decode loop's correctness criterion is exact: greedy generation
through the cached path must produce the same tokens as re-running the
full (uncached) TransformerLM forward at every step.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from seldon_core_tpu.models.generate import Generator, GenerativeLM
from seldon_core_tpu.models.transformer import TransformerLM

CFG = dict(vocab_size=64, d_model=32, num_layers=2, num_heads=4, max_len=64)


@pytest.fixture(scope="module")
def lm():
    module = TransformerLM(dtype=jnp.float32, **CFG)
    params = module.init(jax.random.key(0), jnp.zeros((1, 8), jnp.int32))["params"]
    return module, params


def _greedy_uncached(module, params, prompt, n):
    """Reference decoder: full forward every step, argmax."""
    tokens = np.asarray(prompt, np.int32).copy()
    out = []
    for _ in range(n):
        logits = module.apply({"params": params}, jnp.asarray(tokens))
        nxt = int(jnp.argmax(logits[0, -1]))
        out.append(nxt)
        tokens = np.concatenate([tokens, [[nxt]]], axis=1)
    return out


class TestGenerator:
    def test_cached_greedy_matches_full_recompute(self, lm):
        module, params = lm
        gen = Generator(params, dtype=jnp.float32, **CFG)
        prompt = np.array([[5, 9, 13, 2, 30]], np.int32)
        n = 8
        got = gen.generate(prompt, max_new_tokens=n)[0].tolist()
        want = _greedy_uncached(module, params, prompt, n)
        assert got == want

    def test_batched_generation(self, lm):
        _, params = lm
        gen = Generator(params, dtype=jnp.float32, **CFG)
        prompts = np.array([[1, 2, 3], [7, 8, 9]], np.int32)
        out = gen.generate(prompts, max_new_tokens=4)
        assert out.shape == (2, 4)
        # each row matches its own single-row generation
        for i in range(2):
            solo = gen.generate(prompts[i : i + 1], max_new_tokens=4)[0]
            np.testing.assert_array_equal(out[i], solo)

    def test_eos_freezes_finished_rows(self, lm):
        module, params = lm
        gen = Generator(params, dtype=jnp.float32, **CFG)
        prompt = np.array([[5, 9, 13, 2, 30]], np.int32)
        # find what greedy emits first, then declare it the eos token
        first = _greedy_uncached(module, params, prompt, 1)[0]
        out = gen.generate(prompt, max_new_tokens=6, eos_id=first)[0]
        assert out[0] == first
        assert (out[1:] == first).all()  # frozen after eos

    def test_prompt_buckets_reuse_compiled_programs(self, lm):
        _, params = lm
        gen = Generator(params, dtype=jnp.float32, prompt_buckets=[8, 16], **CFG)
        gen.generate(np.array([[1, 2, 3]], np.int32), max_new_tokens=2)
        gen.generate(np.array([[4, 5, 6, 7, 1]], np.int32), max_new_tokens=2)
        # both prompts pad to bucket 8 -> one compiled program
        assert len(gen._generate_jit) == 1
        gen.generate(np.arange(12, dtype=np.int32)[None], max_new_tokens=2)
        assert len(gen._generate_jit) == 2  # bucket 16

    def test_too_long_rejected(self, lm):
        _, params = lm
        gen = Generator(params, dtype=jnp.float32, **CFG)
        from seldon_core_tpu.runtime.component import MicroserviceError

        with pytest.raises(MicroserviceError):
            gen.generate(np.zeros((1, 60), np.int32), max_new_tokens=30)

    def test_sampling_is_seeded_and_varies(self, lm):
        _, params = lm
        gen = Generator(params, dtype=jnp.float32, **CFG)
        prompt = np.array([[5, 9, 13]], np.int32)
        a = gen.generate(prompt, max_new_tokens=8, temperature=1.5, seed=1)
        b = gen.generate(prompt, max_new_tokens=8, temperature=1.5, seed=1)
        c = gen.generate(prompt, max_new_tokens=8, temperature=1.5, seed=2)
        np.testing.assert_array_equal(a, b)  # deterministic per seed
        assert not np.array_equal(a, c) or not np.array_equal(b, c)

    def test_top_k_restricts_choices(self, lm):
        module, params = lm
        gen = Generator(params, dtype=jnp.float32, **CFG)
        prompt = np.array([[5, 9, 13]], np.int32)
        # top_k=1 at any temperature is greedy
        hot = gen.generate(prompt, max_new_tokens=5, temperature=2.0, top_k=1, seed=3)[0]
        want = _greedy_uncached(module, params, prompt, 5)
        assert hot.tolist() == want


class TestGenerativeLMComponent:
    def test_component_serves_token_ids(self):
        comp = GenerativeLM(max_new_tokens=4, seed=0, **CFG)
        comp.load()
        out = comp.predict(np.array([[3, 1, 4]], np.int32), [])
        assert out.shape == (1, 4)
        assert out.dtype == np.int32 or np.issubdtype(out.dtype, np.integer)
        assert (out >= 0).all() and (out < CFG["vocab_size"]).all()

    def test_per_request_sampling_overrides_via_meta_tags(self):
        comp = GenerativeLM(max_new_tokens=3, seed=0, **CFG)
        comp.load()
        out = comp.predict(
            np.array([[3, 1, 4]], np.int32), [],
            meta={"tags": {"max_new_tokens": 6}},
        )
        assert out.shape == (1, 6)
