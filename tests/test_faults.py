"""Fault-injection harness + chaos tests (r10).

Each ``SELDON_TPU_FAULT`` point is driven under load with the allocator
audit enabled, asserting the graceful-degradation invariants the
runbook promises: no stuck streams (every waiter resolves), the
``SELDON_TPU_PAGED_DEBUG`` audit stays clean after every injected
failure, the queue drains, and ``fail_all`` is never needed (the engine
keeps serving afterwards).
"""

import asyncio
import threading
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from seldon_core_tpu.models.paged import PagedEngine
from seldon_core_tpu.models.transformer import TransformerLM
from seldon_core_tpu.runtime.component import MicroserviceError
from seldon_core_tpu.utils import faults


CFG = dict(vocab_size=64, d_model=32, num_layers=1, num_heads=2, max_len=64)


@pytest.fixture(scope="module")
def params():
    module = TransformerLM(dtype=jnp.float32, **CFG)
    return module.init(jax.random.key(0), jnp.zeros((1, 8), jnp.int32))["params"]


@pytest.fixture(autouse=True)
def _disarm():
    faults.clear()
    yield
    faults.clear()


def _engine(params, **kw):
    base = dict(dtype=jnp.float32, page_size=8, max_slots=2, steps_per_call=4)
    base.update(kw)
    return PagedEngine(params, **CFG, **base)


# ---------------------------------------------------------------------------
# registry / spec parsing
# ---------------------------------------------------------------------------


class TestSpec:
    def test_parse_single_point_defaults(self):
        faults.configure("paged.alloc")
        assert faults.enabled()
        assert faults.fire("paged.alloc")  # times=1 default
        assert not faults.fire("paged.alloc")  # disarmed after one firing

    def test_parse_params_and_multiple_points(self):
        faults.configure("paged.alloc:times=2;transport.delay:ms=25,times=1")
        assert faults.fire("paged.alloc")
        assert faults.fire("paged.alloc")
        assert not faults.fire("paged.alloc")
        assert faults.delay_s("transport.delay") == pytest.approx(0.025)
        assert faults.delay_s("transport.delay") == 0.0

    def test_unknown_point_or_param_rejected(self):
        with pytest.raises(ValueError):
            faults.configure("paged.everything")
        with pytest.raises(ValueError):
            faults.configure("paged.alloc:bogus=1")

    # ---- negative grammar (the tests PR 6 deferred): every
    # malformation must error LOUDLY naming the fragment — a chaos
    # harness that silently no-ops on a typo certifies resilience it
    # never exercised ------------------------------------------------------

    def test_malformed_kv_pair_rejected_loudly(self):
        # bare key, no '='
        with pytest.raises(ValueError, match=r"malformed fault parameter 'times'"):
            faults.configure("paged.alloc:times")
        # '=' with empty value
        with pytest.raises(ValueError, match=r"malformed fault parameter 'ms='"):
            faults.configure("transport.delay:ms=")
        assert not faults.enabled()  # nothing half-armed

    def test_bad_numeric_values_rejected_with_context(self):
        with pytest.raises(ValueError, match=r"bad value.*'times=lots'.*paged\.alloc"):
            faults.configure("paged.alloc:times=lots")
        with pytest.raises(ValueError, match=r"bad value.*'prob=maybe'"):
            faults.configure("paged.chunk:prob=maybe")
        with pytest.raises(ValueError, match=r"bad value.*'ms=fast'"):
            faults.configure("transport.delay:ms=fast")

    def test_out_of_range_values_rejected(self):
        with pytest.raises(ValueError, match="prob must be in"):
            faults.configure("paged.alloc:prob=1.5")
        with pytest.raises(ValueError, match="prob must be in"):
            faults.configure("paged.alloc:prob=-0.1")
        with pytest.raises(ValueError, match="times must be >= 0"):
            faults.configure("paged.alloc:times=-2")
        with pytest.raises(ValueError, match="ms must be >= 0"):
            faults.configure("transport.delay:ms=-50")

    def test_duplicate_point_rejected(self):
        with pytest.raises(ValueError, match="duplicate fault point"):
            faults.configure("paged.alloc:times=1;paged.alloc:times=2")

    def test_unknown_point_names_known_points(self):
        with pytest.raises(ValueError, match="transport.slow"):
            faults.configure("paged.everything")

    def test_inject_rejects_unknown_point(self):
        with pytest.raises(ValueError, match="unknown fault point"):
            faults.inject("paged.everything")

    def test_times_inf_still_parses(self):
        faults.configure("paged.alloc:times=inf,prob=1.0")
        for _ in range(5):
            assert faults.fire("paged.alloc")
        faults.clear()

    def test_failed_configure_leaves_registry_disarmed(self):
        faults.configure("paged.alloc:times=3")
        assert faults.enabled()
        with pytest.raises(ValueError):
            faults.configure("paged.alloc:times=3;bogus.point")
        # the bad spec cleared nothing mid-way: configure is atomic
        # (parse first, swap under the lock after)
        assert faults.enabled()
        assert faults.fire("paged.alloc")
        faults.clear()

    def test_env_configure_and_clear(self, monkeypatch):
        monkeypatch.setenv(faults.ENV_VAR, "paged.chunk:times=1")
        faults.configure()
        assert faults.enabled()
        with pytest.raises(faults.InjectedFault):
            faults.raise_if("paged.chunk")
        faults.clear()
        assert not faults.enabled()
        faults.raise_if("paged.chunk")  # disarmed: no-op

    def test_injected_fault_reads_as_grpc_unavailable(self):
        from seldon_core_tpu.engine.transport import (
            _grpc_retryable,
            _grpc_status_name,
        )

        e = faults.InjectedFault("transport.drop")
        assert _grpc_status_name(e) == "UNAVAILABLE"
        assert _grpc_retryable(e)
        assert isinstance(e, ConnectionError)

    def test_stats_count_firings(self):
        before = faults.stats().get("paged.alloc", 0)
        faults.inject("paged.alloc", times=3)
        for _ in range(5):
            faults.fire("paged.alloc")
        assert faults.stats()["paged.alloc"] == before + 3


# ---------------------------------------------------------------------------
# paged.alloc: allocator exhaustion under concurrent load, audit on
# ---------------------------------------------------------------------------


class TestAllocFaultChaos:
    def test_alloc_exhaustion_degrades_gracefully(self, params, monkeypatch):
        monkeypatch.setenv("SELDON_TPU_PAGED_DEBUG", "1")
        eng = _engine(params, max_slots=2, num_pages=9)
        faults.inject("paged.alloc", times=4)
        streams = [
            eng.submit(np.arange(10) + i, max_new_tokens=12) for i in range(4)
        ]
        eng.run()  # audit runs at every chunk boundary
        assert faults.stats()["paged.alloc"] >= 1
        # invariant: no stuck streams — every waiter resolved, and only
        # with a result (injected exhaustion looks like pool pressure,
        # which the stall/evict path absorbs without failing anyone)
        for s in streams:
            assert s.event.is_set()
            assert s.result is not None or isinstance(s.error, MicroserviceError)
        assert not eng.has_work()  # queue drained
        with eng._lock:
            eng._check_invariants_locked()  # audit clean at rest
        # fail_all never needed: the engine keeps serving
        assert eng.generate(np.arange(6), max_new_tokens=4).shape == (4,)

    def test_alloc_fault_during_prefix_match_rolls_back(self, params, monkeypatch):
        """The admission-time alloc failure path must roll back matched
        prefix refcounts (the audit catches a missed rollback)."""
        monkeypatch.setenv("SELDON_TPU_PAGED_DEBUG", "1")
        eng = _engine(params, max_slots=2)
        shared = np.arange(16)  # two full pages -> registered prefixes
        first = eng.submit(shared, max_new_tokens=4)
        eng.run()
        assert first.result is not None
        faults.inject("paged.alloc", times=1)
        follower = eng.submit(
            np.concatenate([shared, np.arange(4)]), max_new_tokens=4
        )
        eng.run()
        assert follower.result is not None
        with eng._lock:
            eng._check_invariants_locked()


# ---------------------------------------------------------------------------
# paged.chunk: contained chunk failure — never fail_all
# ---------------------------------------------------------------------------


class TestChunkFaultChaos:
    def test_chunk_fault_fails_only_that_wave(self, params, monkeypatch):
        monkeypatch.setenv("SELDON_TPU_PAGED_DEBUG", "1")
        eng = _engine(params, max_slots=2)
        faults.inject("paged.chunk", times=1)
        a = eng.submit(np.arange(10), max_new_tokens=8)
        b = eng.submit(np.arange(10) + 1, max_new_tokens=8)
        late = eng.submit(np.arange(10) + 2, max_new_tokens=8)
        eng.run()
        # the wave that hit the fault errored cleanly (503, named reason)
        faulted = [s for s in (a, b, late) if s.error is not None]
        assert faulted, "the injected chunk fault must surface somewhere"
        for s in faulted:
            assert s.error.status_code == 503
            assert s.error.reason == "ENGINE_CHUNK_FAULT"
            assert s.event.is_set()
        # streams outside the faulted wave completed normally
        survivors = [s for s in (a, b, late) if s.error is None]
        assert all(s.result is not None for s in survivors)
        assert eng.engine_stats()["chunk_faults"] == 1
        assert not eng.has_work()
        with eng._lock:
            eng._check_invariants_locked()

    def test_engine_serves_bit_exact_after_chunk_fault(self, params):
        eng = _engine(params)
        faults.inject("paged.chunk", times=1)
        doomed = eng.submit(np.arange(10), max_new_tokens=8)
        eng.run()
        assert doomed.error is not None
        faults.clear()
        got = eng.generate(np.arange(10), max_new_tokens=8)
        want = _engine(params).generate(np.arange(10), max_new_tokens=8)
        np.testing.assert_array_equal(got, want)

    def test_speculative_chunk_fault_contained_too(self, params, monkeypatch):
        monkeypatch.setenv("SELDON_TPU_PAGED_DEBUG", "1")
        eng = _engine(
            params, speculative={"draft": "ngram", "draft_k": 2},
        )
        faults.inject("paged.chunk", times=1)
        s = eng.submit(np.array([3, 5, 3, 5, 3], np.int32), max_new_tokens=8)
        eng.run()
        assert s.event.is_set()
        assert s.result is not None or s.error.reason == "ENGINE_CHUNK_FAULT"
        assert not eng.has_work()
        with eng._lock:
            eng._check_invariants_locked()
        assert eng.engine_stats()["chunk_faults"] == 1


# ---------------------------------------------------------------------------
# transport delay / drop through the real node clients
# ---------------------------------------------------------------------------


def _run(coro):
    return asyncio.run(coro)


class TestTransportFaults:
    def test_rest_drop_recovers_via_retry(self):
        from aiohttp import web
        from aiohttp.test_utils import TestClient, TestServer

        from seldon_core_tpu.engine.graph import Endpoint, UnitSpec
        from seldon_core_tpu.engine.transport import RestClient
        from seldon_core_tpu.runtime.message import InternalMessage

        calls = {"n": 0}

        async def ok(request):
            calls["n"] += 1
            return web.json_response({"data": {"ndarray": [[9.0]]}})

        async def scenario():
            app = web.Application()
            app.router.add_post("/predict", ok)
            server = TestServer(app)
            tc = TestClient(server)
            await tc.start_server()
            unit = UnitSpec(
                name="m", type="MODEL",
                endpoint=Endpoint(host=server.host, port=server.port,
                                  transport="REST"),
            )
            client = RestClient(unit, retries=3)
            faults.inject("transport.drop", times=1)
            msg = InternalMessage(payload=np.array([[1.0]]), kind="ndarray")
            out = await client.transform_input(msg)
            await client.close()
            await tc.close()
            return out

        out = _run(scenario())
        assert out.array().tolist() == [[9.0]]
        assert calls["n"] == 1  # first attempt dropped before the wire
        assert faults.stats()["transport.drop"] >= 1

    def test_rest_drop_exhaustion_carries_injected_attempts(self):
        from aiohttp import web
        from aiohttp.test_utils import TestClient, TestServer

        from seldon_core_tpu.engine.graph import Endpoint, UnitSpec
        from seldon_core_tpu.engine.transport import RestClient
        from seldon_core_tpu.runtime.message import InternalMessage

        async def scenario():
            app = web.Application()
            server = TestServer(app)
            tc = TestClient(server)
            await tc.start_server()
            unit = UnitSpec(
                name="m", type="MODEL",
                endpoint=Endpoint(host=server.host, port=server.port,
                                  transport="REST"),
            )
            client = RestClient(unit, retries=2)
            faults.inject("transport.drop", times=5)
            msg = InternalMessage(payload=np.array([[1.0]]), kind="ndarray")
            try:
                await client.transform_input(msg)
            finally:
                await client.close()
                await tc.close()

        with pytest.raises(MicroserviceError) as ei:
            _run(scenario())
        assert len(ei.value.attempts) == 2
        assert all(a["status"] == "InjectedFault" for a in ei.value.attempts)

    def test_rest_delay_fires_and_call_still_succeeds(self):
        from aiohttp import web
        from aiohttp.test_utils import TestClient, TestServer

        from seldon_core_tpu.engine.graph import Endpoint, UnitSpec
        from seldon_core_tpu.engine.transport import RestClient
        from seldon_core_tpu.runtime.message import InternalMessage

        async def ok(request):
            return web.json_response({"data": {"ndarray": [[9.0]]}})

        async def scenario():
            app = web.Application()
            app.router.add_post("/predict", ok)
            server = TestServer(app)
            tc = TestClient(server)
            await tc.start_server()
            unit = UnitSpec(
                name="m", type="MODEL",
                endpoint=Endpoint(host=server.host, port=server.port,
                                  transport="REST"),
            )
            client = RestClient(unit)
            faults.inject("transport.delay", times=1, delay_ms=50)
            msg = InternalMessage(payload=np.array([[1.0]]), kind="ndarray")
            t0 = time.perf_counter()
            out = await client.transform_input(msg)
            elapsed = time.perf_counter() - t0
            await client.close()
            await tc.close()
            return out, elapsed

        out, elapsed = _run(scenario())
        assert out.array().tolist() == [[9.0]]
        assert elapsed >= 0.05
        assert faults.stats()["transport.delay"] >= 1

    def test_transport_slow_is_latency_not_error_with_its_own_budget(self):
        """The straggler point (r12): `transport.slow` delays an
        attempt WITHOUT failing it, and its times/prob budget is
        independent of `transport.delay`/`transport.drop` — so a chaos
        scenario can arm stragglers and drops simultaneously and tell
        the effects apart."""
        from aiohttp import web
        from aiohttp.test_utils import TestClient, TestServer

        from seldon_core_tpu.engine.graph import Endpoint, UnitSpec
        from seldon_core_tpu.engine.transport import RestClient
        from seldon_core_tpu.runtime.message import InternalMessage

        calls = {"n": 0}

        async def ok(request):
            calls["n"] += 1
            return web.json_response({"data": {"ndarray": [[9.0]]}})

        before = faults.stats()  # _fired_total is cumulative per process

        async def scenario():
            app = web.Application()
            app.router.add_post("/predict", ok)
            server = TestServer(app)
            tc = TestClient(server)
            await tc.start_server()
            unit = UnitSpec(
                name="m", type="MODEL",
                endpoint=Endpoint(host=server.host, port=server.port,
                                  transport="REST"),
            )
            client = RestClient(unit, retries=3)
            # both latency points armed with SEPARATE budgets, plus one
            # drop: every budget must fire independently
            faults.configure(
                "transport.slow:times=1,ms=80;"
                "transport.delay:times=1,ms=40;"
                "transport.drop:times=1"
            )
            msg = InternalMessage(payload=np.array([[1.0]]), kind="ndarray")
            t0 = time.perf_counter()
            out = await client.transform_input(msg)
            elapsed = time.perf_counter() - t0
            await client.close()
            await tc.close()
            return out, elapsed

        out, elapsed = _run(scenario())
        assert out.array().tolist() == [[9.0]]
        # slow fired (latency, no error): total covers both delays
        assert elapsed >= 0.08
        stats = faults.stats()
        assert stats["transport.slow"] - before.get("transport.slow", 0) == 1
        assert stats["transport.delay"] - before.get("transport.delay", 0) == 1
        # the drop still dropped — each budget independent of the others
        assert stats["transport.drop"] - before.get("transport.drop", 0) == 1
        assert calls["n"] == 1  # exactly one attempt reached the wire

    def test_grpc_drop_recovers_via_retry(self):
        async def scenario():
            import grpc

            from seldon_core_tpu.engine.graph import Endpoint, UnitSpec
            from seldon_core_tpu.engine.transport import GrpcClient
            from seldon_core_tpu.runtime import grpc_server
            from seldon_core_tpu.runtime.message import InternalMessage

            class Doubler:
                def predict(self, X, names, meta=None):
                    return np.asarray(X) * 2

            server = grpc_server.build_server(Doubler())
            port = server.add_insecure_port("127.0.0.1:0")
            await server.start()
            unit = UnitSpec(
                name="m", type="MODEL",
                endpoint=Endpoint(host="127.0.0.1", port=port,
                                  transport="GRPC"),
            )
            client = GrpcClient(unit, retries=3)
            faults.inject("transport.drop", times=1)
            msg = InternalMessage(payload=np.array([[2.0]]), kind="ndarray")
            out = await client.transform_input(msg)
            await client.close()
            await server.stop(None)
            return out

        out = _run(scenario())
        assert out.array().tolist() == [[4.0]]
        assert faults.stats()["transport.drop"] >= 1


# ---------------------------------------------------------------------------
# env-spec chaos: every point armed at once, concurrent load, audit on
# ---------------------------------------------------------------------------


class TestConcurrentChaos:
    def test_all_engine_points_under_concurrent_load(self, params, monkeypatch):
        monkeypatch.setenv("SELDON_TPU_PAGED_DEBUG", "1")
        monkeypatch.setenv(
            faults.ENV_VAR, "paged.alloc:times=3;paged.chunk:times=2"
        )
        faults.configure()  # from the env, as a worker process would
        eng = _engine(params, max_slots=2, num_pages=9, max_queue=8)
        results = []
        lock = threading.Lock()

        def client(i):
            try:
                s = eng.submit(np.arange(10) + i, max_new_tokens=10)
                s.event.wait(timeout=60)
                with lock:
                    results.append((i, s.result is not None, s.error))
            except MicroserviceError as e:  # shed at submit is legal
                with lock:
                    results.append((i, False, e))

        threads = [
            threading.Thread(target=client, args=(i,)) for i in range(6)
        ]
        stepper = threading.Thread(target=eng.run)
        for t in threads:
            t.start()
        time.sleep(0.01)
        stepper.start()
        for t in threads:
            t.join(timeout=90)
            assert not t.is_alive(), "stuck client thread"
        # the engine may briefly idle between client submits: drain
        # whatever is left, then the queue must be empty
        for _ in range(50):
            if not eng.has_work():
                break
            eng.step()
        stepper.join(timeout=60)
        assert len(results) == 6
        for i, ok_, err in results:
            assert ok_ or isinstance(err, MicroserviceError), (i, err)
        assert not eng.has_work()
        with eng._lock:
            eng._check_invariants_locked()  # audit clean after the storm
        # fail_all never needed — the engine still serves, bit-exact
        faults.clear()
        got = eng.generate(np.arange(10), max_new_tokens=8)
        want = _engine(params).generate(np.arange(10), max_new_tokens=8)
        np.testing.assert_array_equal(got, want)
        fired = faults.stats()
        assert fired.get("paged.alloc", 0) >= 1
        assert fired.get("paged.chunk", 0) >= 1


# ---------------------------------------------------------------------------
# r17 fault points: paged.nan (poison-stream quarantine) and
# transport.corrupt (KV-container byte flips)
# ---------------------------------------------------------------------------


class TestR17Grammar:
    """Strict-grammar negative tests for the new points — same
    discipline as the PR 9 suite: every malformation errors LOUDLY."""

    def test_new_points_parse_with_defaults(self):
        faults.configure("paged.nan;transport.corrupt:k=3,times=2")
        assert faults.fire("paged.nan")
        assert not faults.fire("paged.nan")
        assert faults.fire_k("transport.corrupt") == 3
        assert faults.fire_k("transport.corrupt") == 3
        assert faults.fire_k("transport.corrupt") == 0  # budget spent

    def test_k_defaults_to_one(self):
        faults.configure("transport.corrupt")
        assert faults.fire_k("transport.corrupt") == 1

    def test_bad_k_value_rejected(self):
        with pytest.raises(ValueError, match=r"bad value.*'k=many'"):
            faults.configure("transport.corrupt:k=many")
        with pytest.raises(ValueError, match="k must be >= 1"):
            faults.configure("transport.corrupt:k=0")
        with pytest.raises(ValueError, match="k must be >= 1"):
            faults.configure("transport.corrupt:k=-4")
        assert not faults.enabled()  # nothing half-armed

    def test_unknown_param_on_new_points_rejected(self):
        with pytest.raises(ValueError, match="unknown fault parameter"):
            faults.configure("paged.nan:bytes=1")

    def test_new_points_listed_in_unknown_point_error(self):
        with pytest.raises(ValueError) as e:
            faults.configure("paged.everything")
        assert "paged.nan" in str(e.value)
        assert "transport.corrupt" in str(e.value)

    def test_corrupt_bytes_noop_when_disarmed(self):
        data = bytes(range(64))
        assert faults.corrupt_bytes("transport.corrupt", data) == data

    def test_corrupt_bytes_flips_when_armed(self):
        faults.inject("transport.corrupt", times=1, k=2)
        data = bytes(64)
        out = faults.corrupt_bytes("transport.corrupt", data)
        assert out != data and len(out) == len(data)
        # budget spent: second call passes through untouched
        assert faults.corrupt_bytes("transport.corrupt", data) == data


class TestNanQuarantine:
    def test_injected_nan_quarantines_one_stream_wave_mates_bit_identical(
        self, params
    ):
        prompts = [np.arange(12) + i for i in range(3)]
        ref = _engine(params, max_slots=4)
        expect = [
            ref.generate(p, max_new_tokens=10, seed=i)
            for i, p in enumerate(prompts)
        ]
        eng = _engine(params, max_slots=4)
        streams = [
            eng.submit(p, max_new_tokens=10, seed=i)
            for i, p in enumerate(prompts)
        ]
        eng.step()  # prefill + first chunk, no fault
        fired_before = faults.stats().get("paged.nan", 0)
        faults.inject("paged.nan", times=1)
        eng.run()
        poisoned = [s for s in streams if s.error is not None]
        assert len(poisoned) == 1
        err = poisoned[0].error
        assert isinstance(err, MicroserviceError)
        assert err.status_code == 500
        assert err.reason == "NUMERIC_POISON"
        assert eng.engine_stats()["quarantined"] == 1
        assert faults.stats().get("paged.nan", 0) == fired_before + 1
        # the wave-mates' outputs are bit-identical to the no-fault run
        for s in streams:
            if s.error is None:
                i = streams.index(s)
                np.testing.assert_array_equal(s.result, expect[i])
        # the engine keeps serving bit-exact afterwards (never fail_all)
        got = eng.generate(np.arange(12), max_new_tokens=10, seed=0)
        np.testing.assert_array_equal(got, expect[0])

    def test_nan_guard_off_skips_screen(self, params, monkeypatch):
        monkeypatch.setenv("SELDON_TPU_NAN_GUARD", "0")
        eng = _engine(params, max_slots=2)
        s = eng.submit(np.arange(12), max_new_tokens=8)
        faults.inject("paged.nan", times=1)
        eng.run()
        # guard off: the injected NaN lane is NOT retired — the stream
        # completes (with whatever the poisoned argmax produced); the
        # quarantine counter stays 0.  This is exactly the silent-
        # garbage failure mode the default-on guard exists to close.
        assert s.error is None
        assert eng.engine_stats()["quarantined"] == 0

    def test_quarantined_stream_drops_poisoned_chunk_tokens(self, params):
        eng = _engine(params, max_slots=2)
        s = eng.submit(np.arange(12), max_new_tokens=16, stream_tokens=True)
        eng.step()  # wave 1: prefill + chunk
        pushed_before = s.streamed
        faults.inject("paged.nan", times=1)
        eng.step()  # wave 2: poisoned chunk — tokens must NOT stream
        assert s.error is not None and s.error.reason == "NUMERIC_POISON"
        assert s.streamed == pushed_before
        # consumer unblocks via the end-of-stream sentinel
        items = []
        while s.token_queue.qsize():
            items.append(s.token_queue.get())
        assert items[-1] is None


class TestTransportCorrupt:
    def test_corrupt_handoff_rejects_with_named_error(self, params):
        from seldon_core_tpu.codec.bufview import (
            pack_kv_handoff,
            unpack_kv_handoff,
        )
        from seldon_core_tpu.codec.tensor import PayloadError

        eng = _engine(params)
        payload = eng.prefill_export(np.arange(20), seed=3)
        buf = pack_kv_handoff(payload)
        faults.inject("transport.corrupt", times=1, k=1)
        bad = faults.corrupt_bytes("transport.corrupt", buf)
        assert bad != buf
        with pytest.raises(PayloadError):
            unpack_kv_handoff(bad)
        # the pristine container still decodes
        out = unpack_kv_handoff(buf)
        np.testing.assert_array_equal(out["prompt"], payload["prompt"])
