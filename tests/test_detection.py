"""Detection family: head shapes, CenterNet decode, checkpoint seeding.

Decode correctness is tested against hand-crafted head maps (known
peak, size, offset -> known box), independent of any trained weights.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from seldon_core_tpu.models.detection import (

    Detector,
    decode_detections,
    make_detector,
)


pytestmark = pytest.mark.slow  # compile-heavy: excluded from the default fast tier (make test-all)


class TestDecode:
    def _maps(self, h=8, w=8, c=3):
        heat = np.full((1, h, w, c), -10.0, np.float32)  # sigmoid ~ 0
        size = np.zeros((1, h, w, 2), np.float32)
        offset = np.zeros((1, h, w, 2), np.float32)
        return heat, size, offset

    def test_single_peak_recovers_box(self):
        heat, size, offset = self._maps()
        cy, cx, cls = 3, 5, 2
        heat[0, cy, cx, cls] = 10.0  # sigmoid ~ 1
        size[0, cy, cx] = [4.0, 6.0]     # w, h in cells
        offset[0, cy, cx] = [0.25, 0.5]  # x, y sub-cell
        out = np.asarray(decode_detections(
            jnp.asarray(heat), jnp.asarray(size), jnp.asarray(offset),
            top_k=5, stride=16, score_threshold=0.5,
        ))
        x1, y1, x2, y2, score, klass = out[0, 0]
        center_x, center_y = (cx + 0.25) * 16, (cy + 0.5) * 16
        assert score > 0.99 and int(klass) == cls
        np.testing.assert_allclose(
            [x1, y1, x2, y2],
            [center_x - 32, center_y - 48, center_x + 32, center_y + 48],
            atol=1e-4,
        )
        # rows under the threshold (flat background "peaks") are zeroed
        assert np.allclose(out[0, 1:], 0.0)

    def test_peak_nms_suppresses_neighbours(self):
        heat, size, offset = self._maps()
        heat[0, 4, 4, 0] = 10.0
        heat[0, 4, 5, 0] = 9.0  # adjacent, weaker -> suppressed
        heat[0, 1, 1, 0] = 8.0  # distant -> second detection
        out = np.asarray(decode_detections(
            jnp.asarray(heat), jnp.asarray(size), jnp.asarray(offset), top_k=5
        ))
        scores = out[0, :, 4]
        assert (scores > 0.5).sum() == 2  # the 9.0 neighbour is gone

    def test_score_threshold_zeroes_rows(self):
        heat, size, offset = self._maps()
        heat[0, 2, 2, 0] = 10.0
        heat[0, 6, 6, 1] = -2.0  # sigmoid ~ 0.12
        out = np.asarray(decode_detections(
            jnp.asarray(heat), jnp.asarray(size), jnp.asarray(offset),
            top_k=5, score_threshold=0.5,
        ))
        assert (out[0, :, 4] > 0).sum() == 1

    def test_static_shapes_and_jittable(self):
        heat, size, offset = self._maps()
        fn = jax.jit(lambda h, s, o: decode_detections(h, s, o, top_k=7))
        out = fn(jnp.asarray(heat), jnp.asarray(size), jnp.asarray(offset))
        assert out.shape == (1, 7, 6)


class TestDetectorModule:
    def test_head_map_shapes(self):
        det = Detector(num_classes=5, backbone="resnet_tiny",
                       num_filters=8, head_dim=16, dtype=jnp.float32)
        variables = det.init(jax.random.key(0), jnp.zeros((1, 64, 64, 3)))
        heat, size, offset = det.apply(variables, jnp.ones((2, 64, 64, 3)))
        # stride-32 backbone map upsampled x2 -> stride 16: 64/16 = 4
        assert heat.shape == (2, 4, 4, 5)
        assert size.shape == (2, 4, 4, 2) and offset.shape == (2, 4, 4, 2)

    def test_classifier_checkpoint_seeds_backbone(self):
        """An ImageNet-style classifier checkpoint (same tree the
        torch/TF converters emit) drops into the detector backbone."""
        from seldon_core_tpu.models import resnet as resnet_mod

        classifier = resnet_mod.ResNetTiny(num_classes=1000, dtype=jnp.float32)
        cvars = classifier.init(jax.random.key(1), jnp.zeros((1, 64, 64, 3)))

        det = Detector(num_classes=5, backbone="resnet_tiny",
                       num_filters=8, head_dim=16, dtype=jnp.float32)
        dvars = det.init(jax.random.key(0), jnp.zeros((1, 64, 64, 3)))
        assert (
            jax.tree_util.tree_structure(dvars["params"]["backbone"])
            == jax.tree_util.tree_structure(cvars["params"])
        )
        grafted = {
            "params": {**dvars["params"], "backbone": cvars["params"]},
            "batch_stats": {**dvars["batch_stats"], "backbone": cvars["batch_stats"]},
        }
        x = jnp.asarray(np.random.default_rng(0).normal(size=(1, 64, 64, 3)), jnp.float32)
        heat, _, _ = det.apply(grafted, x)
        # the grafted backbone must produce the classifier's features
        _, want_features = classifier.apply(cvars, x, capture_features=True)
        got_features = det.apply(
            grafted, x, method=lambda m, x: m.backbone_module(x, capture_features=True)
        )[1]
        np.testing.assert_allclose(np.asarray(got_features), np.asarray(want_features))
        assert np.isfinite(np.asarray(heat)).all()


class TestServing:
    def test_detector_through_jaxserver(self):
        from seldon_core_tpu.models.jaxserver import JaxServer

        server = JaxServer(
            model="detector_tiny", num_classes=5, input_shape=(64, 64, 3),
            dtype="float32", max_batch_size=2, warmup=False,
            warmup_dtypes=("float32",),
            model_kwargs={"num_filters": 8, "head_dim": 16, "top_k": 10},
        )
        server.load()
        out = np.asarray(server.predict(np.zeros((2, 64, 64, 3), np.float32), []))
        assert out.shape == (2, 10, 6)
        assert np.isfinite(out).all()
        server.unload()

    def test_registry_has_detector_family(self):
        from seldon_core_tpu.models.jaxserver import _model_registry

        names = set(_model_registry())
        assert {"detector_tiny", "detector_resnet18", "detector_resnet50"} <= names
