"""SLO-aware request lifecycle: end-to-end deadlines, priority
admission with load shedding, and preemptive evict/restore.

Covers the r10 robustness layer end to end:

* deadline primitives (utils/deadlines): carrier extraction, contextvar
  activation (tighter-wins nesting), per-hop injection, fast-fail;
* the paged engine's SLO admission: expired submits fast-fail, queued
  expiry is shed before touching the device, mid-decode expiry cancels
  at the chunk boundary, the bounded queue sheds expired-first then
  lowest-priority, higher priority admits first, and a pages-starved
  high-priority admission preempts (then restores) a lower-priority
  in-flight stream;
* deadline-expiry e2e through BOTH the REST and gRPC microservice
  lanes: an expired upstream budget never reaches the model and the
  error names the exhausted hop;
* RestClient's bounded retries with per-attempt history (the GrpcClient
  parity satellite).
"""

import asyncio
import threading
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from seldon_core_tpu.models.paged import PagedEngine
from seldon_core_tpu.models.transformer import TransformerLM
from seldon_core_tpu.runtime.component import MicroserviceError, TPUComponent
from seldon_core_tpu.utils import deadlines


CFG = dict(vocab_size=64, d_model=32, num_layers=1, num_heads=2, max_len=64)


@pytest.fixture(scope="module")
def params():
    module = TransformerLM(dtype=jnp.float32, **CFG)
    return module.init(jax.random.key(0), jnp.zeros((1, 8), jnp.int32))["params"]


def _engine(params, **kw):
    base = dict(dtype=jnp.float32, page_size=8, max_slots=2, steps_per_call=4)
    base.update(kw)
    return PagedEngine(params, **CFG, **base)


# ---------------------------------------------------------------------------
# deadline primitives
# ---------------------------------------------------------------------------


class TestDeadlinePrimitives:
    def test_after_ms_remaining_and_expiry(self):
        d = deadlines.Deadline.after_ms(50)
        assert 0 < d.remaining_ms() <= 50
        assert not d.expired
        assert deadlines.Deadline(expires_at=time.monotonic() - 1).expired

    def test_extract_from_dict_headers_and_metadata_tuples(self):
        assert deadlines.extract_ms({"X-Seldon-Deadline-Ms": "250"}) == 250.0
        assert deadlines.extract_ms({"x-seldon-deadline-ms": "40.5"}) == 40.5
        assert deadlines.extract_ms(
            [("x-seldon-deadline-ms", "10"), ("other", "1")]
        ) == 10.0
        assert deadlines.extract_ms({}) is None
        assert deadlines.extract_ms(None) is None

    def test_extract_malformed_is_none_never_raises(self):
        for bad in ("abc", "", "nan", "inf", None):
            assert deadlines.extract_ms({"X-Seldon-Deadline-Ms": bad}) is None

    def test_extract_clamps_negative_and_absurd(self):
        assert deadlines.extract_ms({"X-Seldon-Deadline-Ms": "-5"}) == 0.0
        assert (
            deadlines.extract_ms({"X-Seldon-Deadline-Ms": "1e18"})
            == deadlines.MAX_DEADLINE_MS
        )

    def test_extract_priority(self):
        assert deadlines.extract_priority({"X-Seldon-Priority": "3"}) == 3
        assert deadlines.extract_priority(
            [("x-seldon-priority", "-2")]
        ) == -2
        assert deadlines.extract_priority({"X-Seldon-Priority": "junk"}) is None
        assert deadlines.extract_priority({}) is None
        # unauthenticated wire: the band clamps (preemption weapon)
        assert deadlines.extract_priority(
            {"X-Seldon-Priority": "999999999"}
        ) == deadlines.MAX_PRIORITY
        assert deadlines.extract_priority(
            {"X-Seldon-Priority": "-999999999"}
        ) == -deadlines.MAX_PRIORITY

    def test_activation_and_injection_roundtrip(self):
        assert deadlines.current_deadline() is None
        with deadlines.activate_ms(5000):
            d = deadlines.current_deadline()
            assert d is not None and 0 < d.remaining_ms() <= 5000
            headers = deadlines.inject({})
            assert int(headers["X-Seldon-Deadline-Ms"]) <= 5000
            md = deadlines.inject_metadata([("a", "b")])
            assert md[0] == ("a", "b")
            assert md[1][0] == deadlines.DEADLINE_HEADER
        assert deadlines.current_deadline() is None
        # no active budget: injection is a no-op
        assert deadlines.inject({}) == {}
        assert deadlines.inject_metadata() == []

    def test_nested_activation_tighter_wins(self):
        with deadlines.activate_ms(10_000):
            outer = deadlines.current_deadline()
            # a LOOSER inner budget cannot extend the caller's
            with deadlines.activate_ms(60_000):
                assert deadlines.current_deadline() is outer
            with deadlines.activate_ms(10):
                inner = deadlines.current_deadline()
                assert inner is not outer
                assert inner.remaining_ms() <= 10

    def test_check_raises_504_naming_the_hop(self):
        with deadlines.activate(deadlines.Deadline(time.monotonic() - 0.5)):
            with pytest.raises(MicroserviceError) as ei:
                deadlines.check("node 'lm' predict (local)")
        assert ei.value.status_code == 504
        assert ei.value.reason == "DEADLINE_EXCEEDED"
        assert "node 'lm' predict (local)" in str(ei.value)
        deadlines.check("no active deadline is a no-op")


# ---------------------------------------------------------------------------
# engine: priority admission, shedding, expiry, preempt/restore
# ---------------------------------------------------------------------------


class TestEngineDeadlines:
    def test_expired_submit_fast_fails_before_queueing(self, params):
        eng = _engine(params)
        with pytest.raises(MicroserviceError) as ei:
            eng.submit(np.arange(8), deadline=time.monotonic() - 0.01)
        assert ei.value.status_code == 504
        assert ei.value.reason == "DEADLINE_EXCEEDED"
        assert eng.engine_stats()["queued_streams"] == 0

    def test_queued_expiry_is_shed_before_the_device(self, params):
        eng = _engine(params, max_slots=1)
        healthy = eng.submit(np.arange(8), max_new_tokens=8)
        doomed = eng.submit(
            np.arange(8) + 1, max_new_tokens=8,
            deadline=time.monotonic() + 0.002,
        )
        time.sleep(0.01)  # budget dies while queued
        prefills_before = eng.engine_stats()["prefills"]
        eng.run()
        assert healthy.result is not None
        assert isinstance(doomed.error, MicroserviceError)
        assert doomed.error.reason == "DEADLINE_EXCEEDED"
        assert "queue" in str(doomed.error)
        stats = eng.engine_stats()
        assert stats["expired"] == 1
        # the expired stream never consumed an admission/prefill
        assert stats["prefills"] - prefills_before == 1

    def test_mid_decode_expiry_cancels_at_chunk_boundary(self, params):
        eng = _engine(params, max_slots=1)
        stream = eng.submit(
            np.arange(8), max_new_tokens=40,
            deadline=time.monotonic() + 0.001,
        )
        eng.step()  # admit + prefill + first chunk
        time.sleep(0.005)
        eng.run()
        assert isinstance(stream.error, MicroserviceError)
        assert stream.error.reason == "DEADLINE_EXCEEDED"
        assert "decode" in str(stream.error)
        assert eng.engine_stats()["expired"] == 1
        assert not eng.has_work()
        # engine stays healthy
        assert eng.generate(np.arange(6), max_new_tokens=4).shape == (4,)

    def test_no_deadline_streams_never_expire(self, params):
        eng = _engine(params)
        out = eng.generate(np.arange(10), max_new_tokens=8)
        assert out.shape == (8,)
        stats = eng.engine_stats()
        assert stats["expired"] == 0 and stats["shed"] == 0


class TestBoundedQueueShedding:
    def test_overflow_sheds_expired_first(self, params):
        eng = _engine(params, max_slots=1, max_queue=2)
        running = eng.submit(np.arange(8), max_new_tokens=16)
        eng.step()  # occupy the slot so later submits queue
        doomed = eng.submit(
            np.arange(8) + 1, deadline=time.monotonic() + 0.001
        )
        healthy = eng.submit(np.arange(8) + 2, max_new_tokens=4)
        time.sleep(0.005)
        # queue full (2): the expired stream sheds, NOT the healthy one
        late = eng.submit(np.arange(8) + 3, max_new_tokens=4)
        assert isinstance(doomed.error, MicroserviceError)
        assert doomed.error.reason == "DEADLINE_EXCEEDED"
        eng.run()
        assert healthy.result is not None and late.result is not None
        assert running.result is not None
        assert eng.engine_stats()["expired"] == 1

    def test_overflow_sheds_lowest_priority_for_a_higher_one(self, params):
        eng = _engine(params, max_slots=1, max_queue=2)
        eng.submit(np.arange(8), max_new_tokens=16)
        eng.step()
        low = eng.submit(np.arange(8) + 1, max_new_tokens=4, priority=0)
        mid = eng.submit(np.arange(8) + 2, max_new_tokens=4, priority=1)
        vip = eng.submit(np.arange(8) + 3, max_new_tokens=4, priority=5)
        assert isinstance(low.error, MicroserviceError)
        assert low.error.reason == "SHED"
        assert low.error.status_code == 503
        eng.run()
        assert mid.result is not None and vip.result is not None
        assert eng.engine_stats()["shed"] == 1

    def test_overflow_rejects_the_newcomer_when_it_ranks_lowest(self, params):
        eng = _engine(params, max_slots=1, max_queue=1)
        eng.submit(np.arange(8), max_new_tokens=16)
        eng.step()
        queued = eng.submit(np.arange(8) + 1, max_new_tokens=4, priority=2)
        with pytest.raises(MicroserviceError) as ei:
            eng.submit(np.arange(8) + 2, max_new_tokens=4, priority=2)
        assert ei.value.reason == "SHED"
        assert ei.value.status_code == 503
        eng.run()
        assert queued.result is not None
        assert eng.engine_stats()["shed"] == 1

    def test_unbounded_default_never_sheds(self, params):
        eng = _engine(params, max_slots=1)
        streams = [
            eng.submit(np.arange(8) + i, max_new_tokens=2) for i in range(8)
        ]
        eng.run()
        assert all(s.result is not None for s in streams)
        assert eng.engine_stats()["shed"] == 0


class TestPredictSiblingCleanup:
    def test_failed_row_cancels_submitted_siblings(self):
        """Multi-row predict under shedding: when a later row's submit
        raises (queue full, 503 SHED), the already-submitted sibling
        streams must be cancelled, not left decoding unread — they hold
        slots and pages exactly when the engine is overloaded enough to
        shed."""
        from seldon_core_tpu.models.paged import StreamingLM

        comp = StreamingLM(
            max_new_tokens=4, max_slots=1, page_size=8, steps_per_call=2,
            max_queue=1, **CFG,
        )
        comp.load()
        try:
            # blocker owns the single slot for many chunks
            blocker = comp.engine.submit(
                np.arange(8, dtype=np.int32), max_new_tokens=40
            )
            comp._wake.set()
            for _ in range(200):
                if blocker.slot is not None:
                    break
                time.sleep(0.01)
            # row 0 fills the queue (bound 1); row 1 overflows and the
            # equal-priority policy rejects the newcomer with SHED
            with pytest.raises(MicroserviceError) as exc_info:
                comp.predict(np.asarray([[1, 2, 3], [4, 5, 6]], np.int32), [])
            assert exc_info.value.reason == "SHED"
            blocker.event.wait(timeout=60)
            for _ in range(500):
                if not comp.engine.has_work():
                    break
                time.sleep(0.01)
            assert not comp.engine.has_work()
            # the cancelled sibling was resolved FROM THE QUEUE — only
            # the blocker ever decoded to completion (pre-fix, row 0
            # kept its queue spot and decoded all 4 tokens unread)
            assert comp.engine.engine_stats()["completed"] == 1
        finally:
            comp.shutdown()


class TestPriorityAdmission:
    def test_higher_priority_admits_first(self, params):
        eng = _engine(params, max_slots=1)
        blocker = eng.submit(np.arange(8), max_new_tokens=4)
        eng.run()  # slot free again, compiles warm
        assert blocker.result is not None
        low = eng.submit(np.arange(8) + 1, max_new_tokens=4, priority=0)
        high = eng.submit(np.arange(8) + 2, max_new_tokens=4, priority=3)
        finish_order = []
        for s, name in ((low, "low"), (high, "high")):
            def waiter(s=s, name=name):
                s.event.wait(timeout=30)
                finish_order.append(name)
            threading.Thread(target=waiter, daemon=True).start()
        eng.run()
        for _ in range(100):
            if len(finish_order) == 2:
                break
            time.sleep(0.01)
        assert finish_order == ["high", "low"]

    def test_equal_priorities_stay_fifo(self, params):
        eng = _engine(params, max_slots=1)
        first = eng.submit(np.arange(8), max_new_tokens=4)
        second = eng.submit(np.arange(8) + 1, max_new_tokens=4)
        eng.step()  # one admission wave: the FIFO head takes the slot
        assert first.slot is not None
        assert second.slot is None
        eng.run()
        assert first.result is not None and second.result is not None


class TestPreemptiveEvictRestore:
    def test_high_priority_admission_preempts_for_pages(self, params):
        # 6 usable pages; the batch stream grows toward 6 so the
        # interactive admission (needs 3) can only get pages by
        # preempting it
        eng = _engine(params, max_slots=2, num_pages=7)
        batch = eng.submit(np.arange(17), max_new_tokens=24, priority=0)
        for _ in range(4):
            eng.step()
        assert batch.slot is not None and len(batch.pages) >= 5
        vip = eng.submit(np.arange(17) + 1, max_new_tokens=4, priority=5)
        eng.step()
        stats = eng.engine_stats()
        assert stats["preempted"] >= 1
        assert vip.slot is not None or vip.result is not None
        eng.run()
        assert vip.result is not None
        assert batch.result is not None  # restored and completed
        stats = eng.engine_stats()
        assert stats["restored"] >= 1
        # preemption must not corrupt the batch stream: greedy decode
        # re-derives deterministically after restore
        fresh = _engine(params, max_slots=2)
        want = fresh.generate(np.arange(17), max_new_tokens=24)
        np.testing.assert_array_equal(batch.result, want)

    def test_equal_priority_never_preempts(self, params):
        eng = _engine(params, max_slots=2, num_pages=7)
        a = eng.submit(np.arange(17), max_new_tokens=16, priority=1)
        for _ in range(3):
            eng.step()
        b = eng.submit(np.arange(17) + 1, max_new_tokens=4, priority=1)
        eng.run()
        assert a.result is not None and b.result is not None
        assert eng.engine_stats()["preempted"] == 0

    def test_allocator_audit_clean_through_preemption(self, params, monkeypatch):
        monkeypatch.setenv("SELDON_TPU_PAGED_DEBUG", "1")
        eng = _engine(params, max_slots=2, num_pages=7)
        batch = eng.submit(np.arange(17), max_new_tokens=24, priority=0)
        for _ in range(4):
            eng.step()
        vip = eng.submit(np.arange(17) + 1, max_new_tokens=4, priority=5)
        eng.run()  # audit runs at every chunk boundary
        assert vip.result is not None and batch.result is not None
        with eng._lock:
            eng._check_invariants_locked()


class TestEngineStatsContract:
    def test_slo_counters_present_and_bridged(self, params):
        from seldon_core_tpu.utils.metrics import (
            ENGINE_STATS_EXCLUDED,
            ENGINE_STATS_METRICS,
        )

        eng = _engine(params)
        stats = eng.engine_stats()
        for key in ("shed", "expired", "preempted", "restored", "chunk_faults"):
            assert key in stats
            assert key in ENGINE_STATS_METRICS or key in ENGINE_STATS_EXCLUDED

    def test_chunk_records_carry_slo_deltas(self, params):
        eng = _engine(params, max_slots=1)
        eng.submit(np.arange(8), max_new_tokens=8,
                   deadline=time.monotonic() + 0.002)
        eng.submit(np.arange(8) + 1, max_new_tokens=4)
        time.sleep(0.01)
        eng.run()
        recs = eng.engine_stats(detail=True)["recorder"]
        assert recs, "flight recorder should have chunk records"
        for key in ("shed", "expired", "preempted", "restored"):
            assert key in recs[-1]
        assert sum(r["expired"] for r in recs) >= 1


# ---------------------------------------------------------------------------
# e2e: expired upstream budget never reaches the model, on both lanes
# ---------------------------------------------------------------------------


class CountingModel(TPUComponent):
    def __init__(self):
        self.calls = 0

    def predict(self, X, names, meta=None):
        self.calls += 1
        return np.asarray(X) * 2


def _run(coro):
    return asyncio.run(coro)


async def _rest_client(app):
    from aiohttp.test_utils import TestClient, TestServer

    server = TestServer(app)
    client = TestClient(server)
    await client.start_server()
    return client


class TestDeadlineE2ERest:
    def test_expired_budget_never_reaches_the_model(self):
        from seldon_core_tpu.runtime import rest

        model = CountingModel()

        async def scenario():
            client = await _rest_client(rest.build_app(model))
            resp = await client.post(
                "/predict",
                json={"data": {"ndarray": [[1.0, 2.0]]}},
                headers={"X-Seldon-Deadline-Ms": "0"},
            )
            body = await resp.json()
            await client.close()
            return resp.status, body

        status, body = _run(scenario())
        assert status == 504
        assert body["status"]["reason"] == "DEADLINE_EXCEEDED"
        assert "ingress /predict" in body["status"]["info"]
        assert model.calls == 0

    def test_generous_budget_passes_through(self):
        from seldon_core_tpu.runtime import rest

        model = CountingModel()

        async def scenario():
            client = await _rest_client(rest.build_app(model))
            resp = await client.post(
                "/predict",
                json={"data": {"ndarray": [[1.0, 2.0]]}},
                headers={"X-Seldon-Deadline-Ms": "30000"},
            )
            body = await resp.json()
            await client.close()
            return resp.status, body

        status, body = _run(scenario())
        assert status == 200
        assert body["data"]["ndarray"] == [[2.0, 4.0]]
        assert model.calls == 1


class TestDeadlineE2EGrpc:
    def _roundtrip(self, model, metadata):
        async def scenario():
            import grpc

            from seldon_core_tpu.proto import pb, services
            from seldon_core_tpu.runtime import grpc_server

            server = grpc_server.build_server(model)
            port = server.add_insecure_port("127.0.0.1:0")
            await server.start()
            channel = grpc.aio.insecure_channel(f"127.0.0.1:{port}")
            call = services.unary_callable(channel, "Model", "Predict")
            req = pb.SeldonMessage()
            req.data.tensor.shape.extend([1, 2])
            req.data.tensor.values.extend([1.0, 2.0])
            resp = await call(req, metadata=metadata, timeout=10)
            await channel.close()
            await server.stop(None)
            return resp

        return _run(scenario())

    def test_expired_metadata_budget_never_reaches_the_model(self):
        model = CountingModel()
        resp = self._roundtrip(model, [("x-seldon-deadline-ms", "0")])
        assert resp.status.code == 504
        assert resp.status.reason == "DEADLINE_EXCEEDED"
        assert "grpc ingress" in resp.status.info
        assert model.calls == 0

    def test_generous_metadata_budget_passes_through(self):
        model = CountingModel()
        resp = self._roundtrip(model, [("x-seldon-deadline-ms", "30000")])
        assert not resp.status.reason
        assert list(resp.data.tensor.values) == [2.0, 4.0]
        assert model.calls == 1


# ---------------------------------------------------------------------------
# NodeClient hop behaviour: fast-fail + downstream injection
# ---------------------------------------------------------------------------


class TestNodeClientDeadlines:
    def test_local_client_fast_fails_naming_the_hop(self):
        from seldon_core_tpu.engine.graph import UnitSpec
        from seldon_core_tpu.engine.transport import LocalClient
        from seldon_core_tpu.runtime.message import InternalMessage

        model = CountingModel()
        client = LocalClient(UnitSpec(name="lm", type="MODEL"), model)
        msg = InternalMessage(payload=np.array([[1.0]]), kind="ndarray")

        async def scenario():
            with deadlines.activate(deadlines.Deadline(time.monotonic() - 1)):
                await client.transform_input(msg)

        with pytest.raises(MicroserviceError) as ei:
            _run(scenario())
        assert ei.value.reason == "DEADLINE_EXCEEDED"
        assert "'lm'" in str(ei.value) and "local" in str(ei.value)
        assert model.calls == 0

    def test_rest_client_injects_remaining_budget_downstream(self):
        from aiohttp import web

        from seldon_core_tpu.engine.graph import Endpoint, UnitSpec
        from seldon_core_tpu.engine.transport import RestClient
        from seldon_core_tpu.runtime.message import InternalMessage

        seen = {}

        async def handler(request):
            seen.update(request.headers)
            return web.json_response({"data": {"ndarray": [[1.0]]}})

        async def scenario():
            from aiohttp.test_utils import TestClient, TestServer

            app = web.Application()
            app.router.add_post("/transform-input", handler)
            server = TestServer(app)
            tc = TestClient(server)
            await tc.start_server()
            unit = UnitSpec(
                name="remote", type="TRANSFORMER",
                endpoint=Endpoint(host=server.host, port=server.port,
                                  transport="REST"),
            )
            client = RestClient(unit)
            msg = InternalMessage(payload=np.array([[1.0]]), kind="ndarray")
            with deadlines.activate_ms(20_000):
                await client.transform_input(msg)
            await client.close()
            await tc.close()

        _run(scenario())
        assert "X-Seldon-Deadline-Ms" in seen
        assert 0 < int(seen["X-Seldon-Deadline-Ms"]) <= 20_000


# ---------------------------------------------------------------------------
# RestClient retry parity with GrpcClient (r10 satellite)
# ---------------------------------------------------------------------------


class TestRestClientRetries:
    def _client_for(self, app_handler_map, retries=3):
        """(TestClient-started app, RestClient) builder run inside the
        caller's scenario coroutine."""

        async def build():
            from aiohttp import web
            from aiohttp.test_utils import TestClient, TestServer

            from seldon_core_tpu.engine.graph import Endpoint, UnitSpec
            from seldon_core_tpu.engine.transport import RestClient

            app = web.Application()
            for path, handler in app_handler_map.items():
                app.router.add_post(path, handler)
            server = TestServer(app)
            tc = TestClient(server)
            await tc.start_server()
            unit = UnitSpec(
                name="flaky", type="MODEL",
                endpoint=Endpoint(host=server.host, port=server.port,
                                  transport="REST"),
            )
            return tc, RestClient(unit, retries=retries)

        return build

    def test_transient_503_retries_then_succeeds(self):
        from aiohttp import web

        from seldon_core_tpu.runtime.message import InternalMessage

        calls = {"n": 0}

        async def flaky(request):
            calls["n"] += 1
            if calls["n"] <= 2:
                return web.json_response(
                    {"status": {"status": "FAILURE", "code": 503}}, status=503
                )
            return web.json_response({"data": {"ndarray": [[7.0]]}})

        async def scenario():
            tc, client = await self._client_for({"/predict": flaky})()
            msg = InternalMessage(payload=np.array([[1.0]]), kind="ndarray")
            out = await client.transform_input(msg)
            await client.close()
            await tc.close()
            return out

        out = _run(scenario())
        assert calls["n"] == 3
        assert out.array().tolist() == [[7.0]]

    def test_exhausted_retries_carry_per_attempt_history(self):
        from aiohttp import web

        from seldon_core_tpu.runtime.message import InternalMessage

        async def always_503(request):
            return web.json_response(
                {"status": {"status": "FAILURE", "code": 503}}, status=503
            )

        async def scenario():
            tc, client = await self._client_for({"/predict": always_503})()
            msg = InternalMessage(payload=np.array([[1.0]]), kind="ndarray")
            try:
                await client.transform_input(msg)
            finally:
                await client.close()
                await tc.close()

        with pytest.raises(MicroserviceError) as ei:
            _run(scenario())
        err = ei.value
        assert err.reason == "UPSTREAM_REST_ERROR"
        assert len(err.attempts) == 3
        assert [a["attempt"] for a in err.attempts] == [1, 2, 3]
        assert all(a["status"] == "503" for a in err.attempts)
        assert all("elapsed_ms" in a for a in err.attempts)
        assert "attempts" in str(err)  # history in the message too

    def test_non_transient_4xx_never_retries(self):
        from aiohttp import web

        from seldon_core_tpu.runtime.message import InternalMessage

        calls = {"n": 0}

        async def bad_request(request):
            calls["n"] += 1
            return web.json_response(
                {"status": {"status": "FAILURE", "code": 400}}, status=400
            )

        async def scenario():
            tc, client = await self._client_for({"/predict": bad_request})()
            msg = InternalMessage(payload=np.array([[1.0]]), kind="ndarray")
            try:
                await client.transform_input(msg)
            finally:
                await client.close()
                await tc.close()

        with pytest.raises(MicroserviceError):
            _run(scenario())
        assert calls["n"] == 1

    def test_send_feedback_is_exempt_from_retries(self):
        from aiohttp import web

        from seldon_core_tpu.runtime.message import InternalFeedback

        calls = {"n": 0}

        async def always_503(request):
            calls["n"] += 1
            return web.json_response(
                {"status": {"status": "FAILURE", "code": 503}}, status=503
            )

        async def scenario():
            tc, client = await self._client_for({"/send-feedback": always_503})()
            try:
                await client.send_feedback(InternalFeedback(reward=1.0))
            finally:
                await client.close()
                await tc.close()

        with pytest.raises(MicroserviceError):
            _run(scenario())
        assert calls["n"] == 1  # non-idempotent: one attempt only
