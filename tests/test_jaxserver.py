"""Dynamic batcher + jaxserver + model zoo tests."""

import threading
import time

import numpy as np
import pytest

from seldon_core_tpu.batching import (
    DynamicBatcher,
    MultiSignatureBatcher,
    bucket_for,
    default_buckets,
)
from seldon_core_tpu.runtime import InternalMessage, MicroserviceError
from seldon_core_tpu.runtime import dispatch


class TestBuckets:
    def test_default_buckets(self):
        assert default_buckets(64) == [1, 2, 4, 8, 16, 32, 64]
        assert default_buckets(48) == [1, 2, 4, 8, 16, 32, 48]
        assert default_buckets(1) == [1]

    def test_bucket_for(self):
        buckets = [1, 2, 4, 8]
        assert bucket_for(1, buckets) == 1
        assert bucket_for(3, buckets) == 4
        assert bucket_for(8, buckets) == 8
        assert bucket_for(100, buckets) == 8

    def test_normalize_buckets(self):
        from seldon_core_tpu.batching import normalize_buckets

        # force-appends max_batch_size when the user list stops short
        assert normalize_buckets([1, 4, 16], 32) == [1, 4, 16, 32]
        # caps over-max buckets
        assert normalize_buckets([1, 4, 64], 32) == [1, 4, 32]
        assert normalize_buckets(None, 4) == [1, 2, 4]
        with pytest.raises(ValueError):
            normalize_buckets([1], 0)

    def test_multi_signature_batcher_normalizes_and_validates(self):
        from seldon_core_tpu.batching import MultiSignatureBatcher

        b = MultiSignatureBatcher(lambda x: x, max_batch_size=32, buckets=[1, 4, 16])
        assert b.buckets == [1, 4, 16, 32]
        with pytest.raises(ValueError):
            MultiSignatureBatcher(lambda x: x, max_batch_size=0)


class TestDynamicBatcher:
    def test_single_request(self):
        calls = []

        def fn(batch):
            calls.append(batch.shape)
            return batch * 2

        with DynamicBatcher(fn, max_batch_size=8, max_wait_ms=1.0) as b:
            out = b.submit(np.ones((3, 2)))
        np.testing.assert_array_equal(out, np.ones((3, 2)) * 2)
        # 3 rows padded to bucket 4
        assert calls == [(4, 2)]

    def test_concurrent_requests_coalesce(self):
        calls = []
        release = threading.Event()

        def fn(batch):
            calls.append(batch.shape[0])
            return batch + 1

        b = DynamicBatcher(fn, max_batch_size=32, max_wait_ms=20.0)
        b.start()
        results = {}

        def worker(i):
            release.wait()
            results[i] = b.submit(np.full((1, 4), float(i)))

        threads = [threading.Thread(target=worker, args=(i,)) for i in range(8)]
        for t in threads:
            t.start()
        release.set()
        for t in threads:
            t.join()
        b.stop()
        # every caller got its own row back
        for i in range(8):
            np.testing.assert_array_equal(results[i], np.full((1, 4), float(i) + 1))
        # fewer device calls than requests (coalesced)
        assert sum(calls) >= 8
        assert len(calls) < 8

    def test_row_order_preserved(self):
        def fn(batch):
            return batch

        with DynamicBatcher(fn, max_batch_size=16, max_wait_ms=5.0) as b:
            out = b.submit(np.arange(12, dtype=np.float64).reshape(6, 2))
        np.testing.assert_array_equal(out, np.arange(12).reshape(6, 2))

    def test_padding_never_leaks(self):
        def fn(batch):
            return batch.sum(axis=1, keepdims=True)

        with DynamicBatcher(fn, max_batch_size=8, max_wait_ms=0.5) as b:
            out = b.submit(np.ones((5, 3)))
        assert out.shape == (5, 1)
        np.testing.assert_array_equal(out, np.full((5, 1), 3.0))

    def test_error_propagates_to_caller(self):
        def fn(batch):
            raise RuntimeError("device on fire")

        with DynamicBatcher(fn, max_batch_size=4, max_wait_ms=0.5) as b:
            with pytest.raises(RuntimeError, match="device on fire"):
                b.submit(np.ones((1, 2)))

    def test_oversized_request_served_whole(self):
        shapes = []

        def fn(batch):
            shapes.append(batch.shape[0])
            return batch

        with DynamicBatcher(fn, max_batch_size=4, max_wait_ms=0.5) as b:
            out = b.submit(np.ones((10, 2)))
        assert out.shape == (10, 2)
        assert shapes == [10]


class TestMultiSignatureBatcher:
    def test_routes_by_trailing_shape(self):
        shapes = []

        def fn(batch):
            shapes.append(batch.shape)
            return batch.sum(axis=tuple(range(1, batch.ndim)), keepdims=False)[:, None]

        with MultiSignatureBatcher(fn, max_batch_size=8, max_wait_ms=0.5) as b:
            out_a = b.submit(np.ones((3, 4)))
            out_b = b.submit(np.ones((2, 6)))
        np.testing.assert_array_equal(out_a, np.full((3, 1), 4.0))
        np.testing.assert_array_equal(out_b, np.full((2, 1), 6.0))
        assert sorted(b.signatures) == [("<f8", (4,)), ("<f8", (6,))]
        # each signature got its own padded device call
        assert sorted(shapes) == [(2, 6), (4, 4)]

    def test_routes_by_dtype(self):
        dtypes = []

        def fn(batch):
            dtypes.append(batch.dtype.name)
            return batch

        with MultiSignatureBatcher(fn, max_batch_size=4, max_wait_ms=0.5) as b:
            b.submit(np.ones((1, 2), np.float32))
            b.submit(np.ones((1, 2), np.uint8))
        assert sorted(dtypes) == ["float32", "uint8"]

    def test_concurrent_mixed_shapes(self):
        def fn(batch):
            return batch * 2

        b = MultiSignatureBatcher(fn, max_batch_size=16, max_wait_ms=5.0)
        b.start()
        results = {}
        release = threading.Event()

        def worker(i):
            release.wait()
            width = 3 if i % 2 else 5
            results[i] = b.submit(np.full((1, width), float(i)))

        threads = [threading.Thread(target=worker, args=(i,)) for i in range(8)]
        for t in threads:
            t.start()
        release.set()
        for t in threads:
            t.join()
        b.stop()
        for i in range(8):
            width = 3 if i % 2 else 5
            np.testing.assert_array_equal(results[i], np.full((1, width), 2.0 * i))
        assert b.stats.requests == 8

    def test_signature_cap(self):
        with MultiSignatureBatcher(lambda b: b, max_wait_ms=0.1, max_signatures=2) as b:
            b.submit(np.ones((1, 1)))
            b.submit(np.ones((1, 2)))
            with pytest.raises(ValueError, match="max_signatures"):
                b.submit(np.ones((1, 3)))

    def test_not_started_rejects(self):
        b = MultiSignatureBatcher(lambda x: x)
        with pytest.raises(RuntimeError, match="not started"):
            b.submit(np.ones((1, 2)))


@pytest.fixture(scope="module")
def mlp_server():
    from seldon_core_tpu.models.jaxserver import JaxServer

    server = JaxServer(
        model="mlp", num_classes=3, input_shape=(4,), dtype="float32",
        max_batch_size=8, max_wait_ms=1.0, warmup_dtypes=("float32",),
    )
    server.load()
    yield server
    server.unload()


class TestJaxServer:
    def test_predict_shapes(self, mlp_server):
        out = mlp_server.predict(np.ones((2, 4), np.float32), [])
        assert out.shape == (2, 3)

    def test_single_example_auto_batched(self, mlp_server):
        out = mlp_server.predict(np.ones(4, np.float32), [])
        assert out.shape == (3,)

    def test_deterministic(self, mlp_server):
        x = np.random.default_rng(0).normal(size=(3, 4)).astype(np.float32)
        a = mlp_server.predict(x, [])
        b = mlp_server.predict(x, [])
        np.testing.assert_allclose(a, b, rtol=1e-6)

    def test_bad_shape_rejected(self, mlp_server):
        with pytest.raises(MicroserviceError):
            mlp_server.predict(np.ones((2, 7), np.float32), [])

    def test_through_dispatch(self, mlp_server):
        msg = InternalMessage(payload=np.ones((1, 4), np.float32), kind="rawTensor")
        out = dispatch.predict(mlp_server, msg)
        assert np.asarray(out.payload).shape == (1, 3)
        assert out.names == ["t:0", "t:1", "t:2"]
        assert any(m["key"] == "jaxserver_mean_batch_rows" for m in out.meta.metrics)

    def test_softmax_option(self):
        from seldon_core_tpu.models.jaxserver import JaxServer

        server = JaxServer(
            model="mlp", num_classes=3, input_shape=(4,), dtype="float32",
            softmax_outputs=True, max_batch_size=4,
        )
        server.load()
        out = server.predict(np.ones((2, 4), np.float32), [])
        np.testing.assert_allclose(out.sum(axis=-1), 1.0, rtol=1e-5)
        server.unload()

    def test_checkpoint_roundtrip(self, tmp_path):
        import jax
        from flax import serialization

        from seldon_core_tpu.models.jaxserver import JaxServer
        from seldon_core_tpu.models.mlp import MLPClassifier

        # train-side: init and save a checkpoint
        module = MLPClassifier(num_classes=3)
        variables = module.init(jax.random.key(42), np.zeros((1, 4), np.float32))
        ckpt = tmp_path / "model.msgpack"
        ckpt.write_bytes(serialization.to_bytes(variables))

        server = JaxServer(
            model="mlp", model_uri=str(ckpt), num_classes=3, input_shape=(4,),
            dtype="float32", max_batch_size=4, warmup=False,
        )
        server.load()
        x = np.ones((1, 4), np.float32)
        expected = module.apply(variables, x)
        np.testing.assert_allclose(server.predict(x, []), np.asarray(expected), rtol=1e-5)
        server.unload()

    def test_warmup_covers_normalized_buckets(self):
        """ADVICE r1: user buckets not ending at max_batch_size must
        still pre-compile the forced final bucket — no request pays a
        trace mid-traffic."""
        from seldon_core_tpu.models.jaxserver import JaxServer

        server = JaxServer(
            model="mlp", num_classes=3, input_shape=(4,), dtype="float32",
            max_batch_size=8, buckets=[1, 2], warmup_dtypes=("float32",),
        )
        server.load()
        try:
            assert server.batcher.buckets == [1, 2, 8]
            # warmup compiled exactly one program per (bucket, dtype)
            assert server._predict_jit._cache_size() == 3
            server.predict(np.ones((8, 4), np.float32), [])
            assert server._predict_jit._cache_size() == 3  # no new trace
        finally:
            server.unload()

    def test_builtin_registration(self):
        import seldon_core_tpu.models  # noqa: F401 — triggers registration
        from seldon_core_tpu.engine.units import BUILTIN_IMPLEMENTATIONS

        assert "JAX_SERVER" in BUILTIN_IMPLEMENTATIONS


class TestMultiSignatureServing:
    def test_transformer_two_context_lengths(self):
        """One server, two context-length signatures, one weight set."""
        from seldon_core_tpu.models.jaxserver import JaxServer

        server = JaxServer(
            model="transformer_encoder", num_classes=3, dtype="float32",
            input_shape=(16,), extra_input_shapes=[(32,)],
            max_batch_size=4, max_wait_ms=0.5, warmup=False,
            warmup_dtypes=("int32",),
            model_kwargs={"vocab_size": 64, "d_model": 32, "num_layers": 1,
                          "num_heads": 2, "max_len": 64},
        )
        server.load()
        rng = np.random.default_rng(0)
        short = rng.integers(0, 64, size=(2, 16)).astype(np.int32)
        long = rng.integers(0, 64, size=(2, 32)).astype(np.int32)
        out_short = server.predict(short, [])
        out_long = server.predict(long, [])
        assert out_short.shape == (2, 3) and out_long.shape == (2, 3)
        assert sorted(server.batcher.signatures) == [("<i4", (16,)), ("<i4", (32,))]
        # parity with a direct module apply at the longer signature
        direct = np.asarray(server.module.apply(server.variables, long))
        np.testing.assert_allclose(out_long, direct, rtol=2e-4, atol=2e-4)
        # a length outside the served signatures is rejected, not retraced
        with pytest.raises(MicroserviceError):
            server.predict(rng.integers(0, 64, size=(2, 24)).astype(np.int32), [])
        assert server.health_status()["signatures"] == [[16], [32]]
        server.unload()


class TestModelZoo:
    def test_resnet_tiny_forward(self):
        import jax

        from seldon_core_tpu.models.resnet import ResNetTiny

        module = ResNetTiny(num_classes=10, dtype=np.float32)
        variables = module.init(jax.random.key(0), np.zeros((1, 32, 32, 3), np.float32))
        out = module.apply(variables, np.ones((2, 32, 32, 3), np.float32))
        assert out.shape == (2, 10)

    def test_resnet50_param_count(self):
        """ResNet-50 structure check without running the full forward."""
        import jax

        from seldon_core_tpu.models.resnet import ResNet50

        module = ResNet50(num_classes=1000)
        variables = jax.eval_shape(
            lambda: module.init(jax.random.key(0), np.zeros((1, 224, 224, 3), np.float32))
        )
        n_params = sum(np.prod(x.shape) for x in jax.tree.leaves(variables["params"]))
        # canonical ResNet-50 has ~25.5M parameters
        assert 25_000_000 < n_params < 26_000_000


class TestTopK:
    def test_topk_output_layout(self):
        from seldon_core_tpu.models.jaxserver import JaxServer

        server = JaxServer(model="mlp", num_classes=10, input_shape=(4,), dtype="float32",
                           softmax_outputs=True, top_k=3, max_batch_size=4,
                           warmup=False, warmup_dtypes=("float32",))
        server.load()
        x = np.random.default_rng(0).normal(size=(2, 4)).astype(np.float32)
        out = server.predict(x, [])
        assert out.shape == (2, 2, 3)  # [batch, (indices, scores), k]
        indices, scores = out[:, 0, :], out[:, 1, :]
        # scores sorted descending, indices are valid classes
        assert (np.diff(scores, axis=1) <= 1e-6).all()
        assert ((indices >= 0) & (indices < 10)).all()
        # parity with full logits top-k
        full = JaxServer(model="mlp", num_classes=10, input_shape=(4,), dtype="float32",
                         softmax_outputs=True, max_batch_size=4, warmup=False,
                         warmup_dtypes=("float32",), seed=0)
        full.load()
        logits = full.predict(x, [])
        np.testing.assert_allclose(np.sort(logits, axis=1)[:, -3:][:, ::-1], scores, rtol=1e-5)
        server.unload(); full.unload()


class TestViT:
    def test_vit_tiny_serves_images(self):
        from seldon_core_tpu.models.jaxserver import JaxServer

        server = JaxServer(
            model="vit_tiny", num_classes=10, input_shape=(32, 32, 3),
            dtype="float32", max_batch_size=4, warmup=False,
            warmup_dtypes=("float32",),
        )
        server.load()
        out = server.predict(np.zeros((2, 32, 32, 3), np.float32), [])
        arr = np.asarray(out)
        assert arr.shape == (2, 10)
        assert np.isfinite(arr).all()
        server.unload()

    def test_vit_patch_and_cls_shapes(self):
        import jax
        import jax.numpy as jnp

        from seldon_core_tpu.models.vit import ViTTiny

        m = ViTTiny(num_classes=5, dtype=jnp.float32)
        variables = m.init(jax.random.key(0), jnp.zeros((1, 32, 32, 3)))
        # 32/8 = 4 -> 16 patches + CLS = 17 positions
        assert variables["params"]["pos_embed"].shape == (1, 17, 64)
        logits = m.apply(variables, jnp.ones((3, 32, 32, 3)))
        assert logits.shape == (3, 5)

    def test_position_interpolation_serves_multiple_resolutions(self):
        """One ViT checkpoint, several input resolutions: pos_embed is
        anchored at pos_grid and bicubically resized at trace time."""
        import jax
        import jax.numpy as jnp

        from seldon_core_tpu.models.vit import ViTTiny

        m = ViTTiny(num_classes=5, dtype=jnp.float32)
        variables = m.init(jax.random.key(0), jnp.zeros((1, 32, 32, 3)))
        for res in (48, 64):
            logits = m.apply(variables, jnp.ones((2, res, res, 3)))
            assert logits.shape == (2, 5)
            assert np.isfinite(np.asarray(logits)).all()
        # still rejects non-multiples of patch_size
        with pytest.raises(ValueError):
            m.apply(variables, jnp.ones((1, 33, 33, 3)))

    def test_interpolation_is_identity_at_native_resolution(self):
        """pos_grid must not perturb the native path: a legacy
        (pos_grid=0) module with the same params produces bitwise-equal
        logits at the anchor resolution."""
        import jax
        import jax.numpy as jnp

        from seldon_core_tpu.models.vit import ViTTiny

        anchored = ViTTiny(num_classes=5, dtype=jnp.float32)
        legacy = ViTTiny(num_classes=5, dtype=jnp.float32, pos_grid=0)
        variables = anchored.init(jax.random.key(0), jnp.zeros((1, 32, 32, 3)))
        x = jnp.asarray(np.random.default_rng(1).normal(size=(2, 32, 32, 3)), jnp.float32)
        np.testing.assert_array_equal(
            np.asarray(anchored.apply(variables, x)),
            np.asarray(legacy.apply(variables, x)),
        )

    def test_multi_resolution_through_jaxserver_signatures(self):
        """Serving-side: extra_input_shapes + pos_grid = one server, one
        checkpoint, several resolutions (MultiSignatureBatcher path)."""
        from seldon_core_tpu.models.jaxserver import JaxServer

        server = JaxServer(
            model="vit_tiny", num_classes=10, input_shape=(32, 32, 3),
            extra_input_shapes=[(48, 48, 3)],
            dtype="float32", max_batch_size=4, warmup=False,
            warmup_dtypes=("float32",),
        )
        server.load()
        small = np.asarray(server.predict(np.zeros((2, 32, 32, 3), np.float32), []))
        large = np.asarray(server.predict(np.zeros((2, 48, 48, 3), np.float32), []))
        assert small.shape == (2, 10) and large.shape == (2, 10)
        assert np.isfinite(small).all() and np.isfinite(large).all()
        server.unload()


class TestFlashAttentionServing:
    def test_transformer_served_with_flash_attention(self):
        from seldon_core_tpu.models.jaxserver import JaxServer

        server = JaxServer(
            model="transformer_encoder", num_classes=3, input_shape=(32,),
            dtype="float32", max_batch_size=2, warmup=False,
            warmup_dtypes=("int32",),
            model_kwargs={"vocab_size": 64, "d_model": 32, "num_layers": 1,
                          "num_heads": 2, "max_len": 32, "attention": "flash"},
        )
        server.load()
        out = np.asarray(server.predict(np.zeros((2, 32), np.int32), []))
        assert out.shape == (2, 3) and np.isfinite(out).all()
        server.unload()

    def test_unknown_attention_rejected(self):
        from seldon_core_tpu.models.jaxserver import JaxServer
        from seldon_core_tpu.runtime.component import MicroserviceError

        server = JaxServer(
            model="transformer_encoder", num_classes=3, input_shape=(32,),
            dtype="float32", warmup=False,
            model_kwargs={"vocab_size": 64, "max_len": 32, "attention": "nope"},
        )
        with pytest.raises(MicroserviceError):
            server.load()

    def test_vit_accepts_flash_attention(self):
        from seldon_core_tpu.models.jaxserver import JaxServer

        server = JaxServer(
            model="vit_tiny", num_classes=10, input_shape=(32, 32, 3),
            dtype="float32", max_batch_size=2, warmup=False,
            warmup_dtypes=("float32",),
            model_kwargs={"attention": "flash"},
        )
        server.load()
        out = np.asarray(server.predict(np.zeros((2, 32, 32, 3), np.float32), []))
        assert out.shape == (2, 10) and np.isfinite(out).all()
        server.unload()
