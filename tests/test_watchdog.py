"""Device-health watchdog (r17): the healthy -> degraded -> evacuating
state machine, the compile false-positive guard, engine wiring
(engine_stats / bridge gauge / fault-rate feed), forced migration, and
the /debug/workers health surface.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from seldon_core_tpu.models.paged import PagedEngine
from seldon_core_tpu.models.transformer import TransformerLM
from seldon_core_tpu.utils import faults
from seldon_core_tpu.utils.watchdog import (
    DEGRADED,
    EVACUATING,
    HEALTHY,
    STATE_CODES,
    EngineWatchdog,
)

CFG = dict(vocab_size=64, d_model=32, num_layers=1, num_heads=2, max_len=64)


@pytest.fixture(scope="module")
def params():
    module = TransformerLM(dtype=jnp.float32, **CFG)
    return module.init(jax.random.key(0), jnp.zeros((1, 8), jnp.int32))["params"]


@pytest.fixture(autouse=True)
def _disarm():
    faults.clear()
    yield
    faults.clear()


def _engine(params, **kw):
    base = dict(dtype=jnp.float32, page_size=8, max_slots=2, steps_per_call=4)
    base.update(kw)
    return PagedEngine(params, **CFG, **base)


# ---------------------------------------------------------------------------
# unit: the state machine
# ---------------------------------------------------------------------------


class TestStateMachine:
    def _wd(self, **kw):
        base = dict(chunk_ms_ceiling=10.0, fault_rate=0.5, compile_storm=0,
                    hbm_pct=0.0, window=4, breaches=2)
        base.update(kw)
        return EngineWatchdog(**base)

    def test_starts_healthy_and_stays_on_clean_waves(self):
        wd = self._wd()
        for _ in range(20):
            assert wd.observe(wall_ms=1.0) == HEALTHY
        assert wd.trips == 0

    def test_wall_breaches_degrade_then_clean_window_recovers(self):
        wd = self._wd()
        wd.observe(wall_ms=50.0)
        assert wd.state == HEALTHY  # one breach < breaches threshold
        assert wd.observe(wall_ms=50.0) == DEGRADED
        assert wd.trips == 1
        # clean waves push the breaches out of the window -> recovery
        state = None
        for _ in range(8):
            state = wd.observe(wall_ms=1.0)
        assert state == HEALTHY

    def test_persistent_degradation_escalates_to_evacuating(self):
        wd = self._wd()
        state = None
        for _ in range(12):  # window=4: >window waves spent degraded
            state = wd.observe(wall_ms=50.0)
        assert state == EVACUATING
        # terminal: clean waves do NOT recover an evacuating engine
        for _ in range(12):
            assert wd.observe(wall_ms=1.0) == EVACUATING

    def test_compile_waves_exempt_from_wall_ceiling(self):
        """The false-positive guard: a wave that paid an XLA compile is
        judged by the compile-storm signal only — seconds of cold-start
        compilation must not read as device sickness."""
        wd = self._wd()
        for _ in range(20):
            assert wd.observe(wall_ms=5000.0, compiled=True) == HEALTHY
        assert wd.trips == 0

    def test_compile_storm_signal_fires_only_above_threshold(self):
        wd = self._wd(compile_storm=3, chunk_ms_ceiling=0.0)
        assert wd.observe(wall_ms=1.0, compiled=True, compiles_delta=1) == HEALTHY
        assert wd.observe(wall_ms=1.0, compiled=True, compiles_delta=1) == HEALTHY
        assert wd.observe(wall_ms=1.0, compiled=True, compiles_delta=1) == DEGRADED

    def test_fault_rate_degrades(self):
        wd = self._wd(chunk_ms_ceiling=0.0, fault_rate=0.5)
        states = [wd.observe(wall_ms=1.0, fault=True) for _ in range(4)]
        assert states[-1] == DEGRADED

    def test_hbm_pressure_degrades(self):
        wd = self._wd(chunk_ms_ceiling=0.0, hbm_pct=90.0)
        wd.observe(wall_ms=1.0, pool_used_pct=95.0)
        assert wd.observe(wall_ms=1.0, pool_used_pct=95.0) == DEGRADED

    def test_forced_evacuation_via_knob(self, monkeypatch):
        wd = self._wd()
        assert wd.observe(wall_ms=1.0) == HEALTHY
        monkeypatch.setenv("SELDON_TPU_FORCE_EVACUATE", "1")
        assert wd.observe(wall_ms=1.0) == EVACUATING

    def test_clearing_force_knob_recovers_forced_engine(self, monkeypatch):
        """A FORCED evacuation is clearable: dropping the knob steps the
        engine back to degraded and a clean window recovers it — only
        organically-evacuating engines are terminal until respawn."""
        wd = self._wd()
        monkeypatch.setenv("SELDON_TPU_FORCE_EVACUATE", "1")
        assert wd.observe(wall_ms=1.0) == EVACUATING
        monkeypatch.delenv("SELDON_TPU_FORCE_EVACUATE")
        state = None
        for _ in range(8):
            state = wd.observe(wall_ms=1.0)
        assert state == HEALTHY

    def test_organic_evacuation_not_cleared_by_force_knob_churn(
        self, monkeypatch
    ):
        wd = self._wd()
        for _ in range(12):
            wd.observe(wall_ms=50.0)
        assert wd.state == EVACUATING  # organic: persisted degradation
        monkeypatch.setenv("SELDON_TPU_FORCE_EVACUATE", "1")
        wd.observe(wall_ms=1.0)
        monkeypatch.delenv("SELDON_TPU_FORCE_EVACUATE")
        # force-knob churn on an engine that was ALREADY organically
        # evacuating must not resurrect it
        assert wd.observe(wall_ms=1.0) == EVACUATING

    def test_stats_payload_carries_signals_and_thresholds(self):
        wd = self._wd()
        wd.observe(wall_ms=50.0)
        s = wd.stats()
        assert s["state"] == HEALTHY
        assert s["state_code"] == STATE_CODES[HEALTHY]
        assert s["wall_breaches"] == 1
        assert s["thresholds"]["window"] == 4

    def test_disabled_ceiling_never_wall_breaches(self):
        wd = self._wd(chunk_ms_ceiling=0.0)
        for _ in range(20):
            assert wd.observe(wall_ms=1e9) == HEALTHY


# ---------------------------------------------------------------------------
# engine wiring
# ---------------------------------------------------------------------------


class TestEngineWiring:
    def test_cold_engine_never_degrades_from_compilation_alone(
        self, params, monkeypatch
    ):
        """The satellite guard: the first chunks of a cold engine spend
        their wall in XLA compilation — with a ceiling far below that
        compile time (but far above a steady-state chunk), the engine
        must stay healthy because the jitwatch sentinel flags those
        waves as compile waves and the watchdog exempts them."""
        monkeypatch.setenv("SELDON_TPU_WATCHDOG_CHUNK_MS", "500")
        monkeypatch.setenv("SELDON_TPU_WATCHDOG_BREACHES", "1")
        monkeypatch.setenv("SELDON_TPU_WATCHDOG_WINDOW", "4")
        eng = _engine(params)
        s = eng.submit(np.arange(10), max_new_tokens=12)
        eng.run()
        assert s.result is not None
        stats = eng.engine_stats()
        assert stats["jit_compiles"] >= 1  # the exemption actually fired
        assert stats["health"] == "healthy"
        assert stats["watchdog_trips"] == 0

    def test_chunk_fault_rate_degrades_engine(self, params, monkeypatch):
        monkeypatch.setenv("SELDON_TPU_WATCHDOG_WINDOW", "4")
        monkeypatch.setenv("SELDON_TPU_WATCHDOG_BREACHES", "2")
        monkeypatch.setenv("SELDON_TPU_WATCHDOG_FAULT_RATE", "0.5")
        eng = _engine(params)
        faults.inject("paged.chunk", times=8)
        for i in range(6):
            st = eng.submit(np.arange(8) + i, max_new_tokens=4)
            eng.run()
            assert st.event.is_set()
        stats = eng.engine_stats()
        assert stats["chunk_faults"] >= 2
        assert stats["health"] in ("degraded", "evacuating")
        assert stats["health_state"] >= 1
        assert stats["watchdog_trips"] >= 1

    def test_watchdog_off_always_healthy(self, params, monkeypatch):
        monkeypatch.setenv("SELDON_TPU_WATCHDOG", "0")
        eng = _engine(params)
        assert eng._watchdog is None
        stats = eng.engine_stats()
        assert stats["health"] == "healthy"
        assert stats["health_state"] == 0

    def test_detail_stats_carry_watchdog_payload(self, params):
        eng = _engine(params)
        s = eng.engine_stats(detail=True)
        assert "watchdog" in s
        assert s["watchdog"]["state"] == "healthy"

    def test_health_state_is_bridge_mapped_gauge(self):
        from seldon_core_tpu.utils.metrics import ENGINE_STATS_METRICS

        kind, name, _doc = ENGINE_STATS_METRICS["health_state"]
        assert kind == "gauge"
        assert name == "seldon_tpu_engine_health_state"
        for key in ("quarantined", "migrated_in", "migrated_out",
                    "watchdog_trips"):
            kind, name, _doc = ENGINE_STATS_METRICS[key]
            assert kind == "counter" and name.endswith("_total")


# ---------------------------------------------------------------------------
# /debug/workers health surface
# ---------------------------------------------------------------------------


class TestDebugWorkers:
    def _gateway(self, health="degraded", code=1):
        from seldon_core_tpu.engine.graph import UnitSpec
        from seldon_core_tpu.engine.server import Gateway, PredictorService
        from seldon_core_tpu.runtime import TPUComponent

        class FakeEngine:
            def engine_stats(self, detail=False):
                return {
                    "chunks": 1, "health": health, "health_state": code,
                    "watchdog_trips": 1, "quarantined": 2,
                    "migrated_out": 3, "migrated_in": 0,
                }

        class GenModel(TPUComponent):
            def __init__(self):
                super().__init__()
                self.engine = FakeEngine()

            def predict(self, X, names, meta=None):
                return np.asarray(X)

        svc = PredictorService(
            UnitSpec(name="lm", type="MODEL", component=GenModel()),
            name="main",
        )
        return Gateway([(svc, 1.0)])

    def test_debug_workers_reports_engine_health(self):
        import asyncio

        from aiohttp.test_utils import TestClient, TestServer

        from seldon_core_tpu.engine.server import build_gateway_app

        async def scenario():
            client = TestClient(TestServer(build_gateway_app(self._gateway())))
            await client.start_server()
            out = await (await client.get("/debug/workers")).json()
            await client.close()
            return out

        out = asyncio.run(scenario())
        eng = out["engines"]["main/lm"]
        assert eng["health"] == "degraded"
        assert eng["health_state"] == 1
        assert eng["quarantined"] == 2
        assert eng["migrated_out"] == 3
        assert out["degraded"] == ["main/lm"]
