"""Wire conformance: a non-Python node joins an inference graph.

The reference proves its wrappers are language-neutral with a Go model
server speaking the SeldonMessage contract
(reference: examples/wrappers/go/server.go:1-165, wrappers/s2i/nodejs/
microservice.js:1-50).  Here the same proof for the TPU framework: the
dependency-free C++ node in native/remote_node.cc serves the REST node
dialect and a deployment's graph calls it through the ordinary
RestClient edge — the engine cannot tell it isn't Python.
"""

import asyncio
import os
import re
import shutil
import subprocess
import time

import numpy as np
import pytest

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
NATIVE_DIR = os.path.join(REPO_ROOT, "native")
BINARY = os.path.join(NATIVE_DIR, "remote_node")


def _spawn_node(binary=BINARY):
    try:
        proc = subprocess.Popen(
            [binary, "0"], stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
            text=True, bufsize=1,
        )
    except OSError:  # e.g. exec-format error on a foreign-arch prebuilt
        return None, None
    line = proc.stdout.readline()
    m = re.search(r"listening on (\d+)", line)
    if m is None:  # binary didn't come up (e.g. glibc mismatch)
        proc.kill()
        proc.wait()
        return None, None
    return proc, int(m.group(1))


@pytest.fixture(scope="module")
def cpp_node():
    have_gxx = shutil.which("g++") is not None
    if not have_gxx and not os.path.exists(BINARY):
        pytest.skip("no g++ toolchain and no prebuilt remote_node")
    src = os.path.join(NATIVE_DIR, "remote_node.cc")
    proc = port = None
    if os.path.exists(BINARY) and (
        not have_gxx or os.path.getmtime(BINARY) >= os.path.getmtime(src)
    ):
        proc, port = _spawn_node()
    if proc is None and have_gxx:
        # the tracked PREBUILT binary can be outdated for this run: built
        # against a newer glibc than the container ships, a foreign arch,
        # or older than an edited remote_node.cc.  Build a host-local copy
        # with the Makefile's own recipe in a git-ignored scratch dir
        # beside the sources (same filesystem as the canonical binary, so
        # no noexec-tmpfs surprises) without ever overwriting the tracked
        # binary.
        build = os.path.join(NATIVE_DIR, ".pytest_build")
        os.makedirs(build, exist_ok=True)
        for name in ("Makefile", "remote_node.cc"):
            shutil.copy(os.path.join(NATIVE_DIR, name), os.path.join(build, name))
        subprocess.run(
            ["make", "-C", build, "remote_node"],
            check=True, capture_output=True,
        )
        proc, port = _spawn_node(os.path.join(build, "remote_node"))
    if proc is None:
        pytest.skip("remote_node binary does not run on this host")
    # readiness: the probe endpoint answers
    import urllib.request

    for _ in range(50):
        try:
            with urllib.request.urlopen(f"http://127.0.0.1:{port}/health/ping", timeout=1):
                break
        except OSError:
            time.sleep(0.05)
    yield port
    proc.kill()
    proc.wait()


@pytest.mark.e2e
class TestCppNodeConformance:
    def test_direct_node_dialect(self, cpp_node):
        """The node speaks the microservice REST dialect the Python
        wrapper serves: SeldonMessage JSON in, SeldonMessage JSON out."""
        from seldon_core_tpu.client.client import SeldonTpuClient

        client = SeldonTpuClient(http_port=cpp_node, transport="rest")
        out = client.microservice(
            "predict", np.asarray([[1.0, 2.5, -3.0]]), payload_kind="ndarray"
        )
        assert out.success
        np.testing.assert_allclose(np.asarray(out.data, dtype=float), [[2.0, 5.0, -6.0]])
        assert out.response.names == ["doubled"]
        assert out.meta.tags.get("wrapper") == "cpp"
        client.close()

    def test_joins_graph_as_remote_model(self, cpp_node):
        """Deployment whose graph root is the C++ process: the engine's
        RestClient edge carries the request there and back."""
        from seldon_core_tpu.controlplane import Deployer, TpuDeployment
        from seldon_core_tpu.runtime.message import InternalMessage

        spec = TpuDeployment.from_dict(
            {
                "name": "cpp-graph",
                "predictors": [
                    {
                        "name": "main",
                        "traffic": 100,
                        "graph": {
                            "name": "cpp-model",
                            "type": "MODEL",
                            "image": "native/remote_node.cc",
                            "endpoint": {
                                "host": "127.0.0.1",
                                "port": cpp_node,
                                "transport": "REST",
                            },
                        },
                    }
                ],
            }
        )

        async def scenario():
            deployer = Deployer()
            managed = await deployer.apply(spec, ready_timeout_s=30.0)
            msg = InternalMessage(payload=np.asarray([[4.0, -1.0]]), kind="ndarray")
            out = await managed.gateway.predict(msg)
            np.testing.assert_allclose(out.array(), [[8.0, -2.0]])
            # the engine's puid survived the C++ hop
            assert out.meta.puid == msg.meta.puid
            assert out.meta.tags.get("wrapper") == "cpp"
            await deployer.delete("cpp-graph")

        asyncio.run(scenario())

    def test_malformed_payload_gets_seldon_failure(self, cpp_node):
        """Protocol errors come back as SeldonMessage status, like the
        Python wrapper's error contract."""
        import json
        import urllib.error
        import urllib.request

        req = urllib.request.Request(
            f"http://127.0.0.1:{cpp_node}/predict",
            data=json.dumps({"strData": "no tensor here"}).encode(),
            headers={"Content-Type": "application/json"},
        )
        with pytest.raises(urllib.error.HTTPError) as err:
            urllib.request.urlopen(req, timeout=5)
        body = json.loads(err.value.read())
        assert body["status"]["status"] == "FAILURE"
        assert body["status"]["reason"] == "NO_NDARRAY"
