"""TF/Keras -> flax checkpoint conversion.

Same exactness criterion as test_torch_convert.py: flax-init params,
inverse-transformed into a synthetic keras-applications-style weight
dict, must convert back to the identical tree (conv-bias folding is
checked against non-zero biases).  A real ``tf.keras.applications``
ResNet50 is converted end-to-end when TensorFlow is importable.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from seldon_core_tpu.utils.tf_convert import KERAS_STAGES, convert_tf_resnet

pytestmark = pytest.mark.slow  # compile-heavy: excluded from the default fast tier (make test-all)



def _flatten(tree, prefix=()):
    out = {}
    for k, v in tree.items():
        if isinstance(v, dict):
            out.update(_flatten(v, prefix + (k,)))
        else:
            out[prefix + (k,)] = np.asarray(v)
    return out


def _to_keras_names(variables, arch, rng):
    """Inverse of the converter: flax tree -> keras-applications names,
    with non-zero conv biases folded OUT of the BN means (so the
    converter's fold-in must recover the flax means)."""
    stage_sizes = KERAS_STAGES[arch]
    sd = {}
    params, stats = variables["params"], variables["batch_stats"]

    def put(conv_layer, bn_layer, conv_node, bn_node, bn_stats):
        bias = rng.normal(size=conv_node["kernel"].shape[-1]).astype(np.float32)
        sd[f"{conv_layer}/kernel"] = np.asarray(conv_node["kernel"])
        sd[f"{conv_layer}/bias"] = bias
        sd[f"{bn_layer}/gamma"] = np.asarray(bn_node["scale"])
        sd[f"{bn_layer}/beta"] = np.asarray(bn_node["bias"])
        sd[f"{bn_layer}/moving_mean"] = np.asarray(bn_stats["mean"]) + bias
        sd[f"{bn_layer}/moving_variance"] = np.asarray(bn_stats["var"])

    put("conv1_conv", "conv1_bn", params["conv_init"], params["bn_init"], stats["bn_init"])
    b = 0
    for stage, size in enumerate(stage_sizes, start=2):
        for j in range(1, size + 1):
            kp = f"conv{stage}_block{j}"
            fb = f"BottleneckBlock_{b}"
            for c in (1, 2, 3):
                put(f"{kp}_{c}_conv", f"{kp}_{c}_bn",
                    params[fb][f"Conv_{c - 1}"], params[fb][f"BatchNorm_{c - 1}"],
                    stats[fb][f"BatchNorm_{c - 1}"])
            if "shortcut_conv" in params[fb]:
                put(f"{kp}_0_conv", f"{kp}_0_bn",
                    params[fb]["shortcut_conv"], params[fb]["shortcut_bn"],
                    stats[fb]["shortcut_bn"])
            b += 1
    sd["predictions/kernel"] = np.asarray(params["head"]["kernel"])
    sd["predictions/bias"] = np.asarray(params["head"]["bias"])
    return sd


def test_roundtrip_exact_with_bias_folding():
    from seldon_core_tpu.models import resnet as resnet_mod

    module = resnet_mod.ResNet50(num_classes=16, dtype=jnp.float32)
    variables = module.init(jax.random.key(0), jnp.zeros((1, 64, 64, 3)))
    flax_vars = {
        "params": jax.tree_util.tree_map(np.asarray, variables["params"]),
        "batch_stats": jax.tree_util.tree_map(np.asarray, variables["batch_stats"]),
    }
    sd = _to_keras_names(flax_vars, "resnet50", np.random.default_rng(7))
    converted = convert_tf_resnet(sd, arch="resnet50")

    want = _flatten(flax_vars)
    got = _flatten(converted)
    assert set(got) == set(want)
    for key in want:
        if key[-1] == "mean":  # (mean + b) - b: float-rounded, not bitwise
            np.testing.assert_allclose(got[key], want[key], atol=1e-6, err_msg=str(key))
        else:
            np.testing.assert_array_equal(got[key], want[key], err_msg=str(key))

    logits = module.apply(
        {"params": converted["params"], "batch_stats": converted["batch_stats"]},
        jnp.ones((2, 64, 64, 3)),
    )
    assert logits.shape == (2, 16)
    assert np.isfinite(np.asarray(logits)).all()


def test_missing_key_reports_name():
    with pytest.raises(KeyError, match="conv1_bn/gamma"):
        convert_tf_resnet({"conv1_conv/kernel": np.zeros((7, 7, 3, 64))}, arch="resnet50")


def test_leftover_keys_rejected():
    from seldon_core_tpu.models import resnet as resnet_mod

    module = resnet_mod.ResNet50(num_classes=4, dtype=jnp.float32)
    variables = module.init(jax.random.key(0), jnp.zeros((1, 32, 32, 3)))
    sd = _to_keras_names(
        {
            "params": jax.tree_util.tree_map(np.asarray, variables["params"]),
            "batch_stats": jax.tree_util.tree_map(np.asarray, variables["batch_stats"]),
        },
        "resnet50",
        np.random.default_rng(0),
    )
    sd["stray_layer/kernel"] = np.zeros(3)
    with pytest.raises(ValueError, match="unconverted"):
        convert_tf_resnet(sd, arch="resnet50")


def test_unknown_arch_rejected():
    with pytest.raises(ValueError, match="resnet18"):
        convert_tf_resnet({}, arch="resnet18")


def test_real_keras_resnet50_converts_and_serves(tmp_path):
    """End-to-end against the REAL keras-applications model: its weight
    names and shapes (independent of our inverse map) convert with
    nothing missing/left over, load into flax ResNet50, and serve."""
    tf = pytest.importorskip("tensorflow")

    from seldon_core_tpu.models import resnet as resnet_mod
    from seldon_core_tpu.utils.tf_convert import flatten_keras_weights

    keras_model = tf.keras.applications.ResNet50(weights=None)
    weights = flatten_keras_weights(keras_model)
    converted = convert_tf_resnet(weights, arch="resnet50")

    module = resnet_mod.ResNet50(num_classes=1000, dtype=jnp.float32)
    variables = module.init(jax.random.key(0), jnp.zeros((1, 64, 64, 3)))
    # every converted leaf must land exactly on a flax-init leaf shape
    want = _flatten({
        "params": jax.tree_util.tree_map(np.asarray, variables["params"]),
        "batch_stats": jax.tree_util.tree_map(np.asarray, variables["batch_stats"]),
    })
    got = _flatten(converted)
    assert set(got) == set(want)
    for key in want:
        assert got[key].shape == want[key].shape, key

    logits = module.apply(
        {"params": converted["params"], "batch_stats": converted["batch_stats"]},
        jnp.ones((1, 64, 64, 3)),
    )
    assert np.isfinite(np.asarray(logits)).all()


def test_loader_flattens_saved_keras_file(tmp_path):
    tf = pytest.importorskip("tensorflow")

    from seldon_core_tpu.utils.tf_convert import load_tf_weights

    inputs = tf.keras.Input((8, 8, 3))
    x = tf.keras.layers.Conv2D(4, 3, name="c0")(inputs)
    x = tf.keras.layers.BatchNormalization(name="b0")(x)
    x = tf.keras.layers.Flatten()(x)
    out = tf.keras.layers.Dense(2, name="d0")(x)
    model = tf.keras.Model(inputs, out)
    path = tmp_path / "tiny.keras"
    model.save(path)

    weights = load_tf_weights(str(path))
    assert set(weights) == {
        "c0/kernel", "c0/bias",
        "b0/gamma", "b0/beta", "b0/moving_mean", "b0/moving_variance",
        "d0/kernel", "d0/bias",
    }
    assert weights["c0/kernel"].shape == (3, 3, 3, 4)
    assert weights["d0/kernel"].shape == (144, 2)
