"""Chunked-prefill co-scheduling (r15): the token-budget wave planner.

Correctness bar: greedy decode is bit-exact chunked-on vs chunked-off —
a slice computes exactly the attention the monolithic prefill computes
— across chunk impls (ring | pool) × w8a8 × prefix-cache, in the f32
exactness regime (the same single-numeric-regime discipline every
cross-program parity suite here uses).

Fast tier: budget accounting (a wave never exceeds the token budget,
decode admitted first, page-aligned slices, priority ordering), knob
resolution, recorder/stats surfaces.  The full parity matrix is @slow.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from seldon_core_tpu.models.paged import PagedEngine, StreamingLM, _Stream
from seldon_core_tpu.models.transformer import TransformerLM

CFG = dict(vocab_size=64, d_model=32, num_layers=1, num_heads=2, max_len=256)


@pytest.fixture(scope="module")
def params():
    lm = TransformerLM(dtype=jnp.float32, **CFG)
    return lm.init(jax.random.key(0), jnp.zeros((1, 8), jnp.int32))["params"]


def _engine(params, **kw):
    base = dict(dtype=jnp.float32, page_size=8, max_slots=3, steps_per_call=4)
    base.update(kw)
    return PagedEngine(params, **CFG, **base)


def _prompts(sizes, seed=3):
    rng = np.random.default_rng(seed)
    return [
        rng.integers(0, CFG["vocab_size"], size=(n,)).astype(np.int32)
        for n in sizes
    ]


class TestKnobResolution:
    def test_ctor_wins_over_env(self, params, monkeypatch):
        monkeypatch.setenv("SELDON_TPU_CHUNK_TOKEN_BUDGET", "64")
        eng = _engine(params, chunk_token_budget=24)
        assert eng.chunk_token_budget == 24
        eng.close()

    def test_env_applies_when_ctor_unset(self, params, monkeypatch):
        monkeypatch.setenv("SELDON_TPU_CHUNK_TOKEN_BUDGET", "64")
        eng = _engine(params)
        assert eng.chunk_token_budget == 64
        eng.close()

    def test_zero_spells_off(self, params, monkeypatch):
        monkeypatch.setenv("SELDON_TPU_CHUNK_TOKEN_BUDGET", "0")
        eng = _engine(params)
        assert eng.chunk_token_budget == 0
        eng.close()

    def test_tiny_budget_clamps_up(self, params):
        # a budget under one page + one decode step could never make
        # page-aligned progress: it clamps instead of livelocking
        eng = _engine(params, chunk_token_budget=3)
        assert eng.chunk_token_budget == eng.page_size + eng.steps_per_call
        eng.close()

    def test_streaminglm_passes_budget_through(self):
        lm = StreamingLM(
            chunk_token_budget=48, page_size=8, max_slots=2,
            steps_per_call=4, max_new_tokens=4, **CFG,
        )
        lm.load()
        try:
            assert lm.engine.chunk_token_budget == 48
            assert lm.engine.engine_stats()["chunk_token_budget"] == 48
        finally:
            lm.shutdown()


class TestSlicePlanner:
    """Host-side planner invariants — no device work."""

    def _stream(self, eng, plen, *, prefilled=0, priority=0, req_id=0):
        s = _Stream(req_id, np.zeros((plen,), np.int32), 4, 0.0, 0, -1, 0)
        s.prefilled = prefilled
        s.priority = priority
        return s

    def test_slices_page_aligned_unless_final(self, params):
        eng = _engine(params, chunk_token_budget=24)
        try:
            a = self._stream(eng, 50, req_id=1)
            plan = eng._plan_prefill_slices_locked([a], 20)
            # 20 tokens floor to 2 pages of 8
            assert plan == [(a, 0, 16)]
            a.prefilled = 48
            plan = eng._plan_prefill_slices_locked([a], 20)
            # final slice may end unaligned: it finishes the prompt
            assert plan == [(a, 48, 2)]
        finally:
            eng.close()

    def test_budget_is_a_hard_cap_and_fifo_within_class(self, params):
        eng = _engine(params, chunk_token_budget=24)
        try:
            a = self._stream(eng, 64, req_id=1)
            b = self._stream(eng, 64, req_id=2)
            plan = eng._plan_prefill_slices_locked([a, b], 20)
            # a (older) takes the floored 16; the 4 left cannot make a
            # page of progress for b
            assert plan == [(a, 0, 16)]
            plan = eng._plan_prefill_slices_locked([a, b], 32)
            assert plan == [(a, 0, 32)]
            assert sum(n for _s, _st, n in plan) <= 32
        finally:
            eng.close()

    def test_priority_first(self, params):
        eng = _engine(params, chunk_token_budget=24)
        try:
            lo = self._stream(eng, 64, priority=0, req_id=1)
            hi = self._stream(eng, 64, priority=2, req_id=2)
            plan = eng._plan_prefill_slices_locked([lo, hi], 16)
            assert plan == [(hi, 0, 16)]
        finally:
            eng.close()

    def test_kv_import_costs_no_budget(self, params):
        eng = _engine(params, chunk_token_budget=24)
        try:
            imp = self._stream(eng, 64, req_id=1)
            imp.kv_import = {"k": None}
            comp = self._stream(eng, 64, req_id=2)
            plan = eng._plan_prefill_slices_locked([imp, comp], 16)
            # the import places computed pages (no FLOPs) and the full
            # compute budget still goes to the computing stream
            assert plan == [(imp, 0, 64), (comp, 0, 16)]
        finally:
            eng.close()


class TestBudgetAccounting:
    def test_wave_never_exceeds_budget(self, params, monkeypatch):
        """The Sarathi invariant, observed end-to-end via the flight
        recorder: every wave's prefill+decode token total stays inside
        the budget, and the workload actually exercises mixed waves."""
        monkeypatch.setenv("SELDON_TPU_FLIGHT_RECORDER", "256")
        budget = 24
        eng = _engine(params, chunk_token_budget=budget, max_slots=3)
        try:
            streams = [
                eng.submit(p, max_new_tokens=12)
                for p in _prompts((5, 70, 120, 33, 64))
            ]
            eng.run()
            assert all(s.result is not None for s in streams)
            recs = eng.engine_stats(detail=True)["recorder"]
            assert recs
            for r in recs:
                assert r["prefill_tokens"] + r["decode_tokens"] <= budget, r
                assert r["tokens"] == r["prefill_tokens"] + r["decode_tokens"]
            assert any(r["prefill_tokens"] for r in recs)
            assert any(r["decode_tokens"] for r in recs)
        finally:
            eng.close()

    def test_decode_admitted_first(self, params, monkeypatch):
        """A wave with running decodes AND a pending prefill spends its
        budget on decode first; prefill gets only the remainder."""
        monkeypatch.setenv("SELDON_TPU_FLIGHT_RECORDER", "256")
        budget = 16  # 3 decode lanes x 4 steps = 12, leaves 4 < 1 page
        eng = _engine(params, chunk_token_budget=budget, max_slots=3)
        try:
            short = [
                eng.submit(p, max_new_tokens=16) for p in _prompts((5, 6, 7))
            ]
            # get all three decoding (prefill waves first)
            while any(s.prefilled < len(s.prompt) for s in short):
                eng.step()
            long = eng.submit(_prompts((120,), seed=9)[0], max_new_tokens=4)
            eng.step()  # 3 decode lanes admitted first: no prefill fits
            recs = eng.engine_stats(detail=True)["recorder"]
            last = recs[-1]
            assert last["decode_tokens"] > 0
            assert last["prefill_tokens"] == 0
            assert long.prefilled == 0
            eng.run()
            assert long.result is not None
        finally:
            eng.close()

    def test_completion_decodes_next_wave(self, params, monkeypatch):
        """A stream whose final slice ran this wave starts decoding the
        NEXT wave — the hard per-wave bound's enabling rule."""
        monkeypatch.setenv("SELDON_TPU_FLIGHT_RECORDER", "256")
        eng = _engine(params, chunk_token_budget=24, max_slots=1)
        try:
            s = eng.submit(_prompts((20,))[0], max_new_tokens=4)
            eng.step()
            recs = eng.engine_stats(detail=True)["recorder"]
            assert recs[-1]["phase"] == "prefill"
            assert recs[-1]["decode_tokens"] == 0
            assert s.prefilled == 20 and not s.tokens
            eng.step()
            assert len(s.tokens) > 0
        finally:
            eng.close()

    def test_long_prompt_spreads_over_waves(self, params, monkeypatch):
        monkeypatch.setenv("SELDON_TPU_FLIGHT_RECORDER", "256")
        eng = _engine(params, chunk_token_budget=16, max_slots=1)
        try:
            out = eng.generate(_prompts((100,))[0], max_new_tokens=4)
            assert out.shape == (4,)
            s = eng.engine_stats()
            # ceil(100 / 16-token slices) -> at least 7 prefill calls
            assert s["prefill_chunks"] >= 7
            assert s["prefill_tokens"] == 100
        finally:
            eng.close()

    def test_prefill_token_counters_match_monolithic(self, params):
        """Chunking changes the schedule, not the work: the same prompt
        set computes the same prefill tokens either way."""
        outs = {}
        for budget in (0, 24):
            eng = _engine(params, chunk_token_budget=budget)
            try:
                for p in _prompts((30, 70)):
                    eng.generate(p, max_new_tokens=4)
                outs[budget] = eng.engine_stats()
            finally:
                eng.close()
        assert outs[0]["prefill_tokens"] == outs[24]["prefill_tokens"] == 100
        assert outs[24]["prefill_chunks"] > outs[0]["prefill_chunks"]


class TestLifecycleStamps:
    def test_ttft_decomposition_stamps(self, params):
        """t_submit <= t_prefill_start <= t_decode_start <=
        t_first_token <= t_finish — the tracer-free terms the bench and
        the profile tool read."""
        eng = _engine(params, chunk_token_budget=24)
        try:
            s = eng.submit(_prompts((40,))[0], max_new_tokens=6)
            eng.run()
            assert s.result is not None
            assert 0 < s.t_submit <= s.t_prefill_start <= s.t_decode_start
            assert s.t_decode_start <= s.t_first_token <= s.t_finish
        finally:
            eng.close()

    def test_recorder_stats_window_mix(self, params, monkeypatch):
        monkeypatch.setenv("SELDON_TPU_FLIGHT_RECORDER", "256")
        eng = _engine(params, chunk_token_budget=24)
        try:
            eng.generate(_prompts((40,))[0], max_new_tokens=6)
            rs = eng.recorder.stats()
            assert rs["window_prefill_tokens"] == 40
            assert rs["window_decode_tokens"] == 6
        finally:
            eng.close()


class TestSpeculativeGeneratorChunkedPrefill:
    def test_chunked_prompt_prefill_exact(self, params):
        """The single-stream speculative lane under the same knob: the
        prompt forwards in page-aligned chunks of one static width —
        emitted tokens identical to the bucket-padded prefill."""
        from seldon_core_tpu.models.speculative import SpeculativeGenerator

        def run(budget):
            gen = SpeculativeGenerator(
                params, dtype=jnp.float32, page_size=8, draft="ngram",
                draft_k=3, chunk_token_budget=budget, **CFG,
            )
            return gen.generate(_prompts((70,))[0], max_new_tokens=10)

        np.testing.assert_array_equal(run(0), run(16))
        # widths stay static across offsets: one chunk program total
        gen = SpeculativeGenerator(
            params, dtype=jnp.float32, page_size=8, draft="ngram",
            draft_k=3, chunk_token_budget=16, **CFG,
        )
        gen.generate(_prompts((70,))[0], max_new_tokens=4)
        gen.generate(_prompts((100,), seed=8)[0], max_new_tokens=4)
        chunk_keys = [
            k for k in gen._forward_jit if k[-1] == "chunk"
        ]
        assert len(chunk_keys) == 1  # ONE width serves every prompt


class TestChunkedParityFast:
    def test_bit_exact_with_prefix_cache_and_streaming(self, params):
        """Default impl: chunked-on vs off bit-exact, prefix-cache hits
        engaged, streamed tokens equal the unary result."""
        rng = np.random.default_rng(4)
        shared = rng.integers(0, 64, size=(16,)).astype(np.int32)
        prompts = [
            np.concatenate(
                [shared, rng.integers(0, 64, size=(3 + i,)).astype(np.int32)]
            )
            for i in range(3)
        ]
        on = _engine(params, chunk_token_budget=16, max_slots=2)
        off = _engine(params, max_slots=2)
        try:
            for p in prompts:
                a = on.generate(p, max_new_tokens=8)
                b = off.generate(p, max_new_tokens=8)
                np.testing.assert_array_equal(a, b)
            s = on.engine_stats()
            assert s["prefix_hits"] == 2  # chunking composes with r9
            stream = on.submit(prompts[0], max_new_tokens=8,
                               stream_tokens=True)
            got = []
            while True:
                on.step()
                while not stream.token_queue.empty():
                    item = stream.token_queue.get()
                    if item is None:
                        break
                    got.extend(item)
                if stream.event.is_set():
                    break
            np.testing.assert_array_equal(
                np.asarray(got[:8], np.int32), stream.result[:8]
            )
        finally:
            on.close()
            off.close()


@pytest.mark.slow
class TestChunkedParityMatrix:
    """The tentpole correctness bar: greedy bit-exactness chunked-on vs
    chunked-off across ring|pool × w8a8 × prefix-cache, in the f32
    exactness regime (same discipline as the r9/r11 matrices)."""

    MCFG = dict(vocab_size=64, d_model=32, num_layers=2, num_heads=4,
                max_len=128)

    @pytest.fixture(scope="class")
    def mparams(self):
        lm = TransformerLM(dtype=jnp.float32, **self.MCFG)
        return lm.init(jax.random.key(1), jnp.zeros((1, 8), jnp.int32))["params"]

    def _prompts(self):
        rng = np.random.default_rng(7)
        shared = rng.integers(0, 64, size=(17,)).astype(np.int32)
        out = [
            np.concatenate(
                [shared, rng.integers(0, 64, size=(2 + i,)).astype(np.int32)]
            )
            for i in range(2)
        ]
        out.append(rng.integers(0, 64, size=(61,)).astype(np.int32))
        return out

    def _run(self, params, monkeypatch, *, impl, precision, prefix_cache,
             budget):
        monkeypatch.setenv("SELDON_TPU_CHUNK_IMPL", impl)
        eng = PagedEngine(
            params, dtype=jnp.float32, page_size=8, max_slots=2,
            steps_per_call=4, precision=precision,
            prefix_cache=prefix_cache, chunk_token_budget=budget,
            **self.MCFG,
        )
        try:
            outs = []
            # concurrent submission: chunked prefill must interleave
            # with live decodes, not just run solo
            streams = [
                eng.submit(p, max_new_tokens=8) for p in self._prompts()
            ]
            eng.run()
            outs = [s.result for s in streams]
            return outs, eng.engine_stats()
        finally:
            eng.close()

    @pytest.mark.parametrize("impl", ["ring", "pool"])
    @pytest.mark.parametrize("precision", ["", "w8a8"])
    @pytest.mark.parametrize("prefix_cache", [True, False])
    def test_chunked_parity(self, mparams, monkeypatch, impl, precision,
                            prefix_cache):
        on, s_on = self._run(mparams, monkeypatch, impl=impl,
                             precision=precision,
                             prefix_cache=prefix_cache, budget=16)
        off, s_off = self._run(mparams, monkeypatch, impl=impl,
                               precision=precision,
                               prefix_cache=prefix_cache, budget=0)
        for a, b in zip(on, off):
            np.testing.assert_array_equal(a, b)
        # same computed prefill work, more (budgeted) device calls
        assert s_on["prefill_tokens"] == s_off["prefill_tokens"]
        assert s_on["prefill_chunks"] >= s_off["prefill_chunks"]

    def test_chunked_speculative_parity(self, mparams, monkeypatch):
        """Spec engine under the budget: verify-first pricing, prompt
        slices in the remainder — outputs equal the plain engine's."""
        monkeypatch.setenv("SELDON_TPU_CHUNK_IMPL", "ring")
        plain, _ = self._run(mparams, monkeypatch, impl="ring",
                             precision="", prefix_cache=False, budget=0)

        def spec_run(budget):
            eng = PagedEngine(
                mparams, dtype=jnp.float32, page_size=8, max_slots=2,
                steps_per_call=4, speculative={"draft": "ngram",
                                               "draft_k": 3},
                prefix_cache=False, chunk_token_budget=budget, **self.MCFG,
            )
            try:
                streams = [
                    eng.submit(p, max_new_tokens=8) for p in self._prompts()
                ]
                eng.run()
                return [s.result for s in streams]
            finally:
                eng.close()

        on = spec_run(16)
        off = spec_run(0)
        for a, b, c in zip(on, off, plain):
            np.testing.assert_array_equal(a, b)
            np.testing.assert_array_equal(a, c)
