"""Run a real multi-process graph and print the stitched per-request
hop table — the cross-process twin of profile_engine_trace.py.

What it does, end to end:

1. spawns worker microservice processes (REST and gRPC transports) with
   ``TRACING=1`` and a per-worker ``SELDON_TPU_TRACE_EXPORT`` JSONL
   span sink;
2. builds a gateway-side predictor whose graph fans out to the workers
   over BOTH transports (an AVERAGE_COMBINER over a REST leg and a
   gRPC leg), installs the in-memory tracer, and drives ``--requests``
   predicts through it;
3. merges the gateway's spans with every worker's exported spans into
   one trace per request (W3C context propagated on every hop makes
   the worker spans real children of the gateway's node spans), and
   prints per request, per hop: total / serialize / network / handle
   decomposition plus payload bytes — the table that answers "where
   did this request's cross-process latency go";
4. verifies the stitching invariants the tracing layer promises
   (every span shares the root trace id; zero orphan microservice
   roots) and says so.

Run:  python tools/profile_trace_stitch.py [--requests 20]
      [--out /tmp/trace-stitch] [--worker seldon_core_tpu.engine.units.StubModel]
"""

import argparse
import asyncio
import json
import os
import socket
import subprocess
import sys
import time
from collections import defaultdict

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)


def free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def spawn_worker(component: str, http_port: int, grpc_port: int, span_path: str,
                 log_path: str):
    env = dict(
        os.environ,
        TRACING="1",
        SELDON_TPU_TRACE_EXPORT=span_path,
        JAX_PLATFORMS=os.environ.get("JAX_PLATFORMS", "cpu"),
    )
    # worker output goes to a FILE, not a pipe: nothing drains a pipe
    # after startup, and a chatty worker (access logs, jit-sentinel
    # WARNs) would fill the 64 KB buffer and block mid-run
    log = open(log_path, "wb")
    proc = subprocess.Popen(
        [
            sys.executable, "-m", "seldon_core_tpu.runtime.microservice",
            component, "--api", "BOTH", "--host", "127.0.0.1",
            "--http-port", str(http_port), "--grpc-port", str(grpc_port),
            "--unit-id", f"worker-{http_port}",
        ],
        cwd=REPO_ROOT, env=env,
        stdout=log, stderr=subprocess.STDOUT,
    )
    log.close()  # the child holds its own fd
    proc.log_path = log_path
    return proc


def await_ready(proc, http_port: int, timeout_s: float = 90.0) -> None:
    import urllib.request

    deadline = time.time() + timeout_s
    while time.time() < deadline:
        if proc.poll() is not None:
            with open(proc.log_path, errors="replace") as f:
                out = f.read()
            raise SystemExit(f"worker died at startup:\n{out[-4000:]}")
        try:
            with urllib.request.urlopen(
                f"http://127.0.0.1:{http_port}/health/ping", timeout=1
            ) as resp:
                if resp.status < 400:
                    return
        except Exception:  # noqa: BLE001
            time.sleep(0.2)
    raise SystemExit("worker never became ready")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--requests", type=int, default=20)
    ap.add_argument("--rows", type=int, default=4, help="payload rows per request")
    ap.add_argument("--out", default="/tmp/trace-stitch")
    ap.add_argument(
        "--worker", default="seldon_core_tpu.engine.units.StubModel",
        help="dotted component class each worker process serves",
    )
    args = ap.parse_args()

    import numpy as np

    from seldon_core_tpu.engine import PredictorService
    from seldon_core_tpu.engine.graph import Endpoint, UnitSpec
    from seldon_core_tpu.runtime.message import InternalMessage
    from seldon_core_tpu.utils import tracing

    os.makedirs(args.out, exist_ok=True)
    http_a, grpc_a = free_port(), free_port()
    http_b, grpc_b = free_port(), free_port()
    span_a = os.path.join(args.out, "worker-a.jsonl")
    span_b = os.path.join(args.out, "worker-b.jsonl")
    for p in (span_a, span_b):
        if os.path.exists(p):
            os.remove(p)

    print(f"spawning 2 workers ({args.worker}) — REST hop :{http_a}, gRPC hop :{grpc_b}")
    workers = [
        spawn_worker(args.worker, http_a, grpc_a, span_a,
                     os.path.join(args.out, "worker-a.log")),
        spawn_worker(args.worker, http_b, grpc_b, span_b,
                     os.path.join(args.out, "worker-b.log")),
    ]
    try:
        for proc, port in zip(workers, (http_a, http_b)):
            await_ready(proc, port)

        tracer = tracing.setup_tracing("stitch-gateway", capacity=65536)
        graph = UnitSpec(
            name="combiner", type="COMBINER", implementation="AVERAGE_COMBINER",
            children=[
                UnitSpec(name="node-a", type="MODEL", remote=True,
                         endpoint=Endpoint("127.0.0.1", http_a, "REST")),
                UnitSpec(name="node-b", type="MODEL", remote=True,
                         endpoint=Endpoint("127.0.0.1", grpc_b, "GRPC")),
            ],
        )
        svc = PredictorService(graph, name="main")

        async def drive():
            puids = []
            t0 = time.perf_counter()
            for i in range(args.requests):
                msg = InternalMessage(
                    payload=np.random.default_rng(i).random((args.rows, 4)),
                    kind="ndarray",
                )
                out = await svc.predict(msg)
                assert out.status["status"] == "SUCCESS", out.status
                puids.append(out.meta.puid)
            wall = time.perf_counter() - t0
            await svc.close()
            return puids, wall

        puids, wall = asyncio.run(drive())
        print(f"drove {args.requests} requests in {wall:.2f}s "
              f"({args.requests / wall:.1f} req/s)\n")
        local_spans = [s.to_dict() for s in list(tracer.spans)]
        tracing._tracer = None
    finally:
        for proc in workers:
            proc.terminate()
        for proc in workers:
            proc.wait(timeout=20)

    worker_spans = []
    for path in (span_a, span_b):
        deadline = time.time() + 10
        while time.time() < deadline and not os.path.exists(path):
            time.sleep(0.2)
        if os.path.exists(path):
            with open(path) as f:
                worker_spans.extend(json.loads(l) for l in f if l.strip())

    gateway_path = os.path.join(args.out, "gateway.jsonl")
    with open(gateway_path, "w") as f:
        for s in local_spans:
            f.write(json.dumps(s) + "\n")
    print(f"gateway spans -> {gateway_path}")
    print(f"worker spans  -> {span_a}, {span_b} ({len(worker_spans)} spans)\n")

    # ---- stitch -----------------------------------------------------------
    spans = local_spans + worker_spans
    by_trace = defaultdict(list)
    for s in spans:
        by_trace[s["traceId"]].append(s)
    children = defaultdict(list)
    for s in spans:
        if s.get("parentSpanId"):
            children[s["parentSpanId"]].append(s)

    def dur_ms(s):
        return s["durationNano"] / 1e6

    header = (f"{'request':<26} {'hop':<34} {'transport':>9} {'total':>8} "
              f"{'serial':>7} {'network':>8} {'handle':>7} {'req B':>7} {'resp B':>7}")
    print(header)
    print("-" * len(header))
    shown = 0
    for puid in puids:
        trace = by_trace.get(puid, [])
        hops = sorted(
            (s for s in trace if s["name"].startswith("node.")),
            key=lambda s: s["name"],
        )
        for hop in hops:
            tags = hop.get("tags", {})
            handle = sum(
                dur_ms(c) for c in children.get(hop["spanId"], [])
                if c["name"].startswith("microservice.")
            )
            print(f"{puid:<26} {hop['name']:<34} "
                  f"{tags.get('transport', '-'):>9} {dur_ms(hop):>8.2f} "
                  f"{tags.get('serialize_ms', 0):>7.2f} "
                  f"{tags.get('network_ms', 0):>8.2f} {handle:>7.2f} "
                  f"{tags.get('request_bytes', 0):>7} "
                  f"{tags.get('response_bytes', 0):>7}")
        shown += 1
        if shown >= 8 and len(puids) > 8:
            print(f"... ({len(puids) - shown} more requests; same shape)")
            break

    # per-hop aggregate
    agg = defaultdict(lambda: defaultdict(float))
    counts = defaultdict(int)
    for puid in puids:
        for s in by_trace.get(puid, []):
            if not s["name"].startswith("node."):
                continue
            tags = s.get("tags", {})
            a = agg[s["name"]]
            a["total"] += dur_ms(s)
            a["serialize"] += float(tags.get("serialize_ms", 0))
            a["network"] += float(tags.get("network_ms", 0))
            a["handle"] += sum(
                dur_ms(c) for c in children.get(s["spanId"], [])
                if c["name"].startswith("microservice.")
            )
            counts[s["name"]] += 1
    print("\nper-hop means (ms):")
    for name in sorted(agg):
        n = max(1, counts[name])
        a = agg[name]
        print(f"  {name:<34} total {a['total'] / n:7.2f}  "
              f"serialize {a['serialize'] / n:6.2f}  "
              f"network {a['network'] / n:7.2f}  handle {a['handle'] / n:6.2f}")

    # ---- stitching invariants --------------------------------------------
    request_spans = [s for t in puids for s in by_trace.get(t, [])]
    stitched = len(request_spans)
    all_request_spans = [
        s for s in spans
        if s["name"].startswith(("node.", "microservice.", "predictor.", "gen."))
    ]
    share = stitched / max(1, len(all_request_spans))
    orphans = [
        s for s in worker_spans
        if s["name"].startswith("microservice.")
        and (not s.get("parentSpanId") or s["parentSpanId"] not in
             {sp["spanId"] for sp in spans})
    ]
    print(f"\nstitching: {stitched}/{len(all_request_spans)} request spans "
          f"share a gateway root trace id ({share * 100:.1f}%), "
          f"{len(orphans)} orphan microservice roots")
    if share < 0.99 or orphans:
        raise SystemExit("TRACE STITCHING BROKEN: see counts above")
    print("stitch OK: one tree per request across "
          f"{len({s['traceId'] for s in request_spans})} traces")


if __name__ == "__main__":
    main()
