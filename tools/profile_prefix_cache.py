"""Drive shared-prefix traffic at a local PagedEngine and print the
per-request prefix-cache decomposition: matched pages, prompt tokens
whose prefill was skipped, and what the suffix actually prefilled.

What it does, end to end:

1. builds a local engine (prefix cache on unless --no-cache) and
   submits ``--streams`` requests one after another: every request
   shares a ``--shared``-token system prompt and appends a distinct
   user suffix, the "millions of users, one system prompt" traffic
   shape the cache exists for.  Sequential submission makes the cache
   dynamics visible request by request — the first request misses and
   publishes the prefix pages, every follower maps them;
2. prints the per-request table (prompt length, pages matched, prompt
   tokens saved, suffix tokens prefilled) plus the engine's cumulative
   prefix counters and, for contrast, the same run with the cache off;
3. optionally (``--pressure``) shrinks the pool so LRU reclamation
   engages, demonstrating cached pages giving way to live allocations
   (the `prefix_evictions` counter).

Run:  python tools/profile_prefix_cache.py [--streams 8] [--shared 256]
      [--suffix 24] [--new 32] [--no-cache] [--pressure] [--dtype f32]

Greedy outputs are asserted identical cache-on vs cache-off: shared
pages are read-only bit-identical KV, so reuse must never change a
token (the correctness bar tests/test_prefix_cache.py enforces across
chunk impls × precisions × speculative).  NUMERIC REGIME: exactness is
a single-regime property — the suffix prefill scores its cached
context in a separate einsum from the full prefill's one in-segment
einsum, and under bf16 the two programs can round a logit one ulp
apart and break a near-tied argmax differently (the same cross-program
caveat the pallas decode kernel and the speculative verify lane carry).
The default dtype here is therefore f32 (the assert is hard); --dtype
bf16 times the serving regime and reports argmax agreement instead.
"""

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--streams", type=int, default=8)
    ap.add_argument("--shared", type=int, default=256,
                    help="shared system-prompt tokens")
    ap.add_argument("--suffix", type=int, default=24,
                    help="base distinct-suffix tokens (varies per request)")
    ap.add_argument("--new", type=int, default=32)
    ap.add_argument("--d-model", type=int, default=256)
    ap.add_argument("--layers", type=int, default=4)
    ap.add_argument("--heads", type=int, default=8)
    ap.add_argument("--vocab", type=int, default=2048)
    ap.add_argument("--page-size", type=int, default=64)
    ap.add_argument("--max-len", type=int, default=1024)
    ap.add_argument("--slots", type=int, default=4)
    ap.add_argument("--no-cache", action="store_true",
                    help="run only the cache-off arm")
    ap.add_argument("--pressure", action="store_true",
                    help="shrink the pool so LRU reclamation engages")
    ap.add_argument("--dtype", choices=("f32", "bf16"), default="f32",
                    help="f32 (default): hard bit-exactness assert; "
                    "bf16: serving regime, argmax agreement reported")
    ap.add_argument("--tp", type=int, default=0,
                    help="tensor-parallel degree (0 = SELDON_TPU_TP "
                    "default, 1 = force single-chip); cached pages are "
                    "heads-sharded like the pool, so reuse works "
                    "identically TP-on")
    args = ap.parse_args()

    import numpy as np

    import jax
    import jax.numpy as jnp

    from seldon_core_tpu.models.paged import PagedEngine
    from seldon_core_tpu.models.transformer import TransformerLM

    dtype = jnp.float32 if args.dtype == "f32" else jnp.bfloat16
    cfg = dict(
        vocab_size=args.vocab, d_model=args.d_model,
        num_layers=args.layers, num_heads=args.heads, max_len=args.max_len,
    )
    lm = TransformerLM(dtype=dtype, **cfg)
    params = lm.init(jax.random.key(0), jnp.zeros((1, 8), jnp.int32))["params"]

    rng = np.random.default_rng(0)
    # --pressure alternates TWO system prompts through a pool sized for
    # one request: the competing prefixes evict each other's cached
    # pages, so the table shows reclamation engaging and hits degrading
    # honestly (the PrefixCacheThrash alert's traffic shape)
    n_shared = 2 if args.pressure else 1
    shareds = [
        rng.integers(0, args.vocab, size=(args.shared,)).astype(np.int32)
        for _ in range(n_shared)
    ]
    prompts = [
        np.concatenate([
            shareds[i % n_shared],
            rng.integers(
                0, args.vocab, size=(args.suffix + (i % 5) * 4,)
            ).astype(np.int32),
        ])
        for i in range(args.streams)
    ]

    num_pages = None
    if args.pressure:
        per_req = max(
            -(-(len(p) + args.new) // args.page_size) for p in prompts
        )
        num_pages = per_req + 2

    def run(prefix_cache: bool):
        eng = PagedEngine(
            params, dtype=dtype, page_size=args.page_size,
            max_slots=args.slots, steps_per_call=8, num_pages=num_pages,
            prefix_cache=prefix_cache,
            tp=args.tp or None, **cfg,
        )
        rows, outs = [], []
        t0 = time.perf_counter()
        for i, p in enumerate(prompts):
            s0 = eng.engine_stats()
            stream = eng.submit(p, max_new_tokens=args.new)
            t_req = time.perf_counter()
            eng.run()
            dt_req = time.perf_counter() - t_req
            s1 = eng.engine_stats()
            saved = s1["prefix_tokens_saved"] - s0["prefix_tokens_saved"]
            rows.append({
                "req": i,
                "prompt": len(p),
                "matched_pages": saved // args.page_size,
                "tokens_saved": saved,
                "prefilled": len(p) - saved,
                "evictions": s1["prefix_evictions"] - s0["prefix_evictions"],
                "ms": dt_req * 1e3,
            })
            outs.append(stream.result)
        wall = time.perf_counter() - t0
        stats = eng.engine_stats()
        eng.close()
        return rows, outs, stats, wall

    mode = "OFF" if args.no_cache else "ON"
    rows, outs, stats, wall = run(prefix_cache=not args.no_cache)
    print(f"\nprefix cache {mode} — {args.streams} requests, "
          f"{args.shared}-token shared prompt, page_size {args.page_size}")
    hdr = (f"{'req':>4} {'prompt':>7} {'matched':>8} {'saved_tok':>10} "
           f"{'prefilled':>10} {'evict':>6} {'ms':>9}")
    print(hdr)
    print("-" * len(hdr))
    for r in rows:
        print(f"{r['req']:>4} {r['prompt']:>7} {r['matched_pages']:>8} "
              f"{r['tokens_saved']:>10} {r['prefilled']:>10} "
              f"{r['evictions']:>6} {r['ms']:>9.1f}")
    hits, misses = stats["prefix_hits"], stats["prefix_misses"]
    print(f"\ncumulative: hits={hits} misses={misses} "
          f"hit_pct={100.0 * hits / max(1, hits + misses):.1f} "
          f"tokens_saved={stats['prefix_tokens_saved']} "
          f"pages_cached={stats['prefix_pages_cached']} "
          f"evictions={stats['prefix_evictions']}  wall={wall:.2f}s")

    if not args.no_cache:
        off_rows, off_outs, _, off_wall = run(prefix_cache=False)
        if args.dtype == "f32":
            for a, b in zip(outs, off_outs):
                assert np.array_equal(a, b), \
                    "greedy outputs must be bit-exact cache-on vs cache-off"
            parity = "outputs bit-exact both arms"
        else:
            # bf16: cross-program one-regime caveat (see module doc) —
            # report agreement instead of asserting a property the
            # regime does not promise
            agree = float(np.mean([
                np.mean(a == b) for a, b in zip(outs, off_outs)
            ]))
            parity = f"bf16 token agreement {agree:.3f} (one-regime caveat)"
        print(f"cache-off contrast: wall={off_wall:.2f}s vs {wall:.2f}s "
              "cache-on (sequential cold protocol: the cache-on arm "
              "pays the suffix-program compiles; the warm per-request "
              f"ms above is the steadier signal) — {parity}")


if __name__ == "__main__":
    main()
