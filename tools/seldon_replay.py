"""Deterministic replay of a request capture container (r21).

``utils/capture.py`` stores one SRT1 container per interesting request:
the exact prompt, the per-request seed the serving component mixed, the
sampling recipe, the adapter selection, the StreamingLM constructor
config, and the knob snapshot of the capturing process.  This tool
closes the forensics loop — it rebuilds that engine, re-submits the
exact request through the SAME ingress path (``StreamingLM.predict``
with a ``seed`` tag override, so adapter resolution and seed mixing are
the production code, not a reimplementation), and diffs the outcome:

* **tokens** — a greedy capture (``temperature == 0``) must replay
  BIT-EXACT on the same numeric regime; sampled captures report the
  first divergence index instead of asserting.
* **latency terms** — the replay runs with the capture plane pointed at
  a throwaway store, so the replayed request produces its own
  five-phase decomposition; the report diffs queued/prefill/decode/
  ttft/total against the original.

One-numeric-regime caveat: bit-exactness is a claim about the SAME
compiled numerics.  A capture taken on TPU bf16 replayed on CPU f32
(or across XLA versions) can legitimately diverge on sampled runs and,
rarely, on logit ties in greedy runs — the report carries both
regimes' identities so a diff is attributable.

Run::

    python tools/seldon_replay.py /path/to/capture-<puid>-<crc>.srt1
    python tools/seldon_replay.py <puid> --store $SELDON_TPU_CAPTURE_DIR
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import sys
import tempfile
from typing import Any, Dict, List, Optional

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# knobs a replay must NOT inherit from the capturing process: the
# capture plane's own switches (the replay wires its own throwaway
# store), journal/dump/export paths (writing into the incident
# process's directories would contaminate the originals), and the
# fleet-polling endpoints (a replay host has no fleet)
_SKIP_KNOB_PREFIXES = ("SELDON_TPU_CAPTURE", "SELDON_TPU_FLEET_")
_SKIP_KNOBS = {
    "SELDON_TPU_DRAIN_JOURNAL",
    "SELDON_TPU_TRACE_EXPORT",
    "SELDON_TPU_DUMP_DIR",
    "SELDON_TPU_PROFILE_DIR",
}


def _skip_knob(name: str) -> bool:
    return name in _SKIP_KNOBS or any(
        name.startswith(p) for p in _SKIP_KNOB_PREFIXES
    )


def _first_divergence(a, b) -> Optional[int]:
    """Index of the first differing token, None when identical
    (length differences diverge at the shorter length)."""
    import numpy as np

    a = np.asarray(a, np.int64).reshape(-1)
    b = np.asarray(b, np.int64).reshape(-1)
    n = min(a.size, b.size)
    neq = np.nonzero(a[:n] != b[:n])[0]
    if neq.size:
        return int(neq[0])
    if a.size != b.size:
        return n
    return None


def load_capture(source: str, store_dir: str = ""):
    """Resolve ``source`` (a container path, or a puid looked up in
    ``store_dir`` / ``SELDON_TPU_CAPTURE_DIR``) to a RequestCapture."""
    from seldon_core_tpu.utils.capture import CaptureStore

    if os.path.isfile(source):
        cap = CaptureStore.load(source)
        if cap is None:
            raise SystemExit(f"unreadable capture container: {source}")
        return cap
    root = store_dir or os.environ.get("SELDON_TPU_CAPTURE_DIR", "")
    if not root:
        raise SystemExit(
            f"{source!r} is not a file and no store directory is set "
            "(--store / SELDON_TPU_CAPTURE_DIR)"
        )
    cap = CaptureStore(root=root).get(source)
    if cap is None:
        raise SystemExit(f"no capture for puid {source!r} under {root}")
    return cap


def replay_capture(cap, *, strict: Optional[bool] = None) -> Dict[str, Any]:
    """Re-execute one capture and return the diff report.

    ``strict`` forces/suppresses the greedy bit-exact assertion
    (default: assert exactly when the capture is greedy).  The report
    dict carries ``bit_exact``, ``first_divergence``, the replayed
    tokens, and the per-term latency diff.
    """
    import numpy as np

    from seldon_core_tpu.utils import capture as capture_mod

    prompt = np.asarray(
        [] if cap.prompt is None else cap.prompt, np.int32
    ).reshape(-1)
    if prompt.size == 0:
        return {
            "puid": cap.puid,
            "replayable": False,
            "info": "capture has no prompt frame "
                    "(SELDON_TPU_CAPTURE_PAYLOADS=0 at capture time)",
        }
    if cap.seed is None:
        return {
            "puid": cap.puid,
            "replayable": False,
            "info": "capture carries no request seed",
        }

    greedy = float(cap.temperature or 0.0) == 0.0
    if strict is None:
        strict = greedy

    touched: Dict[str, Optional[str]] = {}

    def setenv(name: str, value: Optional[str]) -> None:
        if name not in touched:
            touched[name] = os.environ.get(name)
        if value is None:
            os.environ.pop(name, None)
        else:
            os.environ[name] = value

    replay_store = tempfile.mkdtemp(prefix="seldon-tpu-replay-")
    lm = None
    try:
        # the captured process's SET knobs, minus the skip list — the
        # engine the replay builds must resolve its env-driven shape
        # (kernel lane, chunk budget, prefix cache, ...) exactly as the
        # capturing engine did
        applied: List[str] = []
        for k in cap.knobs or []:
            name = str(k.get("name", ""))
            if not name or _skip_knob(name):
                continue
            setenv(name, str(k.get("value", "")))
            applied.append(name)
        # throwaway capture plane for the replay itself: the replayed
        # request writes its own container, which is where its
        # five-phase latency decomposition comes from
        setenv("SELDON_TPU_CAPTURE", "1")
        setenv("SELDON_TPU_CAPTURE_SAMPLE", "1")
        setenv("SELDON_TPU_CAPTURE_PAYLOADS", "1")
        setenv("SELDON_TPU_CAPTURE_DIR", replay_store)
        capture_mod.reset_default_store()

        from seldon_core_tpu.models.paged import StreamingLM

        model_cfg = dict(cap.model or {})
        lm = StreamingLM(**model_cfg)
        tags: Dict[str, Any] = {
            "seed": int(cap.seed),
            "max_new_tokens": int(cap.max_new_tokens),
            "temperature": float(cap.temperature),
            "top_k": int(cap.top_k),
        }
        if cap.adapter:
            tags["adapter"] = cap.adapter
        if cap.priority:
            tags["priority"] = int(cap.priority)
        meta = {"puid": cap.puid, "tags": tags}
        result = lm.predict(prompt.reshape(1, -1), [], meta=meta)
        replayed = np.asarray(result[0], np.int32).reshape(-1)

        captured = np.asarray(
            [] if cap.tokens is None else cap.tokens, np.int32
        ).reshape(-1)
        divergence = _first_divergence(captured, replayed)
        bit_exact = divergence is None

        replay_cap = capture_mod.CaptureStore(root=replay_store).get(cap.puid)
        latency: Dict[str, Any] = {}
        if replay_cap is not None:
            for term in ("queued_ms", "prefill_ms", "decode_ms",
                         "ttft_ms", "total_ms"):
                was = (cap.phases or {}).get(term)
                now = (replay_cap.phases or {}).get(term)
                latency[term] = {
                    "captured": was,
                    "replayed": now,
                    "delta": (round(now - was, 3)
                              if was is not None and now is not None
                              else None),
                }

        report = {
            "puid": cap.puid,
            "replayable": True,
            "greedy": greedy,
            "status_at_capture": cap.status,
            "trigger": cap.trigger,
            "adapter": cap.adapter,
            "seed": cap.seed,
            "knobs_applied": applied,
            "bit_exact": bool(bit_exact),
            "first_divergence": divergence,
            "captured_tokens": captured.tolist(),
            "replayed_tokens": replayed.tolist(),
            "latency": latency,
        }
        if strict and not bit_exact:
            raise AssertionError(
                f"greedy replay diverged at token {divergence}: "
                f"captured={captured.tolist()} "
                f"replayed={replayed.tolist()}"
            )
        return report
    finally:
        if lm is not None:
            try:
                lm.shutdown()
            except Exception:  # noqa: BLE001 — teardown best-effort
                pass
        for name, old in touched.items():
            if old is None:
                os.environ.pop(name, None)
            else:
                os.environ[name] = old
        capture_mod.reset_default_store()
        shutil.rmtree(replay_store, ignore_errors=True)


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument(
        "source",
        help="capture container path, or a puid resolved via --store",
    )
    ap.add_argument(
        "--store", default="",
        help="capture store directory for puid lookups "
             "(default: $SELDON_TPU_CAPTURE_DIR)",
    )
    ap.add_argument(
        "--no-strict", action="store_true",
        help="report instead of asserting on greedy divergence",
    )
    ap.add_argument("--json", action="store_true", help="machine output")
    args = ap.parse_args(argv)

    cap = load_capture(args.source, store_dir=args.store)
    report = replay_capture(cap, strict=False if args.no_strict else None)
    if args.json:
        print(json.dumps(report, indent=2, default=str))
        return 0 if report.get("bit_exact", not report["replayable"]) else 1
    if not report["replayable"]:
        print(f"[replay] {report['puid']}: NOT replayable — {report['info']}")
        return 2
    print(f"[replay] puid={report['puid']} trigger={report['trigger']} "
          f"greedy={report['greedy']} adapter={report['adapter'] or '-'}")
    if report["bit_exact"]:
        print(f"[replay] tokens: BIT-EXACT "
              f"({len(report['captured_tokens'])} tokens)")
    else:
        print(f"[replay] tokens: DIVERGED at index "
              f"{report['first_divergence']}")
    for term, d in report["latency"].items():
        print(f"[replay] {term:>11}: captured={d['captured']} "
              f"replayed={d['replayed']} delta={d['delta']}")
    return 0 if report["bit_exact"] else 1


if __name__ == "__main__":
    raise SystemExit(main())
