"""Profile the fused Pallas paged-decode lane against the XLA gather
lane on the raw attention step (r18, ROADMAP 1).

Times `paged_attention_decode` (kernel) vs the dense gather+softmax
XLA program on identical pool state — N iterations inside one jit per
arm (one dispatch, one readback, so the harness relay cannot pollute
the per-step number) — and prints the capacity-side arithmetic next to
the timing: HBM bytes/step at bf16 vs int8 page storage and the Mosaic
grid-step count of each kernel impl.

Off-TPU the kernel runs in interpret mode: a correctness harness, not
a timing one — the tool still prints the host-arithmetic terms but
labels the timing columns accordingly.  The bench's compact
`paged_kernel_x` gate (>= 1.5) is adjudicated from the engine-level
`kernel_lane` blob on a TPU run, not from this micro-probe; this tool
exists to decompose WHERE a regression lives (kernel step vs engine
overhead) when that gate moves.

Run:  python tools/profile_paged_kernel.py [--streams 16] [--ctx 512]
      [--impl stream|grid] [--kv-dtype bf16|int8] [--steps 32]
"""

import argparse
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _time_arm(fn, args, steps, repeats):
    """Best-of-N wall over a scan-of-steps jit: returns per-step us."""
    import jax

    out = fn(*args)
    jax.block_until_ready(out)  # compile + warm
    best = float("inf")
    for _ in range(repeats):
        t0 = time.perf_counter()
        out = fn(*args)
        jax.block_until_ready(out)
        best = min(best, time.perf_counter() - t0)
    return best / steps * 1e6


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--streams", type=int, default=16)
    ap.add_argument("--ctx", type=int, default=512)
    ap.add_argument("--heads", type=int, default=8)
    ap.add_argument("--head-dim", type=int, default=64)
    ap.add_argument("--layers", type=int, default=8,
                    help="layer count for the HBM bytes/step term "
                    "(the micro-probe times ONE layer's attention)")
    ap.add_argument("--page-size", type=int, default=64)
    ap.add_argument("--impl", choices=("stream", "grid"), default="stream")
    ap.add_argument("--kv-dtype", choices=("bf16", "int8"), default="bf16")
    ap.add_argument("--steps", type=int, default=32)
    ap.add_argument("--repeats", type=int, default=5)
    args = ap.parse_args()

    os.environ["SELDON_TPU_PAGED_KERNEL_IMPL"] = args.impl

    import jax
    import jax.numpy as jnp

    from seldon_core_tpu.models.paged import paged_hbm_accounting
    from seldon_core_tpu.ops.kernels import paged_attention_decode

    B, h, hd, ps = args.streams, args.heads, args.head_dim, args.page_size
    pages_per = -(-args.ctx // ps)
    num_pages = B * pages_per + 1
    on_tpu = jax.default_backend() == "tpu"

    rng = np.random.default_rng(0)
    dt = jnp.bfloat16
    q = jnp.asarray(rng.normal(size=(B, h, hd)), dt)
    pk = jnp.asarray(rng.normal(size=(num_pages, ps, h, hd)), dt)
    pv = jnp.asarray(rng.normal(size=(num_pages, ps, h, hd)), dt)
    tables = jnp.asarray(
        1 + np.arange(B * pages_per).reshape(B, pages_per) % (num_pages - 1),
        jnp.int32)
    lengths = jnp.full((B,), args.ctx, jnp.int32)

    kv_scales = None
    if args.kv_dtype == "int8":
        amax = jnp.maximum(
            jnp.max(jnp.abs(pk.astype(jnp.float32)), axis=(1, 2, 3)) / 127.0,
            1e-8)
        pk = jnp.clip(jnp.round(pk.astype(jnp.float32)
                                / amax[:, None, None, None]),
                      -127, 127).astype(jnp.int8)
        vmax = jnp.maximum(
            jnp.max(jnp.abs(pv.astype(jnp.float32)), axis=(1, 2, 3)) / 127.0,
            1e-8)
        pv = jnp.clip(jnp.round(pv.astype(jnp.float32)
                                / vmax[:, None, None, None]),
                      -127, 127).astype(jnp.int8)
        kv_scales = (amax, vmax)

    steps = args.steps

    @jax.jit
    def kernel_arm(q, pk, pv, tables, lengths):
        def step(c, _):
            acc, m, el = paged_attention_decode(
                c, pk, pv, tables, lengths, page_size=ps,
                kv_scales=kv_scales)
            return (acc / jnp.maximum(el, 1e-9)[..., None]).astype(c.dtype), 0
        out, _ = jax.lax.scan(step, q, None, length=steps)
        return out

    @jax.jit
    def xla_arm(q, pk, pv, tables, lengths):
        def step(c, _):
            gk = pk[tables].reshape(B, pages_per * ps, h, hd)
            gv = pv[tables].reshape(B, pages_per * ps, h, hd)
            if kv_scales is not None:
                gk = (gk.astype(jnp.float32)
                      * kv_scales[0][tables].reshape(B, pages_per, 1, 1, 1)
                      .repeat(ps, 1).reshape(B, pages_per * ps, 1, 1))
                gv = (gv.astype(jnp.float32)
                      * kv_scales[1][tables].reshape(B, pages_per, 1, 1, 1)
                      .repeat(ps, 1).reshape(B, pages_per * ps, 1, 1))
            s = jnp.einsum("bhd,bkhd->bhk", c.astype(jnp.float32),
                           gk.astype(jnp.float32))
            mask = jnp.arange(pages_per * ps)[None, :] < lengths[:, None]
            s = jnp.where(mask[:, None, :], s, -jnp.inf)
            w = jax.nn.softmax(s, axis=-1)
            out = jnp.einsum("bhk,bkhd->bhd", w, gv.astype(jnp.float32))
            return out.astype(c.dtype), 0
        out, _ = jax.lax.scan(step, q, None, length=steps)
        return out

    arm_args = (q, pk, pv, tables, lengths)
    kern_us = _time_arm(kernel_arm, arm_args, steps, args.repeats)
    xla_us = _time_arm(xla_arm, arm_args, steps, args.repeats)

    acct_kw = dict(
        num_layers=args.layers, d_model=h * hd, page_size=ps,
        ctx_len=args.ctx, streams=B, chunk_impl="pool", flat_pool=False,
        dtype_bytes=2)
    bf16_bytes = paged_hbm_accounting(**acct_kw)["pool_bytes"]
    int8_bytes = paged_hbm_accounting(kv_dtype="int8", **acct_kw)["pool_bytes"]
    grid_steps = B if args.impl == "stream" else B * pages_per

    lane = "TPU" if on_tpu else "interpret (CORRECTNESS ONLY, not a timing)"
    print(f"paged-decode kernel probe — impl={args.impl} "
          f"kv_dtype={args.kv_dtype} lane={lane}")
    print(f"  streams={B} ctx={args.ctx} heads={h} head_dim={hd} "
          f"page_size={ps} pages/seq={pages_per}")
    print(f"  kernel per-step: {kern_us:10.1f} us")
    print(f"  XLA    per-step: {xla_us:10.1f} us")
    print(f"  kernel_x       : {xla_us / max(kern_us, 1e-9):10.2f}x"
          + ("" if on_tpu else "   (interpret-mode ratio — not citable)"))
    print(f"  mosaic grid steps/launch: {grid_steps}"
          f"  (DMA loop depth {pages_per} per lane)" )
    print(f"  HBM pool bytes ({args.layers}L model): "
          f"bf16 {bf16_bytes:,}  int8 {int8_bytes:,}  "
          f"ratio {bf16_bytes / max(int8_bytes, 1):.2f}x")


if __name__ == "__main__":
    main()
