"""Fleet top: a terminal view of the r20 telemetry plane (§5c-ter).

Polls the gateway's ``GET /debug/fleet`` (or, with ``--endpoints``,
builds its own in-process :class:`TelemetryAggregator` and polls the
replicas' ``/debug/telemetry`` directly — no gateway required) and
renders one row per replica: freshness state, goodput / prefill tok/s,
queue depth, slot and KV-pool occupancy, prefix hit rate, resident
adapters, and KV page-seconds/s (the cost ledger's burn rate), topped
by the fleet rollup line the autoscaler would read.

Run:  python tools/seldon_top.py --gateway http://localhost:8000
      python tools/seldon_top.py --endpoints r0=http://h0:9000,r1=http://h1:9000
      python tools/seldon_top.py --gateway ... --once --json   # scripting
"""

import argparse
import json
import os
import sys
import time
import urllib.request

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)

CLEAR = "\x1b[2J\x1b[H"
STATE_GLYPH = {"ok": " ", "stale": "?", "incompatible": "!", "never": "-"}


def fetch_gateway(base: str, timeout_s: float) -> dict:
    url = f"{base.rstrip('/')}/debug/fleet"
    with urllib.request.urlopen(url, timeout=timeout_s) as resp:
        return json.loads(resp.read().decode("utf-8"))


def build_aggregator(endpoints: str, poll_s: float):
    from seldon_core_tpu.controlplane.fleetview import (
        TelemetryAggregator,
        endpoints_from_knob,
    )

    eps = endpoints_from_knob(endpoints)
    if not eps:
        raise SystemExit("no replica endpoints parsed from --endpoints")
    return TelemetryAggregator(endpoints=eps, poll_s=poll_s)


def _pct(used, total) -> str:
    total = float(total or 0)
    return f"{100.0 * float(used or 0) / total:5.1f}%" if total else "    -"


def _bytes(n) -> str:
    """Human bytes for the HOSTKV column; '-' when the replica runs
    with the KV tier off (key absent from its snapshot)."""
    if n is None:
        return "-"
    n = float(n)
    for unit in ("B", "K", "M", "G", "T"):
        if n < 1024.0 or unit == "T":
            return f"{n:.0f}{unit}" if unit == "B" else f"{n:.1f}{unit}"
        n /= 1024.0
    return f"{n:.1f}T"


def render(view: dict) -> str:
    roll = view.get("rollup", {})
    lines = [
        "seldon-tpu fleet  replicas {}/{} ok  goodput {:.1f} tok/s  "
        "queue {:.0f}  sat max {:.2f}  cost {:.3f} page-s/s".format(
            roll.get("replicas_ok", 0), roll.get("replicas_total", 0),
            roll.get("fleet_goodput_tok_s", 0.0),
            roll.get("fleet_queue_depth", 0.0),
            roll.get("fleet_saturation_max", 0.0),
            roll.get("fleet_cost_page_s_s", 0.0),
        ),
        "",
        "  {:<16} {:<6} {:>9} {:>9} {:>6} {:>7} {:>7} {:>7} {:>8} "
        "{:>10}  {}".format(
            "REPLICA", "STATE", "GOOD t/s", "PREF t/s", "QUEUE",
            "SLOTS", "KV%", "HIT%", "HOSTKV", "COST p-s/s", "ADAPTERS",
        ),
    ]
    for name in sorted(view.get("replicas", {})):
        r = view["replicas"][name]
        p = r.get("latest") or {}
        lines.append(
            " {}{:<16} {:<6} {:>9.1f} {:>9.1f} {:>6d} {:>4d}/{:<2d} {:>7} "
            "{:>6.1f} {:>8} {:>10.3f}  {}".format(
                STATE_GLYPH.get(r.get("state"), " "), name[:16],
                r.get("state", "?"),
                float(p.get("goodput_tok_s", 0.0)),
                float(p.get("prefill_tok_s", 0.0)),
                int(p.get("queue_depth", 0)),
                int(p.get("active_slots", 0)),
                int(p.get("active_slots_total", 0)),
                _pct(p.get("pool_pages_used"), p.get("pool_pages_total")),
                float(p.get("prefix_hit_pct", 0.0)),
                _bytes(p.get("kv_tier_host_bytes")),
                float(p.get("cost_page_s_s", 0.0)),
                ",".join(p.get("adapters") or []) or "-",
            )
        )
        if r.get("last_err"):
            lines.append(f"    last_err: {r['last_err']}")
    adapters = view.get("adapters") or {}
    if adapters:
        lines.append("")
        lines.append("  adapter residency: " + "  ".join(
            f"{a}->{','.join(reps)}" for a, reps in adapters.items()))
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--gateway", default="",
                    help="gateway base URL serving /debug/fleet")
    ap.add_argument("--endpoints", default="",
                    help="direct replica endpoints (name=url,name=url); "
                         "bypasses the gateway")
    ap.add_argument("--interval", type=float, default=2.0,
                    help="refresh interval seconds (default 2)")
    ap.add_argument("--timeout", type=float, default=3.0)
    ap.add_argument("--once", action="store_true",
                    help="render one frame and exit")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="emit the raw fleet view as JSON instead of a table")
    args = ap.parse_args(argv)

    if not args.gateway and not args.endpoints:
        ap.error("need --gateway or --endpoints")

    agg = None
    if args.endpoints:
        agg = build_aggregator(args.endpoints, args.interval)

    try:
        while True:
            if agg is not None:
                view = agg.poll_once()
            else:
                view = fetch_gateway(args.gateway, args.timeout)
            if args.as_json:
                out = json.dumps(view, indent=2, sort_keys=True)
            else:
                out = render(view)
            if args.once:
                print(out)
                return 0
            sys.stdout.write(CLEAR + out + "\n")
            sys.stdout.flush()
            time.sleep(args.interval)
    except KeyboardInterrupt:
        return 0


if __name__ == "__main__":
    sys.exit(main())
