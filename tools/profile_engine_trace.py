"""Drive synthetic mixed-length traffic at a local PagedEngine and
print the per-request lifecycle decomposition the flight recorder +
tracing layers exist for.

What it does, end to end (the same three observability layers a
production deployment gets, exercised standalone):

1. installs the in-memory tracer, builds a local engine, and submits a
   bimodal prompt mix (short/long alternating — the traffic shape the
   length-bucketed gather serves) with more streams than slots, so the
   queue-wait term is actually nonzero;
2. collects the flight-recorder ring and dumps it to JSONL
   (``--out``), alongside a JSONL of every gen.* span;
3. prints the per-request queue-wait / prefill / decode decomposition
   table from the lifecycle spans, plus the chunk-wall summary from
   the recorder — the table that answers "where did this request's
   latency go" without a profiler attached.

Run:  python tools/profile_engine_trace.py [--slots 8] [--streams 24]
      [--short 16] [--long 192] [--new 64] [--out /tmp/engine-trace]

Set SELDON_TPU_PROFILE_DIR to additionally wrap the first chunks in
``jax.profiler.trace`` for XLA-level inspection.
"""

import argparse
import json
import os
import sys
import time
from collections import defaultdict

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--slots", type=int, default=8)
    ap.add_argument("--streams", type=int, default=24)
    ap.add_argument("--short", type=int, default=16)
    ap.add_argument("--long", type=int, default=192)
    ap.add_argument("--new", type=int, default=64)
    ap.add_argument("--d-model", type=int, default=256)
    ap.add_argument("--layers", type=int, default=4)
    ap.add_argument("--heads", type=int, default=8)
    ap.add_argument("--vocab", type=int, default=2048)
    ap.add_argument("--page-size", type=int, default=64)
    ap.add_argument("--max-len", type=int, default=512)
    ap.add_argument(
        "--chunk-budget", type=int, default=0,
        help="SELDON_TPU_CHUNK_TOKEN_BUDGET for the engine (0 = "
             "monolithic prefill, the historical scheduler)",
    )
    ap.add_argument("--out", default="/tmp/engine-trace")
    args = ap.parse_args()

    import numpy as np

    import jax
    import jax.numpy as jnp

    from seldon_core_tpu.models.paged import PagedEngine
    from seldon_core_tpu.models.transformer import TransformerLM
    from seldon_core_tpu.utils import tracing

    tracer = tracing.setup_tracing("profile-engine-trace", capacity=65536)

    lm = TransformerLM(
        vocab_size=args.vocab, d_model=args.d_model,
        num_layers=args.layers, num_heads=args.heads,
        max_len=args.max_len, dtype=jnp.bfloat16)
    params = lm.init(jax.random.key(0), jnp.zeros((1, 8), jnp.int32))["params"]

    eng = PagedEngine(
        params, vocab_size=args.vocab, d_model=args.d_model,
        num_layers=args.layers, num_heads=args.heads,
        max_len=args.max_len, page_size=args.page_size,
        max_slots=args.slots, steps_per_call=8,
        chunk_token_budget=args.chunk_budget,
        dtype=jnp.bfloat16,
    )

    rng = np.random.default_rng(7)
    prompts = [
        rng.integers(
            0, args.vocab,
            size=(args.short if i % 2 == 0 else args.long,),
        ).astype(np.int32)
        for i in range(args.streams)
    ]

    print(f"submitting {args.streams} streams ({args.short}/{args.long} "
          f"bimodal prompts, {args.new} new tokens) at {args.slots} slots")
    t0 = time.perf_counter()
    streams = [
        eng.submit(p, max_new_tokens=args.new, trace_id=f"req-{i:03d}")
        for i, p in enumerate(prompts)
    ]
    eng.run()
    wall = time.perf_counter() - t0
    total = sum(int(s.result.shape[0]) for s in streams)
    print(f"done: {total} tokens in {wall:.2f}s = {total / wall:.0f} tok/s\n")

    # ---- artifacts --------------------------------------------------------
    os.makedirs(args.out, exist_ok=True)
    rec_path = os.path.join(args.out, "flightrec.jsonl")
    if eng.recorder is not None:
        eng.recorder.dump_jsonl(rec_path)
    span_path = os.path.join(args.out, "spans.jsonl")
    with tracer._lock:  # noqa: SLF001 — read-only snapshot
        spans = list(tracer.spans)
    with open(span_path, "w") as f:
        for s in spans:
            f.write(json.dumps(s.to_dict()) + "\n")
    print(f"flight recorder -> {rec_path}\nspans          -> {span_path}\n")

    # ---- per-request decomposition ---------------------------------------
    by_req = defaultdict(dict)
    for s in spans:
        if s.name.startswith("gen."):
            by_req[s.trace_id][s.name] = s
    by_rid_stream = {f"req-{i:03d}": s for i, s in enumerate(streams)}
    print(f"{'request':<10} {'queue ms':>9} {'prefill ms':>11} "
          f"{'decode ms':>10} {'ttft ms':>8} {'tokens':>7} {'slot':>5} "
          f"{'evicted':>8}")
    agg = defaultdict(float)
    for rid in sorted(by_req):
        phases = by_req[rid]
        q = phases.get("gen.queued")
        p = phases.get("gen.prefill")
        d = phases.get("gen.decode")
        fin = phases.get("gen.finish")
        # TTFT: first decode token minus submit — the interactive
        # latency the chunked-prefill scheduler exists to protect
        # (queue + prefill + first decode chunk, in one number)
        st = by_rid_stream.get(rid)
        ttft = (
            (st.t_first_token - st.t_submit) * 1000.0
            if st is not None and st.t_first_token and st.t_submit else 0.0
        )
        row = [
            q.duration_s * 1000 if q else 0.0,
            p.duration_s * 1000 if p else 0.0,
            d.duration_s * 1000 if d else 0.0,
        ]
        agg["queue"] += row[0]
        agg["prefill"] += row[1]
        agg["decode"] += row[2]
        agg["ttft"] += ttft
        print(f"{rid:<10} {row[0]:>9.1f} {row[1]:>11.1f} {row[2]:>10.1f} "
              f"{ttft:>8.1f} "
              f"{(fin.tags.get('tokens') if fin else 0):>7} "
              f"{(fin.tags.get('slot') if fin else '-'):>5} "
              f"{'yes' if 'gen.evict' in phases else 'no':>8}")
    n = max(1, len(by_req))
    print(f"\nmeans: queue {agg['queue'] / n:.1f} ms, "
          f"prefill {agg['prefill'] / n:.1f} ms, "
          f"decode {agg['decode'] / n:.1f} ms, "
          f"ttft {agg['ttft'] / n:.1f} ms over {len(by_req)} requests")

    if eng.recorder is not None:
        rs = eng.recorder.stats()
        recs = eng.recorder.snapshot()
        stalls = sum(r.get("stalls", 0) for r in recs)
        print(f"chunks recorded {rs['records']}, chunk p99 "
              f"{rs['chunk_p99_ms']:.1f} ms, stalls {stalls}, "
              f"last queue depth {rs['last_queue_depth']}")
        # the scheduler's chosen chunk mix (r15): what each wave
        # actually carried under the token budget
        total = max(
            1, rs["window_prefill_tokens"] + rs["window_decode_tokens"]
        )
        mixed = sum(
            1 for r in recs
            if r.get("prefill_tokens", 0) and r.get("decode_tokens", 0)
        )
        print(f"chunk mix (budget={eng.chunk_token_budget or 'off'}): "
              f"{rs['window_prefill_tokens']} prefill + "
              f"{rs['window_decode_tokens']} decode tokens "
              f"({100.0 * rs['window_prefill_tokens'] / total:.0f}% "
              f"prefill), {mixed}/{rs['records']} waves mixed "
              "prefill+decode")
    eng.close()
    tracing._tracer = None


if __name__ == "__main__":
    main()
