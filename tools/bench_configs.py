"""Benchmark the five reference configs end-to-end.

BASELINE.md lists the five benchmark configurations the reference is
measured on (iris-style single model over REST, tabular regressor over
gRPC, ResNet-50, the MAB two-model graph with feedback, and the
combiner + transformer pipeline).  This harness deploys each config's
example spec through the real control plane, serves it on real
loopback ports, drives it with the client SDK under closed-loop load,
and prints one JSON line per config plus a summary line.

    python tools/bench_configs.py --quick            # CPU smoke, no resnet
    python tools/bench_configs.py --seconds 10       # the full matrix

The headline driver benchmark stays `bench.py`; this is the breadth
harness for the config matrix (reference analogue: the per-server
sample deployments under servers/*/samples + helm-charts/seldon-mab).
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import sys
import time
from typing import Any, Dict, Optional

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

# name -> (spec file, request shape, transport, extras)
CONFIGS = {
    "single_model_rest": ("examples/single_model.yaml", (1, 4), "rest", {}),
    "tabular_grpc": ("examples/tabular_grpc.yaml", (1, 13), "grpc", {}),
    "resnet50_grpc": ("examples/resnet50_tpu.yaml", (1, 224, 224, 3), "grpc", {"dtype": "uint8"}),
    "mab_feedback": ("examples/mab_abtest.yaml", (1, 4), "rest", {"feedback": True}),
    "combiner_pipeline": ("examples/combiner_pipeline.yaml", (1, 4), "rest", {}),
}


async def _bench_one(
    name: str,
    spec_path: str,
    shape,
    transport: str,
    extras: Dict[str, Any],
    seconds: float,
    concurrency: int,
) -> Dict[str, Any]:
    import numpy as np

    from seldon_core_tpu.client.client import SeldonTpuClient
    from seldon_core_tpu.controlplane import Deployer, TpuDeployment
    from seldon_core_tpu.controlplane.deployer import serve_deployment
    from seldon_core_tpu.testing.loadgen import run_load

    spec = TpuDeployment.load(os.path.join(REPO, spec_path))
    # every config gets its own ephemeral ports — parallel-safe
    import socket

    def free_port() -> int:
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            return s.getsockname()[1]

    http_port, grpc_port = free_port(), free_port()
    deployer = Deployer()
    t0 = time.perf_counter()
    await deployer.apply(spec, ready_timeout_s=600.0)
    handles = None
    clients = []  # per-thread SDK clients; closed in the teardown
    try:
        handles = await serve_deployment(
            deployer, spec.name, host="127.0.0.1",
            http_port=http_port, grpc_port=grpc_port,
        )
        setup_s = time.perf_counter() - t0

        dtype = extras.get("dtype", "float32")
        payload_rng = np.random.default_rng(0)
        if dtype == "uint8":
            payload = payload_rng.integers(0, 256, size=shape).astype(np.uint8)
        else:
            payload = payload_rng.normal(size=shape).astype(np.float32)
        feedback_every = 10 if extras.get("feedback") else 0

        import threading

        tl = threading.local()

        def make_worker():
            """One client + rng + counter per worker thread (sessions,
            channels, and numpy Generators are not thread-safe)."""
            client = SeldonTpuClient(
                host="127.0.0.1", http_port=http_port, grpc_port=grpc_port,
                transport=transport,
            )
            clients.append(client)
            rng = np.random.default_rng(threading.get_ident() & 0xFFFFFFFF)
            state = {"n": 0}

            def one() -> bool:
                state["n"] += 1
                out = client.predict(payload)
                if not out.success:
                    return False
                if feedback_every and state["n"] % feedback_every == 0:
                    # the bandit loop: reward the route that served us
                    fb = client.feedback(reward=float(rng.random() < 0.7),
                                         request=payload, response=out.response)
                    return fb.success
                return True

            return one

        def request_fn() -> bool:
            fn = getattr(tl, "fn", None)
            if fn is None:
                tl.fn = fn = make_worker()
            return fn()

        result = await asyncio.to_thread(
            run_load, request_fn, seconds, concurrency, 0.5
        )
    finally:
        # teardown must run even when the load phase dies, or the leaked
        # deployment skews every following config's numbers
        for client in clients:
            try:
                client.close()
            except Exception:  # noqa: BLE001 — teardown must finish
                pass
        await deployer.delete(spec.name)
        if handles is not None:
            runner, grpc_srv = handles
            await grpc_srv.stop(grace=None)
            await runner.cleanup()
    out = {"config": name, "transport": transport, "setup_s": round(setup_s, 1)}
    out.update(result.summary())
    return out


async def main_async(args) -> int:
    results = []
    failed = 0
    for name in args.configs:
        spec_path, shape, transport, extras = CONFIGS[name]
        try:
            out = await _bench_one(
                name, spec_path, shape, transport, extras,
                seconds=args.seconds, concurrency=args.concurrency,
            )
        except Exception as e:  # noqa: BLE001 — one config must not sink the rest
            out = {"config": name, "error": f"{type(e).__name__}: {e}"[:300]}
            failed += 1
        print(json.dumps(out), flush=True)
        results.append(out)
    summary = {
        "summary": True,
        "configs_run": len(results),
        "configs_failed": failed,
        "total_qps": round(sum(r.get("qps") or 0 for r in results), 1),
    }
    print(json.dumps(summary), flush=True)
    return 1 if failed else 0


def main(argv: Optional[list] = None) -> int:
    parser = argparse.ArgumentParser(description="benchmark the five reference configs")
    parser.add_argument("--seconds", type=float, default=10.0)
    parser.add_argument("--concurrency", type=int, default=8)
    parser.add_argument("--configs", default="",
                        help="comma-separated subset (default: all five)")
    parser.add_argument("--quick", action="store_true",
                        help="CPU smoke: short load, skip resnet50")
    parser.add_argument("--platform", default="",
                        help="force jax platform (cpu for local smoke)")
    args = parser.parse_args(argv)

    if args.platform:
        import jax

        jax.config.update("jax_platforms", args.platform)
    if args.quick:
        args.seconds = min(args.seconds, 3.0)
        default = [c for c in CONFIGS if c != "resnet50_grpc"]
    else:
        default = list(CONFIGS)
    args.configs = [c.strip() for c in args.configs.split(",") if c.strip()] or default
    unknown = [c for c in args.configs if c not in CONFIGS]
    if unknown:
        parser.error(f"unknown configs {unknown}; choose from {sorted(CONFIGS)}")
    return asyncio.run(main_async(args))


if __name__ == "__main__":
    sys.exit(main())
