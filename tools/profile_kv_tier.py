"""Profile the hierarchical KV tier (r22): demote/promote bandwidth
per level plus a churn demo of the tier absorbing HBM reclaim.

Two arms:

1. **Bandwidth table** (default): splits the tier data path into its
   stages and times each one over ``--pages`` real engine pages —
   device→host gather, SRT1 container pack, host-level put/pop,
   container unpack, disk-level spill/read (when ``--spill-dir`` is
   given), and the donated-scatter import back into the pool.  Each
   row reports pages/s and GiB/s so the demote and promote costs can
   be compared level by level (the promote path is pop + unpack +
   scatter; the demote path is gather + pack + put).
2. **``--churn``**: thrashes two session sets through an HBM pool
   sized for ONE session, tier on vs tier off, same traffic.  Tier
   off, every revisit re-pays full prefill; tier on, the evicted
   chains demote to host RAM and promote back at transfer cost.  The
   table shows per-round demotions/promotions and the end-to-end
   revisit speedup, with greedy outputs asserted bit-exact between
   the arms (f32 default — same single-regime caveat as
   tools/profile_prefix_cache.py).

Run:  python tools/profile_kv_tier.py [--pages 16] [--spill-dir /tmp/kvspill]
      python tools/profile_kv_tier.py --churn [--rounds 4] [--dtype f32]
      SELDON_TPU_KV_DTYPE=int8 python tools/profile_kv_tier.py   # int8+scales
"""

import argparse
import os
import shutil
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _row(name, pages, nbytes, dt):
    gib = nbytes / (1 << 30)
    return (f"{name:<26} {pages:>6} {pages / dt:>10.1f} "
            f"{gib / dt:>9.3f} {dt * 1e3 / max(1, pages):>9.3f}")


def bandwidth(args, eng, np, jnp):
    """Stage-by-stage timing over real resident pages."""
    from seldon_core_tpu.codec.bufview import pack_kv_handoff
    from seldon_core_tpu.models.kvtier import HostKvTier

    # collect the page chain the warm-up request registered
    with eng._lock:
        entries = [
            (e.key, e.parent, e.tokens, page)
            for page, e in sorted(eng._page_entry.items())
        ]
    entries = entries[: args.pages]
    if not entries:
        raise SystemExit("warm-up request registered no prefix pages")
    pages = np.asarray([e[3] for e in entries], np.int32)
    P = len(pages)

    # -- demote side: device->host gather, then per-page container pack
    t0 = time.perf_counter()
    idx = jnp.asarray(pages)
    k = np.asarray(eng.pages_k[:, idx])
    v = np.asarray(eng.pages_v[:, idx])
    ks = vs = None
    if eng._kv_int8:
        ks = np.asarray(eng.scales_k[:, idx])
        vs = np.asarray(eng.scales_v[:, idx])
    t_gather = time.perf_counter() - t0
    layout = "flat" if eng._pool_flat else "split"

    blobs = []
    t0 = time.perf_counter()
    for i, (key, parent, toks, _pg) in enumerate(entries):
        payload = {
            "prompt": np.asarray(toks, np.int32),
            "last_logits": np.zeros((1,), np.float32),
            "k": k[:, i:i + 1], "v": v[:, i:i + 1],
            "page_size": eng.page_size, "layout": layout,
        }
        if ks is not None:
            payload["k_scales"] = ks[:, i:i + 1]
            payload["v_scales"] = vs[:, i:i + 1]
        blobs.append(pack_kv_handoff(payload))
    t_pack = time.perf_counter() - t0
    nbytes = sum(len(b) for b in blobs)

    # -- host level: put then pop (pop includes the CRC-verified unpack)
    tier = HostKvTier(budget_bytes=nbytes * 4)
    t0 = time.perf_counter()
    for (key, parent, toks, _pg), blob in zip(entries, blobs):
        tier.put(key, parent, toks, blob)
    t_put = time.perf_counter() - t0
    t0 = time.perf_counter()
    payloads = [
        tier.pop(key, parent, toks)[0]
        for key, parent, toks, _pg in entries
    ]
    t_pop = time.perf_counter() - t0

    from seldon_core_tpu.codec.bufview import unpack_kv_handoff
    t0 = time.perf_counter()
    for b in blobs:
        unpack_kv_handoff(b)
    t_unpack = time.perf_counter() - t0

    # -- disk level: zero host budget forces every put straight to disk
    t_spill = t_read = None
    if args.spill_dir:
        spill = os.path.join(args.spill_dir, "profile")
        shutil.rmtree(spill, ignore_errors=True)
        dtier = HostKvTier(
            budget_bytes=0, spill_dir=spill,
            spill_budget_bytes=nbytes * 4,
        )
        t0 = time.perf_counter()
        for (key, parent, toks, _pg), blob in zip(entries, blobs):
            dtier.put(key, parent, toks, blob)
        t_spill = time.perf_counter() - t0
        t0 = time.perf_counter()
        for key, parent, toks, _pg in entries:
            assert dtier.pop(key, parent, toks)[2] == "disk"
        t_read = time.perf_counter() - t0
        shutil.rmtree(spill, ignore_errors=True)

    # -- promote side: the donated-scatter import back into the pool,
    # exactly the program _tier_promote_ready runs (back into the SAME
    # pages the chain occupies, so pool content is unchanged)
    kc = np.concatenate([np.asarray(p["k"]) for p in payloads], axis=1)
    vc = np.concatenate([np.asarray(p["v"]) for p in payloads], axis=1)
    fn = eng._import_kv_jit.get(P)
    if fn is None:
        fn = eng._import_kv_jit[P] = eng._build_import_kv(P)

    def scatter():
        kd = jnp.asarray(kc, eng._pool_dtype)
        vd = jnp.asarray(vc, eng._pool_dtype)
        if eng._kv_int8:
            kd = (kd, jnp.asarray(np.concatenate(
                [np.asarray(p["k_scales"]) for p in payloads], axis=1)))
            vd = (vd, jnp.asarray(np.concatenate(
                [np.asarray(p["v_scales"]) for p in payloads], axis=1)))
        pk, pv = fn(eng.params, *eng._kv_args(), kd, vd, jnp.asarray(pages))
        eng._store_kv(pk, pv)

    scatter()  # compile outside the timed region
    t0 = time.perf_counter()
    scatter()
    t_scatter = time.perf_counter() - t0

    hdr = (f"{'stage':<26} {'pages':>6} {'pages/s':>10} "
           f"{'GiB/s':>9} {'ms/page':>9}")
    print(f"\nKV tier bandwidth — {P} pages x {eng.page_size} tokens, "
          f"{nbytes / (1 << 20):.1f} MiB of containers, "
          f"pool={'int8+scales' if eng._kv_int8 else args.dtype}")
    print(hdr)
    print("-" * len(hdr))
    print(_row("demote: gather (d2h)", P, nbytes, t_gather))
    print(_row("demote: container pack", P, nbytes, t_pack))
    print(_row("demote: host put", P, nbytes, t_put))
    if t_spill is not None:
        print(_row("demote: disk spill", P, nbytes, t_spill))
    print(_row("promote: host pop+unpack", P, nbytes, t_pop))
    if t_read is not None:
        print(_row("promote: disk read+unpack", P, nbytes, t_read))
    print(_row("promote: unpack alone", P, nbytes, t_unpack))
    print(_row("promote: scatter (h2d)", P, nbytes, t_scatter))


def churn(args, make_engine, np):
    """Two session sets through a one-session pool, tier on vs off."""
    rng = np.random.default_rng(0)
    prompts = [
        rng.integers(0, args.vocab, size=(args.prompt,)).astype(np.int32)
        for _ in range(2)
    ]

    def run(offload: bool):
        per_req = -(-(args.prompt + args.new) // args.page_size)
        eng = make_engine(offload=offload, num_pages=per_req + 2)
        outs, walls = [], []
        for rnd in range(args.rounds):
            for p in prompts:  # A then B: each admission evicts the other
                t0 = time.perf_counter()
                s = eng.submit(p, max_new_tokens=args.new)
                eng.run()
                walls.append(time.perf_counter() - t0)
                outs.append(np.asarray(s.result))
        stats = eng.engine_stats()
        eng.close()
        return outs, walls, stats

    on_outs, on_walls, on = run(offload=True)
    off_outs, off_walls, _ = run(offload=False)
    for a, b in zip(on_outs, off_outs):
        assert np.array_equal(a, b), \
            "greedy outputs must be bit-exact tier-on vs tier-off"

    # first visit of each session is a cold miss in both arms; every
    # later visit is the returning-session shape the tier exists for
    revisit_on = sum(on_walls[2:])
    revisit_off = sum(off_walls[2:])
    hits = on["kv_tier_host_hits"] + on["kv_tier_disk_hits"]
    total = hits + on["kv_tier_misses"]
    print(f"\nchurn — 2 sessions x {args.rounds} rounds through a "
          f"one-session pool ({args.prompt}-token prompts)")
    print(f"  tier on : revisit wall {revisit_on:.2f}s   "
          f"demotions={on['kv_tier_demotions']} "
          f"promotions={on['kv_tier_promotions']} "
          f"host_hits={on['kv_tier_host_hits']} "
          f"hit_rate={hits / max(1, total):.2f} "
          f"bytes_demoted={on['kv_tier_bytes_demoted']}")
    print(f"  tier off: revisit wall {revisit_off:.2f}s (full re-prefill "
          f"every visit)")
    print(f"  promote speedup: {revisit_off / max(1e-9, revisit_on):.2f}x — "
          f"outputs bit-exact both arms")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--pages", type=int, default=16,
                    help="pages in the bandwidth sample")
    ap.add_argument("--prompt", type=int, default=512,
                    help="prompt tokens per session")
    ap.add_argument("--new", type=int, default=16)
    ap.add_argument("--rounds", type=int, default=4,
                    help="--churn revisit rounds per session")
    ap.add_argument("--d-model", type=int, default=256)
    ap.add_argument("--layers", type=int, default=4)
    ap.add_argument("--heads", type=int, default=8)
    ap.add_argument("--vocab", type=int, default=2048)
    ap.add_argument("--page-size", type=int, default=64)
    ap.add_argument("--max-len", type=int, default=2048)
    ap.add_argument("--dtype", choices=("f32", "bf16"), default="f32")
    ap.add_argument("--spill-dir", default="",
                    help="also time the disk level under this directory")
    ap.add_argument("--churn", action="store_true",
                    help="run the two-session thrash demo instead")
    args = ap.parse_args()

    if args.churn and args.dtype != "f32":
        ap.error("--churn asserts bit-exactness; use --dtype f32")

    spill_tmp = None
    if args.spill_dir == "":
        args.spill_dir = spill_tmp = tempfile.mkdtemp(prefix="kvtier_prof_")

    os.environ["SELDON_TPU_KV_HOST_BUDGET_GIB"] = "2"

    import numpy as np
    import jax
    import jax.numpy as jnp

    from seldon_core_tpu.models.paged import PagedEngine
    from seldon_core_tpu.models.transformer import TransformerLM

    dtype = jnp.float32 if args.dtype == "f32" else jnp.bfloat16
    cfg = dict(
        vocab_size=args.vocab, d_model=args.d_model,
        num_layers=args.layers, num_heads=args.heads, max_len=args.max_len,
    )
    lm = TransformerLM(dtype=dtype, **cfg)
    params = lm.init(jax.random.key(0), jnp.zeros((1, 8), jnp.int32))["params"]

    def make_engine(offload: bool, num_pages=None):
        os.environ["SELDON_TPU_KV_OFFLOAD"] = "1" if offload else "0"
        return PagedEngine(
            params, dtype=dtype, page_size=args.page_size,
            max_slots=2, steps_per_call=8, num_pages=num_pages,
            prefix_cache=True, **cfg,
        )

    try:
        if args.churn:
            churn(args, make_engine, np)
        else:
            need = args.pages * args.page_size
            eng = make_engine(offload=True)
            rng = np.random.default_rng(0)
            prompt = rng.integers(0, args.vocab, size=(need,)).astype(np.int32)
            s = eng.submit(prompt, max_new_tokens=args.new)
            eng.run()
            assert s.result is not None
            bandwidth(args, eng, np, jnp)
            eng.close()
    finally:
        os.environ.pop("SELDON_TPU_KV_OFFLOAD", None)
        if spill_tmp:
            shutil.rmtree(spill_tmp, ignore_errors=True)


if __name__ == "__main__":
    main()
