"""Decompose the tensor-parallel paged decode chunk: which collectives
GSPMD inserted, where the per-layer budget goes, and what the TP lane
actually buys over single-chip.

The TP engine (`PagedEngine(tp=N)`) pins megatron param specs and a
heads-sharded KV pool on every program signature (`_tp_jit`), then lets
XLA insert the collectives.  This harness makes that visible:

1. **HLO collective audit** — lowers the TP chunk/prefill programs with
   the engine's own annotation helper and counts the collective ops XLA
   actually inserted (`all-reduce`, `all-gather`, `reduce-scatter`,
   `collective-permute`), printed per program and divided per layer.
   The expected shape for a megatron block is ONE all-reduce per
   attention out-projection + ONE per MLP down-projection = 2/layer
   in the forward; a higher count means the partitioner fell back to
   resharding an activation (a spec bug worth chasing).
2. **cost split** — XLA's compiled cost analysis (flops, bytes
   accessed) for the TP program vs the TP=1 program: per-chip flops
   must shrink ~1/N while collective bytes appear on the TP side.
3. **measured contrast** (``--measure``) — the bench's min-of-3
   serving protocol TP=N vs TP=1 on the same prompts, reporting
   per-chip efficiency (`paged_tp_eff_pct`'s formula: per-chip tok/s
   vs the TP=1 rate).

Run:  python tools/profile_paged_tp.py [--tp 2] [--slots 8] [--steps 8]
      [--measure] [--d-model 512] [--layers 8] [--mesh 2x2]

``--mesh DxM`` audits the 2-D (data x model) serving mesh instead: the
collective count is SPLIT per mesh axis by classifying each op's
``replica_groups`` device lists (the mesh is data-major, so model
groups are contiguous id runs over fast ICI and data groups are
strided).  The expected split is megatron ``all-reduce`` on the model
axis plus the page-gather ``all-reduce``/``all-gather`` traffic on the
data axis — a model-axis all-gather means the partitioner fell back to
resharding an activation.

Single-chip hosts degrade honestly: without ``--tp`` devices the tool
prints the TP=1 audit (zero collectives — the byte-identical-program
claim, checkable) instead of crashing.
"""

import argparse
import os
import re
import sys
import time
from collections import Counter

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


COLLECTIVES = (
    "all-reduce", "all-gather", "reduce-scatter", "collective-permute",
    "all-to-all",
)

# explicit replica groups: replica_groups={{0,1},{2,3}}
_RG_EXPLICIT = re.compile(r"replica_groups=\{(\{[^=]*?\})\}")
# collective-permute spells its groups as source_target_pairs instead
_STP = re.compile(r"source_target_pairs=\{(\{[^=]*?\})\}")
# iota (v2) groups: replica_groups=[4,2]<=[8] or [2,4]<=[4,2]T(1,0)
_RG_IOTA = re.compile(r"replica_groups=\[(\d+),(\d+)\]<=\[[\d,]+\](T\([\d,]+\))?")


def _axis_of_groups(groups) -> str:
    """Classify replica groups against the data-major 2-D mesh: the
    model axis is MINOR (contiguous device-id runs, adjacent/fast ICI);
    the data axis is MAJOR (constant stride = model-axis size)."""
    strides = set()
    for g in groups:
        if len(g) < 2:
            continue
        diffs = {b - a for a, b in zip(g, g[1:])}
        if len(diffs) != 1:
            return "mixed"
        strides |= diffs
    if not strides or strides == {1}:
        return "model"
    if len(strides) == 1:
        return "data"
    return "mixed"


def _classify_axis(line: str) -> str:
    """Mesh axis a collective instruction runs over, from its
    replica_groups attribute ('?' when the spelling is unrecognised)."""
    m = _RG_EXPLICIT.search(line)
    if m:
        groups = [
            [int(x) for x in body.split(",") if x.strip()]
            for body in re.findall(r"\{([^{}]*)\}", m.group(1))
        ]
        return _axis_of_groups(groups)
    m = _RG_IOTA.search(line)
    if m:
        # identity iota = contiguous runs (minor/model axis); any
        # transpose permutes ids into strided groups (major/data axis)
        return "data" if m.group(3) else "model"
    m = _STP.search(line)
    if m:
        # a permute ring over one axis hops a constant |stride| (mod
        # wrap): minor-axis hops are +-1, major-axis hops are +-M
        pairs = [
            [int(x) for x in body.split(",") if x.strip()]
            for body in re.findall(r"\{([^{}]*)\}", m.group(1))
        ]
        hops = {abs(p[1] - p[0]) for p in pairs if len(p) == 2}
        hops.discard(0)
        if hops <= {1} or not hops:
            return "model"
        # wrap-around edges show as a larger jump; one non-unit hop
        # size (+ its wrap) is still a single-axis ring
        if len(hops - {max(hops)}) <= 1:
            return "data" if 1 not in hops else "mixed"
        return "mixed"
    return "?"


def collective_counts(hlo_text: str, by_axis: bool = False) -> Counter:
    """Count collective instructions in HLO text (start/done pairs for
    async collectives count once via the -start spelling).  With
    ``by_axis`` the keys are ``(op, axis)`` where axis is the mesh axis
    the op's replica_groups span ('model' minor / 'data' major)."""
    counts: Counter = Counter()
    for line in hlo_text.splitlines():
        s = line.strip()
        # instruction lines look like "%x = ... all-reduce(...)" or
        # "... all-reduce-start(..."; match the op name at its call site
        for op in COLLECTIVES:
            if f" {op}(" in s or f" {op}-start(" in s:
                counts[(op, _classify_axis(s)) if by_axis else op] += 1
    return counts


def audit_program(name: str, lowered, num_layers: int, by_axis: bool = False):
    compiled = lowered.compile()
    try:
        hlo = compiled.as_text()
    except Exception:  # noqa: BLE001 — older jax spelling
        hlo = "\n".join(
            m.to_string() for m in compiled.runtime_executable().hlo_modules()
        )
    counts = collective_counts(hlo)
    axis_counts = collective_counts(hlo, by_axis=True) if by_axis else None
    total = sum(counts.values())
    cost = {}
    try:
        analyses = compiled.cost_analysis()
        cost = analyses[0] if isinstance(analyses, (list, tuple)) else analyses
    except Exception:  # noqa: BLE001 — cost analysis is backend-optional
        pass
    flops = float(cost.get("flops", 0.0)) if cost else 0.0
    bytes_acc = float(cost.get("bytes accessed", 0.0)) if cost else 0.0
    print(f"\n{name}:")
    if total == 0:
        print("  collectives: none (single-chip program)")
    else:
        per_layer = ", ".join(
            f"{op}={n} ({n / num_layers:.1f}/layer)"
            for op, n in sorted(counts.items())
        )
        print(f"  collectives: {total} total — {per_layer}")
        if axis_counts:
            for axis in ("model", "data", "mixed", "?"):
                ops = {op: n for (op, a), n in axis_counts.items() if a == axis}
                if ops:
                    detail = ", ".join(
                        f"{op}={n}" for op, n in sorted(ops.items()))
                    print(f"    {axis} axis: {detail}")
    if flops:
        print(f"  per-chip cost: {flops / 1e9:.3f} GFLOP, "
              f"{bytes_acc / 1e6:.1f} MB accessed")
    return counts, flops, axis_counts


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--tp", type=int, default=0,
                    help="TP degree (0 = largest of 4/2 the host fits)")
    ap.add_argument("--slots", type=int, default=8)
    ap.add_argument("--steps", type=int, default=8)
    ap.add_argument("--d-model", type=int, default=512)
    ap.add_argument("--layers", type=int, default=8)
    ap.add_argument("--heads", type=int, default=8)
    ap.add_argument("--vocab", type=int, default=32000)
    ap.add_argument("--page-size", type=int, default=64)
    ap.add_argument("--max-len", type=int, default=1024)
    ap.add_argument("--new", type=int, default=64)
    ap.add_argument("--mesh", type=str, default="",
                    help="audit a 2-D DxM (data x model) serving mesh, "
                         "e.g. --mesh 2x2; collectives are split per axis")
    ap.add_argument("--measure", action="store_true",
                    help="also time serving TP=N vs TP=1 (min-of-3)")
    args = ap.parse_args()

    import numpy as np

    import jax
    import jax.numpy as jnp

    from seldon_core_tpu.models.paged import PagedEngine
    from seldon_core_tpu.models.transformer import TransformerLM

    n_dev = len(jax.devices())
    tp = args.tp or max((d for d in (4, 2) if d <= n_dev), default=1)
    if tp > n_dev:
        raise SystemExit(
            f"--tp {tp} needs {tp} devices, host exposes {n_dev}"
        )
    mesh_dp = mesh_tp = 0
    if args.mesh:
        try:
            mesh_dp, mesh_tp = (int(x) for x in args.mesh.lower().split("x"))
        except ValueError:
            raise SystemExit(f"--mesh wants DxM (e.g. 2x2), got {args.mesh!r}")
        if mesh_dp * mesh_tp > n_dev:
            raise SystemExit(
                f"--mesh {args.mesh} needs {mesh_dp * mesh_tp} devices, "
                f"host exposes {n_dev}"
            )

    cfg = dict(
        vocab_size=args.vocab, d_model=args.d_model,
        num_layers=args.layers, num_heads=args.heads, max_len=args.max_len,
    )
    lm = TransformerLM(dtype=jnp.bfloat16, **cfg)
    params = lm.init(jax.random.key(0), jnp.zeros((1, 8), jnp.int32))["params"]

    def build(tp_n, dp_n=1):
        # tp=1/dp=1 passed EXPLICITLY: they force single-chip even when
        # SELDON_TPU_TP/SELDON_TPU_DP are exported in the shell — the
        # tp=1 reference audit must never silently come up parallel
        return PagedEngine(
            params, dtype=jnp.bfloat16, page_size=args.page_size,
            max_slots=args.slots, steps_per_call=args.steps,
            tp=tp_n, dp=dp_n, **cfg,
        )

    pages = -(-args.max_len // args.page_size)
    horizon = 1 << max(0, (pages - 1).bit_length())  # pow2 pages/slot

    def lowered_chunk(eng):
        """The engine's REAL chunk program, lowered through its own
        shared audit surface (same body + annotation as serving)."""
        return eng.lower_chunk(args.steps, ((args.slots, horizon),))

    print(f"host devices={n_dev}  auditing tp={tp} vs tp=1  "
          f"(d{args.d_model}/L{args.layers}, {args.slots} slots, "
          f"{args.steps}-step chunk)")

    eng1 = build(1)
    c1, flops1, _ = audit_program(
        f"chunk tp=1 ({args.steps} steps)", lowered_chunk(eng1), args.layers)
    eng1.close()

    if tp > 1:
        engN = build(tp)
        assert engN.tp_degree == tp, (
            f"engine degraded to tp={engN.tp_degree} — host mesh too small"
        )
        cN, flopsN, _ = audit_program(
            f"chunk tp={tp} ({args.steps} steps)", lowered_chunk(engN),
            args.layers)
        engN.close()
        assert sum(c1.values()) == 0, "tp=1 program must carry no collectives"
        if flops1 and flopsN:
            print(f"\nper-chip flops ratio tp{tp}/tp1: {flopsN / flops1:.3f} "
                  f"(ideal {1 / tp:.3f})")

    if mesh_dp:
        eng2d = build(mesh_tp, mesh_dp)
        assert eng2d.tp_degree == mesh_tp and eng2d.dp_degree == mesh_dp, (
            f"engine degraded to (dp={eng2d.dp_degree}, tp={eng2d.tp_degree})"
            f" — host mesh too small for --mesh {args.mesh}"
        )
        _, flops2d, axis2d = audit_program(
            f"chunk mesh={mesh_dp}x{mesh_tp} data x model "
            f"({args.steps} steps)",
            lowered_chunk(eng2d), args.layers, by_axis=True)
        eng2d.close()
        if axis2d:
            model_ag = sum(
                n for (op, a), n in axis2d.items()
                if a == "model" and op == "all-gather"
            )
            if model_ag:
                print(f"  NOTE: {model_ag} model-axis all-gather(s) — the "
                      f"partitioner reshards an activation (spec bug worth "
                      f"chasing); megatron wants all-reduce only there")
        if flops1 and flops2d:
            print(f"\nper-chip flops ratio mesh/tp1: {flops2d / flops1:.3f} "
                  f"(ideal {1 / mesh_tp:.3f} — the data axis shards KV "
                  f"pages + lanes, not weight flops)")

    if args.measure:
        rng = np.random.default_rng(0)
        plen = max(8, min(64, (args.max_len - args.new) // 2))
        prompts = [
            rng.integers(0, args.vocab, size=(plen + (i % 5) * 2,)).astype(
                np.int32)
            for i in range(args.slots)
        ]

        def serve(tp_n):
            eng = build(tp_n)
            try:
                def go():
                    streams = [
                        eng.submit(p, max_new_tokens=args.new)
                        for p in prompts
                    ]
                    eng.run()
                    return sum(int(s.result.shape[0]) for s in streams)

                go()  # compiles
                best = 0.0
                for _ in range(3):
                    t0 = time.perf_counter()
                    n = go()
                    best = max(best, n / (time.perf_counter() - t0))
                return best
            finally:
                eng.close()

        r1 = serve(1)
        print(f"\nserving tp=1: {r1:,.0f} tok/s")
        if tp > 1:
            rN = serve(tp)
            eff = 100.0 * (rN / tp) / max(r1, 1e-9)
            print(f"serving tp={tp}: {rN:,.0f} tok/s "
                  f"({rN / tp:,.0f} tok/s/chip, {eff:.1f}% per-chip "
                  f"efficiency vs tp=1)")


if __name__ == "__main__":
    main()
