"""Decompose the tensor-parallel paged decode chunk: which collectives
GSPMD inserted, where the per-layer budget goes, and what the TP lane
actually buys over single-chip.

The TP engine (`PagedEngine(tp=N)`) pins megatron param specs and a
heads-sharded KV pool on every program signature (`_tp_jit`), then lets
XLA insert the collectives.  This harness makes that visible:

1. **HLO collective audit** — lowers the TP chunk/prefill programs with
   the engine's own annotation helper and counts the collective ops XLA
   actually inserted (`all-reduce`, `all-gather`, `reduce-scatter`,
   `collective-permute`), printed per program and divided per layer.
   The expected shape for a megatron block is ONE all-reduce per
   attention out-projection + ONE per MLP down-projection = 2/layer
   in the forward; a higher count means the partitioner fell back to
   resharding an activation (a spec bug worth chasing).
2. **cost split** — XLA's compiled cost analysis (flops, bytes
   accessed) for the TP program vs the TP=1 program: per-chip flops
   must shrink ~1/N while collective bytes appear on the TP side.
3. **measured contrast** (``--measure``) — the bench's min-of-3
   serving protocol TP=N vs TP=1 on the same prompts, reporting
   per-chip efficiency (`paged_tp_eff_pct`'s formula: per-chip tok/s
   vs the TP=1 rate).

Run:  python tools/profile_paged_tp.py [--tp 2] [--slots 8] [--steps 8]
      [--measure] [--d-model 512] [--layers 8]

Single-chip hosts degrade honestly: without ``--tp`` devices the tool
prints the TP=1 audit (zero collectives — the byte-identical-program
claim, checkable) instead of crashing.
"""

import argparse
import os
import sys
import time
from collections import Counter

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


COLLECTIVES = (
    "all-reduce", "all-gather", "reduce-scatter", "collective-permute",
    "all-to-all",
)


def collective_counts(hlo_text: str) -> Counter:
    """Count collective instructions in HLO text (start/done pairs for
    async collectives count once via the -start spelling)."""
    counts: Counter = Counter()
    for line in hlo_text.splitlines():
        s = line.strip()
        # instruction lines look like "%x = ... all-reduce(...)" or
        # "... all-reduce-start(..."; match the op name at its call site
        for op in COLLECTIVES:
            if f" {op}(" in s or f" {op}-start(" in s:
                counts[op] += 1
    return counts


def audit_program(name: str, lowered, num_layers: int):
    compiled = lowered.compile()
    try:
        hlo = compiled.as_text()
    except Exception:  # noqa: BLE001 — older jax spelling
        hlo = "\n".join(
            m.to_string() for m in compiled.runtime_executable().hlo_modules()
        )
    counts = collective_counts(hlo)
    total = sum(counts.values())
    cost = {}
    try:
        analyses = compiled.cost_analysis()
        cost = analyses[0] if isinstance(analyses, (list, tuple)) else analyses
    except Exception:  # noqa: BLE001 — cost analysis is backend-optional
        pass
    flops = float(cost.get("flops", 0.0)) if cost else 0.0
    bytes_acc = float(cost.get("bytes accessed", 0.0)) if cost else 0.0
    print(f"\n{name}:")
    if total == 0:
        print("  collectives: none (single-chip program)")
    else:
        per_layer = ", ".join(
            f"{op}={n} ({n / num_layers:.1f}/layer)"
            for op, n in sorted(counts.items())
        )
        print(f"  collectives: {total} total — {per_layer}")
    if flops:
        print(f"  per-chip cost: {flops / 1e9:.3f} GFLOP, "
              f"{bytes_acc / 1e6:.1f} MB accessed")
    return counts, flops


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--tp", type=int, default=0,
                    help="TP degree (0 = largest of 4/2 the host fits)")
    ap.add_argument("--slots", type=int, default=8)
    ap.add_argument("--steps", type=int, default=8)
    ap.add_argument("--d-model", type=int, default=512)
    ap.add_argument("--layers", type=int, default=8)
    ap.add_argument("--heads", type=int, default=8)
    ap.add_argument("--vocab", type=int, default=32000)
    ap.add_argument("--page-size", type=int, default=64)
    ap.add_argument("--max-len", type=int, default=1024)
    ap.add_argument("--new", type=int, default=64)
    ap.add_argument("--measure", action="store_true",
                    help="also time serving TP=N vs TP=1 (min-of-3)")
    args = ap.parse_args()

    import numpy as np

    import jax
    import jax.numpy as jnp

    from seldon_core_tpu.models.paged import PagedEngine
    from seldon_core_tpu.models.transformer import TransformerLM

    n_dev = len(jax.devices())
    tp = args.tp or max((d for d in (4, 2) if d <= n_dev), default=1)
    if tp > n_dev:
        raise SystemExit(
            f"--tp {tp} needs {tp} devices, host exposes {n_dev}"
        )

    cfg = dict(
        vocab_size=args.vocab, d_model=args.d_model,
        num_layers=args.layers, num_heads=args.heads, max_len=args.max_len,
    )
    lm = TransformerLM(dtype=jnp.bfloat16, **cfg)
    params = lm.init(jax.random.key(0), jnp.zeros((1, 8), jnp.int32))["params"]

    def build(tp_n):
        # tp=1 passed EXPLICITLY: it forces single-chip even when
        # SELDON_TPU_TP is exported in the shell — the tp=1 reference
        # audit must never silently come up tensor-parallel
        return PagedEngine(
            params, dtype=jnp.bfloat16, page_size=args.page_size,
            max_slots=args.slots, steps_per_call=args.steps,
            tp=tp_n, **cfg,
        )

    pages = -(-args.max_len // args.page_size)
    horizon = 1 << max(0, (pages - 1).bit_length())  # pow2 pages/slot

    def lowered_chunk(eng):
        """The engine's REAL chunk program, lowered through its own
        shared audit surface (same body + annotation as serving)."""
        return eng.lower_chunk(args.steps, ((args.slots, horizon),))

    print(f"host devices={n_dev}  auditing tp={tp} vs tp=1  "
          f"(d{args.d_model}/L{args.layers}, {args.slots} slots, "
          f"{args.steps}-step chunk)")

    eng1 = build(1)
    c1, flops1 = audit_program(
        f"chunk tp=1 ({args.steps} steps)", lowered_chunk(eng1), args.layers)
    eng1.close()

    if tp > 1:
        engN = build(tp)
        assert engN.tp_degree == tp, (
            f"engine degraded to tp={engN.tp_degree} — host mesh too small"
        )
        cN, flopsN = audit_program(
            f"chunk tp={tp} ({args.steps} steps)", lowered_chunk(engN),
            args.layers)
        engN.close()
        assert sum(c1.values()) == 0, "tp=1 program must carry no collectives"
        if flops1 and flopsN:
            print(f"\nper-chip flops ratio tp{tp}/tp1: {flopsN / flops1:.3f} "
                  f"(ideal {1 / tp:.3f})")

    if args.measure:
        rng = np.random.default_rng(0)
        plen = max(8, min(64, (args.max_len - args.new) // 2))
        prompts = [
            rng.integers(0, args.vocab, size=(plen + (i % 5) * 2,)).astype(
                np.int32)
            for i in range(args.slots)
        ]

        def serve(tp_n):
            eng = build(tp_n)
            try:
                def go():
                    streams = [
                        eng.submit(p, max_new_tokens=args.new)
                        for p in prompts
                    ]
                    eng.run()
                    return sum(int(s.result.shape[0]) for s in streams)

                go()  # compiles
                best = 0.0
                for _ in range(3):
                    t0 = time.perf_counter()
                    n = go()
                    best = max(best, n / (time.perf_counter() - t0))
                return best
            finally:
                eng.close()

        r1 = serve(1)
        print(f"\nserving tp=1: {r1:,.0f} tok/s")
        if tp > 1:
            rN = serve(tp)
            eff = 100.0 * (rN / tp) / max(r1, 1e-9)
            print(f"serving tp={tp}: {rN:,.0f} tok/s "
                  f"({rN / tp:,.0f} tok/s/chip, {eff:.1f}% per-chip "
                  f"efficiency vs tp=1)")


if __name__ == "__main__":
    main()
