"""Is the int8 MXU path actually taken?  HLO evidence + measured ratio.

The w8a8 lane's whole premise is the v5e's 394 TOPS int8 MXU path (2x
its 197 TF/s bf16 peak) — but XLA is free to silently upcast an
int8×int8 ``preferred_element_type=int32`` contraction, and a bf16
program timed under an int8 label would fabricate the win.  This tool
is the adjudicator the bench and docs cite:

1. **Lowering audit** (`ops/w8a8.int8_lowering_report`): compile a
   representative int8 matmul, an int8 conv at ResNet-50 shapes, and a
   w8a8 ResNet forward; classify every dot/conv in the optimised HLO
   by operand dtype — ``int8`` (s8 into the op: the MXU path),
   ``int-widened`` (integer but s32 — CPU's exact-math fallback), or
   ``float-upcast`` (the failure mode: quantised operands converted to
   float before the op).  Evidence lines are printed verbatim.

2. **Timing** (only meaningful on TPU): bf16-vs-int8 two-point chained
   ``fori_loop`` matmul/conv — same honest-barrier methodology as
   `tools/profile_conv.py` (value-fetch completion, seconds-scale
   loops so the ~100 ms dispatch penalty cannot produce negative
   slopes).  On the MXU the 4096² int8 matmul should approach 2x the
   bf16 rate; ≈1.0x with an ``int8`` audit verdict means the MXU ran
   int8 without a speed win (report it); ≈1.0x with ``float-upcast``
   means the lane is a no-op (report THAT — no silent wins).

Run:  python tools/profile_int8.py [--model resnet_tiny|resnet50]
"""

import argparse
import os
import sys
import time

# runnable as `python tools/profile_int8.py` from a checkout, like the
# sibling profilers run with the package importable
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _audit(name, fn, *args):
    from seldon_core_tpu.ops.w8a8 import int8_lowering_report

    rep = int8_lowering_report(fn, *args)
    print(f"[audit] {name}: verdict={rep['verdict']} "
          f"int8_majority={rep['int8_majority']} "
          f"(s8 ops={rep['int8_ops']}, int-widened={rep['int_widened_ops']}, "
          f"float={rep['float_ops']}, backend={rep['backend']})")
    for line in rep["evidence"][:4]:
        print(f"        {line}")
    return rep


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--model", default="resnet_tiny",
                        help="resnet family model for the end-to-end audit")
    parser.add_argument("--skip-timing", action="store_true")
    args = parser.parse_args()

    import jax
    import jax.numpy as jnp
    import numpy as np

    from seldon_core_tpu.ops.w8a8 import w8a8_conv, w8a8_matmul

    print(f"backend: {jax.default_backend()}  devices: {jax.devices()}")

    # ---- lowering audits -------------------------------------------------
    x = jnp.asarray(np.random.default_rng(0).normal(size=(256, 1024)), jnp.float32)
    w = jnp.asarray(np.random.default_rng(1).normal(size=(1024, 1024)), jnp.float32)
    _audit("w8a8 matmul 256x1024x1024", lambda a, b: w8a8_matmul(a, b), x, w)

    xc = jnp.asarray(
        np.random.default_rng(2).normal(size=(8, 14, 14, 256)), jnp.float32
    )
    wc = jnp.asarray(
        np.random.default_rng(3).normal(size=(3, 3, 256, 256)), jnp.float32
    )
    _audit("w8a8 conv 3x3 c=256 @14",
           lambda a, b: w8a8_conv(a, b, (1, 1), "SAME"), xc, wc)

    # end-to-end: the served w8a8 ResNet program (stem/head stay bf16 by
    # design, so float convs are EXPECTED — the verdict that matters is
    # that s8/int ops exist at all alongside them)
    from seldon_core_tpu.models.jaxserver import JaxServer

    server = JaxServer(
        model=args.model,
        num_classes=10 if args.model == "resnet_tiny" else 1000,
        input_shape=(32, 32, 3) if args.model == "resnet_tiny" else (224, 224, 3),
        dtype="bfloat16" if jax.default_backend() == "tpu" else "float32",
        max_batch_size=8, warmup=False, precision="w8a8",
    )
    server.load()
    img = jnp.zeros((8, *server.input_shape), jnp.uint8)
    rep = _audit(f"w8a8 {args.model} forward",
                 server._apply_fn, server.variables, img)
    server.unload()
    if rep["int8_ops"] == 0 and rep["int_widened_ops"] == 0:
        print("[audit] !! the w8a8 model lowered to float ops only — "
              "the int8 lane is a silent upcast on this backend")

    if args.skip_timing:
        return

    # ---- timing: bf16 vs int8, chained fori_loop, value-fetch barrier ----
    def probe_matmul_pair(n=4096, iters=64):
        key = jax.random.key(0)
        a16 = jax.random.normal(key, (n, n), jnp.bfloat16) * 0.01
        b16 = jax.random.normal(jax.random.key(1), (n, n), jnp.bfloat16) * 0.01
        a8 = jnp.clip(jnp.round(a16.astype(jnp.float32) * 100), -127, 127).astype(jnp.int8)
        b8 = jnp.clip(jnp.round(b16.astype(jnp.float32) * 100), -127, 127).astype(jnp.int8)

        def run_bf16(a, b, it):
            def body(i, x):
                return (x @ b) * (1.0 / n)

            return jax.lax.fori_loop(0, it, body, a)[0, 0].astype(jnp.float32)

        def run_int8(a, b, it):
            # chained int8: requantise the int32 accumulator back to
            # int8 each step so every iteration feeds the int8 op
            def body(i, x):
                acc = jax.lax.dot_general(
                    x, b, (((1,), (0,)), ((), ())),
                    preferred_element_type=jnp.int32,
                )
                return jnp.clip(acc // n, -127, 127).astype(jnp.int8)

            return jax.lax.fori_loop(0, it, body, a)[0, 0].astype(jnp.float32)

        results = {}
        for tag, fn, ops in (("bf16", run_bf16, (a16, b16)),
                             ("int8", run_int8, (a8, b8))):
            rj = jax.jit(fn)
            float(rj(*ops, 4))  # compile
            t0 = time.perf_counter(); float(rj(*ops, 4)); d1 = time.perf_counter() - t0
            t0 = time.perf_counter(); float(rj(*ops, 4 + iters)); d2 = time.perf_counter() - t0
            dt = max((d2 - d1) / iters, 1e-9)
            tops = 2 * n ** 3 / dt / 1e12
            results[tag] = dt
            print(f"[time] matmul {n}² {tag}: {dt*1e3:7.3f} ms  {tops:6.1f} T(FL)OP/s")
        print(f"[time] int8-vs-bf16 matmul ratio: "
              f"{results['bf16'] / results['int8']:.2f}x "
              f"(MXU int8 target ≈2x; ≈1x = no win; <1x = int8 slower)")

    probe_matmul_pair(4096 if jax.default_backend() == "tpu" else 512,
                      64 if jax.default_backend() == "tpu" else 8)


if __name__ == "__main__":
    main()
