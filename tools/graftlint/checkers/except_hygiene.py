"""Checker: exception hygiene on serving paths (GL6xx).

Invariant (PRs 3-8 convention): a broad ``except Exception`` in the
engine/transport/models hot paths either **re-raises**, **converts**
the exception into a reply (MicroserviceError / a status payload that
uses the caught value), or **justifies itself** with a comment on the
``except`` line — a silent ``pass``/log-only swallow is how contained
faults become invisible corruption.  Bare ``except:`` additionally
swallows KeyboardInterrupt/SystemExit and always needs a pragma.
"""

from __future__ import annotations

import ast
import re
from typing import Iterable, List

from tools.graftlint.core import LintContext, Source, Violation, attr_root

NAME = "except-hygiene"

# pure-logging callees: using the caught exception here is reporting,
# not conversion
_LOG_ROOTS = {"logger", "logging", "log", "print", "warnings"}

_NOQA_RE = re.compile(r"#\s*noqa[:,]?\s*[A-Z0-9, ]*")


class _Checker:
    name = NAME
    codes = ("GL601", "GL602", "GL603")
    doc = __doc__

    def run(self, ctx: LintContext) -> Iterable[Violation]:
        out: List[Violation] = []
        for src in ctx.sources:
            out.extend(self.check_source(src))
        return out

    # separated so fixture tests can run one file
    def check_source(self, src: Source) -> List[Violation]:
        out: List[Violation] = []
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            broad = node.type is None or (
                isinstance(node.type, ast.Name)
                and node.type.id in ("Exception", "BaseException")
            )
            if not broad:
                continue
            base_exc = (
                isinstance(node.type, ast.Name)
                and node.type.id == "BaseException"
            )
            if self._reraises(node):
                continue
            if not base_exc and node.type is not None:
                if self._converts(node) or self._justified(src, node.lineno):
                    continue
                out.append(Violation(
                    checker=self.name, code="GL601", path=src.path,
                    line=node.lineno, symbol=f"except@{node.lineno}",
                    message=(
                        "broad `except Exception` neither re-raises, converts "
                        "the exception into a reply, nor carries a "
                        "justification comment on the except line"
                    ),
                ))
            elif node.type is None:
                out.append(Violation(
                    checker=self.name, code="GL602", path=src.path,
                    line=node.lineno, symbol=f"except@{node.lineno}",
                    message=(
                        "bare `except:` swallows KeyboardInterrupt/SystemExit; "
                        "catch Exception (with justification) or re-raise"
                    ),
                ))
            else:
                out.append(Violation(
                    checker=self.name, code="GL603", path=src.path,
                    line=node.lineno, symbol=f"except@{node.lineno}",
                    message=(
                        "`except BaseException` without re-raise traps "
                        "interpreter shutdown signals"
                    ),
                ))
        return out

    @staticmethod
    def _reraises(handler: ast.ExceptHandler) -> bool:
        return any(isinstance(n, ast.Raise) for n in ast.walk(handler))

    @staticmethod
    def _converts(handler: ast.ExceptHandler) -> bool:
        """The caught name is USED somewhere that is not pure logging —
        built into a status reply, returned, attached to a record."""
        if handler.name is None:
            return False
        caught = handler.name

        class V(ast.NodeVisitor):
            def __init__(self):
                self.converts = False

            def visit_Call(self, call: ast.Call):
                root = attr_root(call.func)
                uses = any(
                    isinstance(n, ast.Name) and n.id == caught
                    for a in list(call.args) + [k.value for k in call.keywords]
                    for n in ast.walk(a)
                )
                if uses and root not in _LOG_ROOTS:
                    self.converts = True
                self.generic_visit(call)

            def visit_Return(self, ret: ast.Return):
                if ret.value is not None and any(
                    isinstance(n, ast.Name) and n.id == caught
                    for n in ast.walk(ret.value)
                ):
                    self.converts = True
                self.generic_visit(ret)

        v = V()
        for stmt in handler.body:
            v.visit(stmt)
        return v.converts

    @staticmethod
    def _justified(src: Source, lineno: int) -> bool:
        """A comment on the except line with real words beyond a bare
        ``noqa`` code counts as the explicit allow pragma."""
        if not 1 <= lineno <= len(src.lines):
            return False
        line = src.lines[lineno - 1]
        if "#" not in line:
            return False
        comment = line.split("#", 1)[1]
        comment = _NOQA_RE.sub("", "#" + comment)
        comment = comment.strip("#").strip(" -—:;")
        return len(re.sub(r"\W", "", comment)) >= 3


CHECKER = _Checker()
