"""Checker: engine lock discipline (GL3xx).

Invariant (paged-engine convention since PR 2): a ``_*_locked`` helper
encodes "caller holds the lock" in its NAME — it must only be invoked
from another ``_*_locked`` method or lexically inside a ``with
self.<lock>:`` block of the same class.  Conversely, mutable state that
``_*_locked`` methods write is lock-guarded by definition, so writes to
those attributes from unlocked contexts are flagged.

Rules:

* GL301 — ``self._x_locked(...)`` called from a method that is neither
  itself ``*_locked`` nor inside a ``with self.<lock>`` block.
* GL302 — write to a lock-guarded ``self.<attr>`` (one that some
  ``*_locked`` method of the class also writes) outside lock scope
  (``__init__``/``__new__`` construct before the object escapes and
  are exempt).
"""

from __future__ import annotations

import ast
from typing import Iterable, List, Optional, Set

from tools.graftlint.core import LintContext, Source, Violation

NAME = "lock-discipline"

# a `with self.<attr>:` item counts as taking the lock when the attr
# looks like one
_LOCK_HINTS = ("lock", "mutex", "_cv", "_mu", "cond")


def _is_lock_attr(attr: str) -> bool:
    a = attr.lower()
    return any(h in a for h in _LOCK_HINTS)


def _with_takes_lock(node: ast.AST) -> bool:
    if not isinstance(node, (ast.With, ast.AsyncWith)):
        return False
    for item in node.items:
        expr = item.context_expr
        # with self._lock:  /  with self._cv:
        if isinstance(expr, ast.Attribute) and _is_lock_attr(expr.attr) \
                and isinstance(expr.value, ast.Name) and expr.value.id == "self":
            return True
        # with self._lock.something(): (e.g. cv timeouts) — still the lock
        if isinstance(expr, ast.Call) and isinstance(expr.func, ast.Attribute):
            v = expr.func.value
            if isinstance(v, ast.Attribute) and _is_lock_attr(v.attr) \
                    and isinstance(v.value, ast.Name) and v.value.id == "self":
                return True
    return False


class _Checker:
    name = NAME
    codes = ("GL301", "GL302")
    doc = __doc__

    def run(self, ctx: LintContext) -> Iterable[Violation]:
        out: List[Violation] = []
        for src in ctx.sources:
            out.extend(self.check_source(src))
        return out

    def check_source(self, src: Source) -> List[Violation]:
        out: List[Violation] = []
        for node in ast.walk(src.tree):
            if isinstance(node, ast.ClassDef):
                out.extend(self._check_class(src, node))
        return out

    def _check_class(self, src: Source, cls: ast.ClassDef) -> List[Violation]:
        methods = [
            n for n in cls.body
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
        ]
        locked_methods = {m.name for m in methods if m.name.endswith("_locked")}
        if not locked_methods:
            return []

        # attrs WRITTEN by *_locked methods = lock-guarded state
        guarded: Set[str] = set()
        for m in methods:
            if m.name in locked_methods:
                guarded |= self._self_writes(m)

        out: List[Violation] = []
        for m in methods:
            holds_by_name = m.name.endswith("_locked")
            exempt_init = m.name in ("__init__", "__new__")
            self._walk(
                src, cls, m, m.body, in_lock=holds_by_name,
                guarded=guarded, exempt_writes=exempt_init or holds_by_name,
                out=out,
            )
        return out

    def _walk(self, src: Source, cls: ast.ClassDef, method,
              body, in_lock: bool, guarded: Set[str],
              exempt_writes: bool, out: List[Violation]) -> None:
        for node in body:
            locked_here = in_lock or _with_takes_lock(node)
            # GL301: self.*_locked(...) calls
            for sub in self._shallow_walk(node):
                if isinstance(sub, ast.Call) \
                        and isinstance(sub.func, ast.Attribute) \
                        and sub.func.attr.endswith("_locked") \
                        and isinstance(sub.func.value, ast.Name) \
                        and sub.func.value.id == "self" \
                        and not locked_here:
                    out.append(Violation(
                        checker=self.name, code="GL301", path=src.path,
                        line=sub.lineno,
                        symbol=f"{cls.name}.{method.name}->{sub.func.attr}",
                        message=(
                            f"self.{sub.func.attr}() called from "
                            f"{cls.name}.{method.name} without holding the "
                            "lock (not a *_locked method, not inside "
                            "`with self.<lock>:`)"
                        ),
                    ))
                # GL302: unlocked writes to guarded attrs
                if not locked_here and not exempt_writes:
                    attr = self._write_target(sub)
                    if attr is not None and attr in guarded:
                        out.append(Violation(
                            checker=self.name, code="GL302", path=src.path,
                            line=sub.lineno,
                            symbol=f"{cls.name}.{method.name}.{attr}",
                            message=(
                                f"self.{attr} is written by *_locked methods "
                                f"(lock-guarded state) but {cls.name}."
                                f"{method.name} writes it outside lock scope"
                            ),
                        ))
            # recurse, tracking lock scope lexically
            children = getattr(node, "body", None)
            if children:
                self._walk(src, cls, method, children, locked_here,
                           guarded, exempt_writes, out)
            for extra in ("orelse", "finalbody", "handlers"):
                sub_body = getattr(node, extra, None)
                if sub_body:
                    items = []
                    for h in sub_body:
                        if isinstance(h, ast.ExceptHandler):
                            items.extend(h.body)
                        else:
                            items.append(h)
                    self._walk(src, cls, method, items, locked_here,
                               guarded, exempt_writes, out)

    @staticmethod
    def _shallow_walk(node: ast.AST):
        """Yield the statement node's expressions without descending
        into nested statements (those are handled by _walk so lock
        scope stays lexical)."""
        if isinstance(node, (ast.With, ast.AsyncWith, ast.If, ast.For,
                             ast.AsyncFor, ast.While, ast.Try)):
            # header expressions only
            for field in ("items", "test", "iter", "target"):
                val = getattr(node, field, None)
                if val is None:
                    continue
                vals = val if isinstance(val, list) else [val]
                for v in vals:
                    expr = getattr(v, "context_expr", v)
                    yield from ast.walk(expr)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                               ast.ClassDef)):
            return  # nested defs run later, in their own context
        else:
            yield from ast.walk(node)

    @staticmethod
    def _self_writes(method) -> Set[str]:
        """Names of self attributes this method assigns/augments/
        subscript-writes."""
        out: Set[str] = set()
        for node in ast.walk(method):
            targets = []
            if isinstance(node, ast.Assign):
                targets = node.targets
            elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
                targets = [node.target]
            for t in targets:
                # self.attr = ... | self.attr[k] = ...
                if isinstance(t, ast.Subscript):
                    t = t.value
                if isinstance(t, ast.Attribute) \
                        and isinstance(t.value, ast.Name) \
                        and t.value.id == "self":
                    out.add(t.attr)
            # self.attr.append/extend/update/clear(...): mutation too
            if isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr in ("append", "extend", "update",
                                           "clear", "pop", "popleft",
                                           "appendleft", "add", "remove",
                                           "discard", "setdefault"):
                v = node.func.value
                if isinstance(v, ast.Attribute) \
                        and isinstance(v.value, ast.Name) and v.value.id == "self":
                    out.add(v.attr)
        return out

    @staticmethod
    def _write_target(node: ast.AST) -> Optional[str]:
        """The self-attribute a statement-level node writes, if any."""
        targets = []
        if isinstance(node, ast.Assign):
            targets = node.targets
        elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
            targets = [node.target]
        elif isinstance(node, ast.Call) \
                and isinstance(node.func, ast.Attribute) \
                and node.func.attr in ("append", "extend", "update", "clear",
                                       "pop", "popleft", "appendleft", "add",
                                       "remove", "discard", "setdefault"):
            v = node.func.value
            if isinstance(v, ast.Attribute) \
                    and isinstance(v.value, ast.Name) and v.value.id == "self":
                return v.attr
        for t in targets:
            if isinstance(t, ast.Subscript):
                t = t.value
            if isinstance(t, ast.Attribute) \
                    and isinstance(t.value, ast.Name) and t.value.id == "self":
                return t.attr
        return None


CHECKER = _Checker()
