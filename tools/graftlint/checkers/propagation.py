"""Checker: deadline + trace propagation (GL5xx).

Invariant (PR 4 + PR 6): **every ingress mints a Deadline and adopts
the caller's trace context; every NodeClient dispatch injects both
downstream and meters a ``_Hop``.**  A handler that dispatches without
activating the budget silently refunds queue time to abandoned
callers; a client method that skips injection orphans the downstream
spans and unbounds the hop.

Ingress rules (over the ingress modules listed below):

* handlers are module-level/nested functions with a ``request``-shaped
  parameter (aiohttp), a gRPC ``(request, context)`` pair, or
  ``__call__`` methods (native-lane bridge objects);
* a handler that DISPATCHES (calls ``run_dispatch``/``predict_async``,
  a gateway/predictor ``predict``/``send_feedback``/``aggregate``/
  ``explain``, or a ``predict_stream`` generator obtained via
  ``getattr``) must handle the deadline (``activate_ms``/``extract_ms``
  — the latter is the meta-tags absolute-expiry carrier stream lanes
  use) -> GL501, and the trace (``activate_context`` or an
  ``extract``/``_remote_ctx``/``_grpc_remote_ctx`` helper) -> GL502.

Transport rules (engine/transport.py):

* every NodeClient subclass's dispatch method (transform_input/
  transform_output/route/aggregate/send_feedback) must — transitively
  through same-class helpers and module functions — construct a
  ``_Hop`` (GL503), inject trace context (GL504) and handle the
  deadline (GL505), unless it merely delegates to another client's
  same-named method (BalancedClient's failover pattern).
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set

from tools.graftlint.core import (
    LintContext,
    Source,
    Violation,
    attr_root,
    call_name,
    str_const,
)

NAME = "propagation"

INGRESS_MODULES = (
    "seldon_core_tpu/runtime/rest.py",
    "seldon_core_tpu/runtime/grpc_server.py",
    "seldon_core_tpu/engine/server.py",
    "seldon_core_tpu/engine/sync_server.py",
    "seldon_core_tpu/engine/native_ingress.py",
    "seldon_core_tpu/native/frontserver.py",
)
TRANSPORT_MODULE = "seldon_core_tpu/engine/transport.py"

DISPATCH_CALLS = {"run_dispatch", "predict_async"}
DISPATCH_ATTRS = {"predict", "send_feedback", "aggregate", "explain",
                  "predict_stream"}
DEADLINE_MARKS = {"activate_ms", "extract_ms", "activate",
                  "_remote_deadline_ms", "_grpc_deadline_ms"}
TRACE_MARKS = {"activate_context", "extract", "_remote_ctx",
               "_grpc_remote_ctx"}

CLIENT_METHODS = ("transform_input", "transform_output", "route",
                  "aggregate", "send_feedback")

REQUEST_PARAMS = {"request", "_request", "_r", "req"}


def _params(fn) -> List[str]:
    a = fn.args
    return [p.arg for p in a.posonlyargs + a.args + a.kwonlyargs]


def _is_handler(fn, cls_name: Optional[str]) -> bool:
    params = _params(fn)
    if cls_name is not None:
        # native-lane bridge objects (__call__) and sync-server servicer
        # methods taking (self, request, context)
        return fn.name == "__call__" or (
            "context" in params and not fn.name.startswith("_")
        )
    if any(p in REQUEST_PARAMS for p in params):
        return True
    return "context" in params and len(params) >= 2  # grpc (request, context)


def _fn_calls(fn) -> Iterable[ast.Call]:
    for node in ast.walk(fn):
        if isinstance(node, ast.Call):
            yield node


def _getattr_marker_aliases(fn) -> Set[str]:
    """Names bound as ``x = getattr(obj, "<dispatch-attr>", ...)`` —
    the stream lanes call the generator through such an alias."""
    out: Set[str] = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and isinstance(node.value, ast.Call) \
                and call_name(node.value) == "getattr" \
                and len(node.value.args) >= 2:
            attr = str_const(node.value.args[1])
            if attr in DISPATCH_ATTRS:
                out.add(node.targets[0].id)
    return out


def _dispatches(fn) -> bool:
    aliases = _getattr_marker_aliases(fn)
    for call in _fn_calls(fn):
        name = call_name(call)
        if name in DISPATCH_CALLS:
            return True
        if isinstance(call.func, ast.Attribute) and name in DISPATCH_ATTRS:
            # self.predict(...) delegates to a SIBLING handler (which is
            # checked itself); self.gateway.predict(...) is the real
            # dispatch
            if isinstance(call.func.value, ast.Name) \
                    and call.func.value.id == "self":
                continue
            return True
        if isinstance(call.func, ast.Name) and call.func.id in aliases:
            return True
    return False


def _marks_used(fn, marks: Set[str]) -> bool:
    return any(call_name(c) in marks for c in _fn_calls(fn))


class _Checker:
    name = NAME
    codes = ("GL501", "GL502", "GL503", "GL504", "GL505")
    doc = __doc__

    def run(self, ctx: LintContext) -> Iterable[Violation]:
        out: List[Violation] = []
        for path in INGRESS_MODULES:
            src = ctx.source(path)
            if src is not None:
                out.extend(self.check_ingress(src))
        transport = ctx.source(TRANSPORT_MODULE)
        if transport is not None:
            out.extend(self.check_transport(transport))
        return out

    # ---- ingress ---------------------------------------------------------

    def check_ingress(self, src: Source) -> List[Violation]:
        out: List[Violation] = []
        for qual, fn, cls in _walk_funcs(src.tree):
            cls_name = cls.name if cls is not None else None
            if not _is_handler(fn, cls_name):
                continue
            if not _dispatches(fn):
                continue  # health/debug/metrics handlers are exempt
            if not _marks_used(fn, DEADLINE_MARKS):
                out.append(Violation(
                    checker=self.name, code="GL501", path=src.path,
                    line=fn.lineno, symbol=qual,
                    message=(
                        f"ingress handler {qual!r} dispatches without "
                        "minting the deadline (deadlines.activate_ms / the "
                        "extract_ms meta-tags carrier)"
                    ),
                ))
            if not _marks_used(fn, TRACE_MARKS):
                out.append(Violation(
                    checker=self.name, code="GL502", path=src.path,
                    line=fn.lineno, symbol=qual,
                    message=(
                        f"ingress handler {qual!r} dispatches without "
                        "adopting the caller's trace context "
                        "(tracing.activate_context / extract)"
                    ),
                ))
        return out

    # ---- transport -------------------------------------------------------

    def check_transport(self, src: Source) -> List[Violation]:
        out: List[Violation] = []
        module_funcs: Dict[str, ast.AST] = {
            n.name: n for n in src.tree.body
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
        }
        for node in src.tree.body:
            if not isinstance(node, ast.ClassDef):
                continue
            if not node.name.endswith("Client"):
                continue
            methods = {
                m.name: m for m in node.body
                if isinstance(m, (ast.FunctionDef, ast.AsyncFunctionDef))
            }
            for mname in CLIENT_METHODS:
                m = methods.get(mname)
                if m is None:
                    continue
                if _body_only_raises(m):
                    continue  # the NodeClient abstract surface
                closure = self._closure(m, methods, module_funcs)
                if self._delegates(closure, mname):
                    continue  # failover wrappers delegate to real clients
                has_hop = any(
                    call_name(c) == "_Hop"
                    for f in closure for c in _fn_calls(f)
                )
                has_trace = any(
                    self._is_trace_inject(c, methods)
                    for f in closure for c in _fn_calls(f)
                )
                has_deadline = any(
                    self._is_deadline_use(c)
                    for f in closure for c in _fn_calls(f)
                )
                qual = f"{node.name}.{mname}"
                if not has_hop:
                    out.append(Violation(
                        checker=self.name, code="GL503", path=src.path,
                        line=m.lineno, symbol=qual,
                        message=f"{qual} dispatches without metering a _Hop "
                                "(per-hop transport telemetry contract)",
                    ))
                if not has_trace:
                    out.append(Violation(
                        checker=self.name, code="GL504", path=src.path,
                        line=m.lineno, symbol=qual,
                        message=f"{qual} dispatches without injecting trace "
                                "context (tracing.inject/inject_metadata/"
                                "_inject_meta)",
                    ))
                if not has_deadline:
                    out.append(Violation(
                        checker=self.name, code="GL505", path=src.path,
                        line=m.lineno, symbol=qual,
                        message=f"{qual} dispatches without checking/"
                                "injecting the deadline budget "
                                "(deadlines.check/inject/inject_metadata)",
                    ))
        return out

    @staticmethod
    def _delegates(closure, mname: str) -> bool:
        """The method (or a helper it calls) invokes
        ``<expr>.<same-method>(...)`` on something that is not ``self``,
        or dispatches dynamically via ``getattr(client, method)(...)`` —
        the failover/balancer delegation patterns.  The wrapped clients
        carry the injection obligations."""
        for fn in closure:
            for call in _fn_calls(fn):
                if isinstance(call.func, ast.Attribute) \
                        and call.func.attr == mname \
                        and attr_root(call.func.value) != "self":
                    return True
                if isinstance(call.func, ast.Call) \
                        and call_name(call.func) == "getattr":
                    return True
        return False

    @staticmethod
    def _closure(m, methods: Dict[str, ast.AST],
                 module_funcs: Dict[str, ast.AST]) -> List[ast.AST]:
        """m plus every same-class method / module-level function it
        transitively calls."""
        seen: Set[str] = set()
        order: List[ast.AST] = []
        stack = [m]
        while stack:
            fn = stack.pop()
            order.append(fn)
            for call in _fn_calls(fn):
                target = None
                key = None
                if isinstance(call.func, ast.Attribute) \
                        and isinstance(call.func.value, ast.Name) \
                        and call.func.value.id == "self":
                    key = f"self.{call.func.attr}"
                    target = methods.get(call.func.attr)
                elif isinstance(call.func, ast.Name):
                    key = call.func.id
                    target = module_funcs.get(call.func.id)
                if target is not None and key not in seen:
                    seen.add(key)
                    stack.append(target)
        return order

    @staticmethod
    def _is_trace_inject(call: ast.Call, methods) -> bool:
        name = call_name(call)
        root = attr_root(call.func)
        if root in ("_tracing", "tracing") and name.startswith("inject"):
            return True
        return name == "_inject_meta" and "_inject_meta" in methods

    @staticmethod
    def _is_deadline_use(call: ast.Call) -> bool:
        name = call_name(call)
        root = attr_root(call.func)
        return root in ("_deadlines", "deadlines") and name in (
            "check", "inject", "inject_metadata", "current_deadline",
        )


def _body_only_raises(fn) -> bool:
    """True for abstract-surface methods whose body is just
    ``raise NotImplementedError`` (plus an optional docstring)."""
    body = [
        n for n in fn.body
        if not (isinstance(n, ast.Expr) and str_const(n.value) is not None)
    ]
    return all(isinstance(n, (ast.Raise, ast.Pass)) for n in body)


def _walk_funcs(tree: ast.Module):
    """(qualname, fn, class-or-None) including nested functions."""
    def walk(node, prefix, cls):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                yield f"{prefix}{child.name}", child, cls
                yield from walk(child, f"{prefix}{child.name}.", cls)
            elif isinstance(child, ast.ClassDef):
                yield from walk(child, prefix + child.name + ".", child)
            else:
                yield from walk(child, prefix, cls)

    yield from walk(tree, "", None)


CHECKER = _Checker()
