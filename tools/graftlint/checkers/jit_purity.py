"""Checker: host purity of jitted programs (GL1xx).

Invariant (PR 4's jitwatch sentinel, made static): a function handed to
``jax.jit`` / ``PagedEngine._tp_jit`` runs ONCE per shape signature at
trace time — host-side branching on traced values raises
``TracerBoolConversionError`` in the best case and silently bakes one
branch into the compiled program in the worst; ``float()/int()/.item()``
on a tracer forces a device sync or crashes; mutating captured Python
state from inside the traced body executes once per COMPILE, not once
per call (the classic "my counter only moved on the first request"
bug); and an unhashable static arg fails at call time.  The runtime
sentinel catches the recompile storm after deploy — this checker
catches the cause in review.

Rules (within resolved jit targets):

* GL101 — ``float()/int()/bool()/complex()`` on a traced value.
* GL102 — ``.item()/.tolist()``, ``np.asarray/np.array``,
  ``jax.device_get``, or ``print`` applied to a traced value.
* GL103 — ``if``/``while``/``assert``/ternary condition on a traced
  value (host control flow on a tracer; use ``jnp.where``/``lax.cond``).
* GL104 — mutation of captured state: ``global``/``nonlocal``
  declarations, or writes to free variables / ``self`` attributes from
  inside the traced body.
* GL105 — ``static_argnums``/``static_argnames`` naming a parameter
  whose default is an unhashable literal (list/dict/set).

Tracked-value analysis is deliberately conservative: parameters are
traced; names assigned from expressions using traced names become
traced; expressions rooted in ``.shape``/``.ndim``/``.dtype``/
``len()``/``isinstance()`` are STATIC (shape math is host-legal), as
are subscripts of ``.shape``.  Anything the analysis cannot prove
traced is left alone — precision over recall, with the allowlist as
the escape hatch.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from tools.graftlint.core import LintContext, Source, Violation, call_name, str_const

NAME = "jit-purity"

JIT_NAMES = {"jit", "_tp_jit"}
WRAPPER_NAMES = {"vmap", "pmap", "partial", "wraps", "checkpoint", "remat",
                 "named_call", "wrap"}
STATIC_ATTRS = {"shape", "ndim", "dtype", "size", "sharding", "aval",
                "weak_type"}
STATIC_CALLS = {"len", "isinstance", "getattr", "hasattr", "type", "range",
                "enumerate", "zip", "min", "max"}
CAST_CALLS = {"float", "int", "bool", "complex"}
HOST_PULL_ATTRS = {"item", "tolist", "to_py"}
HOST_PULL_CALLS = {"asarray", "array", "device_get"}


def _jit_target(call: ast.Call) -> Optional[ast.AST]:
    """The function expression handed to a jit call, unwrapping
    vmap/partial-style wrappers."""
    if not call.args:
        for kw in call.keywords:
            if kw.arg in ("fun", "f"):
                return kw.value
        return None
    target = call.args[0]
    while isinstance(target, ast.Call) and call_name(target) in WRAPPER_NAMES:
        if not target.args:
            return None
        target = target.args[0]
    return target


def _static_params(call: ast.Call, fn) -> Set[str]:
    """Parameter names made static by static_argnums/static_argnames."""
    params = [p.arg for p in fn.args.posonlyargs + fn.args.args]
    out: Set[str] = set()
    for kw in call.keywords:
        if kw.arg == "static_argnames":
            vals = kw.value.elts if isinstance(kw.value, (ast.Tuple, ast.List)) \
                else [kw.value]
            for v in vals:
                s = str_const(v)
                if s:
                    out.add(s)
        elif kw.arg == "static_argnums":
            vals = kw.value.elts if isinstance(kw.value, (ast.Tuple, ast.List)) \
                else [kw.value]
            for v in vals:
                if isinstance(v, ast.Constant) and isinstance(v.value, int) \
                        and 0 <= v.value < len(params):
                    out.add(params[v.value])
    return out


class _PurityVisitor(ast.NodeVisitor):
    """Walks one jitted function body with a traced-name set."""

    def __init__(self, checker, src: Source, qual: str, fn, traced: Set[str],
                 local: Set[str], out: List[Violation]):
        self.checker = checker
        self.src = src
        self.qual = qual
        self.fn = fn
        self.traced = set(traced)
        self.local = set(local)
        self.out = out

    # -- traced-ness of an expression -----------------------------------

    def _is_traced(self, node: ast.AST) -> bool:
        """Does evaluating ``node`` touch a traced value dynamically
        (i.e. not through a shape/dtype/len escape)?"""
        if isinstance(node, ast.Name):
            return node.id in self.traced
        if isinstance(node, ast.Attribute):
            if node.attr in STATIC_ATTRS:
                return False
            return self._is_traced(node.value)
        if isinstance(node, ast.Subscript):
            # x.shape[0] is static; traced[i] is traced
            return self._is_traced(node.value)
        if isinstance(node, ast.Call):
            if call_name(node) in STATIC_CALLS:
                return False
            args = list(node.args) + [k.value for k in node.keywords]
            return any(self._is_traced(a) for a in args) or (
                isinstance(node.func, ast.Attribute)
                and self._is_traced(node.func.value)
            )
        if isinstance(node, (ast.BinOp,)):
            return self._is_traced(node.left) or self._is_traced(node.right)
        if isinstance(node, ast.UnaryOp):
            return self._is_traced(node.operand)
        if isinstance(node, ast.BoolOp):
            return any(self._is_traced(v) for v in node.values)
        if isinstance(node, ast.Compare):
            return self._is_traced(node.left) or any(
                self._is_traced(c) for c in node.comparators
            )
        if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
            return any(self._is_traced(e) for e in node.elts)
        if isinstance(node, ast.IfExp):
            return any(self._is_traced(n)
                       for n in (node.test, node.body, node.orelse))
        if isinstance(node, ast.Starred):
            return self._is_traced(node.value)
        return False

    def _emit(self, code: str, node: ast.AST, msg: str) -> None:
        self.out.append(Violation(
            checker=self.checker.name, code=code, path=self.src.path,
            line=getattr(node, "lineno", self.fn.lineno),
            symbol=self.qual, message=f"in jitted {self.qual!r}: {msg}",
        ))

    # -- assignments propagate traced-ness ------------------------------

    def visit_Assign(self, node: ast.Assign):
        traced_rhs = self._is_traced(node.value)
        for t in node.targets:
            for n in ast.walk(t):
                if isinstance(n, ast.Name):
                    self.local.add(n.id)
                    if traced_rhs:
                        self.traced.add(n.id)
        self._check_capture_write(node.targets, node)
        self.generic_visit(node)

    def visit_AugAssign(self, node: ast.AugAssign):
        if isinstance(node.target, ast.Name):
            self.local.add(node.target.id)
            if self._is_traced(node.value):
                self.traced.add(node.target.id)
        self._check_capture_write([node.target], node)
        self.generic_visit(node)

    def _check_capture_write(self, targets: Sequence[ast.AST], node) -> None:
        for t in targets:
            base = t
            is_container_write = False
            while isinstance(base, (ast.Subscript, ast.Attribute)):
                is_container_write = True
                base = base.value
            if not is_container_write:
                continue
            if isinstance(base, ast.Name):
                if base.id == "self":
                    self._emit("GL104", node,
                               "writes self state from inside the traced "
                               "body (runs once per COMPILE, not per call)")
                elif base.id not in self.local and base.id not in self.traced:
                    self._emit("GL104", node,
                               f"writes captured variable {base.id!r} from "
                               "inside the traced body (runs once per "
                               "COMPILE, not per call)")

    def visit_Global(self, node: ast.Global):
        self._emit("GL104", node,
                   "`global` inside a jitted function — captured-state "
                   "mutation executes at trace time only")

    def visit_Nonlocal(self, node: ast.Nonlocal):
        self._emit("GL104", node,
                   "`nonlocal` inside a jitted function — captured-state "
                   "mutation executes at trace time only")

    # -- host pulls / casts ---------------------------------------------

    def visit_Call(self, node: ast.Call):
        name = call_name(node)
        if name in CAST_CALLS and node.args \
                and self._is_traced(node.args[0]):
            self._emit("GL101", node,
                       f"{name}() on a traced value forces a host sync / "
                       "TracerConversionError — keep it on-device "
                       "(jnp.asarray / astype)")
        elif name in HOST_PULL_ATTRS and isinstance(node.func, ast.Attribute) \
                and self._is_traced(node.func.value):
            self._emit("GL102", node,
                       f".{name}() pulls a traced value to host at trace "
                       "time")
        elif name in HOST_PULL_CALLS and isinstance(node.func, ast.Attribute):
            root = node.func.value
            rootname = root.id if isinstance(root, ast.Name) else ""
            if rootname in ("np", "numpy", "jax") and node.args \
                    and self._is_traced(node.args[0]):
                self._emit("GL102", node,
                           f"{rootname}.{name}() materializes a traced value "
                           "on host (use jnp inside the program)")
        elif name == "print" and any(
            self._is_traced(a) for a in node.args
        ):
            self._emit("GL102", node,
                       "print(traced) runs at trace time only (use "
                       "jax.debug.print)")
        self.generic_visit(node)

    # -- host control flow on tracers -----------------------------------

    def visit_If(self, node: ast.If):
        if self._is_traced(node.test):
            self._emit("GL103", node,
                       "`if` on a traced value — host control flow cannot "
                       "branch on tracers (use jnp.where / lax.cond)")
        self.generic_visit(node)

    def visit_While(self, node: ast.While):
        if self._is_traced(node.test):
            self._emit("GL103", node,
                       "`while` on a traced value (use lax.while_loop)")
        self.generic_visit(node)

    def visit_Assert(self, node: ast.Assert):
        if self._is_traced(node.test):
            self._emit("GL103", node,
                       "`assert` on a traced value (use checkify or move "
                       "the check outside the program)")
        self.generic_visit(node)

    def visit_IfExp(self, node: ast.IfExp):
        if self._is_traced(node.test):
            self._emit("GL103", node,
                       "ternary condition on a traced value (use jnp.where)")
        self.generic_visit(node)

    # nested defs/lambdas get their params as local, not traced
    def visit_FunctionDef(self, node):
        self.local.add(node.name)
        inner_locals = {a.arg for a in node.args.args + node.args.posonlyargs
                        + node.args.kwonlyargs}
        self.local |= inner_locals
        self.generic_visit(node)

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Lambda(self, node: ast.Lambda):
        self.local |= {a.arg for a in node.args.args + node.args.posonlyargs
                       + node.args.kwonlyargs}
        self.generic_visit(node)


class _Checker:
    name = NAME
    codes = ("GL101", "GL102", "GL103", "GL104", "GL105")
    doc = __doc__

    def run(self, ctx: LintContext) -> Iterable[Violation]:
        out: List[Violation] = []
        for src in ctx.sources:
            out.extend(self.check_source(src))
        return out

    def check_source(self, src: Source) -> List[Violation]:
        out: List[Violation] = []
        index = _FunctionIndex(src.tree)
        seen: Set[Tuple[int, int]] = set()
        for scope_stack, call in _jit_calls(src.tree):
            target = _jit_target(call)
            if target is None:
                continue
            fn, qual = index.resolve(target, scope_stack)
            if fn is None:
                continue
            key = (fn.lineno, getattr(fn, "col_offset", 0))
            if key in seen:
                continue  # one function jitted from several sites
            seen.add(key)
            statics = _static_params(call, fn) if isinstance(fn, (
                ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)) else set()
            out.extend(self._check_target(src, call, fn, qual, statics))
        # decorator spellings: @jax.jit / @partial(jax.jit, ...)
        for qual, fn in index.decorated_jits():
            key = (fn.lineno, getattr(fn, "col_offset", 0))
            if key in seen:
                continue
            seen.add(key)
            statics: Set[str] = set()
            for dec in fn.decorator_list:
                if isinstance(dec, ast.Call):
                    statics |= _static_params(dec, fn)
            out.extend(self._check_target(src, fn, fn, qual, statics))
        return out

    def _check_target(self, src: Source, call, fn, qual: str,
                      statics: Set[str]) -> List[Violation]:
        out: List[Violation] = []
        if isinstance(fn, ast.Lambda):
            params = {a.arg for a in fn.args.args + fn.args.posonlyargs}
            v = _PurityVisitor(self, src, qual, fn,
                               traced=params - statics, local=set(params), out=out)
            v.visit(fn.body)
            return out
        params = [a.arg for a in fn.args.posonlyargs + fn.args.args
                  + fn.args.kwonlyargs]
        traced = {p for p in params if p not in statics and p != "self"}
        # GL105: unhashable static-arg defaults
        defaults = dict(zip(reversed([a.arg for a in fn.args.args]),
                            reversed(fn.args.defaults)))
        for p in sorted(statics):
            d = defaults.get(p)
            if isinstance(d, (ast.List, ast.Dict, ast.Set)):
                out.append(Violation(
                    checker=self.name, code="GL105", path=src.path,
                    line=fn.lineno, symbol=qual,
                    message=(
                        f"in jitted {qual!r}: static arg {p!r} defaults to "
                        "an unhashable literal — static args must be "
                        "hashable (use a tuple / frozen mapping)"
                    ),
                ))
        v = _PurityVisitor(self, src, qual, fn, traced=traced,
                           local=set(params), out=out)
        for stmt in fn.body:
            v.visit(stmt)
        return out


class _FunctionIndex:
    """Resolve a jit call's target expression to a FunctionDef in the
    same module: bare names to the enclosing lexical scope, ``self.X``
    to a method of the enclosing class."""

    def __init__(self, tree: ast.Module):
        self.tree = tree

    def resolve(self, target: ast.AST, scope_stack) -> Tuple[Optional[ast.AST], str]:
        if isinstance(target, ast.Lambda):
            return target, "<lambda>"
        name = None
        method_of_self = False
        if isinstance(target, ast.Name):
            name = target.id
        elif isinstance(target, ast.Attribute) \
                and isinstance(target.value, ast.Name) \
                and target.value.id == "self":
            name = target.attr
            method_of_self = True
        if name is None:
            return None, ""
        # innermost scope first
        for scope in reversed(scope_stack):
            if method_of_self and not isinstance(scope, ast.ClassDef):
                continue
            for child in ast.iter_child_nodes(scope):
                if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                        and child.name == name:
                    return child, name
        return None, ""

    def decorated_jits(self):
        for node in ast.walk(self.tree):
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            for dec in node.decorator_list:
                base = dec.func if isinstance(dec, ast.Call) else dec
                if call_name(base) == "jit" or (
                    isinstance(dec, ast.Call)
                    and call_name(dec) == "partial"
                    and dec.args
                    and call_name(dec.args[0]) == "jit"
                ):
                    yield node.name, node
                    break


def _jit_calls(tree: ast.Module):
    """Yield (enclosing-scope-stack, jit Call) pairs."""
    def walk(node, stack):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.Call) and call_name(child) in JIT_NAMES:
                yield stack, child
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.ClassDef, ast.Module)):
                yield from walk(child, stack + [child])
            else:
                yield from walk(child, stack)

    yield from walk(tree, [tree])


CHECKER = _Checker()
