"""Checker: central knob registry (GL2xx).

Invariant (PR 7 review catch, generalized): every ``SELDON_TPU_*`` env
var, ``seldon.io/*`` annotation and ``X-Seldon-*`` header the package
touches is DECLARED in ``runtime/knobs.py`` — with type, default,
``=0``-means-OFF semantics and a docs anchor — and every env read goes
through the registry (``knobs.raw``/``knobs.flag``), never through
``os.environ`` directly.  Docs drift fails too: a registered knob
missing from ``docs/`` or a ``SELDON_TPU_*`` token in the docs that no
longer exists in the registry.

Rules:

* GL201 — direct ``os.environ.get/[]`` / ``os.getenv`` read of a
  ``SELDON_TPU_*`` name anywhere outside ``runtime/knobs.py``.
* GL202 — a full-string ``SELDON_TPU_*`` / ``seldon.io/*`` /
  ``X-Seldon-*`` literal that is not declared in the registry.
* GL203 — docs drift (registry -> docs and docs -> registry).
* GL204 — ``knobs.raw``/``knobs.flag`` called with an undeclared
  literal (the static twin of the runtime UndeclaredKnobError).
"""

from __future__ import annotations

import ast
import re
from typing import Iterable, List, Optional

from tools.graftlint.core import (
    LintContext,
    Source,
    Violation,
    attr_root,
    call_name,
    module_constants,
    str_const,
)

NAME = "knob-registry"

KNOBS_MODULE = "seldon_core_tpu/runtime/knobs.py"

ENV_RE = re.compile(r"^SELDON_TPU_[A-Z0-9_]+$")
ANN_RE = re.compile(r"^seldon\.io/[a-z0-9.\-]+$")
HDR_RE = re.compile(r"^[Xx]-[Ss]eldon-[A-Za-z\-]+$")
DOCS_TOKEN_RE = re.compile(r"\bSELDON_TPU_[A-Z0-9_]+\b")


def _registry():
    from seldon_core_tpu.runtime import knobs

    return knobs


class _Checker:
    name = NAME
    codes = ("GL201", "GL202", "GL203", "GL204")
    doc = __doc__

    def run(self, ctx: LintContext) -> Iterable[Violation]:
        knobs = _registry()
        out: List[Violation] = []
        for src in ctx.sources:
            out.extend(self.check_source(src, knobs))
        out.extend(self._docs_drift(ctx, knobs))
        return out

    def check_source(self, src: Source, knobs=None) -> List[Violation]:
        if knobs is None:
            knobs = _registry()
        out: List[Violation] = []
        consts = module_constants(src.tree)
        in_registry_module = src.path == KNOBS_MODULE

        def env_name(node: ast.AST) -> Optional[str]:
            """The SELDON_TPU_* name an expression denotes (literal or
            module-level constant), else None."""
            s = str_const(node)
            if s is None and isinstance(node, ast.Name):
                s = consts.get(node.id)
            if s is not None and ENV_RE.match(s):
                return s
            return None

        for node in ast.walk(src.tree):
            # -- GL201: direct environ reads ------------------------------
            if isinstance(node, ast.Call):
                fname = call_name(node)
                root = attr_root(node.func)
                is_env_get = (
                    fname == "getenv"
                    or (
                        fname == "get"
                        and isinstance(node.func, ast.Attribute)
                        and isinstance(node.func.value, ast.Attribute)
                        and node.func.value.attr == "environ"
                    )
                    or (
                        fname == "get"
                        and isinstance(node.func, ast.Attribute)
                        and isinstance(node.func.value, ast.Name)
                        and node.func.value.id == "environ"
                    )
                )
                if is_env_get and node.args and not in_registry_module:
                    name = env_name(node.args[0])
                    if name is not None:
                        out.append(Violation(
                            checker=self.name, code="GL201", path=src.path,
                            line=node.lineno, symbol=name,
                            message=(
                                f"direct environ read of {name!r}: go through "
                                "runtime/knobs.py (knobs.raw / knobs.flag)"
                            ),
                        ))
                # -- GL204: registry read of an undeclared name ----------
                if fname in ("raw", "flag") and root in ("knobs", "_knobs"):
                    if node.args:
                        s = str_const(node.args[0])
                        if s is None and isinstance(node.args[0], ast.Name):
                            s = consts.get(node.args[0].id)
                        if s is not None and ENV_RE.match(s) \
                                and s not in knobs.ENV_KNOBS:
                            out.append(Violation(
                                checker=self.name, code="GL204",
                                path=src.path, line=node.lineno, symbol=s,
                                message=(
                                    f"knobs.{fname}({s!r}) reads a knob that "
                                    "is not declared in runtime/knobs.py"
                                ),
                            ))
            elif isinstance(node, ast.Subscript) and isinstance(node.ctx, ast.Load):
                v = node.value
                is_environ = (
                    isinstance(v, ast.Attribute) and v.attr == "environ"
                ) or (isinstance(v, ast.Name) and v.id == "environ")
                if is_environ and not in_registry_module:
                    name = env_name(node.slice)
                    if name is not None:
                        out.append(Violation(
                            checker=self.name, code="GL201", path=src.path,
                            line=node.lineno, symbol=name,
                            message=(
                                f"direct environ[{name!r}] read: go through "
                                "runtime/knobs.py"
                            ),
                        ))

            # -- GL202: undeclared full-string literals -------------------
            s = str_const(node)
            if s is None or in_registry_module:
                continue
            if ENV_RE.match(s) and s not in knobs.ENV_KNOBS:
                out.append(Violation(
                    checker=self.name, code="GL202", path=src.path,
                    line=node.lineno, symbol=s,
                    message=(
                        f"env knob {s!r} is not declared in runtime/knobs.py "
                        "(name, kind, default, zero-off semantics, docs anchor)"
                    ),
                ))
            elif ANN_RE.match(s) and s not in knobs.ANNOTATIONS:
                out.append(Violation(
                    checker=self.name, code="GL202", path=src.path,
                    line=node.lineno, symbol=s,
                    message=(
                        f"annotation {s!r} is not declared in "
                        "runtime/knobs.py ANNOTATIONS"
                    ),
                ))
            elif HDR_RE.match(s) and not knobs.declared(s):
                out.append(Violation(
                    checker=self.name, code="GL202", path=src.path,
                    line=node.lineno, symbol=s,
                    message=(
                        f"header {s!r} is not declared in "
                        "runtime/knobs.py HEADERS"
                    ),
                ))
        return out

    def _docs_drift(self, ctx: LintContext, knobs) -> List[Violation]:
        out: List[Violation] = []
        docs = ctx.docs_text
        for name, knob in sorted(knobs.ENV_KNOBS.items()):
            if name not in docs:
                out.append(Violation(
                    checker=self.name, code="GL203", path=KNOBS_MODULE,
                    line=1, symbol=name,
                    message=(
                        f"registered knob {name!r} (anchor {knob.anchor!r}) "
                        "is not documented anywhere under docs/"
                    ),
                ))
            if not knob.anchor:
                out.append(Violation(
                    checker=self.name, code="GL203", path=KNOBS_MODULE,
                    line=1, symbol=name,
                    message=f"registered knob {name!r} has an empty docs anchor",
                ))
        for token in sorted(set(DOCS_TOKEN_RE.findall(docs))):
            if token not in knobs.ENV_KNOBS:
                out.append(Violation(
                    checker=self.name, code="GL203", path="docs/",
                    line=1, symbol=token,
                    message=(
                        f"docs mention {token!r} but the registry does not "
                        "declare it — ghost knob or docs drift"
                    ),
                ))
        return out


CHECKER = _Checker()
