"""Checker: capture-store redaction (GL408).

Invariant (r21): **every request-capture container serialized for the
capture store routes through ``utils/capture.redact``.**  The capture
plane persists raw prompt/output token ids to disk; ``redact`` is the
single write-side privacy filter — it stamps payload lengths and,
under ``SELDON_TPU_CAPTURE_PAYLOADS=0``, strips the payload frames so
raw ids never reach the store.  A writer that calls
``codec/bufview.pack_capture`` without routing its payload through
``redact`` silently bypasses that filter.

Rule: any function (or module-level code) calling ``pack_capture``
must also call ``redact`` in the same scope -> GL408.  ``unpack``-side
code and the codec's own definition are naturally exempt (they never
call ``pack_capture``).
"""

from __future__ import annotations

import ast
from typing import Iterable, List

from tools.graftlint.core import (
    LintContext,
    Source,
    Violation,
    call_name,
    iter_funcs,
)

NAME = "capture-redaction"

PACK_CALL = "pack_capture"
REDACT_CALL = "redact"


def _calls(node: ast.AST) -> List[ast.Call]:
    return [n for n in ast.walk(node) if isinstance(n, ast.Call)]


class _Checker:
    name = NAME
    codes = ("GL408",)
    doc = __doc__

    def run(self, ctx: LintContext) -> Iterable[Violation]:
        out: List[Violation] = []
        for src in ctx.sources:
            out.extend(self.check_source(src))
        return out

    def check_source(self, src: Source) -> List[Violation]:
        out: List[Violation] = []
        in_function_calls = set()
        for qual, fn, _cls in iter_funcs(src.tree):
            calls = _calls(fn)
            in_function_calls.update(id(c) for c in calls)
            packs = [c for c in calls if call_name(c) == PACK_CALL]
            if not packs:
                continue
            if any(call_name(c) == REDACT_CALL for c in calls):
                continue
            out.append(self._violation(src, packs[0].lineno, qual))
        # module-level writers (scripts, constants built at import time)
        module_calls = [
            c for c in _calls(src.tree) if id(c) not in in_function_calls
        ]
        module_packs = [
            c for c in module_calls if call_name(c) == PACK_CALL
        ]
        if module_packs and not any(
            call_name(c) == REDACT_CALL for c in module_calls
        ):
            out.append(self._violation(src, module_packs[0].lineno, "<module>"))
        return out

    def _violation(self, src: Source, line: int, qual: str) -> Violation:
        return Violation(
            checker=self.name, code="GL408", path=src.path,
            line=line, symbol=qual,
            message=(
                f"{qual!r} serializes a capture container "
                "(pack_capture) without routing the payload through "
                "capture.redact — the store's write-side privacy "
                "filter (SELDON_TPU_CAPTURE_PAYLOADS contract)"
            ),
        )


CHECKER = _Checker()
