"""Checker: generation metrics contract (GL4xx).

Invariant (PR 3, extended by PRs 5-8): ``PagedEngine.engine_stats()``
is complete-by-contract — every key it emits maps to a canonical
Prometheus metric in ``GenerationPrometheusBridge``
(``ENGINE_STATS_METRICS``) or is explicitly excluded
(``ENGINE_STATS_EXCLUDED``), and the SLO counter keys the flight
recorder threads per-chunk (``_SLO_COUNTER_KEYS``) are real, mapped
counters.  The per-subsystem runtime contract tests asserted slices of
this; the checker generalizes them into one static pass that also
polices metric NAMING (``seldon_tpu_`` prefix, counters end
``_total``).

Rules:

* GL401 — engine_stats key neither bridge-mapped nor excluded.
* GL402 — bridge-mapped/excluded key that engine_stats never emits.
* GL403 — metric naming: prefix/suffix discipline in
  ``ENGINE_STATS_METRICS`` and ``TRANSPORT_METRICS``.
* GL404 — ``_SLO_COUNTER_KEYS`` entry that is not a mapped
  engine-stats counter (the flight-recorder threading contract).
* GL405 — ``record_transport_hop`` keyword parameter with no
  ``TRANSPORT_METRICS`` mapping and no ``TRANSPORT_RECORD_EXCLUDED``
  entry: a per-hop measurement (e.g. the r14 ``zero_copy_bytes``
  split) that would silently skip Prometheus export.
* GL406 — ``TelemetryAggregator.fleet_rollup()`` key neither mapped
  (``FLEET_METRICS``) nor excluded (``FLEET_EXCLUDED``): a fleet
  aggregate that would silently skip ``seldon_tpu_fleet_*`` export
  (the r20 fleet-telemetry contract, same shape as GL401).
* GL407 — ``FLEET_METRICS``/``FLEET_EXCLUDED`` key the rollup never
  emits — dead fleet mapping (the GL402 twin).

The GL403 naming pass also covers ``COST_LEDGER_METRICS`` (the
per-adapter cost-ledger export) and ``FLEET_METRICS``.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set, Tuple

from tools.graftlint.core import LintContext, Source, Violation, str_const

NAME = "metrics-contract"

PAGED = "seldon_core_tpu/models/paged.py"
METRICS = "seldon_core_tpu/utils/metrics.py"
FLEETVIEW = "seldon_core_tpu/controlplane/fleetview.py"


def _dict_literal_keys(node: ast.Dict) -> List[str]:
    out = []
    for k in node.keys:
        s = str_const(k) if k is not None else None
        if s is not None:
            out.append(s)
    return out


def _assigned_dict(tree: ast.AST, name: str, attr_of_self: bool = False) -> Optional[ast.Dict]:
    """First ``<name> = {...}`` (or ``self.<name> = {...}``) dict
    literal in the tree."""
    for node in ast.walk(tree):
        if not isinstance(node, ast.Assign) or len(node.targets) != 1:
            continue
        t = node.targets[0]
        match = (
            isinstance(t, ast.Attribute) and t.attr == name
            if attr_of_self else
            isinstance(t, ast.Name) and t.id == name
        )
        if match and isinstance(node.value, ast.Dict):
            return node.value
    return None


def _metric_specs(tree: ast.AST, name: str) -> Dict[str, Tuple[str, str]]:
    """Parse ``NAME: Dict[...] = { "key": (kind, metric, doc), ... }``
    into {key: (kind, metric_name)}."""
    out: Dict[str, Tuple[str, str]] = {}
    for node in ast.walk(tree):
        target = None
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            target, value = node.targets[0], node.value
        elif isinstance(node, ast.AnnAssign):
            target, value = node.target, node.value
        else:
            continue
        if not (isinstance(target, ast.Name) and target.id == name):
            continue
        if not isinstance(value, ast.Dict):
            continue
        for k, v in zip(value.keys, value.values):
            key = str_const(k) if k is not None else None
            if key is None or not isinstance(v, ast.Tuple) or len(v.elts) < 2:
                continue
            kind = str_const(v.elts[0]) or ""
            metric = str_const(v.elts[1]) or ""
            out[key] = (kind, metric)
    return out


def _set_literal(tree: ast.AST, name: str) -> Optional[Set[str]]:
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            t = node.targets[0]
            if isinstance(t, ast.Name) and t.id == name \
                    and isinstance(node.value, (ast.Set, ast.Tuple, ast.List)):
                return {
                    s for e in node.value.elts
                    if (s := str_const(e)) is not None
                }
    return None


def _engine_stats_keys(paged: Source) -> Set[str]:
    """Keys engine_stats() emits: the ``self._counters`` init dict plus
    the literal keys of the dict built inside ``engine_stats``."""
    keys: Set[str] = set()
    counters = _assigned_dict(paged.tree, "_counters", attr_of_self=True)
    if counters is not None:
        keys |= set(_dict_literal_keys(counters))
    for node in ast.walk(paged.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and node.name == "engine_stats":
            for sub in ast.walk(node):
                if isinstance(sub, ast.Dict):
                    keys |= set(_dict_literal_keys(sub))
    # detail-mode additions (out["recorder"] = ...) are not part of the
    # DEFAULT contract; they only exist under detail=True
    keys.discard("records")
    return keys


def _fleet_rollup_keys(fleetview: Source) -> Set[str]:
    """Keys ``fleet_rollup()`` emits: the literal keys of every dict
    built inside the function (one return literal today; the walk keeps
    the contract honest if it grows helpers)."""
    keys: Set[str] = set()
    for node in ast.walk(fleetview.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and node.name == "fleet_rollup":
            for sub in ast.walk(node):
                if isinstance(sub, ast.Dict):
                    keys |= set(_dict_literal_keys(sub))
    return keys


def _hop_record_params(tree: ast.AST) -> List[Tuple[str, int]]:
    """The keyword parameters of ``record_transport_hop`` (the per-hop
    recording surface) with their line — every quantitative one must be
    bridge-mapped or excluded."""
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and node.name == "record_transport_hop":
            a = node.args
            return [(p.arg, node.lineno) for p in a.kwonlyargs]
    return []


class _Checker:
    name = NAME
    codes = ("GL401", "GL402", "GL403", "GL404", "GL405", "GL406", "GL407")
    doc = __doc__

    def run(self, ctx: LintContext) -> Iterable[Violation]:
        paged = ctx.source(PAGED)
        metrics = ctx.source(METRICS)
        if paged is None or metrics is None:
            return []
        out = self.check_pair(paged, metrics)
        fleetview = ctx.source(FLEETVIEW)
        if fleetview is not None:
            out += self.check_fleet(fleetview, metrics)
        return out

    def check_fleet(self, fleetview: Source, metrics: Source) -> List[Violation]:
        """The r20 fleet-rollup contract: every fleet_rollup() key is
        FLEET_METRICS-mapped or FLEET_EXCLUDED, and no dead mappings."""
        out: List[Violation] = []
        specs = _metric_specs(metrics.tree, "FLEET_METRICS")
        excluded = _set_literal(metrics.tree, "FLEET_EXCLUDED") or set()
        produced = _fleet_rollup_keys(fleetview)
        if not specs or not produced:
            out.append(Violation(
                checker=self.name, code="GL407", path=METRICS, line=1,
                symbol="FLEET_METRICS",
                message=(
                    "could not locate FLEET_METRICS / fleet_rollup keys — "
                    "the fleet contract anchor moved; update "
                    "tools/graftlint/checkers/metrics_contract.py"
                ),
            ))
            return out
        for key in sorted(produced - set(specs) - excluded):
            out.append(Violation(
                checker=self.name, code="GL406", path=FLEETVIEW, line=1,
                symbol=key,
                message=(
                    f"fleet_rollup() emits {key!r} but the fleet bridge "
                    "neither maps it (FLEET_METRICS) nor excludes it "
                    "(FLEET_EXCLUDED) — the aggregate would silently "
                    "skip seldon_tpu_fleet_* export"
                ),
            ))
        for key in sorted((set(specs) | excluded) - produced):
            out.append(Violation(
                checker=self.name, code="GL407", path=METRICS, line=1,
                symbol=key,
                message=(
                    f"{key!r} is fleet-mapped/excluded but fleet_rollup() "
                    "never emits it — dead mapping (or a renamed rollup)"
                ),
            ))
        return out

    def check_pair(self, paged: Source, metrics: Source) -> List[Violation]:
        out: List[Violation] = []
        specs = _metric_specs(metrics.tree, "ENGINE_STATS_METRICS")
        excluded = _set_literal(metrics.tree, "ENGINE_STATS_EXCLUDED") or set()
        produced = _engine_stats_keys(paged)
        slo_keys = _set_literal(paged.tree, "_SLO_COUNTER_KEYS") or set()

        if not specs or not produced:
            out.append(Violation(
                checker=self.name, code="GL402", path=METRICS, line=1,
                symbol="ENGINE_STATS_METRICS",
                message=(
                    "could not locate ENGINE_STATS_METRICS / engine_stats "
                    "keys — the contract anchor moved; update "
                    "tools/graftlint/checkers/metrics_contract.py"
                ),
            ))
            return out

        detail_only = {"recorder", "recorder_stats", "seq"}
        for key in sorted(produced - set(specs) - excluded - detail_only):
            out.append(Violation(
                checker=self.name, code="GL401", path=PAGED, line=1,
                symbol=key,
                message=(
                    f"engine_stats() emits {key!r} but the Prometheus bridge "
                    "neither maps it (ENGINE_STATS_METRICS) nor excludes it "
                    "(ENGINE_STATS_EXCLUDED) — the counter would silently "
                    "skip export"
                ),
            ))
        for key in sorted((set(specs) | excluded) - produced):
            out.append(Violation(
                checker=self.name, code="GL402", path=METRICS, line=1,
                symbol=key,
                message=(
                    f"{key!r} is bridge-mapped/excluded but engine_stats() "
                    "never emits it — dead mapping (or a renamed counter)"
                ),
            ))

        transport_specs = _metric_specs(metrics.tree, "TRANSPORT_METRICS")
        # the r20 additions ride the same naming discipline (fixtures
        # without them contribute nothing — _metric_specs returns {})
        cost_specs = _metric_specs(metrics.tree, "COST_LEDGER_METRICS")
        fleet_specs = _metric_specs(metrics.tree, "FLEET_METRICS")
        # iterate the spec maps SEPARATELY: cost-ledger keys reuse
        # engine-stats key names ("prefill_tokens"), and a dict merge
        # would shadow one mapping's metric name from the naming pass
        for spec_map in (specs, transport_specs, cost_specs, fleet_specs):
            for key, (kind, metric) in sorted(spec_map.items()):
                if not metric.startswith("seldon_tpu_"):
                    out.append(Violation(
                        checker=self.name, code="GL403", path=METRICS, line=1,
                        symbol=metric,
                        message=f"metric {metric!r} (key {key!r}) must carry "
                                "the seldon_tpu_ prefix",
                    ))
                if kind == "counter" and not metric.endswith("_total"):
                    out.append(Violation(
                        checker=self.name, code="GL403", path=METRICS, line=1,
                        symbol=metric,
                        message=f"counter {metric!r} (key {key!r}) must end "
                                "in _total (Prometheus naming)",
                    ))
                if kind == "gauge" and metric.endswith("_total"):
                    out.append(Violation(
                        checker=self.name, code="GL403", path=METRICS, line=1,
                        symbol=metric,
                        message=f"gauge {metric!r} (key {key!r}) must not "
                                "end in _total",
                    ))

        excluded_record = _set_literal(metrics.tree, "TRANSPORT_RECORD_EXCLUDED") or set()
        # internal plumbing kwargs of the recording call, not measurements
        record_plumbing = {"registry", "error"}
        # fields the recorder derives rather than receives (the seconds
        # pair maps the *_s internals) are already TRANSPORT_METRICS keys
        for param, line in _hop_record_params(metrics.tree):
            if param in record_plumbing or param in excluded_record:
                continue
            if param not in transport_specs:
                out.append(Violation(
                    checker=self.name, code="GL405", path=METRICS, line=line,
                    symbol=param,
                    message=(
                        f"record_transport_hop takes {param!r} but "
                        "TRANSPORT_METRICS neither maps it nor "
                        "TRANSPORT_RECORD_EXCLUDED excludes it — the "
                        "per-hop measurement would silently skip "
                        "Prometheus export"
                    ),
                ))

        for key in sorted(slo_keys):
            if key not in produced or specs.get(key, ("", ""))[0] != "counter":
                out.append(Violation(
                    checker=self.name, code="GL404", path=PAGED, line=1,
                    symbol=key,
                    message=(
                        f"_SLO_COUNTER_KEYS entry {key!r} must be an "
                        "engine_stats counter mapped by the bridge — the "
                        "flight recorder threads its per-chunk delta"
                    ),
                ))
        return out


CHECKER = _Checker()
