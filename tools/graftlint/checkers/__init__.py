"""Checker registry.  Every checker module under this package exports a
``CHECKER`` singleton; the suite entrypoint runs exactly this list (a
meta-test asserts the directory and the registry agree, so a new
checker cannot be written and silently never run)."""

from tools.graftlint.checkers.capture_redaction import CHECKER as CAPTURE_REDACTION
from tools.graftlint.checkers.except_hygiene import CHECKER as EXCEPT_HYGIENE
from tools.graftlint.checkers.jit_purity import CHECKER as JIT_PURITY
from tools.graftlint.checkers.knob_registry import CHECKER as KNOB_REGISTRY
from tools.graftlint.checkers.lock_discipline import CHECKER as LOCK_DISCIPLINE
from tools.graftlint.checkers.metrics_contract import CHECKER as METRICS_CONTRACT
from tools.graftlint.checkers.propagation import CHECKER as PROPAGATION

ALL_CHECKERS = (
    JIT_PURITY,
    KNOB_REGISTRY,
    LOCK_DISCIPLINE,
    METRICS_CONTRACT,
    PROPAGATION,
    EXCEPT_HYGIENE,
    CAPTURE_REDACTION,
)

BY_NAME = {c.name: c for c in ALL_CHECKERS}
