"""graftlint — AST-based invariant checkers for seldon-core-tpu.

The codebase's load-bearing invariants (PRs 1-8) exist as conventions:
jitted programs stay host-pure, every knob is declared and documented,
``_*_locked`` helpers only run under the engine lock, engine_stats
counters thread complete-by-contract into the Prometheus bridge, every
ingress mints a deadline and adopts the caller's trace, and hot-path
``except Exception`` blocks justify themselves.  graftlint turns each
convention into a checker over the package's ASTs (stdlib ``ast``, no
dependencies), so regressions fail the tier-1 suite instead of waiting
for a reviewer — run ``python -m tools.graftlint`` or ``make lint``.

See docs/architecture.md "Invariants & linting" for the checker
catalogue and the allowlist/pragma burn-down workflow.
"""

from tools.graftlint.core import (  # noqa: F401
    LintContext,
    Source,
    Violation,
    load_allowlist,
    run_suite,
)
