"""CLI: ``python -m tools.graftlint [--json] [--checker NAME ...]``.

Exit code 0 when the tree is clean (inline pragmas and the allowlist
burn-down file are the only sanctioned suppressions), 1 when any
violation survives, 2 on usage errors.  ``--json`` emits the full
machine-readable result (the same dict the tier-1 test and the bench's
``lint_violations`` key consume).
"""

from __future__ import annotations

import argparse
import json
import os
import sys

# same sys.path bootstrap as every tools/ script: runnable from any cwd
REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
if REPO_ROOT not in sys.path:
    sys.path.insert(0, REPO_ROOT)


def main(argv=None) -> int:
    from tools.graftlint.checkers import ALL_CHECKERS, BY_NAME
    from tools.graftlint.core import run_suite

    parser = argparse.ArgumentParser(
        prog="python -m tools.graftlint",
        description="AST-based invariant checkers for seldon-core-tpu",
    )
    parser.add_argument("--json", action="store_true",
                        help="machine-readable output")
    parser.add_argument("--root", default=REPO_ROOT,
                        help="repo root (default: this checkout)")
    parser.add_argument("--checker", action="append", default=[],
                        metavar="NAME",
                        help="run only the named checker(s); repeatable")
    parser.add_argument("--list", action="store_true",
                        help="list registered checkers and exit")
    args = parser.parse_args(argv)

    if args.list:
        for c in ALL_CHECKERS:
            first = (c.doc or "").strip().splitlines()[0]
            print(f"{c.name:18s} {','.join(c.codes):30s} {first}")
        return 0

    checkers = None
    if args.checker:
        unknown = [n for n in args.checker if n not in BY_NAME]
        if unknown:
            print(f"unknown checker(s): {', '.join(unknown)} "
                  f"(known: {', '.join(sorted(BY_NAME))})", file=sys.stderr)
            return 2
        checkers = [BY_NAME[n] for n in args.checker]

    result = run_suite(args.root, checkers=checkers)
    if args.json:
        print(json.dumps(result, indent=2, sort_keys=True))
    else:
        for v in result["violations"]:
            print(f"{v['path']}:{v['line']}: {v['code']} ({v['checker']})"
                  f"{' [' + v['symbol'] + ']' if v['symbol'] else ''} "
                  f"{v['message']}")
        n = len(result["violations"])
        s = len(result["suppressed"])
        print(
            f"graftlint: {n} violation(s), {s} allowlisted, "
            f"{result['files_scanned']} files, "
            f"{len(result['checkers'])} checkers"
        )
    return 0 if result["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
