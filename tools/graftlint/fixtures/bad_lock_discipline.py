"""Seeded lock-discipline violations (GL301/302).  Never imported."""
import threading


class BadEngine:
    def __init__(self):
        self._lock = threading.Lock()
        self._queue = []
        self._count = 0

    def _pop_locked(self):
        self._count -= 1
        return self._queue.pop()

    def _push_locked(self, item):
        self._queue.append(item)
        self._count += 1

    def good_caller(self, item):
        with self._lock:
            self._push_locked(item)
            return self._pop_locked()

    def bad_caller(self):
        return self._pop_locked()  # GL301: no lock held

    def bad_writer(self):
        self._count = 0  # GL302: lock-guarded state written outside the lock
        self._queue.append("x")  # GL302: container mutation outside the lock

    def good_locked_branch(self, item):
        with self._lock:
            if item:
                self._push_locked(item)  # inside the with: fine
