"""Seeded TRUE-POSITIVE fixtures for the graftlint checker tests.

Each ``bad_*.py`` file contains known violations of exactly one
checker's invariant; tests/test_graftlint.py runs the checker over the
fixture and asserts every seeded violation is caught (a checker that
goes vacuous fails its fixture test, not just silently passes the
tree).  These files are NEVER imported — syntax-valid but semantically
nonsense on purpose.
"""
