"""Seeded exception-hygiene violations (GL601-603).  Never imported."""
import logging

logger = logging.getLogger(__name__)


def swallow_silently(fn):
    # seeded GL601: no raise, no conversion, no justification comment
    # (the comment must sit OFF the except line or it would count)
    try:
        return fn()
    except Exception:
        return None


def log_only(fn):
    try:
        return fn()
    # seeded GL601: a bare noqa code is not a justification
    except Exception:  # noqa: BLE001
        logger.exception("it broke")


def bare(fn):
    try:
        return fn()
    except:  # GL602: bare except
        return None


def base_exc(fn):
    try:
        return fn()
    except BaseException:  # GL603: traps interpreter shutdown
        return None


def reraises_fine(fn):
    try:
        return fn()
    except Exception:
        raise


def converts_fine(fn):
    try:
        return fn()
    except Exception as e:
        return {"status": {"info": str(e), "code": 500}}


def justified_fine(fn):
    try:
        return fn()
    except Exception:  # metrics must never break the data plane
        return None
