"""Seeded jit-purity violations (GL101-105).  Never imported."""
import jax
import jax.numpy as jnp
import numpy as np

COUNTER = {"calls": 0}


def impure(x, y, opts=[1, 2]):  # noqa: B006 — part of the GL105 seed
    if x > 0:  # GL103: host branch on a tracer
        y = y + 1
    z = x * 2
    f = float(z)  # GL101: host cast of a traced value
    v = x.item()  # GL102: host pull
    arr = np.asarray(y)  # GL102: host materialization
    COUNTER["calls"] += 1  # GL104: captured-state mutation
    while y < 0:  # GL103: host loop on a tracer
        y = y + 1
    return jnp.sum(z) + f + v + arr.sum()


jitted = jax.jit(impure, static_argnums=(2,))  # GL105: unhashable static default


class Engine:
    def __init__(self):
        self._hits = 0
        self._fn = jax.jit(self._method)

    def _method(self, x):
        self._hits += 1  # GL104: self-state mutation inside the traced body
        ok = bool(x)  # GL101
        return x * 2, ok


def pure_ok(x, n_steps):
    # all host work here is shape/static math: must NOT be flagged
    b = x.shape[0]
    if b > 4:
        x = x[:4]
    for _ in range(int(n_steps) if isinstance(n_steps, int) else 1):
        x = x * 2
    return jnp.sum(x)


jitted_ok = jax.jit(pure_ok, static_argnames=("n_steps",))
