"""Seeded metrics-contract fixture: the FLEET side (r20).  Paired with
bad_metrics_metrics.py by tests/test_graftlint.py.  Never imported."""


class FakeAggregator:
    def fleet_rollup(self):
        return {
            "t": 0.0,                  # excluded: fine
            "replicas_ok": 0,          # mapped: fine
            "fleet_queue_depth": 0,    # mapped: fine
            "phantom_rollup": 0.0,     # not mapped, not excluded -> GL406
        }
