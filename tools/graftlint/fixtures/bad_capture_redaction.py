"""Seeded-bad fixture for the capture-redaction checker (GL408)."""

from seldon_core_tpu.codec.bufview import pack_capture
from seldon_core_tpu.utils.capture import redact


def bad_writer(payload, path):
    # GL408: serializes for the store without the redaction filter
    blob = pack_capture(payload)
    with open(path, "wb") as f:
        f.write(blob)


def good_writer(payload, path):
    blob = pack_capture(redact(payload))
    with open(path, "wb") as f:
        f.write(blob)


def good_reader(blob):
    # unpack-side code never packs — naturally exempt
    from seldon_core_tpu.codec.bufview import unpack_capture

    return unpack_capture(blob)
