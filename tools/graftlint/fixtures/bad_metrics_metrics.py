"""Seeded metrics-contract fixture: the BRIDGE side.  Never imported."""

ENGINE_STATS_METRICS = {
    "chunks": ("counter", "seldon_tpu_engine_chunks_total", "chunks"),
    "shed": ("counter", "seldon_tpu_engine_shed_total", "shed"),
    "active_slots": ("gauge", "seldon_tpu_engine_slot_occupancy", "slots"),
    # GL402: mapped but the engine never emits it
    "never_emitted": ("counter", "seldon_tpu_engine_never_total", "ghost"),
    # GL403: counter without _total suffix
    "bad_name": ("counter", "seldon_tpu_engine_bad_name", "bad"),
}

ENGINE_STATS_EXCLUDED = {"chunk_wall_s", "bad_name"}

TRANSPORT_METRICS = {
    # GL403: missing the seldon_tpu_ prefix
    "requests": ("counter", "transport_requests_total", "reqs"),
    "zero_copy_bytes": ("counter", "seldon_tpu_transport_zero_copy_bytes_total",
                        "by-reference bytes"),
}

TRANSPORT_RECORD_EXCLUDED = {"unit", "method", "transport", "error"}

COST_LEDGER_METRICS = {
    # GL403: counter without _total suffix (cost-ledger naming rides
    # the same pass)
    "page_seconds": ("counter", "seldon_tpu_engine_cost_adapter_page_seconds",
                     "bad"),
}

FLEET_METRICS = {
    "replicas_ok": ("gauge", "seldon_tpu_fleet_replicas_ok", "ok"),
    "fleet_queue_depth": ("gauge", "seldon_tpu_fleet_queue_depth", "depth"),
    # GL407: fleet-mapped but fleet_rollup never emits it
    "never_rolled": ("gauge", "seldon_tpu_fleet_never", "ghost"),
    # GL403: gauge ending in _total
    "fleet_bad_gauge": ("gauge", "seldon_tpu_fleet_bad_total", "bad"),
}

FLEET_EXCLUDED = {"t"}


def record_transport_hop(
    unit, method, transport, *,
    requests=0,          # clean: TRANSPORT_METRICS maps it
    zero_copy_bytes=0,   # clean: mapped
    ghost_measurement=0,  # GL405: neither mapped nor excluded
    error=False,          # clean: excluded
    registry=None,        # clean: plumbing
):
    """Seeded recording surface — never called."""
    return unit, method, transport, requests, zero_copy_bytes, \
        ghost_measurement, error, registry
