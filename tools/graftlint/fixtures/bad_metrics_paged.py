"""Seeded metrics-contract fixture: the ENGINE side.  Paired with
bad_metrics_metrics.py by tests/test_graftlint.py.  Never imported."""

_SLO_COUNTER_KEYS = ("shed", "ghost_slo_key")  # ghost_slo_key -> GL404


class FakeEngine:
    def __init__(self):
        self._counters = {
            "chunks": 0,
            "shed": 0,
            "unmapped_counter": 0,  # not mapped, not excluded -> GL401
            "chunk_wall_s": 0.0,  # excluded: fine
        }

    def engine_stats(self, detail=False):
        return {
            **self._counters,
            "active_slots": 0,
        }
