"""Seeded propagation violations (GL501-505).  Never imported."""
from seldon_core_tpu.runtime.executor_pool import run_dispatch
from seldon_core_tpu.utils import deadlines as _deadlines
from seldon_core_tpu.utils import tracing as _tracing
from seldon_core_tpu.utils.tracing import activate_context


class _Hop:  # stand-in so the fixture parses standalone
    def __init__(self, *a): ...

    def finish(self, error=False): ...


async def bad_handler(request):
    # GL501 + GL502: dispatches with neither deadline nor trace handling
    body = await request.json()
    return await run_dispatch(lambda: body)


async def good_handler(request):
    with activate_context(None), _deadlines.activate_ms(None):
        _deadlines.check("fixture ingress")
        return await run_dispatch(lambda: None)


class BadClient:
    """GL503/504/505: dispatch method with no hop, no injection, no
    deadline handling."""

    async def transform_input(self, msg):
        return await self._post(msg)

    async def _post(self, msg):
        return msg


class GoodClient:
    async def transform_input(self, msg):
        return await self._call("transform_input", msg)

    async def _call(self, method, msg):
        _deadlines.check("fixture hop")
        headers = _tracing.inject({})
        hop = _Hop("unit", method, "rest")
        try:
            return (msg, headers)
        finally:
            hop.finish()
