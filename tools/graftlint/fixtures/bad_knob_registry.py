"""Seeded knob-registry violations (GL201/202/204).  Never imported."""
import os

from seldon_core_tpu.runtime import knobs

# GL201 + (registered name, so not GL202): direct environ read of a knob
TP = os.environ.get("SELDON_TPU_TP", "")
# GL201 via os.getenv
DBG = os.getenv("SELDON_TPU_PAGED_DEBUG")
# GL201 via subscript
QUEUE = os.environ["SELDON_TPU_MAX_QUEUE"]
# GL201 via a module-level constant name
_MY_KNOB = "SELDON_TPU_PREFIX_CACHE"
PC = os.environ.get(_MY_KNOB)

# GL202: undeclared knob literal (never registered)
MYSTERY = os.environ.get("SELDON_TPU_TOTALLY_UNDECLARED", "1")

# GL202: undeclared annotation / header literals
ANN = "seldon.io/not-a-real-annotation"
HDR = "X-Seldon-Mystery-Header"

# GL204: registry read of an undeclared name
GHOST = knobs.raw("SELDON_TPU_GHOST_KNOB")
