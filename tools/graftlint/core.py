"""graftlint core: source loading, pragmas, the allowlist, the suite.

Design rules every checker follows:

* **One violation = one (checker, path, symbol) identity.**  Line
  numbers churn; the allowlist matches on the stable triple so a
  justified entry survives refactors and a STALE entry (matching
  nothing) is itself reported — burn-down files cannot rot silently.
* **Inline pragmas are for single sites**: ``# graftlint:
  allow[<checker>] — reason`` on the flagged line (or the line above)
  suppresses that site; a pragma with no reason text does not count.
* **Checkers never import the scanned code.**  Everything is stdlib
  ``ast`` over the files; the only runtime import is the knob registry
  (``seldon_core_tpu.runtime.knobs``), which is itself stdlib-only.
"""

from __future__ import annotations

import ast
import os
import re
from dataclasses import dataclass, field
from typing import Any, Dict, Iterable, List, Optional, Sequence

PRAGMA_RE = re.compile(
    r"#\s*graftlint:\s*allow\[(?P<names>[a-z0-9_,\- ]+)\]\s*(?P<reason>.*)"
)

# generated protobuf modules: machine-written, exempt wholesale
GENERATED_SUFFIXES = ("_pb2.py",)

DEFAULT_PACKAGE = "seldon_core_tpu"


@dataclass
class Violation:
    checker: str
    code: str  # e.g. "GL201"
    path: str  # repo-relative, forward slashes
    line: int
    message: str
    symbol: str = ""  # stable identity for allowlisting (qualname, knob, ...)

    def to_dict(self) -> Dict[str, Any]:
        return {
            "checker": self.checker,
            "code": self.code,
            "path": self.path,
            "line": self.line,
            "symbol": self.symbol,
            "message": self.message,
        }

    def render(self) -> str:
        sym = f" [{self.symbol}]" if self.symbol else ""
        return f"{self.path}:{self.line}: {self.code} ({self.checker}){sym} {self.message}"


@dataclass
class Source:
    """One parsed file plus the line-level pragma index."""

    path: str  # repo-relative
    abspath: str
    text: str
    lines: List[str]
    tree: ast.Module

    def pragma_allows(self, line: int, checker: str) -> bool:
        """True when ``line`` (1-based) or the line above carries a
        justified ``graftlint: allow[...]`` pragma naming ``checker``."""
        for ln in (line, line - 1):
            if 1 <= ln <= len(self.lines):
                m = PRAGMA_RE.search(self.lines[ln - 1])
                if m is None:
                    continue
                names = {n.strip() for n in m.group("names").split(",")}
                reason = m.group("reason").strip(" -—:#")
                if checker in names and len(re.sub(r"\W", "", reason)) >= 3:
                    return True
        return False


@dataclass
class LintContext:
    root: str  # repo root (abs)
    sources: List[Source]
    docs_text: str  # concatenated docs/*.md
    extra: Dict[str, Any] = field(default_factory=dict)

    def source(self, rel_path: str) -> Optional[Source]:
        for s in self.sources:
            if s.path == rel_path:
                return s
        return None


def _load_source(root: str, abspath: str) -> Optional[Source]:
    rel = os.path.relpath(abspath, root).replace(os.sep, "/")
    try:
        with open(abspath, encoding="utf-8") as f:
            text = f.read()
        tree = ast.parse(text, filename=rel)
    except (OSError, SyntaxError, ValueError):
        return None
    return Source(path=rel, abspath=abspath, text=text,
                  lines=text.splitlines(), tree=tree)


def collect_sources(root: str, package: str = DEFAULT_PACKAGE) -> List[Source]:
    out: List[Source] = []
    pkg_dir = os.path.join(root, package)
    for dirpath, dirnames, filenames in os.walk(pkg_dir):
        dirnames[:] = sorted(d for d in dirnames if d != "__pycache__")
        for name in sorted(filenames):
            if not name.endswith(".py"):
                continue
            if any(name.endswith(sfx) for sfx in GENERATED_SUFFIXES):
                continue
            src = _load_source(root, os.path.join(dirpath, name))
            if src is not None:
                out.append(src)
    return out


def load_docs_text(root: str) -> str:
    parts = []
    docs_dir = os.path.join(root, "docs")
    if os.path.isdir(docs_dir):
        for name in sorted(os.listdir(docs_dir)):
            if name.endswith(".md"):
                try:
                    with open(os.path.join(docs_dir, name), encoding="utf-8") as f:
                        parts.append(f.read())
                except OSError:
                    pass
    return "\n".join(parts)


# ---------------------------------------------------------------------------
# allowlist (TOML subset: [[allow]] tables of `key = "basic string"`)
# ---------------------------------------------------------------------------

@dataclass
class AllowEntry:
    checker: str
    path: str
    symbol: str
    reason: str
    line: int  # line in allowlist.toml (for stale-entry reporting)
    used: bool = False

    def matches(self, v: Violation) -> bool:
        if self.checker != v.checker or self.path != v.path:
            return False
        return self.symbol in ("", "*") or self.symbol == v.symbol


_TOML_KV = re.compile(r'^\s*([A-Za-z_][A-Za-z0-9_-]*)\s*=\s*"((?:[^"\\]|\\.)*)"\s*(?:#.*)?$')


def load_allowlist(path: str) -> List[AllowEntry]:
    """Parse the graftlint allowlist.

    A deliberately tiny TOML subset (python 3.10 has no tomllib):
    ``[[allow]]`` array-of-tables whose values are basic one-line
    strings.  Anything else in the file is a hard error — a burn-down
    file that half-parses would silently widen the allowlist."""
    entries: List[AllowEntry] = []
    if not os.path.exists(path):
        return entries
    current: Optional[Dict[str, Any]] = None
    with open(path, encoding="utf-8") as f:
        for lineno, raw in enumerate(f, 1):
            line = raw.strip()
            if not line or line.startswith("#"):
                continue
            if line == "[[allow]]":
                current = {"line": lineno}
                entries.append(current)  # type: ignore[arg-type]
                continue
            m = _TOML_KV.match(raw)
            if m and current is not None:
                current[m.group(1)] = (
                    m.group(2).encode().decode("unicode_escape")
                )
                continue
            raise ValueError(
                f"{path}:{lineno}: unparseable allowlist line {line!r} "
                "(supported: [[allow]] tables with key = \"value\")"
            )
    out: List[AllowEntry] = []
    for e in entries:
        if not isinstance(e, dict):
            continue
        reason = str(e.get("reason", "")).strip()
        if not reason:
            raise ValueError(
                f"{path}:{e['line']}: allowlist entry without a reason — "
                "every kept violation carries a one-line justification"
            )
        out.append(AllowEntry(
            checker=str(e.get("checker", "")),
            path=str(e.get("path", "")),
            symbol=str(e.get("symbol", "")),
            reason=reason,
            line=int(e["line"]),
        ))
    return out


# ---------------------------------------------------------------------------
# suite
# ---------------------------------------------------------------------------

def run_suite(
    root: str,
    checkers: Optional[Sequence[Any]] = None,
    allowlist_path: Optional[str] = None,
    package: str = DEFAULT_PACKAGE,
) -> Dict[str, Any]:
    """Run ``checkers`` (default: the full registry) over ``package``
    under ``root``; returns the machine-readable result dict."""
    from tools.graftlint.checkers import ALL_CHECKERS

    active = list(checkers) if checkers is not None else list(ALL_CHECKERS)
    sources = collect_sources(root, package=package)
    ctx = LintContext(root=root, sources=sources,
                      docs_text=load_docs_text(root))
    raw: List[Violation] = []
    for checker in active:
        found = list(checker.run(ctx))
        for v in found:
            src = ctx.source(v.path)
            if src is not None and src.pragma_allows(v.line, v.checker):
                continue
            raw.append(v)

    if allowlist_path is None:
        allowlist_path = os.path.join(
            root, "tools", "graftlint", "allowlist.toml"
        )
    allow = load_allowlist(allowlist_path)
    kept: List[Violation] = []
    suppressed: List[Dict[str, Any]] = []
    for v in raw:
        hit = next((a for a in allow if a.matches(v)), None)
        if hit is not None:
            hit.used = True
            suppressed.append({**v.to_dict(), "reason": hit.reason})
        else:
            kept.append(v)
    active_names = {c.name for c in active}
    for a in allow:
        # staleness is only judged for checkers that actually ran: a
        # --checker subset run must not flag other checkers' entries
        if a.checker not in active_names:
            continue
        if not a.used:
            kept.append(Violation(
                checker="allowlist", code="GL001",
                path=os.path.relpath(allowlist_path, root).replace(os.sep, "/"),
                line=a.line,
                symbol=f"{a.checker}:{a.path}:{a.symbol}",
                message=(
                    "stale allowlist entry matches no current violation — "
                    "delete it (the burn-down shrank, keep the file honest)"
                ),
            ))

    kept.sort(key=lambda v: (v.path, v.line, v.code))
    counts: Dict[str, int] = {}
    for v in kept:
        counts[v.checker] = counts.get(v.checker, 0) + 1
    return {
        "ok": not kept,
        "violations": [v.to_dict() for v in kept],
        "suppressed": suppressed,
        "counts": counts,
        "files_scanned": len(sources),
        "checkers": [c.name for c in active],
    }


# ---------------------------------------------------------------------------
# shared AST helpers used by several checkers
# ---------------------------------------------------------------------------

def call_name(node: ast.AST) -> str:
    """Last dotted component of a call target: ``a.b.c(...)`` -> 'c',
    ``f(...)`` -> 'f', anything else -> ''."""
    if isinstance(node, ast.Call):
        node = node.func
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return ""


def attr_root(node: ast.AST) -> str:
    """Leftmost name of an attribute chain: ``a.b.c`` -> 'a'."""
    while isinstance(node, ast.Attribute):
        node = node.value
    return node.id if isinstance(node, ast.Name) else ""


def str_const(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def iter_funcs(tree: ast.Module) -> Iterable[tuple]:
    """Yield (qualname, func_node, class_node_or_None) for every
    function/method in the module, including nested ones."""
    def walk(node: ast.AST, prefix: str, cls: Optional[ast.ClassDef]):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                q = f"{prefix}{child.name}"
                yield q, child, cls
                yield from walk(child, q + ".", cls)
            elif isinstance(child, ast.ClassDef):
                yield from walk(child, prefix + child.name + ".", child)
            else:
                yield from walk(child, prefix, cls)

    yield from walk(tree, "", None)


def module_constants(tree: ast.Module) -> Dict[str, str]:
    """Module-level ``NAME = "literal"`` string constants."""
    out: Dict[str, str] = {}
    for node in tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            t = node.targets[0]
            v = str_const(node.value)
            if isinstance(t, ast.Name) and v is not None:
                out[t.id] = v
    return out
