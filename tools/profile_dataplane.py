#!/usr/bin/env python
"""Data-plane profiling harness.

Equivalent of the reference's engine profiling image
(reference: testing/profiling/engine/) for the TPU data plane: drives
the in-process predict path under load and reports where request time
goes — cProfile for the Python orchestration layers and (optionally)
a jax profiler trace for the device timeline.

    python tools/profile_dataplane.py [--spec examples/single_model.yaml]
        [--seconds 5] [--concurrency 16] [--jax-trace /tmp/jaxtrace]
        [--top 30]
"""

from __future__ import annotations

import argparse
import asyncio
import cProfile
import io
import os
import pstats
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--spec", default=None, help="deployment spec yaml (default: stub model)")
    parser.add_argument("--seconds", type=float, default=5.0)
    parser.add_argument("--concurrency", type=int, default=16)
    parser.add_argument("--batch", type=int, default=1)
    parser.add_argument("--jax-trace", default=None, help="directory for a jax profiler trace")
    parser.add_argument("--top", type=int, default=30)
    args = parser.parse_args()

    from seldon_core_tpu.controlplane import Deployer, TpuDeployment
    from seldon_core_tpu.runtime.message import InternalMessage

    if args.spec:
        spec = TpuDeployment.load(args.spec)
    else:
        spec = TpuDeployment.from_dict(
            {
                "name": "profile-target",
                "predictors": [
                    {"name": "main", "graph": {"name": "stub", "type": "MODEL",
                                               "implementation": "SIMPLE_MODEL"}}
                ],
            }
        )

    async def drive() -> int:
        deployer = Deployer()
        managed = await deployer.apply(spec)
        payload = np.ones((args.batch, 4), np.float32)
        done = 0
        stop_at = time.perf_counter() + args.seconds

        async def worker():
            nonlocal done
            while time.perf_counter() < stop_at:
                msg = InternalMessage(payload=payload, kind="rawTensor")
                await managed.gateway.predict(msg)
                done += 1

        await asyncio.gather(*(worker() for _ in range(args.concurrency)))
        await deployer.delete(spec.name)
        return done

    if args.jax_trace:
        import jax

        jax.profiler.start_trace(args.jax_trace)

    profiler = cProfile.Profile()
    profiler.enable()
    total = asyncio.run(drive())
    profiler.disable()

    if args.jax_trace:
        import jax

        jax.profiler.stop_trace()
        print(f"jax trace written to {args.jax_trace}", file=sys.stderr)

    out = io.StringIO()
    stats = pstats.Stats(profiler, stream=out)
    stats.sort_stats("cumulative").print_stats(args.top)
    print(out.getvalue())
    print(f"requests={total} qps={total / args.seconds:.1f}")


if __name__ == "__main__":
    main()
