"""Decompose the paged decode step's per-step cost on the real chip.

The serving lane runs ~4 ms/step at 16 streams (d512/L8) while the
decode compute is ~10 us — the step is op-overhead-bound, and the docs
attribute the remaining paged-vs-scan gap to per-step fixed cost
(docs/architecture.md, r4 table).  This harness times the step's
components in isolation, each as a scan over N iterations inside one
jit (one dispatch, one readback — the relay cannot pollute the
per-step number):

  forward   — the paged transformer apply only
  write     — the 2xB-slot DUS pool write only
  sample    — RNG split + sample_batch only
  bookkeep  — the where/mask carry updates only
  full      — the real engine step

Run:  python tools/profile_paged_step.py [--steps 64] [--slots 16]
"""

import argparse
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=64)
    ap.add_argument("--slots", type=int, default=16)
    ap.add_argument("--d-model", type=int, default=512)
    ap.add_argument("--layers", type=int, default=8)
    ap.add_argument("--heads", type=int, default=8)
    ap.add_argument("--vocab", type=int, default=32000)
    ap.add_argument("--page-size", type=int, default=64)
    ap.add_argument("--pages", type=int, default=128)
    ap.add_argument("--max-len", type=int, default=2048)
    ap.add_argument("--repeats", type=int, default=5)
    ap.add_argument("--tp", type=int, default=0,
                    help="tensor-parallel degree (0 = SELDON_TPU_TP "
                    "default, 1 = force single-chip)")
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp

    from seldon_core_tpu.models.paged import PagedEngine
    from seldon_core_tpu.models.transformer import TransformerLM

    lm = TransformerLM(
        vocab_size=args.vocab, d_model=args.d_model,
        num_layers=args.layers, num_heads=args.heads,
        max_len=args.max_len, dtype=jnp.bfloat16)
    init_params = lm.init(
        jax.random.key(0), jnp.zeros((1, 8), jnp.int32))["params"]

    eng = PagedEngine(
        init_params,
        vocab_size=args.vocab,
        d_model=args.d_model,
        num_layers=args.layers,
        num_heads=args.heads,
        max_len=args.max_len,
        page_size=args.page_size,
        num_pages=args.pages,
        max_slots=args.slots,
        steps_per_call=args.steps,
        tp=args.tp or None,
    )

    B, L = args.slots, args.layers
    h, hd = args.heads, args.d_model // args.heads
    params = eng.params
    # match the engine's pool layout (flat (L, pages, ps, d) by default
    # since r5; split (L, pages, ps, h, hd) under kernel mode) AND its
    # sharding — under a TP mesh the chunk program pins heads-sharded
    # pools on its signature, so replicated zeros would pay a reshard
    # copy every timed call.  Created ALREADY sharded (jit with
    # out_shardings, same pattern as shard_decode_state): an eager
    # jnp.zeros would materialise the full pool on one device first.
    def _make_pool(ref):
        return jax.jit(
            lambda: jnp.zeros(ref.shape, ref.dtype),
            out_shardings=ref.sharding,
        )()

    pk = _make_pool(eng.pages_k)
    pv = _make_pool(eng.pages_v)
    logits = jnp.zeros((B, args.vocab), jnp.float32)
    # every slot mid-generation at a distinct length
    lengths = jnp.asarray(
        np.random.default_rng(0).integers(64, 256, size=B), jnp.int32)
    horizon = 8  # pages visible per slot (256/32 rounded up, pow2)
    block_tables = jnp.asarray(
        np.arange(1, B * horizon + 1).reshape(B, horizon) % args.pages,
        jnp.int32)
    keys = jax.random.key_data(
        jax.vmap(jax.random.PRNGKey)(jnp.arange(B, dtype=jnp.uint32)))
    done = jnp.zeros((B,), bool)
    emitted = jnp.zeros((B,), jnp.int32)
    max_new = jnp.full((B,), 10_000, jnp.int32)
    temps = jnp.zeros((B,), jnp.float32)
    top_ks = jnp.zeros((B,), jnp.int32)
    eos_ids = jnp.full((B,), -1, jnp.int32)

    token0 = jnp.zeros((B,), jnp.int32)

    def forward_only(params, pk, pv, lengths):
        def step(carry, _):
            lengths, acc = carry
            new_logits, nk, nv = eng.module.apply(
                {"params": params}, token0[:, None],
                jnp.minimum(lengths[:, None], args.max_len - 1),
                pk, pv, block_tables, lengths,
            )
            # fold outputs into the carry so nothing is dead code
            acc = acc + new_logits[:, 0, 0] + nk.sum() + nv.sum()
            return (lengths + 1, acc), ()

        (lengths, acc), _ = jax.lax.scan(
            step, (lengths, jnp.zeros((B,), jnp.float32)), None,
            length=args.steps)
        return acc

    def write_only(pk, pv, lengths):
        nk = jnp.ones((L, B, 1, h, hd), jnp.bfloat16)
        nv = nk

        def step(carry, _):
            pk, pv, lengths = carry
            pk, pv = eng._write_kv(
                pk, pv, nk, nv, block_tables, lengths,
                jnp.ones((B, 1), bool))
            return (pk, pv, lengths + 1), ()

        (pk, pv, lengths), _ = jax.lax.scan(
            step, (pk, pv, lengths), None, length=args.steps)
        return pk.sum() + pv.sum()

    def sample_only(logits, keys):
        def step(carry, _):
            logits, keys = carry
            typed = jax.random.wrap_key_data(keys)
            split = jax.vmap(jax.random.split)(typed)
            token = eng._sample_batch(logits, split[:, 1], temps, top_ks)
            keys = jax.random.key_data(split[:, 0])
            logits = logits + token[:, None].astype(jnp.float32) * 1e-9
            return (logits, keys), ()

        (logits, keys), _ = jax.lax.scan(
            step, (logits, keys), None, length=args.steps)
        return logits.sum()

    def bookkeep_only(logits, lengths, done, emitted):
        def step(carry, _):
            logits, lengths, done, emitted = carry
            token = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            active = ~done
            token = jnp.where(active, token, eos_ids)
            emitted = emitted + active.astype(jnp.int32)
            done = done | (token == eos_ids) | (emitted >= max_new)
            logits = jnp.where(active[:, None], logits, logits)
            lengths = lengths + active.astype(jnp.int32)
            return (logits, lengths, done, emitted), token

        (logits, lengths, done, emitted), toks = jax.lax.scan(
            step, (logits, lengths, done, emitted), None, length=args.steps)
        return toks.sum() + lengths.sum()

    ident_perm = jnp.arange(args.slots, dtype=jnp.int32)
    full = eng._get_chunk(args.steps, ((args.slots, horizon),))

    def barrier(out):
        # block_until_ready on the axon relay backend returns BEFORE
        # the computation finishes (async futures) — measured: a probe
        # "completed" in 0.06 ms whose value then took 930 ms to fetch.
        # The only honest completion barrier is fetching a value that
        # depends on the computation.
        return np.asarray(jax.tree_util.tree_leaves(out)[0]).ravel()[:1]

    def timed(name, fn, *a, n_steps=None, **kw):
        n_steps = n_steps or args.steps
        barrier(fn(*a, **kw))  # compile + drain
        best = float("inf")
        for _ in range(args.repeats):
            t0 = time.perf_counter()
            barrier(fn(*a, **kw))
            best = min(best, time.perf_counter() - t0)
        per_step_us = best / n_steps * 1e6
        print(f"{name:>12}: {best*1e3:8.2f} ms total  {per_step_us:8.1f} us/step"
              f"  ({args.slots/best*n_steps:,.0f} tok/s)")
        return best

    print(f"B={B} L={L} d={args.d_model} steps={args.steps} "
          f"tp={eng.tp_degree} (one dispatch per timing; relay excluded)")
    timed("forward", jax.jit(forward_only), params, pk, pv, lengths)
    timed("write", jax.jit(write_only), pk, pv, lengths)
    timed("sample", jax.jit(sample_only), logits, keys)
    timed("bookkeep", jax.jit(bookkeep_only), logits, lengths, done, emitted)
    # full chunk donates pk/pv; pass copies so reruns stay valid
    def full_fresh():
        return full(params, jnp.copy(pk), jnp.copy(pv), logits, lengths,
                    block_tables, keys, done, emitted, max_new, temps,
                    top_ks, eos_ids, ident_perm)
    timed("full", full_fresh)

    # -------- two-point slope: the session degrades to a fixed
    # ~100 ms per-dispatch penalty once any real program compiles
    # (see docs/architecture.md "session dispatch modes"), so a single
    # timing conflates per-call and per-step cost.  Marginal per-step
    # cost = (t(4N) - t(N)) / 3N; the intercept is the per-call
    # penalty.  This is the number kernel work should attack.
    print("\ntwo-point marginal per-step cost (relay per-call term removed):")
    hi = 4 * args.steps

    def slope(name, build):
        t_lo = timed(f"{name}@{args.steps}", *build(args.steps))
        t_hi = timed(f"{name}@{hi}", *build(hi), n_steps=hi)
        per_step = (t_hi - t_lo) / (hi - args.steps)
        print(f"{name:>10}: {per_step*1e6:8.1f} us/step marginal, "
              f"{(t_lo - per_step*args.steps)*1e3:6.1f} ms per-call intercept"
              f"  ({args.slots/per_step:,.0f} tok/s asymptotic)")

    def build_forward(n):
        def fo(params, pk, pv, lengths):
            def step(carry, _):
                lengths, acc = carry
                new_logits, nk, nv = eng.module.apply(
                    {"params": params}, token0[:, None],
                    jnp.minimum(lengths[:, None], args.max_len - 1),
                    pk, pv, block_tables, lengths,
                )
                acc = acc + new_logits[:, 0, 0] + nk.sum() + nv.sum()
                return (lengths + 1, acc), ()

            (lengths, acc), _ = jax.lax.scan(
                step, (lengths, jnp.zeros((B,), jnp.float32)), None, length=n)
            return acc
        return jax.jit(fo), params, pk, pv, lengths

    def build_full(n):
        fn = eng._get_chunk(n, ((args.slots, horizon),))

        def run():
            return fn(params, jnp.copy(pk), jnp.copy(pv), logits, lengths,
                      block_tables, keys, done, emitted, max_new, temps,
                      top_ks, eos_ids, ident_perm)
        return (run,)

    slope("forward", build_forward)
    slope("full", build_full)


if __name__ == "__main__":
    main()
