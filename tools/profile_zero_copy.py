"""Per-stage cost of the three payload lanes: proto vs JSON vs buffer-view.

For one request tensor, times each stage a payload passes between the
ingress bytes and the model call — parse (wire container), decode
(payload -> ndarray), device_put (host -> HBM staging), dispatch (the
jitted model call) — and counts the bytes COPIED inside Python at each
stage.  The buffer-view (SRT1) lane's parse/decode stages are
header-only + `np.frombuffer` views, so its copied-bytes column is the
lane's whole argument (docs/architecture.md §9a):

    python tools/profile_zero_copy.py --rows 32 --feat 1024 --iters 300

Prints one table; run on CPU (`JAX_PLATFORMS=cpu`) for the host-side
story or on the TPU host for true device_put numbers.
"""

from __future__ import annotations

import argparse
import base64
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _pct(vals, q=0.5):
    vals = sorted(vals)
    return vals[max(0, int(q * len(vals)) - 1)] * 1e6  # us


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--rows", type=int, default=32)
    ap.add_argument("--feat", type=int, default=1024)
    ap.add_argument("--dtype", default="float32")
    ap.add_argument("--iters", type=int, default=300)
    ap.add_argument("--model", default="mlp",
                    help="jaxserver model for the dispatch stage")
    args = ap.parse_args()

    import numpy as np

    from seldon_core_tpu import codec
    from seldon_core_tpu.codec import bufview
    from seldon_core_tpu.models.jaxserver import JaxServer
    from seldon_core_tpu.proto import pb

    rng = np.random.default_rng(7)
    x = rng.normal(size=(args.rows, args.feat)).astype(codec.np_dtype(args.dtype))
    nbytes = x.nbytes

    # ---- wire bodies ------------------------------------------------------
    req = pb.SeldonMessage()
    req.data.rawTensor.dtype = x.dtype.name
    req.data.rawTensor.shape.extend(x.shape)
    req.data.rawTensor.data = x.tobytes()
    proto_bytes = req.SerializeToString()
    json_bytes = json.dumps({"data": {"rawTensor": {
        "shape": list(x.shape), "dtype": x.dtype.name,
        "data": base64.b64encode(x.tobytes()).decode(),
    }}}).encode()
    frame = bufview.pack_frame(x)

    server = JaxServer(
        model=args.model, num_classes=8, input_shape=(args.feat,),
        dtype="float32", warmup_dtypes=(x.dtype.name,),
        max_batch_size=max(args.rows, 1), warmup=True,
    )
    server.load()

    lanes = {}

    # proto lane: FromString copies the payload into the message; the
    # frombuffer decode is a view over those message bytes
    def proto_stages():
        t0 = time.perf_counter()
        m = pb.SeldonMessage.FromString(proto_bytes)
        t1 = time.perf_counter()
        arr = codec.raw_tensor_to_array(m.data.rawTensor)
        t2 = time.perf_counter()
        return (t1 - t0, t2 - t1), arr

    # JSON lane: json parse + base64 decode (one full copy) + frombuffer
    def json_stages():
        t0 = time.perf_counter()
        body = json.loads(json_bytes)
        t1 = time.perf_counter()
        rt = body["data"]["rawTensor"]
        arr = np.frombuffer(
            base64.b64decode(rt["data"]), dtype=rt["dtype"]
        ).reshape(rt["shape"])
        t2 = time.perf_counter()
        return (t1 - t0, t2 - t1), arr

    # buffer-view lane: header-only parse, view decode — zero copies
    def view_stages():
        t0 = time.perf_counter()
        view = bufview.unpack_frame(frame)
        t1 = time.perf_counter()
        arr = view.array()
        t2 = time.perf_counter()
        return (t1 - t0, t2 - t1), arr

    copied = {
        "proto": {"parse": nbytes, "decode": 0},
        "json": {"parse": len(json_bytes), "decode": nbytes},
        "bufview": {"parse": 0, "decode": 0},
    }

    for name, fn in (("proto", proto_stages), ("json", json_stages),
                     ("bufview", view_stages)):
        parse_t, decode_t, put_t, disp_t = [], [], [], []
        for _ in range(args.iters):
            (tp, td), arr = fn()
            t0 = time.perf_counter()
            dev = codec.to_device(arr)
            dev.block_until_ready()
            t1 = time.perf_counter()
            out = server.raw_batch_call(arr)
            t2 = time.perf_counter()
            parse_t.append(tp)
            decode_t.append(td)
            put_t.append(t1 - t0)
            disp_t.append(t2 - t1)
            del out, dev
        lanes[name] = {
            "parse": parse_t, "decode": decode_t,
            "device_put": put_t, "dispatch": disp_t,
        }

    hdr = (f"{'lane':9s} {'stage':11s} {'p50 us':>10s} {'p99 us':>10s} "
           f"{'copied B/req':>13s}")
    print(f"\npayload: {x.shape} {x.dtype.name} = {nbytes} bytes "
          f"(proto body {len(proto_bytes)}B, json body {len(json_bytes)}B, "
          f"frame {len(frame)}B)\n")
    print(hdr)
    print("-" * len(hdr))
    for name, stages in lanes.items():
        for stage, vals in stages.items():
            cp = copied[name].get(stage, nbytes if stage == "device_put" else 0)
            print(f"{name:9s} {stage:11s} {_pct(vals, 0.5):10.1f} "
                  f"{_pct(vals, 0.99):10.1f} {cp:13d}")
        total50 = sum(_pct(v, 0.5) for v in stages.values())
        print(f"{name:9s} {'TOTAL':11s} {total50:10.1f}")
        print("-" * len(hdr))
    v50 = sum(_pct(v, 0.5) for v in lanes["bufview"].values())
    p50 = sum(_pct(v, 0.5) for v in lanes["proto"].values())
    j50 = sum(_pct(v, 0.5) for v in lanes["json"].values())
    print(f"\nbufview vs proto: {p50 / v50:.2f}x   bufview vs json: {j50 / v50:.2f}x")
    server.unload()
    return 0


if __name__ == "__main__":
    sys.exit(main())
