"""Repo tooling: profiling harnesses (``profile_*.py``) and the
``tools.graftlint`` static-analysis suite (``python -m tools.graftlint``)."""
