"""Op-level conv/matmul efficiency probe — where ResNet-50's MFU goes.

Methodology (hard-won on this harness, see docs/architecture.md
"dispatch modes" + the honest-barrier note): value-fetch completion
barriers, and loops long enough (seconds of device time) that the
~100 ms per-dispatch penalty and its variance cannot produce negative
two-point slopes.  Chained ops (y = conv(y, w)) keep every iteration
data-dependent so XLA cannot hoist the work out of the loop.

Measured on TPU v5 lite (2026-07-31, r4):

    matmul 4096:              93% of 197 TF/s peak   (the chip is fine)
    3x3 conv c=128..512:      95-98%                 (XLA convs are fine)
    3x3 conv c=64 @56:        76%                    (half-lane channels)
    1x1 conv c=256 @56:       21%  <- bandwidth-bound: arithmetic
        intensity 128 flop/byte vs the 240 flop/byte roofline knee
        puts this op's ceiling at ~53% MFU regardless of codegen

ResNet-50's composite 23% MFU is therefore a mix of near-peak 3x3s and
bandwidth-bound 1x1s/elementwise — the remaining headroom is memory
behaviour (layout/fusion of the 1x1 chain), not MXU scheduling.

Run:  python tools/profile_conv.py
"""

import time


def main():
    import jax
    import jax.numpy as jnp

    def fetch(x):
        return float(x)

    def probe_matmul(n, iters=32):
        a = jax.random.normal(jax.random.key(0), (n, n), jnp.bfloat16) * 0.01
        b = jax.random.normal(jax.random.key(1), (n, n), jnp.bfloat16) * 0.01

        def run(a, b, it):
            def body(i, x):
                return (x @ b) * (1.0 / n)

            return jax.lax.fori_loop(0, it, body, a)[0, 0].astype(jnp.float32)

        rj = jax.jit(run)
        fetch(rj(a, b, 4))
        t0 = time.perf_counter(); fetch(rj(a, b, 4)); d1 = time.perf_counter() - t0
        t0 = time.perf_counter(); fetch(rj(a, b, 4 + iters)); d2 = time.perf_counter() - t0
        dt = (d2 - d1) / iters
        tf = 2 * n ** 3 / dt / 1e12
        print(f"matmul {n}x{n}: {dt*1e3:7.3f} ms  {tf:6.1f} TF/s")

    def probe_conv(name, batch, hw, c, k, iters):
        x = jax.random.normal(jax.random.key(0), (batch, hw, hw, c), jnp.bfloat16) * 0.1
        w = jax.random.normal(
            jax.random.key(1), (k, k, c, c), jnp.bfloat16
        ) * (1.0 / (k * k * c) ** 0.5)
        dn = jax.lax.conv_dimension_numbers(x.shape, w.shape, ("NHWC", "HWIO", "NHWC"))

        def run(x, w, it):
            def body(i, y):
                return jax.lax.conv_general_dilated(
                    y, w, (1, 1), "SAME", dimension_numbers=dn
                )

            return jax.lax.fori_loop(0, it, body, x)[0, 0, 0, 0].astype(jnp.float32)

        rj = jax.jit(run)
        fetch(rj(x, w, iters // 8))
        t0 = time.perf_counter(); fetch(rj(x, w, iters // 8)); d1 = time.perf_counter() - t0
        t0 = time.perf_counter(); fetch(rj(x, w, iters)); d2 = time.perf_counter() - t0
        dt = (d2 - d1) / (iters - iters // 8)
        flops = 2 * batch * hw * hw * c * c * k * k
        # NHWC activation read + write, bf16
        traffic = 2 * batch * hw * hw * c * 2 * 2
        tf = flops / dt / 1e12
        gbs = traffic / dt / 1e9
        print(f"{name:>26}: {dt*1e3:7.3f} ms  {tf:6.1f} TF/s  {gbs:5.0f} GB/s act-traffic")

    B = 128
    probe_matmul(4096)
    probe_conv("3x3 c=64 @56 (stage1)", B, 56, 64, 3, 4000)
    probe_conv("3x3 c=128 @28 (stage2)", B, 28, 128, 3, 8000)
    probe_conv("3x3 c=256 @14 (stage3)", B, 14, 256, 3, 8000)
    probe_conv("3x3 c=512 @7 (stage4)", B, 7, 512, 3, 8000)
    probe_conv("1x1 c=256 @56", B, 56, 256, 1, 4000)
    probe_conv("1x1 c=1024 @14", B, 14, 1024, 1, 8000)


if __name__ == "__main__":
    main()
