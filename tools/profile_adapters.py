"""Profile the batched multi-LoRA lane (r16, §5b-quinquies): per-wave
adapter-mix timing, the grouped-matmul-vs-per-adapter-loop contrast,
and the HLO audit that adapter gathering adds NO collectives under TP.

Three sections:

1. **adapter-mix table** — the serving protocol at K = 0..max distinct
   adapters across the lanes (K=0 is the adapter-less baseline on the
   SAME adapter-enabled engine): one row per mix with tok/s, waves,
   `multi_adapter_chunks`, and the jit-compile count — which must NOT
   grow with K (any mix is ONE compiled program; the Punica property).
2. **grouped vs per-adapter-loop** — the same K-adapter workload served
   (a) mixed in one engine wave-set (the grouped gather) vs (b) as K
   sequential per-adapter batches (what per-adapter bucketing would
   do).  The grouped lane's win is wave occupancy: K sparse batches
   decode at 1/K occupancy each.
3. **HLO collective audit** (``--tp N``) — lowers the chunk program
   through the engine's own `lower_chunk` with adapters ON and OFF and
   diffs the collective counts.  The pinned invariant (also
   tests/test_lora.py): adapters add ZERO gather/scatter-class
   collectives — factors shard with their base layer, so no activation
   ever reshards — and the only additions are all-reduces over RANK-r
   intermediates (a row-parallel input contracting into the r-dim),
   whose bytes are r/d_model of one base megatron reduce (~3% at r=8,
   d=256).  Single-chip hosts print the zero-collective baseline
   instead of crashing.

Run:  python tools/profile_adapters.py [--adapters 4] [--rank 8]
      [--slots 8] [--d-model 256] [--layers 4] [--new 64] [--tp 2]
"""

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

from tools.profile_paged_tp import collective_counts


def build(args, max_adapters, tp=1):
    import jax
    import jax.numpy as jnp

    from seldon_core_tpu.models.paged import PagedEngine
    from seldon_core_tpu.models.transformer import TransformerLM
    from seldon_core_tpu.ops.lora import adapter_bytes, make_lora_params
    from seldon_core_tpu.models.registry import WeightRegistry

    cfg = dict(
        vocab_size=args.vocab, d_model=args.d_model,
        num_layers=args.layers, num_heads=args.heads, max_len=args.max_len,
    )
    lm = TransformerLM(dtype=jnp.bfloat16, **cfg)
    params = lm.init(jax.random.key(0), jnp.zeros((1, 8), jnp.int32))["params"]
    reg = None
    if max_adapters:
        reg = WeightRegistry(budget_bytes=0)
        for i in range(args.adapters):
            ad = make_lora_params(
                500 + i, num_layers=args.layers, d_model=args.d_model,
                rank=args.rank,
            )
            reg.register(f"ad{i}", (lambda a=ad: a),
                         bytes_hint=adapter_bytes(ad))
    eng = PagedEngine(
        params, dtype=jnp.bfloat16, page_size=64, max_slots=args.slots,
        steps_per_call=8, max_steps_per_call=64, tp=tp or 1,
        max_adapters=max_adapters, lora_rank=args.rank,
        weight_registry=reg,
        # prefix cache OFF: per-adapter chain roots make cache-hit
        # patterns depend on the MIX, so hit/miss group compositions
        # would compile new suffix-prefill shapes and muddy the
        # one-program claim — which is about the DECODE wave
        prefix_cache=False, **cfg,
    )
    return eng, cfg


def prompts_for(args, cfg, seed=3):
    rng = np.random.default_rng(seed)
    return [
        rng.integers(0, cfg["vocab_size"], size=(64 + (i % 4) * 16,)).astype(
            np.int32
        )
        for i in range(args.slots)
    ]


def serve(eng, prompts, new, select):
    streams = [
        eng.submit(p, max_new_tokens=new, adapter=select(i))
        for i, p in enumerate(prompts)
    ]
    eng.run()
    return sum(int(s.result.shape[0]) for s in streams)


def best_of(n, fn):
    best = float("inf")
    for _ in range(n):
        t0 = time.perf_counter()
        fn()
        best = min(best, time.perf_counter() - t0)
    return best


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--adapters", type=int, default=4)
    ap.add_argument("--rank", type=int, default=8)
    ap.add_argument("--slots", type=int, default=8)
    ap.add_argument("--d-model", type=int, default=256)
    ap.add_argument("--layers", type=int, default=4)
    ap.add_argument("--heads", type=int, default=8)
    ap.add_argument("--vocab", type=int, default=8192)
    ap.add_argument("--max-len", type=int, default=512)
    ap.add_argument("--new", type=int, default=64)
    ap.add_argument("--tp", type=int, default=0,
                    help="also audit the TP=N lowering (needs devices)")
    args = ap.parse_args()

    eng, cfg = build(args, max_adapters=args.adapters)
    prompts = prompts_for(args, cfg)
    K_max = min(args.adapters, args.slots)

    # ---- 1. adapter-mix table ------------------------------------------
    print(f"== adapter-mix table ({args.slots} lanes x {args.new} new, "
          f"rank {args.rank}) ==")
    print(f"{'K':>3} {'tok/s':>10} {'waves':>6} {'multi_waves':>11} "
          f"{'jit_compiles':>12}")
    tok_s_by_k = {}
    mix_table_compiles = None
    try:
        for K in range(0, K_max + 1):
            select = (lambda i, K=K: f"ad{i % K}" if K else None)
            serve(eng, prompts, args.new, select)  # warm (compiles + loads)
            s0 = eng.engine_stats()
            dt = best_of(3, lambda: serve(eng, prompts, args.new, select))
            s1 = eng.engine_stats()
            total = args.slots * args.new
            tok_s_by_k[K] = total / dt
            if K == 1:
                # every program is compiled by here; K>1 must add none
                mix_table_compiles = s1["jit_compiles"]
            print(f"{K:>3} {total / dt:>10.1f} "
                  f"{(s1['chunks'] - s0['chunks']) // 3:>6} "
                  f"{(s1['multi_adapter_chunks'] - s0['multi_adapter_chunks']) // 3:>11} "
                  f"{s1['jit_compiles']:>12}")
        compiles_end = eng.engine_stats()["jit_compiles"]

        # ---- 2. grouped vs per-adapter-loop ----------------------------
        K = K_max
        select = lambda i: f"ad{i % K}"
        grouped_dt = best_of(3, lambda: serve(eng, prompts, args.new, select))

        def per_adapter_loop():
            # what per-adapter bucketing would do: K sequential sparse
            # batches, each lane-set at 1/K occupancy
            for k in range(K):
                lanes = [p for i, p in enumerate(prompts) if i % K == k]
                streams = [
                    eng.submit(p, max_new_tokens=args.new, adapter=f"ad{k}")
                    for p in lanes
                ]
                eng.run()
                for s in streams:
                    assert s.result is not None

        per_adapter_loop()  # warm the sparse-occupancy programs
        loop_dt = best_of(3, per_adapter_loop)
        print(f"\n== grouped vs per-adapter-loop (K={K}) ==")
        print(f"  grouped (one mixed wave-set): {grouped_dt * 1e3:9.1f} ms")
        print(f"  per-adapter loop ({K} passes): {loop_dt * 1e3:9.1f} ms")
        print(f"  grouped speedup: {loop_dt / grouped_dt:.2f}x")
        one_program = compiles_end == mix_table_compiles
        print(f"  mixes beyond K=1 compiled new programs: "
              f"{'NO (one grouped program)' if one_program else 'YES (BUG)'}")
        print(f"  adapter stats: {eng.adapter_stats()['requests']}")
    finally:
        eng.close()

    # ---- 3. HLO collective audit ---------------------------------------
    import jax

    tp = args.tp if args.tp and len(jax.devices()) >= args.tp else 1
    print(f"\n== HLO collective audit (tp={tp}) ==")
    audited = {}
    for adapters_on in (0, args.adapters):
        eng, _ = build(args, max_adapters=adapters_on, tp=tp)
        try:
            spec = ((args.slots, 2),)
            compiled = eng.lower_chunk(8, spec).compile()
            try:
                hlo = compiled.as_text()
            except Exception:  # noqa: BLE001 — older jax spelling
                hlo = "\n".join(
                    m.to_string()
                    for m in compiled.runtime_executable().hlo_modules()
                )
            counts = collective_counts(hlo)
            audited[adapters_on] = counts
            label = f"adapters={adapters_on or 'off'}"
            print(f"  chunk[{label}]: {sum(counts.values())} collectives "
                  f"({dict(counts) if counts else 'none'})")
        finally:
            eng.close()
    if tp > 1:
        off_c, on_c = audited[0], audited[args.adapters]
        added_other = sum(
            on_c[op] - off_c.get(op, 0)
            for op in on_c if op != "all-reduce"
        )
        added_ar = on_c.get("all-reduce", 0) - off_c.get("all-reduce", 0)
        verdict = "PASS" if added_other == 0 else "FAIL"
        print(f"  adapter delta: +{added_ar} all-reduce (rank-{args.rank} "
              f"intermediates — ~{args.rank / args.d_model:.1%} of a base "
              f"reduce's bytes each), +{added_other} gather/scatter-class "
              f"[{verdict}: the latter must be 0 — factors shard with "
              "their base layer, nothing reshards]")
    else:
        print("  single-chip: both lowerings carry zero collectives by "
              "construction; rerun with --tp 2 on a multi-device host")


if __name__ == "__main__":
    main()
