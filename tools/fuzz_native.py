"""Malformed-input fuzzing of the native C++ core (SURVEY §5.2).

Drives the codec hot loops (b64, JSON number parsing, batch gather) and
the front server's HTTP/protocol parser with adversarial inputs.  Run
against a sanitizer build to turn silent memory bugs into reports:

    make -C native asan
    SELDON_TPU_NATIVE_SO=native/libseldon_tpu_native_asan.so \
        python tools/fuzz_native.py --iterations 2000

Exit code 0 = survived; any ASan report aborts the process (that is the
point).  tests/test_sanitizers.py runs a budgeted version of this in CI
fashion; the reference's equivalent is its Java/Go race and fuzz test
tiers (SURVEY §5.2).
"""

from __future__ import annotations

import argparse
import os
import random
import socket
import string
import sys

# runnable as `python tools/fuzz_native.py` from anywhere
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def fuzz_codecs(iterations: int, seed: int) -> int:
    from seldon_core_tpu import native

    if not native.available():
        print("native library unavailable; nothing to fuzz", file=sys.stderr)
        return 1
    rng = random.Random(seed)
    printable = string.printable
    b64ish = string.ascii_letters + string.digits + "+/=\n\r "
    for i in range(iterations):
        n = rng.randrange(0, 512)
        case = i % 4
        if case == 0:  # arbitrary bytes into the b64 decoder
            text = "".join(rng.choice(printable) for _ in range(n))
        elif case == 1:  # base64 alphabet but wrong padding/length
            text = "".join(rng.choice(b64ish) for _ in range(n))
        elif case == 2:  # valid encode, then corrupt
            raw = bytes(rng.randrange(256) for _ in range(n))
            text = native.b64encode(raw)
            if text:
                pos = rng.randrange(len(text))
                text = text[:pos] + rng.choice(printable) + text[pos + 1:]
        else:  # truncation
            raw = bytes(rng.randrange(256) for _ in range(n))
            text = native.b64encode(raw)[: rng.randrange(0, max(n, 1))]
        try:
            native.b64decode(text)
        except Exception:  # noqa: BLE001 — rejection is fine; crashing is not
            pass

        # JSON float-array parser: malformed numbers, nesting, junk
        frags = ["[", "]", ",", "-", ".", "e", "E", "+", "1", "9", "0",
                 "nan", "inf", "null", '"x"', "{", "}", " "]
        text = "".join(rng.choice(frags) for _ in range(rng.randrange(0, 64)))
        try:
            native.parse_f64_array(text)
        except Exception:  # noqa: BLE001
            pass
    print(f"codec fuzz: {iterations} iterations survived")
    return 0


def fuzz_frontserver(iterations: int, seed: int) -> int:
    """Raw socket garbage at the front server's HTTP parser."""
    from seldon_core_tpu.native.frontserver import NativeFrontServer

    rng = random.Random(seed)
    with NativeFrontServer(stub=True, feature_dim=4, out_dim=3) as srv:
        for i in range(iterations):
            kind = i % 5
            if kind == 0:  # pure garbage
                payload = bytes(rng.randrange(256) for _ in range(rng.randrange(1, 256)))
            elif kind == 1:  # plausible request line, broken headers
                payload = (
                    b"POST /predict HTTP/1.1\r\nContent-Length: "
                    + str(rng.randrange(-5, 1 << 32)).encode()
                    + b"\r\n\r\n" + b"A" * rng.randrange(0, 64)
                )
            elif kind == 2:  # huge/negative lengths and truncated bodies
                payload = (
                    b"POST /predict HTTP/1.1\r\nContent-Length: 999999999\r\n\r\n{}"
                )
            elif kind == 3:  # header folding / missing terminator
                payload = b"GET /metrics HTTP/1.1\r\nX-Junk: " + b"\xff" * 64
            else:  # valid-ish JSON with broken tensor bodies
                body = ('{"data":{"tensor":{"shape":[' +
                        ",".join(str(rng.randrange(-4, 9)) for _ in range(3)) +
                        '],"values":[' + "1," * rng.randrange(0, 8) + "}}}" )
                payload = (
                    b"POST /predict HTTP/1.1\r\nContent-Length: "
                    + str(len(body)).encode() + b"\r\n\r\n" + body.encode()
                )
            try:
                with socket.create_connection(("127.0.0.1", srv.port), timeout=1) as s:
                    s.sendall(payload)
                    s.settimeout(0.5)
                    try:
                        s.recv(4096)
                    except OSError:
                        pass
            except OSError:
                pass
        # the server must still answer a well-formed request afterwards
        import json
        import urllib.request

        req = urllib.request.Request(
            f"http://127.0.0.1:{srv.port}/predict",
            data=json.dumps({"data": {"tensor": {"shape": [1, 4], "values": [1, 2, 3, 4]}}}).encode(),
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=5) as resp:
            assert resp.status == 200
    print(f"frontserver fuzz: {iterations} iterations survived, server still sane")
    return 0


def fuzz_h2(iterations: int, seed: int) -> int:
    """Adversarial HTTP/2 frames + HPACK blocks at the h2c gRPC lane.

    The h2 path parses attacker-controlled frame headers, HPACK
    integers/strings/Huffman, and protobuf wire format — every one a
    classic memory-bug surface.  Strategies: random frames after a
    valid preface, truncated/oversized declared lengths, mutated HPACK
    blocks, mutated gRPC/proto payloads, and mid-frame connection cuts.
    """
    from seldon_core_tpu.native import get_lib
    from seldon_core_tpu.native.frontserver import (
        NativeFrontServer,
        build_grpc_request_parts,
    )

    if not hasattr(get_lib(), "lg_run_h2"):
        print("h2 fuzz: native lib lacks lg_run_h2 (stale build?); skipping",
              file=sys.stderr)
        return 0

    rng = random.Random(seed)
    preface = b"PRI * HTTP/2.0\r\n\r\nSM\r\n\r\n"

    def frame(ftype, flags, sid, payload: bytes) -> bytes:
        n = len(payload)
        return (bytes([(n >> 16) & 0xFF, (n >> 8) & 0xFF, n & 0xFF,
                       ftype & 0xFF, flags & 0xFF,
                       (sid >> 24) & 0x7F, (sid >> 16) & 0xFF,
                       (sid >> 8) & 0xFF, sid & 0xFF]) + payload)

    # a valid request to mutate
    block, data = build_grpc_request_parts(
        "/seldon.protos.Seldon/Predict",
        bytes.fromhex("1a0a0a08120612041a020104"),  # tiny-ish proto-ish bytes
    )

    with NativeFrontServer(stub=True, feature_dim=4, out_dim=3) as srv:
        for i in range(iterations):
            kind = i % 6
            if kind == 0:  # random frames
                payload = preface + b"".join(
                    frame(rng.randrange(0, 12), rng.randrange(256),
                          rng.randrange(0, 1 << 31),
                          bytes(rng.randrange(256) for _ in range(rng.randrange(0, 64))))
                    for _ in range(rng.randrange(1, 6))
                )
            elif kind == 1:  # declared length lies (truncated payload)
                n = rng.randrange(1, 1 << 20)
                hdr = bytes([(n >> 16) & 0xFF, (n >> 8) & 0xFF, n & 0xFF,
                             rng.randrange(0, 10), rng.randrange(256), 0, 0, 0, 1])
                payload = preface + hdr + b"x" * rng.randrange(0, 128)
            elif kind == 2:  # mutated HPACK block in HEADERS
                b = bytearray(block)
                for _ in range(rng.randrange(1, 8)):
                    b[rng.randrange(len(b))] = rng.randrange(256)
                payload = preface + frame(0x1, 0x4 | 0x1, 1, bytes(b))
            elif kind == 3:  # valid HEADERS, mutated gRPC DATA
                d = bytearray(data)
                for _ in range(rng.randrange(1, 8)):
                    d[rng.randrange(len(d))] = rng.randrange(256)
                payload = (preface + frame(0x1, 0x4, 1, bytes(block))
                           + frame(0x0, 0x1, 1, bytes(d)))
            elif kind == 4:  # HPACK integer/string bombs
                bomb = bytes([0x1F] + [0xFF] * rng.randrange(1, 12)) + \
                       bytes([0x7F] + [0xFF] * rng.randrange(1, 12))
                payload = preface + frame(0x1, 0x4 | 0x1, 1, bomb)
            else:  # truncated preface / mid-frame cut
                full = preface + frame(0x1, 0x4, 1, bytes(block))
                payload = full[: rng.randrange(1, len(full))]
            try:
                with socket.create_connection(("127.0.0.1", srv.port), timeout=1) as s:
                    s.sendall(payload)
                    s.settimeout(0.3)
                    try:
                        s.recv(4096)
                    except OSError:
                        pass
            except OSError:
                pass

        # the server must still serve a well-formed gRPC request afterwards
        from seldon_core_tpu.native.frontserver import native_load_grpc
        from seldon_core_tpu.proto import pb

        req = pb.SeldonMessage()
        req.data.tensor.shape.extend([1, 4])
        req.data.tensor.values.extend([1.0, 2.0, 3.0, 4.0])
        out = native_load_grpc(srv.port, "/seldon.protos.Seldon/Predict",
                               req.SerializeToString(), seconds=1.0,
                               connections=1, depth=2)
        assert out and out["ok"] > 0, f"h2 lane dead after fuzzing: {out}"
    print(f"h2 fuzz: {iterations} iterations survived, gRPC lane still sane")
    return 0


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--iterations", type=int, default=2000)
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--target", choices=("codecs", "frontserver", "h2", "all"), default="all")
    args = parser.parse_args(argv)
    rc = 0
    if args.target in ("codecs", "all"):
        rc |= fuzz_codecs(args.iterations, args.seed)
    if args.target in ("frontserver", "all"):
        rc |= fuzz_frontserver(max(args.iterations // 10, 50), args.seed)
    if args.target in ("h2", "all"):
        rc |= fuzz_h2(max(args.iterations // 10, 50), args.seed)
    return rc


if __name__ == "__main__":
    sys.exit(main())
