"""Server-side dynamic batching."""

from seldon_core_tpu.batching.batcher import (  # noqa: F401
    BatcherStats,
    DynamicBatcher,
    MultiSignatureBatcher,
    bucket_for,
    default_buckets,
    normalize_buckets,
)
