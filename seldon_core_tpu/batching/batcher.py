"""Server-side dynamic batching for jit-compiled models.

XLA compiles one program per input shape, so per-request ragged batch
sizes would either retrace constantly or serialise requests.  The
batcher solves both:

* concurrent requests are coalesced into one device call (row-wise
  concatenation), up to ``max_batch_size`` rows or ``max_wait_ms`` of
  queueing delay, whichever comes first;
* the coalesced batch is padded up to a fixed **bucket** size
  (powers of two by default), so the jit cache holds exactly
  ``len(buckets)`` compiled programs — no retracing in steady state;
* results are sliced back per request, padding rows discarded.

The reference has no equivalent (its engine forwards one request per
hop; concurrency came from replica pods).  This is the component that
turns the <10 ms p50 latency target and high QPS/chip into the same
design problem: keep the MXU fed with large batches without holding
any single request longer than the wait budget.

The execution is a **two-stage pipeline**: a collector thread coalesces
requests and *launches* the device call (XLA dispatch is async), then
immediately starts an async device->host copy of the result and hands
the in-flight batch to a finisher pool; finishers materialise results
and resolve request futures.  Collection of batch N+1 overlaps the
device compute and the host copy of batch N (and host-copy latencies of
several in-flight batches overlap each other), so throughput is set by
the slowest stage, not the sum — crucial when device->host readback has
a high fixed latency, as it does both over PCIe-attached hosts and in
this harness's relayed-TPU setup.

Thread-based on purpose: model calls arrive from worker threads (the
server runs user dispatch via ``asyncio.to_thread``) and XLA execution
releases the GIL, so the pipeline threads drive the device while
request threads only block on their own future.
"""

from __future__ import annotations

import logging
import queue
import threading
import time
from collections import deque
from concurrent.futures import Future
from dataclasses import dataclass
from typing import Any, Callable, List, Optional, Sequence

import numpy as np

logger = logging.getLogger(__name__)


def default_buckets(max_batch_size: int) -> List[int]:
    """Powers of two up to max_batch_size (always includes it)."""
    buckets: List[int] = []
    b = 1
    while b < max_batch_size:
        buckets.append(b)
        b *= 2
    buckets.append(max_batch_size)
    return sorted(set(buckets))


def normalize_buckets(buckets: Optional[Sequence[int]], max_batch_size: int) -> List[int]:
    """Canonical bucket list: sorted, deduped, capped at and always
    ending with ``max_batch_size``.  Both batchers and the jaxserver
    warmup must agree on this list — warming the raw user-supplied
    buckets would leave the forced final bucket uncompiled and the
    first full batch would pay an XLA trace mid-traffic."""
    if max_batch_size < 1:
        raise ValueError("max_batch_size must be >= 1")
    out = sorted(set(buckets)) if buckets else default_buckets(max_batch_size)
    if out[-1] != max_batch_size:
        out = [b for b in out if b < max_batch_size] + [max_batch_size]
    return out


def bucket_for(n: int, buckets: Sequence[int]) -> int:
    for b in buckets:
        if n <= b:
            return b
    return buckets[-1]


@dataclass
class _WorkItem:
    x: np.ndarray  # [rows, ...]
    rows: int
    future: Future
    enqueued_at: float


class BatcherStats:
    def __init__(self, reservoir: int = 8192) -> None:
        self.requests = 0
        self.batches = 0
        self.rows = 0
        self.padded_rows = 0
        # server-side latency reservoirs (ms), newest-wins ring buffers:
        # wait = enqueue -> device launch; total = enqueue -> result set
        # (arrival->response inside the serving process, the histogram
        # client RTT cannot give).  Appends are atomic, but ITERATION
        # concurrent with appends raises "deque mutated during
        # iteration" — readers and writers share _lat_lock
        self._lat_lock = threading.Lock()
        self.wait_ms: "deque[float]" = deque(maxlen=reservoir)
        self.total_ms: "deque[float]" = deque(maxlen=reservoir)

    def record_wait(self, ms: float) -> None:
        with self._lat_lock:
            self.wait_ms.append(ms)

    def record_total(self, ms: float) -> None:
        with self._lat_lock:
            self.total_ms.append(ms)

    def latency_snapshot(self) -> tuple:
        """Consistent copies of both reservoirs (safe under traffic)."""
        with self._lat_lock:
            return list(self.wait_ms), list(self.total_ms)

    def observe(self, batch_requests: int, rows: int, padded: int) -> None:
        self.requests += batch_requests
        self.batches += 1
        self.rows += rows
        self.padded_rows += padded

    @property
    def mean_batch_rows(self) -> float:
        return self.rows / self.batches if self.batches else 0.0

    def latency_summary(self) -> dict:
        """Percentiles of the in-process arrival->response histogram
        (and of queue wait alone).  Empty dict when nothing recorded."""
        wait, total = self.latency_snapshot()
        if not total:
            return {}
        total.sort()
        wait.sort()

        def pct(sorted_vals, q):
            if not sorted_vals:
                return None
            # nearest-rank: ceil(q*n)-1 — int(q*n) reads one order
            # statistic high (p99 of 100 samples would be the max)
            import math

            idx = max(0, math.ceil(q * len(sorted_vals)) - 1)
            return round(sorted_vals[idx], 3)

        return {
            "p50_ms": pct(total, 0.50),
            "p90_ms": pct(total, 0.90),
            "p99_ms": pct(total, 0.99),
            "wait_p50_ms": pct(wait, 0.50),
            "wait_p99_ms": pct(wait, 0.99),
            "count": len(total),
        }


class DynamicBatcher:
    """Coalesces row-batched requests into padded-bucket device calls.

    `predict_fn(batch) -> batch_out` must accept a leading batch dim and
    preserve row order; typically a jitted model apply.
    """

    def __init__(
        self,
        predict_fn: Callable[[np.ndarray], Any],
        max_batch_size: int = 64,
        max_wait_ms: float = 2.0,
        buckets: Optional[Sequence[int]] = None,
        name: str = "batcher",
        pipeline_depth: int = 16,
        finisher_threads: int = 12,
    ):
        self.predict_fn = predict_fn
        self.max_batch_size = max_batch_size
        self.max_wait_s = max_wait_ms / 1000.0
        self.buckets = normalize_buckets(buckets, max_batch_size)
        self.name = name
        self.stats = BatcherStats()
        self._queue: "queue.Queue[Optional[_WorkItem]]" = queue.Queue()
        # deferred item that would overflow the current batch (collector
        # thread only — no locking needed)
        self._carry: Optional[_WorkItem] = None
        # bounded: backpressure when `pipeline_depth` batches are in flight
        self._inflight: "queue.Queue[Optional[tuple]]" = queue.Queue(maxsize=pipeline_depth)
        self._thread: Optional[threading.Thread] = None
        self._finishers: List[threading.Thread] = []
        self.finisher_threads = finisher_threads
        self._running = False

    # ---------------------------------------------------------------- public

    def start(self) -> None:
        if self._running:
            return
        self._running = True
        self._thread = threading.Thread(target=self._loop, daemon=True, name=f"seldon-tpu-{self.name}")
        self._thread.start()
        self._finishers = [
            threading.Thread(target=self._finish_loop, daemon=True, name=f"seldon-tpu-{self.name}-fin{i}")
            for i in range(self.finisher_threads)
        ]
        for t in self._finishers:
            t.start()

    def stop(self) -> None:
        if not self._running:
            return
        self._running = False
        self._queue.put(None)
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
        if self._carry is not None:  # deferred item must not hang its caller
            self._carry.future.set_exception(
                RuntimeError(f"batcher {self.name!r} stopped")
            )
            self._carry = None
        for _ in self._finishers:
            self._inflight.put(None)
        for t in self._finishers:
            t.join(timeout=5.0)
        self._finishers = []

    def submit_future(self, x: np.ndarray) -> Future:
        """Enqueue one request batch [rows, ...]; returns its Future
        without blocking (async servers await it, no thread pinned)."""
        if not self._running:
            raise RuntimeError(f"batcher {self.name!r} not started")
        x = np.asarray(x)
        if x.ndim < 1:
            raise ValueError("batcher input must have a leading batch dimension")
        item = _WorkItem(x=x, rows=x.shape[0], future=Future(), enqueued_at=time.perf_counter())
        self._queue.put(item)
        return item.future

    def submit(self, x: np.ndarray, timeout_s: float = 30.0):
        """Blocking submit of one request batch [rows, ...]; returns [rows, ...out]."""
        return self.submit_future(x).result(timeout=timeout_s)

    # ---------------------------------------------------------------- worker

    def _collect(self) -> Optional[List[_WorkItem]]:
        """Block for the first item, then fill until bucket/deadline.

        A row-batched request that would push the coalesced batch PAST
        ``max_batch_size`` is carried over to the next batch instead of
        merged: two already-full batches concatenated would form an
        oversized shape no warmup ever compiled, stalling the dispatch
        thread on a mid-traffic jit trace.  (A single oversized request
        still gets its honest full-size call — only merging is capped.)
        """
        first = self._carry if self._carry is not None else self._queue.get()
        self._carry = None
        if first is None:
            return None
        items = [first]
        rows = first.rows
        deadline = time.perf_counter() + self.max_wait_s
        while rows < self.max_batch_size:
            remaining = deadline - time.perf_counter()
            if remaining <= 0:
                break
            try:
                item = self._queue.get(timeout=remaining)
            except queue.Empty:
                break
            if item is None:
                self._queue.put(None)  # re-signal shutdown for the outer loop
                break
            if rows + item.rows > self.max_batch_size:
                self._carry = item
                break
            items.append(item)
            rows += item.rows
        return items

    def _launch_batch(self, items: List[_WorkItem]) -> None:
        """Stage 1 (collector thread): pad, launch, start async readback."""
        rows = sum(it.rows for it in items)
        bucket = bucket_for(rows, self.buckets)
        if rows > bucket:  # oversized single request: honest full-size call
            bucket = rows
        padded = bucket - rows
        arrays = [it.x for it in items]
        homogeneous = all(
            a.dtype == arrays[0].dtype and a.shape[1:] == arrays[0].shape[1:] for a in arrays[1:]
        )
        if homogeneous and (len(arrays) > 1 or padded):
            from seldon_core_tpu import native

            batch = native.gather_pad(arrays, bucket)  # one-pass C++ gather+pad
        else:
            batch = arrays[0] if len(arrays) == 1 else np.concatenate(arrays, axis=0)
            if padded:
                pad_width = [(0, padded)] + [(0, 0)] * (batch.ndim - 1)
                batch = np.pad(batch, pad_width)
        out = self.predict_fn(batch)  # async XLA dispatch: returns immediately
        if hasattr(out, "copy_to_host_async"):
            out.copy_to_host_async()  # overlap readback with later batches
        self.stats.observe(len(items), rows, padded)
        launched = time.perf_counter()
        for it in items:
            self.stats.record_wait((launched - it.enqueued_at) * 1000.0)
        self._inflight.put((items, out))

    def _finish_loop(self) -> None:
        """Stage 2 (finisher pool): materialise results, resolve futures.
        Several finishers run so the fixed device->host latency of
        consecutive batches overlaps."""
        while True:
            entry = self._inflight.get()
            if entry is None:
                return
            items, out = entry
            try:
                out = np.asarray(out)
                done = time.perf_counter()
                offset = 0
                for it in items:
                    it.future.set_result(out[offset : offset + it.rows])
                    offset += it.rows
                    self.stats.record_total((done - it.enqueued_at) * 1000.0)
            except Exception as e:  # noqa: BLE001 — propagate to every caller
                logger.exception("batch readback failed")
                for it in items:
                    if not it.future.done():
                        it.future.set_exception(e)

    def _loop(self) -> None:
        while self._running:
            items = self._collect()
            if items is None:
                break
            try:
                self._launch_batch(items)
            except Exception as e:  # noqa: BLE001 — propagate to every caller
                logger.exception("batch launch failed")
                for it in items:
                    if not it.future.done():
                        it.future.set_exception(e)

    def __enter__(self) -> "DynamicBatcher":
        self.start()
        return self

    def __exit__(self, *exc) -> None:
        self.stop()


class MultiSignatureBatcher:
    """Per-(dtype, trailing-shape) dynamic batching for multi-signature models.

    One served model may legitimately accept several input signatures —
    a transformer served at multiple context-length buckets, or mixed
    uint8/float32 image payloads.  XLA compiles one program per
    signature regardless, so giving each signature its own queue adds
    nothing to the compile cache while letting each signature coalesce
    independently; mixing them in one queue would force a flush (and a
    small-batch device call) on every signature change in the arrival
    stream.

    Signature groups are created lazily on first sight and capped at
    ``max_signatures`` (each group owns a collector thread and a
    finisher pool); an over-cap signature is rejected rather than
    silently degrading into unbounded thread growth — mirroring how the
    jit cache itself must be bounded on a serving host.
    """

    def __init__(
        self,
        predict_fn: Callable[[np.ndarray], Any],
        max_batch_size: int = 64,
        max_wait_ms: float = 2.0,
        buckets: Optional[Sequence[int]] = None,
        name: str = "batcher",
        pipeline_depth: int = 16,
        finisher_threads: int = 4,
        max_signatures: int = 16,
    ):
        self.predict_fn = predict_fn
        self.max_batch_size = max_batch_size
        self.max_wait_ms = max_wait_ms
        # normalize eagerly so construction fails fast on a bad
        # max_batch_size and callers (warmup) see the canonical list
        self.buckets = normalize_buckets(buckets, max_batch_size)
        self.name = name
        self.pipeline_depth = pipeline_depth
        self.finisher_threads = finisher_threads
        self.max_signatures = max_signatures
        self._groups: dict[tuple, DynamicBatcher] = {}
        self._lock = threading.Lock()
        self._running = False

    # ---------------------------------------------------------------- public

    def start(self) -> None:
        with self._lock:
            self._running = True
            for g in self._groups.values():
                g.start()

    def stop(self) -> None:
        with self._lock:
            self._running = False
            groups = list(self._groups.values())
        for g in groups:
            g.stop()

    def signature_of(self, x: np.ndarray) -> tuple:
        return (x.dtype.str, tuple(x.shape[1:]))

    def submit_future(self, x: np.ndarray) -> Future:
        x = np.asarray(x)
        if x.ndim < 1:
            raise ValueError("batcher input must have a leading batch dimension")
        key = self.signature_of(x)
        # resolve the group AND submit under one lock: a concurrent
        # stop() between the two would otherwise surface as the inner
        # group's RuntimeError instead of this batcher's rejection
        with self._lock:
            if not self._running:
                raise RuntimeError(f"batcher {self.name!r} not started")
            group = self._groups.get(key)
            if group is None:
                if len(self._groups) >= self.max_signatures:
                    raise ValueError(
                        f"batcher {self.name!r}: signature {key} would exceed "
                        f"max_signatures={self.max_signatures} "
                        f"(seen: {sorted(self._groups)})"
                    )
                group = DynamicBatcher(
                    self.predict_fn,
                    max_batch_size=self.max_batch_size,
                    max_wait_ms=self.max_wait_ms,
                    buckets=self.buckets,
                    name=f"{self.name}[{key[0]}{'x'.join(map(str, key[1]))}]",
                    pipeline_depth=self.pipeline_depth,
                    finisher_threads=self.finisher_threads,
                )
                group.start()
                self._groups[key] = group
            return group.submit_future(x)

    def submit(self, x: np.ndarray, timeout_s: float = 30.0):
        return self.submit_future(x).result(timeout=timeout_s)

    @property
    def signatures(self) -> List[tuple]:
        with self._lock:
            return sorted(self._groups)

    @property
    def stats(self) -> BatcherStats:
        """Aggregate stats over all signature groups."""
        with self._lock:
            groups = list(self._groups.values())
        # reservoir sized to hold EVERY group's samples: aggregating N
        # full groups into a default-size ring would silently evict all
        # but the last-iterated signature's latencies
        agg = BatcherStats(reservoir=max(1, len(groups)) * 8192)
        for g in groups:
            agg.requests += g.stats.requests
            agg.batches += g.stats.batches
            agg.rows += g.stats.rows
            agg.padded_rows += g.stats.padded_rows
            gw, gt = g.stats.latency_snapshot()
            agg.wait_ms.extend(gw)
            agg.total_ms.extend(gt)
        return agg

    def __enter__(self) -> "MultiSignatureBatcher":
        self.start()
        return self

    def __exit__(self, *exc) -> None:
        self.stop()
