"""Synchronous gRPC front server — the low-latency ingress.

grpc.aio schedules every request through the event loop; profiling the
serving path on a small host showed asyncio callback dispatch as the
top cost line, and a thread-pool (sync) gRPC server with direct
dispatch measured ~2x the QPS.  This server serves the external
``Seldon`` service from the C-core's thread pool:

* single-local-MODEL predictors take the **fast path** —
  ``PredictorService.predict_sync`` on the handler thread (the thread
  blocks on the dynamic batcher; XLA and gRPC C code hold no GIL);
* multi-node graphs and feedback bridge into the deployment's asyncio
  loop via ``run_coroutine_threadsafe`` (full engine semantics).

This is the role the reference gives its Java engine's Tomcat/Netty
front ends; the C++ front server planned in ROADMAP.md replaces the
Python handler layer next.
"""

from __future__ import annotations

import asyncio
import logging
from concurrent import futures
from typing import Optional

import grpc
import numpy as np

from seldon_core_tpu.proto import pb, services
from seldon_core_tpu.runtime.message import InternalFeedback, InternalMessage

logger = logging.getLogger(__name__)

DEFAULT_MAX_MSG_BYTES = 512 * 1024 * 1024


class SyncSeldonService:
    def __init__(self, gateway, loop: asyncio.AbstractEventLoop, issuer=None):
        self.gateway = gateway
        self.loop = loop
        self.issuer = issuer  # utils.auth.TokenIssuer when oauth is on

    def _bridge(self, coro):
        return asyncio.run_coroutine_threadsafe(coro, self.loop).result()

    def _check_auth(self, context) -> None:
        if self.issuer is not None and not self.issuer.verify_grpc(context):
            from seldon_core_tpu.utils.auth import UNAUTHENTICATED_MSG

            context.abort(grpc.StatusCode.UNAUTHENTICATED, UNAUTHENTICATED_MSG)

    def predict(self, request: pb.SeldonMessage, context) -> pb.SeldonMessage:
        self._check_auth(context)
        from seldon_core_tpu.engine.service import failure_message
        from seldon_core_tpu.runtime.component import MicroserviceError
        from seldon_core_tpu.runtime.grpc_server import (
            _grpc_deadline_ms,
            _grpc_remote_ctx,
        )
        from seldon_core_tpu.utils import deadlines as _deadlines
        from seldon_core_tpu.utils.tracing import activate_context

        msg = InternalMessage.from_proto(request)
        prio = _deadlines.extract_priority(context.invocation_metadata() or ())
        if prio is not None and "priority" not in msg.meta.tags:
            msg.meta.tags["priority"] = prio
        svc = self.gateway.pick()
        for shadow in self.gateway.shadows:
            # isolated copy: primary and shadow both mutate meta
            asyncio.run_coroutine_threadsafe(shadow.predict(msg.copy()), self.loop)
        # extraction happens on the handler thread; the bridged lane
        # re-activates INSIDE the coroutine because
        # run_coroutine_threadsafe does not carry the submitting
        # thread's contextvars into the loop task (the deadline budget
        # rides the same re-activation)
        ctx = _grpc_remote_ctx(context)
        budget_ms = _grpc_deadline_ms(context)
        # mint the ABSOLUTE expiry here, once: the bridged lane crosses
        # a thread hand-off, and re-minting from a duration there would
        # silently refund the queueing time
        budget = _deadlines.Deadline.after_ms(budget_ms) if budget_ms is not None else None
        try:
            if svc.single_local_model() is not None:
                with activate_context(ctx), _deadlines.activate(budget):
                    _deadlines.check("gateway grpc ingress Seldon/Predict")
                    out = svc.predict_sync(msg)
            else:
                async def _predict_with_ctx():
                    with activate_context(ctx), _deadlines.activate(budget):
                        _deadlines.check("gateway grpc ingress Seldon/Predict")
                        return await svc.predict(msg)

                out = self._bridge(_predict_with_ctx())
        except MicroserviceError as e:  # ingress fast-fail (DEADLINE_EXCEEDED)
            out = failure_message(e, msg.meta.puid)
        return self.gateway.finalize_response(out, msg, svc).to_proto()

    def send_feedback(self, request: pb.Feedback, context) -> pb.SeldonMessage:
        self._check_auth(context)
        from seldon_core_tpu.engine.service import failure_message
        from seldon_core_tpu.runtime.component import MicroserviceError
        from seldon_core_tpu.runtime.grpc_server import (
            _grpc_deadline_ms,
            _grpc_remote_ctx,
        )
        from seldon_core_tpu.utils import deadlines as _deadlines
        from seldon_core_tpu.utils.tracing import activate_context

        fb = InternalFeedback.from_proto(request)
        # same ingress contract as predict: absolute expiry minted on
        # the handler thread, re-activated inside the bridged coroutine
        # (run_coroutine_threadsafe drops contextvars)
        ctx = _grpc_remote_ctx(context)
        budget_ms = _grpc_deadline_ms(context)
        budget = _deadlines.Deadline.after_ms(budget_ms) if budget_ms is not None else None

        async def _feedback_with_ctx():
            with activate_context(ctx), _deadlines.activate(budget):
                _deadlines.check("gateway grpc ingress Seldon/SendFeedback")
                return await self.gateway.send_feedback(fb)

        try:
            out = self._bridge(_feedback_with_ctx())
        except MicroserviceError as e:  # ingress fast-fail (DEADLINE_EXCEEDED)
            out = failure_message(
                e, fb.request.meta.puid if fb.request else ""
            )
        return out.to_proto()

    def generate_stream(self, request: pb.SeldonMessage, context):
        """Token streaming (server-streaming ``Seldon/GenerateStream``):
        one prompt in, a SeldonMessage of newly decoded token ids out
        per engine chunk.  Served when the picked predictor is a single
        local model whose component implements ``predict_stream``
        (STREAMING_LM does); anything else is UNIMPLEMENTED with
        guidance — graph semantics for mid-stream transformers don't
        exist in the contract."""
        self._check_auth(context)
        from seldon_core_tpu.runtime.component import MicroserviceError

        msg = InternalMessage.from_proto(request)
        svc = self.gateway.pick()
        fast = svc.single_local_model()
        component = fast[1] if fast is not None else None
        gen_fn = getattr(component, "predict_stream", None)
        if gen_fn is None:
            context.abort(
                grpc.StatusCode.UNIMPLEMENTED,
                "GenerateStream needs a single-local-model predictor whose "
                "component implements predict_stream (e.g. STREAMING_LM)",
            )
        meta = {"tags": dict(msg.meta.tags), "puid": msg.meta.puid}
        import time as _mono_time

        from seldon_core_tpu.utils import deadlines as _deadlines

        md = context.invocation_metadata() or ()
        # absolute expiry minted AT ingress (in-process lane, monotonic
        # is a valid carrier): a relative tag re-minted at submit would
        # refund the hand-off/queueing time
        stream_ms = _deadlines.extract_ms(md)
        if stream_ms is not None:
            meta["tags"].setdefault(
                "deadline_at_monotonic", _mono_time.monotonic() + stream_ms / 1000.0
            )
        stream_prio = _deadlines.extract_priority(md)
        if stream_prio is not None:
            meta["tags"].setdefault("priority", stream_prio)
        stream_adapter = _deadlines.extract_adapter(md)
        if stream_adapter:
            meta["tags"].setdefault("adapter", stream_adapter)
        it = gen_fn(msg.array(), [], meta=meta)
        try:
            for chunk in it:
                out = InternalMessage(
                    payload=np.asarray(chunk)[None, :], kind="ndarray"
                )
                out.meta.puid = msg.meta.puid
                yield out.to_proto()
        except MicroserviceError as e:
            context.abort(
                grpc.StatusCode.INVALID_ARGUMENT
                if 400 <= e.status_code < 500 else grpc.StatusCode.INTERNAL,
                str(e),
            )
        finally:
            # client cancel/disconnect: closing the component generator
            # runs its finally-clause, cancelling the engine stream so
            # an abandoned request stops holding a slot
            it.close()

    def predict_stream(self, request_iterator, context):
        """Chunked predict: reassemble on the handler thread, run the
        ordinary predict path, stream the reply back in chunks.  Bounded
        by the stream lane's own total-size cap."""
        self._check_auth(context)  # fail before buffering the stream
        parts = []
        total = 0
        for chunk in request_iterator:
            total += len(chunk.data)
            if total > services.STREAM_MAX_BYTES:
                context.abort(
                    grpc.StatusCode.RESOURCE_EXHAUSTED,
                    f"stream exceeds {services.STREAM_MAX_BYTES} bytes",
                )
            parts.append(chunk.data)
        request = pb.SeldonMessage.FromString(b"".join(parts))
        reply = self.predict(request, context)
        yield from services.chunk_message(reply)


def build_sync_seldon_server(
    gateway,
    loop: asyncio.AbstractEventLoop,
    max_workers: int = 64,
    max_message_bytes: int = DEFAULT_MAX_MSG_BYTES,
    auth=None,
) -> grpc.Server:
    issuer = None
    if auth is not None:
        from seldon_core_tpu.utils.auth import TokenIssuer

        issuer = TokenIssuer(auth)
    service = SyncSeldonService(gateway, loop, issuer=issuer)
    server = grpc.server(
        futures.ThreadPoolExecutor(max_workers=max_workers, thread_name_prefix="seldon-grpc"),
        options=[
            ("grpc.max_send_message_length", max_message_bytes),
            ("grpc.max_receive_message_length", max_message_bytes),
        ],
    )
    server.add_generic_rpc_handlers(
        (
            services.generic_handler(
                "Seldon",
                {
                    "Predict": service.predict,
                    "SendFeedback": service.send_feedback,
                    "PredictStream": service.predict_stream,
                    "GenerateStream": service.generate_stream,
                },
            ),
        )
    )
    return server
