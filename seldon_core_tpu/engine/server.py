"""Orchestrator front server: external REST + gRPC around predictors.

The ingress-facing shell of the data plane, equivalent to the reference
engine's controllers (reference: RestClientController.java:127-268,
SeldonGrpcServer.java:30-60, SeldonService.java:30-67):

    POST /api/v0.1/predictions   POST /api/v0.1/feedback
    GET  /ping /ready /live      PUT/POST /pause /unpause
    GET  /metrics
    gRPC seldon.protos.Seldon/Predict, /SendFeedback

A ``Gateway`` fronts one *deployment* = several predictors with traffic
weights (canary / A-B across predictors, the reference's Istio
VirtualService weight semantics,
reference: seldondeployment_controller.go:171-239) plus optional shadow
traffic (reference: ambassador.go:50-133).
"""

from __future__ import annotations

import asyncio
import logging
import random
import threading
from collections import OrderedDict
from typing import Dict, List, Optional, Sequence, Tuple

import grpc
from aiohttp import web

from seldon_core_tpu.engine.service import PredictorService, failure_message
from seldon_core_tpu.proto import pb, services
from seldon_core_tpu.runtime.component import MicroserviceError
from seldon_core_tpu.runtime.message import InternalFeedback, InternalMessage
from seldon_core_tpu.runtime.rest import _error_response, _request_body

logger = logging.getLogger(__name__)


class Gateway:
    """Weighted traffic split across predictors of one deployment."""

    def __init__(
        self,
        predictors: Sequence[Tuple[PredictorService, float]],
        shadows: Sequence[PredictorService] = (),
        seed: Optional[int] = None,
        supervisor=None,
        request_logger=None,
    ):
        if not predictors:
            raise ValueError("gateway needs at least one predictor")
        # gateway-level request/response pair sink (r21): the
        # `seldon.io/request-logger` annotation lands here — one logger
        # sees every FINALIZED pair regardless of which predictor
        # served it (the per-predictor loggers inside PredictorService
        # see pre-routing graph traffic instead).  Pairs are stamped
        # with puid + traceparent + cost by utils/reqlogger.build_pair.
        self.request_logger = request_logger
        # the Supervisor owning this deployment's remote workers (None
        # when every node is in-process): /debug/workers reads through
        # it so the breaker/alert layer can see a restart-exhausted
        # (silently dead) worker instead of inferring it from absence
        self.supervisor = supervisor
        self.entries: List[Tuple[PredictorService, float]] = list(predictors)
        total = sum(w for _, w in self.entries)
        if total <= 0:  # all-zero weights -> uniform
            self.entries = [(p, 1.0) for p, _ in self.entries]
            total = float(len(self.entries))
        self._weights = [w / total for _, w in self.entries]
        self.shadows = list(shadows)
        self._rng = random.Random(seed)
        # puid -> serving predictor name, so feedback can be routed to
        # the predictor that actually served the request (reference
        # semantics: PredictiveUnitBean.java:206-246 follows the
        # recorded routing; broadcasting would teach every predictor's
        # MAB from traffic it never saw).  Bounded FIFO eviction.
        self._served: "OrderedDict[str, str]" = OrderedDict()
        self._served_cap = 65536
        self._served_lock = threading.Lock()

    def _record_served(self, puid: str, predictor: str) -> None:
        if not puid:
            return
        with self._served_lock:
            self._served[puid] = predictor
            while len(self._served) > self._served_cap:
                self._served.popitem(last=False)

    def finalize_response(self, response: InternalMessage, request: InternalMessage,
                          svc: PredictorService) -> InternalMessage:
        """Stamp the serving predictor on the response and record the
        puid→predictor mapping — single helper shared by the async and
        sync ingress paths so they cannot drift.  The tag is assigned
        unconditionally: a request may arrive with a stale client-echoed
        `predictor` tag that would otherwise misroute feedback."""
        response.meta.tags["predictor"] = svc.name
        self._record_served(response.meta.puid or request.meta.puid, svc.name)
        return response

    def _feedback_target(self, feedback: InternalFeedback) -> Optional[PredictorService]:
        """The predictor that served the request, if identifiable: by
        the `predictor` response tag, else by the recorded puid.  An
        unresolvable tag (renamed/removed predictor, garbage client
        tag) falls through to the puid lookup rather than giving up."""
        for msg in (feedback.response, feedback.request):
            if msg is None:
                continue
            name = msg.meta.tags.get("predictor")
            if name:
                svc = self.by_name(str(name))
                if svc is not None:
                    return svc
            if msg.meta.puid:
                with self._served_lock:
                    name = self._served.get(msg.meta.puid)
                if name:
                    svc = self.by_name(name)
                    if svc is not None:
                        return svc
        return None

    @property
    def predictors(self) -> List[PredictorService]:
        return [p for p, _ in self.entries]

    def pick(self) -> PredictorService:
        r = self._rng.random()
        acc = 0.0
        for (svc, _), w in zip(self.entries, self._weights):
            acc += w
            if r < acc:
                return svc
        return self.entries[-1][0]

    def by_name(self, name: str) -> Optional[PredictorService]:
        for svc in self.predictors:
            if svc.name == name:
                return svc
        return None

    async def predict(self, request: InternalMessage, predictor: Optional[str] = None) -> InternalMessage:
        svc = self.by_name(predictor) if predictor else None
        if svc is None:
            svc = self.pick()
        # shadow traffic: fire-and-forget isolated copies, responses
        # dropped — the primary and shadows each mutate their own meta
        # (puid assignment), never a shared one
        for shadow in self.shadows:
            asyncio.ensure_future(shadow.predict(request.copy()))
        response = await svc.predict(request)
        response = self.finalize_response(response, request, svc)
        if self.request_logger is not None:
            # buffered sinks return immediately; the JSONL sink does
            # one small write — either way a logging failure must lose
            # a pair, never a request
            try:
                self.request_logger(request, response)
            except Exception:  # noqa: BLE001 — lose a pair, never a request
                logger.exception("gateway request logger failed")
        return response

    async def send_feedback(self, feedback: InternalFeedback) -> InternalMessage:
        # feedback goes ONLY to the predictor that served the request
        # (predictor tag or recorded puid).  Unidentifiable feedback is
        # a counted drop — never a broadcast: the reference follows the
        # recorded routing path and nothing else
        # (reference: PredictiveUnitBean.java:206-246); broadcasting
        # would teach every predictor's bandit from traffic it never
        # served, silently corrupting A/B statistics.
        target = self._feedback_target(feedback)
        if target is None and len(self.entries) == 1 and not self._has_identifiers(feedback):
            # single-predictor gateway AND the feedback never carried a
            # tag/puid (the reference client's bare request-only shape):
            # the route is unambiguous.  Feedback whose identifiers
            # FAILED to resolve (stale tag from a removed predictor,
            # evicted puid) still drops — it may belong to a predictor
            # that no longer exists here.
            target = self.entries[0][0]
        if target is None:
            self._count_unrouted_feedback()
            msg = InternalMessage(
                payload=None,
                kind="jsonData",
                status={
                    "status": "FAILURE",
                    "code": 404,
                    "info": "feedback not routable: no predictor tag and "
                            "puid unknown (expired or never served here)",
                    "reason": "FEEDBACK_UNROUTED",
                },
            )
            return msg
        return await target.send_feedback(feedback)

    @staticmethod
    def _has_identifiers(feedback: InternalFeedback) -> bool:
        """True when the feedback carries any routing identifier (a
        predictor tag or a puid) on its response or request."""
        for msg in (feedback.response, feedback.request):
            if msg is not None and (msg.meta.tags.get("predictor") or msg.meta.puid):
                return True
        return False

    def _count_unrouted_feedback(self) -> None:
        logger.warning("dropping unroutable feedback (no predictor tag, puid unknown)")
        from seldon_core_tpu.utils.metrics import increment_counter

        increment_counter(
            "seldon_api_gateway_feedback_unrouted",
            "feedback messages dropped because the serving predictor "
            "could not be identified",
        )

    async def ready(self) -> bool:
        checks = await asyncio.gather(*(p.ready() for p in self.predictors))
        return all(checks)

    def pause(self) -> None:
        for p in self.predictors:
            p.pause()

    def unpause(self) -> None:
        for p in self.predictors:
            p.unpause()

    async def close(self) -> None:
        await asyncio.gather(*(p.close() for p in self.predictors))
        if self.request_logger is not None and hasattr(self.request_logger, "close"):
            try:
                self.request_logger.close()
            except Exception:  # noqa: BLE001 — shutdown must finish
                logger.exception("gateway request logger close failed")


def _http_status(out: InternalMessage) -> int:
    """HTTP code for a gateway response: FAILURE statuses surface their
    code (clamped to a valid HTTP error range), everything else is 200.
    Shared by the REST handlers and the native lane's bridge handler
    (native/frontserver.py)."""
    if out.status and out.status.get("status") == "FAILURE":
        code = int(out.status.get("code", 500))
        return code if 400 <= code < 600 else 500
    return 200


def build_gateway_app(gateway: Gateway, auth=None) -> web.Application:
    """``auth`` is an ``utils.auth.OAuthConfig``; when set, the data
    endpoints require ``Authorization: Bearer`` tokens issued by this
    gateway's ``/oauth/token`` (client-credentials grant — the
    reference's legacy API-gateway flow,
    reference: seldon_client.py:1186-1227). Health/metrics endpoints
    stay open, like the reference's probe surface."""
    issuer = None
    if auth is not None:
        from seldon_core_tpu.utils.auth import TokenIssuer, parse_basic_auth

        issuer = TokenIssuer(auth)

        @web.middleware
        async def require_token(request: web.Request, handler):
            # data endpoints AND mutating admin verbs (/pause, /unpause)
            # need a token; probes + /metrics + /oauth/token stay open
            guarded = (
                request.path.startswith("/api/")
                or request.path in ("/predict", "/pause", "/unpause")
            )
            if guarded and not issuer.verify_header(request.headers.get("Authorization")):
                from seldon_core_tpu.utils.auth import UNAUTHENTICATED_MSG

                resp = web.json_response(
                    {"status": {"status": "FAILURE", "code": 401,
                                "info": UNAUTHENTICATED_MSG,
                                "reason": "UNAUTHORIZED"}},
                    status=401,
                )
                # small declared bodies drain (keeps keep-alive sockets
                # reusable); body-less requests (GET/HEAD probes, POSTs
                # with no Content-Length and no Transfer-Encoding) have
                # nothing to drain and keep their socket too; only
                # chunked/unsized uploads or oversized declared bodies
                # force a close — buffering those for a 401 would pay
                # for bytes we are rejecting
                cl = request.content_length
                chunked = "chunked" in request.headers.get("Transfer-Encoding", "").lower()
                if cl is not None and cl <= 1 << 20:
                    await request.read()
                elif cl is None and not chunked:
                    pass  # no body on the wire — nothing to drain
                else:
                    resp.force_close()
                return resp
            return await handler(request)

        app = web.Application(
            client_max_size=1024 * 1024 * 512, middlewares=[require_token]
        )

        async def oauth_token(request: web.Request) -> web.Response:
            creds = parse_basic_auth(request.headers.get("Authorization"))
            if creds is None or not issuer.check_credentials(*creds):
                return web.json_response({"error": "invalid_client"}, status=401)
            return web.json_response(issuer.issue())

        app.router.add_post("/oauth/token", oauth_token)
    else:
        app = web.Application(client_max_size=1024 * 1024 * 512)

    async def predictions(request: web.Request) -> web.Response:
        from seldon_core_tpu.runtime.rest import _remote_ctx, _remote_deadline_ms
        from seldon_core_tpu.utils import deadlines as _deadlines
        from seldon_core_tpu.utils.tracing import activate_context

        try:
            body = await _request_body(request)
            msg = InternalMessage.from_json(body)
            # SLO ingress: X-Seldon-Deadline-Ms mints the end-to-end
            # budget (carried by contextvar through every hop below);
            # X-Seldon-Priority lands in meta.tags so the generation
            # engine's admission/shedding sees it.  An explicit tag in
            # the body wins over the header.
            prio = _deadlines.extract_priority(request.headers)
            if prio is not None and "priority" not in msg.meta.tags:
                msg.meta.tags["priority"] = prio
            # X-Seldon-Adapter selects the LoRA weight set (r16); an
            # explicit tag in the body wins, same precedence as priority
            adapter = _deadlines.extract_adapter(request.headers)
            if adapter and "adapter" not in msg.meta.tags:
                msg.meta.tags["adapter"] = adapter
            # an external caller's traceparent makes the gateway's
            # predictor.predict span a child of ITS trace — the whole
            # graph then stitches under the caller's root
            with activate_context(_remote_ctx(request)), \
                    _deadlines.activate_ms(_remote_deadline_ms(request)):
                _deadlines.check("gateway ingress /api/v0.1/predictions")
                out = await gateway.predict(msg, predictor=request.query.get("predictor"))
            return web.json_response(out.to_json(), status=_http_status(out))
        except Exception as e:  # noqa: BLE001
            return _error_response(e)

    async def explanations(request: web.Request) -> web.Response:
        from seldon_core_tpu.runtime.rest import _remote_ctx, _remote_deadline_ms
        from seldon_core_tpu.utils import deadlines as _deadlines
        from seldon_core_tpu.utils.tracing import activate_context

        try:
            body = await _request_body(request)
            msg = InternalMessage.from_json(body)
            svc = gateway.by_name(request.query.get("predictor", "")) or gateway.pick()
            # every ingress mints the deadline and adopts the caller's
            # trace (graftlint: propagation) — explanations included
            with activate_context(_remote_ctx(request)), \
                    _deadlines.activate_ms(_remote_deadline_ms(request)):
                _deadlines.check("gateway ingress /api/v0.1/explanations")
                out = await svc.explain(msg)
            return web.json_response(out.to_json(), status=_http_status(out))
        except Exception as e:  # noqa: BLE001
            return _error_response(e)

    async def generate_stream_sse(request: web.Request) -> web.StreamResponse:
        """Token streaming over HTTP: Server-Sent Events, one
        ``data: {"tokens": [...]}`` event per engine chunk, then
        ``event: end`` carrying the puid (the REST twin of the gRPC
        ``Seldon/GenerateStream`` lane; same eligibility rule)."""
        import asyncio as _asyncio
        import json as _json

        import numpy as _np

        from seldon_core_tpu.runtime.component import MicroserviceError

        try:
            body = await _request_body(request)
            msg = InternalMessage.from_json(body)
        except Exception as e:  # noqa: BLE001
            return _error_response(e)
        svc = gateway.by_name(request.query.get("predictor", "")) or gateway.pick()
        fast = svc.single_local_model()
        component = fast[1] if fast is not None else None
        gen_fn = getattr(component, "predict_stream", None)
        if gen_fn is None:
            return web.json_response(
                {"status": {"status": "FAILURE", "code": 501,
                            "info": "token streaming needs a single-local-model "
                                    "predictor whose component implements "
                                    "predict_stream (e.g. STREAMING_LM)",
                            "reason": "NOT_IMPLEMENTED"}},
                status=501,
            )
        meta = {"tags": dict(msg.meta.tags), "puid": msg.meta.puid}
        # the streaming generator runs on plain executor threads (no
        # contextvar copy), so the SLO headers ride meta.tags instead
        # of the ambient budget (tags in the body win).  The expiry is
        # minted ABSOLUTE here, at ingress: a relative deadline_ms tag
        # re-minted at submit would silently refund the executor
        # queueing time (this lane calls the local model in-process,
        # so a monotonic timestamp is a valid carrier)
        import time as _mono_time

        from seldon_core_tpu.utils import deadlines as _deadlines

        sse_ms = _deadlines.extract_ms(request.headers)
        if sse_ms is not None:
            meta["tags"].setdefault(
                "deadline_at_monotonic", _mono_time.monotonic() + sse_ms / 1000.0
            )
        sse_prio = _deadlines.extract_priority(request.headers)
        if sse_prio is not None:
            meta["tags"].setdefault("priority", sse_prio)
        sse_adapter = _deadlines.extract_adapter(request.headers)
        if sse_adapter:
            meta["tags"].setdefault("adapter", sse_adapter)
        loop = _asyncio.get_running_loop()
        sentinel = object()
        # pull the FIRST chunk before sending headers: bad prompts /
        # engine rejections surface as proper HTTP errors, not an
        # abruptly-closed 200 stream (the gRPC twin aborts with status)
        try:
            arr = msg.array()
            it = gen_fn(arr, [], meta=meta)
            first = await loop.run_in_executor(None, next, it, sentinel)
        except Exception as e:  # noqa: BLE001
            return _error_response(e)
        resp = web.StreamResponse(headers={
            "Content-Type": "text/event-stream",
            "Cache-Control": "no-cache",
        })
        try:
            await resp.prepare(request)
            chunk = first
            while True:
                if chunk is sentinel:
                    await resp.write(
                        (f"event: end\ndata: {_json.dumps({'puid': msg.meta.puid})}\n\n").encode()
                    )
                    break
                payload = _json.dumps({"tokens": _np.asarray(chunk).tolist()})
                await resp.write(f"data: {payload}\n\n".encode())
                try:
                    chunk = await loop.run_in_executor(None, next, it, sentinel)
                except MicroserviceError as e:
                    await resp.write(
                        (f"event: error\ndata: {_json.dumps(e.to_status())}\n\n").encode()
                    )
                    break
                except Exception as e:  # noqa: BLE001 — mid-stream engine fault:
                    # the consumer must see an error event, never a
                    # silent truncation that reads as completion
                    status = {"status": "FAILURE", "code": 500,
                              "info": str(e), "reason": "ENGINE_ERROR"}
                    await resp.write(
                        (f"event: error\ndata: {_json.dumps(status)}\n\n").encode()
                    )
                    break
            await resp.write_eof()
        except (ConnectionResetError, ConnectionError, _asyncio.CancelledError):
            pass  # client went away; the finally-clause frees the stream
        finally:
            await loop.run_in_executor(None, it.close)
        return resp

    async def feedback(request: web.Request) -> web.Response:
        from seldon_core_tpu.runtime.rest import _remote_ctx, _remote_deadline_ms
        from seldon_core_tpu.utils import deadlines as _deadlines
        from seldon_core_tpu.utils.tracing import activate_context

        try:
            body = await _request_body(request)
            fb = InternalFeedback.from_json(body)
            # feedback is exempt from RETRIES/hedging, not from the
            # ingress contract: the budget still rides (and fast-fails)
            # and reward spans still stitch under the caller's trace
            with activate_context(_remote_ctx(request)), \
                    _deadlines.activate_ms(_remote_deadline_ms(request)):
                _deadlines.check("gateway ingress /api/v0.1/feedback")
                out = await gateway.send_feedback(fb)
            return web.json_response(out.to_json(), status=_http_status(out))
        except Exception as e:  # noqa: BLE001
            return _error_response(e)

    async def ping(_r: web.Request) -> web.Response:
        return web.Response(text="pong")

    async def live(_r: web.Request) -> web.Response:
        return web.Response(text="live")

    async def ready(_r: web.Request) -> web.Response:
        ok = await gateway.ready()
        return web.Response(text="ready" if ok else "not ready", status=200 if ok else 503)

    async def pause(_r: web.Request) -> web.Response:
        gateway.pause()
        return web.Response(text="paused")

    async def unpause(_r: web.Request) -> web.Response:
        gateway.unpause()
        return web.Response(text="unpaused")

    async def metrics_endpoint(_r: web.Request) -> web.Response:
        from prometheus_client import CONTENT_TYPE_LATEST, generate_latest

        return web.Response(body=generate_latest(), content_type=CONTENT_TYPE_LATEST.split(";")[0])

    async def debug_engine(request: web.Request) -> web.Response:
        """Generation-engine stats for every local component that runs a
        paged engine, keyed predictor -> node.  ``?detail=1`` adds the
        flight recorder's per-chunk ring (the post-incident forensics
        payload; see docs/architecture.md §Generation observability)."""
        detail = request.query.get("detail", "") in ("1", "true", "yes")
        out: Dict[str, Dict[str, object]] = {}
        for svc in gateway.predictors:
            nodes = {}
            for unit in svc.graph.walk():
                component = svc.executor.component(unit.name)
                engine = getattr(component, "engine", None)
                stats_fn = getattr(engine, "engine_stats", None)
                if stats_fn is None:
                    continue
                try:
                    nodes[unit.name] = stats_fn(detail=detail)
                except TypeError:  # engines predating the detail arg
                    nodes[unit.name] = stats_fn()
            if nodes:
                out[svc.name] = nodes
        return web.json_response(out)

    async def debug_workers(_r: web.Request) -> web.Response:
        """Supervised-worker lifecycle (r12): alive/ready/restarts plus
        the ``exhausted`` flag — a worker whose restart budget is spent
        is DEAD until redeployed, and this endpoint is where the
        breaker/alert layer (and operators) see that instead of
        inferring it from connection refusals."""
        sup = gateway.supervisor
        health = sup.health() if sup is not None else {}
        # engine health (r17): the device-health watchdog's state per
        # local paged engine — healthy | degraded | evacuating — plus
        # the quarantine/migration counters the evacuation layer and
        # alerting read alongside the process lifecycle states above
        engines: Dict[str, Dict[str, object]] = {}
        for svc in gateway.predictors:
            for unit in svc.graph.walk():
                component = svc.executor.component(unit.name)
                engine = getattr(component, "engine", None)
                stats_fn = getattr(engine, "engine_stats", None)
                if stats_fn is None:
                    continue
                try:
                    s = stats_fn()
                except Exception:  # noqa: BLE001 — one sick engine must
                    # not take the whole debug surface down
                    engines[f"{svc.name}/{unit.name}"] = {"error": True}
                    continue
                engines[f"{svc.name}/{unit.name}"] = {
                    "health": s.get("health", "healthy"),
                    "health_state": s.get("health_state", 0),
                    "watchdog_trips": s.get("watchdog_trips", 0),
                    "quarantined": s.get("quarantined", 0),
                    "migrated_out": s.get("migrated_out", 0),
                    "migrated_in": s.get("migrated_in", 0),
                }
        return web.json_response({
            "workers": health,
            "engines": engines,
            "degraded": sorted(
                name for name, h in engines.items()
                if h.get("health") not in (None, "healthy")
            ),
            "exhausted": sorted(
                name for name, h in health.items() if h.get("exhausted")
            ),
        })

    async def debug_traces(request: web.Request) -> web.Response:
        """Spans from the in-process tracer ring: ``?trace_id=<puid>``
        for one trace (the engine request span + its gen.* lifecycle
        spans), else the newest ``?limit=`` spans — the debug surface
        the tracing module promises."""
        from seldon_core_tpu.utils.tracing import get_tracer

        tracer = get_tracer()
        if tracer is None:
            return web.json_response(
                {"enabled": False, "spans": [],
                 "info": "tracing not set up (call setup_tracing / set "
                         "OTEL_EXPORTER_OTLP_ENDPOINT)"},
            )
        trace_id = request.query.get("trace_id", "")
        try:
            limit = max(1, min(int(request.query.get("limit", "256")), 4096))
        except ValueError:
            limit = 256
        if trace_id:
            spans = tracer.find(trace_id)
        else:
            with tracer._lock:  # noqa: SLF001 — same package, read-only copy
                spans = list(tracer.spans)
        return web.json_response(
            {"enabled": True, "spans": [s.to_dict() for s in spans[-limit:]]}
        )

    async def debug_weights(_r: web.Request) -> web.Response:
        """The weight-multiplexing surface (r16): the process weight
        registry's residency/budget state (null when this process never
        touched it) plus every local paged engine's adapter-pool
        stats, keyed predictor -> node — "which weight sets is this
        gateway actually serving" as one curl."""
        from seldon_core_tpu.models.registry import registry_snapshot

        engines: Dict[str, Dict[str, object]] = {}
        for svc in gateway.predictors:
            nodes = {}
            for unit in svc.graph.walk():
                component = svc.executor.component(unit.name)
                engine = getattr(component, "engine", None)
                stats_fn = getattr(engine, "adapter_stats", None)
                if stats_fn is None:
                    continue
                nodes[unit.name] = stats_fn()
            if nodes:
                engines[svc.name] = nodes
        return web.json_response({
            "registry": registry_snapshot(),
            "engines": engines,
        })

    async def debug_telemetry(request: web.Request) -> web.Response:
        """This process's replica telemetry snapshot (r20): the
        versioned time-series-ring payload, ``?window=<s>`` bounded.
        One engine-bearing component (the common topology) serves its
        snapshot directly — the shape the fleet aggregator polls;
        multi-component graphs nest per-node snapshots."""
        try:
            window_s = float(request.query.get("window", "0") or 0.0)
        except ValueError:
            window_s = 0.0
        snaps: Dict[str, object] = {}
        for svc in gateway.predictors:
            for unit in svc.graph.walk():
                component = svc.executor.component(unit.name)
                snap_fn = getattr(component, "telemetry_snapshot", None)
                if snap_fn is None:
                    continue
                snap = snap_fn(window_s)
                if snap is not None:
                    snaps[f"{svc.name}/{unit.name}"] = snap
        if not snaps:
            from seldon_core_tpu.utils import telemetry as _telemetry

            return web.json_response(
                {"enabled": _telemetry.telemetry_enabled(), "components": {},
                 "info": "no telemetry ring in this process "
                         "(SELDON_TPU_TELEMETRY=0 or no generation engine)"},
            )
        if len(snaps) == 1:
            return web.json_response(next(iter(snaps.values())))
        from seldon_core_tpu.utils import telemetry as _telemetry

        return web.json_response({
            "schema_version": _telemetry.TELEMETRY_SCHEMA_VERSION,
            "components": snaps,
        })

    async def debug_fleet(_r: web.Request) -> web.Response:
        """The merged fleet view (r20): per-replica freshness +
        saturation, adapter/prefix residency maps and the fleet rollup.
        Endpoints come from ``SELDON_TPU_FLEET_ENDPOINTS``, else from
        the local supervisor's workers; polls happen at most once per
        poll interval, executor-side (urllib must not block the loop)."""
        import asyncio as _asyncio
        import time as _time

        agg = getattr(gateway, "_fleet_aggregator", None)
        if agg is None:
            from seldon_core_tpu.controlplane import fleetview

            endpoints = fleetview.endpoints_from_knob()
            if not endpoints and gateway.supervisor is not None:
                endpoints = fleetview.endpoints_from_supervisor(
                    gateway.supervisor
                )
            if not endpoints:
                return web.json_response({
                    "enabled": False,
                    "info": "no fleet endpoints (set "
                            "SELDON_TPU_FLEET_ENDPOINTS or run workers "
                            "under the local supervisor)",
                })
            agg = fleetview.TelemetryAggregator(endpoints)
            try:
                from seldon_core_tpu.utils.metrics import (
                    FleetPrometheusBridge,
                )

                agg.bridge = FleetPrometheusBridge(agg)
            except Exception:  # noqa: BLE001 — metrics never block the view
                logger.exception("fleet prometheus bridge unavailable")
            gateway._fleet_aggregator = agg
            gateway._fleet_last_poll = 0.0
        now = _time.monotonic()
        if now - getattr(gateway, "_fleet_last_poll", 0.0) >= agg.poll_s:
            gateway._fleet_last_poll = now
            await _asyncio.get_running_loop().run_in_executor(
                None, agg.poll_once
            )
        return web.json_response({"enabled": True, **agg.fleet_view()})

    async def debug_request(request: web.Request) -> web.Response:
        """One request's stitched forensics timeline (r21): the stored
        capture container (knob snapshot, sampling recipe, five-phase
        latency split, per-wave recorder slice, cost totals, payload
        frames unless redacted) merged with the live span ring — the
        "why was THIS request slow" surface.  404 only when neither
        plane knows the puid."""
        import dataclasses as _dc

        import numpy as np

        from seldon_core_tpu.utils import capture as _capture
        from seldon_core_tpu.utils.tracing import get_tracer

        puid = request.match_info["puid"]
        cap = None
        if _capture.capture_enabled():
            try:
                cap = await asyncio.get_running_loop().run_in_executor(
                    None, _capture.default_store().get, puid
                )
            except Exception:  # noqa: BLE001 — a corrupt container must
                # not take the debug surface down; spans may still match
                logger.exception("capture load failed (puid=%s)", puid)
        tracer = get_tracer()
        spans = [s.to_dict() for s in tracer.find(puid)] if tracer else []
        if cap is None and not spans:
            return web.json_response(
                {"puid": puid, "found": False,
                 "info": "no capture container and no spans for this puid "
                         "(capture off, not triggered, or evicted)"},
                status=404,
            )
        capture_doc = None
        timeline = []
        if cap is not None:
            capture_doc = _dc.asdict(cap)
            capture_doc["prompt"] = (
                np.asarray(cap.prompt).reshape(-1).tolist()
                if cap.prompt is not None else []
            )
            capture_doc["tokens"] = (
                np.asarray(cap.tokens).reshape(-1).tolist()
                if cap.tokens is not None else []
            )
            stamps = (cap.phases or {}).get("stamps") or {}
            for name, t in stamps.items():
                if t:
                    timeline.append(
                        {"t": float(t), "event": name, "source": "stream"}
                    )
        for s in spans:
            timeline.append({
                "t": s["startTimeUnixNano"] / 1e9,
                "event": f"span:{s['name']}",
                "duration_ms": round(s["durationNano"] / 1e6, 3),
                "source": "tracer",
            })
        timeline.sort(key=lambda e: e["t"])
        return web.json_response({
            "puid": puid,
            "found": True,
            "capture": capture_doc,
            "spans": spans,
            "timeline": timeline,
        })

    async def debug_knobs(_r: web.Request) -> web.Response:
        """The central knob registry (runtime/knobs.py) with this
        process's effective values: "what is this gateway actually
        running with" as one curl instead of a grep through env dumps.
        Declared metadata only — no secrets live in SELDON_TPU_*."""
        from seldon_core_tpu.runtime import knobs as _knobs

        snap = _knobs.snapshot()
        return web.json_response({
            "knobs": snap,
            "set": sorted(k["name"] for k in snap if k["set"]),
        })

    async def openapi_endpoint(_r: web.Request) -> web.Response:
        from seldon_core_tpu.runtime.openapi import gateway_openapi

        return web.json_response(gateway_openapi())

    app.router.add_get("/seldon.json", openapi_endpoint)
    app.router.add_post("/api/v0.1/predictions", predictions)
    app.router.add_get("/api/v0.1/predictions", predictions)
    app.router.add_post("/predict", predictions)  # convenience alias
    app.router.add_post("/api/v0.1/feedback", feedback)
    app.router.add_post("/api/v0.1/generate/stream", generate_stream_sse)
    app.router.add_post("/api/v0.1/explanations", explanations)
    app.router.add_get("/ping", ping)
    app.router.add_get("/live", live)
    app.router.add_get("/ready", ready)
    app.router.add_route("*", "/pause", pause)
    app.router.add_route("*", "/unpause", unpause)
    app.router.add_get("/metrics", metrics_endpoint)
    app.router.add_get("/debug/engine", debug_engine)
    app.router.add_get("/debug/workers", debug_workers)
    app.router.add_get("/debug/traces", debug_traces)
    app.router.add_get("/debug/knobs", debug_knobs)
    app.router.add_get("/debug/weights", debug_weights)
    app.router.add_get("/debug/telemetry", debug_telemetry)
    app.router.add_get("/debug/fleet", debug_fleet)
    app.router.add_get("/debug/request/{puid}", debug_request)
    return app


def add_seldon_service(server: grpc.aio.Server, gateway: Gateway, auth=None) -> None:
    """Register the external Seldon gRPC service.  With ``auth`` set,
    calls must carry ``authorization: Bearer <token>`` metadata."""
    issuer = None
    if auth is not None:
        from seldon_core_tpu.utils.auth import TokenIssuer

        issuer = TokenIssuer(auth)

    async def check_auth(context) -> None:
        if issuer is not None and not issuer.verify_grpc(context):
            from seldon_core_tpu.utils.auth import UNAUTHENTICATED_MSG

            await context.abort(grpc.StatusCode.UNAUTHENTICATED, UNAUTHENTICATED_MSG)

    async def predict(request: pb.SeldonMessage, context) -> pb.SeldonMessage:
        await check_auth(context)
        from seldon_core_tpu.runtime.grpc_server import (
            _grpc_deadline_ms,
            _grpc_remote_ctx,
        )
        from seldon_core_tpu.utils import deadlines as _deadlines
        from seldon_core_tpu.utils.tracing import activate_context

        msg = InternalMessage.from_proto(request)
        prio = _deadlines.extract_priority(context.invocation_metadata() or ())
        if prio is not None and "priority" not in msg.meta.tags:
            msg.meta.tags["priority"] = prio
        try:
            with activate_context(_grpc_remote_ctx(context)), \
                    _deadlines.activate_ms(_grpc_deadline_ms(context)):
                _deadlines.check("gateway grpc ingress Seldon/Predict")
                out = await gateway.predict(msg)
        except MicroserviceError as e:  # ingress fast-fail (DEADLINE_EXCEEDED)
            out = failure_message(e, msg.meta.puid)
        return out.to_proto()

    async def send_feedback(request: pb.Feedback, context) -> pb.SeldonMessage:
        await check_auth(context)
        from seldon_core_tpu.runtime.grpc_server import (
            _grpc_deadline_ms,
            _grpc_remote_ctx,
        )
        from seldon_core_tpu.utils import deadlines as _deadlines
        from seldon_core_tpu.utils.tracing import activate_context

        fb = InternalFeedback.from_proto(request)
        try:
            with activate_context(_grpc_remote_ctx(context)), \
                    _deadlines.activate_ms(_grpc_deadline_ms(context)):
                _deadlines.check("gateway grpc ingress Seldon/SendFeedback")
                out = await gateway.send_feedback(fb)
        except MicroserviceError as e:  # ingress fast-fail (DEADLINE_EXCEEDED)
            out = failure_message(e, fb.request.meta.puid if fb.request else "")
        return out.to_proto()

    async def generate_stream(request: pb.SeldonMessage, context):
        """Token streaming on the aio server — same eligibility rule as
        the sync lane: a single-local-model predictor whose component
        implements ``predict_stream``.  The blocking generator is
        driven from the default executor so the event loop never
        blocks on a decode chunk."""
        await check_auth(context)
        import numpy as np

        from seldon_core_tpu.runtime.component import MicroserviceError

        msg = InternalMessage.from_proto(request)
        svc = gateway.pick()
        fast = svc.single_local_model()
        component = fast[1] if fast is not None else None
        gen_fn = getattr(component, "predict_stream", None)
        if gen_fn is None:
            await context.abort(
                grpc.StatusCode.UNIMPLEMENTED,
                "GenerateStream needs a single-local-model predictor whose "
                "component implements predict_stream (e.g. STREAMING_LM)",
            )
        meta = {"tags": dict(msg.meta.tags), "puid": msg.meta.puid}
        # SLO parity with the SSE twin: the streaming generator runs on
        # plain executor threads (no contextvar copy), so the deadline
        # and priority ride meta.tags as an ABSOLUTE monotonic expiry
        # minted here at ingress (tags in the body win).  Without this
        # the gRPC stream lane silently ignored x-seldon-deadline-ms.
        import time as _mono_time

        from seldon_core_tpu.runtime.grpc_server import _grpc_deadline_ms
        from seldon_core_tpu.utils import deadlines as _deadlines

        md_ms = _grpc_deadline_ms(context)
        if md_ms is not None:
            meta["tags"].setdefault(
                "deadline_at_monotonic", _mono_time.monotonic() + md_ms / 1000.0
            )
        md_prio = _deadlines.extract_priority(context.invocation_metadata() or ())
        if md_prio is not None:
            meta["tags"].setdefault("priority", md_prio)
        md_adapter = _deadlines.extract_adapter(
            context.invocation_metadata() or ()
        )
        if md_adapter:
            meta["tags"].setdefault("adapter", md_adapter)
        loop = asyncio.get_running_loop()
        it = gen_fn(msg.array(), [], meta=meta)
        sentinel = object()
        try:
            while True:
                try:
                    chunk = await loop.run_in_executor(None, next, it, sentinel)
                except MicroserviceError as e:
                    await context.abort(
                        grpc.StatusCode.INVALID_ARGUMENT
                        if 400 <= e.status_code < 500
                        else grpc.StatusCode.INTERNAL,
                        str(e),
                    )
                if chunk is sentinel:
                    break
                out = InternalMessage(
                    payload=np.asarray(chunk)[None, :], kind="ndarray"
                )
                out.meta.puid = msg.meta.puid
                yield out.to_proto()
        finally:
            # client cancel/disconnect: closing the generator triggers
            # its finally-clause, which cancels the engine stream
            await loop.run_in_executor(None, it.close)

    async def predict_stream(request_iterator, context):
        """Chunked predict: reassemble -> predict -> stream the reply.

        The stream lane has its own total-size cap (the per-frame gRPC
        limit no longer bounds memory once frames accumulate)."""
        await check_auth(context)  # fail before buffering the stream
        parts = []
        total = 0
        async for chunk in request_iterator:
            total += len(chunk.data)
            if total > services.STREAM_MAX_BYTES:
                await context.abort(
                    grpc.StatusCode.RESOURCE_EXHAUSTED,
                    f"stream exceeds {services.STREAM_MAX_BYTES} bytes",
                )
            parts.append(chunk.data)
        request = pb.SeldonMessage.FromString(b"".join(parts))
        from seldon_core_tpu.runtime.grpc_server import (
            _grpc_deadline_ms,
            _grpc_remote_ctx,
        )
        from seldon_core_tpu.utils import deadlines as _deadlines
        from seldon_core_tpu.utils.tracing import activate_context

        # chunked predict is a unary call once reassembled: the
        # standard ingress contract applies (deadline minted AFTER the
        # stream is buffered — reassembly time counts against the
        # caller's budget only if they set the native gRPC deadline)
        msg = InternalMessage.from_proto(request)
        try:
            with activate_context(_grpc_remote_ctx(context)), \
                    _deadlines.activate_ms(_grpc_deadline_ms(context)):
                _deadlines.check("gateway grpc ingress Seldon/PredictStream")
                out = await gateway.predict(msg)
        except MicroserviceError as e:  # ingress fast-fail (DEADLINE_EXCEEDED)
            out = failure_message(e, msg.meta.puid)
        for chunk in services.chunk_message(out.to_proto()):
            yield chunk

    server.add_generic_rpc_handlers(
        (
            services.generic_handler(
                "Seldon",
                {
                    "Predict": predict,
                    "SendFeedback": send_feedback,
                    "PredictStream": predict_stream,
                    "GenerateStream": generate_stream,
                },
            ),
        )
    )


class GrpcServerHandle:
    """Uniform async facade over the sync and aio gRPC servers."""

    def __init__(self, server, is_aio: bool):
        self.server = server
        self.is_aio = is_aio

    async def stop(self, grace=None):
        if self.is_aio:
            await self.server.stop(grace)
        else:
            event = self.server.stop(grace)
            await asyncio.get_running_loop().run_in_executor(None, event.wait)


async def serve_gateway(
    gateway: Gateway,
    host: str = "0.0.0.0",
    http_port: int = 8000,
    grpc_port: int = 5001,
    max_message_bytes: int = 512 * 1024 * 1024,
    grpc_mode: str = "sync",  # sync (fast path, default) | aio
    tls=None,  # utils.tls.TlsConfig — terminates TLS on both listeners
    auth=None,  # utils.auth.OAuthConfig — bearer tokens on both listeners
):
    """Start REST + gRPC front servers; returns (runner, GrpcServerHandle)."""
    from seldon_core_tpu.runtime import rest
    from seldon_core_tpu.utils.tls import add_grpc_port

    app = build_gateway_app(gateway, auth=auth)
    runner = await rest.serve(app, host=host, port=http_port, tls=tls)
    if grpc_mode == "sync":
        from seldon_core_tpu.engine.sync_server import build_sync_seldon_server

        server = build_sync_seldon_server(
            gateway, asyncio.get_running_loop(), max_message_bytes=max_message_bytes,
            auth=auth,
        )
        add_grpc_port(server, f"{host}:{grpc_port}", tls)
        server.start()
        return runner, GrpcServerHandle(server, is_aio=False)
    server = grpc.aio.server(
        options=[
            ("grpc.max_send_message_length", max_message_bytes),
            ("grpc.max_receive_message_length", max_message_bytes),
        ]
    )
    add_seldon_service(server, gateway, auth=auth)
    add_grpc_port(server, f"{host}:{grpc_port}", tls)
    await server.start()
    return runner, GrpcServerHandle(server, is_aio=True)
