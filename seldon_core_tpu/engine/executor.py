"""The graph executor — per-request orchestration of an inference graph.

Replicates the reference engine's execution algebra
(reference: PredictiveUnitBean.java:106-199 getOutputAsync):

1. record this node in ``requestPath``
2. ``transform_input`` (a MODEL's predict) -> merge meta (puid kept,
   tags latest-wins, per-node metrics collected then cleared from the
   message — reference: PredictiveUnitBean.java:370-388 mergeMeta)
3. leaf -> transformed input is the output
4. ``route``: ROUTER picks one branch, -1/no router means all children
   (reference: PredictiveUnitBean.java:151-169); branch recorded in
   ``routing``
5. children execute concurrently (asyncio fan-out; reference used a
   Spring @Async pool, reference: PredictiveUnitBean.java:171-184)
6. ``aggregate``: COMBINER merges, default takes the single child output
7. ``transform_output`` -> merge meta
8. at the top: routing map, request path, and all collected node metrics
   are folded into the response meta
   (reference: PredictiveUnitBean.java:72-93 getOutput)

Feedback walks the same tree following the recorded routing map
(reference: PredictiveUnitBean.java:206-246).

The crucial TPU difference: for co-located nodes the "call" on the
right-hand side of every step is a direct dispatch on a live component
— a graph edge costs one function call and payloads stay device-resident.
"""

from __future__ import annotations

import asyncio
import importlib
import logging
from typing import Any, Awaitable, Callable, Dict, List, Optional, Tuple

import numpy as np

from seldon_core_tpu.engine import units as builtin_units
from seldon_core_tpu.engine.graph import (
    AGGREGATE,
    GRPC,
    REST,
    ROUTE,
    SEND_FEEDBACK,
    TRANSFORM_INPUT,
    TRANSFORM_OUTPUT,
    UnitSpec,
    validate_graph,
)
from seldon_core_tpu.engine.transport import (
    CircuitBreaker,
    GrpcClient,
    LocalClient,
    NodeClient,
    RestClient,
    breakers_enabled,
)
from seldon_core_tpu.runtime.component import MicroserviceError
from seldon_core_tpu.runtime.message import InternalFeedback, InternalMessage, MsgMeta
from seldon_core_tpu.runtime.params import parse_parameters

logger = logging.getLogger(__name__)

# observers: (event, unit_name, payload) -> None; used by metrics/tracing
Observer = Callable[[str, str, Any], None]


def _instantiate_component(unit: UnitSpec) -> Any:
    """Materialise the in-process component for a unit, if any."""
    if unit.component is not None:
        return unit.component
    kwargs = parse_parameters(unit.parameters)
    if unit.implementation:
        return builtin_units.make_builtin(unit.implementation, **kwargs)
    if unit.component_class:
        module_name, _, class_name = unit.component_class.rpartition(".")
        module = importlib.import_module(module_name)
        obj = getattr(module, class_name)(**kwargs)
        return obj
    return None


def build_client(unit: UnitSpec, annotations: Optional[Dict[str, str]] = None) -> Optional[NodeClient]:
    """Pick the transport for a unit: in-process beats remote.

    `annotations` carries the deployment's cross-cutting knobs; the
    remote transports honour the reference's timeout/retry annotations
    (reference: InternalPredictionService.java:80-98):
    seldon.io/rest-connection-timeout (ms), seldon.io/rest-read-timeout
    (ms), seldon.io/rest-retries, seldon.io/grpc-read-timeout (ms),
    seldon.io/grpc-retries (attempt budget for transient statuses).

    Failure containment (r12): seldon.io/breaker ("0"/"off" disables
    circuit breaking for this deployment), seldon.io/breaker-failures
    (consecutive transient failures to trip, default 5),
    seldon.io/breaker-reset-ms (open→half-open cooldown, default 1000),
    seldon.io/breaker-probes (concurrent half-open probes, default 2),
    and seldon.io/hedge-ms (idempotent unary hedging delay; unset/0 =
    off).
    """
    ann = annotations or {}

    def _ms(key: str, default_s: float) -> float:
        try:
            return float(ann[key]) / 1000.0
        except (KeyError, ValueError):
            return default_s

    def _int(key: str, default: int) -> int:
        try:
            return int(ann[key])
        except (KeyError, ValueError):
            return default

    def _breaker(endpoint_key: str):
        """The annotation-configured shared breaker for an endpoint, or
        False (= off) when disabled by annotation or env."""
        if not breakers_enabled() or str(
            ann.get("seldon.io/breaker", "1")
        ).lower() in ("0", "off", "false"):
            return False
        return CircuitBreaker.for_endpoint(
            endpoint_key,
            failures=_int("seldon.io/breaker-failures", 5),
            reset_s=_ms("seldon.io/breaker-reset-ms", 1.0),
            probes=_int("seldon.io/breaker-probes", 2),
        )

    hedge_ms = _ms("seldon.io/hedge-ms", 0.0) * 1000.0

    if not unit.remote:
        # in-process beats remote — unless the node is declared remote,
        # in which case implementation/component_class describe what the
        # *worker process* runs, not something to instantiate here
        component = _instantiate_component(unit)
        if component is not None:
            if hasattr(component, "load"):
                component.load()
            return LocalClient(unit, component, breaker=_breaker(f"local:{unit.name}"))
    elif unit.endpoint is None:
        raise MicroserviceError(
            f"node {unit.name!r} is remote but has no endpoint — deploy "
            "through the control plane (it spawns the worker) or set one",
            status_code=500,
            reason="BAD_GRAPH",
        )
    if unit.endpoint is not None:
        endpoint_key = f"{unit.endpoint.host}:{unit.endpoint.port}"
        if unit.endpoint.transport == REST:
            try:
                retries = int(ann.get("seldon.io/rest-retries", 3))
            except ValueError:
                retries = 3
            return RestClient(
                unit,
                connect_timeout_s=_ms("seldon.io/rest-connection-timeout", 2.0),
                read_timeout_s=_ms("seldon.io/rest-read-timeout", 5.0),
                retries=retries,
                breaker=_breaker(endpoint_key),
                hedge_ms=hedge_ms,
            )
        try:
            grpc_retries = int(ann.get("seldon.io/grpc-retries", 3))
        except ValueError:
            grpc_retries = 3
        return GrpcClient(
            unit,
            deadline_s=_ms("seldon.io/grpc-read-timeout", 5.0),
            retries=grpc_retries,
            breaker=_breaker(endpoint_key),
            hedge_ms=hedge_ms,
        )
    return None


class GraphExecutor:
    """Executes one predictor's graph; owns the node clients."""

    def __init__(
        self,
        root: UnitSpec,
        clients: Optional[Dict[str, NodeClient]] = None,
        observer: Optional[Observer] = None,
        annotations: Optional[Dict[str, str]] = None,
    ):
        validate_graph(root)
        self.root = root
        self.observer = observer
        self.clients: Dict[str, NodeClient] = {}
        for unit in root.walk():
            if clients is not None and unit.name in clients:
                self.clients[unit.name] = clients[unit.name]
            else:
                client = build_client(unit, annotations)
                if client is not None:
                    self.clients[unit.name] = client
        # fail fast on unexecutable nodes with methods
        for unit in root.walk():
            if unit.node_methods() and unit.name not in self.clients:
                raise MicroserviceError(
                    f"no client available for node {unit.name!r}", status_code=500, reason="BAD_GRAPH"
                )

    # ------------------------------------------------------------------ util

    def _emit(self, event: str, unit: str, payload: Any = None) -> None:
        if self.observer is not None:
            try:
                self.observer(event, unit, payload)
            except Exception:  # observers must never break the data plane
                logger.exception("observer failed for %s/%s", event, unit)

    def component(self, name: str) -> Optional[Any]:
        """The live in-process component of a node, if local."""
        client = self.clients.get(name)
        return client.component if isinstance(client, LocalClient) else None

    async def _timed(self, unit: UnitSpec, method: str, coro: Awaitable, puid: str):
        """Time one node method call; emit a node_call event and a trace
        span (the reference's engine->node client histograms + per-node
        spans, reference: PredictiveUnitBean.java:77-78, analytics.md)."""
        import time

        from seldon_core_tpu.utils.tracing import maybe_span

        start = time.perf_counter()
        with maybe_span(f"node.{unit.name}.{method}", trace_id=puid, unit_type=unit.type):
            result = await coro
        self._emit("node_call", unit.name, (method, time.perf_counter() - start))
        return result

    @staticmethod
    def _merge_meta(latest: InternalMessage, previous: List[InternalMessage], puid: str) -> None:
        """Reference mergeMeta: keep puid, union tags with latest-wins,
        clear per-message metrics (they were already collected)."""
        tags: Dict[str, Any] = {}
        for prev in previous:
            tags.update(prev.meta.tags)
        tags.update(latest.meta.tags)
        latest.meta.puid = puid
        latest.meta.tags = tags
        latest.meta.metrics = []

    def _collect_metrics(
        self, msg: Optional[InternalMessage], unit: UnitSpec, metrics: Dict[str, List[Dict]]
    ) -> None:
        if msg is None or not msg.meta.metrics:
            return
        self._emit("node_metrics", unit.name, msg.meta.metrics)
        metrics.setdefault(unit.name, []).extend(msg.meta.metrics)

    @staticmethod
    def _branch_index(routing_msg: InternalMessage, unit: UnitSpec) -> int:
        try:
            arr = np.asarray(routing_msg.host_payload())
            branch = int(arr.ravel()[0])
        except (ValueError, IndexError, TypeError) as e:
            raise MicroserviceError(
                f"router {unit.name!r} returned undecodable routing", status_code=500,
                reason="ENGINE_INVALID_ROUTING",
            ) from e
        if branch < -1 or branch >= len(unit.children):
            raise MicroserviceError(
                f"router {unit.name!r} returned invalid branch {branch} "
                f"for {len(unit.children)} children",
                status_code=500,
                reason="ENGINE_INVALID_ROUTING",
            )
        return branch

    # --------------------------------------------------------------- predict

    async def predict(self, request: InternalMessage) -> InternalMessage:
        """Execute the full graph for one request."""
        puid = request.meta.puid
        routing: Dict[str, int] = {}
        request_path: Dict[str, str] = {}
        metrics: Dict[str, List[Dict]] = {}
        response = await self._execute(self.root, request, puid, routing, request_path, metrics)
        response.meta.routing.update(routing)
        response.meta.request_path.update(request_path)
        flat: List[Dict] = []
        for mlist in metrics.values():
            flat.extend(mlist)
        response.meta.metrics = flat
        response.meta.puid = puid
        return response

    @staticmethod
    def _fallback_worthy(e: Exception) -> bool:
        """Failures a fallback route may absorb: the primary's breaker
        is open (CIRCUIT_OPEN), its retries exhausted transiently (502),
        or it shed/refused transiently (503).  Deterministic errors
        would fail identically on the fallback — that includes remote
        4xx/plain-500 replies the transports re-raise as 502
        UPSTREAM_*_ERROR, which is why the transports tag ``transient``
        on the error (absent = transient: a bare component 503 like SHED
        is still worth a degraded answer).  A spent deadline (504) has
        no budget left to spend on a second subtree."""
        if not isinstance(e, MicroserviceError):
            return False
        if e.reason == "DEADLINE_EXCEEDED":
            return False
        if e.status_code not in (502, 503):
            return False
        return getattr(e, "transient", True)

    async def _execute(
        self,
        unit: UnitSpec,
        msg: InternalMessage,
        puid: str,
        routing: Dict[str, int],
        request_path: Dict[str, str],
        metrics: Dict[str, List[Dict]],
    ) -> InternalMessage:
        if unit.fallback is None:
            return await self._execute_primary(
                unit, msg, puid, routing, request_path, metrics
            )
        try:
            return await self._execute_primary(
                unit, msg, puid, routing, request_path, metrics
            )
        except MicroserviceError as e:
            if not self._fallback_worthy(e):
                raise
            logger.warning(
                "node %s failed (%s: %s) — taking fallback route %s",
                unit.name, e.reason, e, unit.fallback.name,
            )
            self._emit("node_fallback", unit.name, e.reason)
            from seldon_core_tpu.utils.metrics import increment_counter

            increment_counter(
                "seldon_tpu_graph_fallbacks_total",
                "requests answered by a fallback route because the "
                "primary's breaker was open or its retries exhausted",
            )
            out = await self._execute(
                unit.fallback, msg, puid, routing, request_path, metrics
            )
            # tag the degraded answer: callers (and the bench) must be
            # able to distinguish a fallback result from a primary one
            out.meta.tags["degraded"] = True
            out.meta.tags["fallback_for"] = unit.name
            return out

    async def _execute_primary(
        self,
        unit: UnitSpec,
        msg: InternalMessage,
        puid: str,
        routing: Dict[str, int],
        request_path: Dict[str, str],
        metrics: Dict[str, List[Dict]],
    ) -> InternalMessage:
        client = self.clients.get(unit.name)
        request_path[unit.name] = unit.image or unit.implementation or unit.component_class or "local"
        self._emit("node_start", unit.name, None)

        # 1. input transform (a MODEL's predict)
        if unit.has_method(TRANSFORM_INPUT):
            transformed = await self._timed(unit, "transform_input", client.transform_input(msg), puid)
            self._collect_metrics(transformed, unit, metrics)
            self._merge_meta(transformed, [msg], puid)
        else:
            transformed = msg

        # 2. leaf
        if not unit.children:
            self._emit("node_done", unit.name, None)
            return transformed

        # 3. routing
        if unit.has_method(ROUTE):
            routing_msg = await self._timed(unit, "route", client.route(transformed), puid)
            self._collect_metrics(routing_msg, unit, metrics)
            branch = self._branch_index(routing_msg, unit)
        else:
            branch = -1
        routing[unit.name] = branch
        selected = unit.children if branch == -1 else [unit.children[branch]]

        # 4. concurrent fan-out to children
        child_outputs: List[InternalMessage] = list(
            await asyncio.gather(
                *(
                    self._execute(child, transformed, puid, routing, request_path, metrics)
                    for child in selected
                )
            )
        )

        # 5. aggregation
        if unit.has_method(AGGREGATE):
            aggregated = await self._timed(unit, "aggregate", client.aggregate(child_outputs), puid)
        else:
            if len(child_outputs) != 1:
                raise MicroserviceError(
                    f"node {unit.name!r} received {len(child_outputs)} child outputs "
                    "but has no combiner",
                    status_code=500,
                    reason="ENGINE_MISSING_COMBINER",
                )
            aggregated = child_outputs[0]
        self._collect_metrics(aggregated, unit, metrics)
        self._merge_meta(aggregated, child_outputs, puid)

        # 6. output transform
        if unit.has_method(TRANSFORM_OUTPUT):
            out = await self._timed(unit, "transform_output", client.transform_output(aggregated), puid)
            self._collect_metrics(out, unit, metrics)
            self._merge_meta(out, [aggregated], puid)
        else:
            out = aggregated

        self._emit("node_done", unit.name, None)
        return out

    # -------------------------------------------------------------- feedback

    async def send_feedback(self, feedback: InternalFeedback) -> None:
        await self._feedback(self.root, feedback)

    async def _feedback(self, unit: UnitSpec, feedback: InternalFeedback) -> None:
        # follow the routing recorded at predict time
        routing = -1
        if feedback.response is not None:
            routing = feedback.response.meta.routing.get(unit.name, -1)
        if routing == -1:
            children = unit.children
        elif 0 <= routing < len(unit.children):
            children = [unit.children[routing]]
        else:
            children = []

        child_tasks = [asyncio.ensure_future(self._feedback(child, feedback)) for child in children]

        if unit.has_method(SEND_FEEDBACK):
            client = self.clients.get(unit.name)
            if client is not None:
                await client.send_feedback(feedback)

        if child_tasks:
            await asyncio.gather(*child_tasks)
        self._emit("node_feedback", unit.name, feedback.reward)

    # ------------------------------------------------------------- readiness

    async def ready(self) -> bool:
        """Graph readiness: every node answers
        (reference: SeldonGraphReadyChecker.java:20-50)."""
        checks = await asyncio.gather(*(c.ready() for c in self.clients.values()))
        return all(checks)

    async def close(self) -> None:
        await asyncio.gather(*(c.close() for c in self.clients.values()))
