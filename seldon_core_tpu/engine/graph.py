"""Inference-graph specification.

The declarative graph of node roles the reference encodes in its CRD
(reference: proto/seldon_deployment.proto:82-161 ``PredictiveUnit``):
a recursive tree of MODEL / ROUTER / COMBINER / TRANSFORMER /
OUTPUT_TRANSFORMER nodes.  Each node is served either

* **in-process** (``component`` — a live TPUComponent; co-located graph
  edges then cost a function call, not a network hop), or
* **remotely** (``endpoint`` — REST or gRPC microservice, for cross-host
  / DCN edges), or
* by a **builtin** implementation (``implementation`` — registry name,
  the reference's in-engine hardcoded units,
  reference: PredictorConfigBean.java:20-60).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

MODEL = "MODEL"
ROUTER = "ROUTER"
COMBINER = "COMBINER"
TRANSFORMER = "TRANSFORMER"
OUTPUT_TRANSFORMER = "OUTPUT_TRANSFORMER"
UNKNOWN_TYPE = "UNKNOWN_TYPE"

UNIT_TYPES = (MODEL, ROUTER, COMBINER, TRANSFORMER, OUTPUT_TRANSFORMER, UNKNOWN_TYPE)

# node methods
TRANSFORM_INPUT = "TRANSFORM_INPUT"
TRANSFORM_OUTPUT = "TRANSFORM_OUTPUT"
ROUTE = "ROUTE"
AGGREGATE = "AGGREGATE"
SEND_FEEDBACK = "SEND_FEEDBACK"

# Which methods each node type exercises during graph execution
# (reference: PredictorConfigBean.java:20-60; note a MODEL's
# TRANSFORM_INPUT maps onto its predict endpoint).
TYPE_METHODS: Dict[str, List[str]] = {
    MODEL: [TRANSFORM_INPUT, SEND_FEEDBACK],
    TRANSFORMER: [TRANSFORM_INPUT],
    OUTPUT_TRANSFORMER: [TRANSFORM_OUTPUT],
    ROUTER: [ROUTE, SEND_FEEDBACK],
    COMBINER: [AGGREGATE],
    UNKNOWN_TYPE: [],
}

REST = "REST"
GRPC = "GRPC"


class GraphSpecError(ValueError):
    pass


@dataclass
class Endpoint:
    host: str = "localhost"
    port: int = 9000
    transport: str = GRPC  # REST | GRPC


@dataclass
class UnitSpec:
    """One node of the inference graph."""

    name: str
    type: str = MODEL
    implementation: str = ""  # builtin registry name, or ""
    children: List["UnitSpec"] = field(default_factory=list)
    component: Optional[Any] = None  # in-process user object
    component_class: str = ""  # dotted path "pkg.module.Class" to instantiate
    endpoint: Optional[Endpoint] = None  # remote microservice
    parameters: List[Dict[str, Any]] = field(default_factory=list)
    methods: List[str] = field(default_factory=list)  # only for UNKNOWN_TYPE
    model_uri: str = ""
    image: str = ""  # recorded into meta.requestPath
    # TPU placement hints consumed by the control plane
    device_ids: List[int] = field(default_factory=list)
    sharding: Optional[Dict[str, Any]] = None
    # run this node out-of-process: the deployer spawns a supervised
    # microservice worker and fills in `endpoint` (the DCN edge — the
    # reference's engine->microservice pod-network hop)
    remote: bool = False
    # degraded answer path (r12): a whole alternate subtree the executor
    # runs INSTEAD of this node when this node's circuit breaker is open
    # or its transport retries exhaust (502/503) — the reference's
    # service-orchestrator failover idea made declarative.  The fallback
    # result is tagged in meta (`degraded`/`fallback_for`) so callers
    # and the bench can distinguish it from a primary answer.
    fallback: Optional["UnitSpec"] = None

    def node_methods(self) -> List[str]:
        if self.type == UNKNOWN_TYPE:
            return self.methods
        return TYPE_METHODS[self.type]

    def has_method(self, method: str) -> bool:
        return method in self.node_methods()

    def walk(self):
        """Every node of the subtree, INCLUDING fallback subtrees — so
        validation, client construction, placement and remote-worker
        spawning all see fallback nodes exactly like primaries (a
        fallback that was never built would fail at the worst moment:
        while its primary is down)."""
        yield self
        for child in self.children:
            yield from child.walk()
        if self.fallback is not None:
            yield from self.fallback.walk()

    def clone(self) -> "UnitSpec":
        """Structural copy: fresh UnitSpec nodes, shared leaf values.

        In-process ``component`` objects are shared by reference (they
        may hold live device buffers); everything the control plane
        mutates per generation (``endpoint`` fills for remote workers)
        lands on the copy, so re-applying one spec object never bleeds
        endpoints between generations.
        """
        import dataclasses

        return dataclasses.replace(
            self,
            children=[c.clone() for c in self.children],
            parameters=list(self.parameters),
            device_ids=list(self.device_ids),
            # endpoints are mutated by defaulting (port fill) — copy them
            endpoint=dataclasses.replace(self.endpoint) if self.endpoint else None,
            fallback=self.fallback.clone() if self.fallback else None,
        )

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "UnitSpec":
        """Parse the JSON/YAML graph form (CRD-equivalent)."""
        if "name" not in d:
            raise GraphSpecError(f"graph node missing 'name': {d!r}")
        unit_type = d.get("type", MODEL).upper()
        if unit_type not in UNIT_TYPES:
            raise GraphSpecError(f"unknown unit type {unit_type!r} for node {d['name']!r}")
        endpoint = None
        if "endpoint" in d:
            e = d["endpoint"]
            endpoint = Endpoint(
                host=e.get("host", "localhost"),
                port=int(e.get("port", 9000)),
                transport=e.get("transport", e.get("type", GRPC)).upper(),
            )
        return cls(
            name=d["name"],
            type=unit_type,
            implementation=d.get("implementation", ""),
            children=[cls.from_dict(c) for c in d.get("children", [])],
            component_class=d.get("componentClass", d.get("component_class", "")),
            endpoint=endpoint,
            parameters=list(d.get("parameters", [])),
            methods=[m.upper() for m in d.get("methods", [])],
            model_uri=d.get("modelUri", d.get("model_uri", "")),
            image=d.get("image", ""),
            device_ids=list(d.get("deviceIds", d.get("device_ids", []))),
            sharding=d.get("sharding"),
            remote=bool(d.get("remote", False)),
            fallback=cls.from_dict(d["fallback"]) if d.get("fallback") else None,
        )

    def to_dict(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {"name": self.name, "type": self.type}
        if self.implementation:
            out["implementation"] = self.implementation
        if self.component_class:
            out["componentClass"] = self.component_class
        if self.endpoint:
            out["endpoint"] = {
                "host": self.endpoint.host,
                "port": self.endpoint.port,
                "transport": self.endpoint.transport,
            }
        if self.parameters:
            out["parameters"] = self.parameters
        if self.methods:
            out["methods"] = self.methods
        if self.model_uri:
            out["modelUri"] = self.model_uri
        if self.image:
            out["image"] = self.image
        if self.remote:
            out["remote"] = True
        if self.children:
            out["children"] = [c.to_dict() for c in self.children]
        if self.fallback is not None:
            out["fallback"] = self.fallback.to_dict()
        return out


def validate_graph(root: UnitSpec) -> None:
    """Structural validation (reference: seldondeployment_webhook.go:358-446).

    * node names unique (fallback subtrees included — `walk` yields them)
    * COMBINER needs >= 1 child; ROUTER needs >= 1 child
    * every node must be executable: a component, component_class,
      endpoint, or builtin implementation (or be a no-method pass-through)
    * a fallback must be able to stand in for its primary: it (or its
      subtree) must itself be executable, and a fallback node may not
      declare its own fallback (one degradation step — a chain would
      hide how degraded an answer actually is)
    """
    seen = set()
    for unit in root.walk():
        if unit.name in seen:
            raise GraphSpecError(f"duplicate node name {unit.name!r}")
        seen.add(unit.name)
        if unit.type == COMBINER and not unit.children:
            raise GraphSpecError(f"COMBINER {unit.name!r} has no children")
        if unit.type == ROUTER and not unit.children:
            raise GraphSpecError(f"ROUTER {unit.name!r} has no children")
        if unit.fallback is not None and unit.fallback.fallback is not None:
            raise GraphSpecError(
                f"fallback {unit.fallback.name!r} of {unit.name!r} declares "
                "its own fallback: only one degradation step is allowed"
            )
        executable = (
            unit.component is not None
            or unit.component_class
            or unit.endpoint is not None
            or unit.implementation
        )
        if unit.node_methods() and not executable:
            raise GraphSpecError(
                f"node {unit.name!r} ({unit.type}) has no component/endpoint/implementation"
            )
