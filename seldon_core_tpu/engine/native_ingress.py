"""Native ingress wiring: put the C++ front server in front of a Gateway.

The reference fronts every predictor with its Java engine; this module
fronts a deployment's ``Gateway`` with the C++ epoll server
(``native/frontserver.cc``) instead of the Python aiohttp app
(reference: doc/source/graph/svcorch.md:1-8 — the data plane does not
run in the model language).

Lane assignment:

* **fast lane** (zero per-request Python) — available when the
  deployment is a single primary predictor whose graph is one
  in-process MODEL exposing ``raw_batch_call`` (JaxServer does);
  request tensors are decoded, coalesced, and batched in C++ and the
  jitted XLA program is invoked once per batch.
* **fallback lane** — everything else (multi-node graphs, traffic
  splits, shadows, exotic payloads, feedback, explanations) bridges
  into the running asyncio engine via ``GatewayRawHandler`` with full
  semantics.

Readiness: the C++ server answers ``/ready`` from a flag that a
background task refreshes from ``gateway.ready()`` (the graph walk).
"""

from __future__ import annotations

import asyncio
import json
import logging
from typing import Optional, Tuple

logger = logging.getLogger(__name__)


def fast_lane_for(gateway) -> Optional[dict]:
    """Fast-lane configuration for a gateway, or None when ineligible.

    Eligibility mirrors ``PredictorService.single_local_model`` plus
    gateway-level constraints: one primary predictor (a traffic split
    must run the weighted pick per request) and no shadows (the fast
    lane would bypass them).
    """
    if len(gateway.entries) != 1 or gateway.shadows:
        return None
    svc = gateway.entries[0][0]
    fast = svc.single_local_model()
    if fast is None:
        return None
    unit, component = fast
    raw_call = getattr(component, "raw_batch_call", None)
    if raw_call is None:
        return None
    try:
        feature_dim = int(component.flat_feature_dim())
        out_dim = int(component.flat_out_dim())
    except Exception:  # noqa: BLE001 — component without flat-shape support
        return None
    names = None
    try:
        cn = component.class_names()
        if cn and len(cn) == out_dim:
            names = [str(n) for n in cn]
    except Exception:  # noqa: BLE001 — class_names is an optional probe
        pass
    buckets = None
    batcher = getattr(component, "batcher", None)
    if batcher is not None and getattr(batcher, "buckets", None):
        buckets = list(batcher.buckets)
    return {
        "feature_dim": feature_dim,
        "out_dim": out_dim,
        "names": names,
        "model_name": unit.name,
        "max_batch": getattr(component, "max_batch_size", 64),
        "buckets": buckets,
    }


def _live_model_fn(gateway, feature_dim: int, out_dim: int):
    """Batch callback that re-resolves the component through the
    gateway on every call, so a rolling swap serves the NEW generation
    on the fast lane too (capturing raw_batch_call at startup would pin
    the old weights forever).  A swap that changes the model's flat
    shapes makes the fast lane error loudly rather than serve wrong
    tensors — re-serve the deployment to renegotiate dims."""

    def model_fn(batch):
        lane_svc = gateway.entries[0][0] if len(gateway.entries) == 1 else None
        fast = lane_svc.single_local_model() if lane_svc is not None else None
        if fast is None:
            raise RuntimeError("fast lane no longer eligible after rolling update")
        component = fast[1]
        if (int(component.flat_feature_dim()) != feature_dim
                or int(component.flat_out_dim()) != out_dim):
            raise RuntimeError(
                "model shape changed across rolling update; re-serve the deployment"
            )
        return component.raw_batch_call(batch)

    return model_fn


class NativeIngressHandle:
    def __init__(self, server, ready_task):
        self.server = server
        self._ready_task = ready_task
        self.port = server.port

    def stats(self) -> dict:
        return self.server.stats()

    async def stop(self) -> None:
        if self._ready_task is not None:
            self._ready_task.cancel()
            try:
                await self._ready_task
            except asyncio.CancelledError:
                pass
            self._ready_task = None
        # off-loop: server.stop() joins worker threads that may be
        # blocked on run_coroutine_threadsafe into THIS loop — joining
        # on the loop thread would deadlock until their timeout
        await asyncio.to_thread(self.server.stop)

    async def cleanup(self) -> None:
        """aiohttp-runner-compatible shutdown, so callers that do
        ``await runner.cleanup()`` work unchanged with frontend=native."""
        await self.stop()


class _DeploymentRawHandler:
    """GatewayRawHandler plus the non-engine GET endpoints the Python
    app serves (/metrics, /seldon.json) so the native ingress is a
    drop-in replacement on the HTTP port."""

    def __init__(self, gateway, loop):
        from seldon_core_tpu.native.frontserver import GatewayRawHandler

        self._inner = GatewayRawHandler(gateway, loop)

    def __call__(self, method: str, path: str, body: bytes) -> Tuple[int, str, bytes]:
        # the C++ lane forwards the full target; match our GET endpoints
        # on a stripped copy but pass the original through (the inner
        # gateway handler reads ?predictor= / ?json= from the query)
        bare = path.split("?", 1)[0]
        if method == "GET" and bare == "/metrics":
            try:
                from prometheus_client import CONTENT_TYPE_LATEST, generate_latest

                return 200, CONTENT_TYPE_LATEST.split(";")[0], generate_latest()
            except Exception as e:  # noqa: BLE001
                return 500, "text/plain", str(e).encode()
        if method == "GET" and bare == "/seldon.json":
            from seldon_core_tpu.runtime.openapi import gateway_openapi

            return 200, "application/json", json.dumps(gateway_openapi()).encode()
        return self._inner(method, path, body)


class _DeploymentGrpcHandler:
    """Full-contract unary gRPC fallback for the native ingress: any
    Seldon method the in-C++ fast lane does not express (SendFeedback,
    Predict with non-tensor payloads, …) arrives here whole and runs
    through the Gateway with full engine semantics — one native server
    for the entire contract, like the reference's Java engine
    (reference: engine/src/main/java/io/seldon/engine/grpc/
    SeldonService.java:30-67)."""

    def __init__(self, gateway, loop):
        self.gateway = gateway
        self.loop = loop

    def __call__(self, path: str, body: bytes):
        from seldon_core_tpu.proto import pb
        from seldon_core_tpu.runtime.component import MicroserviceError
        from seldon_core_tpu.runtime.message import InternalFeedback, InternalMessage

        try:
            if path == "/seldon.protos.Seldon/PredictRaw":
                # zero-copy h2c lane: the gRPC message IS one SRT1 frame
                # (gRPC's own length-prefixed framing delimits it) — no
                # proto parse anywhere on the request path; the reply is
                # the response frame.  Gated like the HTTP frame lane.
                from seldon_core_tpu import codec

                if not codec.zero_copy_enabled():
                    return 12, ("PredictRaw needs SELDON_TPU_ZERO_COPY=1; "
                                "use Seldon/Predict"), b""
                import numpy as np

                try:
                    views = codec.unpack_frames(body)
                except codec.PayloadError as e:
                    return 3, str(e)[:200], b""
                if len(views) > 1:
                    # multi-frame container = the batched-submission
                    # surface (same eligibility rule as the HTTP lane:
                    # single-local-MODEL, no shadows/splits)
                    fast = None
                    if len(self.gateway.entries) == 1 and not self.gateway.shadows:
                        fast = self.gateway.entries[0][0].single_local_model()
                    raw_views = getattr(fast[1], "raw_batch_views", None) if fast else None
                    if raw_views is None:
                        return 3, ("multi-frame containers need a "
                                   "single-local-MODEL predictor with "
                                   "raw_batch_views"), b""
                    try:
                        return 0, "", codec.pack_frames(raw_views(views))
                    except codec.PayloadError as e:
                        # container shape/dtype mismatch is the CLIENT's
                        # fault — INVALID_ARGUMENT, matching the HTTP
                        # twin's 400 for the identical body
                        return 3, str(e)[:200], b""
                msg = InternalMessage(payload=views[0], kind="rawTensor")
                out = asyncio.run_coroutine_threadsafe(
                    self.gateway.predict(msg), self.loop
                ).result(timeout=120.0)
                if out.status and out.status.get("status") == "FAILURE":
                    code = int(out.status.get("code", 500) or 500)
                    return (3 if 400 <= code < 500 else 13), str(
                        out.status.get("info", "engine failure")
                    ), b""
                try:
                    return 0, "", codec.pack_frame(np.asarray(out.host_payload()))
                except codec.PayloadError as e:
                    # healthy answer, un-frameable dtype (strings): the
                    # frame-only lane cannot express it — point the
                    # client at the full-contract method
                    return 3, f"response not frameable ({e}); use Seldon/Predict", b""
            if path == "/seldon.protos.Seldon/Predict":
                msg = InternalMessage.from_proto(pb.SeldonMessage.FromString(body))
                fut = asyncio.run_coroutine_threadsafe(
                    self.gateway.predict(msg), self.loop
                )
            elif path == "/seldon.protos.Seldon/SendFeedback":
                fb = InternalFeedback.from_proto(pb.Feedback.FromString(body))
                fut = asyncio.run_coroutine_threadsafe(
                    self.gateway.send_feedback(fb), self.loop
                )
            else:
                return 12, f"native ingress: no handler for {path}", b""
            out = fut.result(timeout=120.0)
            return 0, "", out.to_proto().SerializeToString()
        except MicroserviceError as e:
            return (3 if 400 <= e.status_code < 500 else 13), str(e), b""
        except Exception as e:  # noqa: BLE001 — wire-level INTERNAL
            logger.exception("native grpc fallback failed for %s", path)
            return 13, str(e)[:200], b""


class _DeploymentGrpcStreamHandler:
    """Seldon/GenerateStream on the native lane: token chunks leave
    through C++ h2 DATA frames as the engine emits them.  The accept
    callback returns immediately; a daemon producer thread drives the
    component's blocking ``predict_stream`` generator and pushes each
    chunk — a dead push (client disconnect) closes the generator, which
    cancels the engine stream (same lifecycle as the Python lane,
    engine/server.py generate_stream)."""

    def __init__(self, gateway, server_ref):
        self.gateway = gateway
        self._server_ref = server_ref  # callable -> NativeFrontServer

    def __call__(self, path: str, body: bytes, handle: int) -> int:
        import threading

        from seldon_core_tpu.proto import pb
        from seldon_core_tpu.runtime.message import InternalMessage

        if path != "/seldon.protos.Seldon/GenerateStream":
            return 12
        server = self._server_ref()
        if server is None:
            return 13
        try:
            msg = InternalMessage.from_proto(pb.SeldonMessage.FromString(body))
        except Exception:  # noqa: BLE001 — malformed request proto
            server.stream_close(handle, 3, "malformed SeldonMessage")
            return 0
        threading.Thread(
            target=self._produce, args=(server, msg, handle),
            name=f"native-genstream-{handle}", daemon=True,
        ).start()
        return 0

    def _produce(self, server, msg, handle: int) -> None:
        import numpy as np

        from seldon_core_tpu.runtime.component import MicroserviceError
        from seldon_core_tpu.runtime.message import InternalMessage

        it = None
        try:
            svc = self.gateway.pick()
            fast = svc.single_local_model()
            component = fast[1] if fast is not None else None
            gen_fn = getattr(component, "predict_stream", None)
            if gen_fn is None:
                server.stream_close(
                    handle, 12,
                    "GenerateStream needs a single-local-model predictor whose "
                    "component implements predict_stream (e.g. STREAMING_LM)",
                )
                return
            meta = {"tags": dict(msg.meta.tags), "puid": msg.meta.puid}
            it = gen_fn(msg.array(), [], meta=meta)
            dead = False
            for chunk in it:
                out = InternalMessage(
                    payload=np.asarray(chunk)[None, :], kind="ndarray"
                )
                out.meta.puid = msg.meta.puid
                if server.stream_push(handle, out.to_proto().SerializeToString()) < 0:
                    dead = True  # client gone: stop decoding
                    break
            # ALWAYS close: the close event is what releases the C++
            # handle and the connection's inflight count — skipping it
            # on a dead stream would leak both for the process lifetime
            # (the server tolerates closing a stream whose h2 side or
            # connection is already gone)
            server.stream_close(handle, 1 if dead else 0,
                                "client cancelled" if dead else "")
        except MicroserviceError as e:
            server.stream_close(
                handle, 3 if 400 <= e.status_code < 500 else 13, str(e)[:200]
            )
        except Exception as e:  # noqa: BLE001 — mid-stream engine fault
            logger.exception("native GenerateStream producer failed")
            server.stream_close(handle, 13, str(e)[:200])
        finally:
            if it is not None:
                it.close()


async def serve_native_ingress(
    gateway,
    host: str = "0.0.0.0",
    http_port: int = 8000,
    max_batch: Optional[int] = None,
    max_wait_ms: float = 1.0,
    batch_threads: Optional[int] = None,
) -> NativeIngressHandle:
    """Start the C++ front server on ``http_port`` for ``gateway``.

    Raises RuntimeError when the native library is unavailable —
    callers fall back to the Python app.
    """
    from seldon_core_tpu.native.frontserver import NativeFrontServer

    import os

    loop = asyncio.get_running_loop()
    handler = _DeploymentRawHandler(gateway, loop)
    grpc_handler = _DeploymentGrpcHandler(gateway, loop)
    server_box: list = [None]
    grpc_stream_handler = _DeploymentGrpcStreamHandler(
        gateway, lambda: server_box[0]
    )
    lane = fast_lane_for(gateway)
    from seldon_core_tpu.runtime import knobs

    if batch_threads is None:
        batch_threads = int(knobs.raw("SELDON_TPU_NATIVE_BATCH_THREADS", "4"))
    # the raw-worker pool now also carries the gRPC fallback lanes
    # (unary SendFeedback/Predict block in fut.result; stream accepts
    # must never queue behind them) — default well above the bare
    # HTTP-fallback sizing of 2
    raw_workers = int(knobs.raw("SELDON_TPU_NATIVE_RAW_WORKERS", "8"))
    kwargs = dict(port=http_port, raw_handler=handler, grpc_handler=grpc_handler,
                  grpc_stream_handler=grpc_stream_handler,
                  max_wait_ms=max_wait_ms, host=host,
                  batch_threads=batch_threads, raw_workers=raw_workers)
    if lane is not None:
        kwargs.update(
            model_fn=_live_model_fn(gateway, lane["feature_dim"], lane["out_dim"]),
            feature_dim=lane["feature_dim"],
            out_dim=lane["out_dim"],
            names=lane["names"],
            model_name=lane["model_name"],
            max_batch=max_batch or lane["max_batch"],
            buckets=lane["buckets"],
        )
        logger.info(
            "native ingress fast lane: model=%s feature_dim=%d out_dim=%d",
            lane["model_name"], lane["feature_dim"], lane["out_dim"],
        )
    else:
        logger.info("native ingress: fallback lane only (graph not fast-lane eligible)")
    server = NativeFrontServer(**kwargs)
    server_box[0] = server
    server.start()

    async def _refresh_ready():
        while True:
            try:
                ok = await gateway.ready()
                server.set_ready(bool(ok))
            except Exception:  # noqa: BLE001 — readiness poll failure = not ready
                server.set_ready(False)
            await asyncio.sleep(0.5)

    task = asyncio.ensure_future(_refresh_ready())
    return NativeIngressHandle(server, task)
