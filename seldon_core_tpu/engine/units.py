"""Builtin graph units — zero-hop implementations selectable by name.

The declarative ``implementation`` field of a graph node picks one of
these instead of a user component or remote endpoint, mirroring the
reference engine's hardcoded units
(reference: SimpleModelUnit.java:29-72, SimpleRouterUnit.java,
AverageCombinerUnit.java, RandomABTestUnit.java:105-112,
PredictorConfigBean.java:20-60).  The stub model is what the published
baseline benchmarks measure (reference:
doc/source/reference/benchmarking.md:19-36), so ours is the unit under
test for data-plane benchmarks too.
"""

from __future__ import annotations

import random
import threading
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from seldon_core_tpu.runtime.component import MicroserviceError, TPUComponent


class StubModel(TPUComponent):
    """Fixed-output model: measures the data plane, not model compute."""

    OUTPUT = np.array([[0.9, 0.05, 0.05]])
    NAMES = ["class0", "class1", "class2"]

    def predict(self, X, names, meta=None):
        return self.OUTPUT

    def class_names(self):
        return self.NAMES


class PassthroughRouter(TPUComponent):
    """Always routes to the first child."""

    def route(self, features, names):
        return 0


class AverageCombiner(TPUComponent):
    """Element-wise mean of children outputs; shapes must agree
    (reference: AverageCombinerUnit.java)."""

    def aggregate(self, features_list, names_list):
        arrays = [np.asarray(f) for f in features_list]
        first = arrays[0].shape
        for a in arrays[1:]:
            if a.shape != first:
                raise MicroserviceError(
                    f"combiner inputs disagree on shape: {first} vs {a.shape}",
                    status_code=400,
                    reason="COMBINER_SHAPE_MISMATCH",
                )
        return np.mean(arrays, axis=0)


class RandomABTest(TPUComponent):
    """Random traffic split between two branches with feedback counters
    (reference: RandomABTestUnit.java:105-112)."""

    def __init__(self, ratio_a: float = 0.5, seed: Optional[int] = None, **kwargs):
        super().__init__(**kwargs)
        self.ratio_a = float(ratio_a)
        self._rng = random.Random(seed)
        self._lock = threading.Lock()
        self.branch_requests = [0, 0]
        self.branch_reward = [0.0, 0.0]

    def route(self, features, names):
        branch = 0 if self._rng.random() < self.ratio_a else 1
        with self._lock:
            self.branch_requests[branch] += 1
        return branch

    def send_feedback(self, features, names, reward, truth, routing=None):
        if routing is not None and 0 <= routing < 2:
            with self._lock:
                self.branch_reward[routing] += reward
        return None

    def checkpoint_state(self):
        with self._lock:
            return {
                "branch_requests": list(self.branch_requests),
                "branch_reward": list(self.branch_reward),
            }

    def restore_state(self, state):
        with self._lock:
            self.branch_requests = list(state["branch_requests"])
            self.branch_reward = list(state["branch_reward"])


# registry: implementation name -> factory(parameters_kwargs) -> component
BUILTIN_IMPLEMENTATIONS: Dict[str, Callable[..., Any]] = {
    # reference-compatible names (reference: seldon_deployment.proto:102-113)
    "SIMPLE_MODEL": StubModel,
    "SIMPLE_ROUTER": PassthroughRouter,
    "AVERAGE_COMBINER": AverageCombiner,
    "RANDOM_ABTEST": RandomABTest,
}


def register_implementation(name: str, factory: Callable[..., Any]) -> None:
    BUILTIN_IMPLEMENTATIONS[name.upper()] = factory


def _load_registrations() -> None:
    """Import the packages whose import side-effect registers the
    prepackaged servers and reusable components."""
    import importlib

    for module in ("seldon_core_tpu.models", "seldon_core_tpu.components"):
        try:
            importlib.import_module(module)
        except ImportError:  # pragma: no cover
            pass


def _lookup_builtin(name: str) -> Callable[..., Any]:
    factory = BUILTIN_IMPLEMENTATIONS.get(name.upper())
    if factory is None:
        _load_registrations()
        factory = BUILTIN_IMPLEMENTATIONS.get(name.upper())
    if factory is None:
        raise MicroserviceError(
            f"unknown builtin implementation {name!r}", status_code=400, reason="UNKNOWN_IMPLEMENTATION"
        )
    return factory


def make_builtin(name: str, **kwargs: Any) -> Any:
    return _lookup_builtin(name)(**kwargs)


def implementation_path(name: str) -> str:
    """Dotted module.Class path of a registered implementation — the
    form the microservice CLI loads (used when an autoscaled node runs
    its implementation out-of-process)."""
    factory = _lookup_builtin(name)
    return f"{factory.__module__}.{factory.__qualname__}"
