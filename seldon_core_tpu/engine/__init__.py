"""Data-plane orchestrator: graph spec, executor, transports, builtins."""

from seldon_core_tpu.engine.graph import (  # noqa: F401
    Endpoint,
    GraphSpecError,
    UnitSpec,
    validate_graph,
)
from seldon_core_tpu.engine.executor import GraphExecutor, build_client  # noqa: F401
from seldon_core_tpu.engine.transport import (  # noqa: F401
    BalancedClient,
    CircuitBreaker,
    backoff_s,
    breakers_enabled,
)
from seldon_core_tpu.engine.service import PredictorService, new_puid  # noqa: F401
from seldon_core_tpu.engine.units import (  # noqa: F401
    BUILTIN_IMPLEMENTATIONS,
    AverageCombiner,
    PassthroughRouter,
    RandomABTest,
    StubModel,
    make_builtin,
    register_implementation,
)
