"""Node transports: how the orchestrator invokes one graph node.

Three client kinds behind one async interface:

* ``LocalClient`` — the node's component lives in this process; methods
  are direct dispatch calls (run on a worker thread so model compute
  never blocks the event loop; XLA releases the GIL during device
  execution).  This replaces the reference's per-hop REST/gRPC
  microservice call + JSON/proto codec
  (reference: InternalPredictionService.java:192-467) for co-located
  nodes.
* ``GrpcClient`` — remote node over gRPC with per-endpoint cached
  channels and per-call deadlines
  (reference: GrpcChannelHandler.java:21-39,
  InternalPredictionService.java:294-340).
* ``RestClient`` — remote node over REST/JSON with connect/read timeouts
  and bounded retries (reference: InternalPredictionService.java:80-98,
  439-467).
"""

from __future__ import annotations

import asyncio
import logging
from typing import Any, Dict, List, Optional

from seldon_core_tpu.engine.graph import (
    AGGREGATE,
    MODEL,
    ROUTE,
    SEND_FEEDBACK,
    TRANSFORM_INPUT,
    TRANSFORM_OUTPUT,
    UnitSpec,
)
from seldon_core_tpu.runtime import dispatch
from seldon_core_tpu.runtime.component import MicroserviceError
from seldon_core_tpu.runtime.message import InternalFeedback, InternalMessage

logger = logging.getLogger(__name__)


class NodeClient:
    """Async invocation surface for one graph node."""

    async def transform_input(self, msg: InternalMessage) -> InternalMessage:
        raise NotImplementedError

    async def transform_output(self, msg: InternalMessage) -> InternalMessage:
        raise NotImplementedError

    async def route(self, msg: InternalMessage) -> InternalMessage:
        raise NotImplementedError

    async def aggregate(self, msgs: List[InternalMessage]) -> InternalMessage:
        raise NotImplementedError

    async def send_feedback(self, feedback: InternalFeedback) -> InternalMessage:
        raise NotImplementedError

    async def ready(self) -> bool:
        return True

    async def close(self) -> None:
        pass


class LocalClient(NodeClient):
    """In-process node: direct dispatch, device arrays pass by handle."""

    def __init__(self, unit: UnitSpec, component: Any):
        self.unit = unit
        self.component = component

    async def _run(self, fn, *args):
        from seldon_core_tpu.runtime.executor_pool import run_dispatch

        return await run_dispatch(fn, *args)

    async def transform_input(self, msg: InternalMessage) -> InternalMessage:
        # A MODEL node's input transform IS its predict
        # (reference: InternalPredictionService.java transformInput routing).
        if self.unit.type == MODEL:
            return await dispatch.predict_async(self.component, msg)
        return await self._run(dispatch.transform_input, self.component, msg)

    async def transform_output(self, msg: InternalMessage) -> InternalMessage:
        return await self._run(dispatch.transform_output, self.component, msg)

    async def route(self, msg: InternalMessage) -> InternalMessage:
        return await self._run(dispatch.route, self.component, msg)

    async def aggregate(self, msgs: List[InternalMessage]) -> InternalMessage:
        return await self._run(dispatch.aggregate, self.component, msgs)

    async def send_feedback(self, feedback: InternalFeedback) -> InternalMessage:
        return await self._run(dispatch.send_feedback, self.component, feedback, self.unit.name)

    async def ready(self) -> bool:
        return True


_METHOD_TO_SERVICE = {
    # method -> (service, rpc, REST path)
    "predict": ("Model", "Predict", "/predict"),
    "transform_input": ("Transformer", "TransformInput", "/transform-input"),
    "transform_output": ("OutputTransformer", "TransformOutput", "/transform-output"),
    "route": ("Router", "Route", "/route"),
    "aggregate": ("Combiner", "Aggregate", "/aggregate"),
    "send_feedback": ("Model", "SendFeedback", "/send-feedback"),
}


class GrpcClient(NodeClient):
    """Remote node over gRPC (channel cached per endpoint)."""

    _channels: Dict[str, Any] = {}

    def __init__(self, unit: UnitSpec, deadline_s: float = 5.0):
        if unit.endpoint is None:
            raise ValueError(f"GrpcClient for {unit.name!r} needs an endpoint")
        self.unit = unit
        self.addr = f"{unit.endpoint.host}:{unit.endpoint.port}"
        self.deadline_s = deadline_s

    def _channel(self):
        import grpc

        chan = GrpcClient._channels.get(self.addr)
        if chan is None:
            chan = grpc.aio.insecure_channel(self.addr)
            GrpcClient._channels[self.addr] = chan
        return chan

    async def _call(self, method: str, request_proto, service_override: Optional[str] = None):
        from seldon_core_tpu.proto import services

        service, rpc, _ = _METHOD_TO_SERVICE[method]
        if service_override:
            service = service_override
        callable_ = services.unary_callable(self._channel(), service, rpc)
        try:
            return await callable_(request_proto, timeout=self.deadline_s)
        except Exception as e:  # grpc.aio.AioRpcError and friends
            raise MicroserviceError(
                f"gRPC call {method} to {self.addr} failed: {e}",
                status_code=502,
                reason="UPSTREAM_GRPC_ERROR",
            ) from e

    async def transform_input(self, msg: InternalMessage) -> InternalMessage:
        method = "predict" if self.unit.type == MODEL else "transform_input"
        resp = await self._call(method, msg.to_proto())
        return InternalMessage.from_proto(resp)

    async def transform_output(self, msg: InternalMessage) -> InternalMessage:
        resp = await self._call("transform_output", msg.to_proto())
        return InternalMessage.from_proto(resp)

    async def route(self, msg: InternalMessage) -> InternalMessage:
        resp = await self._call("route", msg.to_proto())
        return InternalMessage.from_proto(resp)

    async def aggregate(self, msgs: List[InternalMessage]) -> InternalMessage:
        from seldon_core_tpu.proto import pb

        msg_list = pb.SeldonMessageList(seldonMessages=[m.to_proto() for m in msgs])
        resp = await self._call("aggregate", msg_list)
        return InternalMessage.from_proto(resp)

    async def send_feedback(self, feedback: InternalFeedback) -> InternalMessage:
        service = "Router" if self.unit.type == "ROUTER" else "Model"
        resp = await self._call("send_feedback", feedback.to_proto(), service_override=service)
        return InternalMessage.from_proto(resp)

    async def ready(self) -> bool:
        try:
            chan = self._channel()
            await asyncio.wait_for(chan.channel_ready(), timeout=self.deadline_s)
            return True
        except Exception:
            return False

    async def close(self) -> None:
        """Close and evict this endpoint's cached channel (replica
        retirement: the address is never reused, so the cache entry
        would otherwise leak forever)."""
        chan = GrpcClient._channels.pop(self.addr, None)
        if chan is not None:
            await chan.close()

    @classmethod
    async def close_all(cls) -> None:
        for chan in cls._channels.values():
            await chan.close()
        cls._channels.clear()


class RestClient(NodeClient):
    """Remote node over REST/JSON with retries."""

    def __init__(
        self,
        unit: UnitSpec,
        connect_timeout_s: float = 2.0,
        read_timeout_s: float = 5.0,
        retries: int = 3,
    ):
        if unit.endpoint is None:
            raise ValueError(f"RestClient for {unit.name!r} needs an endpoint")
        self.unit = unit
        self.base = f"http://{unit.endpoint.host}:{unit.endpoint.port}"
        self.connect_timeout_s = connect_timeout_s
        self.read_timeout_s = read_timeout_s
        self.retries = retries
        self._session = None

    def _get_session(self):
        import aiohttp

        if self._session is None or self._session.closed:
            timeout = aiohttp.ClientTimeout(
                connect=self.connect_timeout_s, total=self.read_timeout_s
            )
            self._session = aiohttp.ClientSession(timeout=timeout)
        return self._session

    async def _post(self, path: str, body: Dict[str, Any]) -> Dict[str, Any]:
        last_err: Optional[Exception] = None
        for attempt in range(self.retries):
            try:
                session = self._get_session()
                async with session.post(self.base + path, json=body) as resp:
                    payload = await resp.json(content_type=None)
                    if resp.status >= 400:
                        raise MicroserviceError(
                            f"REST call {path} to {self.base} returned {resp.status}: {payload}",
                            status_code=502,
                            reason="UPSTREAM_REST_ERROR",
                        )
                    return payload
            except MicroserviceError:
                raise
            except Exception as e:
                last_err = e
                logger.warning("REST %s attempt %d/%d failed: %s", path, attempt + 1, self.retries, e)
                await asyncio.sleep(0.05 * (attempt + 1))
        raise MicroserviceError(
            f"REST call {path} to {self.base} failed after {self.retries} tries: {last_err}",
            status_code=502,
            reason="UPSTREAM_REST_ERROR",
        )

    async def transform_input(self, msg: InternalMessage) -> InternalMessage:
        path = "/predict" if self.unit.type == MODEL else "/transform-input"
        return InternalMessage.from_json(await self._post(path, msg.to_json()))

    async def transform_output(self, msg: InternalMessage) -> InternalMessage:
        return InternalMessage.from_json(await self._post("/transform-output", msg.to_json()))

    async def route(self, msg: InternalMessage) -> InternalMessage:
        return InternalMessage.from_json(await self._post("/route", msg.to_json()))

    async def aggregate(self, msgs: List[InternalMessage]) -> InternalMessage:
        body = {"seldonMessages": [m.to_json() for m in msgs]}
        return InternalMessage.from_json(await self._post("/aggregate", body))

    async def send_feedback(self, feedback: InternalFeedback) -> InternalMessage:
        return InternalMessage.from_json(await self._post("/send-feedback", feedback.to_json()))

    async def ready(self) -> bool:
        try:
            session = self._get_session()
            async with session.get(self.base + "/health/ping") as resp:
                return resp.status < 400
        except Exception:
            return False

    async def close(self) -> None:
        if self._session is not None and not self._session.closed:
            await self._session.close()


class BalancedClient(NodeClient):
    """Round-robin load balancer over replica clients of one node.

    The role a k8s Service plays in front of an HPA-scaled Deployment in
    the reference (reference:
    operator/controllers/seldondeployment_controller.go:894-930): graph
    edges hold one NodeClient while the replica set behind it grows and
    shrinks.  ``set_clients`` swaps the replica list atomically (the
    autoscaler calls it on every scale event); each call starts at the
    next rotation slot and fails over to the remaining replicas before
    surfacing the last error.
    """

    def __init__(self, clients: Optional[List[NodeClient]] = None):
        import threading

        self._clients: List[NodeClient] = list(clients or [])
        self._retired: List[NodeClient] = []
        self._rr = 0
        self._lock = threading.Lock()

    def set_clients(self, clients: List[NodeClient]) -> None:
        """Swap the replica list; replaced clients are parked and closed
        on the serving loop at the next call (their grpc.aio channels
        are loop-bound, and this method runs on the autoscaler thread)."""
        fresh = list(clients)
        with self._lock:
            keep = set(map(id, fresh))
            self._retired.extend(c for c in self._clients if id(c) not in keep)
            self._clients = fresh

    async def _drain_retired(self) -> None:
        with self._lock:
            retired, self._retired = self._retired, []
        for client in retired:
            try:
                await client.close()
            except Exception as e:  # noqa: BLE001
                logger.debug("closing retired replica client failed: %s", e)

    @property
    def replica_count(self) -> int:
        with self._lock:
            return len(self._clients)

    def _rotation(self) -> List[NodeClient]:
        with self._lock:
            if not self._clients:
                return []
            start = self._rr % len(self._clients)
            self._rr += 1
            return self._clients[start:] + self._clients[:start]

    async def _call(self, method: str, *args, failover: bool = True):
        await self._drain_retired()
        rotation = self._rotation()
        if not rotation:
            raise MicroserviceError(
                "no replicas available", status_code=503, reason="NO_REPLICAS"
            )
        last: Optional[Exception] = None
        for client in rotation:
            try:
                return await getattr(client, method)(*args)
            except MicroserviceError as e:
                # deterministic client errors (4xx) would fail identically
                # on every replica — surface immediately
                if e.status_code is not None and 400 <= e.status_code < 500:
                    raise
                last = e
                if not failover:
                    raise
                logger.warning("replica call %s failed, failing over: %s", method, e)
            except Exception as e:  # noqa: BLE001 — fail over to next replica
                last = e
                if not failover:
                    raise
                logger.warning("replica call %s failed, failing over: %s", method, e)
        raise last  # type: ignore[misc]

    async def transform_input(self, msg: InternalMessage) -> InternalMessage:
        return await self._call("transform_input", msg)

    async def transform_output(self, msg: InternalMessage) -> InternalMessage:
        return await self._call("transform_output", msg)

    async def route(self, msg: InternalMessage) -> InternalMessage:
        return await self._call("route", msg)

    async def aggregate(self, msgs: List[InternalMessage]) -> InternalMessage:
        return await self._call("aggregate", msgs)

    async def send_feedback(self, feedback: InternalFeedback) -> InternalMessage:
        # not idempotent: a timeout after the reward was applied must not
        # replay the same feedback on another replica (double-counting)
        return await self._call("send_feedback", feedback, failover=False)

    async def ready(self) -> bool:
        for client in self._rotation():
            if await client.ready():
                return True
        return False

    async def close(self) -> None:
        await self._drain_retired()
        for client in self._rotation():
            await client.close()
