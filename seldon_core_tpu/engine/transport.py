"""Node transports: how the orchestrator invokes one graph node.

Three client kinds behind one async interface:

* ``LocalClient`` — the node's component lives in this process; methods
  are direct dispatch calls (run on a worker thread so model compute
  never blocks the event loop; XLA releases the GIL during device
  execution).  This replaces the reference's per-hop REST/gRPC
  microservice call + JSON/proto codec
  (reference: InternalPredictionService.java:192-467) for co-located
  nodes.
* ``GrpcClient`` — remote node over gRPC with per-endpoint cached
  channels and per-call deadlines
  (reference: GrpcChannelHandler.java:21-39,
  InternalPredictionService.java:294-340).
* ``RestClient`` — remote node over REST/JSON with connect/read timeouts
  and bounded retries (reference: InternalPredictionService.java:80-98,
  439-467).

Every client is a deadline hop: the ambient end-to-end budget
(utils/deadlines contextvar, minted at ingress from
``X-Seldon-Deadline-Ms`` / gRPC metadata / the native gRPC deadline)
fast-fails the call with ``DEADLINE_EXCEEDED`` *before* dispatch when
it is already spent — naming the exhausted hop — and the REMAINING
budget is re-injected downstream (REST header, gRPC metadata, and the
native gRPC ``timeout`` clamped to it), the per-hop decrement the
reference applies to its internal timeouts
(reference: InternalPredictionService.java:80-98).

Every client is a failure-containment hop (r12): a per-ENDPOINT
:class:`CircuitBreaker` — shared by every caller that dials the
endpoint, across all three lanes — fast-fails calls with a 503
``CIRCUIT_OPEN`` *before* any dial/retry work while the endpoint is
tripped (closed → open on consecutive transient failures → half-open
probe trickle after the cooldown → closed on a probe success), so a
flapping child costs its callers one cheap rejection instead of a full
retry+backoff ladder per request.  Idempotent unary calls can opt into
**hedging** (``seldon.io/hedge-ms``): a duplicate fired to the same
endpoint after the delay races the original first-wins with loser
cancellation — suppressed while the breaker is half-open and when the
remaining deadline budget cannot cover a second attempt.  Retry
backoff is full-jitter (:func:`backoff_s`): deterministic backoff
synchronises callers into the retry storm ``TransportRetryStorm``
alerts on.  ``SELDON_TPU_BREAKER=0`` disables breaking globally; with
breakers off, hedging unset, and no fallback routes the transport is
behaviour-identical to the pre-r12 engine.

Every client is a tracing hop: the current span's W3C context is
injected on the way out (REST headers, gRPC metadata, and
``InternalMessage.meta.trace_context`` for the local/native lanes), so
the receiving runtime parents its spans under the caller's — the role
the reference's opentracing RestTemplate/channel interceptors play
(reference: InternalPredictionService.java:145-149).  Each call also
records per-hop transport telemetry (payload bytes, codec-vs-network
time split, retries, in-flight) into the canonical
``seldon_tpu_transport_*`` metrics (utils/metrics.py) and tags the
enclosing node span with the same numbers for per-request hop tables
(tools/profile_trace_stitch.py).
"""

from __future__ import annotations

import asyncio
import json
import logging
import os
import random
import threading
import time
from contextlib import contextmanager
from typing import Any, Callable, Dict, List, Optional, Tuple

from seldon_core_tpu.engine.graph import (
    AGGREGATE,
    MODEL,
    ROUTE,
    SEND_FEEDBACK,
    TRANSFORM_INPUT,
    TRANSFORM_OUTPUT,
    UnitSpec,
)
from seldon_core_tpu.runtime import dispatch
from seldon_core_tpu.runtime.component import MicroserviceError
from seldon_core_tpu.runtime.message import InternalFeedback, InternalMessage
from seldon_core_tpu.utils import deadlines as _deadlines
from seldon_core_tpu.utils import faults as _faults
from seldon_core_tpu.utils import metrics as _metrics
from seldon_core_tpu.utils import tracing as _tracing

logger = logging.getLogger(__name__)


class _Hop:
    """Meters one NodeClient call: in-flight gauge around the await,
    codec-vs-network wall split, byte counts, retry count.  ``finish``
    folds everything into the ``seldon_tpu_transport_*`` metrics and
    tags the enclosing (node) span so stitched traces carry the hop
    decomposition.  Constructing one is cheap when telemetry is off."""

    __slots__ = (
        "unit", "method", "transport", "t0", "serialize_s",
        "request_bytes", "response_bytes", "zero_copy_bytes", "retries",
        "_gauge",
    )

    def __init__(self, unit: str, method: str, transport: str):
        self.unit, self.method, self.transport = unit, method, transport
        self.serialize_s = 0.0
        self.request_bytes = 0
        self.response_bytes = 0
        # bytes passed BY REFERENCE (buffer views / device handles on
        # the local lane) vs request/response_bytes, which are COPIED
        # through a wire codec — the zero-copy-vs-copied split
        self.zero_copy_bytes = 0
        self.retries = 0
        self._gauge = _metrics.transport_inflight(unit, method, transport)
        if self._gauge is not None:
            self._gauge.inc()
        self.t0 = time.perf_counter()

    @contextmanager
    def codec(self):
        """Time one encode/decode section (the serialization share)."""
        t = time.perf_counter()
        try:
            yield
        finally:
            self.serialize_s += time.perf_counter() - t

    def finish(self, error: bool = False) -> None:
        total = time.perf_counter() - self.t0
        if self._gauge is not None:
            self._gauge.dec()
        network_s = max(0.0, total - self.serialize_s)
        _metrics.record_transport_hop(
            self.unit, self.method, self.transport,
            request_bytes=self.request_bytes,
            response_bytes=self.response_bytes,
            zero_copy_bytes=self.zero_copy_bytes,
            serialize_seconds=self.serialize_s,
            network_seconds=network_s,
            retries=self.retries,
            error=error,
        )
        span = _tracing.current_span()
        if span is not None and not span.remote:
            span.tags["transport"] = self.transport
            if self.transport != "local":
                span.tags["request_bytes"] = self.request_bytes
                span.tags["response_bytes"] = self.response_bytes
                span.tags["serialize_ms"] = round(self.serialize_s * 1000.0, 3)
                span.tags["network_ms"] = round(network_s * 1000.0, 3)
            if self.zero_copy_bytes:
                span.tags["zero_copy_bytes"] = self.zero_copy_bytes
            if self.retries:
                span.tags["retries"] = self.retries
            if error:
                span.tags["error"] = True


@contextmanager
def kv_handoff_hop(unit: str, transport: str = "local"):
    """Meter one disaggregated KV-page handoff (prefill worker ->
    decode pool) through the SAME ``seldon_tpu_transport_*`` surface
    NodeClient hops use, under ``method="kv_handoff"`` — so the
    dashboards price the handoff lane next to the request lanes it
    displaces.  The caller sets byte counts on the yielded hop:
    ``zero_copy_bytes`` for the local buffer-view lane (the container
    is passed by reference and reopened as views), ``request_bytes``
    for a DCN transfer that re-encoded it.  Yields None when telemetry
    is off — metering must cost nothing then."""
    if not _metrics.transport_telemetry_enabled():
        yield None
        return
    hop = _Hop(unit, "kv_handoff", transport)
    try:
        yield hop
    except BaseException:
        hop.finish(error=True)
        raise
    hop.finish()


@contextmanager
def migration_hop(unit: str, transport: str = "local"):
    """Meter one live-stream migration (evacuating engine -> healthy
    peer) under ``method="migrate"`` — same canonical transport surface
    as :func:`kv_handoff_hop`, so the dashboards price evacuations next
    to the request and handoff lanes.  ``zero_copy_bytes`` for the
    in-process adoption lane (payload passes by reference),
    ``request_bytes`` for a DCN container ship.  Yields None when
    telemetry is off."""
    if not _metrics.transport_telemetry_enabled():
        yield None
        return
    hop = _Hop(unit, "migrate", transport)
    try:
        yield hop
    except BaseException:
        hop.finish(error=True)
        raise
    hop.finish()


def backoff_s(attempt: int, base_s: float = 0.05, cap_s: float = 2.0) -> float:
    """Full-jitter exponential backoff for attempt ``attempt`` (0-based
    retry index): uniform over [0, min(cap, base * 2^attempt)].

    Deterministic backoff synchronises callers: every client that saw
    the same failure retries at the same instant, so a restarting
    upstream takes the whole herd again at once — the exact storm the
    ``TransportRetryStorm`` alert pages on.  Full jitter (AWS
    architecture-blog discipline) spreads the herd over the window."""
    return random.uniform(0.0, min(cap_s, base_s * (2 ** max(0, attempt))))


# ---------------------------------------------------------------------------
# per-endpoint circuit breakers
# ---------------------------------------------------------------------------

BREAKER_CLOSED = "closed"
BREAKER_OPEN = "open"
BREAKER_HALF_OPEN = "half_open"


def breakers_enabled() -> bool:
    """SELDON_TPU_BREAKER=0 disables circuit breaking globally (the
    parity lane: breaker-off behaviour is byte-identical to the
    pre-breaker engine)."""
    from seldon_core_tpu.runtime import knobs

    return knobs.flag("SELDON_TPU_BREAKER")


class CircuitBreaker:
    """One endpoint's failure-containment state machine, SHARED by every
    client that dials the endpoint (keyed by endpoint, not caller: a
    flapping child must fail fast for all of its callers at once, not be
    re-probed by each on every request).

    closed --[``failures`` consecutive transient failures]--> open
    open   --[``reset_s`` cooldown elapsed]-->                half-open
    half-open --[a probe succeeds]-->                         closed
    half-open --[a probe fails transiently]-->                open

    While open, :meth:`acquire` raises a 503 ``CIRCUIT_OPEN``
    *before* any dial/retry ladder — the same pre-dispatch fast-fail
    discipline as the deadline check.  While half-open, at most
    ``probes`` concurrent calls pass through as probes; the rest keep
    fast-failing so a recovering upstream is not re-stampeded.

    Only *transient* outcomes (the retry classifier's set: UNAVAILABLE /
    DEADLINE_EXCEEDED / RESOURCE_EXHAUSTED statuses, REST 502/503/504,
    connection faults) count toward a trip; a deterministic reply (4xx,
    plain 500) proves the endpoint is alive and RESETS the streak.
    """

    _registry: Dict[str, "CircuitBreaker"] = {}
    _registry_lock = threading.Lock()

    def __init__(self, key: str, failures: int = 5, reset_s: float = 1.0,
                 probes: int = 2):
        self.key = key
        self.failures = max(1, int(failures))
        self.reset_s = float(reset_s)
        self.probes = max(1, int(probes))
        self._lock = threading.Lock()
        self._state = BREAKER_CLOSED
        self._streak = 0  # consecutive transient failures while closed
        self._open_until = 0.0
        self._probes_inflight = 0
        # incident counters (bench + tests read these; prometheus gets
        # transitions/fastfails through utils.metrics)
        self.counters = {
            "trips": 0, "reopens": 0, "closes": 0,
            "fastfails": 0, "probes": 0, "transient_failures": 0,
        }

    # ---- registry ---------------------------------------------------------

    @classmethod
    def for_endpoint(cls, key: str, failures: int = 5, reset_s: float = 1.0,
                     probes: int = 2) -> "CircuitBreaker":
        """The shared breaker for ``key`` (created on first use;
        first-creator's config wins — per-endpoint knobs come from ONE
        deployment's annotations, so racing configs don't happen in
        practice)."""
        with cls._registry_lock:
            b = cls._registry.get(key)
            if b is None:
                b = cls(key, failures=failures, reset_s=reset_s, probes=probes)
                cls._registry[key] = b
            return b

    @classmethod
    def discard(cls, key: str) -> None:
        """Evict one endpoint's breaker (replica retirement: autoscaled
        replicas use fresh ephemeral ports, so without eviction the
        registry — and the per-endpoint breaker-state label series —
        would grow monotonically with every scale event, the same leak
        the gRPC channel cache eviction exists for)."""
        with cls._registry_lock:
            cls._registry.pop(key, None)

    @classmethod
    def reset_all(cls) -> None:
        """Drop every registered breaker (test isolation; a fresh
        deployment starts every endpoint closed)."""
        with cls._registry_lock:
            cls._registry.clear()

    # ---- state machine ----------------------------------------------------

    @property
    def state(self) -> str:
        with self._lock:
            return self._effective_state_locked()

    def _effective_state_locked(self) -> str:
        """OPEN lazily decays to HALF_OPEN when the cooldown elapsed —
        computed on read so no timer thread is needed."""
        if self._state == BREAKER_OPEN and \
                time.monotonic() >= self._open_until:
            self._state = BREAKER_HALF_OPEN
            self._probes_inflight = 0
            self._note_transition(BREAKER_HALF_OPEN)
        return self._state

    def _note_transition(self, to_state: str) -> None:
        _metrics.record_breaker_state(self.key, to_state)

    def acquire(self, unit: str, method: str, transport: str) -> bool:
        """Admission decision for one call: returns True when the call
        is a half-open PROBE (the caller must report its outcome), False
        on the ordinary closed path — or raises the 503 ``CIRCUIT_OPEN``
        fast-fail before any dispatch work happens."""
        with self._lock:
            state = self._effective_state_locked()
            if state == BREAKER_CLOSED:
                return False
            if state == BREAKER_HALF_OPEN and \
                    self._probes_inflight < self.probes:
                self._probes_inflight += 1
                self.counters["probes"] += 1
                return True
            self.counters["fastfails"] += 1
            remaining = max(0.0, self._open_until - time.monotonic())
        _metrics.record_breaker_fastfail(unit, method, transport)
        raise MicroserviceError(
            f"circuit open for {self.key}: {self.failures} consecutive "
            f"transient failures tripped the breaker (node {unit!r} "
            f"{method}; next probe in {remaining:.2f}s)",
            status_code=503, reason="CIRCUIT_OPEN",
        )

    def on_transient(self) -> None:
        """One transient failure ATTEMPT (counts toward the trip
        threshold; any transient failure while half-open reopens
        immediately).  Probe-slot release is separate (:meth:`release`)
        so a multi-attempt call reports per-attempt evidence but
        settles exactly once."""
        with self._lock:
            self.counters["transient_failures"] += 1
            state = self._effective_state_locked()
            if state == BREAKER_HALF_OPEN:
                self._state = BREAKER_OPEN
                self._open_until = time.monotonic() + self.reset_s
                self._streak = 0
                self.counters["reopens"] += 1
                self._note_transition(BREAKER_OPEN)
                return
            if state == BREAKER_CLOSED:
                self._streak += 1
                if self._streak >= self.failures:
                    self._state = BREAKER_OPEN
                    self._open_until = time.monotonic() + self.reset_s
                    self._streak = 0
                    self.counters["trips"] += 1
                    self._note_transition(BREAKER_OPEN)

    def release(self, probe: bool, healthy: Optional[bool]) -> None:
        """Settle one admitted call.  ``healthy=True`` (a reply came
        back — success OR a deterministic error: the endpoint answered)
        resets the streak and closes a half-open breaker; ``False``
        (transient exhaustion — the attempts already counted) and
        ``None`` (cancelled, no evidence) only release the probe slot."""
        with self._lock:
            if probe:
                self._probes_inflight = max(0, self._probes_inflight - 1)
            if healthy:
                self._streak = 0
                if self._state == BREAKER_HALF_OPEN:
                    self._state = BREAKER_CLOSED
                    self.counters["closes"] += 1
                    self._note_transition(BREAKER_CLOSED)

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {"state": self._effective_state_locked(),
                    "streak": self._streak, **self.counters}


class _BreakerCall:
    """Pairs one breaker acquire with exactly one settle.  The clients
    thread it through their try/except/finally so every exit path
    (success, transient exhaustion, deterministic error, hedge-loser
    cancellation) releases the probe slot exactly once, while
    per-attempt transient evidence feeds the trip threshold as it
    happens."""

    __slots__ = ("breaker", "probe", "_settled")

    def __init__(self, breaker: Optional["CircuitBreaker"],
                 unit: str, method: str, transport: str):
        self.breaker = breaker
        self.probe = (
            breaker.acquire(unit, method, transport)
            if breaker is not None else False
        )
        self._settled = breaker is None

    def attempt_transient(self) -> None:
        """One transient failure attempt (mid- or end-of-ladder)."""
        if self.breaker is not None:
            self.breaker.on_transient()

    def settle(self, healthy: Optional[bool]) -> None:
        if not self._settled:
            self._settled = True
            self.breaker.release(self.probe, healthy)

    def open_now(self) -> bool:
        """True when the breaker is no longer closed — the retry ladder
        reads this between attempts so an open circuit stops the ladder
        instead of burning the remaining backoff budget."""
        return (
            self.breaker is not None
            and self.breaker.state != BREAKER_CLOSED
        )


def _resolve_breaker(key: str, breaker) -> Optional[CircuitBreaker]:
    """Ctor-argument convention shared by the three client lanes:
    ``None`` = the endpoint's shared default breaker (unless globally
    disabled), ``False`` = breaker off for this client, an instance =
    use it (build_client passes annotation-configured ones)."""
    if breaker is False:
        return None
    if isinstance(breaker, CircuitBreaker):
        return breaker
    return CircuitBreaker.for_endpoint(key) if breakers_enabled() else None


class NodeClient:
    """Async invocation surface for one graph node."""

    async def transform_input(self, msg: InternalMessage) -> InternalMessage:
        raise NotImplementedError

    async def transform_output(self, msg: InternalMessage) -> InternalMessage:
        raise NotImplementedError

    async def route(self, msg: InternalMessage) -> InternalMessage:
        raise NotImplementedError

    async def aggregate(self, msgs: List[InternalMessage]) -> InternalMessage:
        raise NotImplementedError

    async def send_feedback(self, feedback: InternalFeedback) -> InternalMessage:
        raise NotImplementedError

    async def ready(self) -> bool:
        return True

    async def close(self) -> None:
        pass


class LocalClient(NodeClient):
    """In-process node: direct dispatch, device arrays pass by handle.

    The tracing hop still exists: the caller's span context propagates
    BOTH through contextvars (run_dispatch copies the caller's context
    onto the pool thread) and explicitly via ``meta.trace_context`` —
    the in-memory lane of the same contract the remote clients put on
    the wire, so dispatch parents identically whichever path survived
    (a queue hand-off loses the contextvar; the meta doesn't)."""

    def __init__(self, unit: UnitSpec, component: Any, breaker=None):
        self.unit = unit
        self.component = component
        # local lane breaker: keyed by unit (there is no endpoint), and
        # tripped ONLY by crash-shaped errors (non-MicroserviceError
        # exceptions).  A well-formed MicroserviceError — 4xx, SHED, an
        # engine's contained chunk fault — is the component SPEAKING,
        # not dead; counting those would turn load shedding into a
        # self-inflicted outage.
        self.breaker = _resolve_breaker(f"local:{unit.name}", breaker)

    async def _run(self, fn, *args):
        from seldon_core_tpu.runtime.executor_pool import run_dispatch

        return await run_dispatch(fn, *args)

    @staticmethod
    def _inject_meta(msg: Any) -> None:
        first = msg[0] if isinstance(msg, list) and msg else msg
        meta = getattr(first, "meta", None) or getattr(
            getattr(first, "request", None), "meta", None
        )
        if meta is not None:
            _tracing.inject(meta.trace_context)

    @staticmethod
    def _ref_bytes(msg: Any) -> int:
        """Payload bytes this hop passes BY REFERENCE: buffer views and
        device-resident arrays cross the local lane as handles, never
        through a codec — the `zero_copy_bytes` share of `_Hop` (the
        remote lanes' request/response_bytes are the COPIED share)."""
        from seldon_core_tpu.codec import BufferView, is_device_array

        total = 0
        for m in (msg if isinstance(msg, list) else [msg]):
            payload = getattr(m, "payload", None)
            if isinstance(payload, BufferView) or is_device_array(payload):
                total += int(getattr(payload, "nbytes", 0) or 0)
        return total

    async def _invoke(self, method: str, factory: Callable[[], Any],
                      msg: Any = None):
        # spent budget: fail before dispatch — the model must never see
        # a request its caller has already abandoned
        _deadlines.check(f"node {self.unit.name!r} {method} (local)")
        # open breaker: fail before dispatch too (same discipline; the
        # acquire raises the 503 CIRCUIT_OPEN fast-fail itself)
        call = _BreakerCall(self.breaker, self.unit.name, method, "local")
        hop = _Hop(self.unit.name, method, "local")
        if hop._gauge is not None and msg is not None:
            # lazy: the isinstance/nbytes walk only runs when telemetry
            # is ON (the gauge child existing is exactly that signal) —
            # off-path local hops stay as cheap as before the lane
            hop.zero_copy_bytes = self._ref_bytes(msg)
        ok = False
        healthy: Optional[bool] = False
        try:
            out = await factory()
            ok = True
            healthy = True
            return out
        except MicroserviceError:
            healthy = True  # a well-formed error is the component speaking
            raise
        except asyncio.CancelledError:
            healthy = None
            raise
        except Exception:
            call.attempt_transient()  # crash-shaped: counts toward the trip
            raise
        finally:
            call.settle(healthy)
            hop.finish(error=not ok)

    async def transform_input(self, msg: InternalMessage) -> InternalMessage:
        self._inject_meta(msg)
        # A MODEL node's input transform IS its predict
        # (reference: InternalPredictionService.java transformInput routing).
        if self.unit.type == MODEL:
            return await self._invoke(
                "predict", lambda: dispatch.predict_async(self.component, msg),
                msg=msg,
            )
        return await self._invoke(
            "transform_input",
            lambda: self._run(dispatch.transform_input, self.component, msg),
            msg=msg,
        )

    async def transform_output(self, msg: InternalMessage) -> InternalMessage:
        self._inject_meta(msg)
        return await self._invoke(
            "transform_output",
            lambda: self._run(dispatch.transform_output, self.component, msg),
            msg=msg,
        )

    async def route(self, msg: InternalMessage) -> InternalMessage:
        self._inject_meta(msg)
        return await self._invoke(
            "route", lambda: self._run(dispatch.route, self.component, msg),
            msg=msg,
        )

    async def aggregate(self, msgs: List[InternalMessage]) -> InternalMessage:
        self._inject_meta(msgs)
        return await self._invoke(
            "aggregate", lambda: self._run(dispatch.aggregate, self.component, msgs),
            msg=msgs,
        )

    async def send_feedback(self, feedback: InternalFeedback) -> InternalMessage:
        self._inject_meta(feedback)
        return await self._invoke(
            "send_feedback",
            lambda: self._run(
                dispatch.send_feedback, self.component, feedback, self.unit.name
            ),
        )

    async def ready(self) -> bool:
        return True


async def _hedged_call(client, method: str, transport: str, factory):
    """First-wins hedging for one idempotent unary call (opt-in via the
    per-node ``seldon.io/hedge-ms`` annotation): when the primary has
    not answered within ``hedge_ms``, fire ONE duplicate of the same
    call to the same endpoint and return whichever finishes first,
    cancelling the loser.  A straggler then costs ~hedge_ms + a median
    service time instead of a full tail quantile.

    Suppressed (plain single call) when:
    * hedging is off for this client (``hedge_ms <= 0``),
    * the endpoint's breaker is not CLOSED — a half-open upstream is
      being probed at a deliberate trickle, and doubling traffic into
      it is exactly how recovering services get re-killed,
    * the remaining end-to-end budget cannot cover a second attempt
      (``remaining <= hedge_ms``: by the time the hedge would fire the
      deadline is spent — the duplicate could never win).

    Error semantics: the FIRST completed success wins; if one lane
    errors the other's outcome is awaited; when both error, the
    primary's error surfaces (it carries the fuller attempt history).
    """
    if client.hedge_ms <= 0:
        return await factory()
    breaker = client.breaker
    if breaker is not None and breaker.state != BREAKER_CLOSED:
        return await factory()
    ambient = _deadlines.current_deadline()
    if ambient is not None and ambient.remaining_ms() <= client.hedge_ms:
        return await factory()
    primary = asyncio.ensure_future(factory())
    await asyncio.wait({primary}, timeout=client.hedge_ms / 1000.0)
    if primary.done():
        return primary.result()  # raises the primary's error unchanged
    client.hedges_fired += 1
    _metrics.record_transport_hedge(client.unit.name, method, transport)
    hedge = asyncio.ensure_future(factory())
    pending = {primary, hedge}
    errors: List[Tuple[Any, BaseException]] = []
    try:
        while pending:
            done, pending = await asyncio.wait(
                pending, return_when=asyncio.FIRST_COMPLETED
            )
            for task in done:
                if task.cancelled():
                    continue
                exc = task.exception()
                if exc is not None:
                    errors.append((task, exc))
                    continue
                if task is hedge:
                    client.hedge_wins += 1
                    _metrics.record_transport_hedge(
                        client.unit.name, method, transport, won=True
                    )
                return task.result()
    finally:
        # loser cancellation — and on any exit, never leak a task
        for task in (primary, hedge):
            if not task.done():
                task.cancel()
        await asyncio.gather(primary, hedge, return_exceptions=True)
    # both lanes failed: surface the primary's error (fuller history)
    for task, exc in errors:
        if task is primary:
            raise exc
    raise errors[0][1]


_METHOD_TO_SERVICE = {
    # method -> (service, rpc, REST path)
    "predict": ("Model", "Predict", "/predict"),
    "transform_input": ("Transformer", "TransformInput", "/transform-input"),
    "transform_output": ("OutputTransformer", "TransformOutput", "/transform-output"),
    "route": ("Router", "Route", "/route"),
    "aggregate": ("Combiner", "Aggregate", "/aggregate"),
    "send_feedback": ("Model", "SendFeedback", "/send-feedback"),
}


def _grpc_status_name(e: Exception) -> Optional[str]:
    """The status-code name of a grpc error, or None for non-grpc."""
    code = getattr(e, "code", None)
    try:
        got = code() if callable(code) else code
        return got.name if got is not None else None
    except Exception:  # noqa: BLE001 — anything weird is "not grpc"
        return None


def _grpc_retryable(e: Exception) -> bool:
    """Transient statuses worth another attempt within the call budget
    (the reference's RestTemplate retries the analogous REST faults)."""
    return _grpc_status_name(e) in (
        "UNAVAILABLE", "DEADLINE_EXCEEDED", "RESOURCE_EXHAUSTED",
    )


class GrpcClient(NodeClient):
    """Remote node over gRPC (channel cached per endpoint), with
    bounded retries on transient statuses.  An exhausted call raises a
    ``MicroserviceError`` carrying the FULL per-attempt history
    (status code + elapsed per attempt) on ``.attempts`` and in the
    message — post-mortems see every retry, not just the last error."""

    _channels: Dict[str, Any] = {}
    # strong refs to the deferred channel-close tasks: the event loop
    # holds tasks only weakly, so a fire-and-forget ensure_future could
    # be garbage-collected mid-sleep and leak the channel's sockets
    _closers: set = set()

    def __init__(self, unit: UnitSpec, deadline_s: float = 5.0, retries: int = 3,
                 breaker=None, hedge_ms: float = 0.0):
        if unit.endpoint is None:
            raise ValueError(f"GrpcClient for {unit.name!r} needs an endpoint")
        self.unit = unit
        self.addr = f"{unit.endpoint.host}:{unit.endpoint.port}"
        self.deadline_s = deadline_s
        self.retries = max(1, int(retries))
        # per-endpoint breaker, SHARED with every other client dialling
        # this address (None = registry default, False = off, instance =
        # annotation-configured by build_client)
        self.breaker = _resolve_breaker(self.addr, breaker)
        # hedging (seldon.io/hedge-ms): after hedge_ms with no reply, a
        # duplicate of the same idempotent call races the original
        self.hedge_ms = float(hedge_ms)
        self.hedges_fired = 0
        self.hedge_wins = 0

    def _channel(self):
        import grpc

        chan = GrpcClient._channels.get(self.addr)
        if chan is None:
            # local subchannel pool: without it grpc-core SHARES
            # subchannels globally per target, so the fresh channel
            # _reset_channel creates would inherit the old, backed-off
            # subchannel and keep failing fast (we hold one channel per
            # address anyway — cross-channel sharing buys nothing here)
            chan = grpc.aio.insecure_channel(
                self.addr, options=[("grpc.use_local_subchannel_pool", 1)]
            )
            GrpcClient._channels[self.addr] = chan
        return chan

    async def _reset_channel(self) -> None:
        """Drop the cached channel after UNAVAILABLE: a channel whose
        subchannel is in reconnect backoff fails new RPCs FAST without
        attempting a connection (wait_for_ready is off), so a retry on
        the same channel — or the first call after the worker respawns
        — would keep failing for the whole backoff window.  A fresh
        channel attempts to connect immediately.

        The old channel is closed LAZILY, one deadline later: closing
        immediately would cancel every sibling RPC still in flight on
        it (grpc.aio close semantics), amplifying one transient fault
        into N CANCELLED requests; by deadline+1s every such RPC has
        completed or timed out on its own."""
        chan = GrpcClient._channels.pop(self.addr, None)
        if chan is None:
            return

        async def close_later(delay: float) -> None:
            await asyncio.sleep(delay)
            try:
                await chan.close()
            except Exception as e:  # noqa: BLE001 — best-effort channel cleanup
                logger.debug("closing backed-off channel failed: %s", e)

        task = asyncio.ensure_future(close_later(self.deadline_s + 1.0))
        GrpcClient._closers.add(task)
        task.add_done_callback(GrpcClient._closers.discard)

    async def _call(
        self,
        method: str,
        build: Callable[[], Any],
        service_override: Optional[str] = None,
        idempotent: bool = True,
    ) -> InternalMessage:
        from seldon_core_tpu.proto import services

        service, rpc, _ = _METHOD_TO_SERVICE[method]
        if service_override:
            service = service_override
        _deadlines.check(f"node {self.unit.name!r} {method} (grpc {self.addr})")
        # open breaker: fast-fail BEFORE the codec/dial work, like the
        # deadline check above (acquire raises the 503 CIRCUIT_OPEN)
        call = _BreakerCall(self.breaker, self.unit.name, method, "grpc")
        hop = _Hop(self.unit.name, method, "grpc")
        ok = False
        healthy: Optional[bool] = False
        try:
            with hop.codec():
                request_proto = build()
                hop.request_bytes = request_proto.ByteSize()
            base_metadata = _tracing.inject_metadata()
            attempts: List[Dict[str, Any]] = []
            last: Optional[Exception] = None
            budget = self.retries if idempotent else 1
            for attempt in range(budget):
                if attempt:
                    hop.retries += 1
                    # retries respect the end-to-end budget too: a dead
                    # upstream must not eat the caller's whole deadline
                    _deadlines.check(
                        f"node {self.unit.name!r} {method} retry "
                        f"{attempt + 1} (grpc {self.addr})"
                    )
                    if call.open_now():
                        # the circuit opened mid-ladder (this call's own
                        # failures crossed the threshold, or a sibling's
                        # did): stop burning the retry/backoff budget —
                        # the accumulated error surfaces below
                        break
                # re-inject PER ATTEMPT: the remaining budget shrank by
                # whatever the failed attempt burned — resending the
                # pre-attempt value would refund it downstream
                metadata = _deadlines.inject_metadata(list(base_metadata))
                callable_ = services.unary_callable(self._channel(), service, rpc)
                # native gRPC deadline clamped to the remaining
                # end-to-end budget: the hop decrement on the wire
                timeout_s = self.deadline_s
                ambient = _deadlines.current_deadline()
                if ambient is not None:
                    timeout_s = max(0.001, min(timeout_s, ambient.remaining_s()))
                t_attempt = time.perf_counter()
                try:
                    delay = (
                        _faults.delay_s("transport.delay")
                        + _faults.delay_s("transport.slow")
                    )
                    if delay:
                        await asyncio.sleep(delay)
                    _faults.raise_if("transport.drop")
                    resp = await callable_(
                        request_proto, timeout=timeout_s, metadata=metadata
                    )
                    hop.response_bytes = resp.ByteSize()
                    with hop.codec():
                        out = InternalMessage.from_proto(resp)
                    ok = True
                    healthy = True
                    return out
                except Exception as e:  # grpc.aio.AioRpcError and friends
                    last = e
                    attempts.append({
                        "attempt": attempt + 1,
                        "status": _grpc_status_name(e) or type(e).__name__,
                        "elapsed_ms": round(
                            (time.perf_counter() - t_attempt) * 1000.0, 3
                        ),
                    })
                    retryable = _grpc_retryable(e)
                    if retryable:
                        call.attempt_transient()
                    else:
                        # a deterministic reply proves the endpoint is
                        # alive — it must not count toward a trip
                        healthy = True
                    if _grpc_status_name(e) == "UNAVAILABLE":
                        # fresh channel for the next attempt (or the
                        # next CALL): the old one is in reconnect
                        # backoff and would fail fast for its duration
                        await self._reset_channel()
                    if not retryable or attempt + 1 >= budget:
                        break
                    logger.warning(
                        "gRPC %s to %s attempt %d/%d failed: %s",
                        method, self.addr, attempt + 1, budget, e,
                    )
                    # full jitter: synchronized deterministic backoff is
                    # the retry-storm shape (TransportRetryStorm)
                    await asyncio.sleep(backoff_s(attempt))
            err = MicroserviceError(
                f"gRPC call {method} to {self.addr} failed: {last} "
                f"(attempts: {json.dumps(attempts)})",
                status_code=502,
                reason="UPSTREAM_GRPC_ERROR",
            )
            err.attempts = attempts  # machine-readable per-attempt history
            # transience classification for the fallback layer: a
            # deterministic upstream reply (INVALID_ARGUMENT, ...) would
            # fail identically on a fallback route — only transient
            # exhaustion is worth a degraded answer
            err.transient = last is None or _grpc_retryable(last)
            raise err from last
        except asyncio.CancelledError:
            healthy = None  # hedge loser / caller gone: no evidence
            raise
        finally:
            call.settle(healthy)
            hop.finish(error=not ok)

    async def transform_input(self, msg: InternalMessage) -> InternalMessage:
        method = "predict" if self.unit.type == MODEL else "transform_input"
        return await _hedged_call(
            self, method, "grpc", lambda: self._call(method, msg.to_proto)
        )

    async def transform_output(self, msg: InternalMessage) -> InternalMessage:
        return await _hedged_call(
            self, "transform_output", "grpc",
            lambda: self._call("transform_output", msg.to_proto),
        )

    async def route(self, msg: InternalMessage) -> InternalMessage:
        return await _hedged_call(
            self, "route", "grpc", lambda: self._call("route", msg.to_proto)
        )

    async def aggregate(self, msgs: List[InternalMessage]) -> InternalMessage:
        def build():
            from seldon_core_tpu.proto import pb

            return pb.SeldonMessageList(seldonMessages=[m.to_proto() for m in msgs])

        return await _hedged_call(
            self, "aggregate", "grpc", lambda: self._call("aggregate", build)
        )

    async def send_feedback(self, feedback: InternalFeedback) -> InternalMessage:
        # not idempotent: a deadline after the reward was applied must
        # not replay it (same rule as BalancedClient's failover)
        service = "Router" if self.unit.type == "ROUTER" else "Model"
        return await self._call(
            "send_feedback", feedback.to_proto, service_override=service,
            idempotent=False,
        )

    async def ready(self) -> bool:
        try:
            chan = self._channel()
            await asyncio.wait_for(chan.channel_ready(), timeout=self.deadline_s)
            return True
        except Exception:  # any dial failure reads as not-ready
            return False

    async def close(self) -> None:
        """Close and evict this endpoint's cached channel AND its
        registry breaker (replica retirement: the address is never
        reused, so both entries would otherwise leak forever)."""
        chan = GrpcClient._channels.pop(self.addr, None)
        if chan is not None:
            await chan.close()
        CircuitBreaker.discard(self.addr)

    @classmethod
    async def close_all(cls) -> None:
        for chan in cls._channels.values():
            await chan.close()
        cls._channels.clear()


# HTTP statuses worth another attempt within the call budget: the
# upstream is overloaded or mid-restart, not wrong (the reference's
# RestTemplate retries the same class of faults,
# reference: InternalPredictionService.java:80-98).  Everything else —
# 4xx, plain 500 — would fail identically on every attempt.
_REST_RETRYABLE_STATUSES = (502, 503, 504)


class RestClient(NodeClient):
    """Remote node over REST/JSON with bounded retries on transient
    faults, matching ``GrpcClient``'s semantics: exponential backoff,
    the FULL per-attempt history (status + elapsed per attempt) on
    ``MicroserviceError.attempts`` and in the message, retries metered
    into the hop telemetry, and ``send_feedback`` exempt (non-idempotent
    — a timeout after the reward was applied must not replay it)."""

    def __init__(
        self,
        unit: UnitSpec,
        connect_timeout_s: float = 2.0,
        read_timeout_s: float = 5.0,
        retries: int = 3,
        breaker=None,
        hedge_ms: float = 0.0,
    ):
        if unit.endpoint is None:
            raise ValueError(f"RestClient for {unit.name!r} needs an endpoint")
        self.unit = unit
        self.base = f"http://{unit.endpoint.host}:{unit.endpoint.port}"
        self.connect_timeout_s = connect_timeout_s
        self.read_timeout_s = read_timeout_s
        self.retries = max(1, int(retries))
        # shared per-endpoint breaker + opt-in hedging: same semantics
        # as GrpcClient (the two remote lanes must not drift)
        self.breaker = _resolve_breaker(
            f"{unit.endpoint.host}:{unit.endpoint.port}", breaker
        )
        self.hedge_ms = float(hedge_ms)
        self.hedges_fired = 0
        self.hedge_wins = 0
        self._session = None

    def _get_session(self):
        import aiohttp

        if self._session is None or self._session.closed:
            timeout = aiohttp.ClientTimeout(
                connect=self.connect_timeout_s, total=self.read_timeout_s
            )
            self._session = aiohttp.ClientSession(timeout=timeout)
        return self._session

    async def _post(
        self,
        path: str,
        method: str,
        encode: Callable[[], Dict[str, Any]],
        idempotent: bool = True,
    ) -> InternalMessage:
        _deadlines.check(f"node {self.unit.name!r} {method} (rest {self.base})")
        # open breaker: fast-fail BEFORE the codec/dial work (the
        # acquire raises the 503 CIRCUIT_OPEN)
        call = _BreakerCall(self.breaker, self.unit.name, method, "rest")
        hop = _Hop(self.unit.name, method, "rest")
        ok = False
        healthy: Optional[bool] = False
        try:
            with hop.codec():
                data = json.dumps(encode()).encode()
                hop.request_bytes = len(data)
            base_headers = _tracing.inject({"Content-Type": "application/json"})
            attempts: List[Dict[str, Any]] = []
            last_err: Optional[Exception] = None
            budget = self.retries if idempotent else 1
            for attempt in range(budget):
                if attempt:
                    hop.retries += 1
                    _deadlines.check(
                        f"node {self.unit.name!r} {method} retry "
                        f"{attempt + 1} (rest {self.base})"
                    )
                    if call.open_now():
                        # circuit opened mid-ladder: stop burning the
                        # retry/backoff budget, surface the accumulated
                        # error below
                        break
                # re-inject PER ATTEMPT: the remaining budget shrank by
                # whatever the failed attempt burned — resending the
                # pre-attempt value would refund it downstream
                headers = _deadlines.inject(dict(base_headers))
                t_attempt = time.perf_counter()
                try:
                    delay = (
                        _faults.delay_s("transport.delay")
                        + _faults.delay_s("transport.slow")
                    )
                    if delay:
                        await asyncio.sleep(delay)
                    _faults.raise_if("transport.drop")
                    session = self._get_session()
                    async with session.post(
                        self.base + path, data=data, headers=headers
                    ) as resp:
                        raw = await resp.read()
                        hop.response_bytes = len(raw)
                        with hop.codec():
                            payload = json.loads(raw)
                        if resp.status >= 400:
                            attempts.append({
                                "attempt": attempt + 1,
                                "status": str(resp.status),
                                "elapsed_ms": round(
                                    (time.perf_counter() - t_attempt) * 1000.0, 3
                                ),
                            })
                            err = MicroserviceError(
                                f"REST call {path} to {self.base} returned "
                                f"{resp.status}: {payload} "
                                f"(attempts: {json.dumps(attempts)})",
                                status_code=502,
                                reason="UPSTREAM_REST_ERROR",
                            )
                            # deterministic upstream replies (4xx, plain
                            # 500) must not be retried here NOR absorbed
                            # by a fallback route upstream
                            err.transient = (
                                resp.status in _REST_RETRYABLE_STATUSES
                            )
                            if resp.status in _REST_RETRYABLE_STATUSES:
                                # overloaded/mid-restart: breaker-transient
                                call.attempt_transient()
                                if attempt + 1 < budget:
                                    last_err = err
                                    logger.warning(
                                        "REST %s to %s attempt %d/%d got %d, retrying",
                                        path, self.base, attempt + 1, budget, resp.status,
                                    )
                                    await asyncio.sleep(backoff_s(attempt))
                                    continue
                            else:
                                # deterministic reply: the endpoint is
                                # alive — never counts toward a trip
                                healthy = True
                            err.attempts = attempts
                            raise err
                        with hop.codec():
                            out = InternalMessage.from_json(payload)
                        ok = True
                        healthy = True
                        return out
                except MicroserviceError:
                    raise
                except asyncio.CancelledError:
                    healthy = None  # hedge loser / caller gone
                    raise
                except Exception as e:  # connection faults: transient by class
                    last_err = e
                    attempts.append({
                        "attempt": attempt + 1,
                        "status": type(e).__name__,
                        "elapsed_ms": round(
                            (time.perf_counter() - t_attempt) * 1000.0, 3
                        ),
                    })
                    call.attempt_transient()
                    if attempt + 1 >= budget:
                        break
                    logger.warning(
                        "REST %s to %s attempt %d/%d failed: %s",
                        path, self.base, attempt + 1, budget, e,
                    )
                    # full jitter (see backoff_s): deterministic backoff
                    # synchronises the herd into a retry storm
                    await asyncio.sleep(backoff_s(attempt))
            err = MicroserviceError(
                f"REST call {path} to {self.base} failed: {last_err} "
                f"(attempts: {json.dumps(attempts)})",
                status_code=502,
                reason="UPSTREAM_REST_ERROR",
            )
            err.attempts = attempts  # machine-readable per-attempt history
            err.transient = True  # connection faults: transient by class
            raise err from last_err
        finally:
            call.settle(healthy)
            hop.finish(error=not ok)

    async def transform_input(self, msg: InternalMessage) -> InternalMessage:
        if self.unit.type == MODEL:
            return await _hedged_call(
                self, "predict", "rest",
                lambda: self._post("/predict", "predict", msg.to_json),
            )
        return await _hedged_call(
            self, "transform_input", "rest",
            lambda: self._post("/transform-input", "transform_input", msg.to_json),
        )

    async def transform_output(self, msg: InternalMessage) -> InternalMessage:
        return await _hedged_call(
            self, "transform_output", "rest",
            lambda: self._post("/transform-output", "transform_output", msg.to_json),
        )

    async def route(self, msg: InternalMessage) -> InternalMessage:
        return await _hedged_call(
            self, "route", "rest",
            lambda: self._post("/route", "route", msg.to_json),
        )

    async def aggregate(self, msgs: List[InternalMessage]) -> InternalMessage:
        def encode():
            return {"seldonMessages": [m.to_json() for m in msgs]}

        return await _hedged_call(
            self, "aggregate", "rest",
            lambda: self._post("/aggregate", "aggregate", encode),
        )

    async def send_feedback(self, feedback: InternalFeedback) -> InternalMessage:
        # not idempotent: a timeout after the reward was applied must
        # not replay it (same rule as GrpcClient / BalancedClient)
        return await self._post(
            "/send-feedback", "send_feedback", feedback.to_json,
            idempotent=False,
        )

    async def ready(self) -> bool:
        try:
            session = self._get_session()
            async with session.get(self.base + "/health/ping") as resp:
                return resp.status < 400
        except Exception:  # any probe failure reads as not-ready
            return False

    async def close(self) -> None:
        if self._session is not None and not self._session.closed:
            await self._session.close()
        # replica retirement: evict the endpoint's registry breaker
        # (fresh ports per scale event would leak entries forever)
        CircuitBreaker.discard(
            f"{self.unit.endpoint.host}:{self.unit.endpoint.port}"
        )


class BalancedClient(NodeClient):
    """Round-robin load balancer over replica clients of one node.

    The role a k8s Service plays in front of an HPA-scaled Deployment in
    the reference (reference:
    operator/controllers/seldondeployment_controller.go:894-930): graph
    edges hold one NodeClient while the replica set behind it grows and
    shrinks.  ``set_clients`` swaps the replica list atomically (the
    autoscaler calls it on every scale event); each call starts at the
    next rotation slot and fails over to the remaining replicas before
    surfacing the last error.
    """

    def __init__(self, clients: Optional[List[NodeClient]] = None):
        import threading

        self._clients: List[NodeClient] = list(clients or [])
        self._retired: List[NodeClient] = []
        self._rr = 0
        self._lock = threading.Lock()

    def set_clients(self, clients: List[NodeClient]) -> None:
        """Swap the replica list; replaced clients are parked and closed
        on the serving loop at the next call (their grpc.aio channels
        are loop-bound, and this method runs on the autoscaler thread)."""
        fresh = list(clients)
        with self._lock:
            keep = set(map(id, fresh))
            self._retired.extend(c for c in self._clients if id(c) not in keep)
            self._clients = fresh

    async def _drain_retired(self) -> None:
        with self._lock:
            retired, self._retired = self._retired, []
        for client in retired:
            try:
                await client.close()
            except Exception as e:  # noqa: BLE001 — best-effort client cleanup
                logger.debug("closing retired replica client failed: %s", e)

    @property
    def replica_count(self) -> int:
        with self._lock:
            return len(self._clients)

    def _rotation(self) -> List[NodeClient]:
        with self._lock:
            if not self._clients:
                return []
            start = self._rr % len(self._clients)
            self._rr += 1
            return self._clients[start:] + self._clients[:start]

    async def _call(self, method: str, *args, failover: bool = True):
        await self._drain_retired()
        rotation = self._rotation()
        if not rotation:
            raise MicroserviceError(
                "no replicas available", status_code=503, reason="NO_REPLICAS"
            )
        last: Optional[Exception] = None
        for client in rotation:
            try:
                return await getattr(client, method)(*args)
            except MicroserviceError as e:
                # deterministic client errors (4xx) would fail identically
                # on every replica — surface immediately
                if e.status_code is not None and 400 <= e.status_code < 500:
                    raise
                last = e
                if not failover:
                    raise
                self._count_failover(client, method)
                logger.warning("replica call %s failed, failing over: %s", method, e)
            except Exception as e:  # noqa: BLE001 — fail over to next replica
                last = e
                if not failover:
                    raise
                self._count_failover(client, method)
                logger.warning("replica call %s failed, failing over: %s", method, e)
        raise last  # type: ignore[misc]

    @staticmethod
    def _count_failover(client: NodeClient, method: str) -> None:
        unit = getattr(getattr(client, "unit", None), "name", "") or "balanced"
        _metrics.record_transport_failover(unit, method)

    async def transform_input(self, msg: InternalMessage) -> InternalMessage:
        return await self._call("transform_input", msg)

    async def transform_output(self, msg: InternalMessage) -> InternalMessage:
        return await self._call("transform_output", msg)

    async def route(self, msg: InternalMessage) -> InternalMessage:
        return await self._call("route", msg)

    async def aggregate(self, msgs: List[InternalMessage]) -> InternalMessage:
        return await self._call("aggregate", msgs)

    async def send_feedback(self, feedback: InternalFeedback) -> InternalMessage:
        # not idempotent: a timeout after the reward was applied must not
        # replay the same feedback on another replica (double-counting)
        return await self._call("send_feedback", feedback, failover=False)

    async def ready(self) -> bool:
        for client in self._rotation():
            if await client.ready():
                return True
        return False

    async def close(self) -> None:
        await self._drain_retired()
        for client in self._rotation():
            await client.close()
