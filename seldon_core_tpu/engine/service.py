"""Predictor service: the request-facing shell around a GraphExecutor.

Equivalent of the reference's PredictionService + lifecycle endpoints
(reference: PredictionService.java:94-141 — puid assignment, graph
dispatch, response status; RestClientController.java:73-118 —
/ping /ready /live /pause /unpause semantics; App.java:60-97 —
graceful drain).
"""

from __future__ import annotations

import asyncio
import logging
import threading
import time
from typing import Any, Callable, Dict, List, Optional

from seldon_core_tpu.engine.executor import GraphExecutor, Observer
from seldon_core_tpu.engine.graph import UnitSpec
from seldon_core_tpu.runtime.component import MicroserviceError
from seldon_core_tpu.runtime.message import InternalFeedback, InternalMessage

# re-exported for callers that import it from here; the implementation
# lives in runtime/puid.py (fork/respawn-safe) so standalone
# microservices mint from the same collision-safe generator
from seldon_core_tpu.runtime.puid import new_puid  # noqa: F401

logger = logging.getLogger(__name__)


def failure_message(error: Exception, puid: str = "") -> InternalMessage:
    if isinstance(error, MicroserviceError):
        status = error.to_status()
    else:
        status = {
            "status": "FAILURE",
            "code": 500,
            "info": str(error),
            "reason": "ENGINE_ERROR",
        }
    msg = InternalMessage(payload=None, kind="jsonData", status=status)
    msg.meta.puid = puid
    return msg


class PredictorService:
    """One deployed predictor: graph executor + lifecycle + bookkeeping."""

    def __init__(
        self,
        graph: UnitSpec,
        name: str = "default",
        observer: Optional[Observer] = None,
        log_requests: bool = False,
        log_responses: bool = False,
        request_logger: Optional[Callable[[InternalMessage, InternalMessage], None]] = None,
        annotations: Optional[Dict[str, str]] = None,
        clients: Optional[Dict[str, Any]] = None,
    ):
        self.name = name
        self.executor = GraphExecutor(
            graph, observer=observer, annotations=annotations, clients=clients
        )
        self.graph = graph
        self._paused = False
        # threading (not asyncio) primitives: predict_sync runs on gRPC
        # thread-pool threads concurrently with the event loop, so the
        # in-flight count and stats need a real lock or drain() can
        # hang / return early under load
        self._inflight = 0
        self._stats_lock = threading.Lock()
        self._inflight_zero = threading.Event()
        self._inflight_zero.set()
        self.log_requests = log_requests
        self.log_responses = log_responses
        self.request_logger = request_logger
        self.stats = {"requests": 0, "failures": 0, "feedback": 0}
        self.explainer = None  # set by the control plane when configured

    def _enter_request(self) -> None:
        with self._stats_lock:
            self._inflight += 1
            self._inflight_zero.clear()
            self.stats["requests"] += 1

    def _exit_request(self, failed: bool = False) -> None:
        with self._stats_lock:
            self._inflight -= 1
            if failed:
                self.stats["failures"] += 1
            if self._inflight == 0:
                self._inflight_zero.set()

    async def explain(self, request: InternalMessage) -> InternalMessage:
        """Run the predictor's explainer (reference: the :explain URL of
        a deployed alibi explainer; here in-process)."""
        if self.explainer is None:
            return failure_message(
                MicroserviceError("predictor has no explainer configured", status_code=404,
                                  reason="NO_EXPLAINER")
            )
        from seldon_core_tpu.runtime.executor_pool import run_dispatch

        try:
            result = await run_dispatch(self.explainer.explain, request.host_payload(), request.names)
            out = InternalMessage(payload=result, kind="jsonData",
                                  status={"status": "SUCCESS", "code": 200})
            out.meta.puid = request.meta.puid or new_puid()
            return out
        except Exception as e:  # noqa: BLE001
            return failure_message(e)

    # ------------------------------------------------------------- lifecycle

    @property
    def paused(self) -> bool:
        return self._paused

    def pause(self) -> None:
        """Flip readiness off ahead of shutdown (reference: /pause)."""
        self._paused = True

    def unpause(self) -> None:
        self._paused = False

    async def live(self) -> bool:
        return True

    async def ready(self) -> bool:
        if self._paused:
            return False
        return await self.executor.ready()

    async def drain(self, timeout_s: float = 20.0) -> bool:
        """Pause and wait for in-flight requests
        (reference: App.java:60-97 Tomcat drain)."""
        self.pause()
        loop = asyncio.get_running_loop()
        return await loop.run_in_executor(None, self._inflight_zero.wait, timeout_s)

    # --------------------------------------------------------------- serving

    async def predict(self, request: InternalMessage) -> InternalMessage:
        puid = request.meta.puid or new_puid()
        request.meta.puid = puid
        self._enter_request()
        failed = False
        start = time.perf_counter()
        try:
            if self.log_requests:
                logger.info("request puid=%s payload_kind=%s", puid, request.kind)
            from seldon_core_tpu.utils.tracing import maybe_span

            with maybe_span("predictor.predict", trace_id=puid, predictor=self.name):
                response = await self.executor.predict(request)
            if response.status is None:
                response.status = {"status": "SUCCESS", "code": 200}
            if self.log_responses:
                logger.info("response puid=%s", puid)
            if self.request_logger is not None:
                try:
                    self.request_logger(request, response)
                except Exception:  # logging must never fail the data plane
                    logger.exception("request logger failed")
            return response
        except Exception as e:
            failed = True
            logger.exception("predict failed puid=%s", puid)
            return failure_message(e, puid)
        finally:
            self._exit_request(failed)
            elapsed = time.perf_counter() - start
            self.executor._emit("predict_done", self.name, elapsed)

    # ---- synchronous fast path -------------------------------------------

    def single_local_model(self):
        """(unit, component) when this predictor is one in-process MODEL
        node — the shape eligible for the no-event-loop fast path."""
        from seldon_core_tpu.engine.transport import LocalClient

        unit = self.graph
        if unit.children or unit.type != "MODEL":
            return None
        client = self.executor.clients.get(unit.name)
        if not isinstance(client, LocalClient):
            return None
        return unit, client.component

    def predict_sync(self, request: InternalMessage) -> InternalMessage:
        """Synchronous predict for single-local-MODEL graphs.

        Semantics identical to the async path (puid, requestPath,
        metric collection, status, observer events) but runs entirely
        on the caller's thread — used by the sync gRPC front server to
        bypass asyncio scheduling on the hot path.
        """
        fast = self.single_local_model()
        if fast is None:
            raise MicroserviceError(
                f"predictor {self.name!r} is not fast-path eligible", reason="NOT_FAST_PATH"
            )
        unit, component = fast
        from seldon_core_tpu.runtime import dispatch

        puid = request.meta.puid or new_puid()
        request.meta.puid = puid
        self._enter_request()
        failed = False
        start = time.perf_counter()
        try:
            t0 = time.perf_counter()
            response = dispatch.predict(component, request)
            self.executor._emit("node_call", unit.name, ("transform_input", time.perf_counter() - t0))
            if response.meta.metrics:
                self.executor._emit("node_metrics", unit.name, response.meta.metrics)
            response.meta.request_path[unit.name] = (
                unit.image or unit.implementation or unit.component_class or "local"
            )
            response.meta.puid = puid
            if response.status is None:
                response.status = {"status": "SUCCESS", "code": 200}
            if self.request_logger is not None:
                try:
                    self.request_logger(request, response)
                except Exception:  # logging must never fail the data plane
                    logger.exception("request logger failed")
            return response
        except Exception as e:  # noqa: BLE001
            failed = True
            logger.exception("predict failed puid=%s", puid)
            return failure_message(e, puid)
        finally:
            self._exit_request(failed)
            self.executor._emit("predict_done", self.name, time.perf_counter() - start)

    async def send_feedback(self, feedback: InternalFeedback) -> InternalMessage:
        try:
            with self._stats_lock:
                self.stats["feedback"] += 1
            await self.executor.send_feedback(feedback)
            out = InternalMessage(payload=None, kind="jsonData", status={"status": "SUCCESS", "code": 200})
            return out
        except Exception as e:
            logger.exception("feedback failed")
            return failure_message(e)

    async def close(self) -> None:
        await self.executor.close()
        # the pair logger is per-generation state this service owns for
        # its lifetime: HttpPairLogger runs a drain thread that must be
        # joined or rolling updates leak one thread per generation
        logger_close = getattr(self.request_logger, "close", None)
        if callable(logger_close):
            await asyncio.get_running_loop().run_in_executor(None, logger_close)
