"""Cross-cutting utilities: persistence, tracing, storage, metrics."""
